"""Managed training entrypoint — the tpuddp analog of the reference's
``multi-GPU-training-accelerate.py`` (call stack SURVEY.md §3.2): the
``Accelerator`` hides process topology, sharding, and gradient sync, and
routes through the same XLA backend as train_native.py.

Deliberate reference-parity behaviors (quirk Q3, SURVEY.md §3.5): the test
loader is NOT prepared, so eval runs the full test set on every process with
per-batch-mean (not sample-weighted) averaging and no cross-process reduction
— exactly like the reference (multi-GPU-training-accelerate.py:60-75,129-131).

Usage parity:  python train_accelerate.py --settings_file local_settings.yaml
"""

from __future__ import annotations

import argparse
import logging
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpuddp import config as cfg_lib
from tpuddp import nn
from tpuddp.accelerate import Accelerator
from tpuddp.resilience import faults
from tpuddp.resilience.guard import ReplicaDesync
from tpuddp.resilience.preemption import (
    EXIT_DESYNC,
    EXIT_PREEMPTED,
    TrainingPreempted,
    auto_resume_requested,
    install_preemption_handler,
    preemption_requested,
)
from tpuddp.data import (
    DataLoader,
    compute_dtype_for,
    flip_for,
    load_datasets_for,
    norm_stats_for,
)
from tpuddp.data.transforms import make_eval_transform, make_train_augment

logging.basicConfig(level=logging.INFO, format="%(message)s")


def setup_dataloaders(training):
    """Plain, distribution-unaware loaders (reference :22-36); prepare() later
    re-creates the train loader sharded."""
    train_dataset, test_dataset = load_datasets_for(training)
    train_loader = DataLoader(
        train_dataset, batch_size=training["train_batch_size"], shuffle=True
    )
    test_loader = DataLoader(test_dataset, batch_size=training["test_batch_size"])
    return train_loader, test_loader


def train(
    model, train_loader, criterion, optimizer, accelerator, augment,
    tel=None, start_batch=0, carried=None, poll=None, progress=None,
    total_batches=None,
):
    """One training epoch. Returns ``(mean_batch_loss, samples_seen)`` —
    the weighted sample count feeds the history.jsonl throughput fields.
    ``tel`` (observability.RunTelemetry) brackets each optimizer step with
    its host-side timing/profiling hooks; under fuse_steps the laps measure
    dispatch rate (the queue flushes every K steps), never forcing a flush.

    Step-granular resume/drain (training/snapshot.py, the v4 cursor): the
    caller hands a tail loader plus ``start_batch`` (the epoch offset its
    batches start at), ``carried`` ({loss_total, n_seen} from the cursor's
    partial accumulator — seeds this pass's sums so the epoch row equals the
    uninterrupted run's), and ``total_batches`` (the FULL epoch's batch
    count, the mean-loss denominator). ``poll`` is checked once per
    completed gradient-accumulation cycle (never mid-cycle — save_state
    refuses a partial cycle); when it returns True the pass stops and
    ``progress`` (a caller-owned dict) records ``interrupted=True`` plus the
    epoch step / loss total / samples so the drain can write an
    exactly-resumable step snapshot.

    Deferred readback (the async pipeline, tpuddp/training/pipeline.py): the
    per-batch ``loss.item()`` host sync the reference pays (quirk Q5) is
    retired on BOTH metric modes — losses are collected as LazyLoss objects
    and become observable at the end-of-epoch drain (or whenever a fuse-queue
    flush materializes them earlier); the loop itself never fences the
    device. ``augment=None`` means augmentation is folded INTO the compiled
    step (``Accelerator(augment=...)``) and raw decoded batches feed
    ``model(...)`` directly — host workers only decode and stack."""
    model.train()
    n_seen = float(carried["n_seen"]) if carried else 0.0
    carried_loss = float(carried["loss_total"]) if carried else None
    interrupted_at = None
    batch_losses = []
    # step-site chaos hook (resilience/faults.py): armed only while an
    # un-fired step fault exists, so normal runs pay nothing per batch
    fault_step = {"i": start_batch} if faults.has_step_fault() else None
    # fuse_steps bookkeeping for the step recorder: an optimizer.step() that
    # merely queues (fuse_steps=K enqueues K-1 of every K) is host-side
    # microseconds, and crediting it as a step would report bookkeeping time
    # as p50 while the Kth lap absorbs K steps of work. Steps accumulate here
    # and are posted as ONE group when the queue has actually drained.
    pend_steps, pend_samples = 0, 0
    from tpuddp.training.pipeline import StallClock, stalled_iter

    stall = StallClock()  # host-blocked time -> step_stats occupancy fields
    # deepest the fuse queue ran since the last posted group — sampled at
    # enqueue time (post time always sees a just-drained queue)
    queue_peak = [0]

    def post_if_flushed(force=False):
        nonlocal pend_steps, pend_samples
        if tel is None or pend_steps == 0:
            return
        if force or not getattr(optimizer, "_queue", None):
            tel.post_dispatch(
                pend_steps, int(pend_samples), host_stall_s=stall.take(),
                inflight_depth=queue_peak[0],
            )
            pend_steps, pend_samples = 0, 0
            queue_peak[0] = 0

    # ONE fresh key per epoch when augmentation runs as its own jitted op
    # (device_augment: false); with in-step augment the key derives from the
    # step rng inside the compiled program and no epoch key is drawn.
    aug_base = accelerator.next_rng_key() if augment is not None else None
    for i, (inputs, labels, weights) in enumerate(
        stalled_iter(train_loader, stall)
    ):
        if fault_step is not None:
            # preempt@step=N / crash@step=N kill the managed run MID-epoch
            # (the step index is the epoch-global batch count, tail-resume
            # aware); the drain poll below runs AFTER the fault so the
            # signal it raised is seen at this same accum-cycle boundary
            faults.maybe_fire("step", step=fault_step["i"])
            fault_step["i"] += 1
        # no .to(device): placement is the backend's job (reference :44 note)
        batch_n = float(np.sum(weights))
        n_seen += batch_n
        optimizer.zero_grad()

        if augment is not None:
            # Flip-augmented inputs (reference transform_train includes
            # RandomHorizontalFlip, data_and_toy_model.py:14-19), keyed off
            # the accelerator's per-process PRNG stream. The fold index is
            # the epoch-global batch position (start_batch + i) so a
            # tail-resumed pass keys each batch exactly as the original did.
            x = augment(aug_base, start_batch + i, jnp.asarray(inputs))
        else:
            x = inputs  # normalize/flip/resize run inside the step program

        if tel is not None:
            # the step about to be enqueued is global_step + pend_steps, and
            # the dispatch that will carry it is the WHOLE queued group — so
            # the window profiler must see pend_steps + 1 upcoming steps, or
            # a TPUDDP_PROFILE_STEPS window falling inside a not-yet-flushed
            # fused group would arm one flush too late and trace the wrong
            # steps
            tel.pre_dispatch(pend_steps + 1)
        # model(...) and criterion(...) record lazily; accelerator.backward
        # runs them as ONE jitted value_and_grad over the sharded global batch,
        # and step() applies the stashed averaged grads.
        outputs = model(x)
        loss = criterion(outputs, labels, weights)
        accelerator.backward(loss)
        optimizer.step()
        pend_steps += 1
        pend_samples += batch_n
        queue_peak[0] = max(
            queue_peak[0], len(getattr(optimizer, "_queue", ()) or ())
        )
        post_if_flushed()

        # collect the LazyLoss; its value materializes when the fuse queue
        # flushes (or at the epoch-end drain) — never a per-batch host sync
        batch_losses.append(loss)
        if (
            poll is not None
            and not getattr(optimizer, "_accum_count", 0)
            and poll()
        ):
            # drain request seen at an accum-cycle boundary: stop HERE —
            # every applied update is a committed step, the cursor names
            # the epoch step the resume continues at
            interrupted_at = start_batch + i + 1
            break
    # a partial gradient-accumulation cycle applies at dataloader end (the
    # HF accumulate() contract) instead of leaking into the next epoch; an
    # interrupted pass stopped AT a cycle boundary, so this is a no-op there
    flush_accum = getattr(optimizer, "flush_accumulation", None)
    if flush_accum is not None and interrupted_at is None:
        flush_accum()
    # the deferred readback drain: sum on device (array-at-a-time over fused
    # flushes), ONE host fetch — per-batch scalar reads cost a dispatch AND a
    # round trip each, and dominated the steps themselves on
    # dispatch-latency-bound runtimes (BASELINE.md's 1,532 samples/s row)
    from tpuddp.accelerate import sum_losses

    running_loss = float(sum_losses(batch_losses, initial=carried_loss))
    # a ragged tail left in the fuse queue was flushed by sum_losses (or by
    # flush_accumulation above): attribute its steps now, post-fence
    post_if_flushed(force=True)
    if progress is not None:
        progress["interrupted"] = interrupted_at is not None
        progress["step"] = (
            interrupted_at if interrupted_at is not None
            else start_batch + len(train_loader)
        )
        progress["loss_total"] = running_loss
        progress["n_seen"] = n_seen
    denom = total_batches if total_batches is not None else len(train_loader)
    return running_loss / denom, n_seen


def transform_host(transform, inputs):
    """Resize+normalize before the managed forward (the managed path keeps the
    torch-like 'model(inputs)' shape, so the transform runs as a separate
    jitted op rather than fused into the step)."""
    return transform(jnp.asarray(inputs))


def evaluate(model, test_loader, criterion, device, transform, deferred=False):
    """Returns ``(mean_batch_loss, accuracy_pct, total_samples)``."""
    model.eval()
    if deferred:
        # scan-fused eval: transform + forward + loss + metric accumulation
        # for K batches per jit dispatch, one host fetch at the end — the
        # managed analog of the native build_eval_scan_step (same quirk-Q3
        # semantics: full test stream on every process, per-batch-mean loss).
        # ONE evaluator per (model, criterion, transform), cached on the
        # model: a fresh instance per epoch would retrace its scan program
        # every epoch.
        from tpuddp.accelerate import FusedEvaluator

        ev = getattr(model, "_tpuddp_fused_eval", None)
        if ev is None or ev.criterion is not criterion or ev.transform is not transform:
            ev = FusedEvaluator(model, criterion, transform=transform)
            model._tpuddp_fused_eval = ev
        for inputs, labels, weights in test_loader:
            ev.add(inputs, labels, weights)
        test_loss, correct, total = ev.finalize()
        accuracy = 100 * correct / total
        return test_loss / len(test_loader), accuracy, total
    correct = 0
    total = 0
    test_loss = 0.0
    for inputs, labels, weights in test_loader:
        inputs = transform_host(transform, inputs)
        outputs = model(inputs)
        loss = criterion(outputs, labels, weights)
        test_loss += loss.item()
        predicted = np.asarray(outputs.argmax(axis=-1))
        mask = weights > 0
        total += int(mask.sum())
        correct += int(((predicted == labels) & mask).sum())
    accuracy = 100 * correct / total
    return test_loss / len(test_loader), accuracy, total


def run_training_loop(
    model,
    train_loader,
    test_loader,
    criterion,
    optimizer,
    save_dir,
    accelerator,
    augment,
    eval_transform,
    num_epochs=20,
    checkpoint_epoch=5,
    deferred_metrics=False,
    start_epoch=0,
    step_stats_every=0,
    run_meta=None,
    pipeline=None,
    observability=None,
    snapshot=None,
):
    # Observability parity with the native epoch driver (training/loop.py):
    # the typed run_meta header opens history.jsonl, epoch rows carry the
    # step recorder's percentile/MFU fields, $TPUDDP_PROFILE traces the
    # first epoch ($TPUDDP_PROFILE_STEPS a step window, SIGUSR1 the next
    # epoch on demand), and $TPUDDP_DEBUG_NANS guards the aggregated losses.
    # The live plane (ISSUE 10) rides too: opt-in /metrics exporter, pod
    # shard publishing + aggregation, crash flight recorder.
    from tpuddp.observability import (
        MetricsWriter,
        RunTelemetry,
        check_finite,
        make_run_meta,
        maybe_start_profiler,
        stamp,
        stop_profiler,
    )
    from tpuddp.observability import aggregate as agg_lib
    from tpuddp.observability import exporter as exp_lib
    from tpuddp.observability import flight as flight_lib
    from tpuddp.resilience import faults
    from tpuddp.resilience import guard as guard_lib
    from tpuddp.resilience import watchdog as wd_lib

    from tpuddp.training.pipeline import resolve_pipeline
    from tpuddp.training import snapshot as snapshot_lib

    obs_cfg = cfg_lib.resolve_observability(observability)
    # training.snapshot (managed flavor): step-granular preemption drains +
    # exact mid-epoch resume. The managed path has no background writer (the
    # fuse queue is its own overlap story) — armed, a drain caught at an
    # accum-cycle boundary writes state_{epoch}_s{step}.npz with the v4 data
    # cursor, and load_state's cursor routes the NEXT run back here to
    # continue that epoch at that step with zero batches replayed.
    snap_cfg = snapshot_lib.resolve_snapshot(snapshot)
    pending_cursor = {"c": getattr(accelerator, "last_restore_cursor", None)}
    flight = None
    if obs_cfg["flight_recorder"] and save_dir is not None:
        flight = flight_lib.install(flight_lib.FlightRecorder(
            save_dir, capacity=int(obs_cfg["flight_capacity"]),
        ))
    metrics_writer = MetricsWriter(save_dir, flight=flight)
    profiling = maybe_start_profiler(save_dir)
    guard_cfg = guard_lib.resolve_guard(getattr(accelerator, "guard", None))
    pipeline = resolve_pipeline(pipeline)
    # elastic resume (ISSUE 7): load_state stashed any topology-change events
    # (the restored state was written on a different world size); the header
    # names the provenance and the typed event rows land right after it
    restore_events = list(getattr(accelerator, "last_restore_events", []) or [])
    meta_extra = {
        "api": "managed",
        "fuse_steps": getattr(accelerator, "fuse_steps", None),
        "grad_accumulation": getattr(
            accelerator, "gradient_accumulation_steps", 1
        ),
        "start_epoch": start_epoch,
        "num_epochs": num_epochs,
        "step_stats_every": int(step_stats_every or 0),
        "pipeline": pipeline.as_dict(),
        # comm compression v2 accounting: the managed emulation's wire is the
        # XLA-inserted f32 psum; density is provenance (it shapes the
        # quantization). The per-update byte counter exists only once the
        # lazily-initialized model/optimizer have materialized (a resumed
        # run); a fresh run learns it at the first step, so the header omits
        # the key rather than recording a null that reads as "no bytes".
        "comm_density": getattr(accelerator, "topk_density", None),
        **(
            {"grad_comm_bytes_per_update": optimizer.grad_comm_bytes_per_step}
            if getattr(optimizer, "grad_comm_bytes_per_step", None) is not None
            else {}
        ),
        **(run_meta or {}),
    }
    topo_change = next(
        (ev for ev in restore_events if ev.get("event") == "topology_change"),
        None,
    )
    if topo_change is not None:
        meta_extra["resumed_from_world"] = topo_change.get("from_world")
    # exporter starts BEFORE the header so the header records the bound port
    exporter = exp_lib.exporter_from_config(obs_cfg, run_dir=save_dir)
    if exporter is not None:
        exporter.start()
    obs_meta = {
        "exporter": exporter.describe() if exporter is not None else False,
        "aggregate": bool(obs_cfg["aggregate"]),
        "straggler_ratio": float(obs_cfg["straggler_ratio"]),
        "straggler_windows": int(obs_cfg["straggler_windows"]),
        "flight_recorder": (
            flight.describe() if flight is not None else False
        ),
    }
    # v10 comm block: the managed path always runs the barrier exchange
    # (XLA-inserted psum); the header records that resolution explicitly
    _overlap = getattr(accelerator, "comm_overlap_meta", None)
    metrics_writer.write(make_run_meta(
        mesh=getattr(accelerator, "mesh", None),
        comm_hook=getattr(accelerator, "comm_hook", None),
        comm_topology=getattr(accelerator, "comm_topology", "flat"),
        guard=guard_cfg,
        observability=obs_meta,
        comm={"overlap": dict(_overlap)} if _overlap is not None else None,
        # v11 snapshot provenance: the managed flavor (drain-time step
        # snapshots, no background writer); False = epoch-granular only
        snapshot=(
            {**snap_cfg.as_dict(), "mode": "drain"}
            if snap_cfg.enabled else False
        ),
        # v12 tuning provenance: which overlay (if any) shaped this run's
        # knobs; null = advisor off / no overlay
        tuning=cfg_lib.tuning_provenance_from_env(),
        extra=meta_extra,
    ))
    for ev in restore_events:
        metrics_writer.write(stamp("event", ev))
    # managed-path step timing is dispatch-resolution (a mid-epoch device
    # fence would flush the fuse_steps queue and break the fusion it is
    # measuring) — the epoch boundary's loss materialization is the fence
    acc_mesh = getattr(accelerator, "mesh", None)
    tel = RunTelemetry(
        writer=metrics_writer,
        save_dir=save_dir,
        step_stats_every=step_stats_every,
        world_size=int(acc_mesh.devices.size) if acc_mesh is not None else 1,
        device_kind=(
            acc_mesh.devices.flat[0].device_kind if acc_mesh is not None else None
        ),
    )
    # live plane: shard publishing + main-process aggregation (multi-host
    # only), exporter sources (native-driver parity)
    aggregator = None
    shard_dir = None
    if obs_cfg["aggregate"] and jax.process_count() > 1:
        shard_dir = wd_lib.heartbeat_dir(save_dir)
        if shard_dir is not None:
            os.makedirs(shard_dir, exist_ok=True)
            if accelerator.is_local_main_process:
                aggregator = agg_lib.PodAggregator(
                    shard_dir,
                    jax.process_count(),
                    writer=metrics_writer,
                    straggler_ratio=float(obs_cfg["straggler_ratio"]),
                    straggler_windows=int(obs_cfg["straggler_windows"]),
                )
    tel.attach_live(
        exporter=exporter,
        aggregator=aggregator,
        shard_dir=shard_dir,
        process_id=jax.process_index(),
    )
    prev_skips = optimizer.skip_counters()[0] if guard_cfg.enabled else 0
    rollback_count = {"n": 0}

    def rollback_to_last_good(epoch, reason):
        """Managed rollback-to-last-good (native-driver parity): restore the
        newest intact ``state_{epoch}.npz`` via load_state — weights,
        moments, EF residual, skip counters, RNG stream — record the event,
        and hand back the epoch to redo (``set_epoch`` re-derives its data
        order). Returns None when no state file exists (caller escalates)."""
        from tpuddp.training import checkpoint as _ckpt

        if _ckpt.latest(save_dir, prefix="state") is None:
            return None
        rollback_count["n"] += 1
        if rollback_count["n"] > guard_cfg.max_rollbacks:
            raise RuntimeError(
                f"guard rollback limit ({guard_cfg.max_rollbacks}) exceeded; "
                f"last trigger: {reason}. The failure recurs after restoring "
                "known-good state — a systematic divergence, not a transient."
            )
        redo_epoch = accelerator.load_state(model, optimizer, save_dir)
        metrics_writer.write(stamp("event", {
            "event": "rollback",
            "epoch": epoch,
            "resume_epoch": redo_epoch,
            "reason": reason,
        }))
        if accelerator.is_local_main_process:
            print(
                f"Guard rollback ({reason}): restored last-good state, "
                f"redoing from epoch {redo_epoch}."
            )
        return redo_epoch
    def drain(last_completed_epoch):
        """Preemption drain (SIGTERM/SIGINT seen at a managed-loop boundary):
        publish the lossless state of the last fully-trained epoch so a
        requeued ``training.resume``/auto-resume run continues after it, then
        raise for the exit-75 conversion in ``__main__``."""
        if last_completed_epoch >= 0:
            accelerator.wait_for_everyone()
            accelerator.save_state(
                model, optimizer, save_dir, epoch=last_completed_epoch
            )
            if accelerator.is_local_main_process:
                print(
                    f"Preempted: emergency state for epoch "
                    f"{last_completed_epoch} saved."
                )
        # the drain's event row, fsync'd before the SIGKILL window closes
        metrics_writer.write(stamp("event", {
            "event": "preempt",
            "epoch": last_completed_epoch + 1,
            "completed": True,
            "step": tel.recorder.global_step,
        }))
        metrics_writer.sync()
        # the exit-75 flight recording (the preempt event rode the tee above)
        if flight is not None:
            flight.note(
                emergency_epoch=last_completed_epoch,
                emergency_step=tel.recorder.global_step,
            )
            flight.dump("preempt")
        raise TrainingPreempted(last_completed_epoch + 1)

    def mid_drain(epoch, prog):
        """Step-granular preemption drain (training.snapshot armed): the
        train pass stopped at an accum-cycle boundary mid-epoch — publish
        ``state_{epoch}_s{step}.npz`` with the v4 data cursor (plan key +
        partial loss/sample totals) so the requeued run continues THIS epoch
        at THIS step with zero batches replayed, retiring the managed
        path's redo-the-epoch resume."""
        step = int(prog["step"])
        accelerator.wait_for_everyone()
        accelerator.save_state(
            model, optimizer, save_dir, epoch=epoch, step=step,
            cursor={
                "plan_key": snapshot_lib.epoch_plan_key(train_loader, epoch),
                "acc": {
                    "loss_total": np.asarray(prog["loss_total"], np.float64),
                    "n_seen": np.asarray(prog["n_seen"], np.float64),
                },
            },
        )
        if accelerator.is_local_main_process:
            print(
                f"Preempted: step snapshot for epoch {epoch} step {step} "
                f"saved (exact resume)."
            )
        metrics_writer.write(stamp("event", {
            "event": "preempt",
            "epoch": epoch,
            "completed": False,
            "step": tel.recorder.global_step,
            "snapshot_step": step,
        }))
        metrics_writer.sync()
        if flight is not None:
            flight.note(
                emergency_epoch=epoch,
                emergency_step=tel.recorder.global_step,
                snapshot_final_step=step,
            )
            flight.dump("preempt")
        raise TrainingPreempted(epoch)

    # per-batch drain polling is single-host-only (one host stopping
    # mid-pass while peers still issue step collectives would wedge the
    # pod) and opt-in via the snapshot block
    poll_cb = (
        preemption_requested
        if snap_cfg.enabled and jax.process_count() == 1 else None
    )

    try:
        epoch = start_epoch
        while epoch < num_epochs:
            # $TPUDDP_FAULT chaos hook (native-driver parity): injected
            # crash/preempt/hang fire at the managed epoch boundary too, so
            # the elastic chaos matrix can kill the Accelerator entrypoint
            # at a deterministic point
            faults.maybe_fire("epoch", epoch=epoch)
            if preemption_requested():
                drain(epoch - 1)
            if (
                guard_cfg.enabled
                and guard_cfg.audit_every_n_epochs
                and (epoch - start_epoch) % guard_cfg.audit_every_n_epochs == 0
                and model._params is not None
            ):
                # periodic cross-replica desync audit (one fingerprint
                # reduction; resilience/guard.py): divergence rolls back to
                # the newest state_{epoch}.npz when configured, else (or
                # with nothing to restore) exits 77 into auto-resume
                bad_leaf = guard_lib.audit_params(accelerator.mesh, model._params)
                if bad_leaf is not None:
                    metrics_writer.write(stamp(
                        "event",
                        {"event": "desync", "epoch": epoch, "leaf": bad_leaf},
                    ))
                    if guard_cfg.on_desync == "rollback":
                        redo = rollback_to_last_good(
                            epoch, f"replica desync at leaf {bad_leaf}"
                        )
                        if redo is not None:
                            epoch = redo
                            prev_skips = optimizer.skip_counters()[0]
                            continue
                    raise guard_lib.ReplicaDesync(
                        bad_leaf, where=f"epoch {epoch} audit"
                    )
            train_loader.set_epoch(epoch)
            # exact mid-epoch resume: a v4 cursor stashed by load_state for
            # THIS epoch skips the already-applied batch-plan prefix and
            # seeds the loss/sample totals it carried — the epoch row comes
            # out equal to the uninterrupted run's. A plan-key mismatch
            # (different sampler config, resharded restore) falls back to
            # the legacy redo-the-epoch path.
            start_batch, carried, pass_loader = 0, None, train_loader
            cur = pending_cursor["c"]
            if cur is not None and int(cur.get("epoch", -1)) == epoch:
                pending_cursor["c"] = None
                expect = snapshot_lib.epoch_plan_key(train_loader, epoch)
                if cur.get("plan_key") == expect:
                    start_batch = int(cur["step"])
                    acc = snapshot_lib.acc_from_cursor(cur)
                    carried = {
                        "loss_total": float(
                            np.asarray(acc.get("loss_total", 0.0))
                        ),
                        "n_seen": float(np.asarray(acc.get("n_seen", 0.0))),
                    }
                    pass_loader = snapshot_lib.EpochTailLoader(
                        train_loader, start_batch
                    )
                    if accelerator.is_local_main_process:
                        print(
                            f"Exact resume: epoch {epoch} continues at step "
                            f"{start_batch} (zero batches replayed)."
                        )
                else:
                    logging.getLogger("tpuddp").warning(
                        "step snapshot plan key mismatch for epoch %d: data "
                        "order changed, redoing the epoch from the restored "
                        "state", epoch,
                    )
            elif cur is not None:
                pending_cursor["c"] = None
            epoch_t0 = time.perf_counter()
            tel.start_epoch(epoch)
            progress = {}
            train_loss, train_samples = train(
                model,
                pass_loader,
                criterion,
                optimizer,
                accelerator,
                augment,
                tel=tel,
                start_batch=start_batch,
                carried=carried,
                poll=poll_cb,
                progress=progress,
                total_batches=len(train_loader),
            )
            # the train pass is done (its end-of-epoch drain materialized
            # the losses — the fence); summarize before eval time can leak
            # in, but keep any SIGUSR1 epoch trace running through evaluation
            step_fields = tel.end_epoch(stop_trace=False)
            if progress.get("interrupted"):
                # the pass stopped at an accum-cycle boundary mid-epoch:
                # write the exactly-resumable step snapshot (never the
                # "epoch done" drain below — its updates are NOT all applied)
                mid_drain(epoch, progress)
            if preemption_requested():
                # the train pass completed, so every update of this epoch is
                # applied — save it as done and lose only the eval metrics
                drain(epoch)
            test_loss, test_accuracy, test_samples = evaluate(
                model,
                test_loader,
                criterion,
                accelerator.device,
                eval_transform,
                deferred=deferred_metrics,
            )
            # the SIGUSR1 'next full epoch' capture includes eval (native
            # parity — an operator tracing a slow eval must see it)
            tel.stop_epoch_trace()
            epoch_time = time.perf_counter() - epoch_t0

            if profiling and epoch == start_epoch:
                stop_profiler()  # trace the first epoch only
                profiling = False

            # epoch summary, gated to one process (reference :96-102)
            if accelerator.is_local_main_process:
                print(
                    f"Epoch {epoch + 1}/{num_epochs}, "
                    f"Train Loss: {train_loss:.4f}, "
                    f"Test Loss: {test_loss:.4f}, "
                    f"Test Accuracy: {test_accuracy:.2f}%"
                )
            # guard skip accounting: one tiny counter fetch per epoch, and
            # a skip is never silent next to a checkpoint
            guard_fields = {}
            consec_skips = 0
            if guard_cfg.enabled:
                total_skips, consec_skips = optimizer.skip_counters()
                guard_fields = {
                    "skipped_steps": total_skips,
                    "skipped_steps_epoch": total_skips - prev_skips,
                }
                prev_skips = total_skips
                if guard_fields["skipped_steps_epoch"] and accelerator.is_local_main_process:
                    print(
                        f"Guard: skipped {guard_fields['skipped_steps_epoch']} "
                        f"non-finite update(s) in epoch {epoch} "
                        f"(total {total_skips})."
                    )

            # live-plane gauges (native-driver parity): last epoch losses +
            # guard skip totals reach /metrics and the published shard
            tel.update_live(
                train_loss=train_loss,
                test_loss=test_loss,
                test_accuracy=test_accuracy,
                skipped_steps=guard_fields.get("skipped_steps", 0),
            )
            if aggregator is not None:
                aggregator.update()
            # native-driver record schema (training/loop.py), written BEFORE
            # the NaN guard so a blown-up epoch still leaves its post-mortem
            # row in history.jsonl (non-finite values land as strict-JSON
            # null, never a bare NaN token)
            metrics_writer.write(stamp("epoch", {
                "epoch": epoch,
                "train_loss": train_loss,
                "test_loss": test_loss,
                "test_accuracy": test_accuracy,
                "train_samples": train_samples,
                "test_samples": test_samples,
                "epoch_time_s": epoch_time,
                "samples_per_sec": (train_samples + test_samples)
                / max(epoch_time, 1e-9),
                **step_fields,
                **guard_fields,
            }))
            if guard_fields.get("skipped_steps_epoch"):
                metrics_writer.write(stamp("event", {
                    "event": "skipped_updates",
                    "epoch": epoch,
                    "count": guard_fields["skipped_steps_epoch"],
                    "total": guard_fields["skipped_steps"],
                }))
            # $TPUDDP_DEBUG_NANS: both losses guarded BEFORE the checkpoint
            # below — a poisoned epoch must never persist its state
            check_finite(train_loss, "train loss")
            if test_samples:
                check_finite(test_loss, "test loss")

            if consec_skips > guard_cfg.max_consecutive_skips:
                # the firewall is skipping updates back to back — training
                # stalled on frozen weights. Roll back to the last saved
                # state, or fail loudly; never finish 0 having silently
                # trained nothing (native-driver parity, training/loop.py).
                redo = rollback_to_last_good(
                    epoch,
                    f"{consec_skips} consecutive non-finite updates skipped",
                )
                if redo is not None:
                    epoch = redo
                    prev_skips = optimizer.skip_counters()[0]
                    continue
                raise FloatingPointError(
                    f"non-finite gradients forced {consec_skips} consecutive "
                    "skipped updates and no saved state exists to roll back "
                    "to (lower checkpoint_epoch to arm rollback)"
                )

            if epoch % checkpoint_epoch == 0:
                # barrier, then a single-writer save of the unwrapped weights
                # (reference :104-108) PLUS the lossless full state (weights +
                # optimizer moments + RNG position) that training.resume
                # restores
                accelerator.wait_for_everyone()
                accelerator.save_model(model, save_dir)
                accelerator.save_state(model, optimizer, save_dir, epoch=epoch)
            epoch += 1
    except TrainingPreempted:
        raise  # drain() already dumped the "preempt" recording
    except ReplicaDesync:
        if flight is not None:
            flight.dump("desync")
        raise
    except BaseException:
        if flight is not None:
            flight.dump("exception")
        raise
    finally:
        # an exception mid-epoch must still flush any active trace (it is
        # the post-mortem artifact) and never leave the JSONL history
        # unflushed/truncated; the live plane tears down with it
        tel.finish()
        if profiling:
            stop_profiler()
        metrics_writer.close()
        if exporter is not None:
            exporter.stop()
        if flight is not None:
            flight_lib.uninstall(flight)

    print("Finished Training.")


def basic_accelerate_training(
    out_dir: str, training=None, num_chips=None, observability=None
):
    training = training or cfg_lib.TRAINING_DEFAULTS
    # SIGTERM/SIGINT -> drain flag (polled at managed-loop boundaries);
    # main-thread only, a no-op under threaded test runners
    install_preemption_handler()
    # Topology discovery happens inside the Accelerator (reference :115);
    # num_chips honors a configured sub-world on multi-chip hosts.
    # fuse_steps batches K optimizer.step()s into one scan dispatch; it only
    # pays off when loss reads are deferred, so "auto" keys off that.
    accum = int(training.get("gradient_accumulation_steps") or 1)
    fuse = training.get("fuse_steps", "auto")
    if fuse in (None, "auto"):
        # fusion pays off only with deferred metric reads (an eager
        # loss.item() per batch flushes the queue every step); "auto" then
        # resolves size-aware inside the Accelerator at the first step
        fuse = "auto" if training.get("deferred_metrics") else 1
    # async pipeline config (training.pipeline): staged depth / host workers
    # / in-step augment; resolved once, recorded in the run_meta header
    from tpuddp.training.pipeline import resolve_pipeline

    pipeline_cfg = resolve_pipeline(training.get("pipeline"))
    # augmentation pipeline: with pipeline.device_augment (the default) the
    # normalize/flip/resize is folded INTO the compiled step programs
    # (Accelerator(augment=...)) so the host loop feeds raw decoded batches
    # — one dispatch per step, host workers only decode and stack
    mean, std = norm_stats_for(training)
    cdtype = compute_dtype_for(training)
    _aug = make_train_augment(
        size=training.get("image_size"),
        flip=flip_for(training),
        mean=mean,
        std=std,
        compute_dtype=cdtype,
    )
    # an EXPLICIT fuse_steps conflicting with accumulation surfaces the
    # library's own mutually-exclusive error instead of a silent override
    accelerator = Accelerator(
        seed=training.get("seed"),
        fuse_steps=fuse if fuse == "auto" else int(fuse),
        num_chips=num_chips,
        clip_grad_norm=training.get("clip_grad_norm"),
        gradient_accumulation_steps=accum,
        weight_update_sharding=bool(training.get("weight_update_sharding", False)),
        # gradient-comm hook (managed emulation; parallel/comm.py): same
        # training.comm_hook / comm_topology / topk_density knobs as the
        # native entrypoint (hierarchical topology is explicit-path-only and
        # refused here rather than silently run flat)
        comm_hook=str(training.get("comm_hook") or "none"),
        bucket_cap_mb=float(training.get("bucket_cap_mb") or 25),
        comm_topology=str(training.get("comm_topology") or "flat"),
        # comm_overlap parity: "auto"/false record disabled provenance here
        # (the managed collective is XLA-inserted); true refuses loudly
        comm_overlap=training.get("comm_overlap", "auto"),
        topk_density=float(training.get("topk_density") or 0.1),
        # numerical guard (resilience/guard.py): non-finite-update firewall
        # in the fused/scan/accumulation programs + prepare-time desync audit
        guard=training.get("guard"),
        augment=_aug if pipeline_cfg.device_augment else None,
    )

    # Data + model (reference :118-122); placement is implicit on this path.
    train_loader, test_loader = setup_dataloaders(training)
    model = load_model_for(training)

    criterion = nn.CrossEntropyLoss()
    # training.optimizer: adam default, lars/lamb/sgdw for large-batch runs —
    # config.optimizer_from, the SAME factory the native entrypoint uses
    optimizer = cfg_lib.optimizer_from(training)

    # prepare() wraps model/optimizer/train loader for the mesh backend
    # (reference :129-131); test_loader deliberately stays unprepared
    # (quirk Q3 parity).
    model, optimizer, training_dataloader = accelerator.prepare(
        model, optimizer, train_loader
    )

    if training.get("prefetch", True) and pipeline_cfg.host_workers > 0:
        from tpuddp.accelerate import StagedUploadLoader
        from tpuddp.data import PrefetchLoader

        # host batch assembly overlaps device compute (PrefetchLoader, the
        # reference's num_workers analog; workers > 1 parallelize assembly
        # over the loader's batch plan) and batch N+1's host->device upload
        # is issued while batch N's step runs (StagedUploadLoader)
        training_dataloader = StagedUploadLoader(
            PrefetchLoader(training_dataloader, workers=pipeline_cfg.host_workers)
        )
        test_loader = StagedUploadLoader(
            PrefetchLoader(test_loader, workers=pipeline_cfg.host_workers)
        )

    if pipeline_cfg.device_augment:
        # augment is compiled into the step programs (Accelerator(augment=)
        # above); train() feeds raw decoded batches straight to model(...)
        augment = None
    else:
        # legacy cadence: one separate jitted augment dispatch per batch;
        # (base_key, batch_index, x) — the per-batch key derivation happens
        # inside the jit (see train()'s aug_base note)
        augment = jax.jit(lambda rng, i, x: _aug(jax.random.fold_in(rng, i), x))
    eval_transform = jax.jit(
        make_eval_transform(
            size=training.get("image_size"), mean=mean, std=std,
            compute_dtype=cdtype,
        )
    )
    # Managed resume (training.resume: true): restore the newest lossless
    # state_{epoch}.npz in out_dir — weights, optimizer moments, RNG stream
    # position. The structure to load into is created by one LAZY forward on
    # a transformed single-sample probe (LazyForward materializes nothing and
    # _ensure_init only reads shape/dtype, so no batch assembly, no prefetch
    # thread, and only the transform's tiny dispatch runs).
    start_epoch = 0
    resume = (
        training.get("resume")
        or training.get("auto_resume")
        # the scheduler-requeue path: same command, env flag set (exit-75 contract)
        or auto_resume_requested()
    )
    if resume:
        img0, _label0 = train_loader.dataset[0]
        x0 = eval_transform(jnp.asarray(np.asarray(img0)[None]))
        model(x0)
        start_epoch = accelerator.load_state(model, optimizer, out_dir)
        cursor = getattr(accelerator, "last_restore_cursor", None)
        if cursor is not None and accelerator.is_local_main_process:
            print(
                f"Resumed from step snapshot: epoch {start_epoch} step "
                f"{int(cursor.get('step', 0))}."
            )
        elif start_epoch and accelerator.is_local_main_process:
            print(f"Resumed from epoch {start_epoch - 1} state.")

    from tpuddp.observability import config_hash

    run_training_loop(
        model,
        training_dataloader,
        test_loader,
        criterion,
        optimizer,
        out_dir,
        accelerator,
        augment,
        eval_transform,
        num_epochs=training["num_epochs"],
        checkpoint_epoch=training["checkpoint_epoch"],
        deferred_metrics=bool(training.get("deferred_metrics")),
        start_epoch=start_epoch,
        step_stats_every=int(training.get("step_stats_every") or 0),
        pipeline=pipeline_cfg,
        observability=observability,
        # step-granular preemption drains + exact mid-epoch resume
        snapshot=training.get("snapshot"),
        # run provenance for the history header: which configuration was this?
        run_meta={
            "config_hash": config_hash(training),
            "model": training.get("model"),
            "dataset": training.get("dataset"),
        },
    )


def load_model_for(training):
    from tpuddp.models import load_model

    from tpuddp.config import num_classes_from

    if training.get("pretrained_path"):
        from tpuddp.models.torch_import import pretrained_from_config

        model, params, mstate = pretrained_from_config(training)
        # consumed by PreparedModel._ensure_init instead of a fresh init
        model._tpuddp_initial_variables = (params, mstate)
    else:
        model = load_model(training["model"], num_classes_from(training))
    if training.get("sync_bn"):
        nn.convert_sync_batchnorm(model)
    return model


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="tpuddp managed-API training (Accelerator over the XLA "
        "mesh backend).",
    )
    parser.add_argument(
        "--settings_file",
        type=str,
        required=True,
        help="YAML settings (see local_settings.yaml for the schema: out_dir, "
        "local.{device,tpu}, optional_args, training overrides).",
    )
    args = parser.parse_args()

    settings = cfg_lib.load_settings(args.settings_file)
    out_dir = cfg_lib.prepare_out_dir(settings, args.settings_file)
    training = cfg_lib.training_config(settings)
    # 2-D mesh: the managed path has no tensor-parallel step (the TP
    # exchanges are written over the explicit shard_map axes) — refuse a
    # model-parallel parallel block here instead of training something else
    if cfg_lib.parallel_config(settings)["model"] > 1:
        raise ValueError(
            "parallel.model > 1 needs the explicit API (train_native.py / "
            "DistributedDataParallel); the managed Accelerator path runs "
            "pure data parallelism"
        )

    # Managed path: world size comes from the runtime, not config — but honor
    # the dev-mode CPU world request like the native entrypoint does, and a
    # configured sub-world (local.tpu.num_chips) on multi-chip hosts.
    world_size = cfg_lib.world_size_from(settings)
    if world_size:
        from tpuddp.parallel.spawn import maybe_reexec_for_world

        maybe_reexec_for_world(world_size, cfg_lib.device_from(settings))

    try:
        basic_accelerate_training(
            out_dir, training, num_chips=world_size,
            observability=cfg_lib.observability_config(settings),
        )
    except TrainingPreempted as e:
        # the exit-code contract (README "Fault tolerance"): 75 = EX_TEMPFAIL,
        # drained after SIGTERM — requeue the same command to auto-resume
        logging.getLogger("tpuddp").warning(
            "%s; exiting %d (requeue+resume)", e, EXIT_PREEMPTED
        )
        raise SystemExit(EXIT_PREEMPTED)
    except ReplicaDesync as e:
        # 77: a replica's parameters diverged (guard auditor) — the state is
        # untrustworthy; requeue into auto-resume from the last intact state
        logging.getLogger("tpuddp").critical("%s; exiting %d", e, EXIT_DESYNC)
        raise SystemExit(EXIT_DESYNC)
