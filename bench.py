"""Benchmark: samples/sec/chip on the toy MLP (the BASELINE.json metric).

Workload parity with the reference hot loop (multi-GPU-training-torch.py:109-132):
per-chip batch 128, Adam lr=1e-3, cross-entropy, CIFAR-shaped 32x32x3 inputs,
full DP train step (forward, backward, grad pmean, update, on-device metrics).

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
baseline is *measured here*: the same workload run through the reference's
stack (torch + torch.optim.Adam) on this host's available torch device (CPU in
this environment — the reference's CUDA path needs NVIDIA hardware that does
not exist on a TPU host). vs_baseline = tpuddp_samples_per_sec / torch_samples_per_sec.

Prints exactly ONE JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def bench_tpuddp(batch_per_chip=128, steps=200, warmup=20):
    import jax
    import jax.numpy as jnp

    from tpuddp import nn, optim
    from tpuddp.models import ToyMLP
    from tpuddp.parallel import make_mesh
    from tpuddp.parallel.ddp import DistributedDataParallel

    devices = jax.devices()
    mesh = make_mesh(devices)
    n_chips = len(devices)
    global_batch = batch_per_chip * n_chips
    log(f"tpuddp bench: {n_chips} chip(s), global batch {global_batch}")

    model = ToyMLP(num_classes=10)
    ddp = DistributedDataParallel(
        model, optim.Adam(1e-3), nn.CrossEntropyLoss(), mesh=mesh, mode="shard_map"
    )
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))

    rng = np.random.RandomState(0)
    x = rng.randn(global_batch, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, global_batch).astype(np.int32)
    w = np.ones(global_batch, np.float32)
    batch = ddp.shard((x, y, w))

    for _ in range(warmup):
        state, metrics = ddp.train_step(state, batch)
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = ddp.train_step(state, batch)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0

    sps = steps * global_batch / dt
    log(f"tpuddp: {sps:,.0f} samples/s total, {sps / n_chips:,.0f} /chip, {dt:.3f}s")
    return sps / n_chips, n_chips


def bench_torch_cpu(batch=128, steps=30, warmup=3):
    """The reference stack's hot loop on this host (torch CPU)."""
    try:
        import torch
        import torch.nn as tnn
    except Exception as e:  # pragma: no cover
        log(f"torch unavailable ({e}); vs_baseline=1.0")
        return None

    torch.manual_seed(0)
    model = tnn.Sequential(
        tnn.Flatten(),
        tnn.Linear(32 * 32 * 3, 256),
        tnn.ReLU(),
        tnn.Linear(256, 128),
        tnn.ReLU(),
        tnn.Linear(128, 10),
    )
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    criterion = tnn.CrossEntropyLoss()
    x = torch.randn(batch, 3, 32, 32)
    y = torch.randint(0, 10, (batch,))

    def step():
        opt.zero_grad()
        loss = criterion(model(x), y)
        loss.backward()
        opt.step()
        return float(loss.item())  # the reference's per-batch sync (quirk Q5)

    for _ in range(warmup):
        step()
    t0 = time.perf_counter()
    for _ in range(steps):
        step()
    dt = time.perf_counter() - t0
    sps = steps * batch / dt
    log(f"torch-cpu baseline: {sps:,.0f} samples/s")
    return sps


def main():
    ours, n_chips = bench_tpuddp()
    baseline = bench_torch_cpu()
    vs = ours / baseline if baseline else 1.0
    print(
        json.dumps(
            {
                "metric": "toy_mlp_train_samples_per_sec_per_chip",
                "value": round(ours, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
