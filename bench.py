"""Benchmark: samples/sec/chip on the reference workload (BASELINE.json metric).

Configs measured (BASELINE.md targets):
- toy MLP, per-chip batch 128, scan-fused (the BASELINE.json headline) -> stdout
- toy MLP per-step dispatch (quantifies the per-dispatch tunnel penalty)
- AlexNet-class 224x224: f32 per-step, f32 + bf16 scan-fused
- ResNet-18 @ native 32x32 with sync-BN, bf16 scan-fused (plus the same row
  under the bf16_ef compressed comm hook — the grad_comm_bytes_per_step pair
  records the gradient-byte reduction as a measured artifact)
- the Bottleneck/VGG halves of the zoo: VGG-11 and ResNet-50 @ 224 (bf16,
  scan-fused, device-MFU recorded like every row); ResNet-101 @ 224 only
  under ``--slow`` / ``$TPUDDP_BENCH_SLOW=1``
- managed (Accelerator) toy MLP: eager per-batch sync (reference-parity mode)
  and deferred-metrics mode

All runs are the FULL DP train step (device-side uint8 augmentation for the
CNNs, forward, backward, grad pmean, Adam update, on-device metrics), matching
the reference hot loop (multi-GPU-training-torch.py:109-132) with per-chip
batch 128 / Adam lr=1e-3 / cross-entropy.

Per config the JSON reports measured MFU: FLOPs are taken from XLA's compiled
cost analysis of the exact program being timed (so fwd+bwd+optimizer+augment,
not a hand model), divided by wall time and the chip's bf16 peak.

Timing methodology: steps are dispatched as an async dependency chain and the
clock stops on a *value fetch* from the final step's metrics — on remote-
tunneled TPU runtimes ``block_until_ready`` can return before execution
completes, so fetching is the only honest fence. Single-step configs measure
dispatch-rate through the tunnel, NOT chip compute — that is exactly what the
scan-fused variants exist to show (see BASELINE.md). The fence itself costs
~100 ms of tunnel RTT once per timed region, so configs compared against each
other (native per-step vs managed) time the SAME number of steps per fetch —
otherwise the comparison measures fence amortization, not the paths.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
baseline is measured here: the same toy-MLP workload through the reference's
stack (torch + Adam + per-batch loss.item(), its quirk Q5 sync included) on
this host's available torch device (CPU — the reference's CUDA path needs
NVIDIA hardware that does not exist on a TPU host).

Output contract (driver-parseable): the FULL results dict is written to
``bench_results.json`` next to this script, and the LAST stdout line is one
compact machine-readable JSON summary (headline metric/value/unit,
vs_baseline, device, config count, results path). Everything else —
per-config lines, warnings, failures — goes to stderr. The big-model tail
(ResNet-101 @ 224) runs only under ``--slow`` / ``$TPUDDP_BENCH_SLOW=1``.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# Peak bf16 MXU FLOP/s per chip by device kind — ONE table for the bench and
# the training-loop telemetry (tpuddp/observability/recorder.py). MFU is
# always reported against the bf16 peak: on TPU, f32 matmuls execute on the
# MXU with bf16 multiplies by default, so bf16 peak is the one ceiling.
from tpuddp.observability import PEAK_FLOPS  # noqa: E402

RESULTS = {}  # name -> {samples_per_sec_per_chip, ms_per_step, mfu}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _program_flops(jitted, *args):
    """FLOPs of one execution of ``jitted(*args)`` from XLA cost analysis
    (compiled if available, HLO estimate otherwise); None when unsupported."""
    try:
        lowered = jitted.lower(*args)
        try:
            cost = lowered.compile().cost_analysis()
        except Exception:
            cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception as e:
        log(f"  cost_analysis unavailable ({type(e).__name__}: {e})")
        return None


def _peak_flops():
    import jax

    kind = jax.devices()[0].device_kind
    return PEAK_FLOPS.get(kind), kind


def _record(name, sps_per_chip, ms_per_step, flops_per_chip_step, extra=None):
    peak, _ = _peak_flops()
    mfu = None
    if flops_per_chip_step and peak:
        mfu = flops_per_chip_step / (ms_per_step / 1e3) / peak
    RESULTS[name] = {
        "samples_per_sec_per_chip": round(sps_per_chip, 1),
        "ms_per_step": round(ms_per_step, 3),
        "mfu": round(mfu, 4) if mfu is not None else None,
    }
    if extra:
        RESULTS[name].update(extra)
    # async-pipeline columns on EVERY row (tpuddp/training/pipeline.py):
    # wall/device ratio and host-stall percentiles. Rows that pre-stage their
    # buffers have no host loader, so their stall is a structural 0; rows
    # without a device-time estimate carry null rather than a guess.
    for k in ("wall_to_device_ratio", "host_stall_ms_p50", "host_stall_ms_p95"):
        RESULTS[name].setdefault(k, None)
    mfu_s = f", MFU {mfu * 100:.1f}%" if mfu is not None else ""
    w2d = RESULTS[name]["wall_to_device_ratio"]
    w2d_s = f", wall/device {w2d:.2f}" if w2d is not None else ""
    log(f"{name}: {sps_per_chip:,.0f} samples/s/chip, {ms_per_step:.2f} ms/step{mfu_s}{w2d_s}")


def _make_runner(ddp, state_box, batch, scan, laps=None):
    """Build run(n_steps) over pre-staged device buffers. Warmup calls must
    reuse the SAME buffers that are timed later: device_put is lazy on
    remote-tunneled runtimes, so a buffer's first use pays its upload.

    ``laps`` (a list) collects one wall-clock lap per dispatch — the raw
    material for the per-row step-time percentiles. The laps are taken
    WITHOUT per-dispatch fences (the timing-honesty contract above forbids
    extra fences inside the timed region), so they measure dispatch
    resolution; under device backpressure they converge to execution time,
    and the row's mean (fenced once, at the fetch) remains the headline."""
    from tpuddp.training.step import stack_batches

    if scan > 1:
        stacked = ddp.shard_stacked(
            stack_batches([tuple(np.asarray(b) for b in batch)] * scan)
        )

        def run(steps):
            outer = max(1, steps // scan)
            metrics = None
            t_prev = time.perf_counter()
            for _ in range(outer):
                state_box[0], metrics = ddp.train_step_many(state_box[0], stacked)
                if laps is not None:
                    t_now = time.perf_counter()
                    laps.append((t_now - t_prev) / scan)
                    t_prev = t_now
            loss_sum = float(np.sum(np.asarray(metrics["loss_sum"])))  # fence
            assert np.isfinite(loss_sum)
            return outer * scan

    else:

        def run(steps):
            metrics = None
            t_prev = time.perf_counter()
            for _ in range(steps):
                state_box[0], metrics = ddp.train_step(state_box[0], batch)
                if laps is not None:
                    t_now = time.perf_counter()
                    laps.append(t_now - t_prev)
                    t_prev = t_now
            loss_sum = float(np.sum(np.asarray(metrics["loss_sum"])))
            assert np.isfinite(loss_sum)
            return steps

    return run


def bench_config(
    name, model, in_shape, batch_per_chip, steps, augment=None,
    x_dtype=np.float32, scan=1, opt=None, comm_hook="none",
):
    import jax
    import jax.numpy as jnp

    from tpuddp import nn, optim
    from tpuddp.parallel import make_mesh
    from tpuddp.parallel.ddp import DistributedDataParallel
    from tpuddp.training.step import stack_batches

    opt = opt or (lambda: optim.Adam(1e-3))
    devices = jax.devices()
    mesh = make_mesh(devices)
    n_chips = len(devices)
    global_batch = batch_per_chip * n_chips

    ddp = DistributedDataParallel(
        model, opt(), nn.CrossEntropyLoss(), mesh=mesh,
        mode="shard_map", augment=augment, comm_hook=comm_hook,
    )
    model_in = in_shape if augment is None else augment(
        jax.random.key(0), jnp.zeros((1,) + in_shape, x_dtype)
    ).shape[1:]
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1,) + tuple(model_in)))

    rng = np.random.RandomState(0)
    if np.issubdtype(x_dtype, np.integer):
        x = rng.randint(0, 256, (global_batch,) + in_shape).astype(x_dtype)
    else:
        x = rng.randn(global_batch, *in_shape).astype(x_dtype)
    y = rng.randint(0, 10, global_batch).astype(np.int32)
    w = np.ones(global_batch, np.float32)
    batch = ddp.shard((x, y, w))

    state_box = [state]
    laps = []
    run = _make_runner(ddp, state_box, batch, scan, laps=laps)
    run(max(3, scan))  # compile + stage all buffers (lazy-upload warm)
    run(max(3, scan))  # second warm pass: steady-state dispatch path
    laps.clear()  # percentiles cover the timed region only
    t0 = time.perf_counter()
    steps = run(steps)
    dt = time.perf_counter() - t0

    # FLOPs of the step actually timed, cross-checked at runtime rather than
    # assumed (two backend/version-dependent conventions could each skew the
    # published MFU by Kx or Nx):
    #  1. scan counting: XLA's cost analysis counts a while/scan body once in
    #     most versions (scan-program flops ~= single-step program flops); if
    #     this backend instead counts the body K times, the ratio test below
    #     detects it and divides by K. Anything else -> MFU suppressed.
    #  2. chip counting: the figure may be whole-program or per-device. With
    #     n_chips > 1 a 1-device probe of the same per-chip workload
    #     disambiguates; an unresolvable ratio -> MFU suppressed.
    flops_note = None
    flops_per_chip = None
    try:
        bx, by, bw = batch
        f_single = _program_flops(
            jax.jit(lambda s, a, b, c: ddp.train_step(s, (a, b, c))),
            state_box[0], bx, by, bw,
        )
        f_step = f_single
        if scan > 1 and f_single:
            stacked = ddp.shard_stacked(
                stack_batches([tuple(np.asarray(b) for b in batch)] * scan)
            )
            xs, ys, ws = stacked
            f_scan = _program_flops(
                jax.jit(lambda s, a, b, c: ddp.train_step_many(s, (a, b, c))),
                state_box[0], xs, ys, ws,
            )
            ratio = (f_scan or 0.0) / f_single
            if 0.75 <= ratio <= 1.33:
                f_step = f_scan  # body counted once (the usual convention)
            elif abs(ratio - scan) / scan <= 0.33:
                f_step = f_scan / scan  # body counted per trip
            else:
                f_step = None
                flops_note = f"scan/single flops ratio {ratio:.2f} unresolvable"
                log(f"  MFU suppressed: {flops_note}")
        if f_step and n_chips > 1:
            # Disambiguate whole-program vs per-device module flops.
            from tpuddp.parallel import make_mesh as _mk
            ddp1 = DistributedDataParallel(
                model, opt(), nn.CrossEntropyLoss(),
                mesh=_mk(devices[:1]), mode="shard_map", augment=augment,
            )
            b1 = ddp1.shard((x[:batch_per_chip], y[:batch_per_chip], w[:batch_per_chip]))
            f_1dev = _program_flops(
                jax.jit(lambda s, a, b, c: ddp1.train_step(s, (a, b, c))),
                state_box[0], *b1,
            )
            if f_1dev:
                r = f_step / f_1dev
                if abs(r - n_chips) / n_chips <= 0.25:
                    flops_per_chip = f_step / n_chips  # whole-program figure
                elif 0.75 <= r <= 1.33:
                    flops_per_chip = f_step  # per-device figure
                else:
                    flops_note = f"{n_chips}-chip/1-chip flops ratio {r:.2f} unresolvable"
                    log(f"  MFU suppressed: {flops_note}")
        elif f_step:
            flops_per_chip = f_step
    except Exception as e:
        log(f"  flops probe failed ({type(e).__name__}: {e})")

    # Model-only MFU: subtract the augment pipeline's FLOPs (resize/flip/
    # normalize) from the whole-program numerator so model-compute utilization
    # isn't flattered by input-pipeline FLOPs (measured ~0.3% on AlexNet@224 —
    # reported so the distinction is auditable, not because it moves much).
    extra = {}
    if flops_per_chip and augment is not None:
        try:
            k0 = jax.random.key(0)
            xp = x[:batch_per_chip]
            aug_flops = _program_flops(jax.jit(lambda r, v: augment(r, v)), k0, xp)
            if aug_flops and aug_flops < flops_per_chip:
                peak, _ = _peak_flops()
                if peak:
                    extra["mfu_model"] = round(
                        (flops_per_chip - aug_flops) / (dt / steps) / peak, 4
                    )
        except Exception as e:
            log(f"  augment flops probe failed ({type(e).__name__}: {e})")
    if flops_note:
        extra["mfu_note"] = flops_note
    # step-time percentiles over the timed region's per-dispatch laps (the
    # observability recorder's percentile code — one definition for bench
    # rows and history.jsonl): a straggling dispatch or a mid-run slowdown
    # shows up as a p95/p99 >> p50, invisible in the mean
    if laps:
        from tpuddp.observability import percentiles as _pct

        pct = _pct(laps)
        extra.update({
            f"ms_per_step_{k}": round(v * 1e3, 3)
            for k, v in pct.items() if v is not None
        })
        extra["timed_dispatches"] = len(laps)
        # wall/device estimator for pre-staged rows: mean timed step (the
        # headline, fence-amortized) over the p50 dispatch lap — under device
        # backpressure the laps converge to execution time, so the ratio
        # isolates the fence/host share. Host stall is a structural 0 here:
        # these rows reuse one pre-staged buffer, no host loader runs (the
        # --pipeline A/B rows measure the real loader-fed ratio).
        if pct.get("p50"):
            extra["wall_to_device_ratio"] = round(
                (dt / steps) / pct["p50"], 3
            )
        extra["host_stall_ms_p50"] = 0.0
        extra["host_stall_ms_p95"] = 0.0
    # per-step gradient-comm wire bytes (parallel/comm.py accounting): the
    # compressed hooks' byte reduction as a recorded bench artifact
    if ddp.grad_comm_bytes_per_step is not None:
        extra["grad_comm_bytes_per_step"] = int(ddp.grad_comm_bytes_per_step)
        if comm_hook != "none":
            extra["comm_hook"] = comm_hook

    sps = steps * global_batch / dt
    _record(name, sps / n_chips, dt / steps * 1e3, flops_per_chip, extra or None)
    return sps / n_chips, n_chips


def bench_managed(batch_per_chip=128, steps=60, deferred=False, fuse=1):
    """The managed (Accelerator) path on the toy MLP — BASELINE.json
    configs[2]. Eager mode keeps the reference's per-batch loss.item() sync
    (quirk Q3/Q5 parity); deferred mode syncs once at the end; fuse > 1 adds
    K-step scan fusion behind the Accelerator (the managed analog of the
    native scan-fused path)."""
    import jax
    import jax.numpy as jnp

    from tpuddp import nn, optim
    from tpuddp.accelerate import Accelerator
    from tpuddp.models import ToyMLP
    from tpuddp.parallel import make_mesh

    mesh = make_mesh(jax.devices())
    n_chips = mesh.devices.size
    global_batch = batch_per_chip * n_chips
    acc = Accelerator(mesh=mesh, seed=0, fuse_steps=fuse)
    model, opt = acc.prepare(ToyMLP(num_classes=10), optim.Adam(1e-3))
    criterion = nn.CrossEntropyLoss()

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(global_batch, 32, 32, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, global_batch).astype(np.int32))

    def run(n):
        losses = []
        total = 0.0
        for _ in range(n):
            opt.zero_grad()
            loss = criterion(model(x), y)
            acc.backward(loss)
            opt.step()
            if deferred:
                losses.append(loss)  # values land when the queue flushes
            else:
                total += loss.item()
        if deferred:
            # sum on device array-at-a-time over fused flushes; one fetch
            from tpuddp.accelerate import sum_losses

            total = float(sum_losses(losses))
        assert np.isfinite(total)

    # warm twice with >= 2 flushes each so every program the timed run uses is
    # compiled: the fused-scan (both pre- and post-donation operand layouts)
    # AND sum_losses' scalar add between flush arrays
    run(2 * max(3, fuse))
    run(2 * max(3, fuse))
    t0 = time.perf_counter()
    run(steps)
    dt = time.perf_counter() - t0
    sps = steps * global_batch / dt
    mode = "deferred" if deferred else "eager per-batch sync"
    if fuse > 1:
        mode += f", {fuse}-step fused"
    _record(f"managed toy_mlp ({mode})", sps / n_chips, dt / steps * 1e3, None)
    return sps / n_chips


def bench_managed_alexnet(batch_per_chip=128, steps=96, fuse=32):
    """The managed (Accelerator) path on the compute-bound flagship config —
    AlexNet s2d bf16 @224, bf16 Adam moments, deferred metrics, fuse_steps
    scan — so the 'native and managed compile to the same step program' claim
    is a measured fact on a real CNN, not an inference from the toy model
    (reference managed entrypoint: multi-GPU-training-accelerate.py:39-56).
    Compare against the native 'alexnet bf16 224 bf16-opt s2d (scan-fused)'
    row: same model, batch, optimizer, augment, and fusion depth."""
    import jax
    import jax.numpy as jnp

    from tpuddp import nn, optim
    from tpuddp.accelerate import Accelerator
    from tpuddp.data.transforms import make_train_augment
    from tpuddp.models import AlexNet
    from tpuddp.parallel import make_mesh

    mesh = make_mesh(jax.devices())
    n_chips = mesh.devices.size
    global_batch = batch_per_chip * n_chips
    acc = Accelerator(mesh=mesh, seed=0, fuse_steps=fuse)
    model, opt = acc.prepare(
        AlexNet(10, space_to_depth=True),
        optim.Adam(1e-3, state_dtype="bfloat16"),
    )
    criterion = nn.CrossEntropyLoss()
    _aug = make_train_augment(size=224, compute_dtype=jnp.bfloat16)
    augment = jax.jit(lambda rng, i, x: _aug(jax.random.fold_in(rng, i), x))

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, 256, (global_batch, 32, 32, 3)).astype(np.uint8))
    y = jnp.asarray(rng.randint(0, 10, global_batch).astype(np.int32))
    aug_base = acc.next_rng_key()
    # stage ONE augmented batch and reuse it, exactly like the native row
    # reuses its pre-staged stacked batch — the timed region then measures
    # the managed STEP path, not per-step augment dispatch/upload overhead
    # (w=None hits the prepared model's cached all-ones weights)
    xb = augment(aug_base, 0, x)

    def run(n):
        from tpuddp.accelerate import sum_losses

        losses = []
        for _ in range(n):
            opt.zero_grad()
            loss = criterion(model(xb), y)
            acc.backward(loss)
            opt.step()
            losses.append(loss)
        total = float(sum_losses(losses))  # one fetch; fences the chain
        assert np.isfinite(total)

    run(2 * fuse)
    run(2 * fuse)
    t0 = time.perf_counter()
    run(steps)
    dt = time.perf_counter() - t0
    sps = steps * global_batch / dt
    _record(
        f"managed alexnet bf16 224 bf16-opt s2d (deferred, {fuse}-step fused)",
        sps / n_chips, dt / steps * 1e3, None,
    )
    return sps / n_chips


def bench_managed_eval(batch_per_chip=128, batches=256, fused=True, fuse_k=None):
    """The managed eval pass on the toy MLP: the facade loop (2+ dispatches
    per test batch: transform, forward, plus per-batch metric ops) vs the
    FusedEvaluator (ONE scan dispatch per K batches + one final fetch — the
    managed analog of the native eval scan). ``fuse_k=None`` measures the
    product default (size-resolved K)."""
    import jax
    import jax.numpy as jnp

    from tpuddp import nn
    from tpuddp.accelerate import Accelerator, FusedEvaluator
    from tpuddp.data.transforms import make_eval_transform
    from tpuddp.models import ToyMLP
    from tpuddp.parallel import make_mesh

    mesh = make_mesh(jax.devices())
    n_chips = mesh.devices.size
    acc = Accelerator(mesh=mesh, seed=0)
    model = acc.prepare(ToyMLP(num_classes=10))
    model.eval()
    criterion = nn.CrossEntropyLoss()
    transform = jax.jit(make_eval_transform(size=None))

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch_per_chip, 32, 32, 3).astype(np.float32))
    y = np.ascontiguousarray(rng.randint(0, 10, batch_per_chip).astype(np.int32))
    w = np.ones(batch_per_chip, np.float32)
    model(np.asarray(x[:1]))  # init params

    if fused:
        ev = FusedEvaluator(model, criterion, transform=transform, fuse_steps=fuse_k)
        # the product default (flat 32; toy batches are far under the
        # staging budget so the probe matches the in-run resolution)
        fuse_k = ev._resolve_fuse()

        def run(n):
            for _ in range(n):
                ev.add(x, y, w)
            loss_sum, _, total = ev.finalize()
            assert np.isfinite(loss_sum) and total == n * batch_per_chip
    else:
        fuse_k = fuse_k or 8  # warmup count only; the facade has no fusion

        def run(n):
            loss_sum = 0.0
            for _ in range(n):
                outputs = model(transform(x))
                loss_sum += criterion(outputs, y, w).item()
            assert np.isfinite(loss_sum)

    run(2 * fuse_k)
    run(2 * fuse_k)
    t0 = time.perf_counter()
    run(batches)
    dt = time.perf_counter() - t0
    sps = batches * batch_per_chip / dt  # full batch on every chip (quirk Q3)
    mode = f"scan-fused K={fuse_k}" if fused else "per-batch facade"
    _record(f"managed eval toy_mlp ({mode})", sps, dt / batches * 1e3, None)
    return sps


def _device_ms_denominator(ddp, state, stacked, scan):
    """Per-step device time of ONE wrap's compiled scan step, measured over a
    pre-staged chunk and fenced once — the denominator of a row's
    ``wall_to_device_ratio``.

    The denominator is only honest for rows dispatching the SAME compiled
    program it was measured under. ``--pipeline`` shares one wrap across its
    on/off rows (the pipeline's HLO-identity contract), so one derivation
    covers both; ``--overlap`` compiles a DIFFERENT step program per row (K
    interleaved collectives vs one trailing block), so each row re-derives
    its denominator here instead of inheriting the other program's number."""
    metrics = None
    for _ in range(2):  # compile + warm
        state, metrics = ddp.train_step_many(state, stacked)
    float(np.sum(np.asarray(metrics["loss_sum"])))
    n_dev = max(4, 32 // scan)
    t0 = time.perf_counter()
    for _ in range(n_dev):
        state, metrics = ddp.train_step_many(state, stacked)
    float(np.sum(np.asarray(metrics["loss_sum"])))  # fence
    return (time.perf_counter() - t0) / (n_dev * scan) * 1e3


def bench_pipeline_pair(batch_per_chip=64, n_train=4096, repeats=2, scan=8):
    """The async-pipeline A/B (``--pipeline``): one epoch of the REAL
    loader-fed training pass (ShardedDataLoader -> staged chunks -> K-fused
    dispatch) on a CNN, measured twice through the actual pipelined runner
    (tpuddp/training/pipeline.py):

    - ``pipeline off``: the synchronous reference — no loader workers, no
      staged lookahead, one blocking readback per dispatch (the serial
      cadence whose cost BASELINE.md's dispatch-RTT section documents);
    - ``pipeline on``: the product default shape (host workers + deep staged
      queue + deferred readback drain).

    Both rows share one device-time denominator — the same step program
    dispatched over a pre-staged chunk, fenced once — so
    ``wall_to_device_ratio`` is comparable: the pipeline's whole claim is
    that the ON row's ratio sits closer to 1.0. Bitwise parity of the two
    passes is asserted in-run (same seed, same data order -> identical final
    loss sums), not just in the test suite."""
    import jax
    import jax.numpy as jnp

    from tpuddp import nn, optim
    from tpuddp.data import PrefetchLoader, ShardedDataLoader
    from tpuddp.data.synthetic import synthetic_uint8_datasets
    from tpuddp.data.transforms import make_train_augment
    from tpuddp.models import ToyCNN
    from tpuddp.parallel import make_mesh
    from tpuddp.parallel.ddp import DistributedDataParallel
    from tpuddp.training import pipeline as pipe
    from tpuddp.training.step import stack_batches

    mesh = make_mesh(jax.devices())
    n_chips = mesh.devices.size
    train_ds, _ = synthetic_uint8_datasets(n_train, 64, seed=0)
    augment = make_train_augment(size=None)  # on-device normalize (in-step)

    class _Cap:
        """Telemetry stub capturing per-dispatch host-stall laps."""

        def __init__(self):
            self.stalls = []

        def offer_batch(self, b):
            pass

        def pre_dispatch(self, n):
            pass

        def post_dispatch(self, n, s, fence=None, host_stall_s=0.0, **occ):
            self.stalls.append(host_stall_s)

    # ONE wrap for both rows: the compiled step programs are shared (the
    # pipeline never enters program construction — its HLO-identity
    # contract), and each row re-inits the state from the same key, so the
    # A/B isolates the host pipeline and nothing else. widths=(8, 16): a
    # real conv net sized so the pair stays O(minutes) on the CPU rung too.
    ddp = DistributedDataParallel(
        ToyCNN(10, widths=(8, 16)), optim.Adam(1e-3), nn.CrossEntropyLoss(),
        mesh=mesh, mode="shard_map", augment=augment,
    )

    def fresh_state():
        return ddp.init_state(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))

    def one_pass(state, loader, cfg, cap=None):
        state, acc, _ = pipe.run_pass(
            ddp, state, loader, scan, ddp.train_step, ddp.train_step_many,
            cfg=cfg, tel=cap,
        )
        # the fence: one value fetch from the accumulated metrics
        loss_sum = float(np.sum(np.asarray(acc["loss_sum"])))
        assert np.isfinite(loss_sum)
        return state, loss_sum

    # shared device-time denominator: the same scan program over ONE
    # pre-staged chunk, fenced once — what the chip does with zero host work
    base_loader = ShardedDataLoader(
        train_ds, batch_per_chip, mesh, shuffle=True, seed=0
    )
    base_loader.set_epoch(0)
    first_chunk = []
    for b in base_loader:
        first_chunk.append(b)
        if len(first_chunk) == scan:
            break
    stacked = ddp.shard_stacked(stack_batches(first_chunk))
    device_ms = _device_ms_denominator(ddp, fresh_state(), stacked, scan)
    # one derivation for both rows is correct HERE because both rows
    # dispatch this one wrap's program (see _device_ms_denominator — rows
    # that change the step program, like --overlap's, must re-derive)
    assert not (ddp.comm_overlap_meta or {}).get("enabled"), (
        "pipeline A/B shares one device denominator; a segmented wrap "
        "breaks that premise"
    )

    rows = {}
    for on in (False, True):
        if on:
            cfg = pipe.PipelineConfig(depth=4, host_workers=2)
        else:
            cfg = pipe.SYNCHRONOUS
        state = fresh_state()
        loader = ShardedDataLoader(
            train_ds, batch_per_chip, mesh, shuffle=True, seed=0
        )
        if on and cfg.host_workers:
            loader = PrefetchLoader(loader, workers=cfg.host_workers)
        loader.set_epoch(0)
        state, _ = one_pass(state, loader, cfg)  # warm/compile epoch
        cap = _Cap()
        n_steps = len(loader) * repeats
        samples = 0
        t0 = time.perf_counter()
        loss_sums = []
        for ep in range(1, repeats + 1):
            loader.set_epoch(ep)
            state, loss_sum = one_pass(state, loader, cfg, cap=cap)
            loss_sums.append(loss_sum)
            samples += len(train_ds)
        dt = time.perf_counter() - t0
        wall_ms = dt / n_steps * 1e3
        from tpuddp.observability import percentiles as _pct

        pct = _pct(cap.stalls)
        name = (
            f"toy_cnn b{batch_per_chip} loader-fed "
            + ("(pipeline on, depth 4)" if on else "(pipeline off, synchronous)")
        )
        extra = {
            "wall_to_device_ratio": round(wall_ms / device_ms, 3),
            "device_ms_per_step": round(device_ms, 3),
            "host_stall_ms_p50": round((pct["p50"] or 0.0) * 1e3, 3),
            "host_stall_ms_p95": round((pct["p95"] or 0.0) * 1e3, 3),
            "pipeline": cfg.as_dict(),
        }
        _record(name, samples / dt / n_chips, wall_ms, None, extra)
        rows[on] = {"sps": samples / dt / n_chips, "loss_sums": loss_sums}
    # bitwise parity of the A/B itself: same seed + same data order must give
    # the same trajectory whichever way the host pipeline ran
    assert rows[True]["loss_sums"] == rows[False]["loss_sums"], (
        "pipeline on/off trajectories diverged: "
        f"{rows[True]['loss_sums']} vs {rows[False]['loss_sums']}"
    )
    return rows[True]["sps"], rows[False]["sps"]


def bench_overlap_pair(batch_per_chip=64, steps=96, hooks=("none", "bf16_ef")):
    """The segmented backward/collective overlap A/B (``--overlap``): the
    same fixed toy-MLP workload per hook, compiled twice — ``comm_overlap``
    off (the barrier step: all collectives in one trailing block) and on
    (bucket-aligned backward segments, each segment's collective issued
    inside the backward walk, training/step.py). Per row:

    - throughput + per-step latency (mean and p50/p99 over unfenced laps);
    - ``wall_to_device_ratio`` with a PER-ROW device denominator — the two
      modes compile DIFFERENT step programs, so a denominator staged under
      one program is not the device time of the other
      (:func:`_device_ms_denominator`);
    - the overlap provenance (enabled/segments) and the HLO
      collective-position evidence (:func:`tpuddp.parallel.comm
      .hlo_overlap_evidence` over the lowered step): collective line
      positions, compute line count, and how many backward-compute lines
      fall between the first and last collective issue.

    In-run assertions make the artifact self-verifying: bitwise
    loss-trajectory parity overlap-on vs off for every hook row, and the ON
    row's program holds >= 2 collectives with compute between them while the
    OFF row's collectives form one block. CPU-rung honesty: the host backend
    executes collectives inline, so the throughput delta here is dispatch
    noise, not a latency-hiding win — the artifact's transferable claim is
    the program SHAPE the interleaving evidence records, which is what a
    real TPU's async collective scheduler exploits.

    Returns ``(overlap_on_sps, overlap_off_sps)`` of the last hook for the
    summary line."""
    import jax
    import jax.numpy as jnp

    from tpuddp import nn, optim
    from tpuddp.models import ToyMLP
    from tpuddp.observability import percentiles as _pct
    from tpuddp.parallel import comm as comm_lib
    from tpuddp.parallel import make_mesh
    from tpuddp.parallel.ddp import DistributedDataParallel
    from tpuddp.training.step import stack_batches

    mesh = make_mesh(jax.devices())
    n_chips = mesh.devices.size
    global_batch = batch_per_chip * n_chips
    rng = np.random.RandomState(7)
    x = rng.randn(global_batch, 8, 8, 3).astype(np.float32)
    y = rng.randint(0, 10, global_batch).astype(np.int32)
    w = np.ones(global_batch, np.float32)
    # a cap of 600 f32 elements splits ToyMLP(hidden=(16,))'s two Linears
    # into separate buckets, so the segmented step genuinely gets K=2
    cap = 600 * 4 / (1024 * 1024)

    sps_pair = {}
    for hook in hooks:
        rows = {}
        for overlap in (False, True):
            ddp = DistributedDataParallel(
                ToyMLP(hidden=(16,)), optim.Adam(1e-2),
                nn.CrossEntropyLoss(), mesh=mesh, mode="shard_map",
                comm_hook=hook, bucket_cap_mb=cap, comm_overlap=overlap,
            )
            state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
            meta = ddp.comm_overlap_meta
            batch = ddp.shard((x, y, w))
            # per-row device denominator over the pre-staged batch: the
            # overlap knob changes the compiled program, so each mode's
            # denominator comes from ITS program (the satellite fix)
            stacked = ddp.shard_stacked(stack_batches([(x, y, w)] * 4))
            device_ms = _device_ms_denominator(ddp, state, stacked, 4)
            # fresh state: the denominator loop donated its buffers
            state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
            # warm the per-step program (also builds ddp._train_step)
            metrics = None
            for _ in range(3):
                state, metrics = ddp.train_step(state, batch)
            float(np.sum(np.asarray(metrics["loss_sum"])))
            # lowered-HLO evidence from the exact step being timed
            xs, ys, ws = batch
            ev = comm_lib.hlo_overlap_evidence(
                ddp._train_step.jitted.lower(state, xs, ys, ws).as_text()
            )
            laps = []
            t_prev = t0 = time.perf_counter()
            for _ in range(steps):
                state, metrics = ddp.train_step(state, batch)
                t_now = time.perf_counter()
                laps.append(t_now - t_prev)
                t_prev = t_now
            loss_sum = float(np.sum(np.asarray(metrics["loss_sum"])))  # fence
            dt = time.perf_counter() - t0
            final_loss = loss_sum / float(np.sum(np.asarray(metrics["n"])))
            assert np.isfinite(final_loss), (hook, overlap)
            # the parity trajectory: a fresh state through the first 8 steps,
            # losses fetched per step (outside the timed region)
            traj_state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
            traj = []
            for _ in range(8):
                traj_state, m = ddp.train_step(traj_state, batch)
                mh = np.asarray(m["loss_sum"])
                traj.append(float(np.sum(mh)))
            pct = _pct(laps)
            wall_ms = dt / steps * 1e3
            name = (
                f"toy_mlp b{batch_per_chip} comm {hook} "
                + ("(overlap on)" if overlap else "(overlap off, barrier)")
            )
            sps = steps * global_batch / dt
            extra = {
                "comm_hook": hook,
                "comm_overlap": bool(meta["enabled"]),
                "comm_overlap_segments": meta["segments"],
                "ms_per_step_p50": round((pct["p50"] or 0.0) * 1e3, 3),
                "ms_per_step_p99": round((pct["p99"] or 0.0) * 1e3, 3),
                "wall_to_device_ratio": round(wall_ms / device_ms, 3),
                "device_ms_per_step": round(device_ms, 3),
                "grad_comm_bytes_per_step": int(ddp.grad_comm_bytes_per_step),
                "hlo_collective_lines": ev["collective_lines"],
                "hlo_compute_lines": len(ev["compute_lines"]),
                "hlo_interleaved_compute": len(ev["interleaved_compute"]),
                "hlo_interleaved": ev["interleaved"],
                "final_loss": round(final_loss, 6),
            }
            _record(name, sps / n_chips, wall_ms, None, extra)
            rows[overlap] = {"sps": sps / n_chips, "traj": traj, "ev": ev,
                             "meta": meta}
        # self-verification: the bitwise-parity and program-shape claims
        assert rows[True]["traj"] == rows[False]["traj"], (
            f"{hook}: overlap on/off trajectories diverged: "
            f"{rows[True]['traj']} vs {rows[False]['traj']}"
        )
        assert rows[True]["meta"]["enabled"] and rows[True]["meta"]["segments"] >= 2
        ev_on, ev_off = rows[True]["ev"], rows[False]["ev"]
        assert len(ev_on["collective_lines"]) >= 2 and ev_on["interleaved"], ev_on
        assert not ev_off["interleaved"], ev_off
        log(f"overlap A/B {hook}: K={rows[True]['meta']['segments']} segments, "
            f"{len(ev_on['interleaved_compute'])} compute lines between "
            "collectives (barrier: 0), trajectories bitwise-identical")
        sps_pair = (rows[True]["sps"], rows[False]["sps"])
    return sps_pair


def bench_comm_matrix(batch_per_chip=64, steps=96, density=0.1):
    """The comm-compression-v2 A/B matrix (``--comm``): every hook
    (none/bf16_ef/int8_ef/topk_ef) x topology (flat/hierarchical) pair on
    the same fixed toy-MLP workload over all local devices — the ISSUE 9
    acceptance artifact (BENCH_r07.json). Per row: throughput, per-step
    gradient wire bytes (total + the inter-/intra-host hop split), the
    hook's density, and the final mean loss. In-run assertions make the
    artifact self-verifying rather than a claim:

    - ``int8_ef`` cuts >= 70% and ``topk_ef`` (density 0.1) >= 85% of the
      f32 gradient wire bytes on the explicit flat path;
    - every compressed run's final loss tracks the uncompressed flat run
      within the documented per-hook bound
      (:func:`tpuddp.parallel.comm.loss_parity_tol` — topk_ef's error
      feedback warms up over ~1/density updates, hence ``steps=96``: the
      matrix compares trajectories past the warmup, where the bound is
      meaningful);
    - hierarchical topology's inter-host bytes are strictly below the flat
      topology's total for the same hook (the reason the topology exists).

    Returns ``(int8_flat_sps, none_flat_sps)`` for the summary line."""
    import jax
    import jax.numpy as jnp

    from tpuddp import nn, optim
    from tpuddp.parallel import comm as comm_lib
    from tpuddp.parallel import make_mesh
    from tpuddp.parallel.ddp import DistributedDataParallel
    from tpuddp.parallel.mesh import hierarchical_mesh
    from tpuddp.models import ToyMLP

    devices = jax.devices()
    n_chips = len(devices)
    global_batch = batch_per_chip * n_chips
    rng = np.random.RandomState(7)
    x = rng.randn(global_batch, 8, 8, 3).astype(np.float32)
    y = rng.randint(0, 10, global_batch).astype(np.int32)
    w = np.ones(global_batch, np.float32)

    topologies = ["flat"]
    if n_chips % 2 == 0 and n_chips >= 2:
        topologies.append("hierarchical")
    else:
        log(f"comm matrix: hierarchical rows skipped ({n_chips} devices "
            "do not factor into a (host, local) split)")

    stats = {}
    for topology in topologies:
        mesh = (
            hierarchical_mesh(devices=devices)
            if topology == "hierarchical"
            else make_mesh(devices)
        )
        for hook in ("none", "bf16_ef", "int8_ef", "topk_ef"):
            ddp = DistributedDataParallel(
                ToyMLP(hidden=(16,)), optim.Adam(1e-2),
                nn.CrossEntropyLoss(), mesh=mesh, mode="shard_map",
                comm_hook=hook, comm_topology=topology, topk_density=density,
            )
            state = ddp.init_state(
                jax.random.key(0), jnp.zeros((1, 8, 8, 3))
            )
            batch = ddp.shard((x, y, w))
            metrics = None
            for _ in range(3):  # compile + warm
                state, metrics = ddp.train_step(state, batch)
            float(np.sum(np.asarray(metrics["loss_sum"])))
            t0 = time.perf_counter()
            for _ in range(steps):
                state, metrics = ddp.train_step(state, batch)
            loss_sum = float(np.sum(np.asarray(metrics["loss_sum"])))  # fence
            dt = time.perf_counter() - t0
            final_loss = loss_sum / float(np.sum(np.asarray(metrics["n"])))
            assert np.isfinite(final_loss), (hook, topology)
            name = f"toy_mlp b{batch_per_chip} comm {hook} {topology}"
            sps = steps * global_batch / dt
            extra = {
                "comm_hook": hook,
                "comm_topology": topology,
                "comm_density": density if hook == "topk_ef" else None,
                "grad_comm_bytes_per_step": int(ddp.grad_comm_bytes_per_step),
                "grad_comm_bytes_per_step_f32": int(
                    ddp.grad_comm_bytes_per_step_f32
                ),
                "grad_comm_bytes_inter_host": int(
                    ddp.grad_comm_bytes_inter_host
                ),
                "grad_comm_bytes_intra_host": int(
                    ddp.grad_comm_bytes_intra_host
                ),
                "final_loss": round(final_loss, 6),
            }
            _record(name, sps / n_chips, dt / steps * 1e3, None, extra)
            stats[(hook, topology)] = {
                "sps": sps / n_chips, "loss": final_loss, **extra,
            }

    base = stats[("none", "flat")]
    f32 = base["grad_comm_bytes_per_step_f32"]
    for hook, floor in (("int8_ef", 0.70), ("topk_ef", 0.85)):
        cut = 1 - stats[(hook, "flat")]["grad_comm_bytes_per_step"] / f32
        assert cut >= floor, (
            f"{hook}: {cut * 100:.1f}% byte cut is under the {floor * 100:.0f}% "
            "acceptance floor"
        )
        log(f"comm matrix: {hook} cuts {cut * 100:.1f}% of gradient wire bytes")
    for (hook, topology), row in stats.items():
        tol = comm_lib.loss_parity_tol(hook, base["loss"])
        assert abs(row["loss"] - base["loss"]) <= tol, (
            f"{hook}/{topology}: final loss {row['loss']:.4f} diverged from "
            f"uncompressed {base['loss']:.4f} (documented tol {tol:.4f})"
        )
    if "hierarchical" in topologies:
        for hook in ("none", "bf16_ef", "int8_ef", "topk_ef"):
            flat_total = stats[(hook, "flat")]["grad_comm_bytes_per_step"]
            inter = stats[(hook, "hierarchical")]["grad_comm_bytes_inter_host"]
            assert inter < flat_total, (
                f"{hook}: hierarchical inter-host bytes {inter} not below "
                f"the flat total {flat_total}"
            )
        log("comm matrix: hierarchical inter-host bytes < flat totals for "
            "every hook")
    return stats[("int8_ef", "flat")]["sps"], base["sps"]


def bench_torch_cpu(batch=128, steps=30, warmup=3):
    """The reference stack's hot loop (toy MLP) on this host (torch CPU)."""
    try:
        import torch
        import torch.nn as tnn
    except Exception as e:  # pragma: no cover
        log(f"torch unavailable ({e}); vs_baseline=1.0")
        return None

    torch.manual_seed(0)
    model = tnn.Sequential(
        tnn.Flatten(),
        tnn.Linear(32 * 32 * 3, 256),
        tnn.ReLU(),
        tnn.Linear(256, 128),
        tnn.ReLU(),
        tnn.Linear(128, 10),
    )
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    criterion = tnn.CrossEntropyLoss()
    x = torch.randn(batch, 3, 32, 32)
    y = torch.randint(0, 10, (batch,))

    def step():
        opt.zero_grad()
        loss = criterion(model(x), y)
        loss.backward()
        opt.step()
        return float(loss.item())  # the reference's per-batch sync (quirk Q5)

    for _ in range(warmup):
        step()
    t0 = time.perf_counter()
    for _ in range(steps):
        step()
    dt = time.perf_counter() - t0
    sps = steps * batch / dt
    log(f"torch-cpu baseline (toy MLP): {sps:,.0f} samples/s")
    return sps


def emit_summary(
    ours, baseline, out_path=None,
    metric="toy_mlp_train_samples_per_sec_per_chip",
    basis="torch-cpu",
):
    """The driver-parseable output contract: the FULL per-config payload goes
    to ``bench_results.json`` (next to this script unless ``out_path``), and
    the returned dict — compact, configs elided — is what :func:`main` prints
    as the LAST stdout line. Keeping the stdout line small and flat is the
    point: the round-5 verdict's ``parsed: null`` came from the full dict
    being the line. ``--pipeline`` mode swaps the headline metric and the
    baseline basis (pipeline-on vs pipeline-off)."""
    vs = ours / baseline if baseline else 1.0
    _, kind = _peak_flops()
    payload = {
        "metric": metric,
        "value": round(ours, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(vs, 2),
        # default basis: the reference stack on this host's only torch
        # device (CPU — no NVIDIA hardware exists here); a chip-vs-CPU
        # ratio, NOT a GPU comparison. Cross-stack correctness evidence is
        # the loss-curve parity tests instead. --pipeline mode uses the
        # pipeline-off row as the basis instead.
        "vs_baseline_basis": basis,
        "device": kind,
        "configs": RESULTS,
    }
    path = out_path or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_results.json"
    )
    # strict JSON on disk: a non-finite row value (a failed/blown-up config)
    # lands as null, never the bare NaN token strict parsers reject
    from tpuddp.observability import json_sanitize

    with open(path, "w") as f:
        json.dump(json_sanitize(payload), f, indent=2, allow_nan=False)
        f.write("\n")
    log(f"full per-config results -> {path}")
    return {
        "metric": payload["metric"],
        "value": payload["value"],
        "unit": payload["unit"],
        "vs_baseline": payload["vs_baseline"],
        "vs_baseline_basis": basis,
        "device": kind,
        "n_configs": len(RESULTS),
        "results_file": os.path.basename(path),
    }


def main(argv=None):
    import jax.numpy as jnp

    from tpuddp.data.transforms import make_train_augment
    from tpuddp.models import (
        AlexNet, ResNet18, ResNet34, ResNet50, ResNet101, ToyMLP, VGG11,
    )

    argv = sys.argv[1:] if argv is None else argv
    slow = "--slow" in argv or os.environ.get("TPUDDP_BENCH_SLOW") == "1"
    out_path = None
    if "--out" in argv:
        i = argv.index("--out")
        if i + 1 >= len(argv) or argv[i + 1].startswith("--"):
            log("--out needs a path argument")
            raise SystemExit(2)
        out_path = argv[i + 1]
    if "--comm" in argv:
        # the comm-compression-v2 A/B matrix (ISSUE 9 acceptance artifact):
        # hook x topology rows with wire-byte accounting + in-run byte-cut /
        # loss-parity / hierarchical-inter-host assertions; the headline is
        # int8_ef-flat throughput against the uncompressed flat baseline
        from tpuddp.observability import json_sanitize

        int8_sps, none_sps = bench_comm_matrix()
        summary = emit_summary(
            int8_sps, none_sps, out_path=out_path,
            metric="toy_mlp_int8_ef_train_samples_per_sec_per_chip",
            basis="comm-hook-none",
        )
        print(json.dumps(json_sanitize(summary), allow_nan=False), flush=True)
        return
    if "--overlap" in argv:
        # the segmented backward/collective overlap A/B: per-hook on/off row
        # pairs with per-row device denominators, latency percentiles, and
        # the HLO collective-position interleaving evidence; the headline is
        # the overlap-on throughput against the barrier row (BENCH_r08
        # acceptance artifact)
        from tpuddp.observability import json_sanitize

        on_sps, off_sps = bench_overlap_pair()
        summary = emit_summary(
            on_sps, off_sps, out_path=out_path,
            metric="toy_mlp_overlap_train_samples_per_sec_per_chip",
            basis="overlap-off",
        )
        print(json.dumps(json_sanitize(summary), allow_nan=False), flush=True)
        return
    if "--pipeline" in argv:
        # the async-pipeline A/B mode: ONLY the loader-fed on/off pair, with
        # the pipeline-off (synchronous) row as the baseline basis — the
        # overlap win is the headline (ISSUE 8 acceptance artifact)
        from tpuddp.observability import json_sanitize

        on_sps, off_sps = bench_pipeline_pair()
        summary = emit_summary(
            on_sps, off_sps, out_path=out_path,
            metric="toy_cnn_pipeline_train_samples_per_sec_per_chip",
            basis="pipeline-off",
        )
        print(json.dumps(json_sanitize(summary), allow_nan=False), flush=True)
        return

    # Headline: the toy model is dispatch-bound (its compute is ~13 us/step),
    # so throughput scales with the fusion depth K until staging/memory costs
    # bite; K=200 measured 1.6-2.2M samples/s/chip across rounds (K=50:
    # 0.6M, K=400: 2.5M but the flops probe's scan cross-check no longer
    # resolves there).
    # The headline row feeds the driver's one-JSON-line contract, so unlike
    # the diagnostic rows below it retries through transient runtime flakes
    # (the tunneled TPU occasionally drops a remote_compile mid-round).
    last_err = None
    for attempt in range(3):
        try:
            ours, n_chips = bench_config(
                "toy_mlp f32 (scan-fused K=200)", ToyMLP(num_classes=10),
                (32, 32, 3), 128, steps=2000, scan=200,
            )
            break
        except Exception as e:
            last_err = e
            log(f"headline bench attempt {attempt + 1} failed: {e}; retrying")
    else:
        raise last_err
    try:
        bench_config(
            "toy_mlp f32 (per-step dispatch)", ToyMLP(num_classes=10),
            (32, 32, 3), 128, steps=256,
        )
    except Exception as e:
        log(f"per-step toy bench failed: {type(e).__name__}: {e}")

    def cifar_resnet(cls):
        # The TPU-friendly CIFAR recipe: a modern ResNet at the native 32x32
        # resolution instead of paying the reference's 49x resize FLOPs.
        return (
            cls(10, sync_bn=True, small_input=True),
            make_train_augment(size=None, compute_dtype=jnp.bfloat16),
        )

    def bf16_alexnet():
        return (
            AlexNet(10),
            make_train_augment(size=224, compute_dtype=jnp.bfloat16),
        )

    from tpuddp import optim as _optim

    bf16_opt = lambda: _optim.Adam(1e-3, state_dtype="bfloat16")
    cnn_configs = [
        # (name, factory, per-chip batch, scan K, timed steps, opt factory)
        # K=64 on the CNN rows = the product default (loop._AUTO_SCAN_CAP,
        # within the staged-chunk budget for these uint8 inputs): the
        # tunnel's per-dispatch RTT varies ~7-240 ms across sessions, and K
        # is the pure-amortization lever against it (BASELINE.md)
        ("alexnet f32 224 (per-step dispatch)",
         lambda: (AlexNet(10), make_train_augment(size=224)), 128, 1, 64, None),
        ("alexnet f32 224 (scan-fused)",
         lambda: (AlexNet(10), make_train_augment(size=224)), 128, 64, 128, None),
        ("alexnet bf16 224 (scan-fused)", bf16_alexnet, 128, 64, 128, None),
        # bf16 Adam m/v storage (training.optimizer_state_dtype): halves the
        # optimizer-state HBM traffic that bounds AlexNet at the reference's
        # own b128 (profile-backed; see BASELINE.md "Where the time goes")
        ("alexnet bf16 224 bf16-opt (scan-fused)", bf16_alexnet, 128, 64, 128,
         bf16_opt),
        # exact space-to-depth stem reparameterization (model: alexnet_s2d):
        # the 11x11/s4 3-channel stem becomes a unit-stride conv over 48
        # blocked channels — same math/params, ~+2.5 MFU points at the
        # reference-constant b128 (amortized away at b512)
        ("alexnet bf16 224 bf16-opt s2d (scan-fused)",
         lambda: (AlexNet(10, space_to_depth=True),
                  make_train_augment(size=224, compute_dtype=jnp.bfloat16)),
         128, 64, 128, bf16_opt),
        # the TPU-right batch: amortizes the remaining fixed per-step
        # param+grad HBM traffic over 4x the samples
        ("alexnet bf16 224 b512 bf16-opt (scan-fused)", bf16_alexnet, 512, 16,
         32, bf16_opt),
        # the measured sweet spot: with the s2d stem, b256 matches-or-beats
        # the b512 row at half the per-chip batch (same-session artifact
        # pair, BENCH_r04.json: 39.3% vs 38.0%)
        ("alexnet bf16 224 b256 bf16-opt s2d (scan-fused)",
         lambda: (AlexNet(10, space_to_depth=True),
                  make_train_augment(size=224, compute_dtype=jnp.bfloat16)),
         256, 32, 64, bf16_opt),
        ("resnet18 bf16 32x32 sync-BN (scan-fused)",
         lambda: cifar_resnet(ResNet18), 128, 64, 128, None),
        ("resnet34 bf16 32x32 sync-BN (scan-fused)",
         lambda: cifar_resnet(ResNet34), 128, 64, 128, None),
        # the full-resolution reference-class CNN (data_and_toy_model.py:13-36
        # is 224x224): profile-backed accounting in BASELINE.md "Where the
        # time goes (ResNet-18@224)"; s2d = exact 7x7/s2 stem
        # reparameterization (resnet18_s2d)
        ("resnet18 bf16 224 b128 bf16-opt (scan-fused)",
         lambda: (ResNet18(10),
                  make_train_augment(size=224, compute_dtype=jnp.bfloat16)),
         128, 64, 128, bf16_opt),
        ("resnet18 bf16 224 b128 bf16-opt s2d (scan-fused)",
         lambda: (ResNet18(10, space_to_depth=True),
                  make_train_augment(size=224, compute_dtype=jnp.bfloat16)),
         128, 64, 128, bf16_opt),
        # the Bottleneck/VGG halves of the model zoo (VERDICT r5: half the
        # zoo had zero perf evidence) — measured rows with device-MFU like
        # every config above, at depths sized so one row stays O(minute)
        ("vgg11 bf16 224 b128 bf16-opt (scan-fused)",
         lambda: (VGG11(10),
                  make_train_augment(size=224, compute_dtype=jnp.bfloat16)),
         128, 16, 32, bf16_opt),
        ("resnet50 bf16 224 b128 bf16-opt (scan-fused)",
         lambda: (ResNet50(10),
                  make_train_augment(size=224, compute_dtype=jnp.bfloat16)),
         128, 16, 32, bf16_opt),
    ]
    if slow:
        # the big-model tail: ResNet-101 @ 224 is minutes of compile+run, so
        # it rides the same slow tier as the test suite's big donors
        cnn_configs.append(
            ("resnet101 bf16 224 b64 bf16-opt (scan-fused, slow)",
             lambda: (ResNet101(10),
                      make_train_augment(size=224, compute_dtype=jnp.bfloat16)),
             64, 8, 16, bf16_opt)
        )
    else:
        log("resnet101 row skipped (slow tier: pass --slow or TPUDDP_BENCH_SLOW=1)")
    for name, make, batch, scan, steps, opt in cnn_configs:
        try:  # diagnostics only — independent, and never break the headline line
            model, augment = make()
            bench_config(
                name, model, (32, 32, 3), batch, steps=steps,
                augment=augment, x_dtype=np.uint8, scan=scan, opt=opt,
            )
        except Exception as e:
            log(f"{name} bench failed: {type(e).__name__}: {e}")

    try:
        # comm-hook artifact pair (parallel/comm.py): the resnet18@32 sync-BN
        # workload again, under the bf16_ef bucketed compressed allreduce —
        # its grad_comm_bytes_per_step sits next to the uncompressed row's in
        # the results file, so the gradient-byte reduction (and any
        # throughput delta) is a recorded bench artifact, not a claim
        model, augment = cifar_resnet(ResNet18)
        bench_config(
            "resnet18 bf16 32x32 sync-BN (scan-fused, bf16_ef comm hook)",
            model, (32, 32, 3), 128, steps=128, augment=augment,
            x_dtype=np.uint8, scan=64, comm_hook="bf16_ef",
        )
    except Exception as e:
        log(f"comm-hook bench failed: {type(e).__name__}: {e}")

    try:
        # the managed path on the compute-bound flagship (VERDICT r4 #3):
        # must land within ~5% of the native s2d scan-fused row
        bench_managed_alexnet(steps=96, fuse=32)
    except Exception as e:
        log(f"managed alexnet bench failed: {type(e).__name__}: {e}")

    for deferred, fuse in ((False, 1), (True, 1), (True, 32)):
        try:
            # eager mode syncs per batch (that IS its cost — quirk Q5 parity),
            # so 60 steps suffice; deferred modes fetch once per run, so they
            # time 256 steps — the same steps-per-fetch as the native per-step
            # config they are compared against (fence amortization parity)
            bench_managed(deferred=deferred, fuse=fuse, steps=256 if deferred else 60)
        except Exception as e:
            log(f"managed bench failed: {type(e).__name__}: {e}")

    try:
        bench_managed_eval(batches=256, fused=False)
        bench_managed_eval(batches=256, fused=True)
    except Exception as e:
        log(f"managed eval bench failed: {type(e).__name__}: {e}")

    try:
        # the async-pipeline A/B rows ride every full bench too, so each
        # BENCH_r artifact records the loader-fed wall/device pair
        bench_pipeline_pair()
    except Exception as e:
        log(f"pipeline A/B bench failed: {type(e).__name__}: {e}")

    baseline = bench_torch_cpu()
    # LAST stdout line: the compact machine-readable summary (the driver
    # parses exactly this line; the full per-config dict went to
    # bench_results.json inside emit_summary). Strict JSON: non-finite
    # values serialize as null, never a bare NaN token.
    from tpuddp.observability import json_sanitize

    print(
        json.dumps(
            json_sanitize(emit_summary(ours, baseline, out_path=out_path)),
            allow_nan=False,
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
