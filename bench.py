"""Benchmark: samples/sec/chip on the reference workload (BASELINE.json metric).

Configs measured (BASELINE.md targets):
- toy MLP, per-chip batch 128 (the BASELINE.json headline metric)  -> stdout
- AlexNet-class / CIFAR-shaped 224x224, f32 and bf16 mixed precision -> stderr

All runs are the FULL DP train step (device-side uint8 augmentation for the
CNN, forward, backward, grad pmean, Adam update, on-device metrics), matching
the reference hot loop (multi-GPU-training-torch.py:109-132) with per-chip
batch 128 / Adam lr=1e-3 / cross-entropy.

Timing methodology: steps are dispatched as an async dependency chain and the
clock stops on a *value fetch* from the final step's metrics — on remote-
tunneled TPU runtimes ``block_until_ready`` can return before execution
completes, so fetching is the only honest fence.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
baseline is measured here: the same toy-MLP workload through the reference's
stack (torch + Adam + per-batch loss.item(), its quirk Q5 sync included) on
this host's available torch device (CPU — the reference's CUDA path needs
NVIDIA hardware that does not exist on a TPU host).

Prints exactly ONE JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _make_runner(ddp, state_box, batch, scan):
    """Build run(n_steps) over pre-staged device buffers. Warmup calls must
    reuse the SAME buffers that are timed later: device_put is lazy on
    remote-tunneled runtimes, so a buffer's first use pays its upload."""
    from tpuddp.training.step import stack_batches

    if scan > 1:
        stacked = ddp.shard_stacked(
            stack_batches([tuple(np.asarray(b) for b in batch)] * scan)
        )

        def run(steps):
            outer = max(1, steps // scan)
            metrics = None
            for _ in range(outer):
                state_box[0], metrics = ddp.train_step_many(state_box[0], stacked)
            loss_sum = float(np.sum(np.asarray(metrics["loss_sum"])))  # fence
            assert np.isfinite(loss_sum)
            return outer * scan

    else:

        def run(steps):
            metrics = None
            for _ in range(steps):
                state_box[0], metrics = ddp.train_step(state_box[0], batch)
            loss_sum = float(np.sum(np.asarray(metrics["loss_sum"])))
            assert np.isfinite(loss_sum)
            return steps

    return run


def bench_config(
    name, model, in_shape, batch_per_chip, steps, augment=None,
    x_dtype=np.float32, scan=1,
):
    import jax
    import jax.numpy as jnp

    from tpuddp import nn, optim
    from tpuddp.parallel import make_mesh
    from tpuddp.parallel.ddp import DistributedDataParallel

    devices = jax.devices()
    mesh = make_mesh(devices)
    n_chips = len(devices)
    global_batch = batch_per_chip * n_chips

    ddp = DistributedDataParallel(
        model, optim.Adam(1e-3), nn.CrossEntropyLoss(), mesh=mesh,
        mode="shard_map", augment=augment,
    )
    model_in = in_shape if augment is None else augment(
        jax.random.key(0), jnp.zeros((1,) + in_shape, x_dtype)
    ).shape[1:]
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1,) + tuple(model_in)))

    rng = np.random.RandomState(0)
    if np.issubdtype(x_dtype, np.integer):
        x = rng.randint(0, 256, (global_batch,) + in_shape).astype(x_dtype)
    else:
        x = rng.randn(global_batch, *in_shape).astype(x_dtype)
    y = rng.randint(0, 10, global_batch).astype(np.int32)
    w = np.ones(global_batch, np.float32)
    batch = ddp.shard((x, y, w))

    state_box = [state]
    run = _make_runner(ddp, state_box, batch, scan)
    run(max(3, scan))  # compile + stage all buffers (lazy-upload warm)
    run(max(3, scan))  # second warm pass: steady-state dispatch path
    t0 = time.perf_counter()
    steps = run(steps)
    dt = time.perf_counter() - t0

    sps = steps * global_batch / dt
    log(
        f"{name}: {sps:,.0f} samples/s total, {sps / n_chips:,.0f} /chip "
        f"({steps} steps, {dt / steps * 1e3:.2f} ms/step, {n_chips} chip(s))"
    )
    return sps / n_chips, n_chips


def bench_torch_cpu(batch=128, steps=30, warmup=3):
    """The reference stack's hot loop (toy MLP) on this host (torch CPU)."""
    try:
        import torch
        import torch.nn as tnn
    except Exception as e:  # pragma: no cover
        log(f"torch unavailable ({e}); vs_baseline=1.0")
        return None

    torch.manual_seed(0)
    model = tnn.Sequential(
        tnn.Flatten(),
        tnn.Linear(32 * 32 * 3, 256),
        tnn.ReLU(),
        tnn.Linear(256, 128),
        tnn.ReLU(),
        tnn.Linear(128, 10),
    )
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    criterion = tnn.CrossEntropyLoss()
    x = torch.randn(batch, 3, 32, 32)
    y = torch.randint(0, 10, (batch,))

    def step():
        opt.zero_grad()
        loss = criterion(model(x), y)
        loss.backward()
        opt.step()
        return float(loss.item())  # the reference's per-batch sync (quirk Q5)

    for _ in range(warmup):
        step()
    t0 = time.perf_counter()
    for _ in range(steps):
        step()
    dt = time.perf_counter() - t0
    sps = steps * batch / dt
    log(f"torch-cpu baseline (toy MLP): {sps:,.0f} samples/s")
    return sps


def main():
    import jax.numpy as jnp

    from tpuddp.data.transforms import make_train_augment
    from tpuddp.models import AlexNet, ToyMLP

    ours, n_chips = bench_config(
        "toy_mlp f32 (scan-fused)", ToyMLP(num_classes=10), (32, 32, 3), 128,
        steps=500, scan=50,
    )
    bench_config(
        "toy_mlp f32 (per-step dispatch)", ToyMLP(num_classes=10), (32, 32, 3),
        128, steps=100,
    )
    def resnet18():
        from tpuddp.models import ResNet18

        # The TPU-friendly CIFAR recipe: a modern ResNet at the native 32x32
        # resolution instead of paying the reference's 49x resize FLOPs.
        return (
            ResNet18(10, sync_bn=True, small_input=True),
            make_train_augment(size=None, compute_dtype=jnp.bfloat16),
        )

    cnn_configs = [
        ("alexnet f32 (uint8->224 on-device)",
         lambda: (AlexNet(10), make_train_augment(size=224))),
        ("alexnet bf16 (uint8->224 on-device)",
         lambda: (AlexNet(10),
                  make_train_augment(size=224, compute_dtype=jnp.bfloat16))),
        ("resnet18 bf16 (native 32x32, sync-BN)", resnet18),
    ]
    for name, make in cnn_configs:
        try:  # diagnostics only — independent, and never break the headline line
            model, augment = make()
            bench_config(
                name, model, (32, 32, 3), 128, steps=30,
                augment=augment, x_dtype=np.uint8,
            )
        except Exception as e:
            log(f"{name} bench failed: {type(e).__name__}: {e}")

    baseline = bench_torch_cpu()
    vs = ours / baseline if baseline else 1.0
    print(
        json.dumps(
            {
                "metric": "toy_mlp_train_samples_per_sec_per_chip",
                "value": round(ours, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
