"""Causal tracing plane (ISSUE 15, tpuddp/observability/trace.py).

The contracts: a bounded span ring with honest drop accounting; Chrome-trace
export that validates under schema v9 with correctly-nesting trees and
follows_from flow edges; tracing ON changes ZERO semantics (a traced
training run's loss trajectory is bitwise the untraced twin's, and tracing
OFF writes nothing); serving requests are one span tree each, and a decode
session that fails over stays ONE trace; the exporter's /metrics, /snapshot
and /trace endpoints never serve a torn payload under a concurrent writer
(the MetricsExporter concurrency satellite); and the trace tooling
(tpuddp_inspect trace, trace_breakdown --merge-host) consumes the artifacts.
"""

import gzip
import json
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

from tpuddp import config as config_lib
from tpuddp.observability import schema as schema_mod
from tpuddp.observability import trace as trace_mod
from tpuddp.observability.trace import NULL, Tracer, tracer_from_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- span model --


def test_ring_bound_and_drop_accounting(tmp_path):
    t = Tracer("train", capacity=4, run_dir=str(tmp_path), process_index=0)
    root = t.start_span("epoch 0", trace_mod.KIND_EPOCH)
    for _ in range(8):
        t.end_span(t.start_span("d", trace_mod.KIND_DISPATCH, parent=root))
    t.end_span(root)
    # 9 completed, ring holds 4, 5 dropped — and the cumulative per-kind
    # counters cover EVERY completed span, not just the ring survivors
    assert t.completed == 9
    assert t.dropped == 5
    assert t.kind_counts["dispatch"] == 8 and t.kind_counts["epoch"] == 1
    rec = t.summary_record()
    assert rec["spans"] == 9 and rec["dropped"] == 5
    assert rec["open_spans"] == 0
    assert rec["slowest"] and rec["slowest"][0]["duration_ms"] >= 0
    assert schema_mod.validate_record(
        schema_mod.stamp("trace_summary", rec)
    ) == []


def test_open_spans_surface_for_flight_embed():
    t = Tracer("train", process_index=0)
    root = t.start_span("epoch 3", trace_mod.KIND_EPOCH)
    child = t.start_span("dispatch", trace_mod.KIND_DISPATCH, parent=root)
    opens = t.open_span_summaries()
    assert [s["name"] for s in opens] == ["epoch 3", "dispatch"]
    assert opens[1]["parent_id"] == root.span_id
    assert opens[0]["duration_ms"] is None  # still open
    t.end_span(child)
    t.end_span(root)
    assert t.open_span_summaries() == []


def test_end_span_idempotent_and_unknown_kind_refused():
    t = Tracer("train", process_index=0)
    with pytest.raises(ValueError, match="unknown span kind"):
        t.start_span("x", "not_a_kind")
    s = t.start_span("x", trace_mod.KIND_STAGE)
    t.end_span(s)
    t.end_span(s)  # second end is a no-op, not a double count
    assert t.completed == 1
    t.end_span(trace_mod.NULL_SPAN)  # the null span is always ignored
    assert t.completed == 1


def test_null_tracer_and_config_gate(tmp_path):
    assert tracer_from_config({"tracing": False}, "train") is NULL
    assert tracer_from_config(None, "train") is NULL
    assert not NULL.enabled
    s = NULL.start_span("x", "anything")  # no kind validation, no recording
    NULL.end_span(s)
    assert NULL.describe() is None
    assert NULL.export(str(tmp_path / "t.json")) is None
    assert not (tmp_path / "t.json").exists()
    live = tracer_from_config(
        config_lib.resolve_observability({"tracing": True}), "train",
        run_dir=str(tmp_path),
    )
    assert live.enabled and live.capacity == 4096


# ----------------------------------------------------------------- export --


def test_export_validates_nests_and_links(tmp_path):
    t = Tracer("decode", capacity=64, run_dir=str(tmp_path), process_index=0)
    root = t.start_span(
        "request", trace_mod.KIND_REQUEST, tid="client",
        attrs={"tenant": "a"},
    )
    q = t.start_span("queue_wait", trace_mod.KIND_QUEUE_WAIT, parent=root)
    t.end_span(q)
    pre = t.start_span(
        "prefill", trace_mod.KIND_PREFILL, parent=root,
        follows_from=q.span_id,
    )
    t.end_span(pre)
    t.end_span(root)
    path = t.export()
    assert path == str(tmp_path / "trace_decode.json")
    errors, n = schema_mod.validate_trace_file(path)
    assert errors == [] and n == 3
    payload = json.load(open(path))
    spans = {
        e["args"]["span_id"]: e
        for e in payload["traceEvents"] if e.get("ph") == "X"
    }
    assert spans[q.span_id]["args"]["parent_id"] == root.span_id
    # one trace, all three spans
    assert len({e["args"]["trace_id"] for e in spans.values()}) == 1
    # follows_from becomes a flow s/f pair
    phases = [e["ph"] for e in payload["traceEvents"]]
    assert "s" in phases and "f" in phases
    # thread metadata rows for the named tids
    names = {
        e["args"]["name"] for e in payload["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert "client" in names


def test_trace_payload_drift_rejected(tmp_path):
    t = Tracer("train", run_dir=str(tmp_path), process_index=0)
    t.end_span(t.start_span("e", trace_mod.KIND_EPOCH))
    payload = t.chrome_payload()
    assert schema_mod.validate_trace_payload(payload) == []
    # missing provenance block
    assert schema_mod.validate_trace_payload(
        {"traceEvents": []}
    )
    # newer-version reject
    newer = json.loads(json.dumps(payload))
    newer["tpuddp"]["schema_version"] = schema_mod.SCHEMA_VERSION + 1
    assert any("newer" in e for e in schema_mod.validate_trace_payload(newer))
    # orphan parent_id is drift — but ONLY while the ring dropped nothing
    orphan = json.loads(json.dumps(payload))
    orphan["traceEvents"][-1]["args"]["parent_id"] = 999999
    errs = schema_mod.validate_trace_payload(orphan)
    assert any("orphan" in e for e in errs)
    orphan["tpuddp"]["dropped"] = 3
    assert not any(
        "orphan" in e for e in schema_mod.validate_trace_payload(orphan)
    )


def test_schema_v9_requires_tracing_field():
    good = schema_mod.make_run_meta(world_size=1, comm_hook=None, guard=None)
    assert good["tracing"] is None
    assert schema_mod.validate_record(good) == []
    dropped = {k: v for k, v in good.items() if k != "tracing"}
    errs = schema_mod.validate_record(dropped)
    assert any("tracing" in e for e in errs)
    # a v8 header (predates the plane) stays valid without the key
    v8 = dict(dropped, schema_version=8)
    assert schema_mod.validate_record(v8) == []
    # trace_summary requires its accounting fields
    bad = schema_mod.stamp("trace_summary", {"role": "train"})
    assert schema_mod.validate_record(bad)


# ------------------------------------------------- training loop end to end --


def _loop_run(mesh, save_dir, observability):
    import jax
    import jax.numpy as jnp

    from tpuddp import optim
    from tpuddp.data import ShardedDataLoader, SyntheticClassification
    from tpuddp.models import ToyMLP
    from tpuddp.nn import CrossEntropyLoss
    from tpuddp.parallel.ddp import DistributedDataParallel
    from tpuddp.training.loop import run_training_loop

    ds = SyntheticClassification(n=64, shape=(8, 8, 3), seed=0)
    loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    test_loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), CrossEntropyLoss(),
        mesh=mesh, comm_hook="bf16_ef",
    )
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    return run_training_loop(
        ddp, state, loader, test_loader, save_dir, num_epochs=2,
        checkpoint_epoch=1, log=lambda *_: None,
        observability=observability,
    )


def test_traced_training_bitwise_and_artifact(mesh, tmp_path):
    """THE acceptance pair: tracing on produces the identical loss
    trajectory (bitwise on the recorded floats), a schema-v9 artifact with
    the full span-kind set (incl. the comm-hook collective annotation),
    and the run_meta/trace_summary records; tracing off writes NOTHING."""
    d_on, d_off = str(tmp_path / "on"), str(tmp_path / "off")
    _, hist_on = _loop_run(mesh, d_on, {"tracing": True})
    _, hist_off = _loop_run(mesh, d_off, None)
    traj = lambda h: [  # noqa: E731
        (e["epoch"], e["train_loss"], e["test_loss"], e["test_accuracy"])
        for e in h
    ]
    assert traj(hist_on) == traj(hist_off)

    art = os.path.join(d_on, "trace_train.json")
    assert os.path.exists(art)
    assert not os.path.exists(os.path.join(d_off, "trace_train.json"))
    errors, n = schema_mod.validate_trace_file(art)
    assert errors == [] and n > 0
    payload = json.load(open(art))
    spans = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    kinds = {e["cat"] for e in spans}
    assert {"epoch", "stage", "dispatch", "readback", "collective"} <= kinds
    # the collective annotation carries the hook's wire accounting
    coll = next(e for e in spans if e["cat"] == "collective")
    assert coll["args"]["hook"] == "bf16_ef"
    assert coll["args"]["wire_bytes_per_update"] > 0
    # epochs share ONE run trace; dispatches nest under their epoch
    epochs = [e for e in spans if e["cat"] == "epoch"]
    assert len({e["args"]["trace_id"] for e in epochs}) == 1
    eids = {e["args"]["span_id"] for e in epochs}
    assert all(
        e["args"]["parent_id"] in eids
        for e in spans if e["cat"] == "dispatch"
    )

    records = [
        json.loads(l) for l in open(os.path.join(d_on, "history.jsonl"))
    ]
    assert schema_mod.validate_history_records(records) == []
    meta = records[0]
    assert meta["tracing"] == {"capacity": 4096, "artifact": "trace_train.json"}
    summary = next(r for r in records if r["type"] == "trace_summary")
    assert summary["role"] == "train" and summary["spans"] > 0
    off_meta = json.loads(
        open(os.path.join(d_off, "history.jsonl")).readline()
    )
    assert off_meta["tracing"] is None


def test_traced_step_hlo_identical(mesh):
    """Tracing never touches the compiled program: the wrap has no tracing
    state at all, so the step lowers byte-identical whether the DRIVER
    traces or not — asserted the direct way, by lowering the same wrap's
    step before and after a traced driver pass would run (the wrap is the
    only thing that contributes to the HLO)."""
    import jax
    import jax.numpy as jnp

    from tpuddp import optim
    from tpuddp.models import ToyMLP
    from tpuddp.nn import CrossEntropyLoss
    from tpuddp.parallel.ddp import DistributedDataParallel

    def lower_text():
        ddp = DistributedDataParallel(
            ToyMLP(hidden=(16,)), optim.Adam(1e-2), CrossEntropyLoss(),
            mesh=mesh,
        )
        state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
        b = ddp.shard((
            np.zeros((64, 8, 8, 3), np.float32),
            np.zeros((64,), np.int32),
            np.ones((64,), np.float32),
        ))
        return jax.jit(
            lambda s, x: ddp.train_step(s, x)
        ).lower(state, b).as_text()

    baseline = lower_text()
    # arm a live tracer around a second lowering — identical text
    tracer = Tracer("train", process_index=0)
    sp = tracer.start_span("epoch 0", trace_mod.KIND_EPOCH)
    traced = lower_text()
    tracer.end_span(sp)
    assert traced == baseline


# ------------------------------------------------------------ serving spans --


def _serving_engine(tmp_path, devices, observability):
    from tpuddp.serving.engine import ServingEngine

    cfg = config_lib._merge_refusing_unknown(
        config_lib.SERVING_DEFAULTS,
        {
            "model": "toy_mlp", "num_classes": 10, "input_shape": [4, 4, 1],
            "num_replicas": 2, "max_batch_size": 8, "batch_timeout_ms": 0.0,
            "stats_window": 8,
        },
        "serving",
    )
    return ServingEngine.from_config(
        cfg, out_dir=str(tmp_path), devices=devices,
        observability=observability,
    )


def test_serving_request_trees_and_live_trace_endpoint(tmp_path, cpu_devices):
    eng = _serving_engine(
        tmp_path, cpu_devices[:2],
        {"tracing": True, "exporter": True, "flight_recorder": False},
    )
    eng.start()
    try:
        rng = np.random.RandomState(0)
        results = [
            eng.submit(f"t{i % 2}", rng.randn(2, 4, 4, 1).astype(np.float32))
            for i in range(10)
        ]
        for r in results:
            r.result(timeout=120)
        # the live /trace endpoint serves the same span model
        live = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{eng.exporter.port}/trace", timeout=10
        ))
        assert live["enabled"] and live["role"] == "serving"
        assert live["completed"] > 0
        assert {"trace_id", "span_id", "kind"} <= set(live["spans"][0])
    finally:
        eng.drain()
    art = os.path.join(str(tmp_path), "trace_serving.json")
    errors, _ = schema_mod.validate_trace_file(art)
    assert errors == []
    payload = json.load(open(art))
    spans = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    roots = [e for e in spans if e["cat"] == "request"]
    assert len(roots) == 10
    # every request tree: admission + queue_wait + serve under its root,
    # in ITS OWN trace
    for root in roots:
        children = [
            e["cat"] for e in spans
            if e["args"].get("parent_id") == root["args"]["span_id"]
        ]
        assert {"admission", "queue_wait", "serve"} <= set(children)
    assert len({r["args"]["trace_id"] for r in roots}) == 10
    # the per-replica infer rows exist
    assert any(e["cat"] == "dispatch" for e in spans)
    # history carries the drain digest
    records = [
        json.loads(l)
        for l in open(os.path.join(str(tmp_path), "history.jsonl"))
    ]
    assert schema_mod.validate_history_records(records) == []
    assert any(r["type"] == "trace_summary" for r in records)


def test_serving_rejected_request_closes_its_trace(tmp_path, cpu_devices):
    from tpuddp.serving.queue import AdmissionError

    eng = _serving_engine(
        tmp_path, cpu_devices[:2], {"tracing": True, "flight_recorder": False}
    )
    eng.start()
    try:
        with pytest.raises(AdmissionError):
            eng.submit("t", np.zeros((1, 3, 3, 1), np.float32))  # bad shape
        assert eng.tracer.open_span_summaries() == []
        rejected = [
            s for s in eng.tracer.endpoint_payload()["spans"]
            if s["kind"] == "request"
        ]
        assert rejected and rejected[0]["attrs"]["error"] == "bad_shape"
    finally:
        eng.drain()


# ---------------------------------------------------- decode failover trace --


def test_decode_failover_stays_one_trace(tmp_path, cpu_devices):
    """A killed replica's resumed streams: the session's queue_wait /
    failover / resume-prefill spans land in the SAME trace as its original
    request root, with a follows_from edge onto the pre-death span — the
    single-trace failover acceptance criterion."""
    from tpuddp.serving.decode import DecodeEngine

    cfg = config_lib.decode_config({"decode": {}})
    cfg.update(
        model="transformer_tiny", vocab_size=32, num_replicas=1, max_slots=4,
        kv_blocks=17, kv_block_size=8, max_seq_len=32, max_new_tokens=8,
        stats_window=16, max_queue_depth=64, recovery_backoff_s=0.01,
    )
    out = str(tmp_path / "run")
    eng = DecodeEngine.from_config(
        cfg, out_dir=out, devices=cpu_devices[:1],
        observability={"tracing": True, "flight_recorder": False},
    )
    eng.start()
    try:
        rng = np.random.RandomState(0)
        prompts = [
            rng.randint(0, 32, size=n).astype(np.int32) for n in (3, 5, 12)
        ]
        twins = [
            np.asarray(eng.submit("t", p, seed=7 + i).result(timeout=120))
            for i, p in enumerate(prompts)
        ]
        replica = eng.replicas[0]
        real_step = replica._step
        state = {"calls": 0, "fired": False}

        def step(params, kpool, vpool, *rest):
            if not state["fired"] and state["calls"] >= 2:
                state["fired"] = True
                raise RuntimeError("injected replica death")
            state["calls"] += 1
            return real_step(params, kpool, vpool, *rest)

        replica._step = step
        results = [
            eng.submit("t", p, seed=7 + i) for i, p in enumerate(prompts)
        ]
        finals = [np.asarray(r.result(timeout=120)) for r in results]
        assert state["fired"]
        for f, tw in zip(finals, twins):
            np.testing.assert_array_equal(f, tw)
    finally:
        eng.drain()
    art = os.path.join(out, "trace_decode.json")
    errors, _ = schema_mod.validate_trace_file(art)
    assert errors == []
    payload = json.load(open(art))
    spans = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    resumes = [
        e for e in spans
        if e["cat"] == "prefill" and e["args"].get("resume")
    ]
    assert resumes, "no resume prefills traced"
    root_by_trace = {
        e["args"]["trace_id"]: e["args"]["span_id"]
        for e in spans if e["cat"] == "request"
    }
    span_ids = {e["args"]["span_id"] for e in spans}
    for r in resumes:
        # the resumed prefill lives in an existing request's trace (ONE
        # trace across the migration), nested under that request's root,
        # causally linked to a pre-death span
        assert r["args"]["trace_id"] in root_by_trace
        assert r["args"]["parent_id"] == root_by_trace[r["args"]["trace_id"]]
        assert r["args"]["follows_from"] in span_ids
    assert any(e["cat"] == "failover" for e in spans)
    assert any(e["cat"] == "probation" for e in spans)
    assert any(e["cat"] == "decode_step" for e in spans)


def test_decode_prefill_death_resume_keeps_linkage(tmp_path, cpu_devices):
    """A PLACE-phase death (the culprit's own prefill raises): the parked
    request reopens a queue_wait in its trace and its re-prefill carries
    the resume attr + a follows_from edge onto the errored prefill — the
    single-trace contract holds for prefill deaths, not just step deaths."""
    from tpuddp.serving.decode import DecodeEngine

    cfg = config_lib.decode_config({"decode": {}})
    cfg.update(
        model="transformer_tiny", vocab_size=32, num_replicas=1, max_slots=4,
        kv_blocks=17, kv_block_size=8, max_seq_len=32, max_new_tokens=8,
        stats_window=16, max_queue_depth=64, recovery_backoff_s=0.01,
    )
    out = str(tmp_path / "run")
    eng = DecodeEngine.from_config(
        cfg, out_dir=out, devices=cpu_devices[:1],
        observability={"tracing": True, "flight_recorder": False},
    )
    eng.start()
    try:
        rng = np.random.RandomState(1)
        p = rng.randint(0, 32, size=5).astype(np.int32)
        twin = np.asarray(eng.submit("t", p, seed=3).result(timeout=120))
        replica = eng.replicas[0]
        real_prefill = replica._prefill
        state = {"fired": False}

        def prefill(params, kpool, vpool, *rest):
            if not state["fired"]:
                state["fired"] = True
                raise RuntimeError("injected prefill death")
            return real_prefill(params, kpool, vpool, *rest)

        replica._prefill = prefill
        got = np.asarray(eng.submit("t", p, seed=3).result(timeout=120))
        assert state["fired"]
        np.testing.assert_array_equal(got, twin)
    finally:
        eng.drain()
    payload = json.load(open(os.path.join(out, "trace_decode.json")))
    spans = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    failed = [
        e for e in spans
        if e["cat"] == "prefill" and "error" in e["args"]
    ]
    assert len(failed) == 1
    trace_id = failed[0]["args"]["trace_id"]
    same_trace = [e for e in spans if e["args"].get("trace_id") == trace_id]
    resume = next(
        e for e in same_trace
        if e["cat"] == "prefill" and e["args"].get("resume")
    )
    # the resume follows causally from the ERRORED prefill, and the second
    # wait is a real queue_wait span in the same trace, not a gap
    assert resume["args"]["follows_from"] == failed[0]["args"]["span_id"]
    assert sum(1 for e in same_trace if e["cat"] == "queue_wait") == 2
    fo = next(e for e in same_trace if e["cat"] == "failover")
    assert fo["args"]["from_replica"] == 0


# ---------------------------------------------------------- fleet job spans --


def test_fleet_controller_job_lifecycle_spans(tmp_path):
    from tpuddp.fleet.controller import FleetController
    from tpuddp.fleet.spec import JobSpec

    ctl = FleetController(
        pool_size=2, fleet_dir=str(tmp_path), observability={"tracing": True},
    )
    ctl.submit(JobSpec(
        name="quickie", argv=(sys.executable, "-c", "pass"),
        min_world=1, max_world=1,
    ))
    assert ctl.run_until(
        lambda c: c.training_complete(), poll=0.1, timeout=60
    )
    ctl.shutdown(timeout=30)
    art = os.path.join(str(tmp_path), "trace_fleet.json")
    errors, _ = schema_mod.validate_trace_file(art)
    assert errors == []
    payload = json.load(open(art))
    spans = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    job = next(e for e in spans if e["cat"] == "job")
    assert job["name"] == "job quickie"
    assert job["args"]["state"] == "done" and job["args"]["exit_code"] == 0
    starts = [
        e for e in spans
        if e["cat"] == "action"
        and e["args"].get("parent_id") == job["args"]["span_id"]
    ]
    assert any(e["name"] == "start" for e in starts)


# -------------------------------------- exporter concurrency (satellite 3) --


def test_exporter_never_serves_torn_payloads_under_writer_hammer(tmp_path):
    """Regression for the concurrent-scrape contract: a writer thread
    hammering the recorder + stats + tracer while /metrics, /snapshot and
    /trace are scraped in parallel must yield ONLY complete, parseable
    responses — every prometheus line whole, every JSON document valid."""
    from tpuddp.observability.exporter import MetricsExporter
    from tpuddp.observability.recorder import StepStatsRecorder
    from tpuddp.observability.telemetry import RunTelemetry

    tel = RunTelemetry(writer=None, step_stats_every=4)
    tracer = Tracer("train", capacity=128, process_index=0)
    exporter = MetricsExporter(port=0).start()
    exporter.set_trace_source(tracer.endpoint_payload)
    tel.attach_live(exporter=exporter)
    stop = threading.Event()
    writer_errors = []

    def writer():
        try:
            tel.start_epoch(0)
            i = 0
            while not stop.is_set():
                i += 1
                tel.post_dispatch(1, 8)
                tel.update_live(train_loss=float(i), skipped_steps=i)
                s = tracer.start_span(
                    f"dispatch {i}", trace_mod.KIND_DISPATCH,
                    attrs={"i": i},
                )
                tracer.end_span(s)
        except Exception as e:  # noqa: BLE001 — surfaced below
            writer_errors.append(e)

    scrape_errors = []

    def scraper(path, check):
        try:
            for _ in range(40):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{exporter.port}{path}", timeout=10
                ) as resp:
                    body = resp.read()
                    assert resp.status == 200
                    check(body)
        except Exception as e:  # noqa: BLE001 — surfaced below
            scrape_errors.append((path, e))

    def check_metrics(body):
        text = body.decode()
        assert text.endswith("\n")
        for line in text.splitlines():
            if line and not line.startswith("#"):
                parts = line.rsplit(" ", 1)
                assert len(parts) == 2, f"torn line {line!r}"
                float(parts[1])

    def check_json(body):
        json.loads(body)

    w = threading.Thread(target=writer, daemon=True)
    w.start()
    threads = [
        threading.Thread(target=scraper, args=a, daemon=True)
        for a in (
            ("/metrics", check_metrics),
            ("/snapshot", check_json),
            ("/trace", check_json),
            ("/metrics", check_metrics),
        )
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    stop.set()
    w.join(timeout=30)
    exporter.stop()
    tel.finish()
    assert not writer_errors, writer_errors
    assert not scrape_errors, scrape_errors


def test_exporter_rendering_error_returns_whole_500(tmp_path):
    """A trace source that raises mid-render must produce a COMPLETE 500
    response (Content-Length framed), never a truncated connection the
    client misreads as a torn payload."""
    from tpuddp.observability.exporter import MetricsExporter

    exporter = MetricsExporter(port=0).start()
    exporter.set_trace_source(lambda: (_ for _ in ()).throw(
        RuntimeError("broken trace feeder")
    ))
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/trace", timeout=10
            )
        err = exc_info.value
        assert err.code == 500
        body = err.read().decode()
        assert "broken trace feeder" in body and body.endswith("\n")
        # the endpoint stays up for the next scrape
        health = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/healthz", timeout=10
        ))
        assert health["status"] == "ok"
    finally:
        exporter.stop()


def test_trace_endpoint_404_without_tracing():
    from tpuddp.observability.exporter import MetricsExporter

    exporter = MetricsExporter(port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/trace", timeout=10
            )
        assert exc_info.value.code == 404
    finally:
        exporter.stop()


# --------------------------------------------------- flight open-span embed --


def test_flight_dump_embeds_open_spans(tmp_path):
    from tpuddp.observability.flight import FlightRecorder

    flight = FlightRecorder(str(tmp_path), process_index=0)
    tracer = Tracer("train", process_index=0)
    flight.add_context("open_spans", tracer.open_span_summaries)
    root = tracer.start_span("epoch 1", trace_mod.KIND_EPOCH)
    tracer.start_span("dispatch", trace_mod.KIND_DISPATCH, parent=root)
    path = flight.dump("exception")
    payload = json.load(open(path))
    opens = payload["notes"]["open_spans"]
    assert [s["name"] for s in opens] == ["epoch 1", "dispatch"]
    assert schema_mod.validate_flight_payload(payload) == []
    # a raising provider records its failure instead of blocking the dump
    flight2 = FlightRecorder(str(tmp_path / "b"), process_index=0)
    flight2.add_context("boom", lambda: 1 / 0)
    path2 = flight2.dump("exception")
    assert "failed" in json.load(open(path2))["notes"]["boom"]


# ------------------------------------------------------------ CLI satellites --


def test_inspect_trace_subcommand(tmp_path):
    tracer = Tracer("train", run_dir=str(tmp_path), process_index=0)
    root = tracer.start_span("epoch 0", trace_mod.KIND_EPOCH)
    tracer.end_span(
        tracer.start_span("dispatch", trace_mod.KIND_DISPATCH, parent=root)
    )
    tracer.end_span(root)
    art = tracer.export()
    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    out = subprocess.run(
        [sys.executable, inspect, "trace", art],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    assert "role=train" in out.stdout and "slowest spans" in out.stdout
    # --validate through content detection too
    assert subprocess.run(
        [sys.executable, inspect, "--validate", art]
    ).returncode == 0
    # a corrupted artifact fails validation with exit 1
    bad = tmp_path / "bad_trace.json"
    payload = json.load(open(art))
    del payload["tpuddp"]["clock_sync"]
    bad.write_text(json.dumps(payload))
    assert subprocess.run(
        [sys.executable, inspect, "trace", str(bad), "--validate"],
        capture_output=True,
    ).returncode == 1


def _device_capture(path, with_meta_name=True):
    """A minimal profiler-shaped capture: one TPU process, one 'XLA Ops'
    thread, two ops — one fully annotated, one BARE (no args at all, the
    shape that used to KeyError the breakdown)."""
    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 2,
         "args": ({"name": "XLA Ops"} if with_meta_name else {})},
        {"ph": "X", "pid": 1, "tid": 2, "name": "fusion.1", "ts": 1000,
         "dur": 50,
         "args": {"tf_op": "dot_general", "source": "model.py"}},
        {"ph": "X", "pid": 1, "tid": 2, "name": "bare.op", "ts": 1100,
         "dur": 30},  # no args: the bare-op tolerance case
        {"ph": "X", "pid": 1, "tid": 2, "name": "no.dur", "ts": 1200},
    ]
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_trace_breakdown_tolerates_bare_ops_and_merges_all_captures(
    tmp_path, capsys
):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import importlib

        import trace_breakdown

        importlib.reload(trace_breakdown)
        # TWO capture files: both must contribute (the old code silently
        # analyzed only the last glob hit)
        _device_capture(str(tmp_path / "a.trace.json.gz"))
        _device_capture(str(tmp_path / "b.trace.json.gz"))
        ops = trace_breakdown.load_ops(str(tmp_path))
        assert len(ops) == 6  # 3 X events per file, bare ops included
        trace_breakdown.breakdown(str(tmp_path))
        out = capsys.readouterr().out
        assert "device op time" in out
        # a capture whose thread meta lacks args.name must not crash either
        _device_capture(
            str(tmp_path / "c.trace.json.gz"), with_meta_name=False
        )
        trace_breakdown.load_ops(str(tmp_path))
    finally:
        sys.path.remove(os.path.join(REPO, "tools"))


def test_trace_breakdown_merge_host(tmp_path):
    _device_capture(str(tmp_path / "dev.trace.json.gz"))
    tracer = Tracer("train", run_dir=str(tmp_path), process_index=0)
    root = tracer.start_span("epoch 0", trace_mod.KIND_EPOCH)
    tracer.end_span(
        tracer.start_span("dispatch", trace_mod.KIND_DISPATCH, parent=root)
    )
    tracer.end_span(root)
    host_art = tracer.export()
    merged_path = str(tmp_path / "merged.json")
    out = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "trace_breakdown.py"),
            str(tmp_path), "--merge-host", host_art, "--out", merged_path,
        ],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    merged = json.load(open(merged_path))
    cats = {e.get("cat") for e in merged["traceEvents"] if e.get("ph") == "X"}
    assert "epoch" in cats  # host spans present
    names = {e.get("name") for e in merged["traceEvents"]}
    assert "fusion.1" in names  # device ops present
    # host pids were remapped off the device pid space
    host_pids = {
        e["pid"] for e in merged["traceEvents"]
        if e.get("cat") in ("epoch", "dispatch")
    }
    assert all(p >= 1000 for p in host_pids)
    # earliest-alignment shifted host spans onto the device epoch
    host_ts = [
        e["ts"] for e in merged["traceEvents"] if e.get("cat") == "epoch"
    ]
    assert min(host_ts) == pytest.approx(1000, abs=1)
