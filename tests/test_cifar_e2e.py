"""Entrypoint-level e2e over miniature CIFAR-formatted archives — both the
``cifar-10-batches-py`` pickle layout and the ``-bin`` binary layout
(reference data_and_toy_model.py:8-38). The real CIFAR-10 archive cannot be
staged in this zero-egress environment (BASELINE.md), so these fixtures make
the ONLY untested link in the reference workload the real archive's bytes:
the exact on-disk formats flow through `python train_native.py
--settings_file ...` as a real subprocess, producing the epoch log and
checkpoints."""

import os
import pickle
import subprocess
import sys

import numpy as np
import pytest
import yaml

from tpuddp.data.cifar10 import CIFAR10

N_PER_BATCH = 16  # 5 train batches of 16 + one test batch of 16


def _images_labels(seed: int, n: int):
    rs = np.random.RandomState(seed)
    # class-dependent mean so the toy model has signal to fit
    labels = rs.randint(0, 10, n).astype(np.int64)
    images = (
        rs.randint(0, 64, (n, 32, 32, 3)) + labels[:, None, None, None] * 19
    ).astype(np.uint8)
    return images, labels


def make_cifar_py_fixture(root) -> None:
    """data_batch_{1-5} / test_batch pickles with the exact torchvision keys:
    b'data' (N, 3072) uint8 rows in CHW order, b'labels' list of ints."""
    d = os.path.join(root, "cifar-10-batches-py")
    os.makedirs(d, exist_ok=True)
    for i, name in enumerate([f"data_batch_{j}" for j in range(1, 6)] + ["test_batch"]):
        images, labels = _images_labels(100 + i, N_PER_BATCH)
        rows = images.transpose(0, 3, 1, 2).reshape(N_PER_BATCH, 3072)
        with open(os.path.join(d, name), "wb") as f:
            pickle.dump({b"data": rows, b"labels": labels.tolist()}, f)


def make_cifar_bin_fixture(root) -> None:
    """data_batch_{1-5}.bin / test_batch.bin: rows of 1 label byte + 3072
    CHW image bytes (the same pixels as the py fixture, by seed)."""
    d = os.path.join(root, "cifar-10-batches-bin")
    os.makedirs(d, exist_ok=True)
    names = [f"data_batch_{j}.bin" for j in range(1, 6)] + ["test_batch.bin"]
    for i, name in enumerate(names):
        images, labels = _images_labels(100 + i, N_PER_BATCH)
        rows = images.transpose(0, 3, 1, 2).reshape(N_PER_BATCH, 3072)
        raw = np.concatenate(
            [labels.astype(np.uint8)[:, None], rows], axis=1
        ).astype(np.uint8)
        raw.tofile(os.path.join(d, name))


def test_py_and_bin_fixtures_load_identically(tmp_path):
    """The two on-disk formats must decode to the same pixels/labels — the
    loader-level guarantee behind running either archive flavor."""
    py_root = tmp_path / "py"
    bin_root = tmp_path / "bin"
    make_cifar_py_fixture(str(py_root))
    make_cifar_bin_fixture(str(bin_root))
    for train in (True, False):
        a = CIFAR10(str(py_root), train=train)
        b = CIFAR10(str(bin_root), train=train)
        np.testing.assert_array_equal(a.images, b.images)
        np.testing.assert_array_equal(a.labels, b.labels)
    assert len(CIFAR10(str(py_root), train=True)) == 5 * N_PER_BATCH


def _run_native_cli(tmp_path, data_root: str):
    settings = {
        "script_path": "train_native.py",
        "out_dir": str(tmp_path / "out"),
        "optional_args": {"set_epoch": True, "print_rand": False},
        "local": {"device": "cpu", "tpu": {"num_chips": 4}},
        "training": {
            "model": "toy_mlp",
            "dataset": "cifar10",
            "data_root": data_root,
            "train_batch_size": 4,
            "test_batch_size": 4,
            "learning_rate": 0.01,
            "num_epochs": 2,
            "checkpoint_epoch": 1,
            "image_size": None,
            "seed": 0,
            "mode": "shard_map",
            "sync_bn": False,
        },
    }
    sf = tmp_path / "s.yaml"
    sf.write_text(yaml.dump(settings))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep the child off the TPU tunnel
    env.pop("TPUDDP_DATA", None)  # the settings' data_root must be what loads
    env["TPUDDP_BACKEND"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "train_native.py", "--settings_file", str(sf)],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    # the fixture data actually loaded: no synthetic-fallback warning
    combined = proc.stdout + proc.stderr
    assert "synthetic" not in combined.lower()
    assert "Epoch 1/2" in proc.stdout and "Epoch 2/2" in proc.stdout
    assert "Test Accuracy" in proc.stdout
    assert os.path.exists(tmp_path / "out" / "ckpt_0.npz")
    assert os.path.exists(tmp_path / "out" / "ckpt_1.npz")


@pytest.mark.slow
def test_native_cli_on_cifar_py_fixture(tmp_path):
    data_root = str(tmp_path / "data")
    make_cifar_py_fixture(data_root)
    _run_native_cli(tmp_path, data_root)


@pytest.mark.slow
def test_native_cli_on_cifar_bin_fixture(tmp_path):
    data_root = str(tmp_path / "data")
    make_cifar_bin_fixture(data_root)
    _run_native_cli(tmp_path, data_root)
