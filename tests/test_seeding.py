"""Rank-aware seeding parity (reference multi-GPU-training-torch.py:54-69) —
the RNG-state probe (reference :180-183) turned into asserts."""

import random

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpuddp.utils.compat import shard_map
from tpuddp import seeding
from tpuddp.parallel.mesh import DATA_AXIS


def test_ranks_get_distinct_keys():
    k0, base = seeding.set_seed_based_on_rank(rank=0, base_seed=1234)
    k1, _ = seeding.set_seed_based_on_rank(rank=1, base_seed=1234)
    assert base == 1234
    assert not np.array_equal(jax.random.key_data(k0), jax.random.key_data(k1))


def test_python_numpy_seeded_in_reduced_range():
    seeding.set_seed_based_on_rank(rank=2, base_seed=2**40)
    py_draw = random.random()
    np_draw = np.random.rand()
    # replay: same reduced seed + rank must reproduce
    expected_seed = (2**40) % (2**32 - 1) + 2
    random.seed(expected_seed)
    np.random.seed(expected_seed % 2**32)
    assert random.random() == py_draw
    assert np.random.rand() == np_draw


def test_fresh_base_seed_per_run():
    _, a = seeding.set_seed_based_on_rank(rank=0)
    _, b = seeding.set_seed_based_on_rank(rank=0)
    assert a != b  # analog of torch initial_seed varying per spawn


def test_probe_string_mentions_base_seed():
    seeding.set_seed_based_on_rank(rank=0, base_seed=42)
    s = seeding.rng_probe_string()
    assert "base seed: 42" in s
    assert seeding.last_base_seed() == 42


def test_fold_in_axis_index_diverges_per_replica(mesh):
    key, _ = seeding.set_seed_based_on_rank(rank=0, base_seed=0)

    def draw(k):
        k = seeding.fold_in_axis_index(k, DATA_AXIS)
        return jax.random.uniform(k, (1,))

    out = jax.jit(
        shard_map(draw, mesh=mesh, in_specs=None, out_specs=P(DATA_AXIS))
    )(key)
    vals = np.asarray(out)
    assert len(set(vals.tolist())) == 8  # every replica drew differently
