"""Collectives over the 8-device CPU mesh — the dist.all_reduce/barrier/broadcast
contracts (SURVEY.md §2b #11, reference multi-GPU-training-torch.py:194-204,245)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuddp.utils.compat import shard_map
from tpuddp.parallel import collectives as col
from tpuddp.parallel.mesh import DATA_AXIS


def shmap(mesh, fn, in_specs, out_specs):
    return jax.jit(
        shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


def test_all_reduce_sum_matches_dist_all_reduce(mesh):
    x = jnp.arange(8.0)
    out = shmap(mesh, lambda v: col.psum(v), P(DATA_AXIS), P())(x)
    np.testing.assert_allclose(out, np.full((1,), 28.0))


def test_pmean_is_ddp_grad_average(mesh):
    x = jnp.arange(8.0)
    out = shmap(mesh, lambda v: col.pmean(v), P(DATA_AXIS), P())(x)
    np.testing.assert_allclose(out, np.full((1,), 3.5))


def test_all_reduce_pytree_and_ops(mesh):
    tree = {"a": jnp.arange(8.0), "b": jnp.ones(8)}
    out = shmap(mesh, lambda t: col.all_reduce(t, "max"), P(DATA_AXIS), P())(tree)
    np.testing.assert_allclose(out["a"], [7.0])
    np.testing.assert_allclose(out["b"], [1.0])
    out = shmap(mesh, col.pmax, P(DATA_AXIS), P())(jnp.arange(8.0))
    np.testing.assert_allclose(out, [7.0])
    with pytest.raises(ValueError):
        col.all_reduce(jnp.ones(8), "median")


def test_all_gather(mesh):
    x = jnp.arange(8.0)
    out = shmap(mesh, lambda v: col.all_gather(v, tiled=True), P(DATA_AXIS), P(DATA_AXIS))(x)
    # every shard holds the full gathered vector; global shape is 8*8
    assert out.shape == (64,)
    np.testing.assert_allclose(np.asarray(out)[:8], np.arange(8.0))


def test_reduce_scatter(mesh):
    x = jnp.ones((8, 8))
    out = shmap(
        mesh, lambda v: col.reduce_scatter(v.sum(0)), P(DATA_AXIS), P(DATA_AXIS)
    )(x)
    np.testing.assert_allclose(out, np.full(8, 8.0))


def test_ppermute_ring(mesh):
    x = jnp.arange(8.0)
    perm = [(i, (i + 1) % 8) for i in range(8)]
    out = shmap(mesh, lambda v: col.ppermute(v, perm), P(DATA_AXIS), P(DATA_AXIS))(x)
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_broadcast_from_root(mesh):
    x = jnp.arange(8.0) + 100.0
    out = shmap(mesh, lambda v: col.broadcast(v, root=3), P(DATA_AXIS), P(DATA_AXIS))(x)
    np.testing.assert_allclose(out, np.full(8, 103.0))


def test_axis_index_is_rank(mesh):
    out = shmap(
        mesh,
        lambda: col.axis_index().reshape(1),
        (),
        P(DATA_AXIS),
    )()
    np.testing.assert_array_equal(out, np.arange(8))


def test_finalize_metrics_aggregates_sharded_metrics(mesh):
    # per-device partial sums, as the shard_map train step emits them; the
    # epoch-end path (the reference's five dist.all_reduce calls,
    # multi-GPU-training-torch.py:198-204) is finalize_metrics
    from tpuddp.training.step import finalize_metrics

    parts = jax.device_put(jnp.arange(8.0), NamedSharding(mesh, P(DATA_AXIS)))
    assert finalize_metrics({"loss_sum": parts})["loss_sum"] == 28.0


def test_barrier_single_host_noop(mesh):
    col.barrier("test", wait_for=jnp.ones(3))  # must not raise


def test_broadcast_one_to_all_single_process_identity():
    tree = {"w": np.ones(3)}
    assert col.broadcast_one_to_all(tree) is tree
