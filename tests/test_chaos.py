"""Chaos suite (ISSUE 1 + ISSUE 3 acceptance): real subprocess kills and
injected faults against the resilience layer.

Scenarios: an external SIGTERM mid-training drains into a valid emergency
checkpoint and exit 75, auto-resume continues exactly where it left off; a
corrupted newest checkpoint is skipped in favor of the previous good one; an
injected ``hang@barrier`` dead peer is detected by the heartbeat watchdog
within the configured timeout (exit 76) instead of hanging forever; an
injected ``nan@step=N`` gradient is skipped by the numerical-guard firewall
(state stays finite, run finishes 0); a single-replica parameter
perturbation is caught by the desync auditor — exit 77, or a recorded
rollback-to-last-good when ``on_desync="rollback"``.

Marked ``chaos`` + ``slow``: run with ``tools/run_chaos.py`` or
``pytest -m chaos``; never part of the tier-1 fast path.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tpuddp.resilience import integrity
from tpuddp.resilience.preemption import (
    EXIT_DESYNC,
    EXIT_INJECTED_CRASH,
    EXIT_PREEMPTED,
    EXIT_WATCHDOG,
)
from tpuddp.training import checkpoint as ckpt

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIN_WORKER = os.path.join(REPO, "tests", "_chaos_train_worker.py")
ACCEL_WORKER = os.path.join(REPO, "tests", "_chaos_accel_worker.py")
HANG_WORKER = os.path.join(REPO, "tests", "_chaos_hang_worker.py")
DESYNC_WORKER = os.path.join(REPO, "tests", "_chaos_desync_worker.py")
SUPERVISE = os.path.join(REPO, "tools", "supervise.py")


def chaos_env(**extra):
    env = dict(os.environ)
    # clean CPU-only children: no TPU plugin, no inherited fault/resume flags
    env.pop("PALLAS_AXON_POOL_IPS", None)
    for k in (
        "TPUDDP_FAULT", "TPUDDP_AUTO_RESUME", "TPUDDP_WATCHDOG_TIMEOUT",
        "TPUDDP_CHAOS_TRAINING", "TPUDDP_DEBUG_NANS", "TPUDDP_WORLD_SIZE",
        "TPUDDP_MODEL_SIZE", "TPUDDP_CHAOS_PARALLEL",
    ):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["TPUDDP_BACKEND"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update({k: str(v) for k, v in extra.items()})
    return env


def run_train_worker(out_dir, epochs, env, timeout=300, worker=TRAIN_WORKER):
    return subprocess.run(
        [sys.executable, "-u", worker, str(out_dir), str(epochs)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout,
    )


def validate_history(out_dir):
    """tpuddp_inspect --validate must accept the (merged, multi-run)
    history.jsonl — the schema-v2 stream the elastic matrix asserts."""
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "tpuddp_inspect.py"),
            "--validate", os.path.join(str(out_dir), "history.jsonl"),
        ],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def topology_events(out_dir):
    return [
        r for r in history_records(out_dir)
        if r.get("event") == "topology_change"
    ]


def history_records(out_dir):
    with open(os.path.join(str(out_dir), "history.jsonl")) as f:
        return [json.loads(line) for line in f]


def history_epochs(out_dir):
    # typed record stream (tpuddp/observability/schema.py): epoch progress is
    # the `epoch`-type rows; run_meta headers and event rows ride alongside
    return [r["epoch"] for r in history_records(out_dir) if r.get("type") == "epoch"]


def test_sigterm_drain_then_auto_resume_round_trip(tmp_path):
    """The headline scenario: a scheduler SIGTERMs the run mid-training; it
    drains into an intact emergency checkpoint and exits 75; the requeued
    command (same argv + $TPUDDP_AUTO_RESUME=1) continues from the recorded
    epoch with no epoch skipped and none lost."""
    epochs = 30
    proc = subprocess.Popen(
        [sys.executable, "-u", TRAIN_WORKER, str(tmp_path), str(epochs)],
        env=chaos_env(), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    killed = False
    deadline = time.time() + 240
    lines = []
    for line in proc.stdout:  # epoch banners stream as training progresses
        lines.append(line)
        if not killed and ", Epoch 1" in line:
            proc.send_signal(signal.SIGTERM)
            killed = True
        assert time.time() < deadline, "worker did not finish draining in time"
    rc = proc.wait(timeout=60)
    out = "".join(lines)
    assert killed, f"never saw the epoch-1 banner:\n{out[-2000:]}"
    assert rc == EXIT_PREEMPTED, f"exit {rc} != {EXIT_PREEMPTED}:\n{out[-2000:]}"
    assert "emergency checkpoint" in out

    # the emergency save is the newest checkpoint, intact, and marked as a
    # mid-epoch drain (completed=0 -> resume redoes that epoch)
    found = ckpt.latest(str(tmp_path))
    assert found is not None
    path, interrupted_epoch = found
    assert integrity.verify_file(path)
    assert ckpt.read_meta(path)["completed"] == 0

    # the drain's fsync'd event row survived the kill (MetricsWriter.sync on
    # the preemption path): the interrupted run's LAST record is a complete
    # preempt event — never a truncated line
    records = history_records(tmp_path)
    assert records[-1].get("event") == "preempt", records[-1]
    assert records[-1]["epoch"] == interrupted_epoch
    assert records[0].get("type") == "run_meta"

    resumed = run_train_worker(tmp_path, epochs=6, env=chaos_env(TPUDDP_AUTO_RESUME=1))
    assert resumed.returncode == 0, resumed.stdout[-2000:] + resumed.stderr[-2000:]
    assert f"Auto-resume: continuing from epoch {interrupted_epoch}." in resumed.stdout
    assert "Finished Training" in resumed.stdout
    # exact continuation: run 1 logged epochs [0..k), run 2 logged [k..6) —
    # appended history covers every epoch exactly once, in order
    assert history_epochs(tmp_path) == list(range(6))


def test_injected_preempt_is_deterministic(tmp_path):
    """preempt@epoch=1 SIGTERMs the process from inside at a known point: the
    drain must land the emergency checkpoint at exactly epoch 1."""
    first = run_train_worker(
        tmp_path, epochs=4, env=chaos_env(TPUDDP_FAULT="preempt@epoch=1")
    )
    assert first.returncode == EXIT_PREEMPTED, (
        first.stdout[-2000:] + first.stderr[-2000:]
    )
    emergency = os.path.join(str(tmp_path), "ckpt_1.npz")
    assert integrity.verify_file(emergency)
    assert ckpt.read_meta(emergency) == {"epoch": 1, "completed": 0}

    resumed = run_train_worker(tmp_path, epochs=4, env=chaos_env(TPUDDP_AUTO_RESUME=1))
    assert resumed.returncode == 0, resumed.stdout[-2000:] + resumed.stderr[-2000:]
    assert "Auto-resume: continuing from epoch 1." in resumed.stdout
    assert history_epochs(tmp_path) == [0, 1, 2, 3]


def test_corrupt_newest_checkpoint_falls_back_on_resume(tmp_path):
    """corrupt@ckpt_1 garbles the epoch-1 checkpoint after publish, then
    crash@epoch=2 kills the run uncleanly (exit 113). The resumed run must
    skip the corrupt newest file with a logged warning and continue from the
    previous good epoch — redoing epoch 1 rather than crashing or trusting
    torn bytes."""
    first = run_train_worker(
        tmp_path, epochs=4,
        env=chaos_env(TPUDDP_FAULT="corrupt@ckpt_1,crash@epoch=2"),
    )
    assert first.returncode == EXIT_INJECTED_CRASH, (
        first.stdout[-2000:] + first.stderr[-2000:]
    )
    assert integrity.verify_file(os.path.join(str(tmp_path), "ckpt_0.npz"))
    assert not integrity.verify_file(os.path.join(str(tmp_path), "ckpt_1.npz"))

    resumed = run_train_worker(tmp_path, epochs=4, env=chaos_env(TPUDDP_AUTO_RESUME=1))
    assert resumed.returncode == 0, resumed.stdout[-2000:] + resumed.stderr[-2000:]
    both = resumed.stdout + resumed.stderr
    assert "failed integrity verification" in both
    assert "Auto-resume: continuing from epoch 1." in resumed.stdout
    # epoch 1 ran twice: its first checkpoint was corrupted, so the resumed
    # run redid it from the epoch-0 state
    assert history_epochs(tmp_path) == [0, 1, 1, 2, 3]
    assert integrity.verify_file(os.path.join(str(tmp_path), "ckpt_3.npz"))


def test_nan_gradient_firewalled_end_to_end(tmp_path):
    """ISSUE 3 chaos proof, firewall leg: a nan@step=N fault poisons one
    train micro-batch's gradient mid-run; the guarded run must skip exactly
    that update (recorded in history.jsonl), keep every later epoch finite,
    and finish with exit 0 — the poisoned step never reaches the state."""
    proc = run_train_worker(
        tmp_path, epochs=4,
        env=chaos_env(
            TPUDDP_FAULT="nan@step=12",  # epoch 1 (8 batch groups/epoch)
            TPUDDP_CHAOS_TRAINING=json.dumps({"guard": True}),
        ),
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "nan@step=12 fired" in proc.stdout + proc.stderr
    rows = [r for r in history_records(tmp_path) if r.get("type") == "epoch"]
    assert [r["epoch"] for r in rows] == [0, 1, 2, 3]
    # the skip also landed as a typed event row next to the epoch fields
    assert any(
        r.get("event") == "skipped_updates" and r["epoch"] == 1
        for r in history_records(tmp_path)
    )
    by_epoch = {r["epoch"]: r for r in rows}
    assert by_epoch[1]["skipped_steps_epoch"] == 1
    assert by_epoch[0]["skipped_steps_epoch"] == 0
    assert by_epoch[3]["skipped_steps"] == 1
    # the poisoned epoch's row is a strict-JSON post-mortem (null, not NaN);
    # every later epoch trains on finite numbers
    assert by_epoch[1]["train_loss"] is None
    for e in (2, 3):
        assert by_epoch[e]["train_loss"] is not None
        assert np.isfinite(by_epoch[e]["train_loss"])


def test_desync_auditor_exits_77(tmp_path):
    """ISSUE 3 chaos proof, auditor leg: one device's copy of a replicated
    parameter is perturbed; the next epoch-boundary audit must name the
    divergent leaf and exit EXIT_DESYNC (77)."""
    proc = subprocess.run(
        [sys.executable, "-u", DESYNC_WORKER, str(tmp_path), "exit"],
        env=chaos_env(), cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == EXIT_DESYNC, (
        f"exit {proc.returncode}:\n" + proc.stdout[-2000:] + proc.stderr[-2000:]
    )
    both = proc.stdout + proc.stderr
    assert "cross-replica desync" in both
    assert "bias" in both or "weight" in both  # the leaf is named
    rows = [
        json.loads(line)
        for line in open(os.path.join(str(tmp_path), "history.jsonl"))
    ]
    assert any(r.get("event") == "desync" for r in rows)


def test_desync_rollback_recovers_and_finishes(tmp_path):
    """ISSUE 3 chaos proof, rollback leg: with on_desync="rollback" and an
    intact epoch-0 checkpoint, the perturbed state is discarded, the run
    restores last-good, redoes the epoch, and finishes with exit 0 and a
    rollback event in history.jsonl."""
    proc = subprocess.run(
        [sys.executable, "-u", DESYNC_WORKER, str(tmp_path), "rollback"],
        env=chaos_env(), cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (
        f"exit {proc.returncode}:\n" + proc.stdout[-2000:] + proc.stderr[-2000:]
    )
    assert "Guard rollback" in proc.stdout + proc.stderr
    rows = [
        json.loads(line)
        for line in open(os.path.join(str(tmp_path), "history.jsonl"))
    ]
    events = [r for r in rows if r.get("event") == "rollback"]
    assert events and events[0]["resume_epoch"] == 1
    assert [r["epoch"] for r in rows if "train_loss" in r] == [0, 1, 2]


ELASTIC_CFG = {"comm_hook": "bf16_ef", "flip": False}  # bf16_ef arms the
# per-replica error-feedback residual — the hardest state to move between
# world sizes; flip off keeps the trajectory partition-independent so the
# parity leg compares like with like.


def _elastic_training(world_bs, **extra):
    cfg = dict(ELASTIC_CFG)
    cfg.update(train_batch_size=world_bs, test_batch_size=world_bs)
    cfg.update(extra)
    return json.dumps(cfg)


def test_elastic_shrink_resume_with_loss_parity(tmp_path):
    """ISSUE 7 chaos proof, headline leg: a bf16_ef run killed on 4 devices
    at the epoch-2 boundary resumes on 2 devices (same GLOBAL batch: the
    per-replica batch size doubles) through the elastic v2 restore — the
    residual redistributes sum-preservingly (M | N: no reset), a
    topology-change event row lands in history.jsonl, the merged stream
    validates as schema v2, and the post-resume loss trajectory matches an
    uninterrupted same-seed 4-device run (the trajectory only moves by the
    partition's f32/bf16 reassociation, not by any lost state)."""
    epochs = 4
    # uninterrupted baseline, world 4 x bs 8 (global 32)
    base_dir = tmp_path / "baseline"
    base = run_train_worker(
        base_dir, epochs,
        env=chaos_env(TPUDDP_CHAOS_TRAINING=_elastic_training(8)),
    )
    assert base.returncode == 0, base.stdout[-2000:] + base.stderr[-2000:]
    base_rows = {
        r["epoch"]: r for r in history_records(base_dir)
        if r.get("type") == "epoch"
    }

    # killed run: same seed/config, preempted at the epoch-2 boundary
    out = tmp_path / "elastic"
    first = run_train_worker(
        out, epochs,
        env=chaos_env(
            TPUDDP_CHAOS_TRAINING=_elastic_training(8),
            TPUDDP_FAULT="preempt@epoch=2",
        ),
    )
    assert first.returncode == EXIT_PREEMPTED, (
        first.stdout[-2000:] + first.stderr[-2000:]
    )
    emergency = os.path.join(str(out), "ckpt_2.npz")
    assert ckpt.read_meta(emergency) == {"epoch": 2, "completed": 0}
    topo = ckpt.read_topology(emergency)
    assert topo["world_size"] == 4
    assert topo["leaves"][".comm_state"]["kind"] == "per_replica"

    # resume on HALF the world, per-replica batch doubled (global unchanged)
    resumed = run_train_worker(
        out, epochs,
        env=chaos_env(
            TPUDDP_CHAOS_TRAINING=_elastic_training(16),
            TPUDDP_AUTO_RESUME=1,
            TPUDDP_WORLD_SIZE=2,
        ),
    )
    assert resumed.returncode == 0, (
        resumed.stdout[-2000:] + resumed.stderr[-2000:]
    )
    assert "Auto-resume: continuing from epoch 2." in resumed.stdout

    # every epoch trained exactly once across the two runs
    assert history_epochs(out) == list(range(epochs))
    # the topology change is a typed, validated record
    events = topology_events(out)
    assert events and events[0]["from_world"] == 4
    assert events[0]["to_world"] == 2
    assert events[0]["residual"] == "redistributed"  # M | N: NO reset
    assert ".comm_state" in events[0]["resharded_leaves"]
    assert not any(
        r.get("event") == "comm_state_reset" for r in history_records(out)
    )
    # the resumed run's header names its provenance
    metas = [
        r for r in history_records(out)
        if r.get("type") == "run_meta" and r.get("resumed_from_world")
    ]
    assert metas and metas[0]["resumed_from_world"] == 4
    assert metas[0]["world_size"] == 2
    validate_history(out)

    # loss-trajectory parity vs the uninterrupted run: epochs 0-1 ran on the
    # identical world (bitwise-equal states feed epoch 2), epochs 2-3 see the
    # SAME global batches partitioned 2-ways instead of 4 — only f32
    # reduction order and per-replica bf16 rounding move, bounded small
    el_rows = {
        r["epoch"]: r for r in history_records(out) if r.get("type") == "epoch"
    }
    for e in range(epochs):
        assert np.isfinite(el_rows[e]["train_loss"])
        np.testing.assert_allclose(
            el_rows[e]["train_loss"], base_rows[e]["train_loss"],
            rtol=0.05, atol=0.05,
            err_msg=f"epoch {e} train-loss parity broken",
        )
        np.testing.assert_allclose(
            el_rows[e]["test_loss"], base_rows[e]["test_loss"],
            rtol=0.05, atol=0.05,
            err_msg=f"epoch {e} test-loss parity broken",
        )


def test_elastic_grow_resume_after_midepoch_kill(tmp_path):
    """N < M leg: a 2-device run is killed MID-epoch (preempt@step fires
    inside epoch 1's train pass) and resumes on 4 devices. The emergency
    checkpoint carries mid-epoch state (completed=0 -> epoch 1 is redone
    from it), the residual redistributes by placement (N | M), and the
    finished stream validates."""
    out = tmp_path / "grow"
    first = run_train_worker(
        out, 3,
        env=chaos_env(
            TPUDDP_CHAOS_TRAINING=_elastic_training(16),
            TPUDDP_WORLD_SIZE=2,
            TPUDDP_FAULT="preempt@step=12",  # epoch 1, batch 4 of 8
        ),
    )
    assert first.returncode == EXIT_PREEMPTED, (
        first.stdout[-2000:] + first.stderr[-2000:]
    )
    assert "preempt@step fired" in first.stdout + first.stderr
    found = ckpt.latest(str(out))
    assert found is not None
    path, epoch = found
    assert epoch == 1 and ckpt.read_meta(path)["completed"] == 0
    assert ckpt.read_topology(path)["world_size"] == 2

    resumed = run_train_worker(
        out, 3,
        env=chaos_env(
            TPUDDP_CHAOS_TRAINING=_elastic_training(8),
            TPUDDP_AUTO_RESUME=1,
            TPUDDP_WORLD_SIZE=4,
        ),
    )
    assert resumed.returncode == 0, (
        resumed.stdout[-2000:] + resumed.stderr[-2000:]
    )
    assert "Auto-resume: continuing from epoch 1." in resumed.stdout
    # run 1 completed epoch 0 only; the interrupted epoch 1 is redone on 4
    assert history_epochs(out) == [0, 1, 2]
    events = topology_events(out)
    assert events and (events[0]["from_world"], events[0]["to_world"]) == (2, 4)
    assert events[0]["residual"] == "redistributed"
    validate_history(out)


def test_elastic_resume_managed_entrypoint(tmp_path):
    """Accelerator-entrypoint leg: a managed run with weight-update sharding
    (flat world-padded moment vectors — the data_flat reshard) killed on 4
    devices resumes on 2 through load_state's elastic path, lands the
    topology-change event row, and finishes with a valid stream."""
    cfg = {"weight_update_sharding": True, "flip": False}
    out = tmp_path / "managed"
    first = run_train_worker(
        out, 4,
        env=chaos_env(
            TPUDDP_CHAOS_TRAINING=json.dumps(dict(cfg, train_batch_size=8,
                                                  test_batch_size=8)),
            TPUDDP_FAULT="preempt@epoch=2",
        ),
        worker=ACCEL_WORKER,
    )
    assert first.returncode == EXIT_PREEMPTED, (
        first.stdout[-2000:] + first.stderr[-2000:]
    )
    # the managed drain publishes the last COMPLETED epoch's lossless state
    found = ckpt.latest(str(out), prefix="state")
    assert found is not None and found[1] == 1
    assert ckpt.read_topology(found[0])["world_size"] == 4

    resumed = run_train_worker(
        out, 4,
        env=chaos_env(
            TPUDDP_CHAOS_TRAINING=json.dumps(dict(cfg, train_batch_size=16,
                                                  test_batch_size=16)),
            TPUDDP_AUTO_RESUME=1,
            TPUDDP_WORLD_SIZE=2,
        ),
        worker=ACCEL_WORKER,
    )
    assert resumed.returncode == 0, (
        resumed.stdout[-2000:] + resumed.stderr[-2000:]
    )
    assert "Resumed from epoch 1 state." in resumed.stdout
    assert history_epochs(out) == [0, 1, 2, 3]
    events = topology_events(out)
    assert events and (events[0]["from_world"], events[0]["to_world"]) == (4, 2)
    # WUS flat moments re-padded onto the smaller world
    assert any(
        leaf.startswith("['opt_state']")
        for leaf in events[0]["resharded_leaves"]
    ), events[0]
    metas = [
        r for r in history_records(out)
        if r.get("type") == "run_meta" and r.get("resumed_from_world")
    ]
    assert metas and metas[0]["resumed_from_world"] == 4
    validate_history(out)


def test_elastic_mismatched_world_resets_residual(tmp_path):
    """M∤N leg (4 -> 3): no sum-preserving redistribution exists, so the
    bf16_ef residual RESETS — the run must still resume and finish, with the
    documented comm_state_reset event row beside the topology change."""
    out = tmp_path / "mismatch"
    first = run_train_worker(
        out, 3,
        env=chaos_env(
            TPUDDP_CHAOS_TRAINING=_elastic_training(8),
            TPUDDP_FAULT="preempt@epoch=1",
        ),
    )
    assert first.returncode == EXIT_PREEMPTED, (
        first.stdout[-2000:] + first.stderr[-2000:]
    )
    resumed = run_train_worker(
        out, 3,
        env=chaos_env(
            TPUDDP_CHAOS_TRAINING=_elastic_training(8),
            TPUDDP_AUTO_RESUME=1,
            TPUDDP_WORLD_SIZE=3,
        ),
    )
    assert resumed.returncode == 0, (
        resumed.stdout[-2000:] + resumed.stderr[-2000:]
    )
    events = topology_events(out)
    assert events and events[0]["residual"] == "reset"
    resets = [
        r for r in history_records(out)
        if r.get("event") == "comm_state_reset"
    ]
    assert resets and resets[0]["from_world"] == 4
    assert resets[0]["to_world"] == 3
    assert history_epochs(out) == [0, 1, 2]
    validate_history(out)


TP_WORKER = os.path.join(REPO, "tests", "_chaos_tp_worker.py")


def test_tp_mesh_failover_both_smaller_shapes_with_loss_parity(tmp_path):
    """ISSUE 16 headline: a TP=2 x DP=2 token-LM job killed mid-epoch
    auto-resumes at BOTH feasible 2-chip shapes — TP=2 x DP=1 (data shrink)
    AND TP=1 x DP=2 (model-width crossing, full reshard) — and each lands
    the same loss trajectory as the uninterrupted 4-chip run. The reshard
    episode is named on every surface: typed topology_change rows with
    model widths, a run_meta resumed_from_model header, an 'elastic
    reshard' trace span, and (second leg, preempted again post-reshard) a
    flight-recorder note in the crash dump."""
    epochs = 3
    base_dir = tmp_path / "baseline"
    base = run_train_worker(base_dir, epochs, env=chaos_env(),
                            worker=TP_WORKER)
    assert base.returncode == 0, base.stdout[-2000:] + base.stderr[-2000:]
    base_rows = {
        r["epoch"]: r for r in history_records(base_dir)
        if r.get("type") == "epoch"
    }

    killed = tmp_path / "tp_elastic"
    first = run_train_worker(
        killed, epochs,
        env=chaos_env(TPUDDP_FAULT="preempt@epoch=1",
                      TPUDDP_CHAOS_OBS='{"tracing": true}'),
        worker=TP_WORKER,
    )
    assert first.returncode == EXIT_PREEMPTED, (
        first.stdout[-2000:] + first.stderr[-2000:]
    )
    emergency = os.path.join(str(killed), "ckpt_1.npz")
    assert ckpt.read_meta(emergency) == {"epoch": 1, "completed": 0}
    topo = ckpt.read_topology(emergency)
    assert topo["world_size"] == 4
    assert topo["model_size"] == 2
    assert topo["placement"]  # model-sharded leaves are tagged

    # fork the killed run dir: ONE capacity-loss event, both target shapes
    shrunk_tp = tmp_path / "tp2dp1"
    shutil.copytree(str(killed), str(shrunk_tp))

    # --- leg 1: TP=2 x DP=1 (the data axis absorbed the loss) -----------
    resumed = run_train_worker(
        shrunk_tp, epochs,
        env=chaos_env(TPUDDP_AUTO_RESUME=1, TPUDDP_WORLD_SIZE=2,
                      TPUDDP_MODEL_SIZE=2,
                      TPUDDP_CHAOS_OBS='{"tracing": true}'),
        worker=TP_WORKER,
    )
    assert resumed.returncode == 0, (
        resumed.stdout[-2000:] + resumed.stderr[-2000:]
    )
    assert "Auto-resume: continuing from epoch 1." in resumed.stdout
    assert history_epochs(shrunk_tp) == list(range(epochs))
    events = topology_events(shrunk_tp)
    assert events and (events[0]["from_world"], events[0]["to_world"]) == (4, 2)
    assert (events[0]["from_model"], events[0]["to_model"]) == (2, 2)
    metas = [
        r for r in history_records(shrunk_tp)
        if r.get("type") == "run_meta" and r.get("resumed_from_world")
    ]
    assert metas and metas[0]["resumed_from_world"] == 4
    assert metas[0]["resumed_from_model"] == 2
    assert metas[0]["mesh"] == {
        "data": 1, "model": 2, "tp_rules_hash": metas[0]["mesh"]["tp_rules_hash"],
    }
    validate_history(shrunk_tp)
    # the reshard episode is a named span in the resumed run's trace
    with open(os.path.join(str(shrunk_tp), "trace_train.json")) as f:
        spans = [
            e for e in json.load(f)["traceEvents"]
            if isinstance(e, dict) and e.get("ph") == "X"
        ]
    reshard_spans = [e for e in spans if e["name"] == "elastic reshard"]
    assert reshard_spans, [e["name"] for e in spans]
    assert reshard_spans[0]["args"]["from_world"] == 4
    assert reshard_spans[0]["args"]["to_world"] == 2

    # --- leg 2: TP=1 x DP=2 (model-width crossing) — preempted AGAIN so
    # the crash dump proves the flight recorder names the episode ---------
    second = run_train_worker(
        killed, epochs,
        env=chaos_env(TPUDDP_AUTO_RESUME=1, TPUDDP_WORLD_SIZE=2,
                      TPUDDP_MODEL_SIZE=1,
                      TPUDDP_FAULT="preempt@epoch=2"),
        worker=TP_WORKER,
    )
    assert second.returncode == EXIT_PREEMPTED, (
        second.stdout[-2000:] + second.stderr[-2000:]
    )
    with open(os.path.join(str(killed), "flightrec_preempt.json")) as f:
        flight = json.load(f)
    note = flight["notes"]["elastic_reshard"]
    assert (note["from_world"], note["to_world"]) == (4, 2)
    assert (note["from_model"], note["to_model"]) == (2, 1)
    final = run_train_worker(
        killed, epochs,
        env=chaos_env(TPUDDP_AUTO_RESUME=1, TPUDDP_WORLD_SIZE=2,
                      TPUDDP_MODEL_SIZE=1),
        worker=TP_WORKER,
    )
    assert final.returncode == 0, final.stdout[-2000:] + final.stderr[-2000:]
    assert history_epochs(killed) == list(range(epochs))
    events = topology_events(killed)
    assert (events[0]["from_model"], events[0]["to_model"]) == (2, 1)
    # the QKV relayout touched params AND their path-congruent moments
    assert any(
        leaf.endswith("['attn']['wqkv']") and leaf.startswith(".opt_state")
        for leaf in events[0]["resharded_leaves"]
    ), events[0]
    validate_history(killed)

    # --- loss-trajectory parity vs uninterrupted: pre-kill epochs fed
    # bitwise-equal state; post-reshard epochs see the SAME global batches
    # partitioned differently — only f32 reassociation moves (the f32
    # 'none' hook keeps compression out of the comparison)
    for out in (shrunk_tp, killed):
        rows = {
            r["epoch"]: r for r in history_records(out)
            if r.get("type") == "epoch"
        }
        for e in range(epochs):
            assert np.isfinite(rows[e]["train_loss"])
            np.testing.assert_allclose(
                rows[e]["train_loss"], base_rows[e]["train_loss"],
                rtol=1e-3, atol=2e-3,
                err_msg=f"{out}: epoch {e} train-loss parity broken",
            )


def test_fleet_resize_tp_job_rides_drain_contract(tmp_path):
    """ISSUE 16 fleet leg: a running TP=2 job resized by the controller
    (displaced by a higher-priority arrival) drains to exit 75 and
    relaunches at the clamped smaller world with $TPUDDP_MODEL_SIZE pinned
    — the child reshards onto TP=2 x DP=1 and finishes."""
    from tpuddp.fleet.controller import FleetController
    from tpuddp.fleet.spec import JobSpec
    from tpuddp.resilience.supervisor import SupervisorPolicy

    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "TPUDDP_BACKEND": "cpu",
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    c = FleetController(
        4, fleet_dir=str(tmp_path), env=env,
        supervisor_policy=SupervisorPolicy(backoff_base=0.1, backoff_cap=0.5),
    )
    tp = c.submit(JobSpec(
        name="tp-job", kind="training", priority=0,
        min_world=2, max_world=4, model_size=2,
        argv=(sys.executable, "-u", TP_WORKER, "{run_dir}", "6"),
    ))
    c.step()
    assert tp.state == "running" and tp.supervisor.world_size == 4
    assert tp.supervisor.model_size == 2
    # let it reach steady training (first checkpoint published) before the
    # displacement, so the SIGTERM drains a live epoch, not a compile
    deadline = time.time() + 300
    while not os.path.exists(os.path.join(tp.run_dir, "ckpt_0.npz")):
        assert time.time() < deadline, "tp job never published ckpt_0"
        assert tp.state == "running"
        c.step()
        time.sleep(0.5)
    c.submit(JobSpec(
        name="filler", kind="training", priority=1,
        min_world=2, max_world=2,
        argv=(sys.executable, "-c", "import time; time.sleep(600)"),
    ))
    # the plan shrinks tp-job 4 -> 2 through the drain; keep ticking until
    # the TP job finishes all 6 epochs at the smaller shape
    assert c.run_until(
        lambda ctl: ctl.jobs["tp-job"].state in ("done", "failed"),
        poll=0.5, timeout=480,
    )
    assert tp.state == "done", (tp.state, tp.exit_code)
    assert tp.resizes >= 1
    c.stop_job("filler")
    c.shutdown(timeout=60)

    assert history_epochs(tp.run_dir) == list(range(6))
    events = topology_events(tp.run_dir)
    assert events and (events[0]["from_world"], events[0]["to_world"]) == (4, 2)
    assert (events[0]["from_model"], events[0]["to_model"]) == (2, 2)
    metas = [
        r for r in history_records(tp.run_dir)
        if r.get("type") == "run_meta" and r.get("resumed_from_world")
    ]
    # the relaunched child derived data = 2 // 2 = 1 from the pinned width
    assert metas and metas[0]["mesh"]["data"] == 1
    assert metas[0]["mesh"]["model"] == 2
    validate_history(tp.run_dir)


def test_supervisor_end_to_end_preempt_then_resume(tmp_path):
    """The restart supervisor drives the whole cycle in ONE command: the
    first attempt is preempted (injected fault, applied to attempt 0 only),
    exits 75, and the supervisor relaunches the same argv with auto-resume —
    the run finishes 0 with every epoch trained exactly once."""
    env = chaos_env(TPUDDP_CHAOS_TRAINING=_elastic_training(8))
    proc = subprocess.run(
        [
            sys.executable, "-u", SUPERVISE,
            "--world", "4", "--max-restarts", "3",
            "--backoff-base", "0.1", "--backoff-cap", "0.5",
            "--first-env", "TPUDDP_FAULT=preempt@epoch=1",
            "--",
            sys.executable, "-u", TRAIN_WORKER, str(tmp_path), "3",
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    both = proc.stdout + proc.stderr
    assert "resuming immediately" in both
    assert history_epochs(tmp_path) == [0, 1, 2]
    validate_history(tmp_path)


def test_wedged_drain_forced_exit_summarized_before_restart(tmp_path):
    """Hang-then-escalate leg, failsafe half (ISSUE 11 satellite): a child
    whose SIGTERM drain WEDGES (never reaches a batch-group boundary) must
    be force-exited 75 by the failsafe only after $TPUDDP_PREEMPT_GRACE,
    dumping flightrec_preempt_forced.json on the way out — and the restart
    supervisor must summarize that recording BEFORE its restart decision."""
    wedge = os.path.join(REPO, "tests", "_chaos_wedge_worker.py")
    proc = subprocess.run(
        [
            sys.executable, "-u", SUPERVISE,
            "--max-restarts", "2", "--backoff-base", "0.1",
            "--flight-dir", str(tmp_path),
            "--",
            sys.executable, "-u", wedge, str(tmp_path), "wedge-drain",
        ],
        env=chaos_env(TPUDDP_PREEMPT_GRACE=3),
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    both = proc.stdout + proc.stderr
    assert proc.returncode == 0, both[-3000:]
    # the drain wedged and the FAILSAFE ended it — not a clean drain, and
    # not a SIGKILL: the grace window was honored, then exit 75
    assert "exceeded the 3s grace window" in both
    flightrec = os.path.join(str(tmp_path), "flightrec_preempt_forced.json")
    assert os.path.exists(flightrec)
    validate = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "tpuddp_inspect.py"),
            "--validate", flightrec,
        ],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert validate.returncode == 0, validate.stdout + validate.stderr
    # ordering: the supervisor read the post-mortem BEFORE deciding to
    # resume — the summary line precedes the restart line in its log
    summary_at = both.find("reason=preempt_forced")
    resume_at = both.find("resuming immediately")
    assert 0 <= summary_at < resume_at, both[-3000:]
    # the recording carried the worker's seeded ring + notes
    with open(flightrec) as f:
        payload = json.load(f)
    assert payload["reason"] == "preempt_forced"
    assert payload["notes"]["wedge_mode"] == "wedge-drain"
    assert any(
        e.get("event") == "wedge_armed" for e in payload["records"]["event"]
    )


def test_fleet_chaos_multi_job_pool(tmp_path):
    """ISSUE 11 acceptance: the scripted fleet chaos demo — >= 3 jobs
    (2 training + 1 serving + a late high-priority arrival) share one pool;
    one training job is SIGKILLed mid-run and resumes, the high-priority
    arrival shrinks a neighbor through the drain contract, the serving job
    autoscales replicas on a p99 SLO breach — then every job's namespaced
    history must validate with correct resumed_from_world attribution."""
    proc = subprocess.run(
        [
            sys.executable, "-u", os.path.join(REPO, "tools", "fleet.py"),
            "chaos-demo", "--out", str(tmp_path), "--timeout", "780",
        ],
        env=chaos_env(), cwd=REPO, capture_output=True, text=True, timeout=840,
    )
    assert proc.returncode == 0, (
        proc.stdout[-4000:] + "\n---\n" + proc.stderr[-4000:]
    )
    assert "fleet chaos: PASS" in proc.stdout
    jobs_dir = os.path.join(str(tmp_path), "jobs")
    names = sorted(os.listdir(jobs_dir))
    assert names == ["serve-c", "train-a", "train-b", "train-d"]
    # independent re-verification over the artifacts the demo left behind
    for name in names:
        validate_history(os.path.join(jobs_dir, name))
    a_records = [
        r for r in history_records(os.path.join(jobs_dir, "train-a"))
    ]
    topo = [r for r in a_records if r.get("event") == "topology_change"]
    assert any(t["from_world"] == 2 and t["to_world"] == 1 for t in topo)
    assert any(
        r.get("type") == "run_meta" and r.get("resumed_from_world") == 2
        for r in a_records
    )
    c_metas = [
        r for r in history_records(os.path.join(jobs_dir, "serve-c"))
        if r.get("type") == "run_meta"
    ]
    assert [m.get("num_replicas") for m in c_metas][0] == 1
    assert any(m.get("num_replicas") == 2 for m in c_metas)
    # namespacing: every training job kept its own checkpoint channel
    # under its own dir (per-job exporter ports are proven distinct by the
    # demo itself, mid-run, via read_live_port against each run dir)
    for name in ("train-a", "train-b", "train-d"):
        run_dir = os.path.join(jobs_dir, name)
        assert any(f.startswith("ckpt_") for f in os.listdir(run_dir))


def test_hang_at_barrier_detected_by_watchdog(tmp_path):
    """A peer that stops making progress (hang@barrier — indistinguishable
    from a preempted host) must be detected by the survivor's watchdog within
    the configured timeout, exiting 76 instead of blocking forever in the
    next collective."""
    timeout_s = 3.0
    survivor = subprocess.Popen(
        [sys.executable, "-u", HANG_WORKER, "0", "2", str(tmp_path)],
        env=chaos_env(TPUDDP_WATCHDOG_TIMEOUT=timeout_s), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    hanger = subprocess.Popen(
        [sys.executable, "-u", HANG_WORKER, "1", "2", str(tmp_path)],
        env=chaos_env(
            TPUDDP_WATCHDOG_TIMEOUT=timeout_s, TPUDDP_FAULT="hang@barrier"
        ),
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        # bound: two jax imports + rendezvous + the 3s stale window; anything
        # near the 120s ceiling means the watchdog failed and the test hung
        out, err = survivor.communicate(timeout=120)
        assert survivor.returncode == EXIT_WATCHDOG, (
            f"exit {survivor.returncode}:\n{out[-1000:]}\n{err[-2000:]}"
        )
        assert "WORKER 0 armed" in out
        assert "stale" in err  # the watchdog named the dead peer before exiting
        # heartbeat lag exported as a typed event record, fsync'd by the
        # detector BEFORE its os._exit(76)
        events = [
            r for r in history_records(tmp_path)
            if r.get("event") == "watchdog_stale"
        ]
        assert events, "no watchdog_stale event record written"
        assert events[0]["process"] == 0
        assert events[0]["stale_peers"][0]["process"] == 1
        assert events[0]["stale_peers"][0]["lag_s"] >= timeout_s
    finally:
        hanger.kill()
        hanger.communicate(timeout=30)
    assert hanger.returncode is not None


# --------------------------------------------- step-granular exact resume --


def train_losses(out_dir):
    return {
        r["epoch"]: r["train_loss"]
        for r in history_records(out_dir) if r.get("type") == "epoch"
    }


SNAPSHOT_TRAINING = {"snapshot": {"every_steps": 3}, "scan_steps": 1}


@pytest.mark.parametrize(
    "variant,extra",
    [
        ("explicit", {}),
        ("wus", {"weight_update_sharding": True}),
        ("bf16_ef", {"comm_hook": "bf16_ef"}),
    ],
)
def test_preempt_at_step_exact_resume_bitwise_parity(tmp_path, variant, extra):
    """ISSUE 18 acceptance: ``preempt@step=N`` kills the run MID-epoch with
    the snapshot engine armed; the drain flushes the async writer into a
    cursor-bearing step snapshot (the flight recording NAMES the flushed
    step), and the supervised auto-resume continues the epoch AT the
    recorded step — zero batches replayed, loss trajectory bitwise-equal to
    an uninterrupted same-seed twin. Across the explicit, weight-update-
    sharded, and error-feedback-compressed paths."""
    overrides = json.dumps(dict(SNAPSHOT_TRAINING, **extra))
    twin = tmp_path / "twin"
    out = tmp_path / "run"
    ref = run_train_worker(
        twin, 2, env=chaos_env(TPUDDP_CHAOS_TRAINING=overrides)
    )
    assert ref.returncode == 0, ref.stdout[-2000:] + ref.stderr[-2000:]

    first = run_train_worker(
        out, 2,
        env=chaos_env(
            TPUDDP_CHAOS_TRAINING=overrides, TPUDDP_FAULT="preempt@step=5"
        ),
    )
    assert first.returncode == EXIT_PREEMPTED, (
        first.stdout[-2000:] + first.stderr[-2000:]
    )
    assert "drained snapshot writer" in first.stdout
    # the drain's artifact is a STEP snapshot (v4 cursor), not ckpt_0.npz
    steps = sorted(
        f for f in os.listdir(out)
        if f.startswith("ckpt_0_s") and f.endswith(".npz")
    )
    assert steps and not os.path.exists(os.path.join(str(out), "ckpt_0.npz"))
    cur = ckpt.read_cursor(os.path.join(str(out), steps[-1]))
    assert cur["epoch"] == 0 and cur["plan_key"]
    drained_step = cur["step"]
    # satellite contract: the exit-75 flight recording names both the
    # writer-flushed step and the final drain step
    with open(os.path.join(str(out), "flightrec_preempt.json")) as f:
        notes = json.load(f)["notes"]
    assert notes["snapshot_final_step"] == drained_step
    assert "snapshot_flushed_step" in notes
    assert notes["snapshot_last"]["path"] in steps

    # requeue through the restart supervisor — the scheduler-shaped path
    resumed = subprocess.run(
        [
            sys.executable, "-u", SUPERVISE,
            "--world", "4", "--max-restarts", "2", "--auto-resume",
            "--backoff-base", "0.2",
            "--",
            sys.executable, "-u", TRAIN_WORKER, str(out), "2",
        ],
        env=chaos_env(TPUDDP_CHAOS_TRAINING=overrides),
        cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    assert resumed.returncode == 0, (
        resumed.stdout[-2000:] + resumed.stderr[-2000:]
    )
    assert (
        f"Exact resume: epoch 0 continues at step {drained_step} "
        "(zero batches replayed)." in resumed.stdout
    )
    # bitwise: the resumed trajectory equals the twin's, both epochs
    assert train_losses(out) == train_losses(twin)
    metas = [
        r for r in history_records(out)
        if r.get("type") == "run_meta" and isinstance(r.get("snapshot"), dict)
    ]
    assert metas and metas[-1]["snapshot"]["every_steps"] == 3
    validate_history(out)


def test_preempt_at_step_managed_exact_resume(tmp_path):
    """The managed-entrypoint leg: a mid-epoch ``preempt@step`` drains a
    ``state_<e>_s<s>.npz`` step snapshot whose cursor carries the partial
    loss accumulator; the requeued run continues AT the step and lands a
    loss trajectory bitwise-equal to the uninterrupted twin."""
    overrides = json.dumps({"snapshot": {"every_steps": 1}})
    twin = tmp_path / "twin"
    out = tmp_path / "run"
    ref = run_train_worker(
        twin, 2, env=chaos_env(TPUDDP_CHAOS_TRAINING=overrides),
        worker=ACCEL_WORKER,
    )
    assert ref.returncode == 0, ref.stdout[-2000:] + ref.stderr[-2000:]

    first = run_train_worker(
        out, 2,
        env=chaos_env(
            TPUDDP_CHAOS_TRAINING=overrides, TPUDDP_FAULT="preempt@step=2"
        ),
        worker=ACCEL_WORKER,
    )
    assert first.returncode == EXIT_PREEMPTED, (
        first.stdout[-2000:] + first.stderr[-2000:]
    )
    assert "step snapshot for epoch 0" in first.stdout
    steps = sorted(
        f for f in os.listdir(out)
        if f.startswith("state_0_s") and f.endswith(".npz")
    )
    assert steps, sorted(os.listdir(out))
    cur = ckpt.read_cursor(os.path.join(str(out), steps[-1]))
    drained_step = cur["step"]
    assert cur["epoch"] == 0 and cur["plan_key"]
    acc_keys = set(json.loads(json.dumps(list(cur["acc"]))))
    assert any("loss_total" in k for k in acc_keys)
    assert any("n_seen" in k for k in acc_keys)

    resumed = run_train_worker(
        out, 2,
        env=chaos_env(
            TPUDDP_CHAOS_TRAINING=overrides, TPUDDP_AUTO_RESUME=1
        ),
        worker=ACCEL_WORKER,
    )
    assert resumed.returncode == 0, (
        resumed.stdout[-2000:] + resumed.stderr[-2000:]
    )
    assert f"Resumed from step snapshot: epoch 0 step {drained_step}." in (
        resumed.stdout
    )
    assert (
        f"Exact resume: epoch 0 continues at step {drained_step} "
        "(zero batches replayed)." in resumed.stdout
    )
    assert train_losses(out) == train_losses(twin)
    validate_history(out)
