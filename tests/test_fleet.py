"""Fleet control plane unit suite (ISSUE 11) — fast tier.

The pure pieces are tested without any process tree: the gang-placement /
priority-preemption / rebalance planner (the acceptance criterion is that
placement decisions are deterministic functions of (pool, specs,
arrivals/exits)), the autoscaler's hysteresis/cooldown/straggler policy
matrix over synthetic observations, the Prometheus-scrape parsing, and the
stale-``exporter.port`` discovery contract. The controller lifecycle tests
use trivial python children (prints/sleeps) — the full jax chaos proof
lives in tests/test_chaos.py and ``tools/fleet.py chaos-demo``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tpuddp.fleet.autoscale import (
    Autoscaler,
    AutoscalePolicy,
    metric_value,
    parse_prometheus,
)
from tpuddp.fleet.controller import (
    FleetController,
    escalate_drain,
)
from tpuddp.fleet.scheduler import JobView, plan_fleet
from tpuddp.fleet.spec import FleetAdmissionError, JobSpec, spec_from_dict
from tpuddp.observability.exporter import MetricsExporter, read_live_port
from tpuddp.resilience.supervisor import (
    RestartSupervisor,
    SupervisorPolicy,
    classify_exit,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------- specs --
def test_jobspec_validation_matrix():
    ok = JobSpec(name="a", argv=("python", "x.py"))
    assert ok.min_world == ok.max_world == 1
    with pytest.raises(FleetAdmissionError) as e:
        JobSpec(name="bad/name", argv=("x",))
    assert e.value.reason == "bad_spec"
    with pytest.raises(FleetAdmissionError):
        JobSpec(name="a", argv=("x",), kind="batch")
    with pytest.raises(FleetAdmissionError):
        JobSpec(name="a", argv=())
    with pytest.raises(FleetAdmissionError):
        JobSpec(name="a", argv=("x",), min_world=0)
    with pytest.raises(FleetAdmissionError):
        JobSpec(name="a", argv=("x",), min_world=4, max_world=2)
    with pytest.raises(FleetAdmissionError):
        JobSpec(name="a", argv=("x",), max_restarts=-1)


def test_jobspec_run_dir_substitution():
    spec = JobSpec(
        name="a",
        argv=("python", "w.py", "{run_dir}", "3"),
        env={"OUT": "{run_dir}/sub", "K": "v"},
    )
    assert spec.resolved_argv("/tmp/j/a") == ["python", "w.py", "/tmp/j/a", "3"]
    assert spec.resolved_env("/tmp/j/a") == {"OUT": "/tmp/j/a/sub", "K": "v"}


def test_jobspec_initial_desired_by_kind():
    t = JobSpec(name="t", argv=("x",), kind="training", min_world=1, max_world=4)
    s = JobSpec(name="s", argv=("x",), kind="serving", min_world=1, max_world=4)
    assert t.initial_desired() == 4  # training soaks spare capacity
    assert s.initial_desired() == 1  # serving earns replicas from SLO pressure


def test_spec_from_dict_refuses_unknown_keys():
    with pytest.raises(FleetAdmissionError) as e:
        spec_from_dict({"name": "a", "argv": ["x"], "wat": 1})
    assert "wat" in str(e.value)
    with pytest.raises(FleetAdmissionError):
        spec_from_dict({"name": "a", "argv": "not-a-list"})
    spec = spec_from_dict(
        {"name": "a", "argv": ["x"], "priority": 3, "kind": "serving"}
    )
    assert spec.priority == 3 and spec.kind == "serving"


def test_spec_env_none_normalizes_and_non_mapping_refused():
    """A YAML `env:` key with no value parses to None — that is an empty
    mapping, not a start-time AttributeError inside the controller tick;
    a non-mapping env is refused AT ADMISSION (bad_spec)."""
    spec = spec_from_dict(
        {"name": "a", "argv": ["x"], "env": None, "first_attempt_env": None}
    )
    assert spec.env == {} and spec.first_attempt_env == {}
    assert spec.resolved_env("/tmp/a") == {}
    with pytest.raises(FleetAdmissionError) as e:
        JobSpec(name="a", argv=("x",), env=["not", "a", "mapping"])
    assert e.value.reason == "bad_spec"
    with pytest.raises(FleetAdmissionError):
        spec_from_dict({"name": "a", "argv": ["x"], "first_attempt_env": "x=1"})


# ----------------------------------------------------------------- planner --
def V(name, **kw):
    return JobView(name=name, **kw)


def test_plan_is_deterministic_and_input_order_free():
    jobs = [
        V("a", priority=1, arrival=0, min_world=1, max_world=4),
        V("b", priority=1, arrival=1, min_world=2, max_world=2),
        V("c", priority=5, arrival=2, min_world=1, max_world=8),
    ]
    p1 = plan_fleet(8, jobs)
    p2 = plan_fleet(8, list(reversed(jobs)))
    assert p1 == p2
    # priority first, then arrival: c gets its growth headroom first
    assert [p.name for p in p1.placements] == ["c", "a", "b"]
    assert p1.alloc == {"c": 5, "a": 1, "b": 2}
    assert p1.free == 0


def test_plan_gang_admission_is_all_or_nothing_with_backfill():
    jobs = [
        V("big", priority=10, arrival=0, min_world=6, max_world=6),
        V("small", priority=1, arrival=1, min_world=2, max_world=2),
    ]
    plan = plan_fleet(4, jobs)
    # big cannot gang-place at 6 on a 4-pool; small backfills behind it
    assert plan.alloc == {"small": 2}
    assert plan.action("big") == "queued"
    assert plan.free == 2


def test_plan_priority_preempts_running_lower_priority():
    jobs = [
        V("low", priority=1, arrival=0, min_world=3, max_world=4,
          running=True, current_world=4),
        V("high", priority=9, arrival=1, min_world=3, max_world=3),
    ]
    plan = plan_fleet(4, jobs)
    assert plan.alloc == {"high": 3}
    assert plan.action("low") == "preempt"
    assert plan.action("high") == "start"


def test_plan_resize_actions_on_membership_change():
    # a finishes -> b grows back toward desired
    before = plan_fleet(4, [
        V("a", priority=9, arrival=1, min_world=2, max_world=2,
          running=True, current_world=2),
        V("b", priority=1, arrival=0, min_world=1, max_world=4,
          running=True, current_world=2),
    ])
    assert before.alloc == {"a": 2, "b": 2}
    after = plan_fleet(4, [
        V("b", priority=1, arrival=0, min_world=1, max_world=4,
          running=True, current_world=2),
    ])
    assert after.alloc == {"b": 4}
    assert after.action("b") == "resize"


def test_plan_desired_is_clamped_to_spec_bounds():
    jobs = [V("a", min_world=2, max_world=4, desired=99)]
    assert plan_fleet(16, jobs).alloc == {"a": 4}
    jobs = [V("a", min_world=2, max_world=4, desired=1)]
    assert plan_fleet(16, jobs).alloc == {"a": 2}
    jobs = [V("a", min_world=2, max_world=4, desired=3)]
    assert plan_fleet(16, jobs).alloc == {"a": 3}


def test_plan_slices_are_disjoint_and_packed():
    jobs = [
        V("a", priority=2, arrival=0, min_world=2, max_world=2),
        V("b", priority=1, arrival=1, min_world=3, max_world=3),
        V("c", priority=3, arrival=2, min_world=1, max_world=1),
    ]
    plan = plan_fleet(8, jobs)
    slices = plan.slices
    assert slices == {"c": (0, 1), "a": (1, 3), "b": (3, 6)}
    spans = sorted(slices.values())
    for (s0, e0), (s1, e1) in zip(spans, spans[1:]):
        assert e0 <= s1  # disjoint
    assert all(0 <= s < e <= 8 for s, e in spans)


def test_plan_rejects_bad_inputs():
    with pytest.raises(ValueError):
        plan_fleet(0, [])
    with pytest.raises(ValueError):
        plan_fleet(4, [V("a"), V("a")])


def test_plan_keep_action_when_nothing_changes():
    jobs = [V("a", min_world=2, max_world=2, running=True, current_world=2)]
    plan = plan_fleet(4, jobs)
    assert plan.action("a") == "keep"


# -------------------------------------------------------------- autoscaler --
def OBS(p99=None, occ=None, stragglers=None, shed=None, cursor=0):
    return {
        "p99_ms": p99,
        "occupancy": occ,
        "straggler_events": stragglers,
        "shed_total": shed,
        "fresh_cursor": cursor,
    }


def test_autoscale_policy_validation():
    with pytest.raises(ValueError):
        AutoscalePolicy(hysteresis=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(cooldown_s=-1)
    with pytest.raises(ValueError):
        AutoscalePolicy(scale_down_below=1.0)
    with pytest.raises(ValueError):
        AutoscalePolicy(shrink_factor=1)
    with pytest.raises(ValueError):
        AutoscalePolicy(shed_high=0)


def test_autoscale_serving_scale_up_needs_hysteresis_of_fresh_windows():
    a = Autoscaler(AutoscalePolicy(slo_p99_ms=100.0, hysteresis=2,
                                   cooldown_s=0.0))
    # breach 1 (fresh): no action yet
    assert a.propose("s", "serving", 1, 1, 4, OBS(p99=500, cursor=1), 0.0) is None
    # same cursor re-scraped: STALE — must not extend the streak
    assert a.propose("s", "serving", 1, 1, 4, OBS(p99=500, cursor=1), 1.0) is None
    assert a.propose("s", "serving", 1, 1, 4, OBS(p99=500, cursor=1), 2.0) is None
    # breach 2 (fresh): act
    assert a.propose("s", "serving", 1, 1, 4, OBS(p99=500, cursor=2), 3.0) == 2
    assert a.actions[-1]["action"] == "scale_up"


def test_autoscale_cooldown_bounds_one_action_per_window():
    a = Autoscaler(AutoscalePolicy(slo_p99_ms=100.0, hysteresis=1,
                                   cooldown_s=30.0))
    assert a.propose("s", "serving", 1, 1, 4, OBS(p99=500, cursor=1), 0.0) == 2
    # still breached on fresh windows, but inside the cooldown
    assert a.propose("s", "serving", 2, 1, 4, OBS(p99=500, cursor=2), 10.0) is None
    assert a.propose("s", "serving", 2, 1, 4, OBS(p99=500, cursor=3), 29.0) is None
    # cooldown over (and the streak rebuilt post-action)
    assert a.propose("s", "serving", 2, 1, 4, OBS(p99=500, cursor=4), 31.0) == 3


def test_autoscale_serving_scale_down_when_far_under_slo():
    a = Autoscaler(AutoscalePolicy(slo_p99_ms=100.0, scale_down_below=0.25,
                                   hysteresis=2, cooldown_s=0.0))
    assert a.propose("s", "serving", 3, 1, 4, OBS(p99=10, cursor=1), 0.0) is None
    assert a.propose("s", "serving", 3, 1, 4, OBS(p99=10, cursor=2), 1.0) == 2
    assert a.actions[-1]["action"] == "scale_down"
    # at min_world: never below
    a2 = Autoscaler(AutoscalePolicy(slo_p99_ms=100.0, hysteresis=1,
                                    cooldown_s=0.0))
    assert a2.propose("s", "serving", 1, 1, 4, OBS(p99=1, cursor=1), 0.0) is None


def test_autoscale_clamps_at_max_world():
    a = Autoscaler(AutoscalePolicy(slo_p99_ms=100.0, hysteresis=1,
                                   cooldown_s=0.0))
    assert a.propose("s", "serving", 4, 1, 4, OBS(p99=500, cursor=1), 0.0) is None


def test_autoscale_occupancy_breach_also_scales_up():
    a = Autoscaler(AutoscalePolicy(occupancy_high=0.9, hysteresis=1,
                                   cooldown_s=0.0))
    assert a.propose("s", "serving", 1, 1, 4, OBS(occ=0.97, cursor=1), 0.0) == 2


def test_autoscale_shed_rate_breach_scales_up_with_hysteresis():
    """The survivability rule (schema v7): >= shed_high NEWLY shed requests
    per fresh window is overload evidence — sustained for the hysteresis,
    it scales serving up even with p99/occupancy silent."""
    a = Autoscaler(AutoscalePolicy(shed_high=2, hysteresis=2, cooldown_s=0.0))
    # first observation is the baseline counter — never a breach, whatever
    # the cumulative total already is
    assert a.propose("s", "serving", 1, 1, 4, OBS(shed=10, cursor=1), 0.0) is None
    # +3 shed in a fresh window: breach 1 of 2
    assert a.propose("s", "serving", 1, 1, 4, OBS(shed=13, cursor=2), 1.0) is None
    # +3 again: hysteresis met -> scale up
    assert a.propose("s", "serving", 1, 1, 4, OBS(shed=16, cursor=3), 2.0) == 2
    assert a.actions[-1]["action"] == "scale_up"
    assert "shed" in a.actions[-1]["why"]


def test_autoscale_shed_stale_window_is_not_evidence():
    """A re-scraped window (cursor unmoved) must not extend the shed streak
    — and the baseline only advances on FRESH windows, so the deferred
    delta still convicts once the engine makes progress."""
    a = Autoscaler(AutoscalePolicy(shed_high=2, hysteresis=1, cooldown_s=0.0))
    assert a.propose("s", "serving", 1, 1, 4, OBS(shed=10, cursor=1), 0.0) is None
    # shed_total climbed but the window is STALE: no action, baseline held
    assert a.propose("s", "serving", 1, 1, 4, OBS(shed=20, cursor=1), 1.0) is None
    # the same total on a fresh window: delta +10 vs the held baseline
    assert a.propose("s", "serving", 1, 1, 4, OBS(shed=20, cursor=2), 2.0) == 2


def test_autoscale_shed_below_threshold_never_acts():
    a = Autoscaler(AutoscalePolicy(shed_high=5, hysteresis=1, cooldown_s=0.0))
    assert a.propose("s", "serving", 1, 1, 4, OBS(shed=0, cursor=1), 0.0) is None
    for i in range(2, 6):  # +1 shed per window, under the threshold
        assert a.propose(
            "s", "serving", 1, 1, 4, OBS(shed=i - 1, cursor=i), float(i)
        ) is None
    assert a.actions == []


def test_autoscale_shed_rule_disabled_without_knob():
    # shed evidence flows through the observation, but shed_high=None
    # (the default) never arms the rule
    a = Autoscaler(AutoscalePolicy(slo_p99_ms=100.0, hysteresis=1,
                                   cooldown_s=0.0))
    assert a.propose("s", "serving", 1, 1, 4, OBS(p99=5, shed=0, cursor=1), 0.0) is None
    assert a.propose("s", "serving", 1, 1, 4,
                     OBS(p99=5, shed=1000, cursor=2), 1.0) is None
    assert a.actions == []


def test_autoscale_training_shrinks_on_new_straggler_conviction():
    a = Autoscaler(AutoscalePolicy(cooldown_s=0.0, shrink_factor=2))
    # first observation establishes the baseline counter — no action
    assert a.propose("t", "training", 4, 1, 4, OBS(stragglers=0, cursor=1), 0.0) is None
    # counter unchanged: no conviction
    assert a.propose("t", "training", 4, 1, 4, OBS(stragglers=0, cursor=2), 1.0) is None
    # a NEW conviction shrinks by the factor
    assert a.propose("t", "training", 4, 1, 4, OBS(stragglers=1, cursor=3), 2.0) == 2
    assert a.actions[-1]["action"] == "shrink"
    # already at min: convicted again, but nowhere to go
    assert a.propose("t", "training", 1, 1, 4, OBS(stragglers=2, cursor=4), 3.0) is None


def test_autoscale_straggler_conviction_survives_cooldown():
    """A conviction landing INSIDE the cooldown is evidence deferred, not
    evidence destroyed: the shrink fires once the cooldown ends."""
    a = Autoscaler(AutoscalePolicy(cooldown_s=30.0, shrink_factor=2))
    assert a.propose("t", "training", 4, 1, 4, OBS(stragglers=0, cursor=1), 0.0) is None
    a._last_action["t"] = 1.0  # a prior action opened the cooldown window
    assert a.propose("t", "training", 4, 1, 4, OBS(stragglers=1, cursor=2), 5.0) is None
    # same counter, cooldown over: the pending conviction still shrinks
    assert a.propose("t", "training", 4, 1, 4, OBS(stragglers=1, cursor=3), 32.0) == 2


def test_autoscale_dead_endpoint_is_no_evidence():
    a = Autoscaler(AutoscalePolicy(slo_p99_ms=100.0, hysteresis=1,
                                   cooldown_s=0.0))
    assert a.propose("s", "serving", 1, 1, 4, None, 0.0) is None
    assert a.actions == []


def test_autoscale_scraper_is_injectable_end_to_end():
    feed = [OBS(p99=900, cursor=1), OBS(p99=900, cursor=2)]
    a = Autoscaler(
        AutoscalePolicy(slo_p99_ms=100.0, hysteresis=2, cooldown_s=0.0),
        scraper=lambda run_dir: feed.pop(0),
    )
    assert a.observe_and_propose("s", "serving", "/x", 1, 1, 4, 0.0) is None
    assert a.observe_and_propose("s", "serving", "/x", 1, 1, 4, 1.0) == 2


# ------------------------------------------------------- prometheus parsing --
def test_parse_prometheus_families_and_labels():
    text = "\n".join([
        "# HELP tpuddp_serving_e2e_ms last-window end-to-end latency",
        "# TYPE tpuddp_serving_e2e_ms summary",
        'tpuddp_serving_e2e_ms{quantile="0.5"} 3.25',
        'tpuddp_serving_e2e_ms{quantile="0.99"} 17.5',
        "tpuddp_serving_completed_total 128",
        'tpuddp_serving_tenant_completed_total{tenant="a\\"b"} 7',
        "garbage line that is not a sample",
        "tpuddp_bad_value nan_is_not_here_but_text_is_skipped x",
    ])
    fam = parse_prometheus(text)
    assert metric_value(fam, "tpuddp_serving_e2e_ms", quantile="0.99") == 17.5
    assert metric_value(fam, "tpuddp_serving_completed_total") == 128
    assert metric_value(
        fam, "tpuddp_serving_tenant_completed_total", tenant='a"b'
    ) == 7
    assert metric_value(fam, "tpuddp_serving_e2e_ms", quantile="0.75") is None
    assert metric_value(fam, "tpuddp_absent_total") is None


# ------------------------------------------- stale exporter.port discovery --
def test_read_live_port_rejects_dead_port_file(tmp_path):
    """Satellite regression (ISSUE 11): a SIGKILLed run leaves exporter.port
    behind — readers must treat a port as live ONLY after /healthz answers,
    within a short timeout."""
    # bind-then-close: a real port that is guaranteed dead
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    (tmp_path / "exporter.port").write_text(f"{dead_port}\n")
    t0 = time.monotonic()
    assert read_live_port(str(tmp_path), probe_timeout=0.5) is None
    assert time.monotonic() - t0 < 5.0  # a short probe, not a hang


def test_read_live_port_missing_or_garbled_file(tmp_path):
    assert read_live_port(str(tmp_path)) is None
    (tmp_path / "exporter.port").write_text("not-a-port\n")
    assert read_live_port(str(tmp_path)) is None


def test_read_live_port_accepts_live_exporter(tmp_path):
    exporter = MetricsExporter(port=0, run_dir=str(tmp_path)).start()
    try:
        assert read_live_port(str(tmp_path), probe_timeout=2.0) == exporter.port
    finally:
        exporter.stop()


def test_read_live_port_probes_recorded_host_line(tmp_path):
    """The port file's line 2 names the BOUND host (legacy single-line files
    fall back to loopback, as do bind-all hosts): a non-loopback-bound
    exporter must be probed where it actually lives, not assumed dead."""
    exporter = MetricsExporter(port=0, run_dir=str(tmp_path)).start()
    try:
        port_file = tmp_path / "exporter.port"
        lines = port_file.read_text().splitlines()
        assert lines == [str(exporter.port), exporter.host]
        # legacy single-line file: loopback fallback still finds the server
        port_file.write_text(f"{exporter.port}\n")
        assert read_live_port(str(tmp_path), probe_timeout=2.0) == exporter.port
        # bind-all recorded host maps onto loopback for the probe
        port_file.write_text(f"{exporter.port}\n0.0.0.0\n")
        assert read_live_port(str(tmp_path), probe_timeout=2.0) == exporter.port
        # an explicit host override wins over the recorded line
        port_file.write_text(f"{exporter.port}\n127.0.0.1\n")
        assert (
            read_live_port(str(tmp_path), host="127.0.0.1", probe_timeout=2.0)
            == exporter.port
        )
    finally:
        exporter.stop()


def test_exporter_start_removes_stale_port_file_before_binding(tmp_path):
    """The writer half of the hardening: a leftover port file is cleared at
    start (pre-bind) and replaced by the LIVE port after bind."""
    stale = tmp_path / "exporter.port"
    stale.write_text("59999\n")
    exporter = MetricsExporter(port=0, run_dir=str(tmp_path))
    exporter.start()
    try:
        assert int(stale.read_text().splitlines()[0]) == exporter.port != 59999
    finally:
        exporter.stop()
    assert not stale.exists()


# ----------------------------------------------- supervisor fleet extensions --
def test_classify_exit_names_signals_and_contract_codes():
    assert classify_exit(-9) == "killed by SIGKILL"
    assert classify_exit(-15) == "killed by SIGTERM"
    assert classify_exit(75) == "preemption drain"
    assert classify_exit(76) == "stale peer"
    assert classify_exit(77) == "replica desync"
    assert classify_exit(1) == "crash"
    assert "signal" in classify_exit(-250)  # out-of-range signum still labels


def test_supervisor_request_stop_prevents_restart():
    calls = []

    def runner(argv, env):
        calls.append(dict(env))
        sup.request_stop()  # the controller preempts mid-flight
        return 75

    sup = RestartSupervisor(
        ["x"], runner=runner, sleep=lambda s: None,
        policy=SupervisorPolicy(backoff_base=0.01, backoff_cap=0.02),
    )
    assert sup.run() == 75  # surfaced, never relaunched
    assert len(calls) == 1


def test_supervisor_stop_before_first_launch_never_spawns():
    """A preemption landing before the FIRST child spawns must not run the
    job even once — preempted work holds no pool capacity."""
    calls = []
    sup = RestartSupervisor(
        ["x"], runner=lambda argv, env: calls.append(1) or 0,
    )
    sup.request_stop()
    assert sup.run() == 0
    assert calls == []


def test_supervisor_world_env_var_override_for_serving():
    calls = []

    def runner(argv, env):
        calls.append(dict(env))
        return 0

    sup = RestartSupervisor(
        ["x"], runner=runner, world_size=3,
        world_env_var="TPUDDP_SERVING_REPLICAS",
    )
    assert sup.run() == 0
    assert calls[0]["TPUDDP_SERVING_REPLICAS"] == "3"
    assert "TPUDDP_WORLD_SIZE" not in calls[0] or not os.environ.get(
        "TPUDDP_WORLD_SIZE"
    )


def test_supervisor_set_world_retargets_next_attempt():
    calls = []

    def runner(argv, env):
        calls.append(env.get("TPUDDP_WORLD_SIZE"))
        if len(calls) == 1:
            sup.set_world(2)  # the fleet rebalance lever
            return 75  # drain: relaunch immediately at the new world
        return 0

    sup = RestartSupervisor(["x"], runner=runner, world_size=4,
                            sleep=lambda s: None)
    assert sup.run() == 0
    assert calls == ["4", "2"]


def test_supervisor_popen_runner_exposes_live_child(tmp_path):
    sup = RestartSupervisor(
        [sys.executable, "-c", "import time; time.sleep(30)"],
        policy=SupervisorPolicy(max_restarts=0),
    )
    import threading

    t = threading.Thread(target=sup.run, daemon=True)
    t.start()
    deadline = time.monotonic() + 30
    while sup.child is None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert sup.child is not None
    sup.request_stop()
    assert sup.signal_child(signal.SIGKILL)
    t.join(timeout=30)
    assert not t.is_alive()
    assert sup.history[-1][1] == -signal.SIGKILL


# -------------------------------------------------------------- controller --
def _trivial_spec(name, seconds=0.0, rc=0, **kw):
    code = f"import time; time.sleep({seconds}); raise SystemExit({rc})"
    return JobSpec(name=name, argv=(sys.executable, "-c", code), **kw)


def test_controller_admission_bounds(tmp_path):
    c = FleetController(2, fleet_dir=str(tmp_path), max_jobs=1)
    c.submit(_trivial_spec("a"))
    with pytest.raises(FleetAdmissionError) as e:
        c.submit(_trivial_spec("a"))
    assert e.value.reason == "duplicate_name"
    with pytest.raises(FleetAdmissionError) as e:
        c.submit(_trivial_spec("b"))
    assert e.value.reason == "fleet_full"
    with pytest.raises(FleetAdmissionError) as e:
        FleetController(2, fleet_dir=str(tmp_path)).submit(
            _trivial_spec("c", min_world=3, max_world=3)
        )
    assert e.value.reason == "bad_spec"


def test_controller_runs_trivial_jobs_to_done_with_namespaced_dirs(tmp_path):
    c = FleetController(2, fleet_dir=str(tmp_path))
    c.submit(_trivial_spec("a"))
    c.submit(_trivial_spec("b"))
    assert c.run_until(lambda ctl: ctl.training_complete(), poll=0.05,
                       timeout=60)
    status = {s["name"]: s for s in c.status()}
    assert status["a"]["state"] == "done"
    assert status["b"]["state"] == "done"
    assert status["a"]["run_dir"] == os.path.join(str(tmp_path), "jobs", "a")
    assert os.path.isdir(status["a"]["run_dir"])
    assert status["a"]["run_dir"] != status["b"]["run_dir"]


def test_controller_failed_job_reports_rc(tmp_path):
    c = FleetController(
        1, fleet_dir=str(tmp_path),
        supervisor_policy=SupervisorPolicy(backoff_base=0.01,
                                           backoff_cap=0.02),
    )
    c.submit(_trivial_spec("bad", rc=3, max_restarts=1))
    assert c.run_until(lambda ctl: ctl.training_complete(), poll=0.05,
                       timeout=60)
    s = c.status()[0]
    assert s["state"] == "failed" and s["exit_code"] == 3


def test_controller_stop_queued_job_without_spawn(tmp_path):
    c = FleetController(1, fleet_dir=str(tmp_path))
    c.submit(_trivial_spec("big", seconds=30.0))
    c.submit(_trivial_spec("waiting"))
    c.step()
    assert c.jobs["waiting"].state == "queued"  # gang-blocked behind big
    c.stop_job("waiting")
    assert c.jobs["waiting"].state == "preempted"
    c.stop_job("big")
    c.shutdown(timeout=60)
    assert c.jobs["big"].state == "preempted"


class _StubChild:
    """A 'live' Popen stand-in: poll() None until signalled/released."""

    def __init__(self):
        self.alive = True
        self.pid = -1

    def poll(self):
        return None if self.alive else 0

    def send_signal(self, sig):
        self.alive = False  # drains instantly

    def kill(self):
        self.alive = False


class _StubSupervisor:
    """Just the surface the controller's capacity/resize/drain machinery
    reads: the launched world (current_world), the retargeted next world
    (world_size), the live child, and the set_world/request_stop levers."""

    def __init__(self, current, target, child_alive=True):
        self._current_world = current
        self.world_size = target
        self.child = _StubChild() if child_alive else None
        self.set_world_calls = []
        self.stop_requested = False

    @property
    def current_world(self):
        return self._current_world

    def set_world(self, world):
        self.set_world_calls.append(world)
        self.world_size = world

    def request_stop(self):
        self.stop_requested = True


def test_controller_defers_start_while_drain_holds_devices(tmp_path):
    """Oversubscription regression: the plan's capacity math assumes a
    resize has LANDED, but the draining child still holds its launched
    world — a new gang must not start until the pool can really seat it."""
    c = FleetController(3, fleet_dir=str(tmp_path))
    # job-a was launched at 3 and is mid-drain down to 1: its child still
    # holds all 3 devices even though the supervisor is retargeted
    a = c.submit(_trivial_spec("a"))
    a.state = "running"
    a.supervisor = _StubSupervisor(current=3, target=1, child_alive=True)
    new = c.submit(_trivial_spec("new", min_world=2, max_world=2))
    c.step()
    assert c.last_plan.action("new") == "start"  # the PLAN seats it...
    assert new.state == "queued" and new.supervisor is None  # ...we defer
    # the drain lands: job-a's child exits, its supervisor holds world 1
    a.supervisor.child = None
    c.step()
    assert new.state == "running" and new.supervisor is not None
    c.shutdown(timeout=60)


def test_controller_defers_grow_while_drain_holds_devices(tmp_path):
    """Same invariant for a GROW resize: the grown job relaunches the
    moment its own (fast) drain lands — a neighbor's unfinished shrink must
    complete before the extra devices are claimed."""
    c = FleetController(4, fleet_dir=str(tmp_path))
    x = c.submit(_trivial_spec("x", min_world=2, max_world=3, priority=1))
    x.state = "running"
    x.supervisor = _StubSupervisor(current=2, target=2, child_alive=True)
    y = c.submit(_trivial_spec("y", min_world=1, max_world=2))
    y.state = "running"
    y.supervisor = _StubSupervisor(current=2, target=2, child_alive=True)
    y.desired = 1  # the autoscaler shrank y; x grows into the freed device
    c.step()
    assert c.last_plan.alloc == {"x": 3, "y": 1}
    assert y.supervisor.set_world_calls == [1]  # shrink proceeds
    assert x.supervisor.set_world_calls == []  # grow deferred: y holds 2
    y.supervisor.child = None  # y's drain lands (relaunches at 1)
    c.step()
    assert x.supervisor.set_world_calls == [3]


def test_controller_shutdown_cancels_queued_jobs(tmp_path):
    """shutdown() must not gang-place NEW work into the capacity its own
    preemptions free: queued jobs are cancelled, not started."""
    c = FleetController(1, fleet_dir=str(tmp_path))
    c.submit(_trivial_spec("long", seconds=30.0))
    c.step()
    waiting = c.submit(_trivial_spec("waiting"))
    c.shutdown(timeout=60)
    assert waiting.state == "preempted"
    assert waiting.supervisor is None  # never spawned
    assert c.jobs["long"].state == "preempted"


def test_escalate_drain_sigkills_only_after_grace(tmp_path):
    """Satellite (ISSUE 11): a child that ignores SIGTERM is SIGKILLed only
    after the grace window — never SIGKILL-first."""
    proc = subprocess.Popen(
        [
            sys.executable, "-u",
            os.path.join(REPO, "tests", "_chaos_wedge_worker.py"),
            str(tmp_path), "ignore-sigterm",
        ],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        assert "armed" in proc.stdout.readline()
        t0 = time.monotonic()
        rc = escalate_drain(proc, grace=1.5, poll=0.05)
        elapsed = time.monotonic() - t0
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()
    assert rc == -signal.SIGKILL
    assert elapsed >= 1.5  # the drain window was honored before escalation
    assert classify_exit(rc) == "killed by SIGKILL"


def test_escalate_drain_returns_clean_drain_rc(tmp_path):
    proc = subprocess.Popen(
        [
            sys.executable, "-c",
            "import signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: sys.exit(75))\n"
            "print('up', flush=True)\n"
            "time.sleep(60)\n",
        ],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        assert proc.stdout.readline().strip() == "up"
        t0 = time.monotonic()
        rc = escalate_drain(proc, grace=30.0, poll=0.05)
        elapsed = time.monotonic() - t0
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()
    assert rc == 75
    assert elapsed < 25.0  # a draining child is never made to wait out grace


# ------------------------------------------------------------------ config --
def test_serving_config_honors_replica_env_override(monkeypatch):
    from tpuddp import config as config_lib

    monkeypatch.delenv("TPUDDP_SERVING_REPLICAS", raising=False)
    cfg = config_lib.serving_config({"serving": {"num_replicas": 1}})
    assert cfg["num_replicas"] == 1
    monkeypatch.setenv("TPUDDP_SERVING_REPLICAS", "3")
    cfg = config_lib.serving_config({"serving": {"num_replicas": 1}})
    assert cfg["num_replicas"] == 3


# -------------------------------------------------------------- bench_trend --
def test_bench_trend_empty_trajectory_exits_zero(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_trend
    finally:
        sys.path.pop(0)
    rc = bench_trend.main(["--repo", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "nothing to compare" in out


def test_bench_trend_fresh_without_rows_exits_zero(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_trend
    finally:
        sys.path.pop(0)
    committed = {
        "metric": "samples_per_sec_per_chip", "device": "cpu",
        "configs": {"toy": {"samples_per_sec_per_chip": 100.0}},
    }
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(committed))
    empty = tmp_path / "bench_results.json"
    empty.write_text(json.dumps({"metric": "x", "device": "cpu",
                                 "configs": {}}))
    rc = bench_trend.main(["--repo", str(tmp_path), "--fresh", str(empty)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "no candidate to judge" in out


def test_bench_trend_tracks_tokens_per_sec_rows(tmp_path, capsys):
    """Decode-flavored rows (tokens_per_sec, ISSUE 12) ride the trajectory
    and the regression gate instead of being silently dropped — and a row
    name shared with a request-rate artifact is judged per metric, never
    across them."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_trend
    finally:
        sys.path.pop(0)
    # a request-granularity serving artifact and two decode artifacts that
    # REUSE the row name "closed_loop" under the other rate metric
    (tmp_path / "SERVING_r01.json").write_text(json.dumps({
        "metric": "rps", "device": "cpu",
        "configs": {"closed_loop": {"samples_per_sec_per_chip": 5000.0}},
    }))
    (tmp_path / "SERVING_r02.json").write_text(json.dumps({
        "metric": "tps", "device": "cpu",
        "configs": {"closed_loop": {"tokens_per_sec": 1000.0}},
    }))
    rc = bench_trend.main(["--repo", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1,000t/s" in out  # the decode row is IN the trajectory
    # a decode regression against the decode best is caught...
    fresh = tmp_path / "bench_results.json"
    fresh.write_text(json.dumps({
        "metric": "tps", "device": "cpu",
        "configs": {"closed_loop": {"tokens_per_sec": 500.0}},
    }))
    rc = bench_trend.main(["--repo", str(tmp_path), "--fresh", str(fresh)])
    err = capsys.readouterr().err
    assert rc == 1
    assert "tokens/s" in err and "closed_loop" in err
    # ...but 1000 tokens/s is NOT judged against the 5000 samples/s row of
    # the same name (cross-metric comparison would flag a phantom 80% drop)
    fresh.write_text(json.dumps({
        "metric": "tps", "device": "cpu",
        "configs": {"closed_loop": {"tokens_per_sec": 1000.0}},
    }))
    assert bench_trend.main(
        ["--repo", str(tmp_path), "--fresh", str(fresh)]
    ) == 0
