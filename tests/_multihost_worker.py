"""Worker for the multi-process DP test (launched by test_multihost.py).

Each OS process = one "host" with 4 virtual CPU devices; jax.distributed
rendezvous glues them into one 8-device world. Exercises the full multi-host
path: global mesh over both processes' devices, per-process shard loading,
cross-process grad pmean, sync_global_devices barriers, process-0-only
logging/checkpointing, broadcast_one_to_all at init.

Usage: python _multihost_worker.py <proc_id> <nprocs> <coord_port> <out_dir>
"""

import json
import sys

proc_id, nprocs, port, out_dir = (
    int(sys.argv[1]),
    int(sys.argv[2]),
    sys.argv[3],
    sys.argv[4],
)

import jax  # noqa: E402

jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nprocs,
    process_id=proc_id,
)

import jax.numpy as jnp  # noqa: E402

from tpuddp import nn, optim  # noqa: E402
from tpuddp.data import ShardedDataLoader, SyntheticClassification  # noqa: E402
from tpuddp.models import ToyCNN  # noqa: E402
from tpuddp.parallel import make_mesh  # noqa: E402
from tpuddp.parallel.ddp import DistributedDataParallel  # noqa: E402
from tpuddp.training.loop import run_training_loop  # noqa: E402

devices = jax.devices("cpu")
assert len(devices) == 8, f"expected 8 global cpu devices, got {len(devices)}"
assert jax.process_count() == nprocs

mesh = make_mesh(devices)
ds = SyntheticClassification(n=128, shape=(8, 8, 3), seed=11)
train_loader = ShardedDataLoader(ds, 4, mesh, shuffle=True, seed=0)
test_loader = ShardedDataLoader(ds, 4, mesh, shuffle=True, seed=0)
local = train_loader.local_ranks
assert len(local) == 4, local

# weight_update_sharding=True: the moments are sharded ACROSS the two
# processes (no host holds the full vector), exercising the reduce-scatter/
# all-gather step collectives AND the cross-host gather inside the
# checkpoint writer (checkpoint.save_on_main)
ddp = DistributedDataParallel(
    ToyCNN(widths=(8,), sync_bn=True),
    optim.Adam(1e-2),
    nn.CrossEntropyLoss(),
    mesh=mesh,
    weight_update_sharding=True,
)
state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
assert not state.opt_state.m.is_fully_addressable  # truly cross-host sharded
state, history = run_training_loop(
    ddp, state, train_loader, test_loader, out_dir,
    num_epochs=2, checkpoint_epoch=1,
)

# --- custom-sampler order broadcast: a NON-deterministic user sampler drawn
# independently per process must not break cross-process shard disjointness —
# process 0's materialized order is broadcast to every process
# (tpuddp/data/loader.py _EpochMemoizedOrder) ---
import numpy as np  # noqa: E402


class _UnseededRandomOrder:
    """Deliberately different on every process: only the broadcast can make
    the shards globally consistent."""

    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __iter__(self):
        return iter(np.random.default_rng().permutation(self.n))


s_loader = ShardedDataLoader(ds, 4, mesh, sampler=_UnseededRandomOrder(len(ds)))
sampler_shards = [s.local_indices().tolist() for s in s_loader.samplers]
# set_epoch must invalidate the memo and re-broadcast a FRESH order (a stale
# cache would replay epoch 0's order; a broadcast mismatch would deadlock)
s_loader.set_epoch(1)
sampler_shards_ep1 = [s.local_indices().tolist() for s in s_loader.samplers]

# --- managed (Accelerator) path over the same multi-process mesh ---
from tpuddp.accelerate import Accelerator  # noqa: E402
from tpuddp.data import DataLoader  # noqa: E402
from tpuddp.models import ToyMLP  # noqa: E402

acc = Accelerator(mesh=mesh, seed=7)
m_model, m_opt, m_loader = acc.prepare(
    ToyMLP(hidden=(16,)), optim.Adam(1e-2), DataLoader(ds, batch_size=4)
)
criterion = nn.CrossEntropyLoss()
managed_losses = []
m_loader.set_epoch(0)
for i, (bx, by, bw) in enumerate(m_loader):
    loss = criterion(m_model(bx), by, bw)
    acc.backward(loss)
    m_opt.step()
    managed_losses.append(round(loss.item(), 6))
    if i == 2:
        break

print(
    "WORKER_RESULT "
    + json.dumps(
        {
            "proc": proc_id,
            "local_ranks": local,
            "train_loss": [round(h["train_loss"], 6) for h in history],
            "n": [h["train_samples"] for h in history],
            "managed_losses": managed_losses,
            "is_main": acc.is_main_process,
            "sampler_shards": sampler_shards,
            "sampler_shards_ep1": sampler_shards_ep1,
        }
    ),
    flush=True,
)
jax.distributed.shutdown()
