"""Pretrained-AlexNet fine-tune workflow, end to end (the reference's central
``alexnet(weights=DEFAULT)`` + head-swap move, data_and_toy_model.py:41-45):
a torch AlexNet checkpoint saved to disk is consumed via
``training.pretrained_path`` by the native entrypoint, head swapped 1000->10,
and the fine-tuned epoch-1 loss beats training from scratch."""

import re
from functools import partial

import jax
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from test_torch_import import torch_alexnet

from tpuddp.data.cifar10 import CIFAR10_MEAN, CIFAR10_STD
from tpuddp.data.synthetic import SyntheticClassification


def _small_uint8_datasets():
    """A small uint8 stand-in with the synthetic fallback's format."""
    full = SyntheticClassification(n=320, shape=(32, 32, 3), seed=0)
    full.images = np.clip(full.images * 40 + 128, 0, 255).astype(np.uint8)
    return full.split(64)


def _pretrain_torch(train_ds, steps=60, image_size=64):
    """Fit a 1000-class-head torch AlexNet on the same data distribution the
    fine-tune will see (stand-in for ImageNet pretraining)."""
    torch.manual_seed(0)
    model = torch_alexnet(num_classes=1000)
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    mean = torch.tensor(CIFAR10_MEAN).view(1, 3, 1, 1)
    std = torch.tensor(CIFAR10_STD).view(1, 3, 1, 1)
    rng = np.random.RandomState(0)
    for _ in range(steps):
        idx = rng.randint(0, len(train_ds), size=64)
        x = torch.from_numpy(
            train_ds.images[idx].astype(np.float32).transpose(0, 3, 1, 2) / 255.0
        )
        x = F.interpolate((x - mean) / std, size=image_size, mode="bilinear")
        y = torch.from_numpy(train_ds.labels[idx].astype(np.int64))
        opt.zero_grad()
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
    return model, float(loss.detach())


def _run_native(tmp_path, capsys, monkeypatch, datasets, training):
    import train_native
    from tpuddp.parallel import backend
    from tpuddp.parallel.spawn import run_ddp_training

    monkeypatch.setattr(
        train_native, "load_datasets_for", lambda *a, **k: datasets
    )
    backend.cleanup()
    run_ddp_training(
        partial(train_native.basic_ddp_training_loop, training=training),
        world_size=8,
        save_dir=str(tmp_path),
        optional_args={"set_epoch": True},
        backend="cpu",
    )
    backend.cleanup()
    out = capsys.readouterr().out
    m = re.search(r"Epoch 1/1, Train Loss: ([0-9.]+)", out)
    assert m, f"no epoch summary in output:\n{out[-2000:]}"
    return float(m.group(1)), out


@pytest.mark.slow
def test_pretrained_finetune_beats_scratch(tmp_path, capsys, monkeypatch):
    datasets = _small_uint8_datasets()
    donor, pre_loss = _pretrain_torch(datasets[0])
    assert pre_loss < 2.0, f"torch pretraining did not learn (loss {pre_loss})"
    ckpt = tmp_path / "alexnet_imagenet.pt"
    torch.save(donor.state_dict(), str(ckpt))

    training = {
        "model": "alexnet",
        "dataset": "cifar10",
        "data_root": "/nonexistent",
        "train_batch_size": 8,
        "test_batch_size": 8,
        "learning_rate": 0.001,
        "num_epochs": 1,
        "checkpoint_epoch": 5,
        "image_size": 64,
        "seed": 0,
        "mode": "shard_map",
        "prefetch": False,
    }
    scratch_loss, _ = _run_native(
        tmp_path / "scratch", capsys, monkeypatch, datasets, training
    )
    finetune_loss, out = _run_native(
        tmp_path / "finetune",
        capsys,
        monkeypatch,
        datasets,
        dict(training, pretrained_path=str(ckpt)),
    )
    assert "Loaded pretrained alexnet weights" in out
    assert finetune_loss < scratch_loss, (finetune_loss, scratch_loss)


def test_load_pretrained_swaps_head_and_keeps_features(tmp_path):
    """1000-class torch checkpoint -> 10-class tpuddp model: head is fresh
    (4096x10), features are the donor's (logit check on the donor head is in
    test_torch_import; here the converted conv weights must match)."""
    from tpuddp.models.torch_import import load_pretrained_alexnet

    torch.manual_seed(1)
    donor = torch_alexnet(num_classes=1000)
    path = tmp_path / "donor.pt"
    torch.save(donor.state_dict(), str(path))

    model, params, _ = load_pretrained_alexnet(
        str(path), jax.random.key(0), num_classes=10, image_size=64
    )
    assert params[-1]["weight"].shape == (4096, 10)
    conv0 = donor.state_dict()["features.0.weight"].numpy().transpose(2, 3, 1, 0)
    np.testing.assert_allclose(np.asarray(params[0]["weight"]), conv0, rtol=1e-6)


def test_pretrained_from_config_honors_num_classes(tmp_path):
    """training.num_classes (or the dataset-derived default) sizes the swapped
    head — a non-CIFAR config must not silently get a 10-class head."""
    from tpuddp.models.torch_import import pretrained_from_config

    torch.manual_seed(2)
    donor = torch_alexnet(num_classes=1000)
    path = tmp_path / "donor.pt"
    torch.save(donor.state_dict(), str(path))

    base = {"model": "alexnet", "pretrained_path": str(path),
            "image_size": 64, "seed": 0}
    _, params, _ = pretrained_from_config(dict(base, dataset="cifar10"))
    assert params[-1]["weight"].shape == (4096, 10)
    _, params, _ = pretrained_from_config(dict(base, num_classes=21))
    assert params[-1]["weight"].shape == (4096, 21)
    with pytest.raises(ValueError, match="num_classes"):
        pretrained_from_config(dict(base, dataset="imagenet21k"))
