"""Config system parity (SURVEY.md §2a #9): schema, provenance copy,
world-size derivation, reference-GPU-schema compatibility."""

import os

import pytest
import yaml

from tpuddp import config as cfg


def write_settings(tmp_path, data):
    p = tmp_path / "settings.yaml"
    p.write_text(yaml.dump(data))
    return str(p)


BASE = {
    "script_path": "train_native.py",
    "out_dir": None,  # filled per test
    "optional_args": {"set_epoch": True, "print_rand": False},
    "local": {"device": "tpu", "tpu": {"num_chips": 8}},
}


def test_load_and_prepare_out_dir_copies_settings(tmp_path):
    data = dict(BASE, out_dir=str(tmp_path / "out"))
    path = write_settings(tmp_path, data)
    settings = cfg.load_settings(path)
    out_dir = cfg.prepare_out_dir(settings, path)
    assert os.path.isdir(out_dir)
    copied = os.path.join(out_dir, "settings.yaml")
    assert os.path.exists(copied)  # provenance copy (reference :300-303)
    assert yaml.safe_load(open(copied))["script_path"] == "train_native.py"


def test_world_size_from_tpu_block(tmp_path):
    assert cfg.world_size_from(BASE) == 8


def test_world_size_from_reference_condor_schema():
    settings = {"local": {"device": "cuda", "condor": {"num_gpus": 2}}}
    assert cfg.world_size_from(settings) == 2
    assert cfg.device_from(settings) is None  # cuda maps onto the ladder


def test_world_size_absent_is_none():
    assert cfg.world_size_from({"local": {}}) is None


def test_world_size_env_override_wins(monkeypatch):
    """$TPUDDP_WORLD_SIZE (the restart supervisor's elastic shrink lever)
    beats the settings file on both entrypoints' resolution path."""
    monkeypatch.setenv("TPUDDP_WORLD_SIZE", "2")
    assert cfg.world_size_from(BASE) == 2
    assert cfg.world_size_from({"local": {}}) == 2
    monkeypatch.delenv("TPUDDP_WORLD_SIZE")
    assert cfg.world_size_from(BASE) == 8


def test_device_validation():
    assert cfg.device_from({"local": {"device": "cpu"}}) == "cpu"
    with pytest.raises(ValueError):
        cfg.device_from({"local": {"device": "mps"}})


def test_training_defaults_match_reference_constants():
    t = cfg.training_config({})
    # BASELINE.md workload constants
    assert t["train_batch_size"] == 128
    assert t["test_batch_size"] == 100
    assert t["learning_rate"] == 0.001
    assert t["num_epochs"] == 20
    assert t["checkpoint_epoch"] == 5
    assert t["image_size"] == 224


def test_training_overrides_merge():
    t = cfg.training_config({"training": {"model": "toy_mlp", "num_epochs": 2}})
    assert t["model"] == "toy_mlp"
    assert t["num_epochs"] == 2
    assert t["train_batch_size"] == 128  # default retained


def test_repo_example_settings_parse():
    settings = cfg.load_settings("local_settings.yaml")
    assert cfg.world_size_from(settings) == 8
    assert cfg.optional_args_from(settings) == {
        "set_epoch": True,
        "print_rand": False,
    }


def test_rendezvous_absent_is_empty():
    assert cfg.rendezvous_from({}) == {}
    assert cfg.rendezvous_from({"local": {}}) == {}


def test_rendezvous_block_parses():
    s = {"local": {"rendezvous": {
        "coordinator_address": "10.0.0.1:8476",
        "num_processes": 4,
        "process_id": 2,
    }}}
    assert cfg.rendezvous_from(s) == {
        "coordinator_address": "10.0.0.1:8476",
        "num_processes": 4,
        "process_id": 2,
    }


def test_rendezvous_env_overrides(monkeypatch):
    """One shared YAML across hosts: the launcher sets the per-host id in the
    environment (torchrun's RANK analog)."""
    s = {"local": {"rendezvous": {
        "coordinator_address": "10.0.0.1:8476", "num_processes": 2,
    }}}
    with pytest.raises(ValueError):  # num_processes>1 needs a process id
        cfg.rendezvous_from(s)
    monkeypatch.setenv("TPUDDP_PROCESS_ID", "1")
    assert cfg.rendezvous_from(s)["process_id"] == 1
    monkeypatch.setenv("TPUDDP_COORDINATOR", "10.0.0.9:9999")
    monkeypatch.setenv("TPUDDP_NUM_PROCESSES", "8")
    out = cfg.rendezvous_from({})
    assert out == {
        "coordinator_address": "10.0.0.9:9999",
        "num_processes": 8,
        "process_id": 1,
    }


def test_rendezvous_unknown_key_rejected():
    with pytest.raises(ValueError):
        cfg.rendezvous_from({"local": {"rendezvous": {"master_addr": "x"}}})


def test_num_classes_derived_from_dataset():
    assert cfg.num_classes_from({"dataset": "cifar10"}) == 10
    assert cfg.num_classes_from({"dataset": "digits"}) == 10
    assert cfg.num_classes_from({}) == 10  # default dataset is cifar10


def test_num_classes_explicit_overrides_dataset():
    assert cfg.num_classes_from({"dataset": "cifar10", "num_classes": 7}) == 7


def test_num_classes_unknown_dataset_requires_explicit():
    with pytest.raises(ValueError, match="num_classes"):
        cfg.num_classes_from({"dataset": "imagenet21k"})


def test_example_configs_parse_and_validate(monkeypatch):
    """Every YAML under configs/ must parse, produce a valid training config,
    and resolve rendezvous/world-size without error."""
    import glob

    for var in ("TPUDDP_COORDINATOR", "TPUDDP_NUM_PROCESSES", "TPUDDP_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sorted(glob.glob(os.path.join(repo, "configs", "*.yaml")))
    assert len(paths) >= 4
    for p in paths:
        settings = cfg.load_settings(p)
        training = cfg.training_config(settings)
        assert cfg.num_classes_from(training) == 10
        cfg.world_size_from(settings)
        cfg.device_from(settings)
        if "rendezvous" in settings.get("local", {}):
            monkeypatch.setenv("TPUDDP_PROCESS_ID", "0")
            rdv = cfg.rendezvous_from(settings)
            assert rdv["coordinator_address"]


def test_rendezvous_multiprocess_requires_coordinator_on_cpu(monkeypatch):
    for var in ("TPUDDP_COORDINATOR", "TPUDDP_NUM_PROCESSES", "TPUDDP_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    rdv = {"rendezvous": {"num_processes": 2, "process_id": 0}}
    # CPU dev rung has no auto-discovery: coordinator required
    with pytest.raises(ValueError, match="coordinator_address"):
        cfg.rendezvous_from({"local": dict(rdv, device="cpu")})
    # TPU pods auto-discover peers: no coordinator needed
    out = cfg.rendezvous_from({"local": dict(rdv, device="tpu")})
    assert out == {"num_processes": 2, "process_id": 0}
    # pod auto-discovery may omit process_id too
    out = cfg.rendezvous_from(
        {"local": {"device": "tpu", "rendezvous": {"num_processes": 2}}}
    )
    assert out == {"num_processes": 2}


def test_training_config_refuses_unknown_keys():
    """A typo'd training knob must fail loudly with a did-you-mean, not be
    silently ignored (which would train a different config than the file
    says)."""
    with pytest.raises(ValueError, match="wieght_update_sharding.*did you mean.*weight_update_sharding"):
        cfg.training_config({"training": {"wieght_update_sharding": True}})
    with pytest.raises(ValueError, match="unknown training key"):
        cfg.training_config({"training": {"zzz_not_a_knob": 1}})
    # every documented key still passes
    ok = cfg.training_config({"training": {"resume": True, "synthetic_n": [64, 32]}})
    assert ok["resume"] is True and ok["synthetic_n"] == [64, 32]


def test_serving_config_defaults_and_merge():
    out = cfg.serving_config({})
    assert out == cfg.SERVING_DEFAULTS
    out = cfg.serving_config(
        {"serving": {"model": "alexnet", "num_replicas": 4,
                     "per_tenant_quota": 8}}
    )
    assert out["model"] == "alexnet"
    assert out["num_replicas"] == 4
    assert out["per_tenant_quota"] == 8
    # untouched knobs keep their defaults
    assert out["max_batch_size"] == cfg.SERVING_DEFAULTS["max_batch_size"]


def test_serving_config_refuses_unknown_keys():
    """The serving block carries the same unknown-key-refusal contract as
    training.guard: a typo'd knob fails loudly with a did-you-mean."""
    with pytest.raises(ValueError, match="max_batch_szie.*did you mean.*max_batch_size"):
        cfg.serving_config({"serving": {"max_batch_szie": 16}})
    with pytest.raises(ValueError, match="unknown serving key"):
        cfg.serving_config({"serving": {"zzz_not_a_knob": 1}})


def test_decode_config_disarmed_by_default():
    serving = cfg.serving_config({})
    assert serving["decode"] is None
    assert cfg.decode_config(serving) is None
    assert cfg.decode_config({"decode": False}) is None


def test_decode_config_true_and_merge():
    assert cfg.decode_config({"decode": True}) == cfg.DECODE_DEFAULTS
    out = cfg.decode_config(
        {"decode": {"max_slots": 16, "stop_token": 3, "temperature": 0.7}}
    )
    assert out["max_slots"] == 16 and out["stop_token"] == 3
    assert out["temperature"] == 0.7
    # untouched knobs keep their defaults
    assert out["kv_block_size"] == cfg.DECODE_DEFAULTS["kv_block_size"]
    # the serving loader carries the block through intact
    serving = cfg.serving_config({"serving": {"decode": {"max_slots": 2}}})
    assert cfg.decode_config(serving)["max_slots"] == 2


def test_decode_config_refuses_unknown_keys_and_bad_type():
    """serving.decode rides the same unknown-key-refusal contract as every
    other block: a typo'd knob fails loudly with a did-you-mean."""
    with pytest.raises(ValueError, match="max_slot.*did you mean.*max_slots"):
        cfg.decode_config({"decode": {"max_slot": 4}})
    with pytest.raises(ValueError, match="unknown serving.decode key"):
        cfg.decode_config({"decode": {"zzz_not_a_knob": 1}})
    with pytest.raises(ValueError, match="mapping or bool"):
        cfg.decode_config({"decode": 7})
