"""2-D ``("data", "model")`` mesh + tensor parallelism (ISSUE 14).

Covers the tentpole contracts:

- spec application over EVERY ``param_logical_axes`` entry (the rule-table
  matrix);
- TP=2 forward/backward against a single-device reference (params gathered,
  logits compared — the row-split contractions change only each matmul's
  summation order, so the comparison is tight-tolerance; the vocab-split
  embedding lookup and logit gather are exact by construction);
- ``model=1`` lowering to HLO byte-identical with today's flat DDP path;
- comm-hook byte accounting on the data axis only, with the error-feedback
  residual keyed by ``(data_index, model_index)``;
- guard: no false positive on TP shards (they legitimately differ across
  the model axis), a genuine data-axis divergence still convicts, and the
  non-finite firewall skip stays a bitwise no-op;
- checkpoint round trip at TP=2 + the typed cross-``model``-width refusal
  (including the v2-record regression: a pre-v3 file written on a 2-D mesh
  must refuse, not mis-slice);
- the config surface: ``parallel`` block unknown-key refusal, ``mesh_from``
  tiling/hierarchical refusals.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuddp import config as cfg_lib
from tpuddp import nn, optim
from tpuddp.models import load_model
from tpuddp.models import transformer as tf_lib
from tpuddp.nn.core import Context
from tpuddp.parallel import comm as comm_lib
from tpuddp.parallel import tensor as tp_lib
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.parallel.mesh import DATA_AXIS, data_mesh
from tpuddp.parallel.mesh2d import (
    AXIS_ROLES,
    MODEL_AXIS,
    data_size,
    describe,
    mesh2d,
    model_size,
    squeeze_model,
)
from tpuddp.resilience import guard as guard_lib
from tpuddp.training import checkpoint as ckpt

KEY = jax.random.PRNGKey(0)
V, T, B = 64, 16, 8


def make_tp(devices, data=2, model=2, **kw):
    m = load_model("transformer_tiny", num_classes=V, max_seq_len=32)
    ddp = DistributedDataParallel(
        m, optim.Adam(lr=1e-2), nn.CrossEntropyLoss(),
        mesh=mesh2d(data, model, devices=devices[: data * model]), **kw,
    )
    return ddp, m


def token_batch(seed=0, b=B):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, V, (b, T)).astype(np.int32),
        rng.integers(0, V, (b, T)).astype(np.int32),
        np.ones((b, T), np.float32),
    )


def leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


# ------------------------------------------------------------- mesh factory --


def test_mesh2d_axes_and_order(cpu_devices):
    mesh = mesh2d(2, 2, devices=cpu_devices[:4])
    assert mesh.axis_names == (DATA_AXIS, MODEL_AXIS)
    assert mesh.devices.shape == (2, 2)
    # model minor: one TP group = adjacent devices
    assert list(mesh.devices[0]) == list(cpu_devices[:2])
    assert model_size(mesh) == 2 and data_size(mesh) == 2
    assert describe(mesh) == {"data": 2, "model": 2}


def test_mesh2d_device_count_must_tile(cpu_devices):
    with pytest.raises(ValueError, match="exactly"):
        mesh2d(3, 2, devices=cpu_devices[:4])


def test_axis_registry_closed():
    assert set(AXIS_ROLES) == {"data", "model", "host", "local"}
    from tpuddp.parallel.mesh2d import validate_axis

    with pytest.raises(ValueError, match="unknown mesh axis"):
        validate_axis("pipeline")


def test_squeeze_model(cpu_devices):
    m1 = mesh2d(4, 1, devices=cpu_devices[:4])
    flat = squeeze_model(m1)
    assert flat.axis_names == (DATA_AXIS,)
    assert list(flat.devices.flat) == list(m1.devices.flat)
    with pytest.raises(ValueError, match="cannot squeeze"):
        squeeze_model(mesh2d(2, 2, devices=cpu_devices[:4]))
    # a mesh without the model axis passes through untouched
    dm = data_mesh(4)
    assert squeeze_model(dm) is dm


def test_model_size_of_1d_meshes(cpu_devices):
    assert model_size(data_mesh(4)) == 1
    assert model_size(None) == 1
    assert describe(None) is None


# ------------------------------------------------------------ config surface --


def test_parallel_block_unknown_key_refused():
    with pytest.raises(ValueError, match="unknown parallel key"):
        cfg_lib.resolve_parallel({"data": 2, "modle": 2})
    assert cfg_lib.resolve_parallel(None) == {"data": "auto", "model": 1}
    assert cfg_lib.parallel_config({"parallel": {"model": 2}})["model"] == 2


def test_mesh_from_refuses_bad_tiling(cpu_devices):
    with pytest.raises(ValueError, match="!= device count|does not tile"):
        cfg_lib.mesh_from({"data": 3, "model": 2}, world_size=4)
    with pytest.raises(ValueError, match="does not tile"):
        cfg_lib.mesh_from({"model": 3}, world_size=4)


def test_mesh_from_refuses_hierarchical_model_parallel():
    with pytest.raises(ValueError, match="hierarchical"):
        cfg_lib.mesh_from(
            {"model": 2}, world_size=4, comm_topology="hierarchical"
        )


def test_mesh_from_model1_is_flat_mesh(cpu_devices):
    mesh = cfg_lib.mesh_from(None, world_size=4)
    assert mesh.axis_names == (DATA_AXIS,)
    mesh2 = cfg_lib.mesh_from({"data": 2, "model": 2}, world_size=4)
    assert mesh2.axis_names == (DATA_AXIS, MODEL_AXIS)


# ------------------------------------------------- spec application matrix --


def test_spec_matrix_covers_every_logical_axes_entry():
    """Every ``param_logical_axes`` entry maps through the TP rule table to
    the expected mesh-axis spec — column-split QKV/mlp-in, row-split
    attn-out/mlp-out, vocab-split embedding, everything else replicated."""
    model = load_model("transformer_tiny", num_classes=V, max_seq_len=32)
    params, _ = model.init(KEY, jnp.zeros((1, T), jnp.int32))
    tp_params = tp_lib.to_tp_tree(params)
    specs = tp_lib.tp_param_specs(model, tp_params)
    expected_block = {
        "ln1": {"scale": P(None), "bias": P(None)},
        "attn": {
            "wqkv": P(None, None, MODEL_AXIS),  # (E, 3, H*Dh) head split
            "bqkv": P(None, MODEL_AXIS),
            "wo": P(MODEL_AXIS, None),          # row split by heads
            "bo": P(None),
        },
        "ln2": {"scale": P(None), "bias": P(None)},
        "mlp": {
            "w1": P(None, MODEL_AXIS),          # column split (mlp)
            "b1": P(MODEL_AXIS),
            "w2": P(MODEL_AXIS, None),          # row split (mlp)
            "b2": P(None),
        },
    }
    assert specs["embed"]["weight"] == P(MODEL_AXIS, None)  # vocab split
    assert specs["pos"]["weight"] == P(None, None)
    assert specs["ln_f"] == {"scale": P(None), "bias": P(None)}
    for blk in specs["blocks"]:
        assert blk == expected_block
    # the matrix covers EVERY logical-axes entry: same leaf count
    axes = tf_lib.param_logical_axes(model, params)
    n_axes = len(jax.tree_util.tree_leaves(
        axes,
        is_leaf=lambda l: isinstance(l, tuple) and bool(l)
        and all(isinstance(n, str) for n in l),
    ))
    assert n_axes == len(jax.tree_util.tree_leaves(specs))


def test_tp_rules_extend_snippet_table_with_vocab():
    rules = tp_lib.tp_rules()
    base = tf_lib.PARTITION_RULES
    assert base["vocab"] is None and rules["vocab"] == MODEL_AXIS
    for k in ("heads", "mlp", "joined_kv"):
        assert rules[k] == base[k] == MODEL_AXIS
    assert len(tp_lib.tp_rules_hash()) == 16
    assert tp_lib.tp_rules_hash() != tp_lib.tp_rules_hash(base)


def test_qkv_layout_roundtrip():
    model = load_model("transformer_tiny", num_classes=V, max_seq_len=32)
    params, _ = model.init(KEY, jnp.zeros((1, T), jnp.int32))
    back = tp_lib.from_tp_tree(tp_lib.to_tp_tree(params))
    assert leaves_equal(params, back)


def test_geometry_refusals(cpu_devices):
    with pytest.raises(ValueError, match="n_heads"):
        tp_lib.validate_tp_geometry(
            load_model("transformer_tiny", num_classes=V), 3
        )
    with pytest.raises(ValueError, match="partition metadata"):
        tp_lib.validate_tp_geometry(load_model("toy_mlp"), 2)


# ------------------------------------------------ forward/backward parity --


def test_tp2_forward_matches_single_device_reference(cpu_devices):
    """TP=2 logits vs the unsharded ``model.apply`` on the gathered params:
    the column-split attention and the vocab-split head/lookup are exact;
    the two row-split projections psum M partials, changing only the
    contraction's summation order — asserted tight."""
    from tpuddp.utils.compat import shard_map

    ddp, model = make_tp(cpu_devices)
    st = ddp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    x, _, _ = token_batch()
    ref_params = tp_lib.gather_params(st)
    ref_logits, _ = model.apply(ref_params, (), x, Context(train=False))
    fn = shard_map(
        lambda p, t: tp_lib.tp_forward(model, p, t),
        mesh=ddp.mesh,
        in_specs=(ddp.tp_param_specs, P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    tp_logits = jax.jit(fn)(st.params, ddp.shard((x,))[0])
    np.testing.assert_allclose(
        np.asarray(tp_logits), np.asarray(ref_logits), rtol=0, atol=2e-5
    )


def test_tp2_backward_matches_single_device_reference(cpu_devices):
    """One Adam step at TP=2xDP=2 lands the same parameters as one
    full-batch step on a single unsharded copy (the DP pmean over the data
    axis + the TP psums reproduce the full-batch gradient)."""
    ddp, model = make_tp(cpu_devices)
    st = ddp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    x, y, w = token_batch()
    ref_params = jax.tree_util.tree_map(jnp.asarray, tp_lib.gather_params(st))
    crit = nn.CrossEntropyLoss()

    def ref_loss(p):
        logits, _ = model.apply(p, (), x, Context(train=True))
        return crit(logits, y, w)

    ref_grads = jax.grad(ref_loss)(ref_params)
    opt = optim.Adam(lr=1e-2)
    ref_new, _ = opt.update(ref_grads, opt.init(ref_params), ref_params)

    st2, _ = ddp.train_step(st, ddp.shard((x, y, w)))
    tp_new = tp_lib.gather_params(st2)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(ref_new)[0],
        jax.tree_util.tree_flatten_with_path(tp_new)[0],
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=5e-4,
            err_msg=jax.tree_util.keystr(pa),
        )


def test_tp2xdp2_loss_trajectory_matches_dp4(cpu_devices):
    """Matched global batch: TP=2xDP=2 and pure DP=4 track the same loss
    trajectory step for step (float-reduction tolerance)."""
    tp, _ = make_tp(cpu_devices)
    dp, _ = make_tp(cpu_devices, data=4, model=1)
    st_tp = tp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    st_dp = dp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    for i in range(6):
        x, y, w = token_batch(seed=10 + i)
        st_tp, m_tp = tp.train_step(st_tp, tp.shard((x, y, w)))
        st_dp, m_dp = dp.train_step(st_dp, dp.shard((x, y, w)))
        l_tp = float(np.asarray(m_tp["loss_sum"]).sum() / np.asarray(m_tp["n"]).sum())
        l_dp = float(np.asarray(m_dp["loss_sum"]).sum() / np.asarray(m_dp["n"]).sum())
        assert abs(l_tp - l_dp) < 1e-4, (i, l_tp, l_dp)


def test_tp_scan_step_matches_repeated_single_steps(cpu_devices):
    ddp, _ = make_tp(cpu_devices)
    st_a = ddp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    st_b = ddp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    b0, b1 = token_batch(seed=3), token_batch(seed=4)
    for b in (b0, b1):
        st_a, _ = ddp.train_step(st_a, ddp.shard(b))
    stacked = tuple(np.stack([p, q]) for p, q in zip(b0, b1))
    st_b, _ = ddp.train_step_many(st_b, ddp.shard_stacked(stacked))
    assert leaves_equal(st_a.params, st_b.params)


def test_tp_eval_step_counts_tokens(cpu_devices):
    ddp, _ = make_tp(cpu_devices)
    st = ddp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    m = ddp.eval_step(st, ddp.shard(token_batch()))
    assert float(np.asarray(m["n"]).sum()) == B * T
    assert np.isfinite(np.asarray(m["loss_sum"])).all()


# ------------------------------------------------------ model=1 HLO identity --


def test_model1_hlo_identity_with_flat_ddp(cpu_devices):
    """``mesh2d(4, 1)`` routes through the EXISTING DDP path unchanged: the
    lowered train-step HLO is byte-identical to a flat ``data_mesh(4)``
    wrap's."""
    m1, _ = make_tp(cpu_devices, data=4, model=1)
    assert m1.mesh.axis_names == (DATA_AXIS,)  # squeezed to the flat mesh
    flat2 = DistributedDataParallel(
        load_model("transformer_tiny", num_classes=V, max_seq_len=32),
        optim.Adam(lr=1e-2), nn.CrossEntropyLoss(), mesh=data_mesh(4),
    )

    def lowered(d):
        st = d.init_state(KEY, jnp.zeros((1, T), jnp.int32))
        struct = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), st
        )
        b = (
            jax.ShapeDtypeStruct((B, T), jnp.int32),
            jax.ShapeDtypeStruct((B, T), jnp.int32),
            jax.ShapeDtypeStruct((B, T), jnp.float32),
        )
        return jax.jit(lambda s, bb: d.train_step(s, bb)).lower(struct, b).as_text()

    assert lowered(m1) == lowered(flat2)


# ------------------------------------------------------- comm-hook composition --


def test_comm_bytes_account_data_axis_only(cpu_devices):
    """The wire counter reports the LOCAL shard payload exchanged across
    data replicas: TP=2 halves the flat gradient vector, so bf16_ef bytes
    are half the model=1 bf16_ef bytes of the same model, and the bf16 cut
    vs the TP run's own f32 baseline stays exactly 50%."""
    tp, model = make_tp(cpu_devices, comm_hook="bf16_ef")
    st = tp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    assert tp.grad_comm_bytes_per_step == tp.grad_comm_bytes_per_step_f32 // 2
    # the local template is the sharded tree: its padded flat length is the
    # comm plan's residual length
    tp_params = jax.tree_util.tree_map(np.asarray, st.params)
    local = tp_lib.local_param_template(tp_params, tp.tp_param_specs, 2)
    expect = comm_lib.comm_bytes_for_hook(local, 2, "bf16_ef")
    assert tp.grad_comm_bytes_per_step == expect
    assert tp._grad_comm_breakdown["intra_host"] == 0


def test_ef_residual_keyed_by_data_model_index(cpu_devices):
    """The error-feedback residual lays out one slice per
    ``(data_index, model_index)`` device — P(("data", "model")) over the
    flat vector — and becomes non-zero once compression error accrues."""
    tp, _ = make_tp(cpu_devices, comm_hook="bf16_ef")
    st = tp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    assert st.comm_state.shape == (tp._comm.spec.total * 4,)
    assert st.comm_state.sharding.spec == P((DATA_AXIS, MODEL_AXIS))
    assert len(st.comm_state.addressable_shards) == 4
    st, _ = tp.train_step(st, tp.shard(token_batch()))
    st, _ = tp.train_step(st, tp.shard(token_batch(seed=1)))
    res = np.asarray(st.comm_state)
    assert np.abs(res).max() > 0


def test_tp_bf16ef_tracks_uncompressed_trajectory(cpu_devices):
    base, _ = make_tp(cpu_devices)
    comp, _ = make_tp(cpu_devices, comm_hook="bf16_ef")
    st_b = base.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    st_c = comp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    for i in range(4):
        b = token_batch(seed=20 + i)
        st_b, m_b = base.train_step(st_b, base.shard(b))
        st_c, m_c = comp.train_step(st_c, comp.shard(b))
    l_b = float(np.asarray(m_b["loss_sum"]).sum() / np.asarray(m_b["n"]).sum())
    l_c = float(np.asarray(m_c["loss_sum"]).sum() / np.asarray(m_c["n"]).sum())
    assert abs(l_b - l_c) <= comm_lib.loss_parity_tol("bf16_ef", l_b)


# --------------------------------------------------------------- guard --


def _perturb_data_replica(ddp, params, leaf_index, device_index):
    """Return params with ONE device's copy of leaf ``leaf_index`` bumped —
    a data-axis divergence the auditor must convict."""
    flat, treedef = jax.tree_util.tree_flatten(params)
    specs = jax.tree_util.tree_leaves(ddp.tp_param_specs)
    leaf, spec = flat[leaf_index], specs[leaf_index]
    pieces = []
    for d_idx, dev in enumerate(ddp.mesh.devices.flat):
        arr = np.asarray(
            [s for s in leaf.addressable_shards if s.device == dev][0].data
        ).copy()
        if d_idx == device_index:
            arr = arr + 1.0
        pieces.append(jax.device_put(arr, dev))
    bad = jax.make_array_from_single_device_arrays(
        leaf.shape, NamedSharding(ddp.mesh, spec), pieces
    )
    return jax.tree_util.tree_unflatten(
        treedef, flat[:leaf_index] + [bad] + flat[leaf_index + 1:]
    )


def test_guard_no_false_positive_on_tp_shards(cpu_devices):
    """A TP state's shards differ across the model axis BY DESIGN; the
    auditor (fingerprint within a model-shard group, compare across data
    replicas) must not convict them — at wrap time or on explicit audit."""
    ddp, _ = make_tp(cpu_devices, guard=True)
    st = ddp.init_state(KEY, jnp.zeros((1, T), jnp.int32))  # audits at wrap
    assert guard_lib.audit_params(
        ddp.mesh, st.params, specs=ddp.tp_param_specs
    ) is None


def test_guard_convicts_data_axis_divergence(cpu_devices):
    ddp, _ = make_tp(cpu_devices)
    st = ddp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    paths = [
        jax.tree_util.keystr(p)
        for p, _ in jax.tree_util.tree_flatten_with_path(st.params)[0]
    ]
    specs = jax.tree_util.tree_leaves(ddp.tp_param_specs)
    # one replicated leaf and one model-SHARDED leaf: both must convict
    # when a data replica's copy diverges (device 2 = (data=1, model=0))
    sharded_i = next(i for i, s in enumerate(specs) if MODEL_AXIS in str(s))
    replicated_i = next(i for i, s in enumerate(specs) if s == P(None))
    for i in (replicated_i, sharded_i):
        bad = _perturb_data_replica(ddp, st.params, i, device_index=2)
        assert guard_lib.audit_params(
            ddp.mesh, bad, specs=ddp.tp_param_specs
        ) == paths[i]


def test_guard_firewall_skip_is_bitwise_noop_on_tp(cpu_devices):
    ddp, _ = make_tp(cpu_devices, guard=True)
    st = ddp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    before = [np.asarray(l).copy() for l in jax.tree_util.tree_leaves(st.params)]
    x, y, w = token_batch()
    w = w.copy()
    w[0, 0] = np.nan  # poisons the loss -> non-finite gradient everywhere
    st2, _ = ddp.train_step(st, ddp.shard((x, y, w)))
    assert int(np.asarray(st2.skipped_steps["total"])) == 1
    assert all(
        np.array_equal(a, np.asarray(b))
        for a, b in zip(before, jax.tree_util.tree_leaves(st2.params))
    )
    # a clean batch afterwards applies and resets the consecutive counter
    st3, _ = ddp.train_step(st2, ddp.shard(token_batch(seed=9)))
    assert int(np.asarray(st3.skipped_steps["consecutive"])) == 0
    assert not leaves_equal(
        jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(st3.params), before
        ),
        st3.params,
    )


# ----------------------------------------------------------- checkpointing --


def test_checkpoint_roundtrip_tp2(cpu_devices, tmp_path):
    tp, _ = make_tp(cpu_devices, comm_hook="bf16_ef")
    st = tp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    st, _ = tp.train_step(st, tp.shard(token_batch()))
    host = [np.asarray(l).copy() for l in jax.tree_util.tree_leaves(st)]
    ckpt.save_on_main(str(tmp_path), 0, st, world_size=4)
    topo = ckpt.read_topology(str(tmp_path / "ckpt_0.npz"))
    assert topo["format"] == ckpt.FORMAT_VERSION
    assert topo["model_size"] == 2
    assert ckpt.topology_model_size(topo) == 2
    # v3 placement tags: every model-sharded leaf names its mesh axes
    # (trailing replicated dims may be elided from the recorded spec)
    assert topo["placement"][".params['embed']['weight']"][0] == "model"
    assert topo["leaves"][".comm_state"]["model"] == 2
    restored, nxt = ckpt.restore_latest(
        str(tmp_path), st, world_size=4, model_size=2
    )
    assert nxt == 1
    assert all(
        np.array_equal(a, np.asarray(b))
        for a, b in zip(host, jax.tree_util.tree_leaves(restored))
    )


def test_checkpoint_cross_model_width_refused_typed(cpu_devices, tmp_path):
    tp, _ = make_tp(cpu_devices)
    st = tp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    ckpt.save_on_main(str(tmp_path), 0, st, world_size=4)
    for width in (1, 4, None):
        with pytest.raises(ckpt.TopologyMismatch, match="model"):
            ckpt.restore_latest(
                str(tmp_path), st, world_size=4, model_size=width
            )


def test_v2_record_on_2d_mesh_refuses_not_misslices(cpu_devices, tmp_path):
    """The elastic-resume hardening satellite: a format-v2 topology record
    (no explicit model_size) written on a 2-D mesh still names its mesh
    axes — loading it under a DIFFERENT model width must raise the typed
    refusal, never re-pad/mis-slice the flat leaves."""
    tp, _ = make_tp(cpu_devices, comm_hook="bf16_ef")
    st = tp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    ckpt.save_on_main(str(tmp_path), 0, st, world_size=4)
    topo = ckpt.read_topology(str(tmp_path / "ckpt_0.npz"))
    # strip the v3 fields -> exactly what a v2 writer on this mesh recorded
    v2 = {k: v for k, v in topo.items() if k not in ("model_size", "placement")}
    v2["format"] = 2
    # the v2 per-replica tag had no model field either
    v2["leaves"] = {
        k: {kk: vv for kk, vv in info.items() if kk != "model"}
        for k, info in topo["leaves"].items()
    }
    assert ckpt.topology_model_size(v2) == 2  # derived from mesh_axes
    host = jax.tree_util.tree_map(np.asarray, st)
    path = str(tmp_path / "ckpt_7.npz")
    ckpt.save(path, host, meta={"epoch": 7, "completed": 1}, topology=v2)
    with pytest.raises(ckpt.TopologyMismatch, match="model=2"):
        ckpt.load(path, st, world_size=4, model_size=1)
    # same width still loads
    assert ckpt.load(path, st, world_size=4, model_size=2) is not None


def test_dp_checkpoint_refused_on_tp_mesh(cpu_devices, tmp_path):
    """A pure-DP (model=1) checkpoint restored onto a TP run refuses typed
    — and a v1 file (no topology record at all) refuses too."""
    dp, _ = make_tp(cpu_devices, data=4, model=1)
    st = dp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    ckpt.save_on_main(str(tmp_path), 0, st, world_size=4)
    with pytest.raises(ckpt.TopologyMismatch, match="model"):
        ckpt.load(
            str(tmp_path / "ckpt_0.npz"), st, world_size=4, model_size=2
        )
    # v1: no topology record
    host = jax.tree_util.tree_map(np.asarray, st)
    v1 = str(tmp_path / "ckpt_3.npz")
    ckpt.save(v1, host, meta={"epoch": 3, "completed": 1}, topology=None)
    with pytest.raises(ckpt.TopologyMismatch, match="format v1"):
        ckpt.load(v1, st, world_size=4, model_size=2)


def test_tp_residual_data_resharding_requires_opt_in(cpu_devices, tmp_path):
    """Changing the DATA width under TP with an EF residual armed refuses by
    default — the (data, model)-keyed slices need the per-model-column
    redistribution in tpuddp.training.reshard, and the refusal names BOTH
    opt-in spellings (reshard_on_mismatch, the offline tool) so the operator
    is pointed at the fix, not just the wall (ISSUE 16 satellite)."""
    tp, _ = make_tp(cpu_devices, comm_hook="bf16_ef")
    st = tp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    ckpt.save_on_main(str(tmp_path), 0, st, world_size=4)
    # a template whose residual is half as long (data=1 x model=2)
    import dataclasses

    smaller = dataclasses.replace(
        st, comm_state=jnp.zeros((st.comm_state.shape[0] // 2,), jnp.float32)
    )
    with pytest.raises(
        ckpt.TopologyMismatch, match="reshard_on_mismatch"
    ) as err:
        ckpt.load(
            str(tmp_path / "ckpt_0.npz"), smaller, world_size=2, model_size=2
        )
    assert "tpuddp_inspect reshard" in str(err.value)


# ----------------------------------------------------------- wrap refusals --


def test_tp_wrap_refusal_surface(cpu_devices):
    model = load_model("transformer_tiny", num_classes=V, max_seq_len=32)
    mesh = mesh2d(2, 2, devices=cpu_devices[:4])

    def build(**kw):
        kwargs = dict(mesh=mesh)
        kwargs.update(kw)
        return DistributedDataParallel(
            model, optim.Adam(lr=1e-2), nn.CrossEntropyLoss(), **kwargs
        )

    with pytest.raises(ValueError, match="shard_map"):
        build(mode="auto")
    with pytest.raises(ValueError, match="weight_update_sharding"):
        build(weight_update_sharding=True)
    with pytest.raises(ValueError, match="hierarchical"):
        build(comm_topology="hierarchical")
    with pytest.raises(ValueError, match="grad_accumulation"):
        build(grad_accumulation=2)
    with pytest.raises(ValueError, match="clip_grad_norm"):
        build(clip_grad_norm=1.0)
    with pytest.raises(ValueError, match="LARS/LAMB"):
        DistributedDataParallel(
            model, optim.LAMB(1e-3), nn.CrossEntropyLoss(), mesh=mesh
        )
    with pytest.raises(ValueError, match="partition metadata"):
        DistributedDataParallel(
            load_model("toy_mlp"), optim.Adam(lr=1e-2),
            nn.CrossEntropyLoss(), mesh=mesh,
        )
    with pytest.raises(ValueError, match="n_heads"):
        # transformer_tiny has 4 heads: a model axis of 8 cannot tile it
        DistributedDataParallel(
            load_model("transformer_tiny", num_classes=V),
            optim.Adam(lr=1e-2), nn.CrossEntropyLoss(),
            mesh=mesh2d(1, 8, devices=cpu_devices[:8]),
        )


# --------------------------------------------------------------- data path --


def test_sharded_loader_samples_per_data_group(cpu_devices):
    """On a 2-D mesh the loader builds one sampler per DATA index: the
    global batch is data_size x batch rows, and placement replicates each
    row group across the model axis."""
    from tpuddp.data.loader import ShardedDataLoader

    class Toy:
        def __len__(self):
            return 64

        def __getitem__(self, i):
            return np.full((4,), i, np.float32), i % 10

    mesh = mesh2d(2, 2, devices=cpu_devices[:4])
    loader = ShardedDataLoader(Toy(), 4, mesh, shuffle=False)
    assert loader.world_size == 2  # data groups, not devices
    x, y, w = next(iter(loader))
    assert x.shape == (8, 4)  # 2 data groups x batch 4
    from tpuddp.parallel.mesh import shard_batch

    placed = shard_batch(mesh, x)
    assert placed.sharding.spec == P(DATA_AXIS, None)
    # model-axis neighbors hold the SAME rows
    shards = {s.device: np.asarray(s.data) for s in placed.addressable_shards}
    d = mesh.devices
    np.testing.assert_array_equal(shards[d[0, 0]], shards[d[0, 1]])
    np.testing.assert_array_equal(shards[d[1, 0]], shards[d[1, 1]])
    assert not np.array_equal(shards[d[0, 0]], shards[d[1, 0]])


# ------------------------------------------------------------ run_meta block --


def test_run_meta_mesh_block_v8():
    from tpuddp.observability import schema

    assert schema.SCHEMA_VERSION >= 8  # the mesh block is required since v8
    meta = schema.make_run_meta(
        mesh=mesh2d(2, 2, devices=jax.devices("cpu")[:4]),
        comm_hook="none", tp_rules_hash="abc123",
    )
    assert meta["mesh"] == {"data": 2, "model": 2, "tp_rules_hash": "abc123"}
    assert not schema.validate_record(meta)
    # a v8 header MISSING the mesh key is drift
    bad = {k: v for k, v in meta.items() if k != "mesh"}
    errors = schema.validate_record(bad)
    assert any("mesh" in e for e in errors)
    # older versions validate at their own version
    old = dict(bad)
    old["schema_version"] = 7
    old["survivability"] = None
    assert not schema.validate_record(old)
    # no-mesh writers carry the key as null
    serving_meta = schema.make_run_meta(world_size=2, comm_hook=None)
    assert serving_meta["mesh"] is None
    assert not schema.validate_record(serving_meta)
