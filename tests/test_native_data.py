"""Native C++ data path (tpuddp/data/_native) and the prefetching loader —
both must be bit-identical to the numpy fallback."""

import os

import numpy as np
import pytest

from tpuddp.data import DataLoader, PrefetchLoader, ShardedDataLoader, SyntheticClassification
from tpuddp.data import _native
from tpuddp.data.loader import _fetch_padded
from tpuddp.parallel import make_mesh


needs_native = pytest.mark.skipif(
    not _native.available(), reason="native gather library unavailable (no g++?)"
)


@needs_native
@pytest.mark.parametrize("dtype", [np.uint8, np.float32])
def test_native_gather_matches_numpy(dtype):
    rng = np.random.RandomState(0)
    src = np.ascontiguousarray(
        (rng.rand(100, 7, 5) * 200).astype(dtype)
    )
    idx = rng.randint(0, 100, 33)
    out = _native.gather_rows(src, idx)
    np.testing.assert_array_equal(out, src[idx])


@needs_native
def test_native_gather_padding_repeats_first_row():
    src = np.arange(40, dtype=np.uint8).reshape(10, 4)
    out = _native.gather_rows(src, np.array([3, 7]), pad_rows=5)
    assert out.shape == (5, 4)
    np.testing.assert_array_equal(out[0], src[3])
    np.testing.assert_array_equal(out[1], src[7])
    for i in (2, 3, 4):
        np.testing.assert_array_equal(out[i], src[3])


@needs_native
def test_native_gather_large_batch_multithreaded():
    rng = np.random.RandomState(1)
    src = np.ascontiguousarray(rng.randint(0, 255, (5000, 3072), dtype=np.uint8))
    idx = rng.randint(0, 5000, 2048)
    out = _native.gather_rows(src, idx)
    np.testing.assert_array_equal(out, src[idx])


def test_native_gather_rejects_noncontiguous():
    src = np.zeros((10, 8), np.uint8)[:, ::2]
    assert _native.gather_rows(src, np.array([0, 1])) is None


def test_fetch_padded_native_equals_fallback(monkeypatch):
    ds = SyntheticClassification(n=50, shape=(6, 6, 3), seed=2)
    idx = np.array([4, 9, 11])
    got = _fetch_padded(ds, idx, 8)
    # force the numpy fallback
    monkeypatch.setattr(_native, "gather_rows", lambda *a, **k: None)
    want = _fetch_padded(ds, idx, 8)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)


def test_prefetch_loader_yields_identical_batches(cpu_devices):
    mesh = make_mesh(cpu_devices[:4])
    ds = SyntheticClassification(n=64, shape=(4, 4, 3), seed=3)
    base = ShardedDataLoader(ds, 4, mesh, shuffle=True, seed=1)
    pre = PrefetchLoader(ShardedDataLoader(ds, 4, mesh, shuffle=True, seed=1))
    assert len(pre) == len(base)
    for epoch in range(2):
        base.set_epoch(epoch)
        pre.set_epoch(epoch)
        for (xa, ya, wa), (xb, yb, wb) in zip(base, pre):
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)
            np.testing.assert_array_equal(wa, wb)


def test_prefetch_loader_delegates_probe(cpu_devices):
    mesh = make_mesh(cpu_devices[:2])
    ds = SyntheticClassification(n=16, shape=(8,), seed=0)
    pre = PrefetchLoader(ShardedDataLoader(ds, 4, mesh, shuffle=False))
    x, _, _ = next(iter(pre))
    assert "replica 0" in pre.probe_fingerprint(x)
    assert pre.world_size == 2  # __getattr__ delegation


def test_prefetch_loader_propagates_exceptions():
    class Exploding:
        def __iter__(self):
            yield (np.zeros(1), np.zeros(1), np.zeros(1))
            raise RuntimeError("loader blew up")

        def __len__(self):
            return 2

    pre = PrefetchLoader(Exploding())
    it = iter(pre)
    next(it)
    with pytest.raises(RuntimeError, match="loader blew up"):
        list(it)


def test_prefetch_wraps_plain_dataloader():
    ds = SyntheticClassification(n=20, shape=(4,), seed=1)
    pre = PrefetchLoader(DataLoader(ds, batch_size=8))
    batches = list(pre)
    assert len(batches) == 3
    assert batches[-1][2].sum() == 4  # padding mask intact through the queue


def test_native_library_path_is_isa_keyed():
    """-march=native builds must not be shared across ISAs (SIGILL on a
    shared filesystem): the cache filename carries a host fingerprint."""
    from tpuddp.data import _native

    tag = _native._isa_tag()
    assert tag and "/" not in tag
    assert tag in os.path.basename(_native._LIB)
    assert _native._LIB.endswith(".so")
