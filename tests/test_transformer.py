"""Transformer family suite (ISSUE 12): decoder-only block semantics
(shapes, causality, tied head), the prefill/decode_step serving protocol's
parity with the full forward, the SNIPPETS.md [2] partition metadata, zoo
registration, and logit parity against a torch reference module through
``convert_transformer_state_dict``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn as tnn

from tpuddp.models import TransformerLM, _REGISTRY, load_model
from tpuddp.models.torch_import import convert_transformer_state_dict
from tpuddp.models.transformer import (
    PARTITION_RULES,
    param_logical_axes,
    partition_spec,
    prefill_buckets,
)
from tpuddp.nn.core import Context

KEY = jax.random.key(0)
CTX = Context(train=False)

V, E, H, L, T = 32, 16, 4, 2, 24  # tiny: every compile trivial


@pytest.fixture(scope="module")
def model():
    return TransformerLM(
        num_classes=V, d_model=E, n_heads=H, n_layers=L, max_seq_len=T
    )


@pytest.fixture(scope="module")
def params(model):
    p, state = model.init(KEY, jnp.zeros((1, 2), jnp.int32))
    assert state == ()
    return p


def _tokens(rng, b, t):
    return jnp.asarray(rng.randint(0, V, size=(b, t)), jnp.int32)


# ----------------------------------------------------------------- forward --


def test_apply_shapes_and_dtype(model, params):
    rng = np.random.RandomState(0)
    logits, state = model.apply(params, (), _tokens(rng, 3, 7), CTX)
    assert logits.shape == (3, 7, V)
    assert logits.dtype == jnp.float32
    assert state == ()


def test_apply_rejects_overlong_sequence(model, params):
    with pytest.raises(ValueError, match="max_seq_len"):
        model.apply(params, (), jnp.zeros((1, T + 1), jnp.int32), CTX)


def test_causal_mask_blocks_future_positions(model, params):
    """Logits at position t must be a function of tokens[0..t] only: editing
    every token AFTER t cannot move them (the autoregressive contract the
    decode engine's bitwise guarantee is built on)."""
    rng = np.random.RandomState(1)
    toks = np.asarray(_tokens(rng, 1, 10))
    logits, _ = model.apply(params, (), jnp.asarray(toks), CTX)
    edited = toks.copy()
    edited[0, 6:] = (edited[0, 6:] + 7) % V
    logits2, _ = model.apply(params, (), jnp.asarray(edited), CTX)
    np.testing.assert_array_equal(
        np.asarray(logits[0, :6]), np.asarray(logits2[0, :6])
    )
    assert not np.array_equal(np.asarray(logits[0, 6:]), np.asarray(logits2[0, 6:]))


def test_lm_head_is_tied_to_embedding(params):
    """No separate head matrix anywhere in the tree — logits must come from
    embed.weight itself (the GPT-2 tying convention the importer enforces)."""
    leaves = jax.tree_util.tree_leaves_with_path(params)
    names = ["/".join(str(k) for k in path) for path, _ in leaves]
    assert not any("head" in n for n in names)


def test_batch_rows_independent(model, params):
    """Row b's logits must not depend on what else shares the batch."""
    rng = np.random.RandomState(2)
    toks = _tokens(rng, 4, 8)
    full, _ = model.apply(params, (), toks, CTX)
    solo, _ = model.apply(params, (), toks[1:2], CTX)
    np.testing.assert_array_equal(np.asarray(full[1]), np.asarray(solo[0]))


# ------------------------------------------------- prefill / decode_step --


def _pool_pair(model, num_blocks=16, block_size=4):
    shape = (model.n_layers, num_blocks, block_size, model.n_heads,
             model.head_dim)
    return jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)


def test_prefill_matches_full_forward_last_position(model, params):
    """The serving prefill (bucketed length, paged-pool commit) must produce
    EXACTLY the full forward's last-position logits — the two code paths
    share the block math, and this pins that they cannot drift."""
    rng = np.random.RandomState(3)
    n = 5
    prompt = np.asarray(_tokens(rng, 1, n))
    kpool, vpool = _pool_pair(model)
    table_row = jnp.asarray([1, 2, 3, 0, 0, 0], jnp.int32)
    P = 8  # the padded bucket
    buf = np.zeros((1, P), np.int32)
    buf[0, :n] = prompt[0]
    last, kpool, vpool = model.prefill(
        params, kpool, vpool, table_row, jnp.asarray(buf),
        jnp.asarray(n, jnp.int32),
    )
    ref, _ = model.apply(params, (), jnp.asarray(prompt), CTX)
    np.testing.assert_array_equal(np.asarray(last), np.asarray(ref[0, n - 1]))


def test_prefill_plus_steps_match_full_forward(model, params):
    """Greedy decode through prefill + fixed-shape steps must equal greedy
    decode through repeated full forwards — KV paging is numerically
    invisible at the model level, not just end to end."""
    rng = np.random.RandomState(4)
    n, steps, S, BS = 4, 5, 3, 4
    prompt = np.asarray(_tokens(rng, 1, n))
    kpool, vpool = _pool_pair(model, num_blocks=16, block_size=BS)
    max_blocks = 6
    tables = np.zeros((S, max_blocks), np.int32)
    tables[1, :3] = [4, 5, 6]  # the sequence under test lives in slot 1
    lengths = np.zeros((S,), np.int32)
    buf = np.zeros((1, 8), np.int32)
    buf[0, :n] = prompt[0]
    last, kpool, vpool = model.prefill(
        params, kpool, vpool, jnp.asarray(tables[1]), jnp.asarray(buf),
        jnp.asarray(n, jnp.int32),
    )
    lengths[1] = n
    got = [int(np.argmax(np.asarray(last)))]
    for _ in range(steps):
        toks = np.zeros((S,), np.int32)
        toks[1] = got[-1]
        logits, kpool, vpool = model.decode_step(
            params, kpool, vpool, jnp.asarray(tables), jnp.asarray(lengths),
            jnp.asarray(toks),
        )
        lengths[1] += 1
        got.append(int(np.argmax(np.asarray(logits)[1])))
    # reference: greedy decode via the full forward, re-running the whole
    # growing sequence every step
    seq = list(prompt[0])
    ref = []
    for _ in range(steps + 1):
        logits, _ = model.apply(
            params, (), jnp.asarray([seq], jnp.int32), CTX
        )
        tok = int(np.argmax(np.asarray(logits)[0, -1]))
        ref.append(tok)
        seq.append(tok)
    assert got == ref


# ------------------------------------------------------ partition metadata --


def test_param_logical_axes_congruent_with_params(model, params):
    axes = param_logical_axes(model, params)
    is_leaf = lambda x: isinstance(x, tuple) and all(
        isinstance(n, str) for n in x
    )
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_a = jax.tree_util.tree_leaves_with_path(axes, is_leaf=is_leaf)
    assert [p for p, _ in flat_a] == [p for p, _ in flat_p]
    for (_, names), (path, leaf) in zip(flat_a, flat_p):
        assert len(names) == leaf.ndim, (path, names, leaf.shape)
        assert all(n in PARTITION_RULES for n in names)


def test_partition_spec_follows_snippet_rule_table(model, params):
    """The tensor-parallel split of SNIPPETS.md [2]: joined QKV column-split,
    attention output row-split, MLP up column-/down row-split on the "model"
    axis; embeddings, norms, and biases on unsharded logical axes."""
    spec = partition_spec(model, params)
    blk = spec["blocks"][0]
    assert blk["attn"]["wqkv"] == (None, "model")  # joined_kv
    assert blk["attn"]["bqkv"] == ("model",)
    assert blk["attn"]["wo"] == ("model", None)  # heads contraction
    assert blk["mlp"]["w1"] == (None, "model")
    assert blk["mlp"]["w2"] == ("model", None)
    assert blk["mlp"]["b1"] == ("model",)
    assert spec["embed"]["weight"] == (None, None)
    assert spec["pos"]["weight"] == (None, None)
    assert spec["ln_f"]["scale"] == (None,)
    # a custom rule table routes through unchanged
    alt = partition_spec(model, params, rules={**PARTITION_RULES, "mlp": "x"})
    assert alt["blocks"][0]["mlp"]["w1"] == (None, "x")


def test_prefill_buckets_ladder():
    assert prefill_buckets(63) == [1, 2, 4, 8, 16, 32, 63]
    assert prefill_buckets(64) == [1, 2, 4, 8, 16, 32, 64]


# ----------------------------------------------------------- zoo + import --


def test_zoo_registration_and_vocab_alias():
    assert "transformer_tiny" in _REGISTRY
    assert "transformer_small" in _REGISTRY
    m = load_model("transformer_tiny", num_classes=100)
    assert isinstance(m, TransformerLM)
    assert m.vocab_size == 100  # num_classes aliases vocab_size


def test_bad_head_split_rejected():
    with pytest.raises(ValueError, match="divisible"):
        TransformerLM(d_model=10, n_heads=3)


class _TorchBlock(tnn.Module):
    def __init__(self, E, H, F):
        super().__init__()
        self.ln1 = tnn.LayerNorm(E)
        self.attn = tnn.Module()
        self.attn.in_proj = tnn.Linear(E, 3 * E)
        self.attn.out_proj = tnn.Linear(E, E)
        self.ln2 = tnn.LayerNorm(E)
        self.mlp = tnn.Module()
        self.mlp.fc1 = tnn.Linear(E, F)
        self.mlp.fc2 = tnn.Linear(F, E)
        self.H = H

    def forward(self, h):
        B, T, E = h.shape
        a = self.ln1(h)
        qkv = self.attn.in_proj(a).reshape(B, T, 3, self.H, E // self.H)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scores = torch.einsum("bqhd,bkhd->bhqk", q, k) / (E // self.H) ** 0.5
        mask = torch.tril(torch.ones(T, T, dtype=torch.bool))
        scores = scores.masked_fill(~mask, -1e30)
        attn = torch.softmax(scores, dim=-1)
        o = torch.einsum("bhqk,bkhd->bqhd", attn, v).reshape(B, T, E)
        h = h + self.attn.out_proj(o)
        return h + self.mlp.fc2(
            tnn.functional.gelu(self.mlp.fc1(self.ln2(h)))
        )


class _TorchLM(tnn.Module):
    """The reference layout ``convert_transformer_state_dict`` documents:
    plain Linears (explicit math), learned positions, TIED lm head."""

    def __init__(self, V, E, H, L, T):
        super().__init__()
        self.embed = tnn.Embedding(V, E)
        self.pos = tnn.Embedding(T, E)
        self.blocks = tnn.ModuleList(_TorchBlock(E, H, 4 * E) for _ in range(L))
        self.ln_f = tnn.LayerNorm(E)

    def forward(self, tokens):
        T = tokens.shape[1]
        h = self.embed(tokens) + self.pos.weight[:T]
        for blk in self.blocks:
            h = blk(h)
        return self.ln_f(h) @ self.embed.weight.T


def test_imported_transformer_reproduces_torch_logits(model, params):
    torch.manual_seed(0)
    ref = _TorchLM(V, E, H, L, T).eval()
    imported = convert_transformer_state_dict(ref.state_dict(), params)
    rng = np.random.RandomState(5)
    toks = np.asarray(rng.randint(0, V, size=(2, 9)), np.int64)
    with torch.no_grad():
        want = ref(torch.from_numpy(toks)).numpy()
    got, _ = model.apply(imported, (), jnp.asarray(toks, jnp.int32), CTX)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_import_rejects_untied_head(model, params):
    torch.manual_seed(1)
    ref = _TorchLM(V, E, H, L, T)
    sd = dict(ref.state_dict())
    sd["head.weight"] = torch.zeros(V, E)  # a separate (untied) head
    with pytest.raises(ValueError, match="does not consume"):
        convert_transformer_state_dict(sd, params)


def test_import_rejects_wrong_geometry(model, params):
    torch.manual_seed(2)
    ref = _TorchLM(V, E * 2, H, L, T)
    with pytest.raises(ValueError):
        convert_transformer_state_dict(ref.state_dict(), params)
