"""Tensor-parallel training worker for the chaos suite (launched by
test_chaos.py — the ISSUE 16 elastic-mesh-failover legs).

Runs a small token-LM job (transformer_small, synthetic next-token batches)
on a 2-D ``(data, model)`` mesh through the full spawn path, so the
resilience wiring is live exactly like the DP worker: SIGTERM drain -> exit
75, ``$TPUDDP_FAULT`` injection, ``$TPUDDP_AUTO_RESUME`` resume — and, new
here, ``reshard_on_mismatch`` so a relaunch on a DIFFERENT mesh shape
reshards the emergency checkpoint instead of refusing it.

Usage: python _chaos_tp_worker.py <out_dir> <num_epochs>

Env levers (the supervisor/fleet relaunch contract):

- ``$TPUDDP_WORLD_SIZE``  — total chips (default 4);
- ``$TPUDDP_MODEL_SIZE``  — tensor-parallel width (default 2; model=1 is a
  pure-DP run of the same workload — the cross-shape parity baseline);
- ``$TPUDDP_CHAOS_TRAINING`` — JSON training-config overrides (e.g.
  ``{"comm_hook": "bf16_ef"}``; the default is the f32 ``none`` hook so the
  cross-shape loss-parity legs compare float-reassociation-only drift).

The loader is bench_mesh's matched-global-batch contract: the same seed
yields the SAME global batches on any mesh shape, which is what makes
"resumed at a different shape, landed the same loss trajectory" a testable
claim rather than a vibe.
"""

import json
import os
import sys

out_dir, num_epochs = sys.argv[1], int(sys.argv[2])
world_size = int(os.environ.get("TPUDDP_WORLD_SIZE") or 4)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tpuddp.parallel.spawn import run_ddp_training  # noqa: E402

CFG = {
    "vocab": 64,
    "seq_len": 32,
    "global_batch": 8,
    "n_batches": 4,
    "seed": 0,
    "learning_rate": 1e-3,
    "comm_hook": "none",  # f32 wire: parity legs compare pure reassociation
    "checkpoint_epoch": 1,
}
CFG.update(json.loads(os.environ.get("TPUDDP_CHAOS_TRAINING") or "{}"))
PARALLEL = json.loads(os.environ.get("TPUDDP_CHAOS_PARALLEL") or "null")
OBSERVABILITY = json.loads(os.environ.get("TPUDDP_CHAOS_OBS") or "null")


def tp_training_loop(rank, world, save_dir, optional_args):
    import jax
    import jax.numpy as jnp

    from tpuddp import config as cfg_lib
    from tpuddp import nn, optim
    from tpuddp.models import load_model
    from tpuddp.parallel.ddp import DistributedDataParallel
    from tpuddp.training.loop import run_training_loop

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    from bench_mesh import TokenLMLoader

    # resolve_parallel honors $TPUDDP_MODEL_SIZE (data falls back to
    # "auto" = world // model) — the exact lever the supervisor/fleet
    # relaunch uses; default mesh when neither env nor block pins it: TP=2
    parallel = PARALLEL
    if parallel is None and not os.environ.get("TPUDDP_MODEL_SIZE"):
        parallel = {"data": "auto", "model": 2}
    mesh = cfg_lib.mesh_from(parallel, world)
    print(f"TP chaos worker: rank {rank}, mesh shape "
          f"{dict(zip(mesh.axis_names, mesh.devices.shape))}")

    model = load_model(
        "transformer_small", num_classes=CFG["vocab"],
        max_seq_len=CFG["seq_len"],
    )
    ddp = DistributedDataParallel(
        model, optim.Adam(lr=CFG["learning_rate"]), nn.CrossEntropyLoss(),
        mesh=mesh, comm_hook=str(CFG["comm_hook"]),
    )
    state = ddp.init_state(
        jax.random.PRNGKey(CFG["seed"]),
        jnp.zeros((1, CFG["seq_len"]), jnp.int32),
    )
    train = TokenLMLoader(
        CFG["vocab"], CFG["seq_len"], CFG["global_batch"], CFG["n_batches"],
        seed=CFG["seed"],
    )
    test = TokenLMLoader(
        CFG["vocab"], CFG["seq_len"], CFG["global_batch"],
        max(2, CFG["n_batches"] // 2), seed=CFG["seed"] + 1,
    )
    run_training_loop(
        ddp, state, train, test, save_dir,
        num_epochs=num_epochs,
        checkpoint_epoch=CFG["checkpoint_epoch"],
        set_epoch=True,
        scan_steps=min(4, CFG["n_batches"]),
        per_replica_log=False,
        auto_resume=bool(os.environ.get("TPUDDP_AUTO_RESUME")),
        # the leg under test: a checkpoint from ANOTHER (data, model) shape
        # reshards onto this mesh at restore instead of refusing
        reshard_on_mismatch=True,
        observability=OBSERVABILITY,
        run_meta={"model": "transformer_small", "dataset": "synthetic_tokens"},
    )


run_ddp_training(
    tp_training_loop,
    world_size=world_size,
    save_dir=out_dir,
    optional_args={},
    backend="cpu",
)
