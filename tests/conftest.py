"""Test harness: an 8-device CPU-simulated world, no TPU required.

This replaces the reference's Gloo fallback (multi-GPU-training-torch.py:36-37)
as the multi-device-without-accelerators test avenue (SURVEY.md §4): XLA's
host platform is split into 8 virtual devices and the whole framework runs on
them via the backend ladder's CPU rung (TPUDDP_BACKEND=cpu).

Env must be set before jax initializes any backends, hence the top-of-conftest
placement.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("TPUDDP_BACKEND", "cpu")
# Keep test compiles off any real TPU attached to the session.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import pytest  # noqa: E402

# The sandbox's sitecustomize imports jax (registering a TPU plugin) before any
# env var set here can take effect, so JAX_PLATFORMS alone cannot force CPU.
# Route all default placements to the host platform explicitly: tests must be
# runnable — and deterministic in f32 — without touching a real TPU.
jax.config.update("jax_default_device", jax.devices("cpu")[0])

WORLD = 8


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= WORLD, (
        f"expected >= {WORLD} virtual CPU devices, got {len(devs)} — XLA_FLAGS "
        "was set too late (another conftest/plugin imported jax first?)"
    )
    return devs[:WORLD]


@pytest.fixture(scope="session")
def mesh(cpu_devices):
    from tpuddp.parallel import make_mesh

    return make_mesh(cpu_devices)
