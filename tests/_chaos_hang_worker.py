"""Dead-peer worker pair for the watchdog chaos test (test_chaos.py).

Two roles sharing one heartbeat directory (the pod's shared-filesystem
rendezvous), driven as separate OS processes:

- role 0 — the healthy survivor: arms the heartbeat + watchdog pair via the
  same ``watchdog.start`` wiring ``spawn.run_ddp_training`` uses, then idles
  like a process wedged in a collective would. Its watchdog must detect the
  peer's stale heartbeat and ``os._exit(76)`` — the test asserts that exit.
- role 1 — the dead peer: heartbeats normally until ``$TPUDDP_FAULT=
  hang@barrier`` fires on barrier entry, which stops its beat and sleeps
  forever (indistinguishable from a preempted/OOM-killed host).

Usage: python _chaos_hang_worker.py <process_id> <num_processes> <shared_dir>
(``$TPUDDP_WATCHDOG_TIMEOUT`` must be set; role 1 also needs $TPUDDP_FAULT.)
"""

import sys
import time

pid, nprocs, shared = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

from tpuddp.resilience import watchdog  # noqa: E402

guard = watchdog.start(shared, pid, nprocs, interval=0.25)
assert guard is not None, "watchdog not armed — $TPUDDP_WATCHDOG_TIMEOUT unset?"
print(f"WORKER {pid} armed", flush=True)

if pid == 1:
    # wait for the peer's first beat so the test measures stale-detection
    # latency, not startup grace
    hb_dir = watchdog.heartbeat_dir(shared)
    deadline = time.time() + 60.0
    while watchdog.read_heartbeat(hb_dir, 0) is None:
        assert time.time() < deadline, "peer 0 never started heartbeating"
        time.sleep(0.05)

    from tpuddp.parallel.collectives import barrier  # noqa: E402

    barrier("chaos_rendezvous")  # hang@barrier fires here and never returns
    print("UNREACHABLE: hang fault did not fire", flush=True)
    sys.exit(1)

while True:  # healthy role: only the watchdog's exit(76) ends this process
    time.sleep(0.25)
