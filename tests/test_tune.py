"""The autotuning plane: advisor rule table, A/B probe arithmetic, the
TUNE_r*.json schema contract, the fleet tuner's apply/measure/revert state
machine, and the advisor-off identity guarantee.

The advisor tests craft run directories (history.jsonl / trace_*.json /
*.writer.json) with exactly the evidence each rule keys on — thresholds come
from the advisor's own module constants so the tests track the boundaries,
not copies of them.
"""

import json
import os

import pytest

from tpuddp import config as cfg_lib
from tpuddp.observability import advisor
from tpuddp.observability import schema
from tpuddp.tune import (
    FleetTuner,
    TunePolicy,
    endorsed_rules_from_report,
    probe,
)


# ------------------------------------------------------------ run builders --


def _write_history(run_dir, records):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "history.jsonl"), "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def _run_meta(**overrides):
    """A minimal-but-plausible v12 training header the advisor reads."""
    meta = {
        "type": "run_meta",
        "schema_version": schema.SCHEMA_VERSION,
        "world_size": 4,
        "process_count": 1,
        "comm_hook": "bf16_ef",
        "comm_topology": "hierarchical",
        "pipeline": {"depth": 2, "host_workers": 2, "sync_readback": False},
        "scan_steps": 8,
        "comm": {"overlap": {"enabled": True, "segments": 2}},
        "snapshot": False,
        "tuning": None,
        "grad_comm_bytes_per_update": 0,
    }
    meta.update(overrides)
    return meta


def _epoch(samples_per_sec=100.0, epoch_time_s=10.0, host_stall_ms=0.0,
           step_time_ms_p50=5.0):
    return {
        "type": "epoch",
        "schema_version": schema.SCHEMA_VERSION,
        "samples_per_sec": samples_per_sec,
        "epoch_time_s": epoch_time_s,
        "host_stall_ms": host_stall_ms,
        "step_time_ms_p50": step_time_ms_p50,
    }


def _write_trace(run_dir, shares, total_us=100_000.0):
    """One trace artifact whose span durations realize ``shares`` of the
    traced step-phase time (dispatch/stage/readback/collective)."""
    events = []
    t = 0.0
    for cat, share in shares.items():
        dur = total_us * share
        events.append({"ph": "X", "cat": cat, "name": f"{cat}.0",
                       "ts": t, "dur": dur})
        t += dur
    payload = {"traceEvents": events, "tpuddp": {"dropped": 0}}
    with open(os.path.join(run_dir, "trace_r0.json"), "w") as f:
        json.dump(payload, f)


def _write_sidecar(run_dir, **stats):
    base = {"snapshots": 3, "skipped_queue_full": 0, "write_s": 0.01,
            "bytes": 4096, "mode": "async"}
    base.update(stats)
    with open(os.path.join(run_dir, "ckpt.writer.json"), "w") as f:
        json.dump(base, f)


def _clean_run(run_dir):
    """Healthy evidence: every rule's predicate is false."""
    _write_history(run_dir, [
        _run_meta(),
        _epoch(), _epoch(), _epoch(),
    ])
    _write_trace(run_dir, {"dispatch": 0.1, "stage": 0.3, "readback": 0.1,
                           "collective": 0.5})


# One builder per rule: arrange exactly the evidence that rule fires on
# (against an otherwise-clean run so only the targeted predicate is true).
def _arm_pipeline_sync(d):
    _write_history(d, [
        _run_meta(pipeline={"depth": 1, "host_workers": 0,
                            "sync_readback": True}),
        _epoch(host_stall_ms=3000.0),
    ])


def _arm_pipeline_stall(d):
    stall = advisor.HOST_STALL_SHARE_THRESHOLD + 0.1
    _write_history(d, [
        _run_meta(),
        _epoch(epoch_time_s=10.0, host_stall_ms=stall * 10.0 * 1000.0),
    ])


def _arm_span_readback(d):
    share = advisor.READBACK_SHARE_THRESHOLD + 0.1
    _write_history(d, [_run_meta(), _epoch()])
    _write_trace(d, {"dispatch": 0.1, "stage": 0.9 - share,
                     "readback": share})


def _arm_span_dispatch(d):
    share = advisor.DISPATCH_SHARE_THRESHOLD + 0.1
    _write_history(d, [_run_meta(scan_steps=1), _epoch()])
    _write_trace(d, {"dispatch": share, "stage": 0.9 - share,
                     "readback": 0.1})


def _arm_comm_hook(d):
    _write_history(d, [
        _run_meta(comm_hook="none",
                  grad_comm_bytes_per_update=advisor.COMM_BYTES_FLOOR * 64),
        _epoch(),
    ])


def _arm_comm_topology(d):
    _write_history(d, [
        _run_meta(comm_topology="flat", process_count=2, world_size=8,
                  grad_comm_bytes_inter_host=1 << 20),
        _epoch(),
    ])


def _arm_comm_overlap(d):
    _write_history(d, [
        _run_meta(comm={"overlap": {"enabled": False, "reason": "off"}}),
        _epoch(),
    ])


def _arm_snapshot_backlog(d):
    _write_history(d, [
        _run_meta(snapshot={"every_steps": 50, "inflight": 1}),
        _epoch(),
    ])
    _write_sidecar(d, skipped_queue_full=4)


def _arm_snapshot_cadence(d):
    _write_history(d, [
        _run_meta(snapshot={"every_steps": advisor.SNAPSHOT_HOT_EVERY_STEPS,
                            "inflight": 2}),
        _epoch(),
    ])
    _write_sidecar(d, write_s=1.5)


def _serving_window(**overrides):
    row = {
        "type": "serving_stats",
        "schema_version": schema.SCHEMA_VERSION,
        "batch_occupancy": 0.9,
        "queue_ms_p50": 1.0,
        "device_ms_p50": 5.0,
        "e2e_ms_p50": 7.0,
        "throughput_rps": 100.0,
        "shed": 0,
        "rejected": 0,
    }
    row.update(overrides)
    return row


def _arm_serving_linger(d):
    _write_history(d, [
        _run_meta(),
        _serving_window(batch_occupancy=advisor.OCCUPANCY_FLOOR - 0.1,
                        queue_ms_p50=20.0, device_ms_p50=4.0,
                        e2e_ms_p50=25.0),
    ])


def _arm_serving_shed(d):
    _write_history(d, [_run_meta(), _serving_window(shed=7)])


def _arm_decode_kv(d):
    _write_history(d, [
        _run_meta(),
        {
            "type": "decode_stats",
            "schema_version": schema.SCHEMA_VERSION,
            "tokens_per_sec": 50.0,
            "ttft_ms_p50": 10.0,
            "itl_ms_p50": 4.0,
            "itl_ms_p95": 20.0,
            "kv_occupancy": advisor.KV_PRESSURE_THRESHOLD + 0.05,
            "shed": 0,
            "failovers": 0,
        },
    ])


_RULE_BUILDERS = {
    "pipeline_sync_readback": _arm_pipeline_sync,
    "pipeline_host_stall_depth": _arm_pipeline_stall,
    "span_readback_share": _arm_span_readback,
    "span_dispatch_share": _arm_span_dispatch,
    "comm_hook_uncompressed": _arm_comm_hook,
    "comm_topology_flat_multihost": _arm_comm_topology,
    "comm_overlap_disabled": _arm_comm_overlap,
    "snapshot_writer_backlog": _arm_snapshot_backlog,
    "snapshot_cadence_hot": _arm_snapshot_cadence,
    "serving_low_occupancy_linger": _arm_serving_linger,
    "serving_shed_pressure": _arm_serving_shed,
    "decode_kv_pressure": _arm_decode_kv,
}


# --------------------------------------------------------------- the rules --


def test_rule_table_is_fully_covered():
    assert {rid for rid, _, _, _ in advisor.RULES} == set(_RULE_BUILDERS)


@pytest.mark.parametrize("rule_id", sorted(_RULE_BUILDERS))
def test_every_rule_fires_on_crafted_evidence(tmp_path, rule_id):
    d = str(tmp_path / rule_id)
    os.makedirs(d)
    _RULE_BUILDERS[rule_id](d)
    report = advisor.advise(d)
    by_rule = {r["rule"]: r for r in report["recommendations"]}
    assert rule_id in by_rule, (
        f"{rule_id} did not fire; got {sorted(by_rule)}; "
        f"insufficient={report['insufficient']}"
    )
    rec = by_rule[rule_id]
    assert rec["rule_class"] in advisor.RULE_CLASSES
    assert rec["predicted_delta_pct"] > 0
    assert isinstance(rec["diff"], dict) and rec["diff"]
    assert rec["evidence"], "a recommendation must cite its evidence"
    for c in rec["evidence"]:
        assert set(c) == {"source", "field", "value"}


def test_clean_run_yields_no_recommendations(tmp_path):
    d = str(tmp_path / "clean")
    _clean_run(d)
    report = advisor.advise(d)
    assert report["recommendations"] == []
    # with a trace present, even the span rules had their evidence and
    # declined — nothing lands in insufficient either
    assert report["insufficient"] == []


def test_traceless_history_degrades_gracefully(tmp_path):
    """A v11-era history (no trace artifact) still runs the metric rules;
    the span rules report insufficient_evidence instead of guessing."""
    d = str(tmp_path / "v11")
    _write_history(d, [
        _run_meta(schema_version=11, comm_hook="none",
                  grad_comm_bytes_per_update=1 << 20),
        _epoch(),
    ])
    meta_path = os.path.join(d, "history.jsonl")
    with open(meta_path) as f:
        head = json.loads(f.readline())
    head.pop("tuning", None)  # v11 headers predate the tuning key
    rest = open(meta_path).readlines()[1:]
    with open(meta_path, "w") as f:
        f.write(json.dumps(head) + "\n")
        f.writelines(rest)

    report = advisor.advise(d)
    fired = {r["rule"] for r in report["recommendations"]}
    assert "comm_hook_uncompressed" in fired
    missing = {m["rule"]: m for m in report["insufficient"]}
    assert set(missing) == {"span_readback_share", "span_dispatch_share"}
    for m in missing.values():
        assert m["needs"] == "trace"
        assert "insufficient_evidence" in m["reason"]


def test_overlay_from_merges_without_clobbering():
    recs = [
        {"section": "training", "diff": {"pipeline": {"depth": 4}}},
        {"section": "training", "diff": {"pipeline": True}},
        {"section": "training", "diff": {"scan_steps": 8}},
        {"section": "serving", "diff": {"batch_timeout_ms": 1}},
        {"section": "training", "diff": {"pipeline": {"host_workers": 4}}},
    ]
    overlay = advisor.overlay_from(recs)
    # a bare enable never erases a sibling rule's dict refinement
    assert overlay["training"]["pipeline"] == {"depth": 4, "host_workers": 4}
    assert overlay["training"]["scan_steps"] == 8
    assert overlay["serving"] == {"batch_timeout_ms": 1}


def test_pending_summary_top_recommendation(tmp_path):
    d = str(tmp_path / "pending")
    _arm_comm_hook(d)
    pending = advisor.pending_summary(d)
    assert pending is not None
    assert pending["rule"] == "comm_hook_uncompressed"
    assert pending["endorsed"] is False
    assert "comm_hook_uncompressed" in pending["pending_rules"]

    clean = str(tmp_path / "pending_clean")
    _clean_run(clean)
    assert advisor.pending_summary(clean) is None
    # and a nonexistent dir must never raise (crash-path contract)
    assert advisor.pending_summary(str(tmp_path / "nope")) is None


def test_measure_run_reads_train_metrics(tmp_path):
    d = str(tmp_path / "measure")
    _write_history(d, [
        _run_meta(grad_comm_bytes_per_update=2048),
        _epoch(samples_per_sec=100.0),
        _epoch(samples_per_sec=200.0),
    ])
    metrics = advisor.measure_run(d, mode="train")
    assert metrics["samples_per_sec"] == pytest.approx(150.0)
    assert metrics["grad_comm_bytes"] == 2048


# --------------------------------------------------------- probe arithmetic --


def test_delta_pct_sign_convention():
    # higher-better: raw relative change
    assert probe.delta_pct("samples_per_sec", 100.0, 150.0) == pytest.approx(50.0)
    assert probe.delta_pct("samples_per_sec", 100.0, 80.0) == pytest.approx(-20.0)
    # lower-better: the REDUCTION is the improvement
    assert probe.delta_pct("step_time_ms_p50", 10.0, 5.0) == pytest.approx(50.0)
    assert probe.delta_pct("grad_comm_bytes", 100.0, 150.0) == pytest.approx(-50.0)


def test_delta_pct_zero_baseline_and_unknowns():
    assert probe.delta_pct("shed", 0.0, 0.0) == 0.0
    assert probe.delta_pct("shed", 0.0, 3.0) == -100.0  # left zero: regression
    assert probe.delta_pct("samples_per_sec", 0.0, 3.0) == 100.0
    assert probe.delta_pct("shed", None, 3.0) is None
    assert probe.delta_pct("shed", 3.0, None) is None
    assert probe.delta_pct("not_a_metric", 1.0, 2.0) is None


def test_endorse_refuses_regressions_and_no_data():
    assert probe.endorse(5.0)
    assert probe.endorse(0.0)
    assert not probe.endorse(-0.1)
    assert not probe.endorse(None), "no data is not a pass"
    assert not probe.endorse(0.5, min_improvement_pct=1.0)


def _rec_fixture(metric="samples_per_sec"):
    return {
        "rule": "comm_hook_uncompressed",
        "rule_class": "comm",
        "section": "training",
        "knob": "comm_hook",
        "diff": {"comm_hook": "bf16_ef"},
        "metric": metric,
        "predicted_delta_pct": 50.0,
        "reason": "test",
        "evidence": [advisor.cite("history.jsonl#run_meta", "comm_hook", None)],
    }


def test_make_result_row_endorsement():
    rec = _rec_fixture()
    good = probe.make_result_row(rec, {"samples_per_sec": 100.0},
                                 {"samples_per_sec": 120.0})
    assert good["measured_delta_pct"] == pytest.approx(20.0)
    assert good["endorsed"] is True
    bad = probe.make_result_row(rec, {"samples_per_sec": 100.0},
                                {"samples_per_sec": 90.0})
    assert bad["endorsed"] is False
    unmeasured = probe.make_result_row(rec, {}, {})
    assert unmeasured["measured_delta_pct"] is None
    assert unmeasured["endorsed"] is False


def test_build_tune_report_round_trips_validation():
    rec = _rec_fixture()
    row = probe.make_result_row(rec, {"samples_per_sec": 100.0},
                                {"samples_per_sec": 120.0})
    payload = probe.build_tune_report(
        device="cpu", mode="train",
        baseline_metrics={"samples_per_sec": 100.0}, results=[row],
    )
    assert payload["type"] == "tune_report"
    assert payload["schema_version"] == schema.SCHEMA_VERSION
    assert schema.validate_tune_payload(payload) == []


def test_build_tune_report_refuses_endorsed_regression():
    rec = _rec_fixture()
    row = probe.make_result_row(rec, {"samples_per_sec": 100.0},
                                {"samples_per_sec": 90.0})
    row["endorsed"] = True  # forge the verdict the probe refused to give
    with pytest.raises(ValueError, match="refus"):
        probe.build_tune_report(
            device="cpu", mode="train",
            baseline_metrics={"samples_per_sec": 100.0}, results=[row],
        )


def test_next_tune_path_numbers_the_artifact_family(tmp_path):
    root = str(tmp_path)
    assert probe.next_tune_path(root).endswith("TUNE_r01.json")
    open(os.path.join(root, "TUNE_r01.json"), "w").close()
    open(os.path.join(root, "TUNE_r07.json"), "w").close()
    assert probe.next_tune_path(root).endswith("TUNE_r08.json")


# ------------------------------------------------------------- schema v12 --


def test_validate_tune_payload_field_contract():
    errors = schema.validate_tune_payload({"type": "tune_report"})
    assert any("schema_version" in e for e in errors)
    assert any("'results'" in e or "results" in e for e in errors)

    payload = {
        "type": "tune_report", "schema_version": 12, "device": "cpu",
        "mode": "train", "baseline_metrics": {},
        "results": [{
            "rule": "x", "rule_class": "comm", "knob": "k", "diff": {},
            "metric": "m", "predicted_delta_pct": 1.0,
            "measured_delta_pct": -4.0, "endorsed": True, "evidence": [],
        }],
    }
    errors = schema.validate_tune_payload(payload)
    assert any("endorsed=true" in e and "regress" in e for e in errors)
    payload["results"][0]["endorsed"] = False
    assert schema.validate_tune_payload(payload) == []
    payload["mode"] = "decode"
    assert any("mode" in e for e in schema.validate_tune_payload(payload))


def test_run_meta_requires_tuning_key_at_v12():
    meta = schema.make_run_meta(world_size=4)
    assert "tuning" in meta and meta["tuning"] is None
    assert schema.validate_record(meta) == []

    stripped = dict(meta)
    del stripped["tuning"]
    assert any("tuning" in e for e in schema.validate_record(stripped))

    # an older header that predates the key keeps validating under this
    # reader — requirements apply at the version a record CARRIES
    stripped["schema_version"] = 11
    assert not any("tuning" in e for e in schema.validate_record(stripped))


def test_run_meta_carries_tuning_provenance():
    prov = {"source": "fleet", "rule": "comm_hook_uncompressed",
            "generation": 2, "applied": {"training": {"comm_hook": "bf16_ef"}},
            "section": "training"}
    meta = schema.make_run_meta(world_size=4, tuning=prov)
    assert meta["tuning"] == prov
    assert schema.validate_record(meta) == []


# -------------------------------------------------------------- fleet tuner --


def _fake_edges(rec, epoch_rows):
    """Injectable advise/reader pair: a fixed recommendation + a mutable
    list of history rows (append to simulate the job's live stream)."""
    def fake_advise(run_dir):
        return {"recommendations": [dict(rec)] if rec else [],
                "insufficient": []}

    def fake_reader(run_dir):
        return list(epoch_rows)

    return fake_advise, fake_reader


def _epoch_row(sps):
    return {"type": "epoch", "samples_per_sec": sps}


def _make_tuner(rec, rows, endorsed=None, **policy):
    policy.setdefault("cooldown_s", 0.0)
    policy.setdefault("baseline_rows", 2)
    policy.setdefault("measure_rows", 2)
    fake_advise, fake_reader = _fake_edges(rec, rows)
    return FleetTuner(
        TunePolicy(**policy),
        endorsed_rules=endorsed,
        advise=fake_advise,
        reader=fake_reader,
    )


def test_fleet_tuner_apply_measure_keep(tmp_path):
    run_dir = str(tmp_path / "job")
    os.makedirs(run_dir)
    rec = _rec_fixture()
    rows = [_epoch_row(100.0), _epoch_row(100.0)]
    tuner = _make_tuner(rec, rows, endorsed={rec["rule"]})

    decision = tuner.observe_and_decide("job", "training", run_dir, now=0.0)
    assert decision["action"] == "apply"
    assert decision["generation"] == 1
    assert decision["baseline_value"] == pytest.approx(100.0)
    env = decision["overlay_env"]
    assert env["source"] == "fleet"
    assert env["rule"] == rec["rule"]
    assert env["training"] == {"comm_hook": "bf16_ef"}
    tuner.mark_applied("job", run_dir, decision, now=0.0)
    assert tuner.counters["applied"] == 1

    # not enough post-change rows yet: the tuner waits, makes no new move
    rows.append(_epoch_row(130.0))
    assert tuner.observe_and_decide("job", "training", run_dir, 1.0) is None

    rows.append(_epoch_row(130.0))
    verdict = tuner.observe_and_decide("job", "training", run_dir, 2.0)
    assert verdict["action"] == "keep"
    assert verdict["measured_delta_pct"] == pytest.approx(30.0)
    assert verdict["overlay_env"] is None, "keep = no drain"
    tuner.mark_applied("job", run_dir, verdict, now=2.0)
    assert tuner.counters["kept"] == 1

    # the kept rule is never re-proposed on this job
    assert tuner.observe_and_decide("job", "training", run_dir, 100.0) is None

    # typed audit: both actions landed as tune_action events in the history
    with open(os.path.join(run_dir, "history.jsonl")) as f:
        events = [json.loads(line) for line in f]
    assert [e["action"] for e in events] == ["apply", "keep"]
    for e in events:
        assert e["type"] == "event" and e["event"] == "tune_action"
        assert e["rule"] == rec["rule"]
        assert schema.validate_record(e) == []


def test_fleet_tuner_reverts_on_regression(tmp_path):
    run_dir = str(tmp_path / "job")
    os.makedirs(run_dir)
    rec = _rec_fixture()
    rows = [_epoch_row(100.0), _epoch_row(100.0)]
    tuner = _make_tuner(rec, rows, endorsed={rec["rule"]})

    decision = tuner.observe_and_decide("job", "training", run_dir, 0.0)
    assert decision["action"] == "apply"
    tuner.mark_applied("job", run_dir, decision, 0.0)

    rows += [_epoch_row(80.0), _epoch_row(80.0)]  # injected regression
    verdict = tuner.observe_and_decide("job", "training", run_dir, 1.0)
    assert verdict["action"] == "revert"
    assert verdict["measured_delta_pct"] == pytest.approx(-20.0)
    # nothing was kept before this apply: revert clears the overlay entirely
    assert verdict["overlay_env"] is None
    tuner.mark_applied("job", run_dir, verdict, 1.0)
    assert tuner.counters["reverted"] == 1

    # the refuted rule is never retried on this job (cooldown is 0)
    assert tuner.observe_and_decide("job", "training", run_dir, 50.0) is None

    with open(os.path.join(run_dir, "history.jsonl")) as f:
        actions = [json.loads(line)["action"] for line in f]
    assert actions == ["apply", "revert"]


def test_fleet_tuner_revert_restores_kept_overlay(tmp_path):
    """A regression on change N rolls back to the overlay kept after
    change N-1, not to bare defaults."""
    run_dir = str(tmp_path / "job")
    os.makedirs(run_dir)
    rec_a = _rec_fixture()
    rows = [_epoch_row(100.0), _epoch_row(100.0)]
    tuner = _make_tuner(rec_a, rows, endorsed=None)  # trust-advisor mode

    d1 = tuner.observe_and_decide("job", "training", run_dir, 0.0)
    tuner.mark_applied("job", run_dir, d1, 0.0)
    rows += [_epoch_row(150.0), _epoch_row(150.0)]
    keep = tuner.observe_and_decide("job", "training", run_dir, 1.0)
    assert keep["action"] == "keep"
    tuner.mark_applied("job", run_dir, keep, 1.0)

    # second rule proposed; its overlay stacks on the kept one
    rec_b = dict(_rec_fixture(), rule="span_dispatch_share",
                 rule_class="pipeline", knob="scan_steps",
                 diff={"scan_steps": 16})
    tuner.advise, tuner.reader = _fake_edges(rec_b, rows)
    d2 = tuner.observe_and_decide("job", "training", run_dir, 2.0)
    assert d2["action"] == "apply" and d2["generation"] == 2
    assert d2["overlay_env"]["training"] == {
        "comm_hook": "bf16_ef", "scan_steps": 16,
    }
    tuner.mark_applied("job", run_dir, d2, 2.0)

    rows += [_epoch_row(60.0), _epoch_row(60.0)]
    tuner.advise, tuner.reader = _fake_edges(rec_b, rows)
    verdict = tuner.observe_and_decide("job", "training", run_dir, 3.0)
    assert verdict["action"] == "revert"
    # the restore target is the kept generation-1 overlay
    assert verdict["overlay_env"]["training"] == {"comm_hook": "bf16_ef"}


def test_fleet_tuner_endorsement_gating(tmp_path):
    run_dir = str(tmp_path / "job")
    os.makedirs(run_dir)
    rec = _rec_fixture()
    rows = [_epoch_row(100.0), _epoch_row(100.0)]

    inert = _make_tuner(rec, rows, endorsed=set())
    assert inert.observe_and_decide("job", "training", run_dir, 0.0) is None

    trusting = _make_tuner(rec, rows, endorsed=None)
    assert trusting.observe_and_decide(
        "job", "training", run_dir, 0.0
    )["action"] == "apply"


def test_fleet_tuner_respects_cooldown_and_prediction_floor(tmp_path):
    run_dir = str(tmp_path / "job")
    os.makedirs(run_dir)
    rows = [_epoch_row(100.0), _epoch_row(100.0)]

    weak = dict(_rec_fixture(), predicted_delta_pct=0.5)
    floor = _make_tuner(weak, rows, endorsed=None, min_improvement_pct=1.0)
    assert floor.observe_and_decide("job", "training", run_dir, 0.0) is None

    rec = _rec_fixture()
    tuner = _make_tuner(rec, rows, endorsed=None, cooldown_s=300.0)
    d = tuner.observe_and_decide("job", "training", run_dir, 0.0)
    tuner.mark_applied("job", run_dir, d, 0.0)
    rows += [_epoch_row(150.0), _epoch_row(150.0)]
    keep = tuner.observe_and_decide("job", "training", run_dir, 10.0)
    tuner.mark_applied("job", run_dir, keep, 10.0)
    # inside the cooldown window nothing new is proposed; after it, idle
    # decisions are possible again (here: same rule, already kept -> None,
    # but the cooldown gate itself must be what blocks at t=20)
    assert not tuner._cooled("job", 20.0)
    assert tuner._cooled("job", 311.0)


def test_fleet_tuner_needs_a_baseline(tmp_path):
    run_dir = str(tmp_path / "job")
    os.makedirs(run_dir)
    tuner = _make_tuner(_rec_fixture(), [], endorsed=None)
    assert tuner.observe_and_decide("job", "training", run_dir, 0.0) is None
    assert tuner.counters["applied"] == 0


def test_fleet_tuner_export_source_shape(tmp_path):
    run_dir = str(tmp_path / "job")
    os.makedirs(run_dir)
    rows = [_epoch_row(100.0), _epoch_row(100.0)]
    tuner = _make_tuner(_rec_fixture(), rows, endorsed=None)
    d = tuner.observe_and_decide("job", "training", run_dir, 0.0)
    tuner.mark_applied("job", run_dir, d, 0.0)

    series = tuner.export_source()
    assert series["tpuddp_tune_applied_total"] == {
        "type": "counter",
        "help": series["tpuddp_tune_applied_total"]["help"],
        "value": 1,
    }
    assert series["tpuddp_tune_reverted_total"]["value"] == 0
    assert series["tpuddp_tune_kept_total"]["value"] == 0
    assert series["tpuddp_tune_measuring"]["type"] == "gauge"
    assert series["tpuddp_tune_measuring"]["value"] == 1


def test_endorsed_rules_from_report(tmp_path):
    path = str(tmp_path / "TUNE_r01.json")
    with open(path, "w") as f:
        json.dump({"type": "tune_report", "results": [
            {"rule": "a", "endorsed": True},
            {"rule": "b", "endorsed": False},
            {"rule": "c", "endorsed": True},
            {"endorsed": True},  # no rule name: ignored
        ]}, f)
    assert endorsed_rules_from_report(path) == {"a", "c"}
    assert endorsed_rules_from_report(str(tmp_path / "missing.json")) == set()


# ------------------------------------------------- overlay + off-identity --


def test_tune_overlay_env_resolves_into_config(monkeypatch):
    overlay = {"source": "advisor", "rule": "comm_hook_uncompressed",
               "generation": 1,
               "training": {"comm_hook": "bf16_ef", "scan_steps": 16}}
    monkeypatch.setenv(cfg_lib.TUNE_OVERLAY_ENV, json.dumps(overlay))
    cfg = cfg_lib.training_config({"training": {"num_epochs": 3}})
    assert cfg["comm_hook"] == "bf16_ef"
    assert cfg["scan_steps"] == 16
    assert cfg["num_epochs"] == 3  # settings survive around the overlay

    prov = cfg_lib.tuning_provenance_from_env()
    assert prov["source"] == "advisor"
    assert prov["rule"] == "comm_hook_uncompressed"
    assert prov["generation"] == 1
    assert prov["applied"]["training"] == {"comm_hook": "bf16_ef",
                                           "scan_steps": 16}


def test_tune_overlay_refuses_unknown_knobs(monkeypatch):
    monkeypatch.setenv(cfg_lib.TUNE_OVERLAY_ENV, json.dumps(
        {"training": {"not_a_knob": 1}}
    ))
    with pytest.raises(ValueError, match="not_a_knob"):
        cfg_lib.training_config({})
    monkeypatch.setenv(cfg_lib.TUNE_OVERLAY_ENV, "{not json")
    with pytest.raises(ValueError):
        cfg_lib.training_config({})


def test_advisor_off_identity(monkeypatch):
    """With no overlay armed the tuning plane is invisible: configs resolve
    identically to a build that never had it, and provenance is None."""
    monkeypatch.delenv(cfg_lib.TUNE_OVERLAY_ENV, raising=False)
    settings = {"training": {"num_epochs": 3, "scan_steps": 4}}
    cfg = cfg_lib.training_config(settings)
    untouched, prov = cfg_lib.apply_tune_overlay(dict(cfg), section="training")
    assert untouched == cfg
    assert prov is None
    assert cfg_lib.tuning_provenance_from_env() is None
    assert cfg_lib.tuning_provenance_from_env("serving") is None
    # and a run_meta built off that provenance carries tuning: null
    assert schema.make_run_meta(world_size=4, tuning=None)["tuning"] is None
