"""Regression tests for the driver entry hooks (``__graft_entry__.py``).

Round-1 lesson: the driver's multi-chip dryrun failed because unplaced
allocations routed to the attached (transiently sick) TPU tunnel instead of
the virtual CPU mesh. These tests run the hooks the way the driver does — in
a subprocess with the session's environment (TPU tunnel included) left
intact — so a hermeticity regression fails here, not at driver time.
"""

from __future__ import annotations

import os
import subprocess
import sys

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _driver_env(n: int) -> dict:
    """The driver's env: virtual host devices forced, platform NOT forced.

    Drop the conftest's CPU-forcing vars so the subprocess sees the session
    default (any TPU tunnel and all); keep only the host-device split the
    driver also sets.
    """
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("TPUDDP_BACKEND", None)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


@pytest.mark.slow
def test_dryrun_multichip_under_driver_env():
    proc = subprocess.run(
        [sys.executable, "-c", "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=REPO,
        env=_driver_env(8),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, (
        f"dryrun_multichip(8) failed under driver env\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "dryrun_multichip ok: 8 devices" in proc.stdout


@pytest.mark.slow
def test_entry_lowers_and_compiles():
    import __graft_entry__ as g

    fn, args = g.entry()
    compiled = jax.jit(fn).lower(*args).compile()
    assert compiled is not None
