"""Model zoo structure checks — param counts must equal the reference stack's
torchvision architectures (same topology, NHWC layout)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import nn
from tpuddp.models import AlexNet, ResNet18, ToyCNN, ToyMLP, load_model
from tpuddp.models.alexnet import replace_head
from tpuddp.nn.core import Context

KEY = jax.random.key(0)


def n_params(tree):
    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(tree))


def test_registry():
    assert isinstance(load_model("toy_mlp"), nn.Sequential)
    with pytest.raises(ValueError):
        load_model("vgg")


def test_toy_models_forward():
    x = jnp.zeros((2, 32, 32, 3))
    for model in (ToyMLP(), ToyCNN()):
        params, state = model.init(KEY, x)
        y, _ = model.apply(params, state, x, Context())
        assert y.shape == (2, 10)


# torchvision isn't in this image, so the oracles are the published
# architecture parameter counts: AlexNet(1000) = 61,100,840 and
# ResNet-18(1000) = 11,689,512, adjusted for the 10-way head swap the
# reference performs (data_and_toy_model.py:43-44).
ALEXNET_10_PARAMS = 61_100_840 - (4096 * 1000 + 1000) + (4096 * 10 + 10)
RESNET18_10_PARAMS = 11_689_512 - (512 * 1000 + 1000) + (512 * 10 + 10)


@pytest.mark.slow
def test_alexnet_matches_torchvision_param_count():
    """Same topology as the reference's load_model() output
    (data_and_toy_model.py:41-45): torchvision AlexNet with a 10-way head."""
    model = AlexNet(num_classes=10)
    params, state = model.init(KEY, jnp.zeros((1, 224, 224, 3)))
    assert n_params(params) == ALEXNET_10_PARAMS

    y, _ = model.apply(params, state, jnp.zeros((2, 224, 224, 3)), Context())
    assert y.shape == (2, 10)


@pytest.mark.slow
def test_resnet18_matches_torchvision_param_count():
    # BN running stats are buffers (model_state), not params — like torch.
    model = ResNet18(num_classes=10)
    params, state = model.init(KEY, jnp.zeros((1, 64, 64, 3)))
    assert n_params(params) == RESNET18_10_PARAMS

    y, _ = model.apply(params, state, jnp.zeros((2, 64, 64, 3)), Context())
    assert y.shape == (2, 10)


def test_resnet18_small_input_stem():
    model = ResNet18(num_classes=10, small_input=True)
    params, state = model.init(KEY, jnp.zeros((1, 32, 32, 3)))
    y, new_state = model.apply(
        params, state, jnp.ones((2, 32, 32, 3)), Context(train=True)
    )
    assert y.shape == (2, 10)
    # BN buffers update in train mode somewhere in the tree
    leaves_before = jax.tree_util.tree_leaves(state)
    leaves_after = jax.tree_util.tree_leaves(new_state)
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(leaves_before, leaves_after)
    )


def test_resnet_sync_bn_conversion():
    model = ResNet18(num_classes=10)
    nn.convert_sync_batchnorm(model)
    # stem BN + every block's BNs flipped
    assert model[1].sync is True
    block = model[4]
    assert block.bn1.sync and block.bn2.sync and block.down_bn.sync


def test_alexnet_replace_head():
    model = AlexNet(num_classes=10)
    params, state = model.init(KEY, jnp.zeros((1, 63, 63, 3)))
    params = list(params)
    new_params = replace_head(model, params, jax.random.key(1), num_classes=7)
    y, _ = model.apply(new_params, state, jnp.zeros((1, 63, 63, 3)), Context())
    assert y.shape == (1, 7)


def test_alexnet_dropout_only_in_train():
    model = AlexNet(num_classes=10, dropout=0.9)
    params, state = model.init(KEY, jnp.zeros((1, 63, 63, 3)))
    x = jnp.ones((1, 63, 63, 3))
    y1, _ = model.apply(params, state, x, Context(train=False))
    y2, _ = model.apply(params, state, x, Context(train=False))
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))  # deterministic eval
    t1, _ = model.apply(params, state, x, Context(train=True, rng=jax.random.key(1)))
    t2, _ = model.apply(params, state, x, Context(train=True, rng=jax.random.key(2)))
    assert not np.allclose(np.asarray(t1), np.asarray(t2))  # stochastic train


def test_resnet34_shapes_and_param_count():
    """ResNet-34: [3,4,6,3] BasicBlocks; torchvision resnet34 has 21.28M
    params at 1000 classes — ours at 10 classes should land at the same
    count minus the head difference."""
    import jax
    import jax.numpy as jnp

    from tpuddp.models import ResNet34
    from tpuddp.nn.core import Context

    model = ResNet34(num_classes=10, small_input=True)
    params, state = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params)
    )
    # torchvision resnet34: 21,797,672 at 1000 classes; minus its head
    # (512*1000+1000), minus the small-input stem delta (7x7x3x64 ->
    # 3x3x3x64 = -7,680), plus our 10-class head (512*10+10)
    assert n_params == 21797672 - 513000 - 7680 + 5130, n_params
    y, _ = model.apply(params, state, jnp.zeros((2, 32, 32, 3)), Context(train=False))
    assert y.shape == (2, 10)


def test_resnet34_registry_and_sync_bn():
    from tpuddp.models import load_model
    from tpuddp.nn.norm import has_divergent_buffers

    m = load_model("resnet34_small", 10, sync_bn=True)
    assert not has_divergent_buffers(m)  # every BN is synced


def test_space_to_depth_stem_is_exact():
    """nn.SpaceToDepthConv2d == nn.Conv2d bit-for-reassociation: same params,
    same forward output and same parameter gradients on the AlexNet stem
    shape (11x11/s4/p2 on 3 channels), plus a non-square odd-size case."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpuddp import nn
    from tpuddp.nn.core import Context

    for (h, w), k, s, p in [((224, 224), 11, 4, 2), ((67, 93), 7, 2, 3)]:
        ref = nn.Conv2d(16, kernel_size=k, strides=s, padding=p)
        s2d = nn.SpaceToDepthConv2d(16, kernel_size=k, strides=s, padding=p)
        x = jnp.asarray(
            np.random.RandomState(0).randn(2, h, w, 3).astype(np.float32)
        )
        params, _ = ref.init(jax.random.key(0), x)

        y_ref, _ = ref.apply(params, (), x, Context())
        y_s2d, _ = s2d.apply(params, (), x, Context())
        assert y_ref.shape == y_s2d.shape
        np.testing.assert_allclose(
            np.asarray(y_ref), np.asarray(y_s2d), rtol=1e-5, atol=1e-5
        )

        def loss(mod):
            def f(p):
                y, _ = mod.apply(p, (), x, Context())
                return jnp.sum(y * y)
            return jax.grad(f)(params)

        g_ref, g_s2d = loss(ref), loss(s2d)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            ),
            g_ref, g_s2d,
        )


def test_alexnet_s2d_same_logits_and_registry():
    """AlexNet(space_to_depth=True) shares parameter trees with the vanilla
    model (checkpoints/imports interchangeable) and produces the same
    logits; the registry exposes it as alexnet_s2d."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpuddp.models import AlexNet, load_model
    from tpuddp.nn.core import Context

    vanilla = AlexNet(num_classes=10)
    s2d = load_model("alexnet_s2d", 10)
    x = jnp.asarray(
        np.random.RandomState(1).randn(2, 224, 224, 3).astype(np.float32)
    )
    params, state = vanilla.init(jax.random.key(0), x)
    p2, _ = s2d.init(jax.random.key(0), x)
    jax.tree_util.tree_map(  # identical tree structure AND shapes
        lambda a, b: (np.testing.assert_array_equal(np.shape(a), np.shape(b))),
        params, p2,
    )
    y1, _ = vanilla.apply(params, state, x, Context(train=False))
    y2, _ = s2d.apply(params, state, x, Context(train=False))
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-4, atol=1e-4)


def test_resnet_s2d_stem_same_logits():
    """ResNet's 7x7/s2 full stem under space_to_depth: same params, same
    eval-mode logits as the plain stem (exactness at the model level)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpuddp.models import ResNet18, load_model
    from tpuddp.nn.core import Context

    plain = ResNet18(num_classes=10)
    s2d = load_model("resnet18_s2d", 10)
    x = jnp.asarray(
        np.random.RandomState(2).randn(2, 96, 96, 3).astype(np.float32)
    )
    params, state = plain.init(jax.random.key(0), x)
    y1, _ = plain.apply(params, state, x, Context(train=False))
    y2, _ = s2d.apply(params, state, x, Context(train=False))
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-4, atol=1e-4)


def test_small_input_rejects_space_to_depth():
    import pytest

    from tpuddp.models import ResNet18

    with pytest.raises(ValueError, match="small_input"):
        ResNet18(small_input=True, space_to_depth=True)


@pytest.mark.slow
def test_space_to_depth_fuzz_matches_conv2d():
    """Property check over random geometries: SpaceToDepthConv2d == Conv2d
    for any (k, s, p, h, w) it accepts — the padding/blocking arithmetic must
    hold everywhere, not just the stems we ship."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpuddp import nn
    from tpuddp.nn.core import Context

    rs = np.random.RandomState(42)
    for trial in range(12):
        s = int(rs.randint(2, 5))
        k = int(rs.randint(1, 12))
        p = int(rs.randint(0, k + 2))
        # bounds guarantee at least one output window per dim
        h = int(rs.randint(max(k - p, s), 40))
        w = int(rs.randint(max(k - p, s), 40))
        c = int(rs.choice([1, 3, 5]))
        ref = nn.Conv2d(8, kernel_size=k, strides=s, padding=p)
        s2d = nn.SpaceToDepthConv2d(8, kernel_size=k, strides=s, padding=p)
        x = jnp.asarray(rs.randn(2, h, w, c).astype(np.float32))
        params, _ = ref.init(jax.random.key(trial), x)
        y_ref, _ = ref.apply(params, (), x, Context())
        y_s2d, _ = s2d.apply(params, (), x, Context())
        assert y_ref.shape == y_s2d.shape, (trial, k, s, p, h, w, c)
        np.testing.assert_allclose(
            np.asarray(y_ref), np.asarray(y_s2d), rtol=1e-4, atol=1e-4,
            err_msg=f"trial {trial}: k={k} s={s} p={p} h={h} w={w} c={c}",
        )
