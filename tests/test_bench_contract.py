"""bench.py's driver-parseable output contract (VERDICT r5: the artifact's
``parsed`` field was null because the full results dict was the stdout line).

The contract: the FULL per-config payload lands in ``bench_results.json``;
the LAST stdout line is one compact JSON summary carrying the headline
toy-MLP number. Pinned here without running the (TPU-scale) benchmarks by
driving :func:`bench.emit_summary` directly."""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import bench  # noqa: E402


def test_summary_line_parses_and_carries_headline(tmp_path, monkeypatch):
    monkeypatch.setitem(bench.RESULTS, "toy_mlp f32 (scan-fused K=200)", {
        "samples_per_sec_per_chip": 1234567.8,
        "ms_per_step": 0.1,
        "mfu": None,
        "grad_comm_bytes_per_step": 1577248,
    })
    out = tmp_path / "bench_results.json"
    summary = bench.emit_summary(1234567.8, 1000.0, out_path=str(out))

    # exactly what main() prints as the last stdout line: it must survive a
    # strict json.loads round trip and stay compact (no per-config payload)
    line = json.dumps(summary)
    parsed = json.loads(line)
    assert parsed["metric"] == "toy_mlp_train_samples_per_sec_per_chip"
    assert parsed["value"] == 1234567.8
    assert parsed["unit"] == "samples/sec/chip"
    assert parsed["vs_baseline"] == 1234.57
    assert parsed["n_configs"] >= 1
    assert parsed["results_file"] == "bench_results.json"
    assert "configs" not in parsed
    assert "\n" not in line

    # the full payload (with per-config rows) round-trips from the file
    payload = json.loads(out.read_text())
    row = payload["configs"]["toy_mlp f32 (scan-fused K=200)"]
    assert row["grad_comm_bytes_per_step"] == 1577248
    assert payload["value"] == parsed["value"]


def test_summary_without_baseline(tmp_path):
    bench.RESULTS.clear()
    summary = bench.emit_summary(10.0, None, out_path=str(tmp_path / "r.json"))
    assert summary["vs_baseline"] == 1.0  # torch missing -> neutral ratio


def test_nonfinite_row_values_serialize_as_strict_json_null(tmp_path):
    """ISSUE 3 satellite: a failed/blown-up config row (NaN/Inf values) must
    land in bench_results.json as ``null`` — never the bare ``NaN`` token
    Python's default json.dump emits, which strict parsers reject. Pinned as
    a full round trip through a parser that refuses non-finite constants."""
    bench.RESULTS.clear()
    bench.RESULTS["exploded f32 (diverged)"] = {
        "samples_per_sec_per_chip": float("nan"),
        "ms_per_step": float("inf"),
        "mfu": None,
    }
    out = tmp_path / "bench_results.json"
    bench.emit_summary(123.0, 10.0, out_path=str(out))
    raw = out.read_text()
    assert "NaN" not in raw and "Infinity" not in raw

    def reject(tok):
        raise AssertionError(f"non-strict JSON token {tok!r} in bench_results.json")

    payload = json.loads(raw, parse_constant=reject)
    row = payload["configs"]["exploded f32 (diverged)"]
    assert row["samples_per_sec_per_chip"] is None
    assert row["ms_per_step"] is None
    assert row["mfu"] is None
    bench.RESULTS.clear()
