"""Native gradient accumulation (DistributedDataParallel(grad_accumulation=A)):
one optimizer update per A micro-batches, fused into the scan step.

The defining property: a cycle of A micro-batches produces EXACTLY the update
of one step over their concatenation (the n-weighted gradient average), so the
equivalence oracle is the plain step at A-times the batch size. The managed
path's gradient_accumulation_steps has its own tests (test_accelerate.py);
here the two knobs' trajectories are also cross-checked.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import optim
from tpuddp.data import SyntheticClassification
from tpuddp.models import ToyCNN, ToyMLP
from tpuddp.nn import CrossEntropyLoss
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.training.step import stack_batches

KEY = jax.random.key(3)


def make_batches(k, n=16, shape=(8, 8, 3), seed=0):
    ds = SyntheticClassification(n=n * k, shape=shape, seed=seed)
    return [
        (
            ds.images[i * n : (i + 1) * n],
            ds.labels[i * n : (i + 1) * n],
            np.ones(n, np.float32),
        )
        for i in range(k)
    ]


def _leaves_allclose(a, b, atol):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if jax.dtypes.issubdtype(np.asarray(x).dtype, jax.dtypes.prng_key):
            continue
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol)


@pytest.mark.parametrize("mode", ["shard_map", "auto"])
@pytest.mark.parametrize("wus", [False, True])
def test_accum_cycle_equals_concatenated_batch(cpu_devices, mode, wus):
    """A=4 over 4 micro-batches of 16 == 1 plain step over the 64-batch, to
    float tolerance (identical math modulo reduction order). SGD keeps the
    comparison free of adaptive-state amplification."""
    if wus and mode != "shard_map":
        pytest.skip("wus is shard_map-only")
    mesh = make_mesh(cpu_devices)
    batches = make_batches(4)
    model = ToyMLP()

    def fresh(accum):
        ddp = DistributedDataParallel(
            model, optim.SGD(1e-1), CrossEntropyLoss(), mesh=mesh, mode=mode,
            grad_accumulation=accum, weight_update_sharding=wus,
        )
        return ddp, ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))

    acc_ddp, acc_state = fresh(4)
    acc_state, acc_m = acc_ddp.train_step_many(
        acc_state, acc_ddp.shard_stacked(stack_batches(batches))
    )

    big_ddp, big_state = fresh(1)
    xs = np.concatenate([b[0] for b in batches])
    ys = np.concatenate([b[1] for b in batches])
    ws = np.concatenate([b[2] for b in batches])
    big_state, big_m = big_ddp.train_step(big_state, big_ddp.shard((xs, ys, ws)))

    _leaves_allclose(acc_state.params, big_state.params, atol=1e-5)
    # metric totals: loss_sum over micro-batches == weighted loss of the
    # concatenation (same per-sample losses on step 0's identical params)
    assert np.isclose(
        float(np.sum(np.asarray(acc_m["loss_sum"]))),
        float(np.sum(np.asarray(big_m["loss_sum"]))),
        atol=1e-4,
    )
    assert float(np.sum(np.asarray(acc_m["n"]))) == 64.0


def test_accum_exact_for_fractional_sample_weights(cpu_devices):
    """The cycle divisor is the exact weight sum (jnp.where, not
    jnp.maximum): fractional per-sample weights — importance weighting, not
    just 0/1 padding masks — must still reproduce the concatenated batch."""
    mesh = make_mesh(cpu_devices)
    rng = np.random.RandomState(9)
    batches = []
    for i in range(2):
        x = rng.randn(16, 8, 8, 3).astype(np.float32)
        y = rng.randint(0, 10, 16)
        w = rng.uniform(0.05, 0.6, 16).astype(np.float32)  # sums < 16
        batches.append((x, y, w))
    model = ToyMLP()

    a_ddp = DistributedDataParallel(
        model, optim.SGD(1e-1), CrossEntropyLoss(), mesh=mesh,
        grad_accumulation=2,
    )
    a_state = a_ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    a_state, _ = a_ddp.train_step_many(
        a_state, a_ddp.shard_stacked(stack_batches(batches))
    )

    b_ddp = DistributedDataParallel(
        model, optim.SGD(1e-1), CrossEntropyLoss(), mesh=mesh
    )
    b_state = b_ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    # The equivalence oracle must hand each replica the SAME samples the
    # accumulation path gives it: replica r sees rows [2r:2r+2] of every
    # micro-batch, so the concatenated batch is interleaved per replica
    # (with non-uniform weights, pmean of per-replica weighted means is NOT
    # invariant to the replica-to-sample assignment — a DDP semantic torch
    # shares, not an accumulation artifact).
    per_replica = 16 // 8

    def interleave(i):
        return np.concatenate([
            np.concatenate([b[i][r * per_replica : (r + 1) * per_replica] for b in batches])
            for r in range(8)
        ])

    cat = (interleave(0), interleave(1), interleave(2))
    b_state, _ = b_ddp.train_step(b_state, b_ddp.shard(cat))

    _leaves_allclose(a_state.params, b_state.params, atol=1e-5)


def test_accum_trajectory_multiple_cycles_adam(cpu_devices):
    """2 cycles of A=2 (scan K=4) track 2 plain Adam steps at doubled batch.
    ToyMLP: BatchNorm models are deliberately excluded — normalizing each
    micro-batch with its OWN statistics makes accumulation inequivalent to the
    concatenated batch (inherent to BN; torch behaves identically)."""
    mesh = make_mesh(cpu_devices)
    batches = make_batches(4, n=16, seed=1)
    model = ToyMLP()

    a_ddp = DistributedDataParallel(
        model, optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh,
        grad_accumulation=2,
    )
    a_state = a_ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    a_state, _ = a_ddp.train_step_many(
        a_state, a_ddp.shard_stacked(stack_batches(batches))
    )

    b_ddp = DistributedDataParallel(
        model, optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh
    )
    b_state = b_ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    for i in range(2):
        x = np.concatenate([batches[2 * i][0], batches[2 * i + 1][0]])
        y = np.concatenate([batches[2 * i][1], batches[2 * i + 1][1]])
        w = np.concatenate([batches[2 * i][2], batches[2 * i + 1][2]])
        b_state, _ = b_ddp.train_step(b_state, b_ddp.shard((x, y, w)))

    _leaves_allclose(a_state.params, b_state.params, atol=2e-4)

    # BN accumulation still RUNS and stays finite (its inequivalence is a
    # documented property, not a crash)
    c_ddp = DistributedDataParallel(
        ToyCNN(sync_bn=True), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh,
        grad_accumulation=2,
    )
    c_state = c_ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    c_state, _ = c_ddp.train_step_many(
        c_state, c_ddp.shard_stacked(stack_batches(batches))
    )
    for leaf in jax.tree_util.tree_leaves((c_state.params, c_state.model_state)):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_all_padding_microbatch_is_inert(cpu_devices):
    """A tail padded with weight-0 micro-batches must produce the same update
    as the unpadded cycle (the epoch driver's _pad_to_cycles contract)."""
    mesh = make_mesh(cpu_devices)
    batches = make_batches(2, n=16, seed=2)
    model = ToyMLP()

    def run(bs, accum):
        ddp = DistributedDataParallel(
            model, optim.SGD(1e-1), CrossEntropyLoss(), mesh=mesh,
            grad_accumulation=accum,
        )
        state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        state, m = ddp.train_step_many(state, ddp.shard_stacked(stack_batches(bs)))
        return state, m

    # cycle of 2 live micro-batches
    s2, m2 = run(batches, 2)
    # cycle of 4 = same 2 live + 2 all-padding
    x0, y0, w0 = batches[-1]
    padded = batches + [(x0, y0, np.zeros_like(w0))] * 2
    s4, m4 = run(padded, 4)

    _leaves_allclose(s2.params, s4.params, atol=1e-6)
    assert float(np.sum(np.asarray(m4["n"]))) == float(np.sum(np.asarray(m2["n"])))
    assert np.isclose(
        float(np.sum(np.asarray(m4["loss_sum"]))),
        float(np.sum(np.asarray(m2["loss_sum"]))),
        atol=1e-5,
    )


def test_non_multiple_scan_length_refused(cpu_devices):
    mesh = make_mesh(cpu_devices)
    ddp = DistributedDataParallel(
        ToyMLP(), optim.SGD(1e-1), CrossEntropyLoss(), mesh=mesh,
        grad_accumulation=3,
    )
    state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    with pytest.raises(ValueError, match="multiple of"):
        ddp.train_step_many(
            state, ddp.shard_stacked(stack_batches(make_batches(4)))
        )


def test_per_batch_step_refused_under_accumulation(cpu_devices):
    mesh = make_mesh(cpu_devices)
    ddp = DistributedDataParallel(
        ToyMLP(), optim.SGD(1e-1), CrossEntropyLoss(), mesh=mesh,
        grad_accumulation=2,
    )
    state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    with pytest.raises(RuntimeError, match="grad_accumulation"):
        ddp.train_step(state, ddp.shard(make_batches(1)[0]))


def test_loop_pads_ragged_tail(cpu_devices):
    """End-to-end: 5 batches with A=2 -> 2-cycle chunks + a padded tail; the
    epoch must see exactly the real samples and a finite loss."""
    from tpuddp.data import ShardedDataLoader
    from tpuddp.training.loop import run_training_loop

    mesh = make_mesh(cpu_devices)
    ds = SyntheticClassification(n=5 * 16, shape=(8, 8, 3), seed=3)
    # batch_size is PER-REPLICA: 2 x 8 devices = 16 global -> 5 batches/epoch
    train = ShardedDataLoader(ds, batch_size=2, mesh=mesh, shuffle=True)
    test = ShardedDataLoader(ds, batch_size=2, mesh=mesh, shuffle=False)
    ddp = DistributedDataParallel(
        ToyMLP(), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh,
        grad_accumulation=2,
    )
    state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    state, history = run_training_loop(
        ddp, state, train, test, save_dir=None, num_epochs=2,
        checkpoint_epoch=10, scan_steps=2, log=lambda *a, **k: None,
    )
    for rec in history:
        assert rec["train_samples"] == 80.0
        assert np.isfinite(rec["train_loss"])
    # 5 batches/epoch with K=2, A=2: two full chunks (2 cycles) + a 1-batch
    # tail padded to a whole cycle -> 6 micro-steps on state.step per epoch
    assert int(np.asarray(state.step)) == 12


def test_native_cli_accepts_gradient_accumulation(tmp_path):
    """Config-level wiring: gradient_accumulation_steps is a native-path knob
    now (was refused through round 4)."""
    import yaml

    from tpuddp import config as cfg_lib

    settings = {
        "script_path": "train_native.py",
        "out_dir": str(tmp_path / "out"),
        "optional_args": {"set_epoch": True, "print_rand": False},
        "local": {"device": "cpu"},
        "training": {
            "dataset": "synthetic",
            "model": "toy_mlp",
            "num_epochs": 1,
            "train_batch_size": 16,
            "test_batch_size": 16,
            "learning_rate": 0.01,
            "checkpoint_epoch": 5,
            "gradient_accumulation_steps": 2,
        },
    }
    p = tmp_path / "settings.yaml"
    p.write_text(yaml.safe_dump(settings))
    cfg = cfg_lib.load_settings(str(p))
    assert int(cfg["training"]["gradient_accumulation_steps"]) == 2
