"""Real-data end-to-end training: the digits dataset (1,797 genuine 8x8
handwritten digit scans, the only real image data available offline in a
zero-egress environment) through the native entrypoint — the stand-in proof
for the reference's real-CIFAR-10 workload (data_and_toy_model.py:8-38):
actual generalization accuracy on held-out human-written data, exercising
entrypoint, dataset dispatch, sharded loading, augmentation, and metrics."""

import re
from functools import partial

import numpy as np
import pytest

from tpuddp.data import digits, load_datasets_for


def test_digits_loads_real_data_with_cifar_contract():
    train, test = digits.load_datasets()
    assert len(train) == 1437 and len(test) == 360
    assert train.images.dtype == np.uint8
    assert train.images.shape[1:] == (8, 8, 3)
    # real data: all 10 digit classes present in both splits, roughly balanced
    for split in (train, test):
        counts = np.bincount(split.labels, minlength=10)
        assert counts.min() > 0.5 * counts.mean()
    # deterministic split
    again_train, _ = digits.load_datasets()
    np.testing.assert_array_equal(train.labels, again_train.labels)


def test_dataset_dispatch_selects_digits():
    train, _ = load_datasets_for({"dataset": "digits"})
    assert len(train) == 1437
    with pytest.raises(ValueError, match="dataset"):
        load_datasets_for({"dataset": "imagenet"})


@pytest.mark.slow
def test_digits_e2e_reaches_real_accuracy(tmp_path, capsys):
    """4 epochs of ToyCNN on digits through the full native entrypoint must
    reach >= 85% held-out accuracy (measured ~95%) — real generalization on
    real data, not synthetic-cluster separation."""
    import train_native
    from tpuddp.parallel import backend
    from tpuddp.parallel.spawn import run_ddp_training

    training = {
        "model": "toy_cnn",
        "dataset": "digits",
        "data_root": "/nonexistent",
        "train_batch_size": 32,
        "test_batch_size": 45,
        "learning_rate": 0.001,
        "num_epochs": 4,
        "checkpoint_epoch": 10,
        "image_size": None,
        "seed": 0,
        "mode": "shard_map",
        "prefetch": False,
        "flip": False,  # digits are not flip-invariant
    }
    backend.cleanup()
    run_ddp_training(
        partial(train_native.basic_ddp_training_loop, training=training),
        world_size=8,
        save_dir=str(tmp_path),
        optional_args={"set_epoch": True},
        backend="cpu",
    )
    backend.cleanup()
    out = capsys.readouterr().out
    accs = re.findall(r"Test Accuracy: ([0-9.]+)%", out)
    assert accs, f"no accuracy lines in output:\n{out[-2000:]}"
    assert float(accs[-1]) >= 85.0, f"final accuracy {accs[-1]}% < 85%"


def test_flip_default_follows_dataset():
    from tpuddp.data import flip_for

    assert flip_for({"dataset": "cifar10"}) is True
    assert flip_for({}) is True
    assert flip_for({"dataset": "digits"}) is False
    assert flip_for({"dataset": "digits", "flip": True}) is True
    assert flip_for({"dataset": "cifar10", "flip": False}) is False
