"""Wedged-drain worker for the hang-then-escalate chaos legs (ISSUE 11).

Two wedge modes, both simulating a child that cannot complete a clean
SIGTERM drain:

``wedge-drain`` (default)
    Installs the real preemption handler, seeds the flight rings, then
    spins WITHOUT ever polling ``preemption_requested()`` — the drain can
    never reach a batch-group boundary (a collective that never completes).
    The preemption failsafe must force exit 75 after
    ``$TPUDDP_PREEMPT_GRACE`` seconds and dump
    ``flightrec_preempt_forced.json`` on the way out. On a SECOND attempt
    (the restart supervisor relaunching it) the marker file is present and
    the worker exits 0 — so a supervisor run proves the recording is
    summarized BEFORE the restart decision.

``ignore-sigterm``
    Sets SIGTERM to SIG_IGN and spins — a child wedged below Python (no
    failsafe can run). Only SIGKILL ends it: the drain-escalation contract
    (``fleet.controller.escalate_drain``) must deliver that, and only
    after the grace window.

Usage: python _chaos_wedge_worker.py <out_dir> [wedge-drain|ignore-sigterm]
"""

import os
import signal
import sys
import time

out_dir = sys.argv[1]
mode = sys.argv[2] if len(sys.argv) > 2 else "wedge-drain"
os.makedirs(out_dir, exist_ok=True)

marker = os.path.join(out_dir, "wedge_attempt.marker")
if os.path.exists(marker):
    print("WEDGE second attempt: clean exit", flush=True)
    sys.exit(0)
with open(marker, "w") as f:
    f.write("1\n")

if mode == "ignore-sigterm":
    signal.signal(signal.SIGTERM, signal.SIG_IGN)
    print("WEDGE armed (SIGTERM ignored)", flush=True)
    while True:
        time.sleep(0.05)

# wedge-drain: the real handler + flight rings, then a drain that can
# never finish
from tpuddp.observability import flight, schema  # noqa: E402
from tpuddp.resilience import preemption  # noqa: E402

recorder = flight.FlightRecorder(out_dir, process_index=0)
flight.install(recorder)
recorder.observe(schema.stamp("event", {"event": "wedge_armed", "epoch": 0}))
recorder.note(wedge_mode=mode, pid=os.getpid())
preemption.install_preemption_handler()
print("WEDGE armed (drain will wedge)", flush=True)
# self-delivered SIGTERM: the drain starts NOW, and can never finish —
# the failsafe must force exit 75 after $TPUDDP_PREEMPT_GRACE
os.kill(os.getpid(), signal.SIGTERM)
while True:
    # never polls preemption_requested(): the drain wedges; only the
    # failsafe's forced exit 75 (flight dump included) can end this loop
    time.sleep(0.05)
