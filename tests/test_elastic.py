"""Elastic resume (ISSUE 7), fast tier: the v2 topology-change-tolerant
checkpoint format, the N->M reshard rules (re-pad / sum-preserving residual
redistribution / documented reset), v1 TopologyMismatch for both checkpoint
families, the restore_latest quorum behavior over mixed prefixes, and the
restart supervisor's exit-code policy (driven by a fake child runner — the
subprocess proofs live in tests/test_chaos.py)."""

import dataclasses
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuddp import optim
from tpuddp.models import ToyMLP
from tpuddp.parallel import make_mesh
from tpuddp.parallel.comm import redistribute_residual
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.resilience.preemption import (
    EXIT_DESYNC,
    EXIT_PREEMPTED,
    EXIT_WATCHDOG,
)
from tpuddp.resilience.supervisor import RestartSupervisor, SupervisorPolicy
from tpuddp.training import checkpoint as ckpt


# ------------------------------------------------------ elastic checkpoints --


def build_world(cpu_devices, world, **kw):
    """A DDP wrap + initialized state on the first ``world`` devices, with
    the two world-size-dependent state kinds armed: weight-update-sharded
    flat optimizer moments and the shard_map bf16_ef per-replica residual."""
    kw.setdefault("comm_hook", "bf16_ef")
    kw.setdefault("weight_update_sharding", True)
    mesh = make_mesh(cpu_devices[:world])
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(8,)), optim.Adam(1e-2), mesh=mesh, **kw
    )
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 4, 4, 3)))
    return ddp, state


def residual_matrix(ddp, rng_seed=0):
    """A non-trivial (world, per) residual respecting the padding invariant
    (zeros past the raw element count — what training guarantees)."""
    spec = ddp._wus_spec
    raw = sum(spec.sizes)
    mat = np.zeros((ddp.world_size, spec.total), np.float32)
    mat[:, :raw] = (
        np.random.default_rng(rng_seed)
        .normal(size=(ddp.world_size, raw))
        .astype(np.float32)
    )
    return mat, raw


def with_residual(ddp, state, mat):
    return dataclasses.replace(
        state,
        comm_state=jax.device_put(
            mat.reshape(-1), NamedSharding(ddp.mesh, P("data"))
        ),
    )


def test_save_on_main_writes_v2_topology(cpu_devices, tmp_path):
    ddp, state = build_world(cpu_devices, 4)
    path = ckpt.save_on_main(str(tmp_path), 3, state, world_size=4)
    topo = ckpt.read_topology(path)
    assert topo["format"] == ckpt.FORMAT_VERSION
    assert topo["world_size"] == 4
    assert topo["mesh_axes"] == ["data"] and topo["mesh_shape"] == [4]
    assert topo["leaves"][".comm_state"]["kind"] == "per_replica"
    assert topo["leaves"][".comm_state"]["world"] == 4
    # the meta scalar contract is unchanged (v1 readers see the same keys)
    assert ckpt.read_meta(path) == {"epoch": 3, "completed": 1}
    # every WUS flat moment vector is tagged for re-padding
    flat_tags = [
        k for k, v in topo["leaves"].items()
        if v["kind"] == "data_flat" and k.startswith(".opt_state")
    ]
    assert flat_tags, topo["leaves"]


def test_same_topology_restore_is_bitwise(cpu_devices, tmp_path):
    ddp, state = build_world(cpu_devices, 4)
    mat, _ = residual_matrix(ddp)
    state = with_residual(ddp, state, mat)
    ckpt.save_on_main(str(tmp_path), 2, state, world_size=4)
    log = []
    restored, nxt = ckpt.restore_latest(
        str(tmp_path), state, world_size=4, reshard_log=log
    )
    assert nxt == 3
    assert log == []  # same topology: no events, no reshard
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        dataclasses.replace(restored, rng=None),
        dataclasses.replace(state, rng=None),
    )


def test_shrink_redistributes_residual_sum_preserving(cpu_devices, tmp_path):
    """4 -> 2 (M | N): each new replica's residual is the elementwise f32 sum
    of its group of two old rows — bitwise-reproducible, per-element sum over
    the replica axis preserved exactly; WUS moments re-pad exactly."""
    ddp4, s4 = build_world(cpu_devices, 4)
    mat, raw = residual_matrix(ddp4)
    per4 = ddp4._wus_spec.total
    s4 = with_residual(ddp4, s4, mat)
    ckpt.save_on_main(str(tmp_path), 5, s4, world_size=4)

    ddp2, s2 = build_world(cpu_devices, 2)
    per2 = ddp2._wus_spec.total
    log = []
    restored, nxt = ckpt.restore_latest(
        str(tmp_path), s2, world_size=2, reshard_log=log
    )
    assert nxt == 6
    got = np.asarray(restored.comm_state).reshape(2, per2)
    cols = np.zeros((4, per2), np.float32)
    keep = min(per4, per2)
    cols[:, :keep] = mat[:, :keep]
    expected = cols.reshape(2, 2, per2).sum(axis=1)
    np.testing.assert_array_equal(got, expected)  # bitwise
    # per-element replica-axis sum preserved (the trajectory-relevant value)
    # up to one f32 rounding per group sum — the redistribution's only
    # arithmetic
    np.testing.assert_allclose(
        got.astype(np.float64).sum(axis=0)[:raw],
        mat.astype(np.float64).sum(axis=0)[:raw],
        rtol=1e-5, atol=1e-5,
    )
    ev = [e for e in log if e["event"] == "topology_change"]
    assert ev and ev[0]["from_world"] == 4 and ev[0]["to_world"] == 2
    assert ev[0]["residual"] == "redistributed"
    assert ".comm_state" in ev[0]["resharded_leaves"]
    # params ride through untouched
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored.params, s4.params,
    )


@pytest.mark.parametrize("hook", ["int8_ef", "topk_ef"])
def test_shrink_redistributes_quantized_sparse_residual(
    cpu_devices, tmp_path, hook
):
    """Comm-compression-v2 satellite: the int8/top-k hooks' comm_state rides
    the SAME v2 topology machinery as bf16_ef — a 4 -> 2 shrink
    redistributes the residual sum-preservingly (bitwise group sums), the
    per-bucket scales are recomputed in-jit rather than checkpointed (the
    checkpoint holds exactly one comm_state leaf), and the restored state
    trains on under the halved world."""
    ddp4, s4 = build_world(cpu_devices, 4, comm_hook=hook)
    mat, raw = residual_matrix(ddp4)
    per4 = ddp4._wus_spec.total
    s4 = with_residual(ddp4, s4, mat)
    path = ckpt.save_on_main(str(tmp_path), 5, s4, world_size=4)
    # scales are not state: comm_state is the only comm leaf in the file
    with np.load(path) as data:
        comm_keys = [k for k in data.files if "comm" in k]
    assert comm_keys == [".comm_state"]
    topo = ckpt.read_topology(path)
    assert topo["leaves"][".comm_state"]["kind"] == "per_replica"

    ddp2, s2 = build_world(cpu_devices, 2, comm_hook=hook)
    per2 = ddp2._wus_spec.total
    log = []
    restored, nxt = ckpt.restore_latest(
        str(tmp_path), s2, world_size=2, reshard_log=log
    )
    assert nxt == 6
    got = np.asarray(restored.comm_state).reshape(2, per2)
    cols = np.zeros((4, per2), np.float32)
    keep = min(per4, per2)
    cols[:, :keep] = mat[:, :keep]
    np.testing.assert_array_equal(got, cols.reshape(2, 2, per2).sum(axis=1))
    ev = [e for e in log if e["event"] == "topology_change"]
    assert ev and ev[0]["residual"] == "redistributed"
    # and the restored state trains on the halved world
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8).astype(np.int32)
    st, m = ddp2.train_step(
        restored, ddp2.shard((x, y, np.ones(8, np.float32)))
    )
    assert np.isfinite(float(np.sum(np.asarray(m["loss_sum"]))))


def test_grow_places_residual_rows(cpu_devices, tmp_path):
    """2 -> 4 (N | M): old row r lands verbatim at new row 2r, the rest are
    zero — a pure placement, bitwise sum-preserving."""
    ddp2, s2 = build_world(cpu_devices, 2)
    mat, _ = residual_matrix(ddp2, rng_seed=1)
    per2 = ddp2._wus_spec.total
    s2 = with_residual(ddp2, s2, mat)
    ckpt.save_on_main(str(tmp_path), 1, s2, world_size=2)

    ddp4, s4 = build_world(cpu_devices, 4)
    per4 = ddp4._wus_spec.total
    log = []
    restored, _ = ckpt.restore_latest(
        str(tmp_path), s4, world_size=4, reshard_log=log
    )
    got = np.asarray(restored.comm_state).reshape(4, per4)
    keep = min(per2, per4)
    np.testing.assert_array_equal(got[0, :keep], mat[0, :keep])
    np.testing.assert_array_equal(got[2, :keep], mat[1, :keep])
    assert not got[1].any() and not got[3].any()
    assert log[0]["residual"] == "redistributed"


def test_no_divisor_relation_resets_residual_with_event(cpu_devices, tmp_path):
    """4 -> 3 (M∤N, N∤M): the documented fallback — residual resets to zero
    and a typed comm_state_reset event is handed back; moments still re-pad."""
    ddp4, s4 = build_world(cpu_devices, 4)
    mat, _ = residual_matrix(ddp4)
    s4 = with_residual(ddp4, s4, mat)
    ckpt.save_on_main(str(tmp_path), 0, s4, world_size=4)

    ddp3, s3 = build_world(cpu_devices, 3)
    log = []
    restored, _ = ckpt.restore_latest(
        str(tmp_path), s3, world_size=3, reshard_log=log
    )
    assert not np.asarray(restored.comm_state).any()
    resets = [e for e in log if e["event"] == "comm_state_reset"]
    assert resets and resets[0]["from_world"] == 4 and resets[0]["to_world"] == 3
    topo_ev = [e for e in log if e["event"] == "topology_change"][0]
    assert topo_ev["residual"] == "reset"


def test_redistribute_residual_rules():
    mat = np.arange(12, dtype=np.float32).reshape(4, 3)
    same, action = redistribute_residual(mat, 4)
    assert action == "unchanged" and same is mat or (same == mat).all()
    shrunk, action = redistribute_residual(mat, 2)
    assert action == "redistributed"
    np.testing.assert_array_equal(shrunk, mat.reshape(2, 2, 3).sum(axis=1))
    grown, action = redistribute_residual(mat, 8)
    assert action == "redistributed"
    np.testing.assert_array_equal(grown[::2], mat)
    assert not grown[1::2].any()
    reset, action = redistribute_residual(mat, 3)
    assert action == "reset" and not reset.any()


def test_nonzero_padding_tail_refuses_reshard(cpu_devices, tmp_path):
    """A 'flat' vector whose tail past the new length is non-zero is NOT
    world-multiple padding (a different model, not a different world):
    truncation would silently lose data, so the fit refuses."""
    ddp4, s4 = build_world(cpu_devices, 4)
    mat = np.ones((4, ddp4._wus_spec.total), np.float32)  # non-zero tail
    s4 = with_residual(ddp4, s4, mat)
    ckpt.save_on_main(str(tmp_path), 0, s4, world_size=4)
    ddp2, s2 = build_world(cpu_devices, 2)
    if ddp2._wus_spec.total >= ddp4._wus_spec.total:
        pytest.skip("padding layout coincides; no truncation to refuse")
    with pytest.raises(ckpt.TopologyMismatch, match="not world-multiple padding"):
        ckpt.restore_latest(str(tmp_path), s2, world_size=2)


def test_per_replica_without_world_size_raises(cpu_devices, tmp_path):
    ddp4, s4 = build_world(cpu_devices, 4)
    mat, _ = residual_matrix(ddp4)
    s4 = with_residual(ddp4, s4, mat)
    path = ckpt.save_on_main(str(tmp_path), 0, s4, world_size=4)
    _, s2 = build_world(cpu_devices, 2)
    with pytest.raises(ckpt.TopologyMismatch, match="world size"):
        ckpt.load(path, s2)  # no world_size: cannot redistribute


# --------------------------------------- v1 family: clear TopologyMismatch --


def test_v1_native_checkpoint_on_different_world_raises(cpu_devices, tmp_path):
    """Satellite: a v1 (no topology record) native TrainState checkpoint
    loaded onto a different world size must raise TopologyMismatch pointing
    at elastic v2 — not reshape or silently mis-slice."""
    ddp4, s4 = build_world(cpu_devices, 4)
    path = str(tmp_path / "v1.npz")
    ckpt.save(path, s4)  # plain save: v1 semantics, no topology
    _, s2 = build_world(cpu_devices, 2)
    with pytest.raises(ckpt.TopologyMismatch) as e:
        ckpt.load(path, s2, world_size=2)
    assert "v2" in str(e.value) or "topology record" in str(e.value)
    # same topology keeps loading unchanged
    restored = ckpt.load(path, s4)
    np.testing.assert_array_equal(
        np.asarray(restored.comm_state), np.asarray(s4.comm_state)
    )


def test_v1_managed_state_on_different_world_raises(tmp_path):
    """Same contract for the managed dict-keyed ``state_{e}.npz`` family:
    the WUS flat moment vector is world-padded, so a v1 file mismatches."""
    tree4 = {
        "params": {"w": np.ones((3, 2), np.float32)},
        "opt_state": {"m": np.zeros(8, np.float32)},  # padded for world 4
    }
    path = str(tmp_path / "state_0.npz")
    ckpt.save(path, tree4)
    tree6 = {
        "params": {"w": np.ones((3, 2), np.float32)},
        "opt_state": {"m": np.zeros(6, np.float32)},  # padded for world 6
    }
    with pytest.raises(ckpt.TopologyMismatch, match="topology"):
        ckpt.load(path, tree6, world_size=6)
    # and an ordinary (non-world-dependent) mismatch stays a plain ValueError
    bad = {"params": {"w": np.ones((4, 2), np.float32)},
           "opt_state": {"m": np.zeros(8, np.float32)}}
    with pytest.raises(ValueError) as e:
        ckpt.load(path, bad)
    assert not isinstance(e.value, ckpt.TopologyMismatch)


# ----------------------------------------------- restore_latest quorum -----


def test_restore_latest_quorum_mixed_prefixes(cpu_devices, tmp_path, caplog):
    """Satellite: corrupted newest + intact older checkpoints across the
    mixed prefix families (ckpt / state / auto): the skip is LOGGED, the
    older epoch is re-derived correctly per family, and the serving 'auto'
    prefix picks the newest intact file across BOTH families."""
    from tpuddp.resilience import integrity
    from tpuddp.serving.replica import _restore_variables

    ddp, state = build_world(
        cpu_devices, 2, comm_hook="none", weight_update_sharding=False
    )

    def corrupt(path):
        with open(path, "r+b") as f:
            f.seek(0)
            f.write(b"\x00GARBAGE\x00" * 4)

    # native family: intact epoch 0, corrupt epoch 2
    ckpt.save_on_main(str(tmp_path), 0, state, world_size=2)
    p2 = ckpt.save_on_main(str(tmp_path), 2, state, world_size=2)
    corrupt(p2)
    assert not integrity.verify_file(p2)
    # managed family: intact epoch 1, corrupt epoch 3
    managed = {"params": state.params, "model_state": state.model_state}
    ckpt.save_on_main(str(tmp_path), 1, managed, prefix="state", world_size=2)
    p3 = ckpt.save_on_main(str(tmp_path), 3, managed, prefix="state", world_size=2)
    corrupt(p3)

    with caplog.at_level(logging.WARNING, logger="tpuddp"):
        restored, nxt = ckpt.restore_latest(str(tmp_path), state, world_size=2)
    assert nxt == 1  # corrupt ckpt_2 skipped, intact ckpt_0 + 1
    assert "failed integrity verification" in caplog.text

    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="tpuddp"):
        _, nxt_state = ckpt.restore_latest(
            str(tmp_path), managed, prefix="state", world_size=2
        )
    assert nxt_state == 2  # corrupt state_3 skipped, intact state_1 + 1
    assert "failed integrity verification" in caplog.text

    # serving's auto prefix: newest INTACT across families is state_1
    _, _, epoch = _restore_variables(
        str(tmp_path), "auto", state.params, state.model_state
    )
    assert epoch == 1


# ------------------------------------------------------- restart supervisor --


class FakeRunner:
    """Scripted child: pops the next exit code, records (argv, env)."""

    def __init__(self, codes):
        self.codes = list(codes)
        self.calls = []

    def __call__(self, argv, env):
        self.calls.append((list(argv), dict(env)))
        return self.codes.pop(0)


def make_supervisor(codes, **kw):
    sleeps = []
    runner = FakeRunner(codes)
    kw.setdefault("policy", SupervisorPolicy(backoff_base=0.01, backoff_cap=0.02))
    sup = RestartSupervisor(
        ["python", "train.py"], runner=runner, sleep=sleeps.append, **kw
    )
    return sup, runner, sleeps


def test_supervisor_clean_exit_passthrough():
    sup, runner, sleeps = make_supervisor([0])
    assert sup.run() == 0
    assert len(runner.calls) == 1 and sleeps == []


def test_supervisor_resumes_preempted_child_immediately():
    """75 -> restart NOW with auto-resume, no backoff; the restart env drops
    the first attempt's injected fault and sets TPUDDP_AUTO_RESUME=1."""
    sup, runner, sleeps = make_supervisor(
        [EXIT_PREEMPTED, EXIT_PREEMPTED, 0],
        first_attempt_env={"TPUDDP_FAULT": "preempt@epoch=1"},
    )
    assert sup.run() == 0
    assert sleeps == []  # preemption never backs off
    assert runner.calls[0][1]["TPUDDP_FAULT"] == "preempt@epoch=1"
    assert "TPUDDP_AUTO_RESUME" not in runner.calls[0][1]
    for _argv, env in runner.calls[1:]:
        assert env["TPUDDP_AUTO_RESUME"] == "1"
        assert "TPUDDP_FAULT" not in env  # chaos must not re-fire on resume
    assert [h[1] for h in sup.history] == [EXIT_PREEMPTED, EXIT_PREEMPTED, 0]


def test_supervisor_shrinks_world_on_repeated_peer_death():
    """Two consecutive watchdog exits (76) shrink the world 8 -> 4 and resume
    through the elastic path (TPUDDP_WORLD_SIZE re-pinned); the shrink resets
    the peer-death streak."""
    sup, runner, sleeps = make_supervisor(
        [EXIT_WATCHDOG, EXIT_WATCHDOG, 0],
        world_size=8,
        policy=SupervisorPolicy(
            backoff_base=0.01, backoff_cap=0.02, shrink_after=2
        ),
    )
    assert sup.run() == 0
    assert [h[2] for h in sup.history] == [8, 8, 4]
    assert runner.calls[0][1]["TPUDDP_WORLD_SIZE"] == "8"
    assert runner.calls[2][1]["TPUDDP_WORLD_SIZE"] == "4"
    assert runner.calls[2][1]["TPUDDP_AUTO_RESUME"] == "1"
    assert len(sleeps) == 1  # first 76 backs off; the shrink restarts at once


def test_supervisor_min_world_blocks_shrink():
    sup, runner, sleeps = make_supervisor(
        [EXIT_WATCHDOG, EXIT_WATCHDOG, EXIT_WATCHDOG, 0],
        world_size=2,
        policy=SupervisorPolicy(
            backoff_base=0.01, backoff_cap=0.02, shrink_after=2, min_world=2
        ),
    )
    assert sup.run() == 0
    assert all(h[2] == 2 for h in sup.history)  # never shrank below min
    assert len(sleeps) == 3  # every 76 backed off instead


def test_supervisor_restart_budget_surfaces_last_code():
    sup, runner, sleeps = make_supervisor(
        [EXIT_DESYNC, EXIT_DESYNC, EXIT_DESYNC],
        policy=SupervisorPolicy(
            max_restarts=2, backoff_base=0.01, backoff_cap=0.02
        ),
    )
    assert sup.run() == EXIT_DESYNC
    assert len(runner.calls) == 3  # initial + 2 restarts


def test_supervisor_backoff_grows_and_is_jittered():
    sleeps = []
    runner = FakeRunner([1, 1, 1, 0])
    sup = RestartSupervisor(
        ["x"], runner=runner, sleep=sleeps.append,
        policy=SupervisorPolicy(backoff_base=1.0, backoff_cap=100.0, jitter=0.5),
    )
    assert sup.run() == 0
    assert len(sleeps) == 3
    # delay(k) = base * 2^(k-1) * U(0.5, 1.5): bounds per consecutive failure
    for k, d in enumerate(sleeps, start=1):
        lo, hi = 2 ** (k - 1) * 0.5, 2 ** (k - 1) * 1.5
        assert lo <= d <= hi


def test_supervisor_signal_death_is_backoff_restartable(caplog):
    """Policy-matrix rows rc=-9/-15 (ISSUE 11 satellite): a child killed by
    a signal (subprocess reports -N) restarts with backoff + auto-resume,
    the log line NAMES the signal, and the death never extends the
    peer-death (76) shrink streak."""
    import logging

    sup, runner, sleeps = make_supervisor(
        [-9, -15, 0],
        world_size=8,
        policy=SupervisorPolicy(
            backoff_base=0.01, backoff_cap=0.02, shrink_after=1
        ),
    )
    with caplog.at_level(logging.WARNING, logger="tpuddp"):
        assert sup.run() == 0
    assert len(sleeps) == 2  # both signal deaths backed off
    assert [h[1] for h in sup.history] == [-9, -15, 0]
    # never shrank: signal deaths are crashes, not peer-death evidence
    # (shrink_after=1 would have shrunk on the FIRST exit-76)
    assert all(h[2] == 8 for h in sup.history)
    assert runner.calls[1][1]["TPUDDP_AUTO_RESUME"] == "1"
    text = caplog.text
    assert "killed by SIGKILL" in text
    assert "killed by SIGTERM" in text


def test_supervisor_signal_death_resets_peer_death_streak():
    """A 76 followed by an OOM SIGKILL followed by another 76 is NOT two
    consecutive peer deaths — the streak restarts at the signal death."""
    sup, runner, sleeps = make_supervisor(
        [EXIT_WATCHDOG, -9, EXIT_WATCHDOG, 0],
        world_size=8,
        policy=SupervisorPolicy(
            backoff_base=0.01, backoff_cap=0.02, shrink_after=2
        ),
    )
    assert sup.run() == 0
    assert all(h[2] == 8 for h in sup.history)  # the streak never hit 2


def test_supervise_cli_parses_and_runs(tmp_path):
    """tools/supervise.py end-to-end over a trivial child command."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, os.path.join(repo, "tools", "supervise.py"),
            "--max-restarts", "1", "--", sys.executable, "-c", "print('ok')",
        ],
        capture_output=True, text=True, timeout=120, cwd=repo, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
