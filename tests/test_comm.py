"""Gradient-communication hooks (tpuddp/parallel/comm.py) — the tpuddp
rebuild of torch DDP's bucketed allreduce + comm hooks (SURVEY.md §2b,
``default_hooks.bf16_compress_hook`` et al.).

Pinned contracts:

- bucket assembly: deterministic whole-leaf packing, cap respected, oversized
  leaves isolated, padding absorbed by the tail, exact cover of the padded
  flat vector;
- the wire really carries bf16: the compiled HLO of the explicit step holds a
  bf16 all-reduce (or bf16 reduce-scatter under weight_update_sharding);
- numerics: bf16_ef training tracks the fp32 path's loss within tolerance
  over N steps on the 8-device virtual world, in every mode the knob reaches
  (explicit shard_map / auto, scan-fused, grad accumulation, managed);
- the comm-bytes counter shows the >= 45% gradient-byte reduction the ISSUE
  acceptance demands;
- the bf16_ef error-feedback residual is training state: it must be nonzero
  once training has run, and must checkpoint-round-trip losslessly on both
  the native (training/checkpoint.py) and managed (save_state/load_state)
  paths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import nn, optim
from tpuddp.data import SyntheticClassification
from tpuddp.models import ToyMLP
from tpuddp.parallel import comm as comm_lib
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.training import checkpoint as ckpt
from tpuddp.training.step import stack_batches

KEY = jax.random.key(0)
MB = 1024 * 1024


def cap_mb(elems: int) -> float:
    """bucket_cap_mb holding exactly ``elems`` f32 elements."""
    return elems * 4 / MB


def make_batch(n=64, seed=5, shape=(8, 8, 3)):
    ds = SyntheticClassification(n=n, shape=shape, seed=seed)
    x, y = ds.get_batch(np.arange(n))
    return x, y, np.ones(n, np.float32)


def build(mesh, hook, mode="shard_map", wus=False, accum=1, cap=None, **kw):
    return DistributedDataParallel(
        ToyMLP(hidden=(16,)),
        optim.Adam(1e-2),
        nn.CrossEntropyLoss(),
        mesh=mesh,
        mode=mode,
        comm_hook=hook,
        weight_update_sharding=wus,
        grad_accumulation=accum,
        **({"bucket_cap_mb": cap} if cap is not None else {}),
        **kw,
    )


# ---------------------------------------------------------------- buckets --


def test_buckets_cover_padded_vector_exactly():
    # 18 raw elements padded to a world multiple (24): the tail bucket
    # absorbs the padding so the buckets tile [0, total) with no gap
    b = comm_lib.make_buckets((6, 6, 6), total=24, bucket_cap_mb=cap_mb(16))
    assert b == ((0, 12), (12, 24))
    assert b[0][0] == 0 and b[-1][1] == 24
    for (s0, e0), (s1, _) in zip(b, b[1:]):
        assert e0 == s1 and s0 < e0


def test_bucket_cap_respected_on_whole_leaf_boundaries():
    sizes = (4, 4, 4, 4, 4)
    b = comm_lib.make_buckets(sizes, total=24, bucket_cap_mb=cap_mb(10))
    # greedy whole-leaf packing: 4+4 <= 10 < 4+4+4 -> buckets of two leaves
    assert b == ((0, 8), (8, 16), (16, 24))
    boundaries = set(np.cumsum((0,) + sizes)) | {24}
    for s, e in b:
        assert s in boundaries  # never splits a leaf


def test_oversized_leaf_gets_its_own_bucket():
    # torch DDP's rule: a tensor larger than the cap is never split
    b = comm_lib.make_buckets((100, 4), total=104, bucket_cap_mb=cap_mb(16))
    assert b == ((0, 100), (100, 104))


def test_buckets_deterministic_and_odd_remainders():
    sizes = (7, 3, 11, 1, 5)  # ragged odd sizes, total padded to 32
    a = comm_lib.make_buckets(sizes, 32, bucket_cap_mb=cap_mb(12))
    assert a == comm_lib.make_buckets(sizes, 32, bucket_cap_mb=cap_mb(12))
    assert a[0][0] == 0 and a[-1][1] == 32
    covered = sum(e - s for s, e in a)
    assert covered == 32
    # every bucket holds at least one whole leaf and respects the cap unless
    # it is a single oversized leaf or the padding-absorbing tail
    edges = list(np.cumsum(sizes))
    for s, e in a[:-1]:
        n_leaves = sum(1 for c in edges if s < c <= e)
        assert n_leaves >= 1
        assert (e - s) <= 12 or n_leaves == 1


def test_bucket_cap_validation(cpu_devices):
    with pytest.raises(ValueError, match="bucket_cap_mb"):
        comm_lib.make_buckets((4,), 8, bucket_cap_mb=0)
    with pytest.raises(ValueError, match="comm_hook"):
        comm_lib.validate_hook("fp8")
    mesh = make_mesh(cpu_devices)
    with pytest.raises(ValueError, match="comm_hook"):
        build(mesh, "int8")
    with pytest.raises(ValueError, match="bucket_cap_mb"):
        build(mesh, "bf16", cap=-1.0)
    # both API levels share the knob contract
    from tpuddp.accelerate import Accelerator

    with pytest.raises(ValueError, match="bucket_cap_mb"):
        Accelerator(mesh=mesh, bucket_cap_mb=0)
    with pytest.raises(ValueError, match="comm_hook"):
        Accelerator(mesh=mesh, comm_hook="int8")


def test_make_grad_comm_plan():
    params = {"w": jnp.zeros((13, 7)), "b": jnp.zeros((7,))}
    assert comm_lib.make_grad_comm(params, 8, "none") is None
    plan = comm_lib.make_grad_comm(params, 8, "bf16_ef", bucket_cap_mb=cap_mb(64))
    assert plan.compressed and plan.needs_residual
    assert plan.buckets[0][0] == 0 and plan.buckets[-1][1] == plan.spec.total
    # residual layouts: per-replica (world * total) vs replicated (total)
    assert plan.init_residual(per_replica=True).shape == (8 * plan.spec.total,)
    assert plan.init_residual(per_replica=False).shape == (plan.spec.total,)
    bf16 = comm_lib.make_grad_comm(params, 8, "bf16")
    assert bf16.compressed and not bf16.needs_residual
    assert bf16.init_residual(per_replica=True) is None


# ----------------------------------------------------------- wire accounting


def test_comm_bytes_reduction_at_least_45_percent():
    # any realistic f32 parameter pytree works; sizes chosen so the
    # world-multiple padding is negligible against the leaf sum
    p = {"w1": jnp.zeros((192, 64)), "b1": jnp.zeros((64,)),
         "w2": jnp.zeros((64, 10)), "b2": jnp.zeros((10,))}
    base = comm_lib.comm_bytes_for_hook(p, 8, "none")
    for hook in ("bf16", "bf16_ef"):
        comp = comm_lib.comm_bytes_for_hook(p, 8, hook)
        assert 1 - comp / base >= 0.45, (hook, comp, base)
    wbase = comm_lib.comm_bytes_for_hook(p, 8, "none", wus=True)
    wcomp = comm_lib.comm_bytes_for_hook(p, 8, "bf16_ef", wus=True)
    assert 1 - wcomp / wbase >= 0.45


def test_ddp_counter_property(cpu_devices):
    mesh = make_mesh(cpu_devices)
    ddp = build(mesh, "bf16_ef")
    assert ddp.grad_comm_bytes_per_step is None  # pre-init: no plan yet
    ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    comp = ddp.grad_comm_bytes_per_step
    base = build(mesh, "none")
    base.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    assert 1 - comp / base.grad_comm_bytes_per_step >= 0.45


def test_auto_mode_counter_reports_f32_wire(cpu_devices):
    """mode="auto": XLA inserts the psum over f32 values and the hook only
    emulates the quantization — the counter must report the f32 payload, not
    a byte cut that never reached the wire."""
    mesh = make_mesh(cpu_devices)
    comp = build(mesh, "bf16_ef", mode="auto")
    comp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    base = build(mesh, "none", mode="auto")
    base.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    assert comp.grad_comm_bytes_per_step == base.grad_comm_bytes_per_step


def test_comm_bytes_formula_per_hook():
    """Satellite (ISSUE 9): the per-hook wire-byte formula, pinned exactly.
    Sparse/quantized payloads must count EVERY wire part — int8 values, the
    int32 top-k indices, and the per-bucket f32 scale scalars — and
    ``wire=False`` (auto/managed, where the collective stays f32) must keep
    reporting the f32 payload for every hook."""
    p = {"w": jnp.zeros((40, 10)), "b": jnp.zeros((10,))}  # 410 raw elems
    world, cap = 8, cap_mb(4096)  # one bucket: 410 -> padded 416
    spec_total = 416
    base = comm_lib.comm_bytes_for_hook(p, world, "none")
    assert base == 410 * 4  # tree pmean reduces the raw elements
    assert comm_lib.comm_bytes_for_hook(
        p, world, "bf16", bucket_cap_mb=cap
    ) == spec_total * 2
    assert comm_lib.comm_bytes_for_hook(
        p, world, "int8_ef", bucket_cap_mb=cap
    ) == spec_total * 1 + 4  # int8 values + ONE f32 scale (one bucket)
    k = comm_lib.bucket_topk(spec_total, 0.1)
    assert comm_lib.comm_bytes_for_hook(
        p, world, "topk_ef", bucket_cap_mb=cap, density=0.1
    ) == k * (1 + 4) + 4  # int8 values + int32 indices + scale
    # multi-bucket: scales are per bucket — 2 buckets => 2 scale scalars
    from tpuddp.training.step import make_flat_param_spec

    spec = make_flat_param_spec(p, world)
    assert spec.total == spec_total
    two = comm_lib.make_buckets(spec.sizes, spec.total, bucket_cap_mb=cap_mb(401))
    assert len(two) == 2
    sizes = [e - s for s, e in two]
    assert comm_lib.comm_bytes_for_hook(
        p, world, "int8_ef", bucket_cap_mb=cap_mb(401)
    ) == sum(sizes) * 1 + 2 * 4
    assert comm_lib.comm_bytes_for_hook(
        p, world, "topk_ef", bucket_cap_mb=cap_mb(401), density=0.1
    ) == sum(comm_lib.bucket_topk(b, 0.1) for b in sizes) * 5 + 2 * 4
    # wus degenerates to ONE whole-vector bucket for every hook
    assert comm_lib.comm_bytes_for_hook(
        p, world, "int8_ef", wus=True
    ) == spec_total * 1 + 4
    # wire=False: auto/managed reduces f32 whatever the hook emulates
    for hook in ("bf16_ef", "int8_ef", "topk_ef"):
        assert comm_lib.comm_bytes_for_hook(
            p, world, hook, wire=False
        ) == base, hook


def test_comm_bytes_acceptance_cuts():
    """The acceptance floors as counter facts: int8_ef >= 70%, topk_ef at
    density 0.1 >= 85% below the f32 payload on a realistic layout."""
    p = {"w1": jnp.zeros((192, 64)), "b1": jnp.zeros((64,)),
         "w2": jnp.zeros((64, 10)), "b2": jnp.zeros((10,))}
    base = comm_lib.comm_bytes_for_hook(p, 8, "none")
    for hook, floor in (("int8_ef", 0.70), ("topk_ef", 0.85)):
        comp = comm_lib.comm_bytes_for_hook(p, 8, hook, density=0.1)
        assert 1 - comp / base >= floor, (hook, comp, base)


def test_comm_bytes_breakdown_hierarchical():
    """Hierarchical accounting: intra-host = the f32 scatter + gather
    operands, inter-host = the compressed shard payload — and the inter-host
    share must sit below the flat topology's total for every hook."""
    p = {"w": jnp.zeros((100, 10))}
    world, local = 8, 4
    from tpuddp.training.step import make_flat_param_spec

    total = make_flat_param_spec(p, world).total
    shard = total // local
    for hook in ("none", "bf16_ef", "int8_ef", "topk_ef"):
        flat = comm_lib.comm_bytes_breakdown(p, world, hook, topology="flat")
        assert flat["intra_host"] == 0
        assert flat["inter_host"] == flat["total"]
        hier = comm_lib.comm_bytes_breakdown(
            p, world, hook, topology="hierarchical", local_size=local
        )
        assert hier["intra_host"] == total * 4 + shard * 4
        assert hier["inter_host"] < flat["total"], hook
        assert hier["total"] == hier["intra_host"] + hier["inter_host"]
    hier = comm_lib.comm_bytes_breakdown(
        p, world, "int8_ef", topology="hierarchical", local_size=local
    )
    assert hier["inter_host"] == shard * 1 + 4
    with pytest.raises(ValueError, match="local_size"):
        comm_lib.comm_bytes_breakdown(p, world, "int8_ef", topology="hierarchical")
    with pytest.raises(ValueError, match="comm_topology"):
        comm_lib.comm_bytes_breakdown(p, world, "int8_ef", topology="ring")


def test_topk_density_validation(cpu_devices):
    with pytest.raises(ValueError, match="density"):
        comm_lib.bucket_topk(100, 0.0)
    with pytest.raises(ValueError, match="density"):
        comm_lib.bucket_topk(100, 1.5)
    assert comm_lib.bucket_topk(100, 0.1) == 10
    assert comm_lib.bucket_topk(3, 0.1) == 1  # never an empty send
    mesh = make_mesh(cpu_devices)
    with pytest.raises(ValueError, match="density"):
        build(mesh, "topk_ef", topk_density=2.0)
    from tpuddp.accelerate import Accelerator

    with pytest.raises(ValueError, match="density"):
        Accelerator(mesh=mesh, topk_density=0.0)


def test_comm_bytes_counter_class():
    from tpuddp.utils.observability import CommBytesCounter

    c = CommBytesCounter(1000)
    c.add_updates(3)
    c.add_updates(2)
    assert c.total_bytes == 5000
    snap = c.snapshot(epoch_updates=2)
    assert snap["grad_comm_bytes_per_update"] == 1000
    assert snap["grad_comm_bytes_total"] == 5000
    assert snap["grad_comm_bytes_epoch"] == 2000
    # inert counter (pre-init ddp / facade without the attribute): epoch
    # records must stay unchanged
    inert = CommBytesCounter(None)
    inert.add_updates(7)
    assert inert.total_bytes is None and inert.snapshot(7) == {}


# ------------------------------------------------------------- wire dtype --


def _collective_window(ddp, st, batch, op):
    """The text window of the first ``op`` in the LOWERED step program.

    Lowered (StableHLO), not backend-compiled: the byte-reduction contract is
    "the program tpuddp emits requests the gradient collective in the wire
    dtype". Whether the wire then honors it is the backend's legalization —
    TPU ICI carries bf16 collectives natively, while this CPU test world
    upcasts them to f32 at compile time (the quantization numerics survive
    either way; that is what the loss-parity tests pin)."""
    fn = lambda s, b: ddp.train_step(s, b)  # noqa: E731
    txt = jax.jit(fn).lower(st, batch).as_text()
    i = txt.find(op)
    assert i >= 0, f"no {op} in the lowered step program"
    return txt[i : i + 900]


def test_lowered_step_requests_bf16_allreduce(cpu_devices):
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    for hook, want in (("bf16", True), ("none", False)):
        ddp = build(mesh, hook)
        st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        window = _collective_window(
            ddp, st, ddp.shard((x, y, w)), "stablehlo.all_reduce"
        )
        assert ("xbf16>" in window) == want, (hook, window[:200])


def test_lowered_wus_step_requests_bf16_reduce_scatter(cpu_devices):
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    ddp = build(mesh, "bf16_ef", wus=True)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    window = _collective_window(
        ddp, st, ddp.shard((x, y, w)), "stablehlo.reduce_scatter"
    )
    assert "xbf16>" in window


# --------------------------------------------------------------- numerics --


def _run_steps(ddp, steps=8, seed=5):
    x, y, w = make_batch(seed=seed)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    m = None
    for _ in range(steps):
        st, m = ddp.train_step(st, ddp.shard((x, y, w)))
    loss = float(np.sum(np.asarray(m["loss_sum"]))) / float(
        np.sum(np.asarray(m["n"]))
    )
    return st, loss


@pytest.mark.parametrize("mode", ["shard_map", "auto"])
@pytest.mark.parametrize("hook", ["bf16", "bf16_ef"])
def test_compressed_training_tracks_f32_loss(cpu_devices, mode, hook):
    mesh = make_mesh(cpu_devices)
    _, base = _run_steps(build(mesh, "none", mode=mode))
    st, comp = _run_steps(build(mesh, hook, mode=mode))
    assert np.isfinite(comp)
    assert abs(comp - base) <= max(0.05, 0.02 * abs(base)), (hook, mode)
    if hook == "bf16_ef":
        res = np.asarray(st.comm_state)
        assert res.dtype == np.float32 and np.any(res != 0)
    else:
        assert st.comm_state is None


def test_bf16_ef_composes_with_wus(cpu_devices):
    mesh = make_mesh(cpu_devices)
    _, base = _run_steps(build(mesh, "none", wus=True))
    st, comp = _run_steps(build(mesh, "bf16_ef", wus=True))
    assert abs(comp - base) <= max(0.05, 0.02 * abs(base))
    assert np.any(np.asarray(st.comm_state) != 0)


def test_bf16_ef_scan_fused_and_accumulation(cpu_devices):
    """The residual threads through the lax.scan carry: K fused steps with
    grad_accumulation=2 stay on the fp32 trajectory and update the
    residual."""
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    k = 4  # 2 optimizer updates per dispatch at accum=2

    def run(hook):
        ddp = build(mesh, hook, accum=2)
        st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        stacked = ddp.shard_stacked(stack_batches([(x, y, w)] * k))
        m = None
        for _ in range(4):
            st, m = ddp.train_step_many(st, stacked)
        loss = float(np.sum(np.asarray(m["loss_sum"]))) / float(
            np.sum(np.asarray(m["n"]))
        )
        return st, loss

    _, base = run("none")
    st, comp = run("bf16_ef")
    assert np.isfinite(comp)
    assert abs(comp - base) <= max(0.05, 0.02 * abs(base))
    assert np.any(np.asarray(st.comm_state) != 0)


@pytest.mark.parametrize("mode", ["shard_map", "auto"])
@pytest.mark.parametrize("hook", ["int8_ef", "topk_ef"])
def test_quantized_sparse_training_tracks_f32_loss(cpu_devices, mode, hook):
    """Comm compression v2: int8_ef/topk_ef stay within their documented
    parity bound of the uncompressed trajectory (topk_ef compared past its
    ~1/density-update error-feedback warmup) and carry a live residual."""
    steps = 24 if hook == "topk_ef" else 8
    mesh = make_mesh(cpu_devices)
    _, base = _run_steps(build(mesh, "none", mode=mode), steps=steps)
    st, comp = _run_steps(build(mesh, hook, mode=mode), steps=steps)
    assert np.isfinite(comp)
    assert abs(comp - base) <= comm_lib.loss_parity_tol(hook, base), (
        hook, mode, comp, base,
    )
    leaves = jax.tree_util.tree_leaves(st.comm_state)
    assert leaves and any(np.any(np.asarray(l) != 0) for l in leaves)


@pytest.mark.parametrize("hook", ["int8_ef", "topk_ef"])
def test_quantized_sparse_composes_with_wus(cpu_devices, hook):
    steps = 24 if hook == "topk_ef" else 8
    mesh = make_mesh(cpu_devices)
    _, base = _run_steps(build(mesh, "none", wus=True), steps=steps)
    st, comp = _run_steps(build(mesh, hook, wus=True), steps=steps)
    assert abs(comp - base) <= comm_lib.loss_parity_tol(hook, base)
    assert np.any(np.asarray(st.comm_state) != 0)


def test_int8_scan_fused_and_accumulation(cpu_devices):
    """The int8 residual threads through the lax.scan carry exactly like
    bf16_ef's: K fused steps at accum=2 stay on the f32 trajectory."""
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    k = 4

    def run(hook):
        ddp = build(mesh, hook, accum=2)
        st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        stacked = ddp.shard_stacked(stack_batches([(x, y, w)] * k))
        m = None
        for _ in range(4):
            st, m = ddp.train_step_many(st, stacked)
        loss = float(np.sum(np.asarray(m["loss_sum"]))) / float(
            np.sum(np.asarray(m["n"]))
        )
        return st, loss

    _, base = run("none")
    st, comp = run("int8_ef")
    assert np.isfinite(comp)
    assert abs(comp - base) <= comm_lib.loss_parity_tol("int8_ef", base)
    assert np.any(np.asarray(st.comm_state) != 0)


# ------------------------------------------------- hierarchical topology --


def hier_build(cpu_devices, hook, **kw):
    from tpuddp.parallel.mesh import hierarchical_mesh

    mesh = hierarchical_mesh(devices=cpu_devices)
    return build(mesh, hook, comm_topology="hierarchical", **kw)


def test_hierarchical_none_matches_flat_pmean(cpu_devices):
    """hook "none" under the hierarchical topology is pure re-bracketing
    (f32 scatter -> f32 psum -> gather): same trajectory as the flat pmean
    up to summation order."""
    mesh = make_mesh(cpu_devices)
    _, base = _run_steps(build(mesh, "none"))
    _, hier = _run_steps(hier_build(cpu_devices, "none"))
    np.testing.assert_allclose(hier, base, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("hook", ["bf16_ef", "int8_ef", "topk_ef"])
def test_hierarchical_compressed_tracks_f32(cpu_devices, hook):
    steps = 24 if hook == "topk_ef" else 8
    mesh = make_mesh(cpu_devices)
    _, base = _run_steps(build(mesh, "none"), steps=steps)
    st, comp = _run_steps(hier_build(cpu_devices, hook), steps=steps)
    assert np.isfinite(comp)
    assert abs(comp - base) <= comm_lib.loss_parity_tol(hook, base), (
        hook, comp, base,
    )
    # the residual is per-replica sharded state, live after training
    assert np.any(np.asarray(st.comm_state) != 0)


def test_hierarchical_inter_host_bytes_below_flat(cpu_devices):
    """The topology's acceptance contract: for every hook, the compressed
    inter-host payload is strictly below the flat topology's total."""
    mesh = make_mesh(cpu_devices)
    for hook in ("none", "bf16_ef", "int8_ef", "topk_ef"):
        flat = build(mesh, hook)
        flat.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        hier = hier_build(cpu_devices, hook)
        hier.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        assert hier.grad_comm_bytes_inter_host < flat.grad_comm_bytes_per_step
        assert hier.grad_comm_bytes_intra_host > 0
        assert flat.grad_comm_bytes_intra_host == 0


def test_hierarchical_refuses_bad_compositions(cpu_devices):
    mesh = make_mesh(cpu_devices)
    with pytest.raises(ValueError, match="hierarchical"):
        build(mesh, "int8_ef", comm_topology="hierarchical")  # 1-D mesh
    with pytest.raises(ValueError, match="mutually exclusive"):
        hier_build(cpu_devices, "int8_ef", wus=True)
    with pytest.raises(ValueError, match="shard_map"):
        hier_build(cpu_devices, "int8_ef", mode="auto")
    with pytest.raises(ValueError, match="comm_topology"):
        build(mesh, "int8_ef", comm_topology="ring")
    from tpuddp.accelerate import Accelerator

    with pytest.raises(ValueError, match="explicit"):
        Accelerator(mesh=mesh, comm_topology="hierarchical")
    from tpuddp.parallel.mesh import hierarchical_mesh

    with pytest.raises(ValueError, match="factorable"):
        hierarchical_mesh(devices=cpu_devices[:3])


def test_lowered_step_requests_int8_allgather(cpu_devices):
    """The explicit int8 step's lowered program carries the compressed
    payload as the collective operand: an i8-element all-gather."""
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    ddp = build(mesh, "int8_ef")
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    window = _collective_window(
        ddp, st, ddp.shard((x, y, w)), "stablehlo.all_gather"
    )
    assert "xi8>" in window, window[:300]


def test_local_quantize_error_feedback_conserves():
    """The managed emulation's invariant: quantized + new_residual == grads +
    old_residual exactly (both sides are the same f32 subtraction)."""
    g = {"w": jnp.asarray(np.random.RandomState(0).randn(32).astype(np.float32))}
    r = comm_lib.init_residual_tree(g)
    q, r1 = comm_lib.local_quantize(g, r, "bf16_ef")
    np.testing.assert_array_equal(
        np.asarray(q["w"] + r1["w"]), np.asarray(g["w"] + r["w"])
    )
    # and the quantized value really is bf16-representable
    qw = np.asarray(q["w"])
    np.testing.assert_array_equal(
        qw, qw.astype(jnp.bfloat16).astype(np.float32)
    )
    # hook "none" is the identity; "bf16" carries no residual
    g2, r2 = comm_lib.local_quantize(g, None, "none")
    assert g2 is g and r2 is None
    q3, r3 = comm_lib.local_quantize(g, None, "bf16")
    assert r3 is None and np.any(np.asarray(q3["w"]) != np.asarray(g["w"]))


@pytest.mark.parametrize("hook", ["int8_ef", "topk_ef"])
def test_local_quantize_int8_topk_conserves(hook):
    """The managed emulation of the quantized/sparse hooks keeps the EF
    invariant exactly (quantized + residual == send, both sides the same
    f32 subtraction), produces genuinely int8-representable values, and —
    for topk — keeps at most ceil(density * n) nonzeros per leaf."""
    vals = np.random.RandomState(0).randn(64).astype(np.float32)
    g = {"w": jnp.asarray(vals)}
    r = comm_lib.init_residual_tree(g)
    q, r1 = comm_lib.local_quantize(g, r, hook, density=0.25)
    np.testing.assert_array_equal(
        np.asarray(q["w"] + r1["w"]), np.asarray(g["w"] + r["w"])
    )
    qw = np.asarray(q["w"])
    scale = np.abs(vals).max() / 127.0
    codes = qw / scale
    np.testing.assert_allclose(codes, np.round(codes), atol=1e-4)
    assert np.abs(codes).max() <= 127.5
    if hook == "topk_ef":
        k = comm_lib.bucket_topk(64, 0.25)
        assert np.count_nonzero(qw) <= k
        # what it kept really is the top-|.| slice of the send
        kept_idx = np.nonzero(qw)[0]
        thresh = np.sort(np.abs(vals))[-k]
        assert np.all(np.abs(vals[kept_idx]) >= thresh - 1e-6)


# ------------------------------------------------------------ checkpoints --


@pytest.mark.parametrize("hook", ["bf16_ef", "int8_ef", "topk_ef"])
def test_native_residual_checkpoint_roundtrip(cpu_devices, tmp_path, hook):
    """Every EF hook's residual is training state: nonzero after steps,
    lossless across the native checkpoint, trains on after restore (scales
    are recomputed per step — never checkpointed, so nothing else rides)."""
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    ddp = build(mesh, hook)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    for _ in range(3):
        st, _ = ddp.train_step(st, ddp.shard((x, y, w)))
    res = np.asarray(st.comm_state)
    assert np.any(res != 0)
    path = ckpt.save(str(tmp_path / "ckpt_1.npz"), st)
    # a fresh same-shape state is the load template (the loop's resume path)
    ddp2 = build(mesh, hook)
    st2 = ddp2.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    restored = ckpt.load(path, st2)
    np.testing.assert_array_equal(np.asarray(restored.comm_state), res)
    # and the restored state trains on (placement re-established by the jit)
    st3, m = ddp2.train_step(restored, ddp2.shard((x, y, w)))
    assert np.isfinite(float(np.sum(np.asarray(m["loss_sum"]))))
    assert np.any(np.asarray(st3.comm_state) != res)


def test_hookless_checkpoint_structure_unchanged(cpu_devices, tmp_path):
    """comm_state=None must not appear as a checkpoint leaf: hook-less
    checkpoints keep their historical structure (old checkpoints stay
    loadable, new hook-less ones stay loadable by old code)."""
    mesh = make_mesh(cpu_devices)
    ddp = build(mesh, "none")
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    path = ckpt.save(str(tmp_path / "ckpt_1.npz"), st)
    with np.load(path) as data:
        assert not any("comm_state" in k for k in data.files)


def test_pre_hook_checkpoint_loads_into_ef_template(cpu_devices, tmp_path):
    """Turning comm_hook="bf16_ef" ON over checkpoints from a hook-less run
    must resume, not crash: the missing residual leaf keeps the template's
    zero initialization (exactly a fresh compressed run's starting state)."""
    mesh = make_mesh(cpu_devices)
    ddp = build(mesh, "none")
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    path = ckpt.save(str(tmp_path / "ckpt_1.npz"), st)  # no comm_state leaf
    ef = build(mesh, "bf16_ef")
    st2 = ef.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    restored = ckpt.load(path, st2)
    assert not np.any(np.asarray(restored.comm_state))
    x, y, w = make_batch()
    st3, m = ef.train_step(restored, ef.shard((x, y, w)))
    assert np.isfinite(float(np.sum(np.asarray(m["loss_sum"]))))
    assert np.any(np.asarray(st3.comm_state) != 0)


def test_managed_residual_roundtrip(cpu_devices, tmp_path):
    from tpuddp.accelerate import Accelerator

    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch(n=32)
    criterion = nn.CrossEntropyLoss()

    def steps(acc, model, opt, n):
        last = None
        for _ in range(n):
            opt.zero_grad()
            loss = criterion(model(x), y, w)
            acc.backward(loss)
            opt.step()
            last = loss.item()
        return last

    acc = Accelerator(mesh=mesh, seed=3, comm_hook="bf16_ef")
    model, opt = acc.prepare(ToyMLP(hidden=(16,)), optim.Adam(1e-2))
    steps(acc, model, opt, 3)
    assert opt._comm_state is not None
    res = jax.tree_util.tree_map(np.asarray, opt._comm_state)
    assert any(np.any(l != 0) for l in jax.tree_util.tree_leaves(res))
    assert opt.grad_comm_bytes_per_step is not None
    acc.save_state(model, opt, str(tmp_path), epoch=1)
    cont = steps(acc, model, opt, 2)  # the run we must be able to reproduce

    acc2 = Accelerator(mesh=mesh, seed=3, comm_hook="bf16_ef")
    model2, opt2 = acc2.prepare(ToyMLP(hidden=(16,)), optim.Adam(1e-2))
    model2(x[:1])  # materialize structure to load into
    assert acc2.load_state(model2, opt2, str(tmp_path)) == 2
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        opt2._comm_state, res,
    )
    resumed = steps(acc2, model2, opt2, 2)
    np.testing.assert_allclose(resumed, cont, rtol=0, atol=1e-6)
