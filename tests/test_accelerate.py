"""Accelerator facade (SURVEY.md §2b #15): API-shape parity, lazy fwd/bwd
bridge correctness, and managed-vs-explicit backend equivalence."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import nn, optim
from tpuddp.accelerate import Accelerator, LazyForward, LazyLoss, PreparedOptimizer
from tpuddp.data import DataLoader, ShardedDataLoader, SyntheticClassification
from tpuddp.models import ToyMLP
from tpuddp.parallel import make_mesh


@pytest.fixture()
def acc(mesh):
    return Accelerator(mesh=mesh, seed=0)


def test_topology_properties(acc):
    assert acc.num_processes == 1
    assert acc.process_index == 0
    assert acc.is_main_process and acc.is_local_main_process
    assert acc.device is acc.mesh.devices.flat[0]


def test_prepare_wraps_and_shards(acc):
    ds = SyntheticClassification(n=64, shape=(8, 8, 3))
    loader = DataLoader(ds, batch_size=4, shuffle=True)
    model, opt, prepared_loader = acc.prepare(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), loader
    )
    assert isinstance(opt, PreparedOptimizer)
    assert isinstance(prepared_loader, ShardedDataLoader)
    assert prepared_loader.batch_size == 4  # per-replica, HF semantics
    assert prepared_loader.world_size == 8


def test_prepare_rejects_unknown(acc):
    with pytest.raises(TypeError):
        acc.prepare(42)


def test_lazy_forward_and_loss_bridge(acc):
    model, opt = acc.prepare(ToyMLP(hidden=(16,)), optim.Adam(1e-2))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 8, 8, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)

    outputs = model(x)
    assert isinstance(outputs, LazyForward)
    loss = criterion(outputs, y)
    assert isinstance(loss, LazyLoss)

    # item() without backward: forward-only path
    v1 = loss.item()
    assert v1 > 0

    # backward populates grads; step applies them
    acc.backward(loss)
    assert model._pending_grads is not None
    p_before = jax.tree_util.tree_map(np.asarray, model.params)
    opt.step()
    assert model._pending_grads is None
    moved = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(
            lambda a, b: np.any(np.asarray(a) != b), model.params, p_before
        )
    )
    assert any(bool(m) for m in moved)


def test_step_without_backward_raises(acc):
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.1))
    model(np.zeros((8, 4, 4, 3), np.float32))  # init params
    with pytest.raises(RuntimeError, match="backward"):
        opt.step()


def test_outputs_materialize_for_eval(acc):
    model = acc.prepare(ToyMLP(hidden=(8,)))
    model.eval()
    x = np.zeros((4, 4, 4, 3), np.float32)
    outputs = model(x)
    assert np.asarray(outputs).shape == (4, 10)
    assert outputs.argmax(axis=-1).shape == (4,)


def test_managed_training_matches_explicit_ddp(cpu_devices):
    """Two-level API contract (SURVEY.md §1): the managed path must produce
    the same parameter trajectory as the explicit DDP path."""
    from tpuddp.nn.core import Context
    from tpuddp.parallel.ddp import DistributedDataParallel

    mesh = make_mesh(cpu_devices)
    ds = SyntheticClassification(n=64, shape=(8, 8, 3), seed=5)
    x, y = ds.get_batch(np.arange(64))
    w = np.ones(64, np.float32)

    # managed
    acc = Accelerator(mesh=mesh, seed=0)
    m_model, m_opt = acc.prepare(ToyMLP(hidden=(16,)), optim.Adam(1e-2))
    criterion = nn.CrossEntropyLoss()
    m_model(x)  # trigger lazy init
    init_params = jax.tree_util.tree_map(np.asarray, m_model.params)
    for _ in range(3):
        loss = criterion(m_model(x), y, w)
        acc.backward(loss)
        m_opt.step()

    # explicit path, seeded with the managed model's initial params
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), criterion, mesh=mesh, mode="auto"
    )
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    state = state.__class__(
        params=jax.tree_util.tree_map(jnp.asarray, init_params),
        model_state=state.model_state,
        opt_state=state.opt_state,
        step=state.step,
        rng=state.rng,
    )
    for _ in range(3):
        state, _ = ddp.train_step(state, ddp.shard((x, y, w)))

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        m_model.params,
        state.params,
    )


def test_save_model_writes_unwrapped_weights(acc, tmp_path):
    model = acc.prepare(ToyMLP(hidden=(8,)))
    model(np.zeros((4, 4, 4, 3), np.float32))
    acc.wait_for_everyone()
    acc.save_model(model, str(tmp_path))
    assert os.path.exists(tmp_path / "model.npz")
    from tpuddp.training import checkpoint as ckpt

    restored = ckpt.load(
        str(tmp_path / "model.npz"),
        {"params": model.params, "model_state": model.model_state},
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored["params"],
        model.params,
    )


def test_gather_single_process(acc):
    x = jnp.arange(8.0)
    np.testing.assert_array_equal(acc.gather(x), np.arange(8.0))


def test_deferred_metrics_matches_eager(cpu_devices):
    """Deferred vs eager metric reads must be numerically identical. The
    train pass always drains losses at epoch end now (the async pipeline
    retired the per-batch loss.item() sync — quirk Q5); the deferred knob
    still selects the fused vs facade EVAL path, and fuse_steps the scan
    batching — neither may change the metrics."""
    import train_accelerate as ta
    from tpuddp.data.transforms import make_eval_transform, make_train_augment

    mesh = make_mesh(cpu_devices)
    results = []
    # (deferred, fuse_steps): fuse=3 over 8 batches exercises two full scan
    # flushes plus an epoch-end remainder flush triggered by the loss reads
    for deferred, fuse in ((False, 1), (True, 1), (True, 3)):
        accel = Accelerator(mesh=mesh, seed=7, fuse_steps=fuse)
        ds = SyntheticClassification(n=64, shape=(8, 8, 3), seed=3)
        train_loader = DataLoader(ds, batch_size=8, shuffle=True)
        test_loader = DataLoader(ds, batch_size=8)
        model, opt, prepared_loader = accel.prepare(
            ToyMLP(hidden=(16,)), optim.Adam(1e-2), train_loader
        )
        criterion = nn.CrossEntropyLoss()
        _aug = make_train_augment(size=None)
        # the entrypoint's augment shape: per-batch key folded inside the jit
        augment = jax.jit(lambda rng, i, v: _aug(jax.random.fold_in(rng, i), v))
        eval_tf = jax.jit(make_eval_transform(size=None))
        prepared_loader.set_epoch(0)
        tr, n_tr = ta.train(
            model, prepared_loader, criterion, opt, accel, augment
        )
        te, pct, n_te = ta.evaluate(
            model, test_loader, criterion, accel.device, eval_tf, deferred=deferred
        )
        assert n_tr == 64.0 and n_te == 64
        results.append((tr, te, pct))
    np.testing.assert_allclose(results[0], results[1], rtol=1e-6)
    # scan fusion must be a pure batching change: identical metrics
    np.testing.assert_allclose(results[0], results[2], rtol=1e-5)


def test_fused_evaluator_matches_eager_eval(mesh):
    """FusedEvaluator (one scan dispatch per K batches) must reproduce the
    facade eval loop's numbers exactly: same loss sum, correct count, and
    total — including a padded last batch and a remainder group < K."""
    from tpuddp.accelerate import FusedEvaluator
    from tpuddp.data.transforms import make_eval_transform

    acc = Accelerator(mesh=mesh, seed=0)
    model = acc.prepare(ToyMLP(hidden=(16,)))
    model.eval()
    criterion = nn.CrossEntropyLoss()
    transform = jax.jit(make_eval_transform(size=None))
    ds = SyntheticClassification(n=52, shape=(8, 8, 3), seed=2)
    loader = DataLoader(ds, batch_size=8)  # 7 batches, last one padded (w=0)

    # eager oracle (the facade loop, 2+ dispatches per batch)
    loss_sum = correct = total = 0.0
    for x, y, w in loader:
        outputs = model(transform(jnp.asarray(x)))
        loss_sum += float(criterion(outputs, y, w).item())
        pred = np.asarray(outputs.argmax(axis=-1))
        mask = w > 0
        correct += int(((pred == y) & mask).sum())
        total += int(mask.sum())

    ev = FusedEvaluator(model, criterion, transform=transform, fuse_steps=4)
    for x, y, w in loader:  # 7 batches: one full flush of 4, remainder of 3
        ev.add(x, y, w)
    f_loss, f_correct, f_total = ev.finalize()
    assert f_total == int(total) == 52
    assert f_correct == int(correct)
    np.testing.assert_allclose(f_loss, loss_sum, rtol=1e-5)
    # evaluator is reusable: a second pass starts from zero
    for x, y, w in loader:
        ev.add(x, y, w)
    f_loss2, f_correct2, f_total2 = ev.finalize()
    assert (f_loss2, f_correct2, f_total2) == (f_loss, f_correct, f_total)


def test_staged_upload_loader_preserves_stream(mesh):
    """StagedUploadLoader must yield the same batches in the same order, with
    x already a device array, and delegate set_epoch/len."""
    from tpuddp.accelerate import StagedUploadLoader

    ds = SyntheticClassification(n=40, shape=(4, 4, 3), seed=1)
    inner = DataLoader(ds, batch_size=8, shuffle=True)
    staged = StagedUploadLoader(inner)
    assert len(staged) == len(inner)

    staged.set_epoch(3)
    expect = [(x.copy(), y.copy(), w.copy()) for x, y, w in inner]  # epoch 3 order
    got = list(staged)
    assert len(got) == len(expect)
    for (xe, ye, we), (xg, yg, wg) in zip(expect, got):
        assert isinstance(xg, jax.Array)
        np.testing.assert_array_equal(np.asarray(xg), xe)
        np.testing.assert_array_equal(yg, ye)
        np.testing.assert_array_equal(wg, we)


def test_superseded_backward_loss_refuses_silent_recompute(acc):
    """A loss whose pending backward was dropped (second backward before
    step, or zero_grad) must raise rather than silently recompute with the
    CURRENT params and a fresh RNG key."""
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.1))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)

    loss1 = criterion(model(x), y)
    acc.backward(loss1)
    loss2 = criterion(model(x), y)
    acc.backward(loss2)  # supersedes loss1's unexecuted backward
    opt.step()
    with pytest.raises(RuntimeError, match="dropped"):
        loss1.item()
    assert loss2.item() > 0  # the executed backward's loss is intact

    loss3 = criterion(model(x), y)
    acc.backward(loss3)
    opt.zero_grad()  # clears the pending backward
    with pytest.raises(RuntimeError, match="dropped"):
        loss3.item()

    # a loss read BEFORE being superseded keeps its (materialized) value
    loss4 = criterion(model(x), y)
    acc.backward(loss4)
    v4 = loss4.item()
    loss5 = criterion(model(x), y)
    acc.backward(loss5)
    opt.step()
    assert loss4.item() == v4

    # forward-only eval losses (no backward ever requested) still compute
    eval_loss = criterion(model(x), y)
    assert eval_loss.item() > 0


def test_fuse_queue_flushes_before_params_are_read(mesh):
    """With fuse_steps > 1, queued updates must land before any read of the
    model: a forward, a loss read, or save_model all trigger a flush."""
    acc = Accelerator(mesh=mesh, seed=1, fuse_steps=4)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.5))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)

    model(x)  # init
    p0 = jax.tree_util.tree_map(np.asarray, model.params)
    losses = []
    for _ in range(2):  # fewer than fuse_steps: stays queued
        loss = criterion(model(x), y)
        acc.backward(loss)
        opt.step()
        losses.append(loss)
    assert len(opt._queue) == 2

    # a concrete forward flushes the queue so it sees updated params
    model.eval()
    _ = np.asarray(model(x))
    assert opt._queue == []
    moved = any(
        bool(np.any(np.asarray(a) != b))
        for a, b in zip(
            jax.tree_util.tree_leaves(model.params),
            jax.tree_util.tree_leaves(p0),
        )
    )
    assert moved
    # queued losses got their values from the scan's loss stack
    assert all(l.device_value() is not None for l in losses)
    assert losses[0].item() != losses[1].item()


def test_prepare_passes_drop_last_through(acc):
    ds = SyntheticClassification(n=70, shape=(4, 4, 3))
    loader = DataLoader(ds, batch_size=4, shuffle=True, drop_last=True)
    prepared = acc.prepare(loader)
    assert prepared.drop_last is True
    # 70 samples / 8 replicas -> sampler pads to 72 -> 9 per replica;
    # drop_last drops the partial batch of 1: 2 full batches of 4
    assert len(prepared) == 2


def test_accelerator_honors_num_chips_subworld(cpu_devices):
    acc = Accelerator(num_chips=4, seed=0)
    assert acc.mesh.devices.size == 4


def test_params_read_flushes_fuse_queue(mesh):
    """A direct model.params read (weight-norm logging, gather) must never
    see values that are K queued updates stale."""
    acc = Accelerator(mesh=mesh, seed=2, fuse_steps=4)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.5))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)
    model(x)
    p0 = jax.tree_util.tree_map(np.asarray, model.params)
    for _ in range(2):
        loss = criterion(model(x), y)
        acc.backward(loss)
        opt.step()
    assert len(opt._queue) == 2
    p_now = model.params  # property read flushes
    assert opt._queue == []
    moved = any(
        bool(np.any(np.asarray(a) != b))
        for a, b in zip(
            jax.tree_util.tree_leaves(p_now), jax.tree_util.tree_leaves(p0)
        )
    )
    assert moved


def test_failed_flush_marks_queued_losses_dropped(mesh, monkeypatch):
    """If the fused-scan dispatch fails, the queued updates are lost — later
    reads of the queued losses must raise, not silently recompute a forward
    against the un-updated params."""
    acc = Accelerator(mesh=mesh, seed=3, fuse_steps=2)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.5))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)

    model(x)
    loss1 = criterion(model(x), y)
    acc.backward(loss1)
    opt.step()  # queued (1 of 2)
    monkeypatch.setattr(
        opt, "_dispatch_flush",
        lambda q: (_ for _ in ()).throw(RuntimeError("simulated dispatch failure")),
    )
    loss2 = criterion(model(x), y)
    acc.backward(loss2)
    with pytest.raises(RuntimeError, match="simulated"):
        opt.step()  # 2nd entry triggers the (failing) flush
    assert opt._queue == []
    for l in (loss1, loss2):
        assert l._queued_on is None
        with pytest.raises(RuntimeError, match="dispatch failed"):
            l.item()
    # compile-time failure: buffers were never donated, params stay readable
    assert model.params is not None


def test_load_model_restores_saved_weights(acc, tmp_path):
    """Managed resume: save_model -> train further -> load_model returns the
    model to the saved weights (the counterpart the native path has via
    restore_latest)."""
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.5))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)
    model(x)
    acc.save_model(model, str(tmp_path))
    saved = jax.tree_util.tree_map(np.asarray, model.params)

    loss = criterion(model(x), y)
    acc.backward(loss)
    opt.step()  # move away from the saved weights

    acc.load_model(model, str(tmp_path))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        model.params, saved,
    )

    fresh = acc.prepare(ToyMLP(hidden=(8,)))
    with pytest.raises(RuntimeError, match="initialized"):
        acc.load_model(fresh, str(tmp_path))


def test_lost_state_sentinel_reads_raise(acc):
    """If a fused dispatch failed after buffer donation, any read of the
    model's variables must raise a clear error, not JAX's obscure
    'Array has been deleted'."""
    from tpuddp.accelerate import _LOST_TO_FAILED_FLUSH

    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.1))
    model(np.zeros((8, 4, 4, 3), np.float32))
    model._params = model._model_state = _LOST_TO_FAILED_FLUSH
    with pytest.raises(RuntimeError, match="checkpoint"):
        _ = model.params
    with pytest.raises(RuntimeError, match="checkpoint"):
        model._forward_concrete(np.zeros((4, 4, 4, 3), np.float32))
    with pytest.raises(RuntimeError, match="re-prepare"):
        acc.load_model(model, "/nonexistent")


def test_managed_clip_grad_norm_bounds_update(mesh):
    """Accelerator(clip_grad_norm=c): the global-batch gradient is clipped
    before the update (with SGD lr=1 the param delta norm equals c)."""
    acc = Accelerator(mesh=mesh, seed=4, clip_grad_norm=0.05)
    model, opt = acc.prepare(ToyMLP(hidden=(16,)), optim.SGD(1.0))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(16, 8, 8, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 16)
    model(x)
    p0 = jax.tree_util.tree_map(np.asarray, model.params)
    loss = criterion(model(x), y)
    acc.backward(loss)
    opt.step()
    delta = jax.tree_util.tree_map(
        lambda a, b: np.asarray(a) - b, model.params, p0
    )
    norm = float(
        np.sqrt(sum(np.sum(d ** 2) for d in jax.tree_util.tree_leaves(delta)))
    )
    assert norm == pytest.approx(0.05, rel=1e-3)


def test_gradient_accumulation_matches_big_batch(mesh):
    """N micro-batches with gradient_accumulation_steps=N must produce the
    same update as one step on the concatenated batch (mean-of-grads ==
    grad-of-mean for equal shards), including the clip applied to the
    AVERAGED gradient."""
    ds = SyntheticClassification(n=32, shape=(8, 8, 3), seed=11)
    x, y = ds.get_batch(np.arange(32))
    w = np.ones(32, np.float32)
    criterion = nn.CrossEntropyLoss()

    # accumulated: 4 micro-batches of 8
    acc_a = Accelerator(mesh=mesh, seed=5, gradient_accumulation_steps=4,
                        clip_grad_norm=0.5)
    m_a, o_a = acc_a.prepare(ToyMLP(hidden=(16,)), optim.SGD(1.0))
    m_a(x[:8])
    p0 = jax.tree_util.tree_map(np.asarray, m_a.params)
    for i in range(4):
        sl = slice(i * 8, (i + 1) * 8)
        loss = criterion(m_a(x[sl]), y[sl], w[sl])
        acc_a.backward(loss)
        o_a.step()
        o_a.zero_grad()  # HF pattern: safe every batch, must not clear accum

    # big batch: one step on all 32, same init
    acc_b = Accelerator(mesh=mesh, seed=6, clip_grad_norm=0.5)
    m_b, o_b = acc_b.prepare(ToyMLP(hidden=(16,)), optim.SGD(1.0))
    m_b(x)
    m_b.params = jax.tree_util.tree_map(jnp.asarray, p0)
    loss = criterion(m_b(x), y, w)
    acc_b.backward(loss)
    o_b.step()

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        m_a.params, m_b.params,
    )
    # mid-cycle state: a partial accumulation leaves params untouched
    loss = criterion(m_a(x[:8]), y[:8], w[:8])
    acc_a.backward(loss)
    o_a.step()
    assert o_a._accum_count == 1
    assert o_a._accum_grads is not None


def test_accumulation_and_fuse_steps_are_exclusive(mesh):
    with pytest.raises(ValueError, match="exclusive"):
        Accelerator(mesh=mesh, fuse_steps=4, gradient_accumulation_steps=2)
    # "auto" composes: accumulation owns the cadence, fusion yields
    acc = Accelerator(mesh=mesh, fuse_steps="auto", gradient_accumulation_steps=2)
    assert acc.fuse_steps == 1


def test_auto_fuse_steps_resolves_by_model_size(mesh):
    """fuse_steps='auto' resolves at the first step: deep fusion (32) for
    dispatch-bound sub-4MB models — the BASELINE-measured policy, so the
    entrypoint's auto mode matches what the bench publishes."""
    acc = Accelerator(mesh=mesh, seed=3, fuse_steps="auto")
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.1))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)
    assert acc.fuse_steps == "auto"
    loss = criterion(model(x), y)
    acc.backward(loss)
    opt.step()
    assert opt._fuse == 32  # ToyMLP(8) is far under the 4MB threshold
    assert acc.fuse_steps == "auto"  # per-OPTIMIZER: other models resolve anew
    assert len(opt._queue) == 1  # the step queued under the resolved depth
    assert loss.item() > 0  # reads still flush correctly


def test_ragged_batch_stream_flushes_homogeneous_prefix(mesh):
    """A raw (unprepared) loader's smaller last batch must not crash the
    fused-scan stack: the queue flushes its homogeneous prefix on a shape
    change, then queues the new shape."""
    acc = Accelerator(mesh=mesh, seed=6, fuse_steps=8)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.1))
    criterion = nn.CrossEntropyLoss()
    rs = np.random.RandomState(0)
    losses = []
    for n in (16, 16, 16, 8):  # ragged tail, as a raw loop would produce
        x = rs.randn(n, 4, 4, 3).astype(np.float32)
        y = rs.randint(0, 10, n)
        loss = criterion(model(x), y)
        acc.backward(loss)
        opt.step()
        losses.append(loss)
    total = float(sum(l.device_value() for l in losses))
    assert total > 0 and np.isfinite(total)
    assert opt._queue == []


def test_short_epoch_partial_queue_flushes_as_one_scan(mesh):
    """An epoch shorter than the fusion depth must still dispatch as ONE scan
    at flush time — not silently degrade to per-step dispatches."""
    acc = Accelerator(mesh=mesh, seed=4, fuse_steps=32)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.1))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)
    model(x)
    losses = []
    for _ in range(3):  # a 3-batch "epoch", far below fuse=32
        loss = criterion(model(x), y)
        acc.backward(loss)
        opt.step()
        losses.append(loss)
    assert len(opt._queue) == 3
    total = float(sum(l.device_value() for l in losses))  # triggers the flush
    assert total > 0
    assert opt._queue == []
    # the 3-step remainder compiled (and ran) as a K=3 scan program
    assert any(k[-1] == 3 for k in model._fused_scans)
    # all three losses came from the scan's stacked losses, in order
    assert losses[0].item() != losses[2].item()


def test_partial_accumulation_cycle_flushes(mesh):
    """A partial cycle must be applied (averaged over the micro-batches seen)
    by flush_accumulation — the HF dataloader-end contract — not leaked into
    the next epoch or dropped."""
    acc = Accelerator(mesh=mesh, seed=7, gradient_accumulation_steps=4)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(1.0))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)
    model(x)
    p0 = jax.tree_util.tree_map(np.asarray, model.params)
    for _ in range(2):  # partial cycle: 2 of 4
        loss = criterion(model(x), y)
        acc.backward(loss)
        opt.step()
    assert opt._accum_count == 2
    opt.flush_accumulation()
    assert opt._accum_count == 0 and opt._accum_grads is None
    moved = any(
        bool(np.any(np.asarray(a) != b))
        for a, b in zip(
            jax.tree_util.tree_leaves(model.params),
            jax.tree_util.tree_leaves(p0),
        )
    )
    assert moved
    opt.flush_accumulation()  # no-op when empty


def test_accumulation_rejects_double_backward(mesh):
    """The torch-canonical N-backwards-then-one-step pattern must raise under
    gradient accumulation, not silently drop micro-batch gradients."""
    acc = Accelerator(mesh=mesh, seed=8, gradient_accumulation_steps=4)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.1))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)
    acc.backward(criterion(model(x), y))
    with pytest.raises(RuntimeError, match="EACH"):
        acc.backward(criterion(model(x), y))


def test_load_model_clears_stale_accumulation(acc_accum_factory=None):
    """load_model must not let gradients of the pre-restore weights apply on
    top of the restored weights."""
    import tempfile

    from tpuddp.parallel import make_mesh

    mesh = make_mesh(jax.devices("cpu")[:8])
    acc = Accelerator(mesh=mesh, seed=9, gradient_accumulation_steps=4)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(1.0))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)
    model(x)
    with tempfile.TemporaryDirectory() as d:
        acc.save_model(model, d)
        saved = jax.tree_util.tree_map(np.asarray, model.params)
        for _ in range(2):  # mid-cycle accumulation
            loss = criterion(model(x), y)
            acc.backward(loss)
            opt.step()
        assert opt._accum_count == 2
        acc.load_model(model, d)
        assert opt._accum_count == 0 and opt._accum_grads is None
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
            model.params, saved,
        )
        # a fresh cycle works normally after the restore
        for _ in range(4):
            loss = criterion(model(x), y)
            acc.backward(loss)
            opt.step()
        moved = any(
            bool(np.any(np.asarray(a) != b))
            for a, b in zip(
                jax.tree_util.tree_leaves(model.params),
                jax.tree_util.tree_leaves(saved),
            )
        )
        assert moved


def test_restore_discards_queued_steps_without_executing(mesh, tmp_path):
    """load_model/load_state must DROP fused steps queued against the
    pre-restore weights — not execute them (a wasted dispatch whose updates
    the restore overwrites). The queued losses' reads then fail loudly."""
    acc = Accelerator(mesh=mesh, seed=11, fuse_steps=4)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.5))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)
    model(x)
    acc.save_model(model, str(tmp_path))
    saved = jax.tree_util.tree_map(np.asarray, model.params)

    losses = []
    for _ in range(2):  # queued, below fuse_steps=4
        loss = criterion(model(x), y)
        acc.backward(loss)
        opt.step()
        losses.append(loss)
    assert len(opt._queue) == 2
    # a dispatch during the restore would be a bug: make it fail loudly
    opt._dispatch_flush = lambda q: (_ for _ in ()).throw(
        AssertionError("queued steps must be discarded, not executed")
    )
    acc.load_model(model, str(tmp_path))
    assert opt._queue == []
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        model.params, saved,
    )
    for l in losses:
        with pytest.raises(RuntimeError, match="discarded"):
            l.item()


def test_load_model_resets_optimizer_moments(acc, tmp_path):
    """save_model is weights-only: after load_model, Adam moments computed
    against the pre-restore weights must NOT steer updates to the restored
    ones — the optimizer state resets and re-inits on the next step."""
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.Adam(1e-2))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)
    model(x)
    acc.save_model(model, str(tmp_path))
    for _ in range(2):
        loss = criterion(model(x), y)
        acc.backward(loss)
        opt.step()
    assert opt.opt_state is not None
    acc.load_model(model, str(tmp_path))
    assert opt.opt_state is None  # stale moments discarded
    loss = criterion(model(x), y)
    acc.backward(loss)
    opt.step()  # re-inits from zero moments
    assert int(np.asarray(opt.opt_state.step)) == 1


def test_sum_losses_empty_returns_zero():
    from tpuddp.accelerate import sum_losses

    assert float(sum_losses([])) == 0.0


def _kill_and_resume_leg(mesh, tmp_path, resume: bool):
    """One 'process lifetime' of the managed kill-and-resume scenario: fresh
    Accelerator/model/optimizer (what a restarted process has), optional
    load_state, then two deterministic train steps."""
    ds = SyntheticClassification(n=32, shape=(4, 4, 3), seed=13)
    x, y = ds.get_batch(np.arange(16))
    w = np.ones(16, np.float32)
    acc = Accelerator(mesh=mesh, seed=21)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.Adam(1e-2))
    criterion = nn.CrossEntropyLoss()
    model(x)  # lazy init: creates the structure load_state needs
    if resume:
        start = acc.load_state(model, opt, str(tmp_path))
        assert start == 4  # saved with epoch=3
    for _ in range(2):
        loss = criterion(model(x), y, w)
        acc.backward(loss)
        opt.step()
    return acc, model, opt


def test_save_state_load_state_lossless_resume(mesh, tmp_path):
    """The managed kill-and-resume contract (native analog: restore_latest on
    the full TrainState): a run that dies after save_state and restarts with
    load_state must continue BIT-EXACTLY like the run that never died —
    weights, Adam moments, and the RNG stream all restored."""
    ds = SyntheticClassification(n=32, shape=(4, 4, 3), seed=13)
    x, y = ds.get_batch(np.arange(16))
    w = np.ones(16, np.float32)

    # continuous run: 3 steps, save full state, 2 more steps
    acc = Accelerator(mesh=mesh, seed=21)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.Adam(1e-2))
    criterion = nn.CrossEntropyLoss()
    for _ in range(3):
        loss = criterion(model(x), y, w)
        acc.backward(loss)
        opt.step()
    acc.save_state(model, opt, str(tmp_path), epoch=3)
    assert os.path.exists(tmp_path / "state_3.npz")
    for _ in range(2):
        loss = criterion(model(x), y, w)
        acc.backward(loss)
        opt.step()
    expect_params = jax.tree_util.tree_map(np.asarray, model.params)
    expect_m = jax.tree_util.tree_map(np.asarray, opt.opt_state.m)

    # killed + restarted run: fresh everything, load_state, same 2 steps
    _, model2, opt2 = _kill_and_resume_leg(mesh, tmp_path, resume=True)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        model2.params, expect_params,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        opt2.opt_state.m, expect_m,
    )
    assert int(np.asarray(opt2.opt_state.step)) == 5

    # without resume, the fresh run diverges (proves the restore did the work)
    _, model3, _ = _kill_and_resume_leg(mesh, str(tmp_path / "nope"), resume=False)
    diverged = any(
        bool(np.any(np.asarray(a) != b))
        for a, b in zip(
            jax.tree_util.tree_leaves(model3.params),
            jax.tree_util.tree_leaves(expect_params),
        )
    )
    assert diverged


def test_load_state_empty_dir_is_fresh_start(mesh, tmp_path):
    acc = Accelerator(mesh=mesh, seed=0)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.Adam(1e-2))
    model(np.zeros((8, 4, 4, 3), np.float32))
    assert acc.load_state(model, opt, str(tmp_path / "none")) == 0


def test_save_state_rejects_mid_accumulation_cycle(mesh, tmp_path):
    acc = Accelerator(mesh=mesh, seed=1, gradient_accumulation_steps=4)
    model, opt = acc.prepare(ToyMLP(hidden=(8,)), optim.SGD(0.1))
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)
    loss = criterion(model(x), y)
    acc.backward(loss)
    opt.step()  # 1 of 4: mid-cycle
    with pytest.raises(RuntimeError, match="accumulation"):
        acc.save_state(model, opt, str(tmp_path))
    opt.flush_accumulation()
    acc.save_state(model, opt, str(tmp_path))  # boundary: fine


def test_state_dtype_mismatch_names_the_leaf(mesh, tmp_path):
    """Restoring bf16-moment state into an f32-state run must fail with the
    optimizer_state_dtype hint, not load garbage."""
    import jax.numpy as jnp

    acc = Accelerator(mesh=mesh, seed=2)
    model, opt = acc.prepare(
        ToyMLP(hidden=(8,)), optim.Adam(1e-2, state_dtype=jnp.bfloat16)
    )
    criterion = nn.CrossEntropyLoss()
    x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 8)
    loss = criterion(model(x), y)
    acc.backward(loss)
    opt.step()
    acc.save_state(model, opt, str(tmp_path))

    acc2 = Accelerator(mesh=mesh, seed=2)
    model2, opt2 = acc2.prepare(ToyMLP(hidden=(8,)), optim.Adam(1e-2))
    model2(x)
    with pytest.raises(ValueError, match="optimizer_state_dtype"):
        acc2.load_state(model2, opt2, str(tmp_path))


class _SequentialSampler:
    """A deliberate, custom ordering (reversed indices) with the sampler
    protocol — prepare() must preserve it, not silently reshuffle."""

    def __init__(self, n):
        self.n = n
        self.epoch = 0

    def __iter__(self):
        return iter(range(self.n - 1, -1, -1))

    def __len__(self):
        return self.n

    def set_epoch(self, epoch):
        self.epoch = epoch


def test_prepare_preserves_custom_sampler_order(acc):
    """HF contract: a user sampler rides inside the sharded batch sampler.
    The prepared loader must yield batches derived from the SAMPLER's order
    (strided across replicas, DistributedSampler-style), not a reshuffle."""
    ds = SyntheticClassification(n=32, shape=(4, 4, 3), seed=3)
    sampler = _SequentialSampler(32)
    loader = DataLoader(ds, batch_size=2, sampler=sampler)
    prepared = acc.prepare(loader)
    assert prepared.base_sampler is sampler
    prepared.set_epoch(5)
    assert sampler.epoch == 5  # set_epoch reaches the user sampler

    order = np.arange(31, -1, -1)
    world = 8
    batches = list(prepared)
    assert len(batches) == 2  # 32 / 8 replicas / batch 2
    for s, (xb, yb, wb) in enumerate(batches):
        expect_idx = np.concatenate(
            [order[r::world][s * 2 : (s + 1) * 2] for r in range(world)]
        )
        ex, ey = ds.get_batch(expect_idx)
        np.testing.assert_array_equal(yb, ey)
        np.testing.assert_array_equal(xb, ex)
        assert wb.all()


def test_train_mode_forward_masks_padded_rows(mesh):
    """A materialized train-mode forward must exclude padded (w=0) rows from
    BatchNorm batch statistics, consistent with the grad/fused/scan steps:
    real-row logits match a forward over just the real rows."""
    from tpuddp.nn.core import Module

    acc = Accelerator(mesh=mesh, seed=5)
    module = nn.Sequential(nn.BatchNorm(), nn.Flatten(), nn.Linear(10))
    model = acc.prepare(module)
    model.train()
    criterion = nn.CrossEntropyLoss()

    rs = np.random.RandomState(0)
    x = rs.randn(8, 4, 4, 3).astype(np.float32)
    x[6:] = 100.0  # garbage padding rows that would skew batch stats
    y = rs.randint(0, 10, 8)
    w = np.ones(8, np.float32)
    w[6:] = 0.0

    out = model(x)
    criterion(out, y, w)  # binds the weights to this forward
    padded_logits = np.asarray(out)[:6]

    real_logits = np.asarray(model(x[:6]))  # stats over the same 6 real rows
    np.testing.assert_allclose(padded_logits, real_logits, rtol=1e-4, atol=1e-5)


def test_auto_fuse_respects_staging_budget():
    """The managed auto depth is flat 32 capped by the ~256MB queued-batch
    staging budget (same bound as the native scan_steps auto), so a
    large-input model cannot queue gigabytes of device batches by default."""
    from tpuddp.accelerate import _resolve_auto_fuse

    assert _resolve_auto_fuse(None) == 32
    # 128 x 224x224x3 bf16 batches: 38.5MB each -> cap 6
    assert _resolve_auto_fuse(None, batch_nbytes=38_535_168) == 6
    assert _resolve_auto_fuse(None, batch_nbytes=400_000) == 32
    assert _resolve_auto_fuse(None, batch_nbytes=10**10) == 1


def test_fused_evaluator_rederives_depth_on_ragged_streams(mesh):
    """ISSUE 2 satellite (advisor r5): the auto fuse depth is cached per
    batch SHAPE, not pinned for the evaluator's lifetime — a depth resolved
    from an early small batch must not let a later large batch stage
    depth x batch bytes past the ~256 MB staging budget."""
    from tpuddp.accelerate import FusedEvaluator, _resolve_auto_fuse

    acc = Accelerator(mesh=mesh, seed=0)
    model = acc.prepare(ToyMLP(hidden=(16,)))
    model.eval()
    criterion = nn.CrossEntropyLoss()
    ev = FusedEvaluator(model, criterion)  # fuse_steps=None -> auto

    small = np.zeros((4, 8, 8, 3), np.float32)
    y4 = np.zeros(4, np.int32)
    model(small)  # materialize params so the depth resolution caches
    ev.add(small, y4)
    assert ev._resolve_fuse() == 32  # tiny batches: the flat auto cap
    ev.finalize()  # drain the small-shape stream

    # a late LARGE batch (224x224 f32, ~77 MB logical — broadcast view, no
    # real allocation): the shape change must re-derive and re-cap the depth
    big = np.broadcast_to(np.zeros((1, 1, 1, 1), np.float32), (128, 224, 224, 3))
    ev.add(big, np.zeros(128, np.int32))
    depth = ev._resolve_fuse()
    assert depth == _resolve_auto_fuse(model._params, big.nbytes) < 32
    ev._queue.clear()  # the broadcast stand-in is never evaluated

    # and back to small: re-derived again, not stuck on the big-batch cap
    ev.add(small, y4)
    assert ev._resolve_fuse() == 32
    ev._queue.clear()
