"""Launcher contracts (SURVEY.md §2b #14): worker signature, exception
propagation (mp.spawn join=True analog), re-exec no-op conditions."""

import pytest

from tpuddp.parallel import backend
from tpuddp.parallel.spawn import maybe_reexec_for_world, run_ddp_training


@pytest.fixture(autouse=True)
def fresh():
    backend.cleanup()
    yield
    backend.cleanup()


def test_worker_called_with_rank_world_save_args(tmp_path):
    calls = []

    def worker(rank, world_size, save_dir, optional_args):
        calls.append((rank, world_size, save_dir, optional_args))

    run_ddp_training(worker, 4, str(tmp_path), {"set_epoch": True}, backend="cpu")
    assert calls == [(0, 4, str(tmp_path), {"set_epoch": True})]
    assert not backend.is_initialized()  # cleanup ran


def test_worker_exception_propagates(tmp_path):
    def worker(rank, world_size, save_dir, optional_args):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        run_ddp_training(worker, 2, str(tmp_path), {}, backend="cpu")
    assert not backend.is_initialized()  # cleanup still ran (join=True contract)


def test_reexec_noop_when_devices_sufficient():
    # 8 virtual CPU devices exist in the test world: must not exec
    maybe_reexec_for_world(8, "cpu")


def test_reexec_guard_detects_failed_expansion(monkeypatch):
    monkeypatch.setenv("TPUDDP_SPAWNED", "1")
    with pytest.raises(RuntimeError, match="re-exec"):
        maybe_reexec_for_world(4096, "cpu")


def test_multihost_reexec_flag_match_is_exact(monkeypatch):
    """A pre-existing --xla_force_host_platform_device_count=16 must NOT
    satisfy a desired =1 via substring containment; the launcher replaces a
    wrong pre-set count instead of skipping the re-exec."""
    from tpuddp.parallel import spawn

    captured = {}

    def fake_exec(exe, argv, env):
        captured["flags"] = env["XLA_FLAGS"]

    monkeypatch.setattr(spawn.os, "execvpe", fake_exec)
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=16")
    monkeypatch.delenv(spawn._REEXEC_GUARD, raising=False)
    spawn.maybe_reexec_for_multihost_world(2, 2, backend="cpu")
    assert captured["flags"] == "--xla_force_host_platform_device_count=1"

    # exact match -> no re-exec
    captured.clear()
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    spawn.maybe_reexec_for_multihost_world(2, 2, backend="cpu")
    assert captured == {}
