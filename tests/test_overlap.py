"""Segmented backward/collective overlap (``comm_overlap``, training/step.py
+ parallel/comm.py::make_segments) — torch DDP's ready-bucket overlap rebuilt
inside the compiled step.

Pinned contracts:

- segment derivation: boundaries are exactly the layer boundaries that
  coincide with bucket edges; buckets are never split; zero-param children
  attach to the neighboring segment; the tail segment absorbs the padding;
- bitwise parity: overlap-on and overlap-off produce bit-identical loss
  trajectories, params, and comm_state for EVERY hook (none/bf16_ef/
  int8_ef/topk_ef), with and without grad accumulation, and under the guard;
- byte accounting: segmentation can never change (or double-count) the wire
  bytes — the per-segment payload sums to the barrier-mode counter exactly,
  scales/indices included (satellite: CommBytesCounter/comm_bytes_breakdown
  formula pin);
- guard firewall: a poisoned step is a no-op over EVERY segment's residual
  slice, not just the whole vector;
- eligibility: ``auto`` falls back to the barrier builder with a recorded
  reason wherever genuine segmentation is impossible (auto mode, WUS,
  hierarchical, model axis, non-Sequential, single segment); ``true``
  refuses loudly on the same matrix;
- checkpoints: a segmented run's comm_state restores bitwise into a
  barrier-mode run (and back), and rides the elastic 4 -> 2 redistribution
  unchanged;
- HLO: the overlap-on step's lowered program holds K > 1 collectives with
  backward compute between them; barrier mode keeps one trailing block
  (comm.hlo_overlap_evidence — the same detector bench.py and the gate use).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import nn, optim
from tpuddp.data import SyntheticClassification
from tpuddp.models import ToyMLP
from tpuddp.observability.metrics import CommBytesCounter
from tpuddp.parallel import comm as comm_lib
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.training import checkpoint as ckpt
from tpuddp.training.step import stack_batches

KEY = jax.random.key(0)
MB = 1024 * 1024
HOOKS = ("none", "bf16_ef", "int8_ef", "topk_ef")


def cap_mb(elems: int) -> float:
    """bucket_cap_mb holding exactly ``elems`` f32 elements."""
    return elems * 4 / MB


def make_batch(n=64, seed=5, shape=(8, 8, 3)):
    ds = SyntheticClassification(n=n, shape=shape, seed=seed)
    x, y = ds.get_batch(np.arange(n))
    return x, y, np.ones(n, np.float32)


# ToyMLP(hidden=(16,)) on 8x8x3 inputs: Flatten -> Linear(192,16) -> ReLU ->
# Linear(16,10). A 600-element cap splits the two Linears into separate
# buckets, so the segmented step genuinely gets K=2.
SPLIT_CAP = cap_mb(600)


def build(cpu_devices, overlap, hook="bf16_ef", world=8, cap=SPLIT_CAP, **kw):
    if kw.get("comm_topology") == "hierarchical":
        from tpuddp.parallel.mesh import hierarchical_mesh

        mesh = hierarchical_mesh(devices=cpu_devices[:world])
    else:
        mesh = make_mesh(cpu_devices[:world])
    return DistributedDataParallel(
        ToyMLP(hidden=(16,)),
        optim.Adam(1e-2),
        nn.CrossEntropyLoss(),
        mesh=mesh,
        comm_hook=hook,
        bucket_cap_mb=cap,
        comm_overlap=overlap,
        **kw,
    )


def run_steps(ddp, steps=4, accum=1, batches=None):
    """Train ``steps`` updates; returns (meta, losses, state)."""
    x, y, w = make_batch()
    state = ddp.init_state(KEY, x[:8])
    losses = []
    for i in range(steps):
        xb, yb, wb = batches[i] if batches else make_batch(seed=100 + i)
        if accum == 1:
            state, m = ddp.train_step(state, ddp.shard((xb, yb, wb)))
        else:
            half = len(xb) // accum
            micros = [
                (xb[j * half:(j + 1) * half], yb[j * half:(j + 1) * half],
                 wb[j * half:(j + 1) * half])
                for j in range(accum)
            ]
            state, m = ddp.train_step_many(
                state, ddp.shard_stacked(stack_batches(micros))
            )
        m = jax.device_get(m)
        losses.append(float(np.sum(m["loss_sum"]) / np.sum(m["n"])))
    return ddp.comm_overlap_meta, losses, state


def assert_states_equal(a, b):
    for pa, pb in zip(
        jax.tree_util.tree_leaves(a.params), jax.tree_util.tree_leaves(b.params)
    ):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
    if a.comm_state is not None or b.comm_state is not None:
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a.comm_state)),
            np.asarray(jax.device_get(b.comm_state)),
        )


# ------------------------------------------------------- make_segments -----


def test_segments_follow_bucket_aligned_layer_boundaries():
    # layers of 6/6/6 elements, buckets of 12+12: only the 12 boundary is
    # both a layer edge and a bucket edge -> two segments of (2, 1) layers
    buckets = comm_lib.make_buckets((6, 6, 6), total=24, bucket_cap_mb=cap_mb(12))
    segs = comm_lib.make_segments((6, 6, 6), buckets, 24)
    assert [s.flat for s in segs] == [(0, 12), (12, 24)]
    assert [s.layers for s in segs] == [(0, 2), (2, 3)]
    assert [s.buckets for s in segs] == [((0, 12),), ((12, 24),)]


def test_segments_never_split_a_bucket():
    # one bucket straddles the layer-1/layer-2 boundary: those layers fuse
    buckets = ((0, 10), (10, 24))
    segs = comm_lib.make_segments((6, 6, 12), buckets, 24)
    assert len(segs) == 1  # no layer edge lands on a bucket edge
    assert segs[0].flat == (0, 24)
    assert segs[0].layers == (0, 3)
    assert segs[0].buckets == buckets


def test_segments_zero_param_children_attach():
    # Flatten(0) Linear(8) ReLU(0) Linear(8): zero-param children never
    # create zero-width segments; trailing ones attach to the last segment
    buckets = comm_lib.make_buckets((0, 8, 0, 8), total=16, bucket_cap_mb=cap_mb(8))
    segs = comm_lib.make_segments((0, 8, 0, 8), buckets, 16)
    assert [s.flat for s in segs] == [(0, 8), (8, 16)]
    assert segs[0].layers == (0, 3) or segs[0].layers == (0, 2)
    assert segs[-1].layers[1] == 4  # trailing children covered
    # every child belongs to exactly one segment, in order
    covered = [s.layers for s in segs]
    assert covered[0][0] == 0 and covered[-1][1] == 4
    for (a0, a1), (b0, b1) in zip(covered, covered[1:]):
        assert a1 == b0


def test_segments_tail_absorbs_padding():
    # raw 12 elements padded to 16: the padding rides the last segment, and
    # the segments tile [0, total) exactly like the buckets do
    buckets = comm_lib.make_buckets((6, 6), total=16, bucket_cap_mb=cap_mb(6))
    segs = comm_lib.make_segments((6, 6), buckets, 16)
    assert segs[-1].flat[1] == 16
    assert segs[0].flat[0] == 0
    for a, b in zip(segs, segs[1:]):
        assert a.flat[1] == b.flat[0]
    assert sum(len(s.buckets) for s in segs) == len(buckets)


def test_segments_single_bucket_is_single_segment():
    buckets = ((0, 24),)
    segs = comm_lib.make_segments((6, 6, 6), buckets, 24)
    assert len(segs) == 1
    assert segs[0] == comm_lib.CommSegment((0, 3), (0, 24), ((0, 24),))


def test_segments_refuse_inconsistent_totals():
    with pytest.raises(ValueError, match="layer sizes"):
        comm_lib.make_segments((30,), ((0, 24),), 24)


# ------------------------------------------------------ bitwise parity -----


@pytest.mark.parametrize("hook", HOOKS)
def test_overlap_bitwise_parity_per_hook(cpu_devices, hook):
    m_on, l_on, s_on = run_steps(build(cpu_devices, True, hook=hook))
    m_off, l_off, s_off = run_steps(build(cpu_devices, False, hook=hook))
    assert m_on["enabled"] and m_on["segments"] > 1, m_on
    assert m_off == {"enabled": False, "segments": None, "reason": "disabled"}
    assert l_on == l_off  # bitwise loss trajectory
    assert_states_equal(s_on, s_off)


@pytest.mark.parametrize("hook", ["none", "bf16_ef"])
def test_overlap_bitwise_parity_under_grad_accumulation(cpu_devices, hook):
    m_on, l_on, s_on = run_steps(
        build(cpu_devices, True, hook=hook, grad_accumulation=2), accum=2
    )
    _, l_off, s_off = run_steps(
        build(cpu_devices, False, hook=hook, grad_accumulation=2), accum=2
    )
    assert m_on["enabled"], m_on
    assert l_on == l_off
    assert_states_equal(s_on, s_off)


def test_overlap_bitwise_parity_with_guard(cpu_devices):
    _, l_on, s_on = run_steps(build(cpu_devices, True, guard=True))
    _, l_off, s_off = run_steps(build(cpu_devices, False, guard=True))
    assert l_on == l_off
    assert_states_equal(s_on, s_off)
    from tpuddp.resilience import guard as guard_lib

    assert guard_lib.read_skip_counters(s_on) == (0, 0)


def test_auto_equals_explicit_true_when_eligible(cpu_devices):
    m_auto, l_auto, s_auto = run_steps(build(cpu_devices, "auto"))
    m_true, l_true, s_true = run_steps(build(cpu_devices, True))
    assert m_auto == m_true and m_auto["enabled"]
    assert l_auto == l_true
    assert_states_equal(s_auto, s_true)


# ------------------------------------------------- guard segment no-op -----


def test_guard_skip_is_noop_across_all_segment_residual_slices(cpu_devices):
    ddp = build(cpu_devices, True, hook="bf16_ef", guard=True)
    x, y, w = make_batch()
    state = ddp.init_state(KEY, x[:8])
    # warm up one clean step so the residual is nonzero in every segment
    state, _ = ddp.train_step(state, ddp.shard((x, y, w)))
    before = np.asarray(jax.device_get(state.comm_state))
    spec_total = ddp._comm.spec.total
    for seg in ddp._segments:
        lo, hi = seg.flat
        per = before.reshape(ddp.world_size, spec_total)[:, lo:hi]
        assert np.abs(per).sum() > 0, f"segment {seg} residual never armed"
    params_before = jax.device_get(state.params)
    xb = x.copy()
    xb[:] = np.nan  # poison EVERY segment's gradient
    state, _ = ddp.train_step(state, ddp.shard((xb, y, w)))
    after = np.asarray(jax.device_get(state.comm_state))
    # the skip must be a no-op over every segment's residual slice: a
    # half-updated residual would silently corrupt error feedback
    np.testing.assert_array_equal(after, before)
    for pa, pb in zip(
        jax.tree_util.tree_leaves(jax.device_get(state.params)),
        jax.tree_util.tree_leaves(params_before),
    ):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
    from tpuddp.resilience import guard as guard_lib

    assert guard_lib.read_skip_counters(state) == (1, 1)


# --------------------------------------------------- eligibility matrix ----


@pytest.mark.parametrize(
    "kw,reason_frag",
    [
        (dict(mode="auto"), "auto"),
        (dict(weight_update_sharding=True), "weight_update_sharding"),
        (dict(comm_topology="hierarchical", hook="bf16_ef"), "hierarchical"),
        (dict(remat=True), "remat"),
    ],
)
def test_auto_falls_back_with_reason(cpu_devices, kw, reason_frag):
    hook = kw.pop("hook", "none")
    ddp = build(cpu_devices, "auto", hook=hook, **kw)
    x, _, _ = make_batch()
    ddp.init_state(KEY, x[:8])
    meta = ddp.comm_overlap_meta
    assert meta["enabled"] is False
    assert meta["segments"] is None
    assert reason_frag in meta["reason"]


@pytest.mark.parametrize(
    "kw",
    [
        dict(mode="auto"),
        dict(weight_update_sharding=True),
        dict(comm_topology="hierarchical", hook="bf16_ef"),
        dict(remat=True),
    ],
)
def test_true_refuses_ineligible(cpu_devices, kw):
    hook = kw.pop("hook", "none")
    ddp = build(cpu_devices, True, hook=hook, **kw)
    x, _, _ = make_batch()
    with pytest.raises(ValueError, match="comm_overlap"):
        ddp.init_state(KEY, x[:8])


def test_auto_single_segment_falls_back(cpu_devices):
    # the 25 MB default cap puts the whole ToyMLP in one bucket -> one
    # segment -> auto quietly keeps the barrier builder (the default-config
    # guarantee: existing runs see a byte-identical step program)
    ddp = build(cpu_devices, "auto", cap=None and SPLIT_CAP or 25.0)
    x, _, _ = make_batch()
    ddp.init_state(KEY, x[:8])
    meta = ddp.comm_overlap_meta
    assert meta["enabled"] is False
    assert "single" in meta["reason"]


def test_true_allows_single_segment(cpu_devices):
    # explicit true with one segment is legal (a degenerate but honest K=1)
    ddp = build(cpu_devices, True, cap=25.0)
    _, losses, _ = run_steps(ddp, steps=2)
    assert ddp.comm_overlap_meta == {
        "enabled": True, "segments": 1, "reason": None,
    }
    assert all(np.isfinite(v) for v in losses)


def test_wus_fallback_parity(cpu_devices):
    # ISSUE's "incl. WUS" parity: auto on a WUS wrap falls back to the exact
    # barrier builder, so it is bitwise the comm_overlap=false run
    kw = dict(weight_update_sharding=True, hook="bf16_ef")
    _, l_auto, s_auto = run_steps(build(cpu_devices, "auto", **kw))
    _, l_off, s_off = run_steps(build(cpu_devices, False, **kw))
    assert l_auto == l_off
    assert_states_equal(s_auto, s_off)


def test_hierarchical_fallback_parity(cpu_devices):
    kw = dict(comm_topology="hierarchical", hook="int8_ef")
    _, l_auto, s_auto = run_steps(build(cpu_devices, "auto", **kw))
    _, l_off, s_off = run_steps(build(cpu_devices, False, **kw))
    assert l_auto == l_off
    assert_states_equal(s_auto, s_off)


def test_accelerator_refuses_true_and_records_reason(tmp_path):
    from tpuddp.accelerate import Accelerator

    with pytest.raises(ValueError, match="comm_overlap"):
        Accelerator(comm_overlap=True)
    acc = Accelerator(comm_overlap="auto")
    meta = acc.comm_overlap_meta
    assert meta["enabled"] is False and meta["reason"]
    acc2 = Accelerator(comm_overlap=False)
    assert acc2.comm_overlap_meta["reason"] == "disabled"


def test_bad_knob_value_refused(cpu_devices):
    with pytest.raises(ValueError, match="comm_overlap"):
        build(cpu_devices, "always")


# ------------------------------------------------- byte accounting pin -----


@pytest.mark.parametrize("hook", HOOKS)
def test_comm_bytes_identical_segmented_vs_barrier(cpu_devices, hook):
    """Satellite pin: segmentation reorders WHEN buckets go on the wire, not
    what they carry — per-step bytes, the f32 baseline, the hop breakdown,
    and the cumulative counter must be equal in both modes, and the
    segmented total must equal the sum of the per-segment bucket payloads
    (scales + indices included), so a per-segment re-derivation can never
    double-count the side-channel bytes."""
    ddp_on = build(cpu_devices, True, hook=hook)
    ddp_off = build(cpu_devices, False, hook=hook)
    x, _, _ = make_batch()
    ddp_on.init_state(KEY, x[:8])
    ddp_off.init_state(KEY, x[:8])
    assert ddp_on.grad_comm_bytes_per_step == ddp_off.grad_comm_bytes_per_step
    assert (
        ddp_on.grad_comm_bytes_per_step_f32
        == ddp_off.grad_comm_bytes_per_step_f32
    )
    assert ddp_on._grad_comm_breakdown == ddp_off._grad_comm_breakdown
    if hook != "none":
        # formula: the barrier counter is a sum over buckets; the segments
        # partition the buckets, so the double sum is the same number
        per_segment = sum(
            comm_lib._bucket_payload_bytes(hook, e - s, ddp_on._comm.density)
            for seg in ddp_on._segments
            for s, e in seg.buckets
        )
        assert per_segment == ddp_on.grad_comm_bytes_per_step
    # the running counter sees identical per-update payloads -> identical
    # totals after any number of updates
    c_on = CommBytesCounter(ddp_on.grad_comm_bytes_per_step)
    c_off = CommBytesCounter(ddp_off.grad_comm_bytes_per_step)
    c_on.add_updates(17)
    c_off.add_updates(17)
    assert c_on.snapshot(5) == c_off.snapshot(5)


# --------------------------------------------------------- checkpoints -----


def test_segmented_checkpoint_resumes_into_barrier_and_back(
    cpu_devices, tmp_path
):
    """comm_state is mode-agnostic state: 3 segmented steps + save + restore
    into a barrier wrap + 3 barrier steps == 6 barrier steps, bitwise (and
    the mirror-image order too)."""
    batches = [make_batch(seed=100 + i) for i in range(6)]
    _, _, ref = run_steps(
        build(cpu_devices, False), steps=6, batches=batches
    )

    def cross(first_overlap, second_overlap):
        ddp_a = build(cpu_devices, first_overlap)
        _, _, s3 = run_steps(ddp_a, steps=3, batches=batches)
        ckpt.save_on_main(str(tmp_path), 1, s3, world_size=8)
        ddp_b = build(cpu_devices, second_overlap)
        x, _, _ = make_batch()
        fresh = ddp_b.init_state(KEY, x[:8])
        restored, _ = ckpt.restore_latest(str(tmp_path), fresh, world_size=8)
        state = dataclasses.replace(restored, rng=s3.rng)
        for i in range(3, 6):
            xb, yb, wb = batches[i]
            state, _ = ddp_b.train_step(state, ddp_b.shard((xb, yb, wb)))
        return state

    assert_states_equal(cross(True, False), ref)
    assert_states_equal(cross(False, True), ref)


def test_segmented_elastic_shrink_4_to_2(cpu_devices, tmp_path):
    """A segmented run's residual rides the elastic 4 -> 2 redistribution
    exactly as a barrier run's (per-replica rows summed in groups), and the
    halved world trains on segmented."""
    ddp4 = build(cpu_devices, True, world=4)
    _, _, s4 = run_steps(ddp4, steps=2)
    assert ddp4.comm_overlap_meta["enabled"]
    mat4 = np.asarray(jax.device_get(s4.comm_state)).reshape(
        4, ddp4._comm.spec.total
    )
    assert np.abs(mat4).sum() > 0
    ckpt.save_on_main(str(tmp_path), 1, s4, world_size=4)

    ddp2 = build(cpu_devices, True, world=2)
    x, _, _ = make_batch()
    fresh = ddp2.init_state(jax.random.key(7), x[:8])
    log = []
    restored, _ = ckpt.restore_latest(
        str(tmp_path), fresh, world_size=2, reshard_log=log
    )
    per2 = ddp2._comm.spec.total
    got = np.asarray(jax.device_get(restored.comm_state)).reshape(2, per2)
    np.testing.assert_array_equal(
        got, mat4[:, :per2].reshape(2, 2, per2).sum(axis=1)
    )
    ev = [e for e in log if e["event"] == "topology_change"]
    assert ev and ev[0]["from_world"] == 4 and ev[0]["to_world"] == 2
    xb, yb, wb = make_batch(seed=9)
    st, m = ddp2.train_step(restored, ddp2.shard((xb, yb, wb)))
    assert np.isfinite(float(np.sum(np.asarray(m["loss_sum"]))))


# ----------------------------------------------------- HLO interleaving ----


def lowered_text(ddp):
    x, y, w = make_batch()
    state = ddp.init_state(KEY, x[:8])
    batch = ddp.shard((x, y, w))
    ddp.train_step(state, batch)  # builds + caches the step
    xs, ys, ws = batch
    return ddp._train_step.jitted.lower(state, xs, ys, ws).as_text()


@pytest.mark.parametrize("hook", ["none", "bf16_ef"])
def test_hlo_shows_interleaved_collectives(cpu_devices, hook):
    ev_on = comm_lib.hlo_overlap_evidence(
        lowered_text(build(cpu_devices, True, hook=hook))
    )
    ev_off = comm_lib.hlo_overlap_evidence(
        lowered_text(build(cpu_devices, False, hook=hook))
    )
    # overlap-on: K >= 2 collectives with backward compute strictly between
    # the first and last issue — the program XLA gets genuinely allows the
    # reductions to run while later (earlier-layer) backward compute proceeds
    assert len(ev_on["collective_lines"]) >= 2, ev_on
    assert ev_on["interleaved"], ev_on
    assert len(ev_on["interleaved_compute"]) > 0
    # barrier mode: whatever collectives exist form one trailing block
    assert not ev_off["interleaved"], ev_off


def test_hlo_overlap_evidence_is_pure_text():
    txt = "\n".join([
        "%dot_general.1 = f32[4,4] dot_general(...)",
        '%all-reduce.1 = f32[8] all-reduce(...)',
        "%dot_general.2 = f32[4,4] dot_general(...)",
        '%all-reduce.2 = f32[8] all-reduce(...)',
    ])
    ev = comm_lib.hlo_overlap_evidence(txt)
    assert ev == {
        "collective_lines": [1, 3], "compute_lines": [0, 2],
        "interleaved_compute": [2], "interleaved": True,
    }
    ev2 = comm_lib.hlo_overlap_evidence("%dot_general.1 ...\n%all-reduce.1 ...")
    assert not ev2["interleaved"]


# ------------------------------------------------------ run provenance -----


def test_run_meta_carries_overlap_provenance():
    from tpuddp.observability import schema

    rec = schema.make_run_meta(
        world_size=8,
        comm={"overlap": {"enabled": True, "segments": 3, "reason": None}},
    )
    assert rec["comm"]["overlap"]["segments"] == 3
    assert schema.validate_record(rec) == []
    # drift rejection: a v10 header whose comm block lacks the overlap
    # member is invalid (and a non-dict comm likewise)
    bad = dict(rec, comm={"something": 1})
    assert schema.validate_record(bad)
    worse = dict(rec, comm=7)
    assert schema.validate_record(worse)
    # meshless/serving headers carry null comm — legal
    rec_null = schema.make_run_meta(world_size=1, comm=None)
    assert schema.validate_record(rec_null) == []
