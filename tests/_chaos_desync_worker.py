"""Desync worker for the chaos suite (launched by test_chaos.py).

Simulates the guard's target failure — ONE device's copy of a replicated
parameter silently diverging (bad host, bit flip, desynced update) — by
rebuilding a leaf with ``make_array_from_single_device_arrays`` so device 3's
buffer differs, then runs the epoch driver with the desync auditor armed
(``guard.audit_every_n_epochs=1``) through the full spawn path so the
exit-code contract is live:

- mode ``exit``:     the audit at the next epoch boundary must name the leaf
                     and exit ``EXIT_DESYNC`` (77).
- mode ``rollback``: epoch 0 first trains clean and checkpoints; the audit
                     then throws the perturbed state away, restores the
                     checkpoint, and the run finishes 0 with a rollback
                     event in history.jsonl.

Usage: python _chaos_desync_worker.py <out_dir> <exit|rollback>
"""

import sys
from functools import partial

out_dir, mode = sys.argv[1], sys.argv[2]
assert mode in ("exit", "rollback"), mode

import dataclasses  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from tpuddp import nn, optim  # noqa: E402
from tpuddp.data import ShardedDataLoader, SyntheticClassification  # noqa: E402
from tpuddp.models import ToyMLP  # noqa: E402
from tpuddp.parallel.ddp import DistributedDataParallel  # noqa: E402
from tpuddp.parallel.mesh import data_mesh  # noqa: E402
from tpuddp.parallel.spawn import run_ddp_training  # noqa: E402
from tpuddp.training.loop import run_training_loop  # noqa: E402


def perturb_one_device(mesh, params):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    host = np.asarray(leaves[0])
    shards = []
    for i, d in enumerate(mesh.devices.flat):
        h = host.copy()
        if i == 3 % mesh.devices.size:
            h.flat[0] += 0.25
        shards.append(jax.device_put(h, d))
    bad = jax.make_array_from_single_device_arrays(
        host.shape, NamedSharding(mesh, P()), shards
    )
    return jax.tree_util.tree_unflatten(treedef, [bad] + leaves[1:])


def demo(rank, world_size, save_dir, optional_args, mode=None):
    mesh = data_mesh(world_size)
    train = ShardedDataLoader(
        SyntheticClassification(n=64, shape=(8, 8, 3), seed=0),
        batch_size=2, mesh=mesh, shuffle=True,
    )
    test = ShardedDataLoader(
        SyntheticClassification(n=16, shape=(8, 8, 3), seed=1),
        batch_size=2, mesh=mesh,
    )
    guard = {
        "audit_every_n_epochs": 1,
        "on_desync": "rollback" if mode == "rollback" else "exit",
    }
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), nn.CrossEntropyLoss(),
        mesh=mesh, guard=guard,
    )
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    start_epoch = 0
    if mode == "rollback":
        # epoch 0 trains clean and publishes ckpt_0 — the last-good state the
        # rollback must land on
        state, _ = run_training_loop(
            ddp, state, train, test, save_dir, num_epochs=1, checkpoint_epoch=1,
            scan_steps=2, per_replica_log=False,
        )
        start_epoch = 1
    state = dataclasses.replace(state, params=perturb_one_device(mesh, state.params))
    run_training_loop(
        ddp, state, train, test, save_dir, num_epochs=3, checkpoint_epoch=1,
        scan_steps=2, per_replica_log=False, start_epoch=start_epoch,
    )


run_ddp_training(
    partial(demo, mode=mode),
    world_size=4,
    save_dir=out_dir,
    optional_args={},
    backend="cpu",
)
