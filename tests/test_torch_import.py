"""Torch AlexNet weight import — the imported tpuddp model must produce the
SAME logits as the torch model (proves end-to-end architecture identity with
the reference's load_model(), data_and_toy_model.py:41-45)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn as tnn

from tpuddp.models import AlexNet
from tpuddp.models.torch_import import convert_alexnet_state_dict, load_torch_alexnet
from tpuddp.nn.core import Context


def torch_alexnet(num_classes=10):
    """torchvision AlexNet topology rebuilt in plain torch (torchvision isn't
    in this image), with torchvision's exact state_dict key layout."""
    features = tnn.Sequential(
        tnn.Conv2d(3, 64, 11, stride=4, padding=2), tnn.ReLU(inplace=True),
        tnn.MaxPool2d(3, 2),
        tnn.Conv2d(64, 192, 5, padding=2), tnn.ReLU(inplace=True),
        tnn.MaxPool2d(3, 2),
        tnn.Conv2d(192, 384, 3, padding=1), tnn.ReLU(inplace=True),
        tnn.Conv2d(384, 256, 3, padding=1), tnn.ReLU(inplace=True),
        tnn.Conv2d(256, 256, 3, padding=1), tnn.ReLU(inplace=True),
        tnn.MaxPool2d(3, 2),
    )
    classifier = tnn.Sequential(
        tnn.Dropout(), tnn.Linear(256 * 6 * 6, 4096), tnn.ReLU(inplace=True),
        tnn.Dropout(), tnn.Linear(4096, 4096), tnn.ReLU(inplace=True),
        tnn.Linear(4096, num_classes),
    )

    class TorchAlexNet(tnn.Module):
        def __init__(self):
            super().__init__()
            self.features = features
            self.avgpool = tnn.AdaptiveAvgPool2d((6, 6))
            self.classifier = classifier

        def forward(self, x):
            x = self.features(x)
            x = self.avgpool(x)
            x = torch.flatten(x, 1)
            return self.classifier(x)

    return TorchAlexNet()


@pytest.fixture(scope="module")
def models():
    torch.manual_seed(0)
    ref = torch_alexnet().eval()
    model = AlexNet(num_classes=10)
    params, state = model.init(jax.random.key(0), jnp.zeros((1, 224, 224, 3)))
    params = convert_alexnet_state_dict(ref.state_dict(), params)
    return ref, model, params, state


@pytest.mark.slow
def test_imported_weights_reproduce_torch_logits(models):
    ref, model, params, state = models
    x = np.random.RandomState(0).randn(2, 224, 224, 3).astype(np.float32)
    ours = model.apply(params, state, jnp.asarray(x), Context(train=False))[0]
    with torch.no_grad():
        theirs = ref(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-3, atol=1e-3)


@pytest.mark.slow
def test_load_from_pt_file(models, tmp_path):
    ref, model, _, state = models
    path = tmp_path / "alexnet.pt"
    torch.save(ref.state_dict(), str(path))
    fresh_params, _ = model.init(jax.random.key(1), jnp.zeros((1, 224, 224, 3)))
    params = load_torch_alexnet(fresh_params, str(path))
    x = np.random.RandomState(1).randn(1, 224, 224, 3).astype(np.float32)
    ours = model.apply(params, state, jnp.asarray(x), Context(train=False))[0]
    with torch.no_grad():
        theirs = ref(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-3, atol=1e-3)


def test_shape_mismatch_raises(models):
    ref, model, params, _ = models
    bad = dict(ref.state_dict())
    bad["features.0.weight"] = torch.zeros(64, 3, 5, 5)
    with pytest.raises(ValueError, match="features.0"):
        convert_alexnet_state_dict(bad, params)


class _TorchBasicBlock(tnn.Module):
    def __init__(self, in_ch, out_ch, stride=1):
        super().__init__()
        self.conv1 = tnn.Conv2d(in_ch, out_ch, 3, stride, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(out_ch)
        self.conv2 = tnn.Conv2d(out_ch, out_ch, 3, 1, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(out_ch)
        self.downsample = None
        if stride != 1 or in_ch != out_ch:
            self.downsample = tnn.Sequential(
                tnn.Conv2d(in_ch, out_ch, 1, stride, bias=False),
                tnn.BatchNorm2d(out_ch),
            )

    def forward(self, x):
        idn = x if self.downsample is None else self.downsample(x)
        h = torch.relu(self.bn1(self.conv1(x)))
        h = self.bn2(self.conv2(h))
        return torch.relu(h + idn)


class _TorchResNet(tnn.Module):
    """Hand-built torchvision-layout BasicBlock ResNet (torchvision is not
    installed; the state_dict keys match torchvision's exactly by attribute
    naming). depths=(2,2,2,2) is ResNet-18, (3,4,6,3) is ResNet-34."""

    def __init__(self, num_classes=1000, depths=(2, 2, 2, 2)):
        super().__init__()
        self.conv1 = tnn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = tnn.BatchNorm2d(64)
        self.maxpool = tnn.MaxPool2d(3, 2, 1)
        widths = [64, 128, 256, 512]
        in_ch = 64
        for i, (w, n) in enumerate(zip(widths, depths), start=1):
            stride = 1 if i == 1 else 2
            blocks = [_TorchBasicBlock(in_ch, w, stride)]
            blocks.extend(_TorchBasicBlock(w, w) for _ in range(n - 1))
            setattr(self, f"layer{i}", tnn.Sequential(*blocks))
            in_ch = w
        self.avgpool = tnn.AdaptiveAvgPool2d(1)
        self.fc = tnn.Linear(512, num_classes)

    def forward(self, x):
        h = self.maxpool(torch.relu(self.bn1(self.conv1(x))))
        for i in (1, 2, 3, 4):
            h = getattr(self, f"layer{i}")(h)
        return self.fc(torch.flatten(self.avgpool(h), 1))


def _TorchResNet18(num_classes=1000):
    return _TorchResNet(num_classes, depths=(2, 2, 2, 2))


def test_imported_resnet18_reproduces_torch_logits():
    """Converted torchvision-layout ResNet-18 weights + BN running stats must
    reproduce the torch model's eval-mode logits."""
    from tpuddp.models import ResNet18
    from tpuddp.models.torch_import import convert_resnet18_state_dict
    from tpuddp.nn.core import Context

    torch.manual_seed(3)
    donor = _TorchResNet18(num_classes=1000)
    # non-trivial running stats: a few train-mode forwards
    donor.train()
    with torch.no_grad():
        for _ in range(2):
            donor(torch.randn(4, 3, 64, 64))
    donor.eval()

    model = ResNet18(num_classes=1000)
    params, mstate = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    params, mstate = convert_resnet18_state_dict(donor.state_dict(), params, mstate)

    x = np.random.RandomState(0).randn(2, 64, 64, 3).astype(np.float32)
    ours, _ = model.apply(params, mstate, jnp.asarray(x), Context(train=False))
    with torch.no_grad():
        ref = donor(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-3, atol=1e-4)


def test_pretrained_resnet18_head_swap(tmp_path):
    from tpuddp.models.torch_import import load_pretrained_resnet18

    torch.manual_seed(4)
    donor = _TorchResNet18(num_classes=1000)
    path = tmp_path / "resnet_donor.pt"
    torch.save(donor.state_dict(), str(path))
    model, params, mstate = load_pretrained_resnet18(
        str(path), jax.random.key(0), num_classes=10, image_size=64
    )
    assert params[-1]["weight"].shape == (512, 10)
    conv1 = donor.state_dict()["conv1.weight"].numpy().transpose(2, 3, 1, 0)
    np.testing.assert_allclose(np.asarray(params[0]["weight"]), conv1, rtol=1e-6)


def test_resnet_import_rejects_missing_downsample(tmp_path):
    """A checkpoint lacking a stride-2 block's downsample tensors must fail
    with a named-tensor error, not a raw shape mismatch deep inside JAX."""
    from tpuddp.models import ResNet18
    from tpuddp.models.torch_import import convert_resnet18_state_dict

    torch.manual_seed(5)
    donor = _TorchResNet18(num_classes=10)
    sd = dict(donor.state_dict())
    del sd["layer2.0.downsample.0.weight"]
    del sd["layer2.0.downsample.1.weight"]
    del sd["layer2.0.downsample.1.bias"]
    del sd["layer2.0.downsample.1.running_mean"]
    del sd["layer2.0.downsample.1.running_var"]

    model = ResNet18(num_classes=10)
    params, mstate = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    with pytest.raises(ValueError, match="layer2.0.*down"):
        convert_resnet18_state_dict(sd, params, mstate)


def test_resnet_import_rejects_deeper_variant():
    """A ResNet-34 checkpoint (shape-compatible early blocks) must not import
    silently into ResNet-18 with half its blocks dropped."""
    from tpuddp.models import ResNet18
    from tpuddp.models.torch_import import convert_resnet18_state_dict

    torch.manual_seed(6)
    donor = _TorchResNet18(num_classes=10)
    sd = dict(donor.state_dict())
    # fabricate an extra layer1.2 block (what a ResNet-34 checkpoint carries)
    for k in list(sd):
        if k.startswith("layer1.1."):
            sd[k.replace("layer1.1.", "layer1.2.")] = sd[k].clone()

    model = ResNet18(num_classes=10)
    params, mstate = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    with pytest.raises(ValueError, match="does not consume"):
        convert_resnet18_state_dict(sd, params, mstate)


@pytest.mark.slow
def test_imported_resnet34_reproduces_torch_logits():
    """Converted torchvision-layout ResNet-34 ([3,4,6,3]) weights + BN running
    stats must reproduce the torch model's eval-mode logits
    (data_and_toy_model.py:41-45's pretrained workflow at the deeper depth)."""
    from tpuddp.models import ResNet34
    from tpuddp.models.torch_import import convert_resnet34_state_dict

    torch.manual_seed(7)
    donor = _TorchResNet(num_classes=1000, depths=(3, 4, 6, 3))
    donor.train()
    with torch.no_grad():
        for _ in range(2):
            donor(torch.randn(4, 3, 64, 64))
    donor.eval()

    model = ResNet34(num_classes=1000)
    params, mstate = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    params, mstate = convert_resnet34_state_dict(donor.state_dict(), params, mstate)

    x = np.random.RandomState(2).randn(2, 64, 64, 3).astype(np.float32)
    ours, _ = model.apply(params, mstate, jnp.asarray(x), Context(train=False))
    with torch.no_grad():
        ref = donor(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-3, atol=1e-4)


def test_pretrained_resnet34_from_config(tmp_path):
    """training.pretrained_path + model: resnet34 resolves through
    pretrained_from_config (the round-3 verdict's failing case)."""
    from tpuddp.models.torch_import import pretrained_from_config

    torch.manual_seed(8)
    donor = _TorchResNet(num_classes=1000, depths=(3, 4, 6, 3))
    path = tmp_path / "resnet34_donor.pt"
    torch.save(donor.state_dict(), str(path))
    model, params, mstate = pretrained_from_config(
        {
            "model": "resnet34",
            "pretrained_path": str(path),
            "seed": 0,
            "num_classes": 10,
            "image_size": 64,
        }
    )
    assert params[-1]["weight"].shape == (512, 10)
    conv1 = donor.state_dict()["conv1.weight"].numpy().transpose(2, 3, 1, 0)
    np.testing.assert_allclose(np.asarray(params[0]["weight"]), conv1, rtol=1e-6)


def test_resnet34_import_rejects_resnet18_checkpoint(tmp_path):
    """An 18-depth checkpoint loaded as ResNet-34 must fail on the missing
    deeper blocks, not silently leave them at init."""
    from tpuddp.models import ResNet34
    from tpuddp.models.torch_import import convert_resnet34_state_dict

    torch.manual_seed(9)
    donor = _TorchResNet18(num_classes=10)
    model = ResNet34(num_classes=10)
    params, mstate = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    with pytest.raises((ValueError, KeyError)):
        convert_resnet34_state_dict(donor.state_dict(), params, mstate)


def test_pretrained_s2d_variants_load_same_checkpoint(tmp_path):
    """The _s2d model names accept the same torch checkpoints (identical
    parameter layout) and produce the same logits as the plain import."""
    from tpuddp.models.torch_import import pretrained_from_config
    from tpuddp.nn.core import Context

    torch.manual_seed(10)
    donor = _TorchResNet18(num_classes=1000)
    path = tmp_path / "donor18.pt"
    torch.save(donor.state_dict(), str(path))
    cfgs = [
        dict(model=m, pretrained_path=str(path), seed=0, num_classes=10, image_size=64)
        for m in ("resnet18", "resnet18_s2d")
    ]
    out = []
    for c in cfgs:
        model, params, mstate = pretrained_from_config(c)
        x = np.random.RandomState(3).randn(2, 64, 64, 3).astype(np.float32)
        y, _ = model.apply(params, mstate, jnp.asarray(x), Context(train=False))
        out.append(np.asarray(y))
    np.testing.assert_allclose(out[0], out[1], rtol=1e-4, atol=1e-4)


def _torch_vgg(name, num_classes=1000):
    """torchvision VGG topology in plain torch with the exact state_dict key
    layout (torchvision is not installed), built from the SAME plan as the
    tpuddp model (tpuddp/models/vgg.py VGG_PLANS)."""
    from tpuddp.models.vgg import VGG_PLANS

    layers, in_ch = [], 3
    for item in VGG_PLANS[name]:
        if item == "M":
            layers.append(tnn.MaxPool2d(2, 2))
        else:
            layers.append(tnn.Conv2d(in_ch, item, 3, padding=1))
            layers.append(tnn.ReLU(inplace=True))
            in_ch = item
    features = tnn.Sequential(*layers)
    classifier = tnn.Sequential(
        tnn.Linear(512 * 7 * 7, 4096), tnn.ReLU(inplace=True), tnn.Dropout(),
        tnn.Linear(4096, 4096), tnn.ReLU(inplace=True), tnn.Dropout(),
        tnn.Linear(4096, num_classes),
    )

    class TorchVGG(tnn.Module):
        def __init__(self):
            super().__init__()
            self.features = features
            self.avgpool = tnn.AdaptiveAvgPool2d((7, 7))
            self.classifier = classifier

        def forward(self, x):
            x = self.features(x)
            x = self.avgpool(x)
            x = torch.flatten(x, 1)
            return self.classifier(x)

    return TorchVGG()


def _torch_vgg11(num_classes=1000):
    return _torch_vgg("vgg11", num_classes)


@pytest.mark.slow
@pytest.mark.parametrize("name", ["vgg11", "vgg13", "vgg16", "vgg19"])
def test_imported_vgg_reproduces_torch_logits(name):
    from tpuddp.models import load_model
    from tpuddp.models.torch_import import convert_vgg_state_dict

    torch.manual_seed(11)
    donor = _torch_vgg(name, num_classes=1000).eval()
    model = load_model(name, 1000)
    params, state = model.init(jax.random.key(0), jnp.zeros((1, 224, 224, 3)))
    params = convert_vgg_state_dict(name, donor.state_dict(), params)
    x = np.random.RandomState(4).randn(2, 224, 224, 3).astype(np.float32)
    ours = model.apply(params, state, jnp.asarray(x), Context(train=False))[0]
    with torch.no_grad():
        theirs = donor(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-3, atol=1e-3)


@pytest.mark.slow
def test_pretrained_vgg11_head_swap_from_config(tmp_path):
    from tpuddp.models.torch_import import pretrained_from_config

    torch.manual_seed(12)
    donor = _torch_vgg11(num_classes=1000)
    path = tmp_path / "vgg_donor.pt"
    torch.save(donor.state_dict(), str(path))
    model, params, mstate = pretrained_from_config(
        {
            "model": "vgg11",
            "pretrained_path": str(path),
            "seed": 0,
            "num_classes": 10,
            "image_size": 64,
        }
    )
    assert params[-1]["weight"].shape == (4096, 10)
    conv0 = donor.state_dict()["features.0.weight"].numpy().transpose(2, 3, 1, 0)
    np.testing.assert_allclose(np.asarray(params[0]["weight"]), conv0, rtol=1e-6)


class _TorchBottleneck(tnn.Module):
    def __init__(self, in_ch, width, stride=1):
        super().__init__()
        out_ch = width * 4
        self.conv1 = tnn.Conv2d(in_ch, width, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(width)
        self.conv2 = tnn.Conv2d(width, width, 3, stride, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(width)
        self.conv3 = tnn.Conv2d(width, out_ch, 1, bias=False)
        self.bn3 = tnn.BatchNorm2d(out_ch)
        self.downsample = None
        if stride != 1 or in_ch != out_ch:
            self.downsample = tnn.Sequential(
                tnn.Conv2d(in_ch, out_ch, 1, stride, bias=False),
                tnn.BatchNorm2d(out_ch),
            )

    def forward(self, x):
        idn = x if self.downsample is None else self.downsample(x)
        h = torch.relu(self.bn1(self.conv1(x)))
        h = torch.relu(self.bn2(self.conv2(h)))
        h = self.bn3(self.conv3(h))
        return torch.relu(h + idn)


class _TorchResNet50(tnn.Module):
    """Hand-built torchvision-layout Bottleneck ResNet-50 (v1.5 stride
    placement: the 3x3 conv strides); state_dict keys match torchvision's
    by attribute naming."""

    def __init__(self, num_classes=1000, depths=(3, 4, 6, 3)):
        super().__init__()
        self.conv1 = tnn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = tnn.BatchNorm2d(64)
        self.maxpool = tnn.MaxPool2d(3, 2, 1)
        widths = [64, 128, 256, 512]
        in_ch = 64
        for i, (w, n) in enumerate(zip(widths, depths), start=1):
            stride = 1 if i == 1 else 2
            blocks = [_TorchBottleneck(in_ch, w, stride)]
            blocks.extend(_TorchBottleneck(w * 4, w) for _ in range(n - 1))
            setattr(self, f"layer{i}", tnn.Sequential(*blocks))
            in_ch = w * 4
        self.avgpool = tnn.AdaptiveAvgPool2d(1)
        self.fc = tnn.Linear(2048, num_classes)

    def forward(self, x):
        h = self.maxpool(torch.relu(self.bn1(self.conv1(x))))
        for i in (1, 2, 3, 4):
            h = getattr(self, f"layer{i}")(h)
        return self.fc(torch.flatten(self.avgpool(h), 1))


@pytest.mark.slow
def test_imported_resnet50_reproduces_torch_logits():
    """Converted torchvision-layout ResNet-50 (Bottleneck) weights + BN
    running stats must reproduce the torch model's eval-mode logits."""
    from tpuddp.models import ResNet50
    from tpuddp.models.torch_import import convert_resnet_bottleneck_state_dict
    from tpuddp.nn.core import Context

    torch.manual_seed(11)
    donor = _TorchResNet50(num_classes=1000)
    donor.train()
    with torch.no_grad():
        for _ in range(2):
            donor(torch.randn(2, 3, 64, 64))
    donor.eval()

    model = ResNet50(num_classes=1000)
    params, mstate = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    params, mstate = convert_resnet_bottleneck_state_dict(
        donor.state_dict(), params, mstate
    )

    x = np.random.RandomState(1).randn(2, 64, 64, 3).astype(np.float32)
    ours, _ = model.apply(params, mstate, jnp.asarray(x), Context(train=False))
    with torch.no_grad():
        ref = donor(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-3, atol=2e-4)


@pytest.mark.slow
def test_pretrained_resnet50_head_swap_and_s2d(tmp_path):
    """load_pretrained_resnet50: 1000-class donor checkpoint -> 10-class
    head-swapped model; the s2d variant loads the SAME checkpoint and
    produces the same logits (exact stem reparameterization)."""
    from tpuddp.models.torch_import import load_pretrained_resnet50
    from tpuddp.nn.core import Context

    torch.manual_seed(12)
    donor = _TorchResNet50(num_classes=1000)
    path = tmp_path / "rn50.pt"
    torch.save(donor.state_dict(), path)

    key = jax.random.key(5)
    model, params, mstate = load_pretrained_resnet50(str(path), key, num_classes=10)
    x = np.random.RandomState(2).randn(2, 64, 64, 3).astype(np.float32)
    logits, _ = model.apply(params, mstate, jnp.asarray(x), Context(train=False))
    assert logits.shape == (2, 10)

    s2d_model, s2d_params, s2d_state = load_pretrained_resnet50(
        str(path), key, num_classes=10, space_to_depth=True
    )
    s2d_logits, _ = s2d_model.apply(
        s2d_params, s2d_state, jnp.asarray(x), Context(train=False)
    )
    np.testing.assert_allclose(
        np.asarray(s2d_logits), np.asarray(logits), rtol=1e-4, atol=1e-4
    )


def test_resnet50_import_rejects_resnet18_checkpoint(tmp_path):
    """A BasicBlock checkpoint fed to the Bottleneck converter must be
    refused loudly (missing conv3/bn3 tensors), not silently mis-mapped."""
    from tpuddp.models import ResNet50
    from tpuddp.models.torch_import import convert_resnet_bottleneck_state_dict

    torch.manual_seed(13)
    donor18 = _TorchResNet18(num_classes=10)
    model = ResNet50(num_classes=10)
    params, mstate = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    with pytest.raises((ValueError, KeyError)):
        convert_resnet_bottleneck_state_dict(donor18.state_dict(), params, mstate)


@pytest.mark.slow
def test_imported_resnet101_reproduces_torch_logits():
    """ResNet-101 ([3,4,23,3] Bottleneck) through the same converter."""
    from tpuddp.models import ResNet101
    from tpuddp.models.torch_import import convert_resnet_bottleneck_state_dict
    from tpuddp.nn.core import Context

    torch.manual_seed(21)
    donor = _TorchResNet50(num_classes=100, depths=(3, 4, 23, 3))
    donor.train()
    with torch.no_grad():
        donor(torch.randn(2, 3, 64, 64))
    donor.eval()

    model = ResNet101(num_classes=100)
    params, mstate = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    params, mstate = convert_resnet_bottleneck_state_dict(
        donor.state_dict(), params, mstate, depths=(3, 4, 23, 3)
    )
    x = np.random.RandomState(4).randn(2, 64, 64, 3).astype(np.float32)
    ours, _ = model.apply(params, mstate, jnp.asarray(x), Context(train=False))
    with torch.no_grad():
        ref = donor(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-3, atol=5e-4)
