"""Torch AlexNet weight import — the imported tpuddp model must produce the
SAME logits as the torch model (proves end-to-end architecture identity with
the reference's load_model(), data_and_toy_model.py:41-45)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn as tnn

from tpuddp.models import AlexNet
from tpuddp.models.torch_import import convert_alexnet_state_dict, load_torch_alexnet
from tpuddp.nn.core import Context


def torch_alexnet(num_classes=10):
    """torchvision AlexNet topology rebuilt in plain torch (torchvision isn't
    in this image), with torchvision's exact state_dict key layout."""
    features = tnn.Sequential(
        tnn.Conv2d(3, 64, 11, stride=4, padding=2), tnn.ReLU(inplace=True),
        tnn.MaxPool2d(3, 2),
        tnn.Conv2d(64, 192, 5, padding=2), tnn.ReLU(inplace=True),
        tnn.MaxPool2d(3, 2),
        tnn.Conv2d(192, 384, 3, padding=1), tnn.ReLU(inplace=True),
        tnn.Conv2d(384, 256, 3, padding=1), tnn.ReLU(inplace=True),
        tnn.Conv2d(256, 256, 3, padding=1), tnn.ReLU(inplace=True),
        tnn.MaxPool2d(3, 2),
    )
    classifier = tnn.Sequential(
        tnn.Dropout(), tnn.Linear(256 * 6 * 6, 4096), tnn.ReLU(inplace=True),
        tnn.Dropout(), tnn.Linear(4096, 4096), tnn.ReLU(inplace=True),
        tnn.Linear(4096, num_classes),
    )

    class TorchAlexNet(tnn.Module):
        def __init__(self):
            super().__init__()
            self.features = features
            self.avgpool = tnn.AdaptiveAvgPool2d((6, 6))
            self.classifier = classifier

        def forward(self, x):
            x = self.features(x)
            x = self.avgpool(x)
            x = torch.flatten(x, 1)
            return self.classifier(x)

    return TorchAlexNet()


@pytest.fixture(scope="module")
def models():
    torch.manual_seed(0)
    ref = torch_alexnet().eval()
    model = AlexNet(num_classes=10)
    params, state = model.init(jax.random.key(0), jnp.zeros((1, 224, 224, 3)))
    params = convert_alexnet_state_dict(ref.state_dict(), params)
    return ref, model, params, state


@pytest.mark.slow
def test_imported_weights_reproduce_torch_logits(models):
    ref, model, params, state = models
    x = np.random.RandomState(0).randn(2, 224, 224, 3).astype(np.float32)
    ours = model.apply(params, state, jnp.asarray(x), Context(train=False))[0]
    with torch.no_grad():
        theirs = ref(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-3, atol=1e-3)


@pytest.mark.slow
def test_load_from_pt_file(models, tmp_path):
    ref, model, _, state = models
    path = tmp_path / "alexnet.pt"
    torch.save(ref.state_dict(), str(path))
    fresh_params, _ = model.init(jax.random.key(1), jnp.zeros((1, 224, 224, 3)))
    params = load_torch_alexnet(fresh_params, str(path))
    x = np.random.RandomState(1).randn(1, 224, 224, 3).astype(np.float32)
    ours = model.apply(params, state, jnp.asarray(x), Context(train=False))[0]
    with torch.no_grad():
        theirs = ref(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-3, atol=1e-3)


def test_shape_mismatch_raises(models):
    ref, model, params, _ = models
    bad = dict(ref.state_dict())
    bad["features.0.weight"] = torch.zeros(64, 3, 5, 5)
    with pytest.raises(ValueError, match="features.0"):
        convert_alexnet_state_dict(bad, params)
