"""Managed-API training worker for the chaos suite (launched by
test_chaos.py) — the Accelerator-entrypoint sibling of _chaos_train_worker.

Runs a small managed training job (toy MLP, synthetic-fallback data, virtual
CPU devices) through ``basic_accelerate_training`` with the resilience wiring
live: SIGTERM drain at loop boundaries -> lossless state_{epoch}.npz + exit
75, ``$TPUDDP_FAULT`` epoch-site injection, ``$TPUDDP_AUTO_RESUME`` resume
through ``load_state`` — which reshards elastically when
``$TPUDDP_WORLD_SIZE`` differs from the world that wrote the state.

Usage: python _chaos_accel_worker.py <out_dir> <num_epochs>

``$TPUDDP_CHAOS_TRAINING``: JSON training-config overrides (same contract as
the native worker). ``$TPUDDP_WORLD_SIZE``: world size (default 4).
"""

import json
import os
import sys

out_dir, num_epochs = sys.argv[1], int(sys.argv[2])
world_size = int(os.environ.get("TPUDDP_WORLD_SIZE") or 4)

from tpuddp.parallel.spawn import maybe_reexec_for_world  # noqa: E402

maybe_reexec_for_world(world_size, "cpu")

from tpuddp.resilience.guard import ReplicaDesync  # noqa: E402
from tpuddp.resilience.preemption import (  # noqa: E402
    EXIT_DESYNC,
    EXIT_PREEMPTED,
    TrainingPreempted,
)
from train_accelerate import basic_accelerate_training  # noqa: E402

TRAINING = {
    "model": "toy_mlp",
    "dataset": "cifar10",
    "data_root": "/nonexistent",  # forces the zero-egress synthetic fallback
    "train_batch_size": 8,  # per replica
    "test_batch_size": 8,
    "learning_rate": 0.01,
    "num_epochs": num_epochs,
    "checkpoint_epoch": 1,
    "image_size": None,
    "seed": 0,
    "synthetic_n": (256, 64),
}
TRAINING.update(json.loads(os.environ.get("TPUDDP_CHAOS_TRAINING") or "{}"))

try:
    basic_accelerate_training(out_dir, TRAINING, num_chips=world_size)
except TrainingPreempted as e:
    print(f"{e}; exiting {EXIT_PREEMPTED} (requeue+resume)")
    sys.exit(EXIT_PREEMPTED)
except ReplicaDesync as e:
    print(f"{e}; exiting {EXIT_DESYNC}")
    sys.exit(EXIT_DESYNC)
