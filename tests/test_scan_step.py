"""Multi-step scan training: K fused steps must be semantically identical to
K sequential single steps (params, buffers, metrics, RNG schedule)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import optim
from tpuddp.data import SyntheticClassification
from tpuddp.models import ToyCNN, ToyMLP
from tpuddp.nn import CrossEntropyLoss
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.training.step import stack_batches

KEY = jax.random.key(7)


def test_resolve_scan_steps_auto_caps_by_model_size():
    from tpuddp.training.loop import resolve_scan_steps

    mb = 1024 * 1024
    assert resolve_scan_steps("auto", 1000) == 32  # unknown batch size: conservative
    assert resolve_scan_steps("auto", 1000, param_bytes=100 * mb) == 32
    # known batch bytes: deep cap, bounded by the ~256MB staging budget
    assert resolve_scan_steps("auto", 1000, param_bytes=100 * mb, batch_nbytes=mb) == 64
    assert resolve_scan_steps("auto", 1000, param_bytes=100 * mb, batch_nbytes=16 * mb) == 16
    assert resolve_scan_steps("auto", 1000, param_bytes=100 * mb, batch_nbytes=10_000 * mb) == 1
    # dispatch-bound small models get the deep cap (BASELINE.md K-sweep)
    assert resolve_scan_steps("auto", 1000, param_bytes=2 * mb) == 64
    assert resolve_scan_steps("auto", 5, param_bytes=2 * mb) == 5  # epoch-bound
    assert resolve_scan_steps(16, 1000, param_bytes=2 * mb) == 16  # explicit wins


def make_batches(k, n=32, shape=(8, 8, 3), seed=0):
    ds = SyntheticClassification(n=n * k, shape=shape, seed=seed)
    return [
        (
            ds.images[i * n : (i + 1) * n],
            ds.labels[i * n : (i + 1) * n],
            np.ones(n, np.float32),
        )
        for i in range(k)
    ]


@pytest.mark.parametrize("mode", ["shard_map", "auto"])
@pytest.mark.parametrize("model_fn", [ToyMLP, lambda: ToyCNN(sync_bn=True)])
def test_scan_equals_sequential(cpu_devices, mode, model_fn):
    mesh = make_mesh(cpu_devices)
    batches = make_batches(4)

    def fresh():
        ddp = DistributedDataParallel(
            model_fn(), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh, mode=mode
        )
        return ddp, ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))

    # sequential
    ddp_a, state_a = fresh()
    total_a = None
    for b in batches:
        state_a, m = ddp_a.train_step(state_a, ddp_a.shard(b))
        total_a = m if total_a is None else jax.tree_util.tree_map(
            jnp.add, total_a, m
        )

    # fused scan
    ddp_b, state_b = fresh()
    stacked = ddp_b.shard_stacked(stack_batches(batches))
    state_b, total_b = ddp_b.train_step_many(state_b, stacked)

    assert int(state_b.step) == int(state_a.step) == 4
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        ),
        state_a.params,
        state_b.params,
    )
    np.testing.assert_allclose(
        np.sum(np.asarray(total_a["loss_sum"])),
        np.sum(np.asarray(total_b["loss_sum"])),
        rtol=1e-4,
    )
    assert float(np.sum(np.asarray(total_b["n"]))) == 4 * 32


def test_stack_batches_shapes():
    batches = make_batches(3, n=8, shape=(4,))
    xs, ys, ws = stack_batches(batches)
    assert xs.shape == (3, 8, 4)
    assert ys.shape == (3, 8)
    assert ws.shape == (3, 8)


@pytest.mark.parametrize("mode", ["shard_map", "auto"])
def test_eval_scan_equals_sequential(cpu_devices, mode):
    """Fused eval (build_eval_scan_step) must produce exactly the summed
    metrics of per-batch eval_step calls, without touching state."""
    mesh = make_mesh(cpu_devices)
    batches = make_batches(4, seed=3)
    ddp = DistributedDataParallel(
        ToyCNN(sync_bn=True), optim.Adam(1e-2), CrossEntropyLoss(),
        mesh=mesh, mode=mode,
    )
    state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))

    total_a = None
    for b in batches:
        m = ddp.eval_step(state, ddp.shard(b))
        total_a = m if total_a is None else jax.tree_util.tree_map(
            jnp.add, total_a, m
        )
    total_b = ddp.eval_step_many(state, ddp.shard_stacked(stack_batches(batches)))

    for k in ("loss_sum", "correct", "n"):
        np.testing.assert_allclose(
            np.sum(np.asarray(total_a[k])), np.sum(np.asarray(total_b[k])),
            rtol=1e-5,
        )


def test_sync_buffers_validated_at_wrap_time(cpu_devices):
    """Divergent BN buffers must not be publishable as replicated state: an
    unsynced stateful BatchNorm + sync_buffers='none' is refused at DDP
    construction, and misspelled modes are refused everywhere."""
    mesh = make_mesh(cpu_devices)

    with pytest.raises(ValueError, match="sync_buffers"):
        DistributedDataParallel(
            ToyCNN(sync_bn=False), optim.Adam(1e-2), CrossEntropyLoss(),
            mesh=mesh, mode="shard_map", sync_buffers="none",
        )
    with pytest.raises(ValueError, match="sync_buffers"):
        DistributedDataParallel(
            ToyMLP(), optim.Adam(1e-2), CrossEntropyLoss(),
            mesh=mesh, sync_buffers="brodcast",
        )
    # no divergent buffers (synced BN) -> 'none' is fine; 'pmean' always fine
    for model, sb in [
        (ToyCNN(sync_bn=True), "none"),
        (ToyMLP(), "none"),
        (ToyCNN(sync_bn=False), "pmean"),
    ]:
        ddp = DistributedDataParallel(
            model, optim.Adam(1e-2), CrossEntropyLoss(),
            mesh=mesh, mode="shard_map", sync_buffers=sb,
        )
        state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        (b,) = make_batches(1)
        state, m = ddp.train_step(state, ddp.shard(b))
        assert np.isfinite(np.sum(np.asarray(m["loss_sum"])))


def test_pmean_buffer_sync_averages_divergent_stats(cpu_devices):
    """sync_buffers='pmean' reconciles per-replica BN stats by averaging:
    after one step the published running mean equals the mean over replicas'
    local batch stats (not rank 0's)."""
    mesh = make_mesh(cpu_devices)
    ddp = DistributedDataParallel(
        ToyCNN(sync_bn=False, widths=(4,)), optim.Adam(1e-3),
        CrossEntropyLoss(), mesh=mesh, mode="shard_map", sync_buffers="pmean",
    )
    state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    (b,) = make_batches(1)
    state, _ = ddp.train_step(state, ddp.shard(b))
    # published state is replicated and finite
    bn_state = jax.tree_util.tree_leaves(state.model_state)
    assert all(np.all(np.isfinite(np.asarray(leaf))) for leaf in bn_state)


def test_undeclared_stateful_module_refused_at_wrap_time(cpu_devices):
    """A future custom stateful layer that never declares divergent_state()
    must be refused under sync_buffers='none' — the by-construction guarantee
    that replaced the old isinstance(BatchNorm) check."""
    from tpuddp import nn
    from tpuddp.nn.core import Module

    mesh = make_mesh(cpu_devices)

    class EmaTracker(Module):
        """Stateful, unsynced, and NOT special-cased anywhere."""

        def init(self, key, x):
            return (), {"ema": jnp.zeros(x.shape[-1])}

        def apply(self, params, state, x, ctx):
            new = {"ema": 0.9 * state["ema"] + 0.1 * x.mean(axis=tuple(range(x.ndim - 1)))}
            return x, new

    model = nn.Sequential(nn.Flatten(), EmaTracker(), nn.Linear(10))
    with pytest.raises(ValueError, match="divergent_state"):
        DistributedDataParallel(
            model, optim.Adam(1e-2), CrossEntropyLoss(),
            mesh=mesh, mode="shard_map", sync_buffers="none",
        )

    class VouchedEmaTracker(EmaTracker):
        def divergent_state(self):
            return False  # (for the test; a real EMA would sync instead)

    model2 = nn.Sequential(nn.Flatten(), VouchedEmaTracker(), nn.Linear(10))
    DistributedDataParallel(
        model2, optim.Adam(1e-2), CrossEntropyLoss(),
        mesh=mesh, mode="shard_map", sync_buffers="none",
    )
