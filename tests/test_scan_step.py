"""Multi-step scan training: K fused steps must be semantically identical to
K sequential single steps (params, buffers, metrics, RNG schedule)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import optim
from tpuddp.data import SyntheticClassification
from tpuddp.models import ToyCNN, ToyMLP
from tpuddp.nn import CrossEntropyLoss
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.training.step import stack_batches

KEY = jax.random.key(7)


def make_batches(k, n=32, shape=(8, 8, 3), seed=0):
    ds = SyntheticClassification(n=n * k, shape=shape, seed=seed)
    return [
        (
            ds.images[i * n : (i + 1) * n],
            ds.labels[i * n : (i + 1) * n],
            np.ones(n, np.float32),
        )
        for i in range(k)
    ]


@pytest.mark.parametrize("mode", ["shard_map", "auto"])
@pytest.mark.parametrize("model_fn", [ToyMLP, lambda: ToyCNN(sync_bn=True)])
def test_scan_equals_sequential(cpu_devices, mode, model_fn):
    mesh = make_mesh(cpu_devices)
    batches = make_batches(4)

    def fresh():
        ddp = DistributedDataParallel(
            model_fn(), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh, mode=mode
        )
        return ddp, ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))

    # sequential
    ddp_a, state_a = fresh()
    total_a = None
    for b in batches:
        state_a, m = ddp_a.train_step(state_a, ddp_a.shard(b))
        total_a = m if total_a is None else jax.tree_util.tree_map(
            jnp.add, total_a, m
        )

    # fused scan
    ddp_b, state_b = fresh()
    stacked = ddp_b.shard_stacked(stack_batches(batches))
    state_b, total_b = ddp_b.train_step_many(state_b, stacked)

    assert int(state_b.step) == int(state_a.step) == 4
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        ),
        state_a.params,
        state_b.params,
    )
    np.testing.assert_allclose(
        np.sum(np.asarray(total_a["loss_sum"])),
        np.sum(np.asarray(total_b["loss_sum"])),
        rtol=1e-4,
    )
    assert float(np.sum(np.asarray(total_b["n"]))) == 4 * 32


def test_stack_batches_shapes():
    batches = make_batches(3, n=8, shape=(4,))
    xs, ys, ws = stack_batches(batches)
    assert xs.shape == (3, 8, 4)
    assert ys.shape == (3, 8)
    assert ws.shape == (3, 8)
