"""End-to-end entrypoint runs (SURVEY.md §3.1-§3.3 call-stack parity) on the
8-device CPU world with synthetic-fallback data."""

import os
import subprocess
import sys

import pytest
import yaml

import submit_job as submit_mod
from tpuddp.parallel import backend


TINY_TRAINING = {
    "model": "toy_mlp",
    "dataset": "cifar10",
    "data_root": "/nonexistent",  # forces synthetic fallback
    "train_batch_size": 8,
    "test_batch_size": 8,
    "learning_rate": 0.01,
    "num_epochs": 1,
    "checkpoint_epoch": 1,
    "image_size": None,
    "seed": 0,
    "mode": "shard_map",
    "sync_bn": False,
}


@pytest.fixture(autouse=True)
def fresh_backend():
    backend.cleanup()
    yield
    backend.cleanup()


@pytest.mark.slow
def test_native_entrypoint_end_to_end(tmp_path, capsys):
    from functools import partial

    from train_native import basic_ddp_training_loop
    from tpuddp.parallel.spawn import run_ddp_training

    run_ddp_training(
        partial(basic_ddp_training_loop, training=TINY_TRAINING),
        world_size=8,
        save_dir=str(tmp_path),
        optional_args={"set_epoch": True, "print_rand": True},
        backend="cpu",
    )
    # checkpoint written with reference naming, epoch 0 (quirk Q6 parity)
    assert os.path.exists(tmp_path / "ckpt_0.npz")
    out = capsys.readouterr().out
    assert "Epoch 1/1" in out
    assert "Test Accuracy" in out
    assert "Python random state" in out  # print_rand probe
    assert "TRAIN: Batch 0" in out  # shard-disjointness probe


@pytest.mark.slow
def test_accelerate_entrypoint_end_to_end(tmp_path, capsys):
    from train_accelerate import basic_accelerate_training

    training = dict(TINY_TRAINING, num_epochs=1)
    basic_accelerate_training(str(tmp_path), training)
    assert os.path.exists(tmp_path / "model.npz")
    out = capsys.readouterr().out
    assert "Epoch 1/1" in out
    assert "Finished Training." in out


@pytest.mark.slow
def test_accelerate_entrypoint_resume(tmp_path, capsys):
    """training.resume on the managed path: a first run leaves
    state_{epoch}.npz files; a restarted run restores the newest (weights +
    optimizer moments + RNG position) and continues from the next epoch."""
    from train_accelerate import basic_accelerate_training

    training = dict(TINY_TRAINING, num_epochs=1, deferred_metrics=True)
    basic_accelerate_training(str(tmp_path), training)
    assert os.path.exists(tmp_path / "state_0.npz")
    capsys.readouterr()

    training = dict(TINY_TRAINING, num_epochs=2, resume=True, deferred_metrics=True)
    basic_accelerate_training(str(tmp_path), training)
    out = capsys.readouterr().out
    assert "Resumed from epoch 0 state." in out
    assert "Epoch 2/2" in out
    assert "Epoch 1/2" not in out  # epoch 0 was not re-trained
    assert os.path.exists(tmp_path / "state_1.npz")


def test_submit_job_tpu_dry_run(tmp_path):
    settings = {
        "script_path": "train_native.py",
        "out_dir": str(tmp_path / "out"),
        "local": {
            "device": "tpu",
            "tpu": {"name": "pod0", "zone": "us-central2-b", "num_chips": 32},
        },
    }
    sf = tmp_path / "s.yaml"
    sf.write_text(yaml.dump(settings))
    rc = submit_mod.main(["--settings_file", str(sf), "--dry_run"])
    assert rc == 0
    script = tmp_path / "out" / "launch_tpu.sh"
    text = script.read_text()
    assert "gcloud compute tpus tpu-vm ssh pod0" in text
    assert "--worker=all" in text
    assert "train_native.py --settings_file" in text
    assert os.access(script, os.X_OK)


def test_submit_job_condor_dry_run(tmp_path):
    """Reference condor schema keeps working (submit_job.py:7-43 contract)."""
    settings = {
        "script_path": "train_native.py",
        "out_dir": str(tmp_path / "out"),
        "local": {
            "device": "cuda",
            "condor": {
                "bid": 50,
                "num_cpus": 2,
                "memory_cpus": 128000,
                "num_gpus": 2,
                "memory_gpus": 60000,
            },
        },
    }
    sf = tmp_path / "s.yaml"
    sf.write_text(yaml.dump(settings))
    rc = submit_mod.main(["--settings_file", str(sf), "--dry_run"])
    assert rc == 0
    sub = (tmp_path / "out" / "submission_file.sub").read_text()
    assert f"executable = {sys.executable}" in sub
    assert "request_gpus = 2" in sub
    assert "TARGET.CUDAGlobalMemoryMb > 60000" in sub
    assert sub.rstrip().endswith("queue")


def test_submit_job_requires_tpu_or_condor(tmp_path):
    sf = tmp_path / "s.yaml"
    sf.write_text(yaml.dump({"script_path": "x", "out_dir": str(tmp_path), "local": {}}))
    with pytest.raises(ValueError):
        submit_mod.main(["--settings_file", str(sf), "--dry_run"])


@pytest.mark.slow
def test_native_cli_subprocess_with_reexec_launcher(tmp_path):
    """Full CLI parity run: `python train_native.py --settings_file ...` on a
    chipless config exercises the spawn-analog re-exec launcher."""
    settings = {
        "script_path": "train_native.py",
        "out_dir": str(tmp_path / "out"),
        "optional_args": {"set_epoch": True, "print_rand": False},
        "local": {"device": "cpu", "tpu": {"num_chips": 4}},
        "training": dict(TINY_TRAINING, train_batch_size=16, test_batch_size=16),
    }
    sf = tmp_path / "s.yaml"
    sf.write_text(yaml.dump(settings))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["TPUDDP_BACKEND"] = "cpu"
    # keep the child TPU-free: a second tunnel client alongside the test
    # process's registered one can crash the shared relay
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "train_native.py", "--settings_file", str(sf)],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "Epoch 1/1" in proc.stdout
    assert os.path.exists(tmp_path / "out" / "ckpt_0.npz")
    # provenance copy of the settings file into out_dir
    assert os.path.exists(tmp_path / "out" / "s.yaml")


@pytest.mark.slow
def test_accelerate_entrypoint_observability_parity(tmp_path, capsys, monkeypatch):
    """The managed loop honors the same observability hooks as the native
    one: history.jsonl written by process 0, and $TPUDDP_DEBUG_NANS guards
    the aggregated losses."""
    import json

    from train_accelerate import basic_accelerate_training

    training = dict(TINY_TRAINING, num_epochs=2, deferred_metrics=True)
    basic_accelerate_training(str(tmp_path), training)
    capsys.readouterr()
    lines = [
        json.loads(l)
        for l in open(tmp_path / "history.jsonl").read().splitlines()
    ]
    # typed stream: a run_meta header opens the file, then one epoch row per
    # epoch, each carrying the step recorder's percentile fields
    assert lines[0]["type"] == "run_meta" and lines[0]["api"] == "managed"
    epochs = [l for l in lines if l.get("type") == "epoch"]
    assert len(epochs) == 2
    assert {"epoch", "train_loss", "test_loss", "test_accuracy"} <= set(epochs[0])
    assert epochs[0]["step_time_ms_p50"] is not None
    from tpuddp.observability import schema as obs_schema

    assert obs_schema.validate_history_records(lines) == []

    # NaN guard: a poisoned epoch must still write its post-mortem row
    # (record-before-check, native-driver parity) and then raise
    monkeypatch.setenv("TPUDDP_DEBUG_NANS", "1")
    monkeypatch.setattr(
        "train_accelerate.train", lambda *a, **k: (float("nan"), 8.0)
    )
    monkeypatch.setattr(
        "train_accelerate.evaluate", lambda *a, **k: (0.1, 50.0, 8)
    )
    with pytest.raises(FloatingPointError, match="train loss"):
        basic_accelerate_training(str(tmp_path / "nan"), training)
    raw = open(tmp_path / "nan" / "history.jsonl").read()
    # strict-JSON contract (ISSUE 3): the poisoned metric lands as null,
    # never the bare NaN token strict parsers reject
    assert "NaN" not in raw
    last = json.loads(raw.splitlines()[-1])
    assert last["epoch"] == 0 and last["train_loss"] is None
