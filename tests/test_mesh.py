"""Mesh abstraction: named axes, N-D tiling (the TP/PP-ready design from
SURVEY.md §2c's build consequence), batch placement."""

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuddp.parallel import DATA_AXIS, data_sharded, make_mesh, replicated
from tpuddp.parallel.mesh import replicate, shard_batch


def test_default_mesh_is_1d_data(cpu_devices):
    mesh = make_mesh(cpu_devices)
    assert mesh.axis_names == (DATA_AXIS,)
    assert mesh.devices.shape == (8,)


def test_nd_mesh_axes(cpu_devices):
    mesh = make_mesh(cpu_devices, axes={"data": 4, "model": 2})
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (4, 2)
    assert mesh.shape["model"] == 2


def test_mesh_axes_must_tile_devices(cpu_devices):
    with pytest.raises(ValueError, match="do not tile"):
        make_mesh(cpu_devices, axes={"data": 3})


def test_sharding_helpers(cpu_devices):
    mesh = make_mesh(cpu_devices)
    assert replicated(mesh).spec == P()
    assert data_sharded(mesh).spec == P(DATA_AXIS)
    assert data_sharded(mesh, ndim=3).spec == P(DATA_AXIS, None, None)


def test_shard_batch_places_disjoint_shards(cpu_devices):
    mesh = make_mesh(cpu_devices)
    x = np.arange(16 * 2, dtype=np.float32).reshape(16, 2)
    placed = shard_batch(mesh, x)
    assert placed.sharding.spec == P(DATA_AXIS, None)
    shards = placed.addressable_shards
    assert len(shards) == 8
    np.testing.assert_array_equal(np.asarray(shards[0].data), x[:2])
    np.testing.assert_array_equal(np.asarray(shards[7].data), x[14:])


def test_replicate_places_full_copy_everywhere(cpu_devices):
    mesh = make_mesh(cpu_devices[:4])
    tree = {"w": jnp.arange(6.0)}
    placed = replicate(mesh, tree)
    assert placed["w"].sharding.spec == P()
    assert len(placed["w"].addressable_shards) == 4
    for s in placed["w"].addressable_shards:
        np.testing.assert_array_equal(np.asarray(s.data), np.arange(6.0))
