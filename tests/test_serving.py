"""Serving engine suite (ISSUE 6): queue/admission semantics, round-robin
fairness, bucketed coalescing correctness (served logits bitwise-equal to a
direct forward over the same padded batch), checkpoint restore through the
integrity path, serving_stats schema emission + drift rejection, and — slow
tier — the SIGTERM drain exit-code contract and a loadgen subprocess smoke.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import yaml

from tpuddp import config as config_lib
from tpuddp.models import load_model
from tpuddp.nn.core import Context
from tpuddp.observability import schema
from tpuddp.resilience.preemption import EXIT_PREEMPTED
from tpuddp.serving import (
    AdmissionError,
    BatchScheduler,
    ReplicaPool,
    Request,
    RequestQueue,
    ServingEngine,
    ServingStats,
)
from tpuddp.serving.replica import _restore_variables
from tpuddp.training import checkpoint as ckpt
from tpuddp.training.train_state import TrainState
from tpuddp.utils import batching

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHAPE = (8, 8, 3)  # tiny sample shape: keeps every compile trivial


def _req(tenant, rows, seed=0):
    rng = np.random.RandomState(seed + rows)
    return Request(tenant, rng.randn(rows, *SHAPE).astype(np.float32))


def _serving_cfg(**overrides):
    cfg = config_lib.serving_config({})
    cfg.update(
        model="toy_mlp",
        input_shape=list(SHAPE),
        num_replicas=2,
        max_batch_size=8,
        batch_timeout_ms=1.0,
        stats_window=8,
        seed=0,
    )
    cfg.update(overrides)
    return cfg


@pytest.fixture
def engine(cpu_devices):
    eng = ServingEngine.from_config(_serving_cfg(), devices=cpu_devices)
    eng.start()
    yield eng
    eng.drain()


# ---------------------------------------------------------------- admission --


def test_queue_depth_reject():
    q = RequestQueue(max_depth=3)
    for i in range(3):
        q.put(_req("a", 1, seed=i))
    with pytest.raises(AdmissionError) as e:
        q.put(_req("a", 1))
    assert e.value.reason == "queue_full"
    # draining a group frees capacity again
    assert q.take_group(max_rows=8) is not None
    q.put(_req("a", 1))


def test_tenant_quota_reject():
    q = RequestQueue(max_depth=16, per_tenant_quota=2)
    q.put(_req("a", 1))
    q.put(_req("a", 1))
    with pytest.raises(AdmissionError) as e:
        q.put(_req("a", 1))
    assert e.value.reason == "tenant_quota"
    # another tenant is unaffected by a's quota exhaustion
    q.put(_req("b", 1))


def test_draining_reject():
    q = RequestQueue(max_depth=4)
    q.put(_req("a", 1))
    q.close()
    with pytest.raises(AdmissionError) as e:
        q.put(_req("a", 1))
    assert e.value.reason == "draining"
    # queued work still drains, then the closed+empty queue signals exit
    assert len(q.take_group(max_rows=8)) == 1
    assert q.take_group(max_rows=8) is None


def test_round_robin_fairness():
    """A tenant queueing 10 requests must not make another tenant's 2 wait
    behind all 10: groups alternate tenants (at most one request per tenant
    per pass)."""
    q = RequestQueue(max_depth=64)
    for i in range(10):
        q.put(_req("flood", 1, seed=i))
    q.put(_req("small", 1, seed=100))
    q.put(_req("small", 1, seed=101))
    first = q.take_group(max_rows=4)
    tenants = [r.tenant for r in first]
    assert tenants == ["flood", "small", "flood", "small"], tenants
    # per-tenant FIFO preserved within the interleave
    floods = [r for r in first if r.tenant == "flood"]
    assert floods[0].id < floods[1].id


def test_engine_rejects_oversized_and_bad_shape(engine):
    with pytest.raises(AdmissionError) as e:
        engine.submit("a", np.zeros((9,) + SHAPE, np.float32))  # > max_batch 8
    assert e.value.reason == "oversized"
    with pytest.raises(AdmissionError) as e:
        engine.submit("a", np.zeros((1, 4, 4, 3), np.float32))
    assert e.value.reason == "bad_shape"
    with pytest.raises(AdmissionError) as e:
        engine.submit("a", np.zeros((1,) + SHAPE, np.float64))
    assert e.value.reason == "bad_shape"
    rej = engine.stats.summary()["rejected"]
    assert rej == {"oversized": 1, "bad_shape": 2}


# ----------------------------------------------------------------- batching --


def test_scheduler_buckets_and_padding():
    q = RequestQueue(max_depth=64)
    sched = BatchScheduler(q, max_batch_size=8, batch_timeout_ms=0.0)
    assert sched.buckets == [1, 2, 4, 8]
    batch = sched.form([_req("a", 2), _req("b", 3)])
    assert batch.rows == 5 and batch.bucket == 8  # 5 -> next pow2 bucket
    assert batch.slices == [(0, 2), (2, 5)]
    assert batch.x.shape == (8,) + SHAPE
    np.testing.assert_array_equal(batch.w, [1, 1, 1, 1, 1, 0, 0, 0])
    assert abs(batch.occupancy - 5 / 8) < 1e-9
    single = sched.form([_req("a", 4)])
    assert single.bucket == 4 and single.occupancy == 1.0


def test_served_bitwise_equals_direct_forward(engine):
    """Acceptance: logits served through queue+scheduler+replica are bitwise
    those of a direct model forward over the same padded batch."""
    module = engine.pool.module
    params = engine.pool.replicas[0].params
    mstate = engine.pool.replicas[0].model_state

    # params as ARGUMENTS, like the replica's own program — a jit CLOSING
    # over them would embed the weights as constants, which XLA may fold
    # into different (1-ulp-off) arithmetic than the served program
    @jax.jit
    def direct(p, s, x):
        ctx = Context(train=False, rng=jax.random.key(0), axis_name=None)
        return module.apply(p, s, x, ctx)[0]

    rng = np.random.RandomState(7)
    for rows in (1, 2, 3, 5, 8):
        x = rng.randn(rows, *SHAPE).astype(np.float32)
        served = engine.submit("bitwise", x).result(timeout=60)
        xp, _, _ = batching.pad_batch(
            x, None, batching.bucket_for(rows, engine.scheduler.max_batch_size)
        )
        ref = np.asarray(direct(params, mstate, xp))[:rows]
        np.testing.assert_array_equal(served, ref)


def test_coalesced_batch_slices_bitwise(cpu_devices):
    """Multiple requests coalesced into ONE padded batch slice back to
    exactly their own rows' logits."""
    pool = ReplicaPool.from_config(_serving_cfg(num_replicas=1),
                                   devices=cpu_devices[:1])
    q = RequestQueue(max_depth=16)
    sched = BatchScheduler(q, max_batch_size=8)
    reqs = [_req("a", 2, seed=1), _req("b", 3, seed=2), _req("a", 1, seed=3)]
    batch = sched.form(reqs)
    logits = np.asarray(pool.replicas[0].infer(batch.x))
    module = pool.module

    @jax.jit
    def direct(p, s, x):
        ctx = Context(train=False, rng=jax.random.key(0), axis_name=None)
        return module.apply(p, s, x, ctx)[0]

    ref = np.asarray(
        direct(pool.replicas[0].params, pool.replicas[0].model_state, batch.x)
    )
    for r, (lo, hi) in zip(reqs, batch.slices):
        np.testing.assert_array_equal(logits[lo:hi], ref[lo:hi])
        assert hi - lo == r.rows


def test_replicas_on_distinct_devices(engine):
    devs = {r.device for r in engine.pool.replicas}
    assert len(devs) == 2
    # params actually live on their replica's device
    for r in engine.pool.replicas:
        leaf = jax.tree_util.tree_leaves(r.params)[0]
        assert leaf.devices() == {r.device}


# --------------------------------------------------------------- overload ----


def test_per_tenant_fairness_under_overload(cpu_devices):
    """One tenant flooding past its quota gets rejected with reason
    tenant_quota; a well-behaved tenant's requests all complete."""
    eng = ServingEngine.from_config(
        _serving_cfg(per_tenant_quota=4, max_queue_depth=64),
        devices=cpu_devices,
    )
    eng.start()
    try:
        flood_results, quota_rejects = [], 0
        for i in range(60):
            try:
                flood_results.append(
                    eng.submit("flood", np.zeros((1,) + SHAPE, np.float32))
                )
            except AdmissionError as e:
                assert e.reason == "tenant_quota"
                quota_rejects += 1
            if i % 10 == 0:
                ok = eng.submit("polite", np.ones((2,) + SHAPE, np.float32))
                assert ok.result(timeout=60).shape == (2, 10)
        for r in flood_results:
            r.result(timeout=60)
    finally:
        summary = eng.drain()
    assert summary["per_tenant_completed"]["polite"] == 6
    assert quota_rejects > 0
    assert summary["rejected"]["tenant_quota"] == quota_rejects
    assert summary["completed"] == 6 + len(flood_results)


def test_dispatch_error_fails_requests_not_engine(cpu_devices):
    eng = ServingEngine.from_config(
        _serving_cfg(num_replicas=1), devices=cpu_devices[:1]
    )
    eng.start()
    try:
        replica = eng.pool.replicas[0]
        real_infer = replica.infer

        def boom(x):
            raise RuntimeError("injected dispatch failure")

        replica.infer = boom
        res = eng.submit("a", np.zeros((1,) + SHAPE, np.float32))
        with pytest.raises(RuntimeError, match="injected dispatch failure"):
            res.result(timeout=60)
        # the loop survives: restore the forward, the next request serves
        replica.infer = real_infer
        ok = eng.submit("a", np.zeros((1,) + SHAPE, np.float32))
        assert ok.result(timeout=60).shape == (1, 10)
    finally:
        summary = eng.drain()
    assert summary["completed"] == 1


def test_replica_marked_unhealthy_after_consecutive_errors(cpu_devices, tmp_path):
    """Graceful degradation (ISSUE 7 satellite): K consecutive dispatch
    errors mark a replica unhealthy and stop routing to it — a broken
    replica must not fail batches forever. Healthy-replica traffic
    continues, a replica_unhealthy event row lands in history.jsonl, and
    drain still exits cleanly."""
    K = 3
    eng = ServingEngine.from_config(
        _serving_cfg(num_replicas=2, unhealthy_after=K),
        out_dir=str(tmp_path),
        devices=cpu_devices[:2],
    )
    eng.start()
    try:
        broken = eng.pool.replicas[0]

        def boom(x):
            raise RuntimeError("injected persistent replica failure")

        broken.infer = boom
        failures = 0
        served = 0
        deadline = time.time() + 120
        # keep submitting until the broken replica has eaten K batches and
        # been retired; every request either fails (broken took it) or
        # serves (healthy replica took it)
        while broken.healthy and time.time() < deadline:
            res = eng.submit("t", np.zeros((1,) + SHAPE, np.float32))
            try:
                res.result(timeout=60)
                served += 1
            except RuntimeError:
                failures += 1
        assert not broken.healthy, "replica never marked unhealthy"
        assert failures >= K
        # routing has stopped: from here on EVERY request lands healthy
        for _ in range(8):
            ok = eng.submit("t", np.zeros((2,) + SHAPE, np.float32))
            assert ok.result(timeout=60).shape == (2, 10)
            served += 1
        assert eng.pool.replicas[1].healthy
    finally:
        summary = eng.drain()  # clean drain despite the dead replica
    assert summary["completed"] == served
    rows = [
        json.loads(line)
        for line in open(os.path.join(str(tmp_path), "history.jsonl"))
    ]
    unhealthy = [r for r in rows if r.get("event") == "replica_unhealthy"]
    assert unhealthy and unhealthy[0]["replica"] == 0
    assert unhealthy[0]["consecutive_errors"] == K
    errs = schema.validate_history_records(rows)
    assert errs == [], errs


def test_last_replica_unhealthy_fails_queued_requests(cpu_devices, tmp_path):
    """When the LAST healthy replica dies, queued requests must fail with an
    error instead of hanging the client (and the drain)."""
    eng = ServingEngine.from_config(
        _serving_cfg(num_replicas=1, unhealthy_after=2),
        out_dir=str(tmp_path),
        devices=cpu_devices[:1],
    )
    eng.start()
    try:
        replica = eng.pool.replicas[0]
        replica.infer = lambda x: (_ for _ in ()).throw(
            RuntimeError("replica dead")
        )
        # sequential submits: each failure is its own batch, so the second
        # one crosses unhealthy_after=2; later requests hit the no-healthy-
        # replicas branch and still fail fast instead of hanging
        for _ in range(4):
            res = eng.submit("t", np.zeros((1,) + SHAPE, np.float32))
            with pytest.raises(RuntimeError):
                res.result(timeout=60)
        assert not replica.healthy
    finally:
        summary = eng.drain()
    assert summary["completed"] == 0


def test_drain_then_submit_rejected(cpu_devices):
    eng = ServingEngine.from_config(
        _serving_cfg(num_replicas=1), devices=cpu_devices[:1]
    )
    eng.start()
    res = eng.submit("a", np.zeros((2,) + SHAPE, np.float32))
    summary = eng.drain()
    assert res.done() and res.result().shape == (2, 10)
    assert summary["completed"] == 1
    with pytest.raises(AdmissionError) as e:
        eng.submit("a", np.zeros((1,) + SHAPE, np.float32))
    assert e.value.reason == "draining"


# -------------------------------------------------------------- checkpoints --


def _toy_variables(seed):
    module = load_model("toy_mlp", num_classes=10)
    return module, *module.init(
        jax.random.key(seed), jnp.zeros((1,) + SHAPE, jnp.float32)
    )


def test_restore_native_trainstate_checkpoint(tmp_path):
    module, params, mstate = _toy_variables(seed=123)
    state = TrainState(
        params=params,
        model_state=mstate,
        opt_state={"m": jax.tree_util.tree_map(jnp.zeros_like, params)},
        step=jnp.zeros((), jnp.int32),
        rng=jax.random.key(9),
    )
    ckpt.save(ckpt.checkpoint_path(str(tmp_path), 3), state,
              meta={"epoch": 3, "completed": 1})
    # template from a DIFFERENT seed: equality below proves the restore
    _, t_params, t_mstate = _toy_variables(seed=7)
    r_params, _, epoch = _restore_variables(
        str(tmp_path), "ckpt", t_params, t_mstate
    )
    assert epoch == 3
    for a, b in zip(
        jax.tree_util.tree_leaves(r_params), jax.tree_util.tree_leaves(params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_managed_state_checkpoint_and_auto(tmp_path):
    module, params, mstate = _toy_variables(seed=42)
    tree = {"params": params, "model_state": mstate,
            "opt_state": {"v": jnp.zeros((3,))}}
    ckpt.save(ckpt.checkpoint_path(str(tmp_path), 5, prefix="state"), tree,
              meta={"epoch": 5, "completed": 1})
    _, t_params, t_mstate = _toy_variables(seed=7)
    # explicit prefix and "auto" (newest across families) both find it
    for prefix in ("state", "auto"):
        r_params, _, epoch = _restore_variables(
            str(tmp_path), prefix, t_params, t_mstate
        )
        assert epoch == 5
        for a, b in zip(
            jax.tree_util.tree_leaves(r_params),
            jax.tree_util.tree_leaves(params),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_missing_checkpoint_raises(tmp_path):
    _, t_params, t_mstate = _toy_variables(seed=7)
    with pytest.raises(FileNotFoundError):
        _restore_variables(str(tmp_path), "auto", t_params, t_mstate)


def test_pool_from_config_restores(tmp_path, cpu_devices):
    module, params, mstate = _toy_variables(seed=5)
    ckpt.save(
        ckpt.checkpoint_path(str(tmp_path), 2, prefix="state"),
        {"params": params, "model_state": mstate},
        meta={"epoch": 2, "completed": 1},
    )
    pool = ReplicaPool.from_config(
        _serving_cfg(checkpoint_dir=str(tmp_path), seed=999, num_replicas=2),
        devices=cpu_devices,
    )
    assert pool.restored_epoch == 2
    for a, b in zip(
        jax.tree_util.tree_leaves(pool.replicas[1].params),
        jax.tree_util.tree_leaves(params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------ schema / stats --


def test_serving_stats_rows_validate(tmp_path, cpu_devices):
    eng = ServingEngine.from_config(
        _serving_cfg(num_replicas=1, stats_window=4),
        out_dir=str(tmp_path),
        devices=cpu_devices[:1],
    )
    eng.start()
    for i in range(10):
        eng.submit(f"t{i % 2}", np.ones((1,) + SHAPE, np.float32)).result(60)
    eng.drain()
    path = os.path.join(str(tmp_path), "history.jsonl")
    errors, n = schema.validate_history_file(path)
    assert errors == [] and n >= 4  # run_meta + >=2 windows + drain event
    records = [json.loads(l) for l in open(path) if l.strip()]
    assert records[0]["type"] == "run_meta"
    assert records[0]["api"] == "serving"
    rows = [r for r in records if r["type"] == "serving_stats"]
    assert sum(r["completed"] for r in rows) == 10
    assert all(r["schema_version"] == schema.SCHEMA_VERSION for r in rows)
    assert records[-1]["type"] == "event"
    assert records[-1]["event"] == "serving_drain"


def test_serving_stats_schema_reject_drift():
    good = schema.stamp("serving_stats", {
        "window": 0, "requests": 4, "completed": 4, "rejected": 0,
        "queue_ms_p50": 1.0, "device_ms_p50": 0.5, "e2e_ms_p50": 2.0,
        "e2e_ms_p95": 3.0, "e2e_ms_p99": 4.0, "throughput_rps": 10.0,
        "batch_occupancy": 0.9, "shed": 0,
    })
    assert schema.validate_record(good) == []
    # v7 drift: a window without its shed count is invalid; a v6 copy
    # without it stays valid (versioned requirement)
    drifted = {k: v for k, v in good.items() if k != "shed"}
    errs = schema.validate_record(drifted)
    assert errs and any("shed" in e for e in errs)
    v6 = dict(drifted)
    v6["schema_version"] = 6
    assert schema.validate_record(v6) == []
    missing = dict(good)
    del missing["e2e_ms_p99"]
    assert any("e2e_ms_p99" in e for e in schema.validate_record(missing))
    newer = dict(good, schema_version=schema.SCHEMA_VERSION + 1)
    assert any("newer" in e for e in schema.validate_record(newer))


def test_inspect_cli_rejects_drifted_serving_history(tmp_path):
    """Satellite: tpuddp_inspect --validate must exit 1 on a serving row
    that drifted off the v2 schema."""
    path = tmp_path / "history.jsonl"
    meta = schema.make_run_meta(world_size=1, comm_hook=None, guard=None,
                                extra={"api": "serving"})
    bad = schema.stamp("serving_stats", {"window": 0, "requests": 1})
    with open(path, "w") as f:
        f.write(json.dumps(meta) + "\n")
        f.write(json.dumps(bad) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpuddp_inspect.py"),
         "--validate", str(path)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "missing required field" in proc.stderr


def test_stats_mark_since():
    stats = ServingStats(writer=None, window=0)
    q = RequestQueue(max_depth=8)
    sched = BatchScheduler(q, max_batch_size=8)
    batch = sched.form([_req("a", 3)])
    t = time.perf_counter()
    stats.record_submit()
    stats.record_batch(batch, t, t + 0.010)
    m = stats.mark()
    batch2 = sched.form([_req("b", 2)])
    stats.record_submit()
    stats.record_batch(batch2, t, t + 0.020)
    d = stats.since(m)
    assert d["completed"] == 1 and d["rows"] == 2
    assert abs(d["device_ms"]["p50"] - 20.0) < 0.5
    total = stats.summary()
    assert total["completed"] == 2 and total["completed_rows"] == 5


# ---------------------------------------------------------------- slow tier --


def _subprocess_env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["TPUDDP_BACKEND"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _write_settings(tmp_path, **serving_overrides):
    serving = dict(
        model="toy_mlp", input_shape=[8, 8, 3], num_replicas=2,
        max_batch_size=8, stats_window=8,
    )
    serving.update(serving_overrides)
    path = os.path.join(str(tmp_path), "settings.yaml")
    with open(path, "w") as f:
        yaml.dump({"out_dir": os.path.join(str(tmp_path), "out"),
                   "serving": serving}, f)
    return path


@pytest.mark.slow
def test_demo_entrypoint(tmp_path):
    settings = _write_settings(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "tpuddp.serving", "--settings", settings,
         "--demo", "20", "--tenants", "2"],
        capture_output=True, text=True, env=_subprocess_env(), cwd=REPO,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["completed"] == 20
    assert set(summary["per_tenant_completed"]) == {"tenant0", "tenant1"}
    errors, _ = schema.validate_history_file(
        os.path.join(str(tmp_path), "out", "history.jsonl")
    )
    assert errors == []


@pytest.mark.slow
@pytest.mark.chaos
def test_sigterm_drain_exit75(tmp_path):
    """SIGTERM while serving: admission closes, in-flight work completes,
    stats flush, and the process exits with the resilience contract's 75."""
    settings = _write_settings(tmp_path)
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "tpuddp.serving", "--settings", settings,
         "--serve", "120"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_subprocess_env(), cwd=REPO,
    )
    try:
        deadline = time.time() + 240
        ready = False
        for line in proc.stdout:
            if "serving: ready" in line:
                ready = True
                break
            if time.time() > deadline:
                break
        assert ready, "server never reported ready"
        proc.send_signal(signal.SIGTERM)
        out = proc.stdout.read()
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == EXIT_PREEMPTED, out[-2000:]
    history = os.path.join(str(tmp_path), "out", "history.jsonl")
    errors, _ = schema.validate_history_file(history)
    assert errors == []
    records = [json.loads(l) for l in open(history) if l.strip()]
    drain = [r for r in records if r.get("event") == "serving_drain"]
    assert drain and drain[-1]["reason"] == "sigterm_drain"


@pytest.mark.slow
def test_loadgen_smoke(tmp_path):
    """Acceptance demo: loadgen drives 2 tenants against 2 replicas on the
    CPU mesh; the latency-vs-offered-throughput curve (>=3 open-loop points
    with p50/p99) lands in bench format and validates."""
    out = os.path.join(str(tmp_path), "bench_results.json")
    proc = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "tools", "loadgen.py"),
         "--quick", "--replicas", "2", "--tenants", "2",
         "--history-dir", str(tmp_path), "--out", out],
        capture_output=True, text=True, env=_subprocess_env(), cwd=REPO,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    last = json.loads(proc.stdout.strip().splitlines()[-1])
    assert last["completed"] >= 100
    payload = json.load(open(out))
    assert schema.validate_bench_payload(payload) == []
    assert payload["tenants"] == 2 and payload["replicas"] == 2
    open_rows = [r for r in payload["configs"].values()
                 if r.get("mode") == "open"]
    assert len(open_rows) >= 3
    for row in open_rows:
        assert row["offered_rps"] > 0
        assert row["e2e_ms_p50"] is not None
        assert row["e2e_ms_p99"] is not None
    errors, _ = schema.validate_history_file(
        os.path.join(str(tmp_path), "history.jsonl")
    )
    assert errors == []
    # the inspect CLI accepts both artifacts (the full gate's serving leg)
    for artifact in (out, os.path.join(str(tmp_path), "history.jsonl")):
        rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "tpuddp_inspect.py"),
             "--validate", artifact],
            capture_output=True, text=True,
        ).returncode
        assert rc == 0
