"""CIFAR-10 parsing + device-side transform parity with the reference's
torchvision pipeline (data_and_toy_model.py:13-36)."""

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp.data import cifar10 as c10
from tpuddp.data import transforms as T


@pytest.fixture(scope="module")
def fake_cifar_root(tmp_path_factory):
    """Write a tiny on-disk CIFAR-10 in both formats."""
    root = tmp_path_factory.mktemp("cifar")
    rng = np.random.RandomState(0)

    pydir = root / c10.PY_DIR
    pydir.mkdir()
    for name in c10.TRAIN_PY + c10.TEST_PY:
        n = 20
        data = rng.randint(0, 256, (n, 3072), dtype=np.uint8)
        labels = rng.randint(0, 10, n).tolist()
        with open(pydir / name, "wb") as f:
            pickle.dump({b"data": data, b"labels": labels}, f)

    bindir = root / c10.BIN_DIR
    bindir.mkdir()
    for name in c10.TRAIN_BIN + c10.TEST_BIN:
        n = 20
        rows = np.concatenate(
            [
                rng.randint(0, 10, (n, 1), dtype=np.uint8),
                rng.randint(0, 256, (n, 3072), dtype=np.uint8),
            ],
            axis=1,
        )
        rows.tofile(str(bindir / name))
    return str(root)


def test_cifar10_py_format(fake_cifar_root):
    ds = c10.CIFAR10(fake_cifar_root, train=True)
    assert ds.images.shape == (100, 32, 32, 3)
    assert ds.images.dtype == np.uint8
    assert ds.labels.shape == (100,)
    x, y = ds.get_batch([0, 5, 7])
    assert x.shape == (3, 32, 32, 3)


def test_cifar10_bin_format(fake_cifar_root, tmp_path):
    # point directly at the bin dir via a root that only contains it
    import shutil

    root = tmp_path / "only_bin"
    root.mkdir()
    shutil.copytree(
        os.path.join(fake_cifar_root, c10.BIN_DIR), root / c10.BIN_DIR
    )
    ds = c10.CIFAR10(str(root), train=False)
    assert ds.images.shape == (20, 32, 32, 3)
    assert 0 <= ds.labels.min() and ds.labels.max() < 10


def test_missing_dataset_raises_clearly(tmp_path):
    with pytest.raises(FileNotFoundError, match="CIFAR-10 not found"):
        c10.CIFAR10(str(tmp_path / "nothing"), download=False)


def test_load_datasets_synthetic_fallback(tmp_path):
    train, test = c10.load_datasets(
        str(tmp_path / "nope"), download=False, synthetic_fallback=True
    )
    assert train.images.dtype == np.uint8
    assert len(train) > len(test)


def test_channel_order_is_rgb_planes(fake_cifar_root):
    """Reference format: 3072 bytes = R plane, G plane, B plane."""
    ds = c10.CIFAR10(fake_cifar_root, train=True)
    with open(os.path.join(fake_cifar_root, c10.PY_DIR, "data_batch_1"), "rb") as f:
        raw = pickle.load(f, encoding="bytes")[b"data"][0]
    np.testing.assert_array_equal(ds.images[0, :, :, 0].reshape(-1), raw[:1024])
    np.testing.assert_array_equal(ds.images[0, :, :, 2].reshape(-1), raw[2048:])


# ---- transforms ----


def test_to_float_and_normalize_matches_torchvision_math():
    x = np.random.RandomState(1).randint(0, 256, (2, 32, 32, 3), dtype=np.uint8)
    out = T.normalize(T._to_float(jnp.asarray(x)))
    manual = (x.astype(np.float32) / 255.0 - np.array(c10.CIFAR10_MEAN)) / np.array(
        c10.CIFAR10_STD
    )
    np.testing.assert_allclose(np.asarray(out), manual, rtol=1e-5, atol=1e-6)


def test_resize_matches_torch_bilinear():
    import torch
    import torch.nn.functional as F

    x = np.random.RandomState(2).rand(2, 32, 32, 3).astype(np.float32)
    ours = T.resize(jnp.asarray(x), 64)
    ref = F.interpolate(
        torch.from_numpy(x.transpose(0, 3, 1, 2)),
        size=64,
        mode="bilinear",
        align_corners=False,
    ).numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-3, atol=1e-4)


def test_random_flip_is_per_sample_and_mirrors():
    x = np.arange(2 * 4 * 4 * 1, dtype=np.float32).reshape(2, 4, 4, 1)
    flipped_all = T.random_horizontal_flip(jax.random.key(0), jnp.asarray(x), p=1.0)
    np.testing.assert_array_equal(np.asarray(flipped_all), x[:, :, ::-1, :])
    none = T.random_horizontal_flip(jax.random.key(0), jnp.asarray(x), p=0.0)
    np.testing.assert_array_equal(np.asarray(none), x)
    # p=0.5 over a big batch: both outcomes occur
    big = jnp.ones((64, 2, 2, 1)).at[:, 0, 0, 0].set(jnp.arange(64.0))
    out = T.random_horizontal_flip(jax.random.key(1), big)
    changed = np.any(np.asarray(out) != np.asarray(big), axis=(1, 2, 3))
    assert 0 < changed.sum() < 64


def test_train_augment_end_to_end_shapes_and_range():
    aug = T.make_train_augment(size=64)
    x = jnp.asarray(
        np.random.RandomState(3).randint(0, 256, (4, 32, 32, 3), dtype=np.uint8)
    )
    out = aug(jax.random.key(0), x)
    assert out.shape == (4, 64, 64, 3)
    assert out.dtype == jnp.float32
    assert float(jnp.abs(out).max()) < 4.0  # normalized range


def test_eval_transform_no_resize_when_size_none():
    t = T.make_eval_transform(size=None)
    x = jnp.zeros((2, 32, 32, 3), jnp.uint8)
    out = t(x)
    assert out.shape == (2, 32, 32, 3)


def test_augment_is_jittable():
    aug = T.make_train_augment(size=48)
    f = jax.jit(aug)
    out = f(jax.random.key(0), jnp.zeros((2, 32, 32, 3), jnp.uint8))
    assert out.shape == (2, 48, 48, 3)


def test_compute_dtype_config():
    import jax.numpy as jnp

    from tpuddp.data import compute_dtype_for

    assert compute_dtype_for({}) == jnp.float32
    assert compute_dtype_for({"compute_dtype": "bfloat16"}) == jnp.bfloat16
    assert compute_dtype_for({"compute_dtype": "bf16"}) == jnp.bfloat16
    with pytest.raises(ValueError, match="compute_dtype"):
        compute_dtype_for({"compute_dtype": "float16x"})
