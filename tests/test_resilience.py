"""Resilience subsystem (ISSUE 1): retry/backoff, fault-spec parsing,
checkpoint integrity + retention, preemption drain round-trip, and the
heartbeat watchdog — all in-process on the 8-device CPU world. The
subprocess-kill scenarios live in test_chaos.py (chaos marker)."""

import logging
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import optim
from tpuddp.data import ShardedDataLoader, SyntheticClassification
from tpuddp.models import ToyMLP
from tpuddp.nn import CrossEntropyLoss
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.resilience import faults, integrity, preemption, retry as retry_mod, watchdog
from tpuddp.resilience.preemption import TrainingPreempted
from tpuddp.resilience.retry import RetryError, RetryPolicy, retry
from tpuddp.training import checkpoint as ckpt
from tpuddp.training.loop import run_training_loop
from tpuddp.utils.observability import MetricsWriter


# ---------------------------------------------------------------- retry


def test_retry_first_attempt_success_no_sleep():
    sleeps = []
    assert retry(lambda: 42, sleep=sleeps.append) == 42
    assert sleeps == []


def test_retry_eventual_success_backs_off():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    out = retry(
        flaky,
        RetryPolicy(max_attempts=5, base_delay=1.0, jitter=0.0),
        sleep=sleeps.append,
    )
    assert out == "ok" and calls["n"] == 3
    assert sleeps == [1.0, 2.0]  # exponential, jitter disabled


def test_retry_exhaustion_raises_retry_error_with_cause():
    sleeps = []
    with pytest.raises(RetryError, match="the-op failed after 3 attempt"):
        try:
            retry(
                lambda: (_ for _ in ()).throw(OSError("boom")),
                RetryPolicy(max_attempts=3, base_delay=0.01),
                describe="the-op",
                sleep=sleeps.append,
            )
        except RetryError as e:
            assert isinstance(e.__cause__, OSError)
            assert len(sleeps) == 2  # no sleep after the final attempt
            raise


def test_retry_non_retryable_propagates_immediately():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry(bad, RetryPolicy(max_attempts=5, retry_on=(OSError,)), sleep=lambda _: None)
    assert calls["n"] == 1


def test_retry_policy_delay_caps_and_jitter_bounds():
    p = RetryPolicy(max_attempts=10, base_delay=1.0, max_delay=4.0, jitter=0.5)
    import random

    rng = random.Random(0)
    for attempt, base in ((1, 1.0), (2, 2.0), (3, 4.0), (6, 4.0)):
        for _ in range(20):
            d = p.delay(attempt, rng)
            assert 0.5 * base <= d <= 1.5 * base


def test_retry_policy_validation():
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)


# ---------------------------------------------------------------- faults


def test_fault_spec_parsing():
    specs = faults.parse_fault_specs("crash@epoch=2, hang@barrier,corrupt@ckpt_1")
    assert [(s.kind, s.site, s.arg) for s in specs] == [
        ("crash", "epoch", "2"),
        ("hang", "barrier", None),
        ("corrupt", "ckpt", "ckpt_1"),
    ]


@pytest.mark.parametrize("bad", ["explode@epoch=1", "crash@nowhere", "crash"])
def test_fault_spec_parsing_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_fault_specs(bad)


def test_fault_no_env_is_noop(monkeypatch):
    monkeypatch.delenv("TPUDDP_FAULT", raising=False)
    faults.reload_faults()
    faults.maybe_fire("epoch", epoch=0)  # nothing to fire
    assert faults.active_faults() == []


def test_fault_corrupt_fires_once_per_spec(tmp_path, monkeypatch):
    victim = tmp_path / "ckpt_1.npz"
    victim.write_bytes(b"PK" + b"x" * 100)
    monkeypatch.setenv("TPUDDP_FAULT", "corrupt@ckpt_1")
    faults.reload_faults()
    try:
        faults.maybe_fire("ckpt", name="ckpt_0", path=None)  # no match
        faults.maybe_fire("ckpt", name="ckpt_1", path=str(victim))
        garbled = victim.read_bytes()
        assert not garbled.startswith(b"PK")
        # fired-once: a second matching hook leaves the file alone
        victim.write_bytes(b"PK" + b"y" * 100)
        faults.maybe_fire("ckpt", name="ckpt_1", path=str(victim))
        assert victim.read_bytes().startswith(b"PK")
    finally:
        monkeypatch.delenv("TPUDDP_FAULT", raising=False)
        faults.reload_faults()


# ---------------------------------------------------------------- integrity


def test_manifest_round_trip_and_tamper_detection(tmp_path):
    f = tmp_path / "a.npz"
    f.write_bytes(b"PK\x03\x04 payload bytes")
    integrity.write_manifest(str(f))
    assert os.path.exists(str(f) + ".sha256")
    assert integrity.verify_file(str(f))
    f.write_bytes(b"PK\x03\x04 payload byteZ")  # same size, different content
    assert not integrity.verify_file(str(f))


def test_truncation_detected_by_size(tmp_path):
    f = tmp_path / "a.npz"
    f.write_bytes(b"PK\x03\x04" + b"d" * 100)
    integrity.write_manifest(str(f))
    f.write_bytes(f.read_bytes()[:50])
    assert not integrity.verify_file(str(f))


def test_verify_without_manifest_uses_structural_check(tmp_path):
    good = tmp_path / "legacy.npz"
    good.write_bytes(b"PK\x03\x04data")  # pre-resilience checkpoint: no sidecar
    assert integrity.verify_file(str(good))
    assert not integrity.verify_file(str(good), require_manifest=True)
    bad = tmp_path / "torn.npz"
    bad.write_bytes(b"\x00garbage")
    assert not integrity.verify_file(str(bad))
    empty = tmp_path / "empty.npz"
    empty.write_bytes(b"")
    assert not integrity.verify_file(str(empty))
    assert not integrity.verify_file(str(tmp_path / "absent.npz"))


# ------------------------------------------------- checkpoint crash-consistency


def make_state():
    model = ToyMLP(hidden=(8,))
    from tpuddp.training.train_state import create_train_state

    return create_train_state(
        model, optim.Adam(1e-3), jax.random.key(0), jnp.zeros((1, 4, 4, 3))
    )


def assert_tree_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)), a, b
    )


def test_meta_round_trip(tmp_path):
    state = make_state()
    path = ckpt.save(str(tmp_path / "s.npz"), state, meta={"epoch": 7, "completed": 0})
    assert ckpt.read_meta(path) == {"epoch": 7, "completed": 0}
    # meta keys are invisible to the template-driven load
    restored = ckpt.load(path, state)
    assert_tree_equal(restored.params, state.params)


def test_kill_between_tmp_write_and_replace_recovers(tmp_path, caplog):
    """A writer killed between the ``.tmp`` write and ``os.replace``
    (checkpoint.py save) leaves a stale .tmp and NO new checkpoint; the .tmp
    must not shadow the previous good epoch."""
    state = make_state()
    ckpt.save_on_main(str(tmp_path), 0, state)
    # simulate the torn epoch-1 save: the .tmp exists, the publish never ran
    (tmp_path / "ckpt_1.npz.tmp").write_bytes(b"PK\x03\x04 half-written")
    found = ckpt.latest(str(tmp_path))
    assert found is not None and found[1] == 0
    restored, next_epoch = ckpt.restore_latest(str(tmp_path), state)
    assert next_epoch == 1
    assert_tree_equal(restored.params, state.params)


def test_corrupt_newest_falls_back_to_previous_good(tmp_path, caplog):
    state = make_state()
    ckpt.save_on_main(str(tmp_path), 0, state)
    path1 = ckpt.save_on_main(str(tmp_path), 1, state)
    # torn write past the atomic publish (node died mid-flush on NFS): header
    # garbage + truncated tail, manifest now stale
    with open(path1, "r+b") as f:
        f.write(b"\x00CHAOS\x00")
        f.truncate(64)
    with caplog.at_level(logging.WARNING, logger="tpuddp"):
        found = ckpt.latest(str(tmp_path))
        assert found is not None and found[1] == 0
        restored, next_epoch = ckpt.restore_latest(str(tmp_path), state)
    assert next_epoch == 1
    assert_tree_equal(restored.params, state.params)
    assert any("failed integrity" in r.message for r in caplog.records)


def test_all_checkpoints_corrupt_yields_fresh_start(tmp_path):
    state = make_state()
    path = ckpt.save_on_main(str(tmp_path), 0, state)
    with open(path, "wb") as f:
        f.write(b"\x00")
    restored, next_epoch = ckpt.restore_latest(str(tmp_path), state)
    assert next_epoch == 0
    assert restored is state


def test_emergency_checkpoint_redoes_interrupted_epoch(tmp_path, caplog):
    state = make_state()
    ckpt.save_on_main(str(tmp_path), 3, state, completed=False)
    assert ckpt.read_meta(str(tmp_path / "ckpt_3.npz"))["completed"] == 0
    with caplog.at_level(logging.WARNING, logger="tpuddp"):
        restored, next_epoch = ckpt.restore_latest(str(tmp_path), state)
    assert next_epoch == 3  # redo epoch 3, not 4
    assert any("EMERGENCY" in r.message for r in caplog.records)


def test_keep_last_retention(tmp_path):
    state = make_state()
    for e in range(5):
        ckpt.save_on_main(str(tmp_path), e, state, keep_last=2)
    kept = sorted(f for f in os.listdir(tmp_path) if f.endswith(".npz"))
    assert kept == ["ckpt_3.npz", "ckpt_4.npz"]
    # manifests pruned alongside their data files
    sidecars = sorted(f for f in os.listdir(tmp_path) if f.endswith(".sha256"))
    assert sidecars == ["ckpt_3.npz.sha256", "ckpt_4.npz.sha256"]
    with pytest.raises(ValueError):
        ckpt.prune_checkpoints(str(tmp_path), keep_last=0)


# ---------------------------------------------------------------- preemption


@pytest.fixture
def preempt_guard(monkeypatch):
    """Keep the grace-window failsafe thread inert and the flag clean."""
    monkeypatch.setenv("TPUDDP_PREEMPT_GRACE", "3600")
    preemption.reset_preemption()
    yield
    preemption.reset_preemption()


def test_grace_env_parsing(monkeypatch):
    monkeypatch.delenv("TPUDDP_PREEMPT_GRACE", raising=False)
    assert preemption.preemption_grace_seconds() == 25.0
    monkeypatch.setenv("TPUDDP_PREEMPT_GRACE", "7.5")
    assert preemption.preemption_grace_seconds() == 7.5
    monkeypatch.setenv("TPUDDP_PREEMPT_GRACE", "not-a-number")
    assert preemption.preemption_grace_seconds() == 25.0


def test_request_sets_flag_and_deadline(preempt_guard):
    assert not preemption.preemption_requested()
    assert preemption.preemption_deadline() is None
    preemption.request_preemption()
    assert preemption.preemption_requested()
    assert preemption.preemption_deadline() is not None
    preemption.reset_preemption()
    assert not preemption.preemption_requested()


class _PreemptingLoader:
    """Delegating loader that requests preemption after ``after`` batches —
    the in-process stand-in for a SIGTERM landing mid-epoch."""

    def __init__(self, inner, after):
        self.inner = inner
        self.after = after

    def __len__(self):
        return len(self.inner)

    def set_epoch(self, epoch):
        self.inner.set_epoch(epoch)

    def __iter__(self):
        for i, batch in enumerate(self.inner):
            if i == self.after:
                preemption.request_preemption()
            yield batch


def _toy_ddp(mesh):
    # batch_size is per replica: 8 x 8 devices = 64-sample global batches,
    # so n=512 gives 8 batch groups per epoch — room for a mid-epoch preempt
    ds = SyntheticClassification(n=512, shape=(8, 8, 3), seed=0)
    loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    test_loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh
    )
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    return ddp, state, loader, test_loader


def test_preemption_round_trip_exact_state(mesh, tmp_path, preempt_guard):
    """SIGTERM mid-epoch -> emergency checkpoint -> auto_resume continues from
    the recorded epoch with the EXACT saved state (params, optimizer moments,
    RNG stream position) — the fast-tier half of the chaos round-trip."""
    ddp, state, loader, test_loader = _toy_ddp(mesh)
    with pytest.raises(TrainingPreempted) as ei:
        run_training_loop(
            ddp, state, _PreemptingLoader(loader, after=2), test_loader,
            str(tmp_path), num_epochs=3, checkpoint_epoch=1, log=lambda *_: None,
        )
    assert ei.value.epoch == 0
    emergency = tmp_path / "ckpt_0.npz"
    assert emergency.exists()
    assert integrity.verify_file(str(emergency))
    assert ckpt.read_meta(str(emergency)) == {"epoch": 0, "completed": 0}

    # the drain saved the state as of the last completed batch group; resume
    # restores it bit-for-bit and redoes the interrupted epoch
    saved = ckpt.load(str(emergency), state)
    restored, resume_epoch = ckpt.restore_latest(str(tmp_path), state)
    assert resume_epoch == 0
    assert_tree_equal(restored.params, saved.params)
    assert_tree_equal(restored.opt_state, saved.opt_state)
    assert jnp.array_equal(
        jax.random.key_data(restored.rng), jax.random.key_data(saved.rng)
    )

    preemption.reset_preemption()
    ddp2, state2, loader2, test_loader2 = _toy_ddp(mesh)
    _, history = run_training_loop(
        ddp2, state2, loader2, test_loader2, str(tmp_path),
        num_epochs=3, checkpoint_epoch=1, auto_resume=True, log=lambda *_: None,
    )
    # the interrupted epoch 0 was redone, then training ran to completion
    assert [h["epoch"] for h in history] == [0, 1, 2]
    # completed end-of-epoch saves overwrite the emergency marker
    assert ckpt.read_meta(str(tmp_path / "ckpt_2.npz"))["completed"] == 1


def test_auto_resume_env_flag(mesh, tmp_path, monkeypatch):
    ddp, state, loader, test_loader = _toy_ddp(mesh)
    run_training_loop(
        ddp, state, loader, test_loader, str(tmp_path),
        num_epochs=1, checkpoint_epoch=1, log=lambda *_: None,
    )
    monkeypatch.setenv("TPUDDP_AUTO_RESUME", "1")
    logs = []
    _, history = run_training_loop(
        ddp, state, loader, test_loader, str(tmp_path),
        num_epochs=2, checkpoint_epoch=1, log=logs.append,
    )
    assert [h["epoch"] for h in history] == [1]
    assert any("Auto-resume: continuing from epoch 1" in l for l in logs)


# ---------------------------------------------------------------- watchdog


def test_heartbeat_file_round_trip(tmp_path):
    watchdog.write_heartbeat(str(tmp_path), 3, now=123.5)
    assert watchdog.read_heartbeat(str(tmp_path), 3) == 123.5
    assert watchdog.read_heartbeat(str(tmp_path), 4) is None


def test_heartbeat_thread_beats(tmp_path):
    hb = watchdog.Heartbeat(str(tmp_path), 0, interval=0.05).start()
    try:
        first = watchdog.read_heartbeat(str(tmp_path), 0)
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if watchdog.read_heartbeat(str(tmp_path), 0) > first:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("heartbeat never advanced")
    finally:
        hb.stop()


def test_watchdog_check_once_semantics(tmp_path):
    wd = watchdog.Watchdog(str(tmp_path), 0, num_processes=3, timeout=10.0)
    wd._started_at = 1000.0
    # no files yet, within startup grace: nothing stale
    assert wd.check_once(now=1005.0) == []
    # past the grace with still no file: both peers stale
    assert [p for p, _ in wd.check_once(now=1011.0)] == [1, 2]
    watchdog.write_heartbeat(str(tmp_path), 1, now=1011.0)
    watchdog.write_heartbeat(str(tmp_path), 2, now=1011.0)
    assert wd.check_once(now=1015.0) == []
    # peer 2 goes quiet past the timeout
    watchdog.write_heartbeat(str(tmp_path), 1, now=1025.0)
    stale = wd.check_once(now=1025.0)
    assert [p for p, _ in stale] == [2]
    assert stale[0][1] == pytest.approx(14.0)


def test_watchdog_fires_callable_action_within_timeout(tmp_path):
    fired = threading.Event()
    stale_seen = []

    def action(stale):
        stale_seen.extend(stale)
        fired.set()

    watchdog.write_heartbeat(str(tmp_path), 1)  # one beat, then silence
    wd = watchdog.Watchdog(
        str(tmp_path), 0, num_processes=2, timeout=0.3, action=action, interval=0.05
    ).start()
    try:
        assert fired.wait(timeout=5.0), "watchdog never fired on a stale peer"
        assert stale_seen and stale_seen[0][0] == 1
    finally:
        wd.stop()


def test_watchdog_timeout_env_parsing(monkeypatch):
    monkeypatch.delenv("TPUDDP_WATCHDOG_TIMEOUT", raising=False)
    assert watchdog.watchdog_timeout_seconds() is None
    monkeypatch.setenv("TPUDDP_WATCHDOG_TIMEOUT", "12")
    assert watchdog.watchdog_timeout_seconds() == 12.0
    monkeypatch.setenv("TPUDDP_WATCHDOG_TIMEOUT", "0")
    assert watchdog.watchdog_timeout_seconds() is None
    monkeypatch.setenv("TPUDDP_WATCHDOG_TIMEOUT", "nope")
    assert watchdog.watchdog_timeout_seconds() is None


def test_watchdog_start_disabled_paths(tmp_path, monkeypatch):
    monkeypatch.delenv("TPUDDP_WATCHDOG_TIMEOUT", raising=False)
    assert watchdog.start(str(tmp_path), 0, 2) is None  # no timeout configured
    monkeypatch.setenv("TPUDDP_WATCHDOG_TIMEOUT", "5")
    assert watchdog.start(str(tmp_path), 0, 1) is None  # no peers
    monkeypatch.delenv("TPUDDP_HEARTBEAT_DIR", raising=False)
    assert watchdog.start(None, 0, 2) is None  # nowhere to beat
    pair = watchdog.start(str(tmp_path), 0, 2)  # armed
    try:
        assert pair is not None
        assert os.path.exists(tmp_path / ".heartbeats" / "hb_0")
    finally:
        watchdog.stop(pair)
    watchdog.stop(None)  # None-safe


def test_watchdog_shrunk_resume_ignores_leftover_heartbeats(tmp_path, monkeypatch):
    """Regression (ISSUE 7 satellite): an elastically-shrunk resume reuses
    the heartbeat_dir of a previous LARGER world. The leftover hb_{i} files —
    both the ids past the new world size and the in-range ids with ancient
    beats — must not make the watchdog kill the healthy smaller run with
    exit 76: start() purges the out-of-range files, and check_once gives
    pre-start beats the startup grace instead of declaring them stale."""
    hb_dir = tmp_path / ".heartbeats"
    os.makedirs(hb_dir)
    ancient = time.time() - 3600.0
    for peer in range(8):  # the previous 8-process world's droppings
        watchdog.write_heartbeat(str(hb_dir), peer, now=ancient)

    monkeypatch.setenv("TPUDDP_WATCHDOG_TIMEOUT", "5")
    monkeypatch.delenv("TPUDDP_HEARTBEAT_DIR", raising=False)
    pair = watchdog.start(str(tmp_path), 0, 2)  # resumed world: 2 processes
    try:
        assert pair is not None
        _hb, wd = pair
        # ids >= num_processes purged outright
        leftover = sorted(os.listdir(hb_dir))
        assert "hb_2" not in leftover and "hb_7" not in leftover
        # peer 1's ancient file is pre-start: startup grace, NOT stale —
        # before the fix this check returned [(1, ~3600s)] and fired exit 76
        assert wd.check_once() == []
        # the grace is not unconditional: past the timeout with still no
        # fresh beat, the peer IS stale
        stale = wd.check_once(now=time.time() + 10.0)
        assert [p for p, _ in stale] == [1]
        # and a fresh in-run beat clears it
        watchdog.write_heartbeat(str(hb_dir), 1)
        assert wd.check_once() == []
    finally:
        watchdog.stop(pair)


def test_purge_stale_peers_counts_and_is_best_effort(tmp_path):
    for peer in (0, 1, 4, 9):
        watchdog.write_heartbeat(str(tmp_path), peer)
    assert watchdog.purge_stale_peers(str(tmp_path), 2) == 2  # hb_4, hb_9
    assert sorted(os.listdir(tmp_path)) == ["hb_0", "hb_1"]
    assert watchdog.purge_stale_peers(str(tmp_path), 2) == 0  # idempotent
    assert watchdog.purge_stale_peers(str(tmp_path / "missing"), 2) == 0


# ------------------------------------------------------------ cifar download


def test_cifar_download_retries_and_cleans_partial(tmp_path, monkeypatch):
    """A flaky download is retried 3x; every failed attempt removes its
    partial file so nothing poisons the next run, and the terminal error names
    the operation."""
    from tpuddp.data import cifar10 as c10

    calls = {"n": 0}

    class FlakyResponse:
        """Yields one chunk, then dies mid-stream — a truncating connection."""

        def __init__(self):
            self.sent = False

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

        def read(self, n=-1):
            if not self.sent:
                self.sent = True
                return b"half an archive"
            raise OSError("connection reset")

    def fake_urlopen(url, timeout=None):
        calls["n"] += 1
        return FlakyResponse()

    monkeypatch.setattr("urllib.request.urlopen", fake_urlopen)
    with pytest.raises(RetryError, match="CIFAR-10 download"):
        c10._maybe_download(str(tmp_path))
    assert calls["n"] == 3
    assert os.listdir(tmp_path) == []  # no .part / truncated archive left


def test_cifar_corrupt_archive_deleted_then_redownloaded(tmp_path, monkeypatch):
    """An archive truncated by an earlier kill fails extraction, is deleted,
    and the retry re-downloads a good copy instead of failing forever."""
    import io
    import tarfile

    from tpuddp.data import cifar10 as c10

    (tmp_path / "cifar-10-python.tar.gz").write_bytes(b"\x1f\x8b not a gzip")

    def fake_urlopen(url, timeout=None):
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tar:
            data = b"hello"
            info = tarfile.TarInfo("cifar-10-batches-py/readme")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
        return io.BytesIO(buf.getvalue())

    monkeypatch.setattr("urllib.request.urlopen", fake_urlopen)
    c10._maybe_download(str(tmp_path))
    assert (tmp_path / "cifar-10-batches-py" / "readme").read_bytes() == b"hello"


# ---------------------------------------------------------------- observability


def test_metrics_writer_flush_and_close(tmp_path):
    w = MetricsWriter(str(tmp_path))
    w.write({"epoch": 0})
    # flushed after every record: readable mid-run, always whole JSON lines
    assert open(w.path).read() == '{"epoch": 0}\n'
    w.write({"epoch": 1})
    w.close()
    w.close()  # idempotent
    assert open(w.path).read().splitlines() == ['{"epoch": 0}', '{"epoch": 1}']
