"""Multi-process DP — the multi-host contract (SURVEY.md §2c: the one place
the build exceeds the reference's single-node scope). Two OS processes with 4
virtual CPU devices each rendezvous via jax.distributed into one 8-device
world and train together."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# Probe script for the multi-process backend env: two 1-device processes
# rendezvous and run the cheapest cross-process collective the framework
# uses (broadcast_one_to_all). Some jaxlib builds rendezvous fine but then
# refuse the computation itself ("Multiprocess computations aren't
# implemented on the CPU backend") — probing initialize alone would miss
# exactly the failure mode these tests die of.
_PROBE = """
import sys
import numpy as np
import jax
from jax.experimental import multihost_utils
jax.distributed.initialize(
    coordinator_address="127.0.0.1:%s", num_processes=2,
    process_id=int(sys.argv[1]),
)
out = multihost_utils.broadcast_one_to_all(np.ones((1,), np.float32))
assert float(out[0]) == 1.0
print("MULTIHOST_PROBE_OK")
"""

_probe_cache = {}


def multiprocess_backend_reason():
    """None when this host can run 2-process CPU-backend collectives; else a
    typed one-line reason (the skip message) naming what is absent."""
    if "reason" in _probe_cache:
        return _probe_cache["reason"]
    port = free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)  # 1 device per probe process
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE % port, str(i)],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    reason = None
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                reason = ("multi-process backend env absent: 2-process "
                          "rendezvous hung")
                break
            if p.returncode != 0 or "MULTIHOST_PROBE_OK" not in out:
                tail = [l for l in out.strip().splitlines() if l][-1:] or ["no output"]
                reason = (
                    "multi-process backend env absent: cross-process CPU "
                    f"collective failed ({tail[0][:160]})"
                )
                break
    finally:
        # a failed probe leaves its SIBLING blocked in rendezvous on the
        # dead coordinator: kill + reap every process on every exit path
        # (no lingering port holder, no zombie)
        for q in procs:
            if q.poll() is None:
                q.kill()
            try:
                q.communicate(timeout=30)
            except Exception:  # noqa: BLE001 — best-effort reap
                pass
    _probe_cache["reason"] = reason
    return reason


@pytest.fixture(scope="module")
def multiprocess_backend():
    """Skip (typed reason), never error, when the multi-process backend env
    is absent — e.g. a jaxlib whose CPU backend rejects multiprocess
    computations, or a sandbox without loopback rendezvous."""
    reason = multiprocess_backend_reason()
    if reason is not None:
        pytest.skip(reason)


@pytest.mark.slow
def test_two_process_dp_world(tmp_path, multiprocess_backend):
    port = free_port()
    env = dict(os.environ)
    # clean CPU-only children: no TPU plugin, 4 host devices each
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["TPUDDP_BACKEND"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "_multihost_worker.py"),
             str(i), "2", str(port), str(tmp_path)],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, f"worker failed:\n{out[-2000:]}\n{err[-3000:]}"
        outs.append(out)

    results = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("WORKER_RESULT ")][0]
        results.append(json.loads(line[len("WORKER_RESULT "):]))
    results.sort(key=lambda r: r["proc"])

    # each process owns a disjoint half of the 8 global replicas, mesh order
    assert results[0]["local_ranks"] == [0, 1, 2, 3]
    assert results[1]["local_ranks"] == [4, 5, 6, 7]

    # both processes computed IDENTICAL global metrics (the psum contract)
    np.testing.assert_allclose(
        results[0]["train_loss"], results[1]["train_loss"], rtol=1e-6
    )
    assert results[0]["n"] == results[1]["n"] == [128.0, 128.0]

    # the managed (Accelerator) path agrees across processes too
    assert len(results[0]["managed_losses"]) == 3
    np.testing.assert_allclose(
        results[0]["managed_losses"], results[1]["managed_losses"], rtol=1e-6
    )
    assert results[0]["is_main"] and not results[1]["is_main"]

    # a custom sampler drawn independently (unseeded) per process still
    # yields globally disjoint shards covering the dataset exactly once —
    # proof that process 0's materialized order was broadcast
    for key in ("sampler_shards", "sampler_shards_ep1"):
        all_idx = [i for r in results for shard in r[key] for i in shard]
        assert sorted(all_idx) == list(range(128))
    # set_epoch invalidated the memo: epoch 1 re-drew (and re-broadcast) a
    # fresh order rather than replaying epoch 0's cached one
    assert results[0]["sampler_shards_ep1"] != results[0]["sampler_shards"]

    # process 0 only wrote the checkpoints; the loop's epoch log printed once
    assert os.path.exists(tmp_path / "ckpt_0.npz")
    assert os.path.exists(tmp_path / "ckpt_1.npz")
    epoch_lines_0 = [l for l in outs[0].splitlines() if l.startswith("Epoch ")]
    epoch_lines_1 = [l for l in outs[1].splitlines() if l.startswith("Epoch ")]
    assert len(epoch_lines_0) == 2  # process 0 logs
    assert len(epoch_lines_1) == 0  # process 1 gated


@pytest.mark.slow
def test_two_host_world_from_cli(tmp_path, multiprocess_backend):
    """VERDICT r2 #3: the multi-host world must be reachable from the actual
    CLI surface — one shared settings file with a ``local.rendezvous`` block,
    per-host process id via $TPUDDP_PROCESS_ID, no library code written by the
    user. Reference analog: MASTER_ADDR/PORT env + mp.spawn
    (multi-GPU-training-torch.py:29-47)."""
    port = free_port()
    settings = {
        "script_path": "train_native.py",
        "out_dir": str(tmp_path / "out"),
        "optional_args": {"set_epoch": True, "print_rand": False},
        "local": {
            "device": "cpu",
            "tpu": {"num_chips": 8},  # GLOBAL world: 2 hosts x 4 devices
            "rendezvous": {
                "coordinator_address": f"127.0.0.1:{port}",
                "num_processes": 2,
                # process_id comes from $TPUDDP_PROCESS_ID, per host
            },
        },
        "training": {
            "model": "toy_mlp",
            "data_root": "/nonexistent",  # synthetic fallback
            "train_batch_size": 8,
            "test_batch_size": 8,
            "num_epochs": 1,
            "checkpoint_epoch": 1,
            "image_size": None,
            "seed": 0,
            "synthetic_n": [64, 32],
        },
    }
    sf = tmp_path / "shared.yaml"
    sf.write_text(yaml.dump(settings))

    def child_env(proc_id):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # the multihost re-exec launcher sets it
        env.pop("PALLAS_AXON_POOL_IPS", None)  # keep children TPU-free
        env["JAX_PLATFORMS"] = "cpu"
        env["TPUDDP_BACKEND"] = "cpu"
        env["TPUDDP_PROCESS_ID"] = str(proc_id)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        return env

    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "train_native.py"),
             "--settings_file", str(sf)],
            env=child_env(i), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, f"worker failed:\n{out[-2000:]}\n{err[-3000:]}"
        outs.append(out)

    # both processes entered the training loop with the 8-wide global world
    assert "Running DDP training on process 0 (8-chip world)." in outs[0]
    assert "Running DDP training on process 1 (8-chip world)." in outs[1]
    # process-0-only epoch log + checkpoint (the dist.barrier/rank-0 contract)
    assert any(l.startswith("Epoch 1/1") for l in outs[0].splitlines())
    assert not any(l.startswith("Epoch 1/1") for l in outs[1].splitlines())
    assert os.path.exists(tmp_path / "out" / "ckpt_0.npz")
