"""Multi-process DP — the multi-host contract (SURVEY.md §2c: the one place
the build exceeds the reference's single-node scope). Two OS processes with 4
virtual CPU devices each rendezvous via jax.distributed into one 8-device
world and train together."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_dp_world(tmp_path):
    port = free_port()
    env = dict(os.environ)
    # clean CPU-only children: no TPU plugin, 4 host devices each
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["TPUDDP_BACKEND"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "_multihost_worker.py"),
             str(i), "2", str(port), str(tmp_path)],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, f"worker failed:\n{out[-2000:]}\n{err[-3000:]}"
        outs.append(out)

    results = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("WORKER_RESULT ")][0]
        results.append(json.loads(line[len("WORKER_RESULT "):]))
    results.sort(key=lambda r: r["proc"])

    # each process owns a disjoint half of the 8 global replicas, mesh order
    assert results[0]["local_ranks"] == [0, 1, 2, 3]
    assert results[1]["local_ranks"] == [4, 5, 6, 7]

    # both processes computed IDENTICAL global metrics (the psum contract)
    np.testing.assert_allclose(
        results[0]["train_loss"], results[1]["train_loss"], rtol=1e-6
    )
    assert results[0]["n"] == results[1]["n"] == [128.0, 128.0]

    # the managed (Accelerator) path agrees across processes too
    assert len(results[0]["managed_losses"]) == 3
    np.testing.assert_allclose(
        results[0]["managed_losses"], results[1]["managed_losses"], rtol=1e-6
    )
    assert results[0]["is_main"] and not results[1]["is_main"]

    # process 0 only wrote the checkpoints; the loop's epoch log printed once
    assert os.path.exists(tmp_path / "ckpt_0.npz")
    assert os.path.exists(tmp_path / "ckpt_1.npz")
    epoch_lines_0 = [l for l in outs[0].splitlines() if l.startswith("Epoch ")]
    epoch_lines_1 = [l for l in outs[1].splitlines() if l.startswith("Epoch ")]
    assert len(epoch_lines_0) == 2  # process 0 logs
    assert len(epoch_lines_1) == 0  # process 1 gated
