"""Serving survivability suite (ISSUE 13, tpuddp/serving/survive.py).

The headline contract: a decode replica that dies mid-stream loses ZERO
streams — every live sequence parks into a host-side session journal,
fails over (to a healthy peer, or to the same replica once it passes
probation), and continues **bitwise-equal** to an undisturbed same-seed
run. Around it: the replica probation state machine
(rejoin / relapse / ``max_recoveries`` -> permanent removal), deadline
load shedding (queued-expired work is never dispatched; in-flight work is
never deadline-killed), per-tenant retry budgets for transient dispatch
failures, the typed ``no_healthy_replica`` terminal outcome (never a
hang), the ``$TPUDDP_FAULT`` serving kinds, and schema-v7 drift
rejection.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from tpuddp import config as config_lib
from tpuddp.observability import schema
from tpuddp.resilience import faults
from tpuddp.serving import (
    AdmissionError,
    NoHealthyReplicaError,
    RetryBudget,
    ServingEngine,
    SurvivePolicy,
)
from tpuddp.serving import queue as queue_mod
from tpuddp.serving import survive as survive_lib
from tpuddp.serving.decode import DecodeEngine
from tpuddp.serving.queue import Request, RequestQueue

VOCAB = 32
SHAPE = (4, 4, 1)


def _decode_cfg(**overrides):
    cfg = config_lib.decode_config({"decode": {}})
    cfg.update(
        model="transformer_tiny",
        vocab_size=VOCAB,
        num_replicas=1,
        max_slots=4,
        kv_blocks=17,  # 16 allocatable = exactly 4 worst-case sequences
        kv_block_size=8,
        max_seq_len=32,
        max_new_tokens=8,
        stats_window=16,
        max_queue_depth=64,
        recovery_backoff_s=0.01,
    )
    cfg.update(overrides)
    return cfg


def _serving_cfg(**overrides):
    cfg = {
        "model": "toy_mlp",
        "num_classes": 10,
        "input_shape": list(SHAPE),
        "num_replicas": 1,
        "max_batch_size": 8,
        "max_queue_depth": 64,
        "batch_timeout_ms": 0.0,
        "stats_window": 16,
        "recovery_backoff_s": 0.01,
    }
    cfg.update(overrides)
    return config_lib._merge_refusing_unknown(
        config_lib.SERVING_DEFAULTS, cfg, "serving"
    )


def _prompt(rng, n=None):
    n = n if n is not None else int(rng.randint(1, 13))
    return rng.randint(0, VOCAB, size=n).astype(np.int32)


def _events(out_dir):
    path = os.path.join(out_dir, "history.jsonl")
    if not os.path.exists(path):
        return []
    return [
        json.loads(line)
        for line in open(path)
        if line.strip() and json.loads(line).get("type") == "event"
    ]


# ------------------------------------------------------------------ policy --


def test_survive_policy_validation_and_from_config():
    with pytest.raises(ValueError):
        SurvivePolicy(request_ttl_s=0)
    with pytest.raises(ValueError):
        SurvivePolicy(max_recoveries=-1)
    with pytest.raises(ValueError):
        SurvivePolicy(recovery_attempts=0)
    with pytest.raises(ValueError):
        SurvivePolicy(recovery_backoff_s=-0.1)
    with pytest.raises(ValueError):
        SurvivePolicy(retry_budget=-1)
    with pytest.raises(ValueError):
        SurvivePolicy(max_failovers=-1)
    # stale config dicts (pre-survivability) resolve to the defaults
    pol = SurvivePolicy.from_config({})
    assert pol.request_ttl_s is None and pol.max_recoveries == 2
    assert pol.max_failovers == 1
    pol = SurvivePolicy.from_config(
        {"request_ttl_s": 1.5, "max_recoveries": 0, "retry_budget": 3}
    )
    assert pol.request_ttl_s == 1.5 and pol.retry_budget == 3
    meta = pol.meta()
    assert meta["max_recoveries"] == 0 and meta["retry_budget"] == 3


def test_admission_deadline_combinations():
    assert survive_lib.admission_deadline(10.0, None, None) is None
    assert survive_lib.admission_deadline(10.0, 5.0, None) == 15.0
    assert survive_lib.admission_deadline(10.0, None, 2.0) == 12.0
    # the TIGHTER of engine TTL and client deadline wins
    assert survive_lib.admission_deadline(10.0, 5.0, 2.0) == 12.0
    assert survive_lib.admission_deadline(10.0, 1.0, 2.0) == 11.0
    with pytest.raises(ValueError):
        survive_lib.admission_deadline(10.0, None, -1.0)


def test_retry_budget_consume_refund_exhaustion():
    b = RetryBudget(2)
    assert b.try_consume("a") and b.try_consume("a")
    assert not b.try_consume("a")  # exhausted
    assert b.try_consume("b")  # per-tenant, not global
    b.refund("a")
    assert b.try_consume("a")
    b.refund("a", n=10)  # over-refund clamps at zero used
    assert b.used("a") == 0
    # disabled budget never allows a retry
    assert not RetryBudget(0).try_consume("a")


def test_run_probation_attempts_and_backoff():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise RuntimeError("not yet")

    pol = SurvivePolicy(recovery_attempts=3, recovery_backoff_s=0.0)
    assert survive_lib.run_probation(
        name="r0", recover=flaky, policy=pol, sleep=lambda s: None
    )
    assert len(calls) == 2
    calls.clear()
    assert not survive_lib.run_probation(
        name="r0",
        recover=lambda: (_ for _ in ()).throw(RuntimeError("dead")),
        policy=pol,
        sleep=lambda s: None,
    )


# ----------------------------------------------------------- queue shedding --


def test_queue_sheds_expired_heads_not_journals():
    q = RequestQueue(max_depth=16)
    shed_seen = []
    q.shed_handler = shed_seen.append
    now = time.perf_counter()
    expired = Request("a", np.zeros((1,) + SHAPE, np.float32), deadline=now - 1)
    live = Request("a", np.zeros((1,) + SHAPE, np.float32), deadline=now + 60)
    q.put(expired)
    q.put(live)
    group = q.take_group(8, wait=False)
    assert [r.id for r in group] == [live.id]
    assert shed_seen == [expired]
    with pytest.raises(AdmissionError) as e:
        expired.result.result(timeout=1)
    assert e.value.reason == "deadline_exceeded"
    assert live.result.done() is False
    # a failover journal (resume_tokens set) is in-flight work: NEVER shed
    q2 = RequestQueue(max_depth=16)
    journal = Request("a", np.zeros((1,) + SHAPE, np.float32), deadline=now - 1)
    journal.resume_tokens = [3, 4]  # duck-typed the decode way
    q2.put(journal)
    group = q2.take_group(8, wait=False)
    assert [r.id for r in group] == [journal.id]


def test_queue_all_expired_returns_empty_not_oversized_error():
    q = RequestQueue(max_depth=16)
    now = time.perf_counter()
    for _ in range(3):
        q.put(Request("a", np.zeros((1,) + SHAPE, np.float32), deadline=now - 1))
    assert q.take_group(8, wait=False) == []
    assert q.depth() == 0


def test_queue_requeue_bypasses_closed_and_jumps_lane_front():
    q = RequestQueue(max_depth=16)
    a = Request("t", np.zeros((1,) + SHAPE, np.float32))
    b = Request("t", np.zeros((1,) + SHAPE, np.float32))
    q.put(a)
    q.put(b)
    q.close()
    with pytest.raises(AdmissionError):
        q.put(Request("t", np.zeros((1,) + SHAPE, np.float32)))
    c = Request("t", np.zeros((1,) + SHAPE, np.float32))
    q.requeue(c)  # already-admitted work re-enters even while draining
    group = q.take_group(8, wait=False)
    assert [r.id for r in group] == [c.id, a.id, b.id]
    assert q.take_group(8) is None  # closed + drained


# ------------------------------------------------------------- fault kinds --


def test_fault_parse_serving_kinds_and_pairings():
    specs = faults.parse_fault_specs(
        "replica_kill@step=4,pool_poison@step=7,dispatch_wedge@batch=2,"
        "replica_kill@batch=9"
    )
    assert [(s.kind, s.site, s.arg) for s in specs] == [
        ("replica_kill", "step", "4"),
        ("pool_poison", "step", "7"),
        ("dispatch_wedge", "batch", "2"),
        ("replica_kill", "batch", "9"),
    ]
    for bad in (
        "pool_poison@batch=1",   # pools live on the decode step site
        "replica_kill@epoch=1",  # serving kinds pair with dispatch sites
        "nan@batch=1",           # training kind on the serving site
        "hang@batch=1",
    ):
        with pytest.raises(ValueError):
            faults.parse_fault_specs(bad)


def test_serving_faults_invisible_to_training_hooks(monkeypatch):
    monkeypatch.setenv("TPUDDP_FAULT", "replica_kill@step=1")
    faults.reload_faults()
    try:
        # the trainer's per-batch hook must not arm, and maybe_fire must
        # not consume the spec
        assert not faults.has_step_fault()
        faults.maybe_fire("step", step=1)
        assert not faults.active_faults()[0].fired
        # the serving hook consumes it exactly once
        assert faults.maybe_serving_fault("step", step=1) == "replica_kill"
        assert faults.maybe_serving_fault("step", step=1) is None
    finally:
        monkeypatch.delenv("TPUDDP_FAULT")
        faults.reload_faults()


# ----------------------------------------- decode failover (the headline) --


def _one_shot_step_killer(replica, after=0, consume_pools=False):
    """Patch ``replica._step`` to fail exactly once after ``after``
    successful calls; later calls (and probation's canary) pass through."""
    real_step = replica._step
    state = {"calls": 0, "fired": False}

    def step(params, kpool, vpool, *rest):
        if not state["fired"] and state["calls"] >= after:
            state["fired"] = True
            if consume_pools:
                kpool.delete()
                vpool.delete()
            raise RuntimeError("injected replica death")
        state["calls"] += 1
        return real_step(params, kpool, vpool, *rest)

    replica._step = step
    return state


@pytest.mark.parametrize(
    "prompt_lens,temperature,kill_after",
    [
        # mid-decode kill, bucket-interior prompts
        ((3, 5, 12), 0.0, 2),
        # prefill-bucket boundary prompts (ladder [1,2,4,8,16,31]): an
        # exact bucket fit and the first length of the next bucket
        ((8, 9), 0.0, 1),
        # temperature sampling: the (seed, index) stream survives failover
        ((4, 6), 0.9, 2),
    ],
)
def test_failover_mid_decode_bitwise(tmp_path, cpu_devices, prompt_lens,
                                     temperature, kill_after):
    """THE acceptance matrix: kill the (only) replica mid-sweep — every
    live stream parks, the replica passes probation, the sessions resume
    on it, and every stream is BITWISE the undisturbed same-seed run."""
    out = str(tmp_path / "run")
    eng = DecodeEngine.from_config(
        _decode_cfg(), out_dir=out, devices=cpu_devices
    )
    eng.start()
    try:
        rng = np.random.RandomState(0)
        prompts = [_prompt(rng, n) for n in prompt_lens]
        twins = [
            np.asarray(
                eng.submit("t", p, seed=7 + i, temperature=temperature)
                .result(timeout=120)
            )
            for i, p in enumerate(prompts)
        ]
        state = _one_shot_step_killer(eng.replicas[0], after=kill_after)
        results = [
            eng.submit("t", p, seed=7 + i, temperature=temperature)
            for i, p in enumerate(prompts)
        ]
        streamed = [list(r.stream(timeout=120)) for r in results]
        assert state["fired"], "the injected death never fired"
        for i, r in enumerate(results):
            final = np.asarray(r.result(timeout=1))
            np.testing.assert_array_equal(final, twins[i])
            assert streamed[i] == list(twins[i])
    finally:
        summary = eng.drain()
    # zero lost streams, one failover event per migrated sequence, the
    # replica back in routing after probation
    assert summary["completed"] == 2 * len(prompt_lens)
    assert summary["failovers"] >= 1
    events = _events(out)
    failovers = [e for e in events if e["event"] == "session_failover"]
    assert len(failovers) == summary["failovers"]
    assert all(e["to_replica"] == 0 for e in failovers)
    assert any(e["event"] == "replica_unhealthy" for e in events)
    recovered = [e for e in events if e["event"] == "replica_recovered"]
    assert recovered and recovered[0]["recoveries"] == 1
    errors, _ = schema.validate_history_file(os.path.join(out, "history.jsonl"))
    assert errors == []


def test_failover_during_prefill_bitwise(cpu_devices):
    """The replica dies DURING a prompt's prefill dispatch: the request
    (a zero-token session) re-prefills after recovery and the whole stream
    is bitwise the undisturbed run — token index 0 samples identically."""
    eng = DecodeEngine.from_config(_decode_cfg(), devices=cpu_devices)
    eng.start()
    try:
        rng = np.random.RandomState(1)
        p = _prompt(rng, 5)
        twin = np.asarray(eng.submit("t", p, seed=3).result(timeout=120))
        replica = eng.replicas[0]
        real_prefill = replica._prefill
        state = {"fired": False}

        def prefill(params, kpool, vpool, *rest):
            if not state["fired"]:
                state["fired"] = True
                raise RuntimeError("injected prefill death")
            return real_prefill(params, kpool, vpool, *rest)

        replica._prefill = prefill
        out = np.asarray(eng.submit("t", p, seed=3).result(timeout=120))
        assert state["fired"]
        np.testing.assert_array_equal(out, twin)
        assert eng.stats.failovers == 1
    finally:
        eng.drain()


def test_failover_spreads_to_surviving_replica(tmp_path, cpu_devices):
    """Two replicas, both carrying live sessions; one dies mid-sweep. Every
    stream completes bitwise (the dead replica's sessions migrate wherever
    capacity lives) and the pool ends with both replicas healthy."""
    out = str(tmp_path / "run")
    eng = DecodeEngine.from_config(
        _decode_cfg(num_replicas=2, max_slots=2, kv_blocks=9),
        out_dir=out,
        devices=cpu_devices,
    )
    eng.start()
    try:
        rng = np.random.RandomState(2)
        prompts = [_prompt(rng) for _ in range(8)]
        twins = [
            np.asarray(eng.submit("t", p, seed=20 + i).result(timeout=120))
            for i, p in enumerate(prompts)
        ]
        # kill replica 0 once it has stepped a few times (it holds live
        # sessions by then; > slots requests keep both replicas busy)
        state = _one_shot_step_killer(eng.replicas[0], after=2)
        results = [
            eng.submit("t", p, seed=20 + i) for i, p in enumerate(prompts)
        ]
        for i, r in enumerate(results):
            np.testing.assert_array_equal(
                np.asarray(r.result(timeout=120)), twins[i]
            )
        assert state["fired"]
    finally:
        summary = eng.drain()
    assert summary["completed"] == 16
    assert all(r.healthy for r in eng.replicas)
    events = _events(out)
    assert any(e["event"] == "session_failover" for e in events)
    assert any(e["event"] == "replica_recovered" for e in events)


def test_failover_via_fault_env_replica_kill(tmp_path, cpu_devices, monkeypatch):
    """The $TPUDDP_FAULT contract end to end: replica_kill@step=N lands
    mid-sweep through the decode loop's own injection site, and the
    survivability layer turns it into zero lost streams + probation."""
    out = str(tmp_path / "run")
    eng = DecodeEngine.from_config(
        _decode_cfg(), out_dir=out, devices=cpu_devices
    )
    eng.start()
    try:
        rng = np.random.RandomState(3)
        prompts = [_prompt(rng, n) for n in (4, 7)]
        twins = [
            np.asarray(eng.submit("t", p, seed=40 + i).result(timeout=120))
            for i, p in enumerate(prompts)
        ]
        steps_so_far = eng.replicas[0].steps
        monkeypatch.setenv(
            "TPUDDP_FAULT", f"replica_kill@step={steps_so_far + 3}"
        )
        faults.reload_faults()
        results = [
            eng.submit("t", p, seed=40 + i) for i, p in enumerate(prompts)
        ]
        for i, r in enumerate(results):
            np.testing.assert_array_equal(
                np.asarray(r.result(timeout=120)), twins[i]
            )
        assert all(s.fired for s in faults.active_faults())
        assert eng.replicas[0].recoveries == 1
        assert not eng.replicas[0].broken  # rebuild cleared the kill
    finally:
        monkeypatch.delenv("TPUDDP_FAULT")
        faults.reload_faults()
        eng.drain()
    assert any(e["event"] == "session_failover" for e in _events(out))


def test_pool_poison_fault_env_rebuilds_and_continues(cpu_devices, monkeypatch):
    """pool_poison@step=N deletes the donated K/V pools mid-sweep (the real
    accelerator donation death): sessions fail over, the pools are rebuilt,
    the stream completes bitwise."""
    eng = DecodeEngine.from_config(_decode_cfg(), devices=cpu_devices)
    eng.start()
    try:
        rng = np.random.RandomState(4)
        p = _prompt(rng, 6)
        twin = np.asarray(eng.submit("t", p, seed=5).result(timeout=120))
        steps_so_far = eng.replicas[0].steps
        monkeypatch.setenv(
            "TPUDDP_FAULT", f"pool_poison@step={steps_so_far + 2}"
        )
        faults.reload_faults()
        out = np.asarray(eng.submit("t", p, seed=5).result(timeout=120))
        np.testing.assert_array_equal(out, twin)
        assert not eng.replicas[0].kpool.is_deleted()
        assert eng.replicas[0].recoveries == 1
    finally:
        monkeypatch.delenv("TPUDDP_FAULT")
        faults.reload_faults()
        eng.drain()


def test_poisoned_request_fails_through_pool_survives(cpu_devices):
    """The poisoned-request firewall (max_failovers): a request whose OWN
    content deterministically kills any prefill dispatch is parked once,
    fails through with the dispatch error on the next incident, and the
    replica — whose probation passes each time (the fault was the request,
    not the device) — stays in routing for everyone else."""
    eng = DecodeEngine.from_config(
        _decode_cfg(max_recoveries=5), devices=cpu_devices
    )
    eng.start()
    replica = eng.replicas[0]
    rng = np.random.RandomState(8)
    poison = _prompt(rng, 5)
    real_prefill = replica._prefill

    def poisoned_prefill(params, kpool, vpool, table, buf, n, *rest):
        row = np.asarray(buf)[0]
        if (int(n) == len(poison)
                and np.array_equal(row[: len(poison)], poison)):
            raise RuntimeError("this request kills the dispatch")
        return real_prefill(params, kpool, vpool, table, buf, n, *rest)

    replica._prefill = poisoned_prefill
    try:
        res = eng.submit("t", poison)
        with pytest.raises(RuntimeError, match="kills the dispatch"):
            res.result(timeout=120)
        # the fail-through verdict is delivered BEFORE the second probation
        # episode finishes — wait for the replica to rejoin routing
        deadline = time.perf_counter() + 60
        while not replica.healthy and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert replica.healthy
        # request-attributed incidents whose canary passed never charge the
        # replica's lifetime max_recoveries budget — the device was
        # provably fine; the request's own failover budget bounded it
        assert replica.recoveries == 0
        # the pool still serves everyone else
        clean = rng.randint(0, VOCAB, size=7).astype(np.int32)
        out = np.asarray(eng.submit("t", clean).result(timeout=120))
        assert out.ndim == 1 and out.size > 0
    finally:
        summary = eng.drain()
    assert summary["completed"] == 1


def test_poison_incidents_never_charge_innocent_sessions(cpu_devices):
    """Attribution regression: repeated incidents CAUSED BY one poisoned
    request must not spend innocent concurrent sessions' failover budgets
    — the innocents park, migrate, and complete bitwise every time."""
    eng = DecodeEngine.from_config(
        _decode_cfg(max_new_tokens=16, max_seq_len=64, kv_blocks=33),
        devices=cpu_devices,
    )
    eng.start()
    replica = eng.replicas[0]
    rng = np.random.RandomState(10)
    innocent = _prompt(rng, 4)
    poison = _prompt(rng, 6)
    twin = np.asarray(
        eng.submit("t", innocent, seed=5, max_new_tokens=16).result(timeout=120)
    )
    real_prefill = replica._prefill
    real_step = replica._step

    def poisoned_prefill(params, kpool, vpool, table, buf, n, *rest):
        row = np.asarray(buf)[0]
        if (int(n) == len(poison)
                and np.array_equal(row[: len(poison)], poison)):
            raise RuntimeError("this request kills the dispatch")
        return real_prefill(params, kpool, vpool, table, buf, n, *rest)

    def slow_step(*a, **k):
        time.sleep(0.02)  # keep the innocent in flight across incidents
        return real_step(*a, **k)

    replica._prefill = poisoned_prefill
    replica._step = slow_step
    try:
        live = eng.submit("t", innocent, seed=5, max_new_tokens=16)
        assert next(live.stream(timeout=120)) is not None  # mid-decode
        # two poisons -> up to four place-phase incidents, each parking the
        # innocent; with default max_failovers=1 an unattributed charge
        # would kill the innocent on the second incident
        poisons = [eng.submit("t", poison), eng.submit("t", poison)]
        for p in poisons:
            with pytest.raises(RuntimeError, match="kills the dispatch"):
                p.result(timeout=120)
        out = np.asarray(live.result(timeout=120))
        np.testing.assert_array_equal(out, twin)
    finally:
        summary = eng.drain()
    assert summary["completed"] == 2  # the twin + the surviving innocent


def test_last_replica_death_during_drain_fails_typed_not_hang(cpu_devices):
    """Drain-window strand regression: with an idle peer's loop already
    EXITED (queue closed and it saw nothing to do), the replica holding
    the last live session dies persistently. Its journal must not be
    handed to the dead peer's loop — no survivors means the typed
    no_healthy_replica failure, promptly, never a hang."""
    eng = DecodeEngine.from_config(
        _decode_cfg(num_replicas=2, max_new_tokens=32, max_seq_len=64,
                    kv_blocks=33),
        devices=cpu_devices,
    )
    eng.start()
    rng = np.random.RandomState(9)
    armed = threading.Event()

    def wrap(replica):
        real_step = replica._step

        def step(*a, **k):
            if armed.is_set():
                raise RuntimeError("device is gone")
            time.sleep(0.01)  # keep the stream alive long enough to drain
            return real_step(*a, **k)

        replica._step = step

    for r in eng.replicas:
        wrap(r)
    res = eng.submit("t", _prompt(rng, 3), max_new_tokens=32)
    assert next(res.stream(timeout=120)) is not None  # live, mid-decode
    # close admission: the IDLE replica's loop exits (drained from its
    # view); the busy one keeps stepping its session
    eng.queue.close()
    deadline = time.perf_counter() + 60
    while (sum(1 for r in eng.replicas if r.loop_alive) > 1
           and time.perf_counter() < deadline):
        time.sleep(0.01)
    assert sum(1 for r in eng.replicas if r.loop_alive) == 1
    armed.set()  # now the busy replica dies persistently (canary included)
    with pytest.raises(NoHealthyReplicaError):
        res.result(timeout=120)
    summary = eng.drain()
    assert summary["completed"] == 0


# ------------------------------------------------- probation state machine --


def test_decode_probation_relapse_then_max_recoveries_removal(
    tmp_path, cpu_devices
):
    """Rejoin -> relapse -> rejoin -> the NEXT incident crosses
    max_recoveries=2 and removes the replica permanently; as the last
    replica, parked and queued work fails with the typed
    no_healthy_replica reason — and nothing hangs."""
    out = str(tmp_path / "run")
    eng = DecodeEngine.from_config(
        _decode_cfg(max_recoveries=2), out_dir=out, devices=cpu_devices
    )
    eng.start()
    rng = np.random.RandomState(5)
    replica = eng.replicas[0]
    try:
        for expected_recoveries in (1, 2):
            _one_shot_step_killer(replica, after=1)
            outv = np.asarray(eng.submit("t", _prompt(rng)).result(timeout=120))
            assert outv.ndim == 1
            assert replica.recoveries == expected_recoveries
            assert replica.healthy
        # third incident: probation budget spent -> removed, typed failure
        _one_shot_step_killer(replica, after=1)
        res = eng.submit("t", _prompt(rng))
        with pytest.raises(NoHealthyReplicaError) as e:
            res.result(timeout=120)
        assert e.value.reason == "no_healthy_replica"
        assert replica.state == "removed"
        # new arrivals fail fast and typed too (mortuary, never a hang)
        late = eng.submit("t", _prompt(rng))
        with pytest.raises(NoHealthyReplicaError):
            late.result(timeout=120)
    finally:
        eng.drain()  # returns — the mortuary loop exits on close + empty
    events = _events(out)
    recovered = [e for e in events if e["event"] == "replica_recovered"]
    assert [e["recoveries"] for e in recovered] == [1, 2]
    removed = [e for e in events if e["event"] == "replica_removed"]
    assert removed and removed[0]["reason"] == "max_recoveries"
    assert any(e["event"] == "no_healthy_replica" for e in events)
    # a removed replica's stale cache is out of the occupancy gauge — the
    # autoscaler must not see phantom KV pressure from a dead pool
    assert eng.kv_occupancy() == 0.0
    errors, _ = schema.validate_history_file(os.path.join(out, "history.jsonl"))
    assert errors == []


def test_decode_last_replica_persistent_death_one_recovery_round_then_typed(
    cpu_devices,
):
    """The regression pair's second outcome: a PERSISTENTLY dead last
    replica (probation's canary keeps failing) parks its sessions,
    attempts one recovery round, and only then fails everything typed —
    queued requests included, and drain still returns."""
    eng = DecodeEngine.from_config(
        _decode_cfg(max_slots=2, kv_blocks=9), devices=cpu_devices
    )
    eng.start()
    rng = np.random.RandomState(6)
    replica = eng.replicas[0]
    attempts = {"n": 0}

    def dead_step(*a, **k):
        attempts["n"] += 1
        raise RuntimeError("device is gone")

    try:
        in_flight = eng.submit("t", _prompt(rng), max_new_tokens=8)
        assert in_flight.stream(timeout=120).__next__() is not None  # live
        replica._step = dead_step
        replica._prefill = dead_step
        queued = [eng.submit("t", _prompt(rng)) for _ in range(3)]
        for res in [in_flight] + queued:
            with pytest.raises(NoHealthyReplicaError):
                res.result(timeout=120)
        # probation genuinely ran before the typed failure: the canary
        # hit the dead dispatch at least recovery_attempts times
        assert attempts["n"] >= eng.survive.recovery_attempts
    finally:
        summary = eng.drain()
    assert summary["completed"] == 0
    assert replica.state == "removed"


def test_serving_replica_probation_rejoins_after_transient_errors(
    tmp_path, cpu_devices
):
    """Request-granularity engine: K consecutive dispatch errors ->
    probation -> the canary passes (the fault was transient) -> the replica
    REJOINS routing instead of dying forever, with the event trail."""
    eng = ServingEngine.from_config(
        _serving_cfg(num_replicas=1, unhealthy_after=2),
        out_dir=str(tmp_path),
        devices=cpu_devices[:1],
    )
    eng.start()
    replica = eng.pool.replicas[0]
    real_infer = replica.infer
    state = {"fails": 0}

    def flaky_infer(x):
        if state["fails"] < 2:
            state["fails"] += 1
            raise RuntimeError("transient device blip")
        return real_infer(x)

    replica.infer = flaky_infer
    try:
        # two sequential failures cross unhealthy_after=2 -> probation ->
        # canary (3rd call) succeeds -> rejoin
        for _ in range(2):
            with pytest.raises(RuntimeError):
                eng.submit("t", np.zeros((1,) + SHAPE, np.float32)).result(
                    timeout=60
                )
        ok = eng.submit("t", np.zeros((2,) + SHAPE, np.float32))
        assert ok.result(timeout=60).shape == (2, 10)
        assert replica.healthy and replica.recoveries == 1
    finally:
        eng.drain()
    events = _events(str(tmp_path))
    assert any(e["event"] == "replica_unhealthy" for e in events)
    assert any(e["event"] == "replica_recovered" for e in events)


# --------------------------------------------------------------- deadlines --


def test_decode_deadline_sheds_queued_never_kills_inflight(
    tmp_path, cpu_devices
):
    """One slot, slow steps: A starts decoding and outlives its own
    deadline (in-flight is untouchable); B queues behind it, expires, and
    is shed with the typed rejection before ever being dispatched."""
    out = str(tmp_path / "run")
    eng = DecodeEngine.from_config(
        _decode_cfg(max_slots=1, kv_blocks=5, max_new_tokens=16,
                    stats_window=4),
        out_dir=out,
        devices=cpu_devices,
    )
    eng.start()
    replica = eng.replicas[0]
    real_step = replica._step

    def slow_step(*a, **k):
        time.sleep(0.03)
        return real_step(*a, **k)

    replica._step = slow_step
    try:
        rng = np.random.RandomState(7)
        # A: ~16 slow steps ≈ 0.5s of decode, deadline 0.15s — it expires
        # mid-flight and must still complete in full
        a = eng.submit("t", _prompt(rng, 3), deadline_s=0.15)
        first = next(a.stream(timeout=120))
        assert isinstance(first, int)  # in flight before B's verdict
        # B: queued behind A's slot for ~0.5s, deadline 0.1 -> shed
        b = eng.submit("t", _prompt(rng, 3), deadline_s=0.1)
        with pytest.raises(AdmissionError) as e:
            b.result(timeout=120)
        assert e.value.reason == "deadline_exceeded"
        out_a = np.asarray(a.result(timeout=120))
        assert out_a.shape == (16,)  # never truncated by its deadline
    finally:
        summary = eng.drain()
    assert summary["completed"] == 1
    assert summary["shed"] == 1
    assert summary["rejected"]["deadline_exceeded"] == 1
    history = os.path.join(out, "history.jsonl")
    errors, _ = schema.validate_history_file(history)
    assert errors == []
    windows = [
        json.loads(l) for l in open(history)
        if l.strip() and json.loads(l).get("type") == "decode_stats"
    ]
    assert sum(w["shed"] for w in windows) == 1


def test_serving_request_ttl_sheds_backlog(cpu_devices):
    """Engine-level admission TTL: with one slow single-request batch in
    flight, the queued backlog expires and is shed — never dispatched."""
    eng = ServingEngine.from_config(
        _serving_cfg(max_batch_size=1, request_ttl_s=0.05),
        devices=cpu_devices[:1],
    )
    eng.start()
    replica = eng.pool.replicas[0]
    real_infer = replica.infer
    replica.infer = lambda x: (time.sleep(0.25), real_infer(x))[1]
    try:
        results = [
            eng.submit("t", np.zeros((1,) + SHAPE, np.float32))
            for _ in range(3)
        ]
        # the first is dispatched immediately (pre-expiry); the rest age
        # out behind its 0.25s dispatch
        assert results[0].result(timeout=60).shape == (1, 10)
        for r in results[1:]:
            with pytest.raises(AdmissionError) as e:
                r.result(timeout=60)
            assert e.value.reason == "deadline_exceeded"
    finally:
        summary = eng.drain()
    assert summary["completed"] == 1
    assert summary["shed"] == 2
    assert summary["rejected"]["deadline_exceeded"] == 2


# ------------------------------------------------------------ retry budget --


def test_retry_budget_transparent_transient_recovery(cpu_devices):
    """retry_budget=2: a transient dispatch failure re-queues the request
    and the client sees a clean result — no exception, one retry counted,
    and the token refunded on success."""
    eng = ServingEngine.from_config(
        _serving_cfg(retry_budget=2, unhealthy_after=0),
        devices=cpu_devices[:1],
    )
    eng.start()
    replica = eng.pool.replicas[0]
    real_infer = replica.infer
    state = {"fails": 0}

    def flaky(x):
        if state["fails"] < 1:
            state["fails"] += 1
            raise RuntimeError("transient")
        return real_infer(x)

    replica.infer = flaky
    try:
        res = eng.submit("t", np.ones((2,) + SHAPE, np.float32))
        assert res.result(timeout=60).shape == (2, 10)
        assert eng.stats.retries == 1
        assert eng.retry_budget.used("t") == 0  # refunded on success
    finally:
        summary = eng.drain()
    assert summary["completed"] == 1 and summary["retries"] == 1


def test_retry_budget_exhaustion_fails_through(cpu_devices):
    """Sustained failure: the budget bounds retries PER REQUEST — a
    request spends its tokens, fails with the dispatch error, and refunds
    on the way out, so a later same-tenant request gets its own retries
    (a dead request must not disable retries for unrelated future work)."""
    eng = ServingEngine.from_config(
        _serving_cfg(retry_budget=2, unhealthy_after=0),
        devices=cpu_devices[:1],
    )
    eng.start()
    eng.pool.replicas[0].infer = lambda x: (_ for _ in ()).throw(
        RuntimeError("persistently dead")
    )
    try:
        res = eng.submit("t", np.ones((1,) + SHAPE, np.float32))
        with pytest.raises(RuntimeError, match="persistently dead"):
            res.result(timeout=60)
        assert eng.stats.retries == 2  # both tokens spent before failing
        assert eng.retry_budget.used("t") == 0  # refunded at failure-through
        res2 = eng.submit("t", np.ones((1,) + SHAPE, np.float32))
        with pytest.raises(RuntimeError):
            res2.result(timeout=60)
        assert eng.stats.retries == 4  # its OWN budget, spent and refunded
        assert eng.retry_budget.used("t") == 0
    finally:
        eng.drain()


# ---------------------------------------------------------------- schema v7 --


def test_v7_run_meta_requires_survivability():
    meta = schema.make_run_meta(world_size=1)
    assert "survivability" in meta and meta["survivability"] is None
    assert schema.validate_record(meta) == []
    drifted = {k: v for k, v in meta.items() if k != "survivability"}
    errs = schema.validate_record(drifted)
    assert errs and any("survivability" in e for e in errs)
    v6 = dict(drifted)
    v6["schema_version"] = 6
    assert schema.validate_record(v6) == []


def test_serving_history_carries_shed_window_and_survivability_header(
    tmp_path, cpu_devices
):
    eng = ServingEngine.from_config(
        _serving_cfg(request_ttl_s=30.0, retry_budget=1),
        out_dir=str(tmp_path),
        devices=cpu_devices[:1],
    )
    eng.start()
    try:
        eng.submit("t", np.zeros((1,) + SHAPE, np.float32)).result(timeout=60)
    finally:
        eng.drain()
    history = os.path.join(str(tmp_path), "history.jsonl")
    errors, _ = schema.validate_history_file(history)
    assert errors == []
    records = [json.loads(l) for l in open(history) if l.strip()]
    meta = records[0]
    assert meta["schema_version"] == schema.SCHEMA_VERSION
    assert meta["survivability"]["request_ttl_s"] == 30.0
    assert meta["survivability"]["retry_budget"] == 1
    windows = [r for r in records if r["type"] == "serving_stats"]
    assert windows and all(
        "shed" in w and "retries" in w for w in windows
    )
