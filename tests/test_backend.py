"""Backend ladder + process-group lifecycle (SURVEY.md §2b #11)."""

import jax
import pytest

from tpuddp.parallel import backend


@pytest.fixture(autouse=True)
def fresh_state():
    backend.cleanup()
    yield
    backend.cleanup()


def test_ladder_prefers_env_override(monkeypatch):
    monkeypatch.setenv("TPUDDP_BACKEND", "cpu")
    assert backend.detect_backend() == "cpu"


def test_ladder_explicit_prefer():
    assert backend.detect_backend("cpu") == "cpu"


def test_available_backends_contains_cpu():
    assert "cpu" in backend.available_backends()


def test_setup_cleanup_lifecycle():
    chosen = backend.setup(world_size=8, backend="cpu")
    assert chosen == "cpu"
    assert backend.is_initialized()
    assert backend.get_backend() == "cpu"
    assert backend.get_world_size() == 8
    assert backend.get_rank() == jax.process_index() == 0
    backend.cleanup()
    assert not backend.is_initialized()
    assert backend.get_backend() is None


def test_setup_rejects_oversized_world():
    with pytest.raises(ValueError):
        backend.setup(world_size=4096, backend="cpu")


def test_setup_twice_is_idempotent():
    backend.setup(world_size=4, backend="cpu")
    assert backend.setup(world_size=8, backend="cpu") == "cpu"
    assert backend.get_world_size() == 4  # second call ignored


def test_resolve_devices_slices_world():
    backend.setup(world_size=4, backend="cpu")
    devs = backend.resolve_devices()
    assert len(devs) == 4
