"""The go/no-go gate (SURVEY.md §7 step 3): 8-way DP loss curves match the
single-device run — the BASELINE.json north-star metric ("loss-curve parity"),
plus DDP gradient semantics and SyncBN-under-DP exactness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import optim
from tpuddp.data import ShardedDataLoader, SyntheticClassification
from tpuddp.models import ToyCNN, ToyMLP
from tpuddp.nn import CrossEntropyLoss
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.training.loop import run_training_loop
from tpuddp.training.step import accumulate_metrics, finalize_metrics

KEY = jax.random.key(42)


def run_config(model_fn, mesh, n_epochs=2, mode="shard_map", n=128, batch=4, lr=1e-2):
    """Train on the mesh; per-replica batch keeps GLOBAL batch fixed at 32."""
    world = mesh.devices.size
    per_replica = (batch * 8) // world
    ds = SyntheticClassification(n=n, shape=(8, 8, 3), seed=7)
    loader = ShardedDataLoader(ds, per_replica, mesh, shuffle=False)
    test_loader = ShardedDataLoader(ds, per_replica, mesh, shuffle=False)
    model = model_fn()
    ddp = DistributedDataParallel(
        model, optim.Adam(lr), CrossEntropyLoss(), mesh=mesh, mode=mode
    )
    state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    state, history = run_training_loop(
        ddp, state, loader, test_loader, save_dir=None, num_epochs=n_epochs,
        set_epoch=False, log=lambda *_: None,
    )
    return history


@pytest.mark.parametrize("mode", ["shard_map", "auto"])
def test_dp8_matches_single_device_losses(cpu_devices, mode):
    """Same data, same init, same global batch: the 8-way DP loss curve must
    equal the 1-device curve (DDP grad-averaging is exactly the global-batch
    gradient when shards are equal)."""
    h1 = run_config(ToyMLP, make_mesh(cpu_devices[:1]), mode=mode)
    h8 = run_config(ToyMLP, make_mesh(cpu_devices), mode=mode)
    for a, b in zip(h1, h8):
        assert a["train_loss"] == pytest.approx(b["train_loss"], rel=2e-4)
        assert a["test_loss"] == pytest.approx(b["test_loss"], rel=2e-4)
        assert a["train_samples"] == b["train_samples"]


def test_shard_map_and_auto_modes_agree(cpu_devices):
    mesh = make_mesh(cpu_devices)
    ha = run_config(ToyMLP, mesh, mode="shard_map")
    hb = run_config(ToyMLP, mesh, mode="auto")
    for a, b in zip(ha, hb):
        assert a["train_loss"] == pytest.approx(b["train_loss"], rel=2e-4)


def test_sync_bn_dp_matches_single_device(cpu_devices):
    """SyncBatchNorm contract end-to-end: a BN model under 8-way DP with
    synced stats reproduces the single-device (global-batch-stats) run."""
    h1 = run_config(lambda: ToyCNN(sync_bn=True), make_mesh(cpu_devices[:1]))
    h8 = run_config(lambda: ToyCNN(sync_bn=True), make_mesh(cpu_devices))
    for a, b in zip(h1, h8):
        assert a["train_loss"] == pytest.approx(b["train_loss"], rel=5e-4)
        assert a["test_loss"] == pytest.approx(b["test_loss"], rel=5e-4)


def test_loss_decreases_on_learnable_data(cpu_devices):
    history = run_config(ToyMLP, make_mesh(cpu_devices), n_epochs=4)
    assert history[-1]["train_loss"] < history[0]["train_loss"] * 0.5
    assert history[-1]["test_accuracy"] > 80.0


def test_ddp_grads_equal_mean_of_shard_grads(cpu_devices):
    """Direct DDP-semantics check (SURVEY.md §4 parity tests): one DP step
    must move params exactly as the mean of per-shard gradients would."""
    mesh = make_mesh(cpu_devices)
    model = ToyMLP(hidden=(16,))
    opt = optim.SGD(lr=0.1)
    criterion = CrossEntropyLoss()
    ddp = DistributedDataParallel(model, opt, criterion, mesh=mesh)
    x = np.random.RandomState(0).randn(16, 8, 8, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 16)
    w = np.ones(16, np.float32)

    state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    params0 = jax.tree_util.tree_map(np.asarray, state.params)
    batch = ddp.shard((x, y, w))
    new_state, _ = ddp.train_step(state, batch)

    # oracle: mean over 8 per-shard gradients of the per-shard mean loss
    from tpuddp.nn.core import Context

    mstate = state.model_state

    def shard_loss(params, xs, ys):
        logits, _ = model.apply(params, mstate, xs, Context(train=True))
        return criterion(logits, ys)

    grad_fn = jax.grad(shard_loss)
    shard_grads = [
        grad_fn(
            jax.tree_util.tree_map(jnp.asarray, params0),
            jnp.asarray(x[i * 2 : (i + 1) * 2]),
            jnp.asarray(y[i * 2 : (i + 1) * 2]),
        )
        for i in range(8)
    ]
    mean_grads = jax.tree_util.tree_map(
        lambda *gs: sum(gs) / len(gs), *shard_grads
    )
    expected = jax.tree_util.tree_map(
        lambda p, g: p - 0.1 * np.asarray(g), params0, mean_grads
    )
    got = jax.tree_util.tree_map(np.asarray, new_state.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        got,
        expected,
    )


def test_bf16_optimizer_state_convergence_parity(cpu_devices):
    """optimizer_state_dtype=bfloat16 (the opt-in that halves optimizer HBM
    traffic) must track the f32-state run on real data: same init, same
    digits batches, loss curves within bf16 rounding and equal-quality
    held-out accuracy."""
    from tpuddp.data import digits
    from tpuddp.data.digits import DIGITS_MEAN, DIGITS_STD
    from tpuddp.data.transforms import make_eval_transform, make_train_augment

    train_ds, test_ds = digits.load_datasets()
    mesh = make_mesh(cpu_devices[:4])
    augment = make_train_augment(
        size=None, flip=False, mean=DIGITS_MEAN, std=DIGITS_STD
    )
    eval_t = make_eval_transform(size=None, mean=DIGITS_MEAN, std=DIGITS_STD)

    def run(state_dtype):
        loader = ShardedDataLoader(train_ds, 32, mesh, shuffle=False)
        test_loader = ShardedDataLoader(test_ds, 45, mesh, shuffle=False)
        ddp = DistributedDataParallel(
            ToyMLP(hidden=(32,)),
            optim.Adam(1e-2, state_dtype=state_dtype),
            CrossEntropyLoss(),
            mesh=mesh,
            augment=augment,
            eval_transform=eval_t,
        )
        state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        _, history = run_training_loop(
            ddp, state, loader, test_loader, save_dir=None, num_epochs=4,
            set_epoch=False, log=lambda *_: None,
        )
        return history

    h32 = run(None)
    h16 = run("bfloat16")
    for a, b in zip(h32, h16):
        assert a["train_loss"] == pytest.approx(b["train_loss"], rel=2e-2)
    # both converge to real generalization; bf16 state costs no accuracy here
    assert h16[-1]["test_accuracy"] >= h32[-1]["test_accuracy"] - 2.0
    assert h16[-1]["test_accuracy"] > 80.0


def test_masked_final_batch_metrics_are_exact(cpu_devices):
    """Padded final batches (static shapes) must not distort sample-weighted
    metrics: n == real dataset size (+ sampler wrap-pads), never the padded size."""
    mesh = make_mesh(cpu_devices[:4])
    ds = SyntheticClassification(n=50, shape=(8, 8, 3), seed=3)
    loader = ShardedDataLoader(ds, batch_size=8, mesh=mesh, shuffle=False)
    model = ToyMLP(hidden=(16,))
    ddp = DistributedDataParallel(model, optim.SGD(0.01), CrossEntropyLoss(), mesh=mesh)
    state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    acc = None
    for host_batch in loader:
        m = ddp.eval_step(state, ddp.shard(host_batch))
        acc = accumulate_metrics(acc, m)
    final = finalize_metrics(acc)
    # 50 samples over 4 replicas -> 13 each = 52 weighted samples (2 wrap-pads)
    assert final["n"] == 52.0
    assert 0 <= final["correct"] <= 52


def test_clip_grad_norm_applies_after_aggregation(cpu_devices):
    """training.clip_grad_norm clips the cross-replica-AVERAGED gradient
    (the reference README's clip-before-aggregate caveat): the DP step with
    a tight clip must match a single-device step whose full-batch grad is
    clipped to the same norm."""
    ds = SyntheticClassification(n=64, shape=(8, 8, 3), seed=9)
    x, y = ds.get_batch(np.arange(64))
    w = np.ones(64, np.float32)
    clip = 0.05

    def run(devices):
        ddp = DistributedDataParallel(
            ToyMLP(hidden=(16,)), optim.SGD(1.0), CrossEntropyLoss(),
            mesh=make_mesh(devices), mode="shard_map", clip_grad_norm=clip,
        )
        state = ddp.init_state(jax.random.key(3), jnp.zeros((1, 8, 8, 3)))
        state, _ = ddp.train_step(state, ddp.shard((x, y, w)))
        return jax.tree_util.tree_map(np.asarray, state.params)

    p_dp = run(cpu_devices)      # 8-way DP
    p_single = run(cpu_devices[:1])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        p_dp, p_single,
    )
    # with SGD lr=1, the param delta norm == the clipped grad norm
    fresh = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.SGD(1.0), CrossEntropyLoss(),
        mesh=make_mesh(cpu_devices), mode="shard_map", clip_grad_norm=clip,
    )
    st0 = fresh.init_state(jax.random.key(3), jnp.zeros((1, 8, 8, 3)))
    p0 = jax.tree_util.tree_map(np.asarray, st0.params)
    delta = jax.tree_util.tree_map(lambda a, b: a - b, p_dp, p0)
    norm = float(np.sqrt(sum(np.sum(d ** 2) for d in jax.tree_util.tree_leaves(delta))))
    assert norm == pytest.approx(clip, rel=1e-3)
