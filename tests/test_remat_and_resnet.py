"""Rematerialization option + ResNet-18 training smoke (BASELINE config 5
machinery: ResNet + sync-BN + sharded sampler on the DP mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import optim
from tpuddp.data import ShardedDataLoader, SyntheticClassification
from tpuddp.models import ResNet18, ToyCNN
from tpuddp.nn import CrossEntropyLoss, convert_sync_batchnorm
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel

KEY = jax.random.key(0)


def one_step(ddp, state, x, y):
    w = np.ones(len(y), np.float32)
    return ddp.train_step(state, ddp.shard((x, y, w)))


def test_remat_matches_plain_step(cpu_devices):
    """jax.checkpoint must change memory behavior only — identical numerics."""
    mesh = make_mesh(cpu_devices)
    x = np.random.RandomState(0).randn(16, 8, 8, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 16)

    results = []
    for remat in (False, True):
        ddp = DistributedDataParallel(
            ToyCNN(sync_bn=True), optim.Adam(1e-2), CrossEntropyLoss(),
            mesh=mesh, remat=remat,
        )
        state = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        state, m = one_step(ddp, state, x, y)
        results.append((state, m))

    (s0, m0), (s1, m1) = results
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        s0.params,
        s1.params,
    )
    np.testing.assert_allclose(
        np.sum(np.asarray(m0["loss_sum"])), np.sum(np.asarray(m1["loss_sum"])),
        rtol=1e-6,
    )


@pytest.mark.slow
def test_resnet18_sync_bn_trains_on_dp_mesh(cpu_devices):
    """A short real training run of the BASELINE config-5 model shape:
    ResNet-18 (CIFAR stem) + converted sync-BN, 8-way DP, sharded sampler."""
    mesh = make_mesh(cpu_devices)
    model = convert_sync_batchnorm(ResNet18(num_classes=10, small_input=True))
    ds = SyntheticClassification(n=64, shape=(32, 32, 3), seed=5, noise=0.3)
    loader = ShardedDataLoader(ds, 2, mesh, shuffle=True)
    ddp = DistributedDataParallel(
        model, optim.Adam(1e-3), CrossEntropyLoss(), mesh=mesh, remat=True
    )
    state = ddp.init_state(KEY, jnp.zeros((1, 32, 32, 3)))

    losses = []
    for epoch in range(2):
        loader.set_epoch(epoch)
        total, n = 0.0, 0.0
        for host_batch in loader:
            state, m = ddp.train_step(state, ddp.shard(host_batch))
            total += float(np.sum(np.asarray(m["loss_sum"])))
            n += float(np.sum(np.asarray(m["n"])))
        losses.append(total / n)
    assert np.isfinite(losses).all()
    assert losses[1] < losses[0]  # learning


@pytest.mark.slow
def test_resnet50_bottleneck_trains_on_dp_mesh(cpu_devices):
    """ResNet-50 (Bottleneck, CIFAR stem) + sync-BN trains under 8-way DP
    with remat — the deepest zoo member exercised through the real step."""
    mesh = make_mesh(cpu_devices)
    from tpuddp.models import ResNet50

    model = convert_sync_batchnorm(ResNet50(num_classes=10, small_input=True))
    ds = SyntheticClassification(n=32, shape=(32, 32, 3), seed=7, noise=0.3)
    loader = ShardedDataLoader(ds, 2, mesh, shuffle=True)
    ddp = DistributedDataParallel(
        model, optim.Adam(1e-3), CrossEntropyLoss(), mesh=mesh, remat=True
    )
    state = ddp.init_state(KEY, jnp.zeros((1, 32, 32, 3)))
    loader.set_epoch(0)
    total, n = 0.0, 0.0
    for host_batch in loader:
        state, m = ddp.train_step(state, ddp.shard(host_batch))
        total += float(np.sum(np.asarray(m["loss_sum"])))
        n += float(np.sum(np.asarray(m["n"])))
    assert np.isfinite(total / n) and n == 32.0
