"""nn layer correctness, with torch (CPU) as the numerical oracle where the
reference stack defines the semantics (BatchNorm buffers, adaptive pooling,
cross-entropy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from tpuddp.utils.compat import shard_map
from tpuddp import nn

KEY = jax.random.key(0)


def ctx_train(rng=None, axis_name=None):
    return nn.Context(train=True, rng=rng, axis_name=axis_name)


def test_linear_shapes_and_math():
    x = jnp.ones((4, 16))
    layer = nn.Linear(8)
    params, state = layer.init(KEY, x)
    assert params["weight"].shape == (16, 8)
    y, _ = layer.apply(params, state, x, nn.Context())
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(x @ params["weight"] + params["bias"]), rtol=1e-6
    )


def test_linear_init_bound_matches_torch_scheme():
    x = jnp.ones((2, 100))
    params, _ = nn.Linear(50).init(KEY, x)
    bound = 1 / np.sqrt(100)
    w = np.asarray(params["weight"])
    assert w.min() >= -bound and w.max() <= bound
    assert w.std() == pytest.approx(bound / np.sqrt(3), rel=0.1)


def test_conv2d_matches_torch():
    x = np.random.RandomState(0).randn(2, 8, 8, 3).astype(np.float32)
    layer = nn.Conv2d(5, kernel_size=3, strides=2, padding=1)
    params, state = layer.init(KEY, jnp.asarray(x))
    y, _ = layer.apply(params, state, jnp.asarray(x), nn.Context())
    # torch oracle: NCHW / OIHW
    w = np.asarray(params["weight"]).transpose(3, 2, 0, 1)  # HWIO -> OIHW
    ref = F.conv2d(
        torch.from_numpy(x.transpose(0, 3, 1, 2)),
        torch.from_numpy(w),
        torch.from_numpy(np.asarray(params["bias"])),
        stride=2,
        padding=1,
    ).numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)


def test_maxpool_matches_torch():
    x = np.random.RandomState(1).randn(2, 9, 9, 4).astype(np.float32)
    layer = nn.MaxPool2d(3, strides=2)
    y, _ = layer.apply((), (), jnp.asarray(x), nn.Context())
    ref = F.max_pool2d(torch.from_numpy(x.transpose(0, 3, 1, 2)), 3, 2).numpy()
    np.testing.assert_allclose(np.asarray(y), ref.transpose(0, 2, 3, 1), rtol=1e-6)


@pytest.mark.parametrize("in_hw,out_hw", [(13, 6), (7, 7), (8, 4), (5, 3), (1, 2)])
def test_adaptive_avg_pool_matches_torch(in_hw, out_hw):
    x = np.random.RandomState(2).randn(2, in_hw, in_hw, 3).astype(np.float32)
    layer = nn.AdaptiveAvgPool2d(out_hw)
    y, _ = layer.apply((), (), jnp.asarray(x), nn.Context())
    ref = F.adaptive_avg_pool2d(
        torch.from_numpy(x.transpose(0, 3, 1, 2)), out_hw
    ).numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("in_hw,out_hw", [(13, 6), (5, 3)])
def test_adaptive_avg_pool_gradient_matches_torch(in_hw, out_hw):
    # (13,6) takes the uniform-bin reduce_window fast path, (5,3) the ragged
    # integral-image path (nn/layers.py) — both backwards must match torch
    x = np.random.RandomState(3).randn(2, in_hw, in_hw, 3).astype(np.float32)
    layer = nn.AdaptiveAvgPool2d(out_hw)
    g = jax.grad(
        lambda v: jnp.sum(layer.apply((), (), v, nn.Context())[0] ** 2)
    )(jnp.asarray(x))
    xt = torch.from_numpy(x.transpose(0, 3, 1, 2)).requires_grad_(True)
    F.adaptive_avg_pool2d(xt, out_hw).pow(2).sum().backward()
    np.testing.assert_allclose(
        np.asarray(g), xt.grad.numpy().transpose(0, 2, 3, 1), rtol=1e-4, atol=1e-5
    )


def test_dropout_train_eval_and_rng():
    x = jnp.ones((100, 100))
    layer = nn.Dropout(0.5)
    y_eval, _ = layer.apply((), (), x, nn.Context())
    np.testing.assert_array_equal(np.asarray(y_eval), np.ones((100, 100)))
    y_train, _ = layer.apply((), (), x, ctx_train(jax.random.key(1)))
    kept = np.asarray(y_train) != 0
    assert 0.4 < kept.mean() < 0.6
    assert np.allclose(np.asarray(y_train)[kept], 2.0)  # inverted scaling
    with pytest.raises(ValueError):
        layer.apply((), (), x, ctx_train(rng=None))


def test_batchnorm_matches_torch_train_and_eval():
    x = np.random.RandomState(3).randn(8, 4, 4, 5).astype(np.float32) * 3 + 1
    layer = nn.BatchNorm()
    params, state = layer.init(KEY, jnp.asarray(x))
    y, new_state = layer.apply(params, state, jnp.asarray(x), ctx_train())

    bn = torch.nn.BatchNorm2d(5)
    bn.train()
    ref = bn(torch.from_numpy(x.transpose(0, 3, 1, 2))).detach().numpy()
    np.testing.assert_allclose(np.asarray(y), ref.transpose(0, 2, 3, 1), rtol=1e-3, atol=1e-4)
    # running buffers (torch keeps unbiased var in the buffer)
    np.testing.assert_allclose(np.asarray(new_state["mean"]), bn.running_mean.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_state["var"]), bn.running_var.numpy(), rtol=1e-4, atol=1e-5)

    # eval mode uses the buffers
    bn.eval()
    y2, same_state = layer.apply(params, new_state, jnp.asarray(x), nn.Context())
    ref2 = bn(torch.from_numpy(x.transpose(0, 3, 1, 2))).detach().numpy()
    np.testing.assert_allclose(np.asarray(y2), ref2.transpose(0, 2, 3, 1), rtol=1e-3, atol=1e-4)
    assert same_state is new_state  # eval must not touch buffers


def test_sync_batchnorm_equals_global_batch_stats(mesh):
    """The SyncBatchNorm contract (SURVEY §2b #16): per-shard BN with sync=True
    must equal single-device BN over the full global batch."""
    from jax.sharding import PartitionSpec as P

    x = np.random.RandomState(4).randn(16, 2, 2, 3).astype(np.float32)
    layer = nn.BatchNorm(sync=True)
    params, state = layer.init(KEY, jnp.asarray(x))

    def per_shard(p, s, xs):
        y, ns = layer.apply(p, s, xs, ctx_train(axis_name="data"))
        return y, ns

    y_sync, st_sync = jax.jit(
        shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(P(), P(), P("data")),
            out_specs=(P("data"), P()),
            check_vma=False,
        )
    )(params, state, jnp.asarray(x))

    layer_local = nn.BatchNorm()
    y_full, st_full = layer_local.apply(params, state, jnp.asarray(x), ctx_train())
    np.testing.assert_allclose(np.asarray(y_sync), np.asarray(y_full), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(st_sync["mean"]), np.asarray(st_full["mean"]), rtol=1e-4, atol=1e-6)
    # unbiased-var correction uses the GLOBAL count when synced
    np.testing.assert_allclose(np.asarray(st_sync["var"]), np.asarray(st_full["var"]), rtol=1e-4, atol=1e-6)


def test_convert_sync_batchnorm_walks_tree():
    model = nn.Sequential(
        nn.Conv2d(4, 3, padding=1),
        nn.BatchNorm(),
        nn.Sequential(nn.BatchNorm(), nn.ReLU()),
    )
    nn.convert_sync_batchnorm(model)
    assert model[1].sync is True
    assert model[2][0].sync is True


def test_cross_entropy_matches_torch():
    logits = np.random.RandomState(5).randn(10, 7).astype(np.float32)
    labels = np.random.RandomState(6).randint(0, 7, 10)
    ours = nn.cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    ref = F.cross_entropy(torch.from_numpy(logits), torch.from_numpy(labels)).item()
    assert float(ours) == pytest.approx(ref, rel=1e-5)
    ours_sum = nn.cross_entropy(jnp.asarray(logits), jnp.asarray(labels), "sum")
    assert float(ours_sum) == pytest.approx(ref * 10, rel=1e-5)


def test_cross_entropy_weighted_mask_ignores_padding():
    logits = np.random.RandomState(7).randn(6, 3).astype(np.float32)
    labels = np.array([0, 1, 2, 0, 1, 2])
    w = jnp.array([1, 1, 1, 1, 0, 0], jnp.float32)
    masked = nn.cross_entropy(jnp.asarray(logits), jnp.asarray(labels), "mean", w)
    unpadded = nn.cross_entropy(jnp.asarray(logits[:4]), jnp.asarray(labels[:4]))
    assert float(masked) == pytest.approx(float(unpadded), rel=1e-6)


def test_sequential_threads_state_and_shapes():
    x = jnp.ones((2, 8, 8, 3))
    model = nn.Sequential(
        nn.Conv2d(4, 3, padding=1),
        nn.BatchNorm(),
        nn.ReLU(),
        nn.MaxPool2d(2),
        nn.Flatten(),
        nn.Linear(10),
    )
    params, state = model.init(KEY, x)
    y, new_state = model.apply(params, state, x, ctx_train())
    assert y.shape == (2, 10)
    assert len(new_state) == 6
    # BN state updated in train mode
    assert not np.allclose(np.asarray(new_state[1]["mean"]), 0.0)


def test_batchnorm_sample_weight_excludes_padding():
    """Padded (weight-0) rows must not bias BN batch statistics: a padded
    batch with a mask must produce the same output rows and running stats as
    the unpadded batch (the torch ragged-last-batch behavior, without the
    ragged recompile)."""
    rng = np.random.RandomState(5)
    real = rng.randn(6, 2, 2, 3).astype(np.float32) * 2 + 4
    padded = np.concatenate([real, np.repeat(real[:1], 2, axis=0)])
    w = np.array([1, 1, 1, 1, 1, 1, 0, 0], np.float32)

    layer = nn.BatchNorm()
    params, state = layer.init(KEY, jnp.asarray(padded))
    y_ref, st_ref = layer.apply(params, state, jnp.asarray(real), ctx_train())
    y_pad, st_pad = layer.apply(
        params, state, jnp.asarray(padded),
        nn.Context(train=True, sample_weight=jnp.asarray(w)),
    )
    np.testing.assert_allclose(
        np.asarray(y_pad)[:6], np.asarray(y_ref), rtol=1e-4, atol=1e-5
    )
    for k in ("mean", "var"):
        np.testing.assert_allclose(
            np.asarray(st_pad[k]), np.asarray(st_ref[k]), rtol=1e-4, atol=1e-6
        )


def test_sync_batchnorm_weighted_equals_global_masked(mesh):
    """sync=True + sample_weight: sharded weighted stats == full-batch stats
    over only the real rows."""
    from jax.sharding import PartitionSpec as P

    rng = np.random.RandomState(6)
    real = rng.randn(13, 2, 2, 3).astype(np.float32)
    padded = np.concatenate([real, np.repeat(real[:1], 3, axis=0)])
    w = np.concatenate([np.ones(13), np.zeros(3)]).astype(np.float32)

    layer = nn.BatchNorm(sync=True)
    params, state = layer.init(KEY, jnp.asarray(padded))

    def per_shard(p, s, xs, ws):
        ctx = nn.Context(train=True, axis_name="data", sample_weight=ws)
        return layer.apply(p, s, xs, ctx)

    y_sync, st_sync = jax.jit(
        shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(P(), P(), P("data"), P("data")),
            out_specs=(P("data"), P()),
            check_vma=False,
        )
    )(params, state, jnp.asarray(padded), jnp.asarray(w))

    y_ref, st_ref = nn.BatchNorm().apply(params, state, jnp.asarray(real), ctx_train())
    np.testing.assert_allclose(
        np.asarray(y_sync)[:13], np.asarray(y_ref), rtol=1e-4, atol=1e-5
    )
    for k in ("mean", "var"):
        np.testing.assert_allclose(
            np.asarray(st_sync[k]), np.asarray(st_ref[k]), rtol=1e-4, atol=1e-6
        )


def test_batchnorm_stable_var_matches_and_survives_large_mean():
    x = np.random.RandomState(7).randn(8, 4, 4, 5).astype(np.float32)
    a = nn.BatchNorm()
    b = nn.BatchNorm(stable_var=True)
    params, state = a.init(KEY, jnp.asarray(x))
    ya, _ = a.apply(params, state, jnp.asarray(x), ctx_train())
    yb, _ = b.apply(params, state, jnp.asarray(x), ctx_train())
    np.testing.assert_allclose(np.asarray(ya), np.asarray(yb), rtol=1e-4, atol=1e-5)

    # large-mean activations: E[x^2]-E[x]^2 cancels catastrophically; the
    # two-pass path keeps the true variance
    big = (x + 300.0).astype(np.float32)  # unit variance at mean 300
    yb2, st2 = b.apply(params, state, jnp.asarray(big), ctx_train())
    np.testing.assert_allclose(
        np.asarray(yb2).reshape(-1, 5).var(axis=0), np.ones(5), rtol=2e-2
    )
    assert np.all(np.asarray(st2["var"]) > 0)
    # the single-pass path visibly degrades on the same input (that's the
    # reason stable_var exists); don't assert a hard bound, just the contrast
    ya2, _ = a.apply(params, state, jnp.asarray(big), ctx_train())
    err_stable = np.abs(np.asarray(yb2).reshape(-1, 5).var(axis=0) - 1).max()
    err_fast = np.abs(np.asarray(ya2).reshape(-1, 5).var(axis=0) - 1).max()
    assert err_stable <= err_fast


def test_batchnorm_all_padded_batch_leaves_running_stats():
    """A fully-padded (all weight-0) shard must leave the running buffers
    untouched rather than decaying them toward mean=0/var=0."""
    x = np.random.RandomState(8).randn(4, 2, 2, 3).astype(np.float32)
    layer = nn.BatchNorm()
    params, _ = layer.init(KEY, jnp.asarray(x))
    state = {"mean": jnp.full((3,), 2.0), "var": jnp.full((3,), 3.0)}
    w = jnp.zeros(4, jnp.float32)
    _, new_state = layer.apply(
        params, state, jnp.asarray(x),
        nn.Context(train=True, sample_weight=w),
    )
    np.testing.assert_array_equal(np.asarray(new_state["mean"]), np.full(3, 2.0))
    np.testing.assert_array_equal(np.asarray(new_state["var"]), np.full(3, 3.0))


def test_divergent_state_protocol():
    """sync_buffers='none' validation holds by construction (Module.
    divergent_state): an UNDECLARED custom stateful leaf counts as divergent;
    declaring divergent_state() -> False vouches replica-invariance."""
    from tpuddp.nn.core import Module
    from tpuddp.nn.norm import has_divergent_buffers

    class Counter(Module):
        def init(self, key, x):
            return (), {"count": jnp.zeros(())}

        def apply(self, params, state, x, ctx):
            return x, {"count": state["count"] + 1.0}

    class InvariantCounter(Counter):
        def divergent_state(self):
            return False

    assert has_divergent_buffers(Counter())
    assert not has_divergent_buffers(InvariantCounter())
    assert has_divergent_buffers(nn.Sequential(nn.Linear(4), Counter()))
    assert not has_divergent_buffers(nn.Sequential(nn.Linear(4), InvariantCounter()))

    class StatefulContainer(Module):
        """Container with its OWN buffer beside clean children — must not
        escape the check just because its children are fine."""

        def __init__(self):
            self.inner = nn.Linear(4)

        def children(self):
            return (self.inner,)

        def init(self, key, x):
            p, s = self.inner.init(key, x)
            return {"inner": p}, {"inner": s, "ema": jnp.zeros(x.shape[-1])}

        def apply(self, params, state, x, ctx):
            y, s = self.inner.apply(params["inner"], state["inner"], x, ctx)
            new = dict(state, inner=s, ema=0.9 * state["ema"])
            return y, new

    assert has_divergent_buffers(StatefulContainer())  # undeclared own init
    assert not has_divergent_buffers(nn.Sequential(nn.Linear(4)))  # declared container
    # the built-in declarations
    assert has_divergent_buffers(nn.BatchNorm())
    assert not has_divergent_buffers(nn.BatchNorm(sync=True))
    assert not has_divergent_buffers(nn.BatchNorm(track_running_stats=False))
    assert not has_divergent_buffers(nn.Sequential(nn.Conv2d(4, 3), nn.ReLU()))


# --------------------------------------------- transformer-family layers --


def test_layernorm_matches_torch():
    x = np.random.RandomState(7).randn(4, 9, 32).astype(np.float32) * 3 + 1
    layer = nn.LayerNorm()
    params, state = layer.init(KEY, jnp.asarray(x))
    assert params["scale"].shape == (32,) and params["bias"].shape == (32,)
    # non-trivial affine so the test covers scale/bias application too
    params = {
        "scale": jnp.asarray(np.random.RandomState(8).randn(32), jnp.float32),
        "bias": jnp.asarray(np.random.RandomState(9).randn(32), jnp.float32),
    }
    y, state2 = layer.apply(params, state, jnp.asarray(x), ctx_train())
    assert state2 == state  # no buffers, nothing diverges
    ref = F.layer_norm(
        torch.from_numpy(x), (32,),
        torch.from_numpy(np.asarray(params["scale"])),
        torch.from_numpy(np.asarray(params["bias"])),
    ).numpy()
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)
    # train and eval are the same math (per-sample statistics)
    y_eval, _ = layer.apply(params, state, jnp.asarray(x), nn.Context())
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y_eval))


def test_layernorm_no_affine_and_no_divergent_buffers():
    x = jnp.asarray(np.random.RandomState(10).randn(2, 8).astype(np.float32))
    layer = nn.LayerNorm(affine=False)
    params, _ = layer.init(KEY, x)
    assert params == {}
    y, _ = layer.apply(params, (), x, nn.Context())
    out = np.asarray(y)
    np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1.0, atol=1e-3)
    assert not layer.divergent_state()


def test_embedding_lookup_and_shape():
    layer = nn.Embedding(10, 6)
    params, state = layer.init(KEY, jnp.zeros((2, 3), jnp.int32))
    assert params["weight"].shape == (10, 6)  # torch (num_embeddings, dim)
    ids = jnp.asarray([[1, 4], [9, 0]], jnp.int32)
    y, _ = layer.apply(params, state, ids, nn.Context())
    assert y.shape == (2, 2, 6)
    np.testing.assert_array_equal(
        np.asarray(y[1, 0]), np.asarray(params["weight"][9])
    )
    assert not layer.divergent_state()
