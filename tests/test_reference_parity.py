"""THE north-star metric (BASELINE.json): loss-curve parity between tpuddp
data-parallel training and the reference stack's real DDP loop — 2 torch
processes over gloo (the reference's own CPU backend rung,
multi-GPU-training-torch.py:36-37), same data, same initial weights, same
hyperparameters, compared epoch by epoch."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EPOCHS, BATCH, LR = 4, 16, 1e-3
N, FEATURES = 256, 192


@pytest.mark.slow
def test_loss_curve_parity_vs_torch_ddp(tmp_path, cpu_devices):
    import jax
    import jax.numpy as jnp
    import torch

    from tpuddp import nn as tnn
    from tpuddp import optim
    from tpuddp.data import ShardedDataLoader
    from tpuddp.parallel import make_mesh
    from tpuddp.parallel.ddp import DistributedDataParallel
    from tpuddp.training.step import accumulate_metrics, finalize_metrics

    rng = np.random.RandomState(3)
    labels = rng.randint(0, 10, N).astype(np.int64)
    means = rng.randn(10, FEATURES).astype(np.float32)
    x = (means[labels] + 0.5 * rng.randn(N, FEATURES)).astype(np.float32)
    data_path = tmp_path / "data.npz"
    np.savez(data_path, x=x, y=labels)

    # --- reference run: 2-process torch DDP over gloo ---
    out_path = tmp_path / "torch_curve.json"
    env = dict(os.environ)
    env["MASTER_PORT"] = "29517"
    # torch-only workers: keep them off the TPU tunnel (sitecustomize would
    # otherwise register a client in every spawned python)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "_torch_ddp_worker.py"),
         str(data_path), str(out_path), str(EPOCHS), str(BATCH), str(LR)],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    torch_curve = json.load(open(out_path))["train_loss"]

    # --- tpuddp run: 2-device DP mesh, identical init/hparams/data split ---
    class ArrayDataset:
        def __init__(self, images, labels):
            self.images, self.labels = images, labels.astype(np.int32)

        def __len__(self):
            return len(self.labels)

        def get_batch(self, idx):
            i = np.asarray(idx)
            return self.images[i], self.labels[i]

    mesh = make_mesh(cpu_devices[:2])
    sd = torch.load(str(out_path) + ".init.pt", weights_only=True)

    def tpuddp_curve(weight_update_sharding: bool):
        model = tnn.Sequential(
            tnn.Linear(256), tnn.ReLU(), tnn.Linear(128), tnn.ReLU(), tnn.Linear(10)
        )
        ddp = DistributedDataParallel(
            model, optim.Adam(LR), tnn.CrossEntropyLoss(), mesh=mesh,
            weight_update_sharding=weight_update_sharding,
        )
        state = ddp.init_state(jax.random.key(0), jnp.zeros((1, FEATURES)))

        # graft the torch run's initial weights (Linear: (out,in) -> (in,out))
        params = list(state.params)
        for layer_idx, torch_idx in [(0, 0), (2, 2), (4, 4)]:
            params[layer_idx] = {
                "weight": jnp.asarray(sd[f"{torch_idx}.weight"].numpy().T),
                "bias": jnp.asarray(sd[f"{torch_idx}.bias"].numpy()),
            }
        state = state.__class__(
            params=tuple(params),
            model_state=state.model_state,
            opt_state=state.opt_state,
            step=state.step,
            rng=state.rng,
        )

        loader = ShardedDataLoader(ArrayDataset(x, labels), BATCH, mesh, shuffle=False)
        curve = []
        for _ in range(EPOCHS):
            acc = None
            for host_batch in loader:
                state, m = ddp.train_step(state, ddp.shard(host_batch))
                acc = accumulate_metrics(acc, m)
            final = finalize_metrics(acc)
            curve.append(final["loss_sum"] / final["n"])
        return curve

    # the north star: loss-curve parity with the reference's DDP baseline —
    # for BOTH optimizer layouts (replicated update AND weight-update-sharded)
    for wus in (False, True):
        ours_curve = tpuddp_curve(wus)
        np.testing.assert_allclose(ours_curve, torch_curve, rtol=2e-3)
        # and the model actually learned
        assert ours_curve[-1] < ours_curve[0] * 0.7
