"""DistributedSampler semantics (SURVEY.md §2b #12) — the reference's manual
shard-disjointness probe (multi-GPU-training-torch.py:112-115) turned into
real asserts, plus padding and set_epoch contracts."""

import numpy as np
import pytest

from tpuddp.parallel import DistributedSampler


def shards(n, world, **kw):
    samplers = [DistributedSampler(n, world, r, **kw) for r in range(world)]
    return samplers, [s.local_indices() for s in samplers]


def test_shards_disjoint_and_cover():
    _, parts = shards(64, 8, seed=1)
    all_idx = np.concatenate(parts)
    assert len(all_idx) == 64
    assert set(all_idx.tolist()) == set(range(64))
    for i in range(8):
        for j in range(i + 1, 8):
            assert not set(parts[i]) & set(parts[j])


def test_equal_shard_sizes_with_padding():
    # 100 samples over 8 ranks -> ceil = 13 each, 104 total with 4 repeats
    samplers, parts = shards(100, 8)
    assert all(len(p) == 13 for p in parts)
    assert all(len(s) == 13 for s in samplers)
    counts = np.bincount(np.concatenate(parts), minlength=100)
    assert counts.min() == 1 and counts.max() == 2 and counts.sum() == 104


def test_padding_wraps_head_samples_when_not_shuffled():
    s = DistributedSampler(10, 4, 0, shuffle=False)
    # global order is 0..9 + [0, 1] pad; rank 0 takes stride-4: [0, 4, 8]
    assert s.local_indices().tolist() == [0, 4, 8]
    s3 = DistributedSampler(10, 4, 3, shuffle=False)
    assert s3.local_indices().tolist() == [3, 7, 1]  # 1 is the wrapped pad


def test_pad_larger_than_dataset():
    s = DistributedSampler(3, 8, 7, shuffle=False)
    assert len(s.local_indices()) == 1
    all_idx = np.concatenate([DistributedSampler(3, 8, r, shuffle=False).local_indices() for r in range(8)])
    assert all_idx.tolist() == [0, 1, 2, 0, 1, 2, 0, 1]


def test_drop_last_trims():
    samplers, parts = shards(100, 8, drop_last=True)
    assert all(len(p) == 12 for p in parts)
    assert len(np.concatenate(parts)) == 96


def test_set_epoch_reshuffles_and_is_deterministic():
    s = DistributedSampler(50, 2, 0, seed=7)
    s.set_epoch(0)
    e0 = s.local_indices()
    s.set_epoch(1)
    e1 = s.local_indices()
    assert not np.array_equal(e0, e1)  # reshuffled
    s.set_epoch(0)
    assert np.array_equal(s.local_indices(), e0)  # deterministic replay


def test_without_set_epoch_order_repeats():
    # The pitfall the reference's toggle reproduces (README.md:82-84).
    s = DistributedSampler(50, 2, 0, seed=7)
    a = s.local_indices()
    b = s.local_indices()
    assert np.array_equal(a, b)


def test_ranks_share_permutation():
    # same seed+epoch => same global permutation, different strided slices
    a = DistributedSampler(16, 4, 1, seed=3)
    b = DistributedSampler(16, 4, 1, seed=3)
    assert np.array_equal(a.local_indices(), b.local_indices())


def test_no_shuffle_is_strided_arange():
    s = DistributedSampler(8, 4, 2, shuffle=False)
    assert list(s) == [2, 6]


def test_validation():
    with pytest.raises(ValueError):
        DistributedSampler(10, 4, 4)
    with pytest.raises(ValueError):
        DistributedSampler(10, None, None)


def test_len_protocol_accepts_dataset_object():
    class DS:
        def __len__(self):
            return 12

    s = DistributedSampler(DS(), 4, 0, shuffle=False)
    assert len(s) == 3


def test_order_source_replaces_permutation_keeps_discipline():
    """order_source (the mechanism behind preserving a user sampler in
    Accelerator.prepare) replaces the seeded permutation while the pad-by-wrap
    and strided-disjoint-shard rules stay authoritative here."""
    order = [5, 3, 8, 1, 0, 7, 2]  # deliberate custom order, len 7
    shards = [
        list(DistributedSampler(10, 4, r, order_source=order)) for r in range(4)
    ]
    # pad-by-wrap to 8: [5, 3, 8, 1, 0, 7, 2, 5]; rank r takes order[r::4]
    assert shards == [[5, 0], [3, 7], [8, 2], [1, 5]]
    # sizes derive from the order's length (a subset), not the dataset's
    assert DistributedSampler(10, 4, 0, order_source=order).num_samples == 2
    assert DistributedSampler(10, 4, 0, order_source=order, drop_last=True).num_samples == 1


def test_order_source_length_change_raises():
    class Shrinking:
        def __init__(self):
            self.n = 6

        def __len__(self):
            return self.n

        def __iter__(self):
            return iter(range(self.n))

    src = Shrinking()
    s = DistributedSampler(10, 2, 0, order_source=src)
    src.n = 4  # sampler sized for 6; producing 4 must fail loudly
    with pytest.raises(ValueError, match="declared len"):
        s.local_indices()
