"""Checkpoint/resume (SURVEY.md §2b #18): rank-0 naming parity, atomic save,
typed-PRNG-key round-trip, resume helper the reference lacks."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from tpuddp import optim
from tpuddp.models import ToyMLP
from tpuddp.training import checkpoint as ckpt
from tpuddp.training.train_state import create_train_state


def make_state():
    model = ToyMLP(hidden=(8,))
    return model, create_train_state(
        model, optim.Adam(1e-3), jax.random.key(0), jnp.zeros((1, 4, 4, 3))
    )


def assert_tree_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)), a, b
    )


def test_round_trip_train_state(tmp_path):
    _, state = make_state()
    path = ckpt.save(str(tmp_path / "s.npz"), state)
    restored = ckpt.load(path, state)
    assert_tree_equal(restored.params, state.params)
    assert_tree_equal(restored.opt_state, state.opt_state)
    # typed PRNG key survives
    assert jnp.array_equal(
        jax.random.key_data(restored.rng), jax.random.key_data(state.rng)
    )


def test_save_on_main_naming_and_barrier(tmp_path):
    _, state = make_state()
    path = ckpt.save_on_main(str(tmp_path), epoch=5, tree=state)
    assert os.path.basename(path) == "ckpt_5.npz"  # reference naming parity
    assert os.path.exists(path)


def test_latest_and_restore(tmp_path):
    _, state = make_state()
    for e in (0, 5, 10):
        ckpt.save_on_main(str(tmp_path), e, state)
    path, epoch = ckpt.latest(str(tmp_path))
    assert epoch == 10 and path.endswith("ckpt_10.npz")
    restored, next_epoch = ckpt.restore_latest(str(tmp_path), state)
    assert next_epoch == 11
    assert_tree_equal(restored.params, state.params)


def test_restore_latest_empty_dir(tmp_path):
    _, state = make_state()
    restored, next_epoch = ckpt.restore_latest(str(tmp_path / "nope"), state)
    assert next_epoch == 0
    assert restored is state


def test_missing_leaf_raises(tmp_path):
    _, state = make_state()
    path = ckpt.save(str(tmp_path / "s.npz"), {"params": state.params})
    try:
        ckpt.load(path, state)
    except KeyError as e:
        assert "missing leaf" in str(e)
    else:
        raise AssertionError("expected KeyError")


def test_shape_mismatch_raises_named_leaf(tmp_path):
    """A same-layout checkpoint with different widths (e.g. a 12-class head
    into a 10-class model) must fail loudly, like torch load_state_dict."""
    path = ckpt.save(str(tmp_path / "s.npz"), {"w": jnp.zeros((12, 4))})
    try:
        ckpt.load(path, {"w": jnp.zeros((10, 4))})
    except ValueError as e:
        assert "['w']" in str(e)  # the offending leaf is named (keystr form)
        assert "(12, 4)" in str(e) and "(10, 4)" in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_dtype_mismatch_raises(tmp_path):
    path = ckpt.save(str(tmp_path / "s.npz"), {"w": jnp.zeros((4,), jnp.bfloat16)})
    try:
        ckpt.load(path, {"w": jnp.zeros((4,), jnp.float32)})
    except ValueError as e:
        assert "dtype" in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_prng_key_shape_mismatch_raises(tmp_path):
    path = ckpt.save(str(tmp_path / "s.npz"), {"rng": jax.random.split(jax.random.key(0), 4)})
    try:
        ckpt.load(path, {"rng": jax.random.key(0)})
    except ValueError as e:
        assert "key-data shape" in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_no_tmp_file_left_behind(tmp_path):
    _, state = make_state()
    ckpt.save(str(tmp_path / "s.npz"), state)
    # data file + its integrity manifest, and no .tmp staging remnants
    assert sorted(os.listdir(tmp_path)) == ["s.npz", "s.npz.sha256"]
