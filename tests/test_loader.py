"""Host loader contracts: static shapes, padding masks, per-replica shard
assembly in mesh order (SURVEY.md §2b #12/#14 consequences)."""

import numpy as np

from tpuddp.data import DataLoader, ShardedDataLoader, SyntheticClassification
from tpuddp.parallel import DistributedSampler, make_mesh


def test_dataloader_batches_and_final_padding():
    ds = SyntheticClassification(n=10, shape=(4,), seed=0)
    loader = DataLoader(ds, batch_size=4)
    batches = list(loader)
    assert len(batches) == len(loader) == 3
    x, y, w = batches[-1]
    assert x.shape == (4, 4) and y.shape == (4,) and w.shape == (4,)
    np.testing.assert_array_equal(w, [1, 1, 0, 0])
    assert all(b[2].sum() == 4 for b in batches[:-1])


def test_dataloader_drop_last():
    ds = SyntheticClassification(n=10, shape=(4,))
    loader = DataLoader(ds, batch_size=4, drop_last=True)
    assert len(list(loader)) == 2


def test_dataloader_shuffle_reshuffles_with_epoch():
    ds = SyntheticClassification(n=32, shape=(2,))
    loader = DataLoader(ds, batch_size=32, shuffle=True, seed=5)
    loader.set_epoch(0)
    (x0, y0, _), = list(loader)
    loader.set_epoch(1)
    (x1, y1, _), = list(loader)
    assert not np.array_equal(y0, y1)
    loader.set_epoch(0)
    (x0b, y0b, _), = list(loader)
    np.testing.assert_array_equal(y0, y0b)


def test_dataloader_with_sampler_shards():
    ds = SyntheticClassification(n=64, shape=(2,))
    loaders = [
        DataLoader(ds, batch_size=8, sampler=DistributedSampler(64, 4, r, shuffle=False))
        for r in range(4)
    ]
    assert all(len(l) == 2 for l in loaders)
    seen = []
    for l in loaders:
        for x, y, w in l:
            assert w.sum() == 8
            seen.extend(y.tolist())
    assert sorted(seen) == sorted(ds.labels.tolist())


def test_sharded_loader_local_batch_layout(cpu_devices):
    mesh = make_mesh(cpu_devices[:4])
    ds = SyntheticClassification(n=64, shape=(2,), seed=1)
    loader = ShardedDataLoader(ds, batch_size=4, mesh=mesh, shuffle=False)
    assert loader.world_size == 4
    assert loader.local_ranks == [0, 1, 2, 3]
    assert len(loader) == 4  # 16 per replica / 4
    x, y, w = next(iter(loader))
    assert x.shape == (16, 2)
    # replica r's first sample is global index r (stride-4 sharding, no shuffle)
    np.testing.assert_array_equal(y[::4], ds.labels[[0, 1, 2, 3]])


def test_sharded_loader_covers_dataset_disjointly(cpu_devices):
    mesh = make_mesh(cpu_devices)
    ds = SyntheticClassification(n=128, shape=(2,), seed=2)
    loader = ShardedDataLoader(ds, batch_size=4, mesh=mesh, shuffle=True, seed=3)
    loader.set_epoch(0)
    idx_seen = []
    for x, y, w in loader:
        assert w.sum() == 32  # all real, 128 divisible
        idx_seen.extend(y.tolist())
    assert len(idx_seen) == 128


def test_sharded_loader_padding_mask(cpu_devices):
    mesh = make_mesh(cpu_devices)
    ds = SyntheticClassification(n=100, shape=(2,))
    loader = ShardedDataLoader(ds, batch_size=8, mesh=mesh, shuffle=False)
    # 100/8 replicas -> 13 samples each -> 2 steps (8 + 5real/3pad)
    assert len(loader) == 2
    batches = list(loader)
    _, _, w_last = batches[-1]
    assert w_last.sum() == 8 * 5  # 5 real per replica in final batch
    total_real = sum(b[2].sum() for b in batches)
    assert total_real == 104  # 100 + 4 wrap-pad duplicates (sampler padding)


def test_probe_fingerprint_mentions_each_replica(cpu_devices):
    mesh = make_mesh(cpu_devices[:2])
    ds = SyntheticClassification(n=16, shape=(8,))
    loader = ShardedDataLoader(ds, batch_size=4, mesh=mesh, shuffle=False)
    x, _, _ = next(iter(loader))
    s = loader.probe_fingerprint(x)
    assert "replica 0" in s and "replica 1" in s
