"""Reference-stack oracle: the reference's native DDP training loop
(multi-GPU-training-torch.py), run for real — 2 processes, torch.distributed
over gloo (the reference's own CPU fallback, :36-37), DistributedSampler,
DDP-wrapped MLP, Adam, sample-weighted loss sums all_reduced per epoch.

Writes initial weights + the per-epoch loss curve for the parity comparison.

Usage: python _torch_ddp_worker.py <data.npz> <out.json> <epochs> <batch> <lr>
"""

import json
import os
import sys

import numpy as np
import torch
import torch.distributed as dist
import torch.multiprocessing as mp
import torch.nn as nn
from torch.nn.parallel import DistributedDataParallel as DDP
from torch.utils.data import DataLoader, DistributedSampler, TensorDataset

WORLD = 2


def make_model(in_features: int):
    torch.manual_seed(1234)
    return nn.Sequential(
        nn.Linear(in_features, 256), nn.ReLU(),
        nn.Linear(256, 128), nn.ReLU(),
        nn.Linear(128, 10),
    )


def worker(rank, data_path, out_path, epochs, batch, lr, weights_path):
    os.environ["MASTER_ADDR"] = "127.0.0.1"
    os.environ.setdefault("MASTER_PORT", "29512")
    dist.init_process_group("gloo", rank=rank, world_size=WORLD)

    data = np.load(data_path)
    x = torch.from_numpy(data["x"])
    y = torch.from_numpy(data["y"]).long()
    ds = TensorDataset(x, y)

    model = make_model(x.shape[1])
    if rank == 0:
        torch.save(model.state_dict(), weights_path)
    ddp_model = DDP(model)
    criterion = nn.CrossEntropyLoss()
    optimizer = torch.optim.Adam(ddp_model.parameters(), lr=lr)

    sampler = DistributedSampler(ds, num_replicas=WORLD, rank=rank, shuffle=False)
    loader = DataLoader(ds, batch_size=batch, sampler=sampler)

    curve = []
    for epoch in range(epochs):
        total = torch.zeros(1)
        n = torch.zeros(1)
        for inputs, labels in loader:
            optimizer.zero_grad()
            loss = criterion(ddp_model(inputs), labels)
            loss.backward()
            optimizer.step()
            bs = inputs.shape[0]
            total += loss.item() * bs
            n += bs
        dist.all_reduce(total)
        dist.all_reduce(n)
        curve.append(float(total.item() / n.item()))

    if rank == 0:
        with open(out_path, "w") as f:
            json.dump({"train_loss": curve}, f)
    dist.barrier()
    dist.destroy_process_group()


if __name__ == "__main__":
    data_path, out_path, epochs, batch, lr = sys.argv[1:6]
    weights_path = out_path + ".init.pt"
    mp.spawn(
        worker,
        args=(data_path, out_path, int(epochs), int(batch), float(lr), weights_path),
        nprocs=WORLD,
        join=True,
    )
