"""Async pipelined runner (ISSUE 8, tpuddp/training/pipeline.py): bitwise
parity pipelined-vs-synchronous at every depth, preemption/guard composition,
HLO identity, PrefetchLoader hardening, FusedEvaluator staging, and the
schema-v3 occupancy fields."""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import optim
from tpuddp.data import (
    DataLoader,
    PrefetchLoader,
    ShardedDataLoader,
    SyntheticClassification,
)
from tpuddp.models import ToyMLP
from tpuddp.nn import CrossEntropyLoss
from tpuddp.observability import schema as schema_mod
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.resilience import guard as guard_lib
from tpuddp.training import pipeline as pipe


def _np(leaf):
    """Comparable numpy view of any state leaf (typed PRNG keys included)."""
    try:
        if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            return np.asarray(jax.random.key_data(leaf))
    except Exception:
        pass
    return np.asarray(leaf)


def assert_states_bitwise_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        xa, ya = _np(x), _np(y)
        assert xa.dtype == ya.dtype
        np.testing.assert_array_equal(xa, ya)


def _make_ddp(mesh, **kw):
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh,
        **kw,
    )
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    return ddp, state


def _loader(mesh, n=640, seed=0, workers=0):
    ds = SyntheticClassification(n=n, shape=(8, 8, 3), seed=seed)
    loader = ShardedDataLoader(ds, 8, mesh, shuffle=True, seed=seed)
    if workers:
        loader = PrefetchLoader(loader, workers=workers)
    loader.set_epoch(0)
    return loader


def _train_epoch(mesh, cfg, scan_k=4, workers=0, inject_cb=None, **ddp_kw):
    ddp, state = _make_ddp(mesh, **ddp_kw)
    loader = _loader(mesh, workers=workers)
    state, acc, interrupted = pipe.run_pass(
        ddp, state, loader, scan_k, ddp.train_step, ddp.train_step_many,
        cfg=cfg, inject_cb=inject_cb,
    )
    assert not interrupted
    return ddp, jax.device_get(state), jax.device_get(acc)


# ------------------------------------------------------------- config knob --


def test_resolve_pipeline_contract():
    assert pipe.resolve_pipeline(None) == pipe.DEFAULT
    assert pipe.resolve_pipeline(True) == pipe.DEFAULT
    sync = pipe.resolve_pipeline(False)
    assert sync.depth == 1 and sync.host_workers == 0 and sync.sync_readback
    # device_augment must NOT differ between on and off: augment placement
    # changes the compiled program, and the A/B must stay HLO-identical
    assert sync.device_augment == pipe.DEFAULT.device_augment
    got = pipe.resolve_pipeline({"depth": 4, "host_workers": 0})
    assert got.depth == 4 and got.host_workers == 0
    with pytest.raises(ValueError, match="unknown training.pipeline"):
        pipe.resolve_pipeline({"dpeth": 4})
    with pytest.raises(ValueError, match="depth"):
        pipe.resolve_pipeline({"depth": 0})
    with pytest.raises(ValueError, match="host_workers"):
        pipe.resolve_pipeline({"host_workers": -1})
    with pytest.raises(ValueError, match="true/false or a mapping"):
        pipe.resolve_pipeline("deep")


def test_staging_depth_byte_capped():
    from tpuddp.utils.batching import STAGE_BYTES_BUDGET

    assert pipe.staging_depth_for(4, None) == 4
    assert pipe.staging_depth_for(4, 1024) == 4
    assert pipe.staging_depth_for(4, STAGE_BYTES_BUDGET // 2) == 2
    assert pipe.staging_depth_for(4, STAGE_BYTES_BUDGET * 2) == 1


# ----------------------------------------------------- bitwise parity core --


def test_pipelined_bitwise_parity_across_depths(mesh):
    """Depth ∈ {1, 2, 4} and the synchronous reference all land the exact
    same params/opt-state after an epoch with a scan remainder (10 batches,
    scan_k=4 -> 2 chunks + 2 single-step remainders)."""
    _, ref_state, ref_acc = _train_epoch(mesh, pipe.SYNCHRONOUS)
    for depth in (1, 2, 4):
        cfg = pipe.PipelineConfig(depth=depth, host_workers=0)
        _, state, acc = _train_epoch(mesh, cfg)
        assert_states_bitwise_equal(ref_state, state)
        assert_states_bitwise_equal(ref_acc, acc)


def test_pipelined_parity_with_prefetch_workers(mesh):
    """The worker-pool loader feeds the identical stream: pipelined run with
    host_workers=3 is bitwise-equal to the synchronous inline run."""
    _, ref_state, ref_acc = _train_epoch(mesh, pipe.SYNCHRONOUS)
    cfg = pipe.PipelineConfig(depth=2, host_workers=3)
    _, state, acc = _train_epoch(mesh, cfg, workers=3)
    assert_states_bitwise_equal(ref_state, state)
    assert_states_bitwise_equal(ref_acc, acc)


def test_pipelined_parity_wus_comm_state(mesh):
    """Weight-update sharding + bf16_ef comm hook (the richest TrainState:
    flat sharded moments + per-replica EF residual) stays bitwise across
    depths — comm_state included."""
    _, ref_state, _ = _train_epoch(
        mesh, pipe.SYNCHRONOUS,
        weight_update_sharding=True, comm_hook="bf16_ef",
    )
    for depth in (2, 4):
        cfg = pipe.PipelineConfig(depth=depth, host_workers=0)
        _, state, _ = _train_epoch(
            mesh, cfg, weight_update_sharding=True, comm_hook="bf16_ef",
        )
        assert_states_bitwise_equal(ref_state, state)


def test_pipelined_parity_managed(cpu_devices):
    """Managed (Accelerator) path: the pipelined loader stack (PrefetchLoader
    workers + StagedUploadLoader) plus the deferred readback drain produces
    bitwise-identical params/opt-state to plain inline loading."""
    from tpuddp.accelerate import Accelerator, StagedUploadLoader
    from tpuddp.nn import CrossEntropyLoss as CE
    from train_accelerate import train

    def run(pipelined):
        acc = Accelerator(
            mesh=make_mesh(cpu_devices[:4]), seed=0, fuse_steps=4
        )
        ds = SyntheticClassification(n=256, shape=(8, 8, 3), seed=1)
        model, opt, loader = acc.prepare(
            ToyMLP(hidden=(16,)),
            optim.Adam(1e-2),
            DataLoader(ds, batch_size=8, shuffle=True),
        )
        if pipelined:
            loader = StagedUploadLoader(PrefetchLoader(loader, workers=2))
        loader.set_epoch(0)
        loss, n = train(model, loader, CE(), opt, acc, augment=None)
        return model.params, opt.opt_state, loss, n

    p_ref, o_ref, loss_ref, n_ref = run(False)
    p_pipe, o_pipe, loss_pipe, n_pipe = run(True)
    assert (loss_ref, n_ref) == (loss_pipe, n_pipe)
    assert_states_bitwise_equal(
        jax.device_get((p_ref, o_ref)), jax.device_get((p_pipe, o_pipe))
    )


def test_pipelined_guard_skip_parity(mesh):
    """A nan-poisoned batch is firewalled identically at every depth: same
    skip counters, bitwise-identical state (the skipped update is a no-op on
    both paths)."""

    def make_inject():
        seen = {"i": 0}

        def inject(host_batch):
            i = seen["i"]
            seen["i"] += 1
            if i == 3:
                x, y, w = host_batch
                x = np.asarray(x, np.float32).copy()
                x[0, 0, 0, 0] = np.nan
                return x, y, w
            return host_batch

        return inject

    _, ref_state, _ = _train_epoch(
        mesh, pipe.SYNCHRONOUS, inject_cb=make_inject(), guard=True,
    )
    total_ref, consec_ref = guard_lib.read_skip_counters(ref_state)
    assert total_ref >= 1  # the poison was seen and firewalled
    for depth in (2, 4):
        cfg = pipe.PipelineConfig(depth=depth, host_workers=0)
        _, state, _ = _train_epoch(
            mesh, cfg, inject_cb=make_inject(), guard=True,
        )
        assert guard_lib.read_skip_counters(state) == (total_ref, consec_ref)
        assert_states_bitwise_equal(ref_state, state)


def test_midepoch_preempt_no_batch_lost_or_double_applied(mesh):
    """An interrupted pass returns the state of exactly the dispatches it
    issued: replaying the recorded dispatch sequence synchronously from the
    same init lands the identical state — nothing in flight was lost, nothing
    was applied twice."""
    for depth in (1, 3):
        ddp, state0 = _make_ddp(mesh)
        issued = []

        def rec_one(s, b):
            issued.append(("one", b))
            return ddp.train_step(s, b)

        def rec_many(s, b):
            issued.append(("many", b))
            return ddp.train_step_many(s, b)

        seen = {"n": 0}

        def probe(i, b):
            seen["n"] = i + 1

        loader = _loader(mesh)
        state, acc, interrupted = pipe.run_pass(
            ddp, state0, loader, 2, rec_one, rec_many,
            cfg=pipe.PipelineConfig(depth=depth, host_workers=0),
            probe_cb=probe, poll=lambda: seen["n"] >= 7,
        )
        assert interrupted
        # replay: fresh identical init, the same dispatches, synchronously
        ddp2, replay = _make_ddp(mesh)
        for kind, b in issued:
            step = ddp2.train_step if kind == "one" else ddp2.train_step_many
            replay, _ = step(replay, b)
        assert_states_bitwise_equal(
            jax.device_get(state), jax.device_get(replay)
        )


def test_hlo_identity_pipeline_on_off(mesh):
    """The pipeline never enters program construction: the lowered scan-step
    HLO after a pipelined pass is byte-identical to the synchronous run's,
    and both passes dispatched the identical shape sequence."""
    shapes = {}

    def run(key, cfg):
        ddp, state = _make_ddp(mesh)
        seq = []

        def rec_one(s, b):
            seq.append(("one", jax.tree_util.tree_map(np.shape, b)))
            return ddp.train_step(s, b)

        def rec_many(s, b):
            seq.append(("many", jax.tree_util.tree_map(np.shape, b)))
            return ddp.train_step_many(s, b)

        loader = _loader(mesh)
        state, _, _ = pipe.run_pass(
            ddp, state, loader, 4, rec_one, rec_many, cfg=cfg,
        )
        shapes[key] = seq
        # lower the exact program the pass used, against a real staged chunk
        state_struct = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(np.shape(l), l.dtype), state
        )
        from tpuddp.training.step import stack_batches

        chunk = []
        for b in _loader(mesh):
            chunk.append(b)
            if len(chunk) == 4:
                break
        stacked = ddp.shard_stacked(stack_batches(chunk))
        lowered = jax.jit(
            lambda s, b: ddp.train_step_many(s, b)
        ).lower(state_struct, stacked)
        return lowered.as_text()

    on = run("on", pipe.PipelineConfig(depth=4, host_workers=0))
    off = run("off", pipe.SYNCHRONOUS)
    assert shapes["on"] == shapes["off"]
    assert on == off


# ------------------------------------------------------ deferred readback --


def test_readback_drain_order_and_inflight():
    drain = pipe._ReadbackDrain()

    class FakeLeaf:
        def __init__(self, ready):
            self._ready = ready
            self.shape, self.dtype = (), np.float32

        def is_ready(self):
            return self._ready

    # numpy metrics (no is_ready): folded eagerly, in order
    drain.offer({"loss_sum": np.asarray([1.0])})
    drain.offer({"loss_sum": np.asarray([2.0])})
    assert drain.inflight == 0
    out = drain.drain()
    np.testing.assert_array_equal(np.asarray(out["loss_sum"]), [3.0])
    # an in-flight leaf defers the fold and is visible as depth
    d2 = pipe._ReadbackDrain()
    d2.offer({"m": FakeLeaf(ready=False)})
    assert d2.inflight == 1


def test_stall_clock_take_semantics():
    c = pipe.StallClock()
    c.add(0.5)
    c.add(0.25)
    assert c.total == pytest.approx(0.75)
    assert c.take() == pytest.approx(0.75)
    assert c.take() == 0.0
    assert c.total == pytest.approx(0.75)


# ------------------------------------------------ PrefetchLoader hardening --


def test_prefetch_pool_identical_stream(cpu_devices):
    mesh4 = make_mesh(cpu_devices[:4])
    ds = SyntheticClassification(n=100, shape=(4, 4, 3), seed=3)
    base = ShardedDataLoader(ds, 4, mesh4, shuffle=True, seed=1)
    pool = PrefetchLoader(
        ShardedDataLoader(ds, 4, mesh4, shuffle=True, seed=1), workers=4
    )
    for epoch in range(2):
        base.set_epoch(epoch)
        pool.set_epoch(epoch)
        got = list(pool)
        want = list(base)
        assert len(got) == len(want)
        for (xa, ya, wa), (xb, yb, wb) in zip(want, got):
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)
            np.testing.assert_array_equal(wa, wb)


class _ExplodingPlanLoader:
    """make_batch_plan protocol whose fetch dies at batch 2 — the worker-pool
    exception path."""

    def __len__(self):
        return 6

    def set_epoch(self, epoch):
        pass

    def make_batch_plan(self):
        def fetch(s):
            if s == 2:
                return self._boom()
            return (np.zeros((4, 2)), np.zeros(4, np.int32), np.ones(4, np.float32))

        return 6, fetch

    def _boom(self):
        raise RuntimeError("decode failed in worker")


def test_prefetch_pool_propagates_exception_with_traceback():
    pre = PrefetchLoader(_ExplodingPlanLoader(), workers=3)
    with pytest.raises(RuntimeError, match="decode failed in worker") as ei:
        list(pre)
    # the ORIGINAL producer-side frames survive the thread hop
    frames = []
    tb = ei.value.__traceback__
    while tb is not None:
        frames.append(tb.tb_frame.f_code.co_name)
        tb = tb.tb_next
    assert "_boom" in frames and "fetch" in frames


def test_prefetch_serial_propagates_exception_with_traceback():
    class Exploding:
        def __len__(self):
            return 3

        def __iter__(self):
            yield (np.zeros(1), np.zeros(1), np.ones(1))
            raise RuntimeError("loader blew up mid-epoch")

    pre = PrefetchLoader(Exploding(), workers=1)
    with pytest.raises(RuntimeError, match="blew up mid-epoch") as ei:
        list(pre)
    frames = []
    tb = ei.value.__traceback__
    while tb is not None:
        frames.append(tb.tb_frame.f_code.co_name)
        tb = tb.tb_next
    assert "__iter__" in frames  # the producer generator's frame


def _prefetch_threads():
    return [
        t for t in threading.enumerate() if t.name.startswith("tpuddp-prefetch")
    ]


@pytest.mark.parametrize("workers", [1, 3])
def test_prefetch_no_thread_leak_on_partial_iteration(workers):
    """Abandoning the iterator mid-epoch (the preemption-drain shape) must
    reap every worker — including one blocked on a full queue."""
    ds = SyntheticClassification(n=400, shape=(4, 4, 3), seed=0)
    pre = PrefetchLoader(DataLoader(ds, batch_size=4), depth=2, workers=workers)
    it = iter(pre)
    next(it)
    next(it)
    it.close()  # GeneratorExit -> the finally block reaps the pool
    deadline = time.monotonic() + 5
    while _prefetch_threads() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert _prefetch_threads() == []


def test_prefetch_effective_depth_byte_capped():
    from tpuddp.utils.batching import STAGE_BYTES_BUDGET

    class Huge:
        batch_nbytes = STAGE_BYTES_BUDGET  # one batch fills the budget

        def __len__(self):
            return 1

    class Small:
        batch_nbytes = 1024

        def __len__(self):
            return 1

    class NoBytes:
        def __len__(self):
            return 1

    assert PrefetchLoader(Huge(), depth=8).effective_depth() == 1
    assert PrefetchLoader(Small(), depth=8).effective_depth() == 8
    # unknowable batch bytes -> the configured depth survives
    assert PrefetchLoader(NoBytes(), depth=3).effective_depth() == 3


# -------------------------------------------------- FusedEvaluator staging --


def test_fused_evaluator_staged_uploads_bitwise_on_ragged_stream(cpu_devices):
    """Eval staging (uploads issued at add-time) must not change metrics —
    ragged final buckets included."""
    from tpuddp.accelerate import Accelerator, FusedEvaluator
    from tpuddp.nn import CrossEntropyLoss as CE

    rng = np.random.RandomState(0)
    batches = [
        (rng.randn(n, 8, 8, 3).astype(np.float32),
         rng.randint(0, 10, n).astype(np.int32),
         np.ones(n, np.float32))
        for n in (8, 8, 8, 5)  # ragged tail
    ]

    def run(stage):
        acc = Accelerator(mesh=make_mesh(cpu_devices[:2]), seed=0)
        model = acc.prepare(ToyMLP(hidden=(16,)))
        model.eval()
        model(batches[0][0][:1])  # init
        ev = FusedEvaluator(model, CE(), fuse_steps=3, stage_uploads=stage)
        for x, y, w in batches:
            ev.add(x, y, w)
        return ev.finalize()

    loss_a, correct_a, n_a = run(False)
    loss_b, correct_b, n_b = run(True)
    assert (correct_a, n_a) == (correct_b, n_b)
    assert loss_a == loss_b  # bitwise: same program, same inputs


# ------------------------------------------------------- schema/telemetry --


def test_step_stats_v3_requires_occupancy_fields():
    base = {
        "epoch": 0, "step_start": 0, "steps": 4,
        "step_time_ms_p50": 1.0, "step_time_ms_p95": 1.0,
        "step_time_ms_p99": 1.0, "step_time_ms_max": 1.0,
        "samples_per_sec": 10.0,
    }
    occ = {"host_stall_ms": 0.1, "inflight_depth": 2, "staging_queue_depth": 1}
    good = schema_mod.stamp("step_stats", {**base, **occ})
    assert schema_mod.validate_record(good) == []
    missing = schema_mod.stamp("step_stats", base)
    errs = schema_mod.validate_record(missing)
    assert any("host_stall_ms" in e for e in errs)
    # a v2 record (pre-pipeline history) without them stays valid
    legacy = {**base, "type": "step_stats", "schema_version": 2}
    assert schema_mod.validate_record(legacy) == []


def test_history_carries_occupancy_fields(mesh, tmp_path):
    """End-to-end: a pipelined epoch-driver run writes step_stats windows
    carrying the occupancy fields and epoch rows carrying host_stall_ms, and
    the whole file validates at schema v3."""
    from tpuddp.observability import schema
    from tpuddp.training.loop import run_training_loop

    ds = SyntheticClassification(n=256, shape=(8, 8, 3), seed=0)
    loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    test_loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh
    )
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    run_training_loop(
        ddp, state, loader, test_loader, str(tmp_path),
        num_epochs=1, checkpoint_epoch=1, step_stats_every=2, scan_steps=2,
        pipeline={"depth": 2, "host_workers": 0},
        log=lambda *_: None,
    )
    records = [
        json.loads(l)
        for l in (tmp_path / "history.jsonl").read_text().splitlines()
    ]
    assert schema.validate_history_records(records) == []
    meta = records[0]
    assert meta["pipeline"]["depth"] == 2
    windows = [r for r in records if r["type"] == "step_stats"]
    assert windows
    for w in windows:
        assert w["host_stall_ms"] >= 0
        assert w["staging_queue_depth"] >= 0
        assert w["inflight_depth"] >= 0
    epochs = [r for r in records if r["type"] == "epoch"]
    assert epochs and epochs[0]["host_stall_ms"] >= 0
