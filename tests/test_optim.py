"""Optimizer parity vs torch (reference uses Adam lr=1e-3,
multi-GPU-training-torch.py:249)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tpuddp import optim


def torch_steps(opt_cls, kwargs, w0, grads_seq):
    w = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    opt = opt_cls([w], **kwargs)
    for g in grads_seq:
        opt.zero_grad()
        w.grad = torch.from_numpy(g.copy())
        opt.step()
    return w.detach().numpy()


def ours_steps(opt, w0, grads_seq):
    params = {"w": jnp.asarray(w0)}
    state = opt.init(params)
    for g in grads_seq:
        params, state = opt.update({"w": jnp.asarray(g)}, state, params)
    return np.asarray(params["w"])


W0 = np.random.RandomState(0).randn(7, 3).astype(np.float32)
GRADS = [np.random.RandomState(i + 1).randn(7, 3).astype(np.float32) for i in range(5)]


def test_adam_matches_torch():
    ref = torch_steps(torch.optim.Adam, dict(lr=1e-3), W0, GRADS)
    got = ours_steps(optim.Adam(lr=1e-3), W0, GRADS)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_adam_weight_decay_matches_torch():
    ref = torch_steps(torch.optim.Adam, dict(lr=1e-2, weight_decay=0.1), W0, GRADS)
    got = ours_steps(optim.Adam(lr=1e-2, weight_decay=0.1), W0, GRADS)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_sgd_plain_matches_torch():
    ref = torch_steps(torch.optim.SGD, dict(lr=0.1), W0, GRADS)
    got = ours_steps(optim.SGD(lr=0.1), W0, GRADS)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_sgd_momentum_nesterov_matches_torch():
    for nesterov in (False, True):
        ref = torch_steps(
            torch.optim.SGD, dict(lr=0.1, momentum=0.9, nesterov=nesterov), W0, GRADS
        )
        got = ours_steps(optim.SGD(lr=0.1, momentum=0.9, nesterov=nesterov), W0, GRADS)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_update_is_jittable_and_state_is_pytree():
    opt = optim.Adam(1e-3)
    params = {"w": jnp.ones((3,))}
    state = opt.init(params)
    jitted = jax.jit(opt.update)
    p2, s2 = jitted({"w": jnp.ones((3,))}, state, params)
    assert int(s2.step) == 1
    jax.tree_util.tree_map(lambda x: x, s2)  # must be a valid pytree


def test_clip_grad_norm():
    grads = {"a": jnp.ones((4,)) * 3.0}  # norm 6
    clipped, norm = optim.clip_grad_norm_(grads, 3.0)
    assert float(norm) == pytest.approx(6.0)
    assert float(optim.global_norm(clipped)) == pytest.approx(3.0, rel=1e-4)
    # no-op when under the limit
    clipped2, _ = optim.clip_grad_norm_(grads, 100.0)
    np.testing.assert_allclose(np.asarray(clipped2["a"]), 3.0)
