"""Optimizer parity vs torch (reference uses Adam lr=1e-3,
multi-GPU-training-torch.py:249)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tpuddp import optim


def torch_steps(opt_cls, kwargs, w0, grads_seq):
    w = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    opt = opt_cls([w], **kwargs)
    for g in grads_seq:
        opt.zero_grad()
        w.grad = torch.from_numpy(g.copy())
        opt.step()
    return w.detach().numpy()


def ours_steps(opt, w0, grads_seq):
    params = {"w": jnp.asarray(w0)}
    state = opt.init(params)
    for g in grads_seq:
        params, state = opt.update({"w": jnp.asarray(g)}, state, params)
    return np.asarray(params["w"])


W0 = np.random.RandomState(0).randn(7, 3).astype(np.float32)
GRADS = [np.random.RandomState(i + 1).randn(7, 3).astype(np.float32) for i in range(5)]


def test_adam_matches_torch():
    ref = torch_steps(torch.optim.Adam, dict(lr=1e-3), W0, GRADS)
    got = ours_steps(optim.Adam(lr=1e-3), W0, GRADS)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_adam_weight_decay_matches_torch():
    ref = torch_steps(torch.optim.Adam, dict(lr=1e-2, weight_decay=0.1), W0, GRADS)
    got = ours_steps(optim.Adam(lr=1e-2, weight_decay=0.1), W0, GRADS)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_sgd_plain_matches_torch():
    ref = torch_steps(torch.optim.SGD, dict(lr=0.1), W0, GRADS)
    got = ours_steps(optim.SGD(lr=0.1), W0, GRADS)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_sgd_momentum_nesterov_matches_torch():
    for nesterov in (False, True):
        ref = torch_steps(
            torch.optim.SGD, dict(lr=0.1, momentum=0.9, nesterov=nesterov), W0, GRADS
        )
        got = ours_steps(optim.SGD(lr=0.1, momentum=0.9, nesterov=nesterov), W0, GRADS)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_update_is_jittable_and_state_is_pytree():
    opt = optim.Adam(1e-3)
    params = {"w": jnp.ones((3,))}
    state = opt.init(params)
    jitted = jax.jit(opt.update)
    p2, s2 = jitted({"w": jnp.ones((3,))}, state, params)
    assert int(s2.step) == 1
    jax.tree_util.tree_map(lambda x: x, s2)  # must be a valid pytree


def test_adam_bf16_state_tracks_f32():
    """bf16 moment storage must keep the trajectory close to f32 Adam —
    storage-only rounding, full-precision math (optim.Adam docstring)."""
    opt = optim.Adam(lr=1e-3, state_dtype=jnp.bfloat16)
    params = {"w": jnp.asarray(W0)}
    state = opt.init(params)
    assert state.m["w"].dtype == jnp.bfloat16
    assert state.v["w"].dtype == jnp.bfloat16
    for g in GRADS:
        params, state = opt.update({"w": jnp.asarray(g)}, state, params)
        assert state.m["w"].dtype == jnp.bfloat16  # storage dtype is stable
    assert params["w"].dtype == jnp.float32  # master params stay f32
    ref = ours_steps(optim.Adam(lr=1e-3), W0, GRADS)
    # bf16 has ~3 decimal digits; after 5 steps of lr=1e-3 updates the
    # parameter delta is ~5e-3, so absolute drift stays well under 1e-4.
    np.testing.assert_allclose(np.asarray(params["w"]), ref, atol=2e-4)


def test_adam_bf16_state_v_decays_from_peak():
    """The reason bf16 state needs stochastic rounding: v's EMA decrement
    (0.1% of v at b2=0.999) is below bf16's half-ulp (~0.2% of v), so
    round-to-nearest would freeze v at its early peak forever and collapse
    the effective step size. Stochastic rounding is unbiased, so feeding
    near-zero grads after a spike must let v decay toward zero."""
    opt = optim.Adam(lr=1e-3, state_dtype=jnp.bfloat16)
    params = {"w": jnp.zeros((256,))}
    state = opt.init(params)
    # one huge-gradient step sets a high v peak
    params, state = opt.update({"w": jnp.full((256,), 100.0)}, state, params)
    v_peak = float(np.asarray(state.v["w"], np.float32).mean())
    # then 600 tiny-gradient steps: v should shed most of the peak
    # (f32 oracle after 600 steps of 0.999-decay: v ~ 0.55 * peak)
    tiny = {"w": jnp.zeros((256,))}
    update = jax.jit(opt.update)
    for _ in range(600):
        params, state = update(tiny, state, params)
    v_end = float(np.asarray(state.v["w"], np.float32).mean())
    assert v_end < 0.7 * v_peak, (v_peak, v_end)  # frozen-v bug => v_end == v_peak


def test_adam_bf16_state_checkpoint_roundtrip(tmp_path):
    """bf16 moments survive the npz checkpoint format (uint16 bit view)."""
    from tpuddp.training import checkpoint as ckpt

    opt = optim.Adam(lr=1e-3, state_dtype="bfloat16")
    params = {"w": jnp.asarray(W0)}
    state = opt.init(params)
    params, state = opt.update({"w": jnp.asarray(GRADS[0])}, state, params)
    path = ckpt.save(str(tmp_path / "s.npz"), state)
    restored = ckpt.load(path, state)
    assert restored.m["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored.m["w"]).view(np.uint16),
        np.asarray(state.m["w"]).view(np.uint16),
    )


# ------------------------------------------ large-batch optimizers (v2) --


def tree_of(w0=None):
    rng = np.random.RandomState(42)
    return {
        "w1": jnp.asarray(rng.randn(7, 3).astype(np.float32)),
        "b1": jnp.asarray(rng.randn(3).astype(np.float32)),
        "w2": jnp.asarray(rng.randn(3, 5).astype(np.float32)),
    }


def grads_like(tree, seed):
    rng = np.random.RandomState(seed)
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.randn(*np.shape(p)).astype(np.float32)), tree
    )


def test_sgdw_decouples_weight_decay():
    """SGDW's decay scales the parameter directly (AdamW-style) instead of
    entering the momentum buffer: one step from a zero buffer equals
    ``p - lr*g - lr*wd*p`` exactly."""
    opt = optim.SGDW(lr=0.1, momentum=0.9, weight_decay=0.01)
    p = {"w": jnp.asarray(W0)}
    g = {"w": jnp.asarray(GRADS[0])}
    new_p, state = opt.update(g, opt.init(p), p)
    np.testing.assert_allclose(
        np.asarray(new_p["w"]),
        W0 - 0.1 * GRADS[0] - 0.1 * 0.01 * W0,
        rtol=1e-6,
    )
    np.testing.assert_array_equal(np.asarray(state.momentum["w"]), GRADS[0])


def test_lars_trust_ratio_scales_per_layer():
    """The defining LARS property: scaling ONE layer's gradient by a large
    constant leaves its update direction (and the other layers' updates)
    unchanged up to the eps term — the trust ratio normalizes per layer."""
    opt = optim.LARS(lr=0.1, momentum=0.0, trust_coefficient=0.01, eps=0.0)
    p = tree_of()
    g = grads_like(p, 1)
    p1, _ = opt.update(g, opt.init(p), p)
    g_scaled = dict(g, w1=g["w1"] * 1000.0)
    p2, _ = opt.update(g_scaled, opt.init(p), p)
    for k in p:
        np.testing.assert_allclose(
            np.asarray(p1[k]), np.asarray(p2[k]), rtol=1e-4
        )
    # and the per-layer step magnitude follows trust_coef * ||p||
    step = np.asarray(p["w1"] - p1["w1"])
    p_n = float(np.linalg.norm(np.asarray(p["w1"])))
    assert np.linalg.norm(step) == pytest.approx(0.1 * 0.01 * p_n, rel=1e-3)


def test_lamb_trust_ratio_and_zero_norm_fallback():
    opt = optim.LAMB(lr=0.01, weight_decay=0.0)
    p = tree_of()
    g = grads_like(p, 2)
    new_p, state = opt.update(g, opt.init(p), p)
    assert int(state.step) == 1
    # per-layer step norm == lr * ||p|| when ratio binds (r_norm > 0)
    for k in p:
        step_n = float(np.linalg.norm(np.asarray(p[k] - new_p[k])))
        p_n = float(np.linalg.norm(np.asarray(p[k])))
        assert step_n == pytest.approx(0.01 * p_n, rel=1e-3), k
    # zero-norm layer (fresh bias at exactly 0): unscaled fallback, no NaN
    pz = {"b": jnp.zeros((4,))}
    gz = {"b": jnp.ones((4,))}
    new_pz, _ = opt.update(gz, optim.LAMB(lr=0.01).init(pz), pz)
    assert np.all(np.isfinite(np.asarray(new_pz["b"])))


@pytest.mark.parametrize("make", [
    lambda: optim.LARS(lr=0.05, momentum=0.9, weight_decay=0.01),
    lambda: optim.LAMB(lr=0.01, weight_decay=0.01),
])
def test_flat_update_matches_tree_update(make):
    """update_flat over the FlatParamSpec's leaf boundaries is the SAME math
    as the tree-mode update — the weight-update-sharding composition
    contract: per-layer norms recovered by segment, trajectories equal."""
    from tpuddp.training.step import (
        _tree_to_vec, _vec_to_tree, make_flat_param_spec,
    )

    p_tree = tree_of()
    spec = make_flat_param_spec(p_tree, world=1)
    tree_opt, flat_opt = make(), make()
    tree_state = tree_opt.init(p_tree)
    p_vec = _tree_to_vec(p_tree, spec)
    flat_state = flat_opt.init(jnp.zeros((spec.total,), jnp.float32))
    for seed in range(3):
        g_tree = grads_like(p_tree, seed)
        p_tree, tree_state = tree_opt.update(g_tree, tree_state, p_tree)
        g_vec = _tree_to_vec(g_tree, spec)
        p_vec, flat_state = flat_opt.update_flat(
            g_vec, flat_state, p_vec, spec=spec
        )
    back = _vec_to_tree(p_vec, spec)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        p_tree, back,
    )


def test_large_batch_optimizers_are_jittable():
    for opt in (
        optim.SGDW(0.1), optim.LARS(0.1), optim.LAMB(0.01),
    ):
        p = tree_of()
        state = opt.init(p)
        p2, s2 = jax.jit(opt.update)(grads_like(p, 3), state, p)
        assert all(
            np.all(np.isfinite(np.asarray(l)))
            for l in jax.tree_util.tree_leaves(p2)
        )
        jax.tree_util.tree_map(lambda x: x, s2)


def test_optimizer_from_config_factory():
    """config.optimizer_from: ONE factory for both entrypoints — knob
    routing, bf16-moments-is-an-Adam-knob refusal, unknown-name refusal."""
    from tpuddp import config as cfg_lib

    base = dict(cfg_lib.TRAINING_DEFAULTS, learning_rate=0.02)
    assert isinstance(cfg_lib.optimizer_from(base), optim.Adam)
    lars = cfg_lib.optimizer_from(dict(
        base, optimizer="lars", weight_decay=0.01, momentum=0.8,
        trust_coefficient=0.002,
    ))
    assert isinstance(lars, optim.LARS)
    assert lars.lr == 0.02 and lars.momentum == 0.8
    assert lars.trust_coefficient == 0.002 and lars.weight_decay == 0.01
    lamb = cfg_lib.optimizer_from(dict(base, optimizer="lamb", weight_decay=0.1))
    assert isinstance(lamb, optim.LAMB) and lamb.weight_decay == 0.1
    assert isinstance(
        cfg_lib.optimizer_from(dict(base, optimizer="sgdw")), optim.SGDW
    )
    assert isinstance(
        cfg_lib.optimizer_from(dict(base, optimizer="sgd")), optim.SGD
    )
    with pytest.raises(ValueError, match="unknown training.optimizer"):
        cfg_lib.optimizer_from(dict(base, optimizer="adamw"))
    with pytest.raises(ValueError, match="Adam knob"):
        cfg_lib.optimizer_from(dict(
            base, optimizer="lamb", optimizer_state_dtype="bfloat16"
        ))
    # the config schema knows the new knobs (unknown-key refusal intact)
    cfg = cfg_lib.training_config({"training": {
        "optimizer": "lars", "weight_decay": 0.01, "momentum": 0.9,
        "trust_coefficient": 0.001, "comm_topology": "hierarchical",
        "topk_density": 0.25,
    }})
    assert cfg["optimizer"] == "lars" and cfg["comm_topology"] == "hierarchical"
    with pytest.raises(ValueError, match="did you mean"):
        cfg_lib.training_config({"training": {"comm_topolgy": "flat"}})


def test_clip_grad_norm():
    grads = {"a": jnp.ones((4,)) * 3.0}  # norm 6
    clipped, norm = optim.clip_grad_norm_(grads, 3.0)
    assert float(norm) == pytest.approx(6.0)
    assert float(optim.global_norm(clipped)) == pytest.approx(3.0, rel=1e-4)
    # no-op when under the limit
    clipped2, _ = optim.clip_grad_norm_(grads, 100.0)
    np.testing.assert_allclose(np.asarray(clipped2["a"]), 3.0)
