"""Optimizer parity vs torch (reference uses Adam lr=1e-3,
multi-GPU-training-torch.py:249)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tpuddp import optim


def torch_steps(opt_cls, kwargs, w0, grads_seq):
    w = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    opt = opt_cls([w], **kwargs)
    for g in grads_seq:
        opt.zero_grad()
        w.grad = torch.from_numpy(g.copy())
        opt.step()
    return w.detach().numpy()


def ours_steps(opt, w0, grads_seq):
    params = {"w": jnp.asarray(w0)}
    state = opt.init(params)
    for g in grads_seq:
        params, state = opt.update({"w": jnp.asarray(g)}, state, params)
    return np.asarray(params["w"])


W0 = np.random.RandomState(0).randn(7, 3).astype(np.float32)
GRADS = [np.random.RandomState(i + 1).randn(7, 3).astype(np.float32) for i in range(5)]


def test_adam_matches_torch():
    ref = torch_steps(torch.optim.Adam, dict(lr=1e-3), W0, GRADS)
    got = ours_steps(optim.Adam(lr=1e-3), W0, GRADS)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_adam_weight_decay_matches_torch():
    ref = torch_steps(torch.optim.Adam, dict(lr=1e-2, weight_decay=0.1), W0, GRADS)
    got = ours_steps(optim.Adam(lr=1e-2, weight_decay=0.1), W0, GRADS)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_sgd_plain_matches_torch():
    ref = torch_steps(torch.optim.SGD, dict(lr=0.1), W0, GRADS)
    got = ours_steps(optim.SGD(lr=0.1), W0, GRADS)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_sgd_momentum_nesterov_matches_torch():
    for nesterov in (False, True):
        ref = torch_steps(
            torch.optim.SGD, dict(lr=0.1, momentum=0.9, nesterov=nesterov), W0, GRADS
        )
        got = ours_steps(optim.SGD(lr=0.1, momentum=0.9, nesterov=nesterov), W0, GRADS)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_update_is_jittable_and_state_is_pytree():
    opt = optim.Adam(1e-3)
    params = {"w": jnp.ones((3,))}
    state = opt.init(params)
    jitted = jax.jit(opt.update)
    p2, s2 = jitted({"w": jnp.ones((3,))}, state, params)
    assert int(s2.step) == 1
    jax.tree_util.tree_map(lambda x: x, s2)  # must be a valid pytree


def test_adam_bf16_state_tracks_f32():
    """bf16 moment storage must keep the trajectory close to f32 Adam —
    storage-only rounding, full-precision math (optim.Adam docstring)."""
    opt = optim.Adam(lr=1e-3, state_dtype=jnp.bfloat16)
    params = {"w": jnp.asarray(W0)}
    state = opt.init(params)
    assert state.m["w"].dtype == jnp.bfloat16
    assert state.v["w"].dtype == jnp.bfloat16
    for g in GRADS:
        params, state = opt.update({"w": jnp.asarray(g)}, state, params)
        assert state.m["w"].dtype == jnp.bfloat16  # storage dtype is stable
    assert params["w"].dtype == jnp.float32  # master params stay f32
    ref = ours_steps(optim.Adam(lr=1e-3), W0, GRADS)
    # bf16 has ~3 decimal digits; after 5 steps of lr=1e-3 updates the
    # parameter delta is ~5e-3, so absolute drift stays well under 1e-4.
    np.testing.assert_allclose(np.asarray(params["w"]), ref, atol=2e-4)


def test_adam_bf16_state_v_decays_from_peak():
    """The reason bf16 state needs stochastic rounding: v's EMA decrement
    (0.1% of v at b2=0.999) is below bf16's half-ulp (~0.2% of v), so
    round-to-nearest would freeze v at its early peak forever and collapse
    the effective step size. Stochastic rounding is unbiased, so feeding
    near-zero grads after a spike must let v decay toward zero."""
    opt = optim.Adam(lr=1e-3, state_dtype=jnp.bfloat16)
    params = {"w": jnp.zeros((256,))}
    state = opt.init(params)
    # one huge-gradient step sets a high v peak
    params, state = opt.update({"w": jnp.full((256,), 100.0)}, state, params)
    v_peak = float(np.asarray(state.v["w"], np.float32).mean())
    # then 600 tiny-gradient steps: v should shed most of the peak
    # (f32 oracle after 600 steps of 0.999-decay: v ~ 0.55 * peak)
    tiny = {"w": jnp.zeros((256,))}
    update = jax.jit(opt.update)
    for _ in range(600):
        params, state = update(tiny, state, params)
    v_end = float(np.asarray(state.v["w"], np.float32).mean())
    assert v_end < 0.7 * v_peak, (v_peak, v_end)  # frozen-v bug => v_end == v_peak


def test_adam_bf16_state_checkpoint_roundtrip(tmp_path):
    """bf16 moments survive the npz checkpoint format (uint16 bit view)."""
    from tpuddp.training import checkpoint as ckpt

    opt = optim.Adam(lr=1e-3, state_dtype="bfloat16")
    params = {"w": jnp.asarray(W0)}
    state = opt.init(params)
    params, state = opt.update({"w": jnp.asarray(GRADS[0])}, state, params)
    path = ckpt.save(str(tmp_path / "s.npz"), state)
    restored = ckpt.load(path, state)
    assert restored.m["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored.m["w"]).view(np.uint16),
        np.asarray(state.m["w"]).view(np.uint16),
    )


def test_clip_grad_norm():
    grads = {"a": jnp.ones((4,)) * 3.0}  # norm 6
    clipped, norm = optim.clip_grad_norm_(grads, 3.0)
    assert float(norm) == pytest.approx(6.0)
    assert float(optim.global_norm(clipped)) == pytest.approx(3.0, rel=1e-4)
    # no-op when under the limit
    clipped2, _ = optim.clip_grad_norm_(grads, 100.0)
    np.testing.assert_allclose(np.asarray(clipped2["a"]), 3.0)
