"""Weight-update sharding (ZeRO-1 over the data axis; the cross-replica
weight-update recipe of arxiv.org/abs/2004.13336): the sharded update must be
numerically the SAME training algorithm as the replicated one — only the
memory/traffic layout changes — with Adam moments genuinely laid out sharded
across the mesh. Reference hot loop being accelerated:
/root/reference/multi-GPU-training-torch.py:109-132."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import nn, optim
from tpuddp.data import SyntheticClassification
from tpuddp.models import ToyCNN, ToyMLP
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.training import checkpoint as ckpt
from tpuddp.training.step import (
    FlatParamSpec,
    _tree_to_vec,
    _vec_to_tree,
    make_flat_param_spec,
    stack_batches,
)

KEY = jax.random.key(0)


def make_batch(n=64, seed=5, shape=(8, 8, 3)):
    ds = SyntheticClassification(n=n, shape=shape, seed=seed)
    x, y = ds.get_batch(np.arange(n))
    return x, y, np.ones(n, np.float32)


def build(mesh, wus, clip=None, opt=None, model=None, mode="shard_map"):
    return DistributedDataParallel(
        model if model is not None else ToyMLP(hidden=(16,)),
        opt if opt is not None else optim.Adam(1e-2),
        nn.CrossEntropyLoss(),
        mesh=mesh,
        mode=mode,
        clip_grad_norm=clip,
        weight_update_sharding=wus,
    )


def test_flat_spec_round_trip():
    params = ({"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3)}, jnp.zeros(()))
    spec = make_flat_param_spec(params, world=4)
    assert spec.total % 4 == 0 and spec.total >= 10
    vec = _tree_to_vec(params, spec)
    assert vec.shape == (spec.total,)
    back = _vec_to_tree(vec, spec)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, back,
    )


def test_flat_spec_rejects_non_f32_leaves():
    with pytest.raises(ValueError, match="f32"):
        make_flat_param_spec({"w": jnp.ones(4, jnp.bfloat16)}, world=2)


def test_sharded_update_matches_replicated(cpu_devices):
    """The whole point: same trajectory as the replicated update (reduce-
    scatter + shard update + all-gather == allreduce + full update), down to
    f32 reduction-order noise — with and without clipping."""
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()

    def run(wus, clip):
        ddp = build(mesh, wus, clip=clip)
        st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        for _ in range(4):
            st, m = ddp.train_step(st, ddp.shard((x, y, w)))
        return st, float(np.sum(np.asarray(m["loss_sum"])))

    for clip in (None, 0.05):
        s_rep, l_rep = run(False, clip)
        s_sh, l_sh = run(True, clip)
        assert l_rep == pytest.approx(l_sh, rel=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            ),
            s_rep.params, s_sh.params,
        )


def test_moments_are_laid_out_sharded(cpu_devices):
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    ddp = build(mesh, True)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    m = st.opt_state.m
    assert m.ndim == 1 and m.shape[0] % 8 == 0
    # each device holds exactly its 1/8 slice — the N-fold memory saving
    assert m.addressable_shards[0].data.shape == (m.shape[0] // 8,)
    assert str(m.sharding.spec) == str(jax.sharding.PartitionSpec("data"))
    st, _ = ddp.train_step(st, ddp.shard((x, y, w)))
    assert st.opt_state.m.addressable_shards[0].data.shape == (m.shape[0] // 8,)


def test_scan_step_and_eval_with_sharded_state(cpu_devices):
    """The K-fused scan and the eval pass must accept the sharded state."""
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    ddp = build(mesh, True)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    stacked = ddp.shard_stacked(stack_batches([(x, y, w), (x, y, w)]))
    st, m = ddp.train_step_many(st, stacked)
    assert np.isfinite(np.sum(np.asarray(m["loss_sum"])))
    ev = ddp.eval_step(st, ddp.shard((x, y, w)))
    assert float(np.sum(np.asarray(ev["n"]))) == 64
    ev2 = ddp.eval_step_many(st, stacked)
    assert float(np.sum(np.asarray(ev2["n"]))) == 128


def test_sharded_state_checkpoint_round_trip(cpu_devices, tmp_path):
    """Checkpointing gathers the sharded moments into the (total,) global
    vector; restore re-places them sharded and training continues."""
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    ddp = build(mesh, True)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    st, _ = ddp.train_step(st, ddp.shard((x, y, w)))
    path = ckpt.save(str(tmp_path / "wus.npz"), st)
    template = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    restored = ckpt.load(path, template)
    np.testing.assert_array_equal(
        np.asarray(restored.opt_state.m), np.asarray(st.opt_state.m)
    )
    # restored (host-side) state steps again: the jit's in_specs re-place it,
    # moments land sharded — the native resume flow needs no special casing
    restored2, _ = ddp.train_step(restored, ddp.shard((x, y, w)))
    assert int(np.asarray(restored2.step)) == 2
    assert restored2.opt_state.m.addressable_shards[0].data.shape[0] * 8 == (
        restored2.opt_state.m.shape[0]
    )


def test_wus_composes_with_bf16_moments_and_syncbn(cpu_devices):
    """optimizer_state_dtype=bfloat16 (sharded bf16 moments) and SyncBN
    both compose with the sharded update."""
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch(shape=(8, 8, 3))
    ddp = build(
        mesh, True,
        opt=optim.Adam(1e-2, state_dtype="bfloat16"),
        model=ToyCNN(widths=(8,), sync_bn=True),
    )
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    assert st.opt_state.m.dtype == jnp.bfloat16
    first = None
    for i in range(6):
        st, m = ddp.train_step(st, ddp.shard((x, y, w)))
        if first is None:
            first = float(np.sum(np.asarray(m["loss_sum"])))
    last = float(np.sum(np.asarray(m["loss_sum"])))
    # functional, not bit-exact: the dither realization differs from the
    # replicated layout (see optim.py layout note), but training must
    # actually learn and the moments must not freeze
    assert np.isfinite(last) and last < first
    assert float(np.max(np.abs(np.asarray(st.opt_state.v)))) > 0
    assert st.opt_state.m.addressable_shards[0].data.shape[0] * 8 == st.opt_state.m.shape[0]


def test_wus_requires_shard_map_mode(cpu_devices):
    mesh = make_mesh(cpu_devices)
    with pytest.raises(ValueError, match="shard_map"):
        build(mesh, True, mode="auto")


def test_wus_step_before_init_raises(cpu_devices):
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    ddp = build(mesh, True)
    with pytest.raises(RuntimeError, match="init_state"):
        ddp.train_step(None, ddp.shard((x, y, w)))


def test_wus_with_caller_supplied_params(cpu_devices):
    """The pretrained fine-tune path (init_state(params=..., model_state=...))
    composes: the flat optimizer layout is re-derived over the supplied
    params and the imported weights are what trains."""
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    model = ToyMLP(hidden=(16,))
    params, mstate = model.init(jax.random.key(7), jnp.zeros((1, 8, 8, 3)))
    marked = jax.tree_util.tree_map(lambda l: l + 0.5, params)  # recognizable
    ddp = build(mesh, True, model=model)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)), params=marked, model_state=mstate)
    for got, want in zip(
        jax.tree_util.tree_leaves(st.params), jax.tree_util.tree_leaves(marked)
    ):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
    assert st.opt_state.m.ndim == 1  # flat sharded layout, not the param tree
    st, m = ddp.train_step(st, ddp.shard((x, y, w)))
    assert np.isfinite(np.sum(np.asarray(m["loss_sum"])))


def test_wus_with_sgd_momentum(cpu_devices):
    """The flat-shard update is optimizer-agnostic: SGD+momentum's buffer
    shards the same way and matches the replicated trajectory."""
    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()

    def run(wus):
        ddp = build(mesh, wus, opt=optim.SGD(0.1, momentum=0.9))
        st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        for _ in range(3):
            st, _ = ddp.train_step(st, ddp.shard((x, y, w)))
        return st

    s_rep, s_sh = run(False), run(True)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        s_rep.params, s_sh.params,
    )


# ---- managed-path (GSPMD) weight-update sharding -------------------------


def _managed_run(mesh, wus, steps=4, fuse=1):
    from tpuddp.accelerate import Accelerator

    x, y, w = make_batch()
    acc = Accelerator(mesh=mesh, seed=21, weight_update_sharding=wus, fuse_steps=fuse)
    model, opt = acc.prepare(ToyMLP(hidden=(16,)), optim.Adam(1e-2))
    criterion = nn.CrossEntropyLoss()
    losses = []
    for _ in range(steps):
        loss = criterion(model(x), y, w)
        acc.backward(loss)
        opt.step()
        losses.append(loss)
    total = float(sum(l.device_value() for l in losses))
    return acc, model, opt, total


def test_managed_wus_matches_replicated(cpu_devices):
    """Accelerator(weight_update_sharding=True): identical trajectory to the
    plain managed run — the flat constrained update is the same elementwise
    math, only the layout (and hence XLA's collective choice) changes."""
    mesh = make_mesh(cpu_devices)
    _, m_rep, o_rep, l_rep = _managed_run(mesh, wus=False)
    _, m_sh, o_sh, l_sh = _managed_run(mesh, wus=True)
    assert l_rep == pytest.approx(l_sh, rel=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        m_rep.params, m_sh.params,
    )
    # the moments genuinely live flat + sharded
    m = o_sh.opt_state.m
    assert m.ndim == 1 and m.shape[0] % 8 == 0
    assert m.addressable_shards[0].data.shape == (m.shape[0] // 8,)


def test_managed_wus_with_fused_scan(cpu_devices):
    """The fuse_steps scan carries the flat sharded state through the scan."""
    mesh = make_mesh(cpu_devices)
    _, m_rep, _, l_rep = _managed_run(mesh, wus=False, steps=4, fuse=1)
    acc, m_sh, o_sh, l_sh = _managed_run(mesh, wus=True, steps=4, fuse=4)
    assert l_rep == pytest.approx(l_sh, rel=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        m_rep.params, m_sh.params,
    )
    assert o_sh.opt_state.m.addressable_shards[0].data.shape[0] * 8 == (
        o_sh.opt_state.m.shape[0]
    )


def test_managed_wus_save_state_round_trip(cpu_devices, tmp_path):
    """save_state gathers the flat moments; load_state re-places them
    sharded and the resumed run continues bit-exactly."""
    from tpuddp.accelerate import Accelerator

    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    criterion = nn.CrossEntropyLoss()

    acc, model, opt, _ = _managed_run(mesh, wus=True, steps=3)
    acc.save_state(model, opt, str(tmp_path), epoch=3)
    for _ in range(2):
        loss = criterion(model(x), y, w)
        acc.backward(loss)
        opt.step()
    expect = jax.tree_util.tree_map(np.asarray, model.params)

    acc2 = Accelerator(mesh=mesh, seed=21, weight_update_sharding=True)
    model2, opt2 = acc2.prepare(ToyMLP(hidden=(16,)), optim.Adam(1e-2))
    model2(x)  # lazy structure init
    assert acc2.load_state(model2, opt2, str(tmp_path)) == 4
    assert opt2.opt_state.m.addressable_shards[0].data.shape[0] * 8 == (
        opt2.opt_state.m.shape[0]
    )
    for _ in range(2):
        loss = criterion(model2(x), y, w)
        acc2.backward(loss)
        opt2.step()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        model2.params, expect,
    )


def test_native_wus_compiles_to_reduce_scatter_all_gather(cpu_devices):
    """The exchange IS the claimed one: the compiled HLO of the native
    weight-update-sharded step carries the gradient reduction as a
    reduce-scatter and re-replicates parameters with one all-gather — no
    full-gradient all-reduce remains."""
    from tpuddp.training import step as step_lib

    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    ddp = build(mesh, True)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    # same step configuration as the product path, specs via the public API
    spec = make_flat_param_spec(st.params, world=8)
    opt_template = ddp.optimizer.init(jnp.zeros((spec.total,), jnp.float32))
    sspec = step_lib.sharded_state_spec(opt_template, spec)
    fn = step_lib.build_train_step(
        ddp.model, ddp.criterion, ddp.optimizer, mesh, mode="shard_map",
        wus_spec=spec, state_spec=sspec,
    )
    txt = jax.jit(fn).lower(st, ddp.shard((x, y, w))).compile().as_text()
    assert txt.count("reduce-scatter") >= 1
    assert txt.count("all-gather") >= 1
    assert txt.count("all-reduce") == 0  # the full-grad allreduce is GONE


def test_managed_wus_composes_with_accumulation_and_clip(cpu_devices):
    """Gradient accumulation (tree-level grad sums) and clipping both ride
    through the flat sharded update unchanged: same params as the plain
    managed run with the same knobs."""
    from tpuddp.accelerate import Accelerator

    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch(n=32)
    criterion = nn.CrossEntropyLoss()

    def run(wus):
        acc = Accelerator(
            mesh=mesh, seed=9, weight_update_sharding=wus,
            gradient_accumulation_steps=2, clip_grad_norm=0.1,
        )
        model, opt = acc.prepare(ToyMLP(hidden=(16,)), optim.SGD(1.0))
        for i in range(4):  # two full accumulation cycles
            sl = slice((i % 2) * 16, (i % 2) * 16 + 16)
            loss = criterion(model(x[sl]), y[sl], w[sl])
            acc.backward(loss)
            opt.step()
        return model, opt

    m_rep, _ = run(False)
    m_sh, o_sh = run(True)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        m_rep.params, m_sh.params,
    )
    # SGD carries no vec state; the adapter's flat layout still holds
    assert o_sh.opt_state.momentum is None
