"""Autoregressive decode suite (ISSUE 12, tpuddp/serving/decode/):
paged-KV-cache accounting, the end-to-end acceptance contract (concurrent
sequences stream token-by-token bitwise-identical to single-sequence
reference decodes; a finishing sequence frees its blocks and a queued
request joins the next step), admission/termination semantics, schema-v6
decode_stats emission + drift rejection, the /metrics scrape-vs-stats
value match, and — slow tier — the --decode demo entrypoint and the
SIGTERM drain exit-75 contract."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest
import yaml

from tpuddp import config as config_lib
from tpuddp.observability import schema
from tpuddp.resilience.preemption import EXIT_PREEMPTED
from tpuddp.serving import AdmissionError
from tpuddp.serving.decode import DecodeEngine, DecodeStats, PagedKVCache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB = 32


def _decode_cfg(**overrides):
    cfg = config_lib.decode_config({"decode": {}})
    cfg.update(
        model="transformer_tiny",
        vocab_size=VOCAB,
        num_replicas=1,
        max_slots=4,
        kv_blocks=17,  # 16 allocatable = exactly 4 worst-case sequences
        kv_block_size=8,
        max_seq_len=32,
        max_new_tokens=8,
        stats_window=16,
        max_queue_depth=64,
    )
    cfg.update(overrides)
    return cfg


@pytest.fixture(scope="module")
def engine(cpu_devices):
    eng = DecodeEngine.from_config(_decode_cfg(), devices=cpu_devices)
    eng.start()
    yield eng
    eng.drain()


def _prompt(rng, n=None):
    n = n if n is not None else int(rng.randint(1, 13))
    return rng.randint(0, VOCAB, size=n).astype(np.int32)


# -------------------------------------------------------------- KV cache --


def test_cache_allocation_accounting():
    c = PagedKVCache(layers=2, heads=4, head_dim=8, num_blocks=9,
                     block_size=4, max_slots=3, max_seq_len=16)
    assert c.allocatable == 8 and c.max_blocks == 4
    assert c.pool_shape() == (2, 9, 4, 4, 8)
    assert c.occupancy() == 0.0
    s0 = c.allocate(9)  # 3 blocks of 4
    assert c.used_blocks == 3 and c.free_slots == 2
    assert c.occupancy() == pytest.approx(3 / 8)
    # the table row names only this sequence's blocks; tail entries are the
    # garbage block 0
    row = c.tables[s0]
    assert (row[:3] > 0).all() and row[3] == 0
    s1 = c.allocate(16)  # 4 blocks
    assert c.used_blocks == 7
    # 1 block left: a 5-token sequence (2 blocks) cannot be admitted even
    # though a slot is free — lifetime budgets are reserved up front
    assert c.free_slots == 1 and not c.can_admit(5)
    assert c.can_admit(4)
    c.free(s0)
    assert c.used_blocks == 4 and c.free_slots == 2
    assert (c.tables[s0] == 0).all() and c.lengths[s0] == 0
    c.free(s1)
    assert c.occupancy() == 0.0


def test_cache_rejects_bad_geometry_and_misuse():
    with pytest.raises(ValueError, match="reserved"):
        PagedKVCache(layers=1, heads=1, head_dim=4, num_blocks=1,
                     block_size=4, max_slots=1, max_seq_len=4)
    with pytest.raises(ValueError, match="cannot hold even one"):
        PagedKVCache(layers=1, heads=1, head_dim=4, num_blocks=3,
                     block_size=2, max_slots=1, max_seq_len=16)
    c = PagedKVCache(layers=1, heads=1, head_dim=4, num_blocks=5,
                     block_size=4, max_slots=2, max_seq_len=16)
    with pytest.raises(ValueError, match="outside"):
        c.allocate(17)
    with pytest.raises(ValueError, match="not allocated"):
        c.free(0)
    c.allocate(16)
    with pytest.raises(RuntimeError, match="cannot admit"):
        c.allocate(16)


def test_cache_blocks_reused_after_free():
    c = PagedKVCache(layers=1, heads=1, head_dim=4, num_blocks=5,
                     block_size=4, max_slots=2, max_seq_len=16)
    s0 = c.allocate(16)
    first = set(int(b) for b in c.tables[s0] if b)
    c.free(s0)
    s1 = c.allocate(16)
    assert set(int(b) for b in c.tables[s1] if b) == first


# ----------------------------------------------------- acceptance contract --


def test_concurrent_streams_bitwise_equal_solo_reference(engine):
    """THE acceptance test: N concurrent requests with different lengths
    stream token-by-token; each sequence's tokens are bitwise-identical to
    a single-sequence reference decode of the same prompt — continuous
    batching and KV paging are numerically invisible."""
    rng = np.random.RandomState(0)
    prompts = [_prompt(rng, n) for n in (1, 3, 5, 8, 12, 2, 7, 10)]
    # reference: each prompt decoded ALONE (waited before the next submit)
    solo = [
        np.asarray(engine.submit("ref", p, seed=9).result(timeout=120))
        for p in prompts
    ]
    # the same prompts all in flight at once (8 sequences > 4 slots, so the
    # batch churns mid-decode as finishers free slots for queued joiners)
    results = [engine.submit(f"t{i % 3}", p, seed=9)
               for i, p in enumerate(prompts)]
    streamed = [list(r.stream(timeout=120)) for r in results]
    for i, r in enumerate(results):
        final = np.asarray(r.result(timeout=120))
        assert final.dtype == np.int32
        np.testing.assert_array_equal(final, solo[i])
        assert streamed[i] == list(solo[i])


def test_finisher_frees_blocks_and_queued_request_joins(engine):
    """More sequences than slots with wildly different generation lengths:
    everything completes (queued requests joined as slots freed), and the
    pool drains back to zero occupancy."""
    rng = np.random.RandomState(1)
    results = [
        engine.submit("t", _prompt(rng), max_new_tokens=int(rng.randint(1, 9)))
        for _ in range(12)
    ]
    for r in results:
        assert np.asarray(r.result(timeout=120)).ndim == 1
    deadline = time.time() + 10
    while engine.active_sequences() and time.time() < deadline:
        time.sleep(0.01)
    assert engine.kv_occupancy() == 0.0
    assert engine.active_sequences() == 0


def test_stream_is_incremental_and_matches_result(engine):
    rng = np.random.RandomState(2)
    res = engine.submit("t", _prompt(rng, 4))
    toks = []
    for tok in res.stream(timeout=120):
        assert isinstance(tok, int)
        toks.append(tok)
    assert res.first_token_at is not None
    np.testing.assert_array_equal(np.asarray(toks, np.int32),
                                  np.asarray(res.result(timeout=1)))
    assert len(toks) == engine.max_new_tokens


def test_stream_timeout_raises_timeout_error():
    """A stalled stream raises TimeoutError — the same type result() raises
    — never the raw queue.Empty internal."""
    from tpuddp.serving.decode.engine import StreamedResult

    res = StreamedResult()
    with pytest.raises(TimeoutError, match="stalled"):
        next(res.stream(timeout=0.01))


def test_stop_token_terminates_and_is_consumed(engine):
    rng = np.random.RandomState(3)
    p = _prompt(rng, 6)
    full = np.asarray(engine.submit("t", p, seed=4).result(timeout=120))
    stop = int(full[2])
    # the same deterministic decode with full[2] armed as the stop token
    # must deliver exactly the tokens BEFORE it — consumed, never emitted
    out = np.asarray(
        engine.submit("t", p, seed=4, stop_token=stop).result(timeout=120)
    )
    np.testing.assert_array_equal(out, full[:2] if stop not in full[:2]
                                  else full[:list(full).index(stop)])
    # stop on the FIRST sampled token: an empty (but successful) stream
    first = int(full[0])
    empty = engine.submit("t", p, seed=4, stop_token=first)
    assert list(empty.stream(timeout=120)) == []
    assert np.asarray(empty.result(timeout=1)).shape == (0,)


def test_temperature_sampling_deterministic_per_seed(engine):
    """Softmax sampling draws from a stream keyed by (seed, token index)
    only: the same request decodes identically alone or among strangers,
    and a different seed genuinely changes the draw."""
    rng = np.random.RandomState(5)
    p = _prompt(rng, 5)
    a = np.asarray(
        engine.submit("t", p, temperature=0.9, seed=11).result(timeout=120)
    )
    crowd = [engine.submit("t", _prompt(rng), temperature=0.9, seed=100 + i)
             for i in range(5)]
    b = engine.submit("t", p, temperature=0.9, seed=11)
    for r in crowd:
        r.result(timeout=120)
    np.testing.assert_array_equal(a, np.asarray(b.result(timeout=120)))
    c = np.asarray(
        engine.submit("t", p, temperature=0.9, seed=12).result(timeout=120)
    )
    assert not np.array_equal(a, c)


# --------------------------------------------------------------- admission --


def test_admission_rejects_bad_prompts(engine):
    with pytest.raises(AdmissionError) as e:
        engine.submit("t", np.zeros((2, 3), np.int32))
    assert e.value.reason == "bad_shape"
    with pytest.raises(AdmissionError) as e:
        engine.submit("t", np.zeros((3,), np.float32))
    assert e.value.reason == "bad_shape"
    with pytest.raises(AdmissionError) as e:
        engine.submit("t", np.asarray([0, VOCAB], np.int32))
    assert e.value.reason == "bad_shape"
    with pytest.raises(AdmissionError) as e:
        engine.submit("t", np.zeros((engine.max_prompt_len + 1,), np.int32))
    assert e.value.reason == "oversized"
    with pytest.raises(AdmissionError) as e:
        engine.submit("t", np.zeros((2,), np.int32), max_new_tokens=0)
    assert e.value.reason == "oversized"
    with pytest.raises(AdmissionError) as e:
        engine.submit("t", np.zeros((28,), np.int32), max_new_tokens=8)
    assert e.value.reason == "oversized"  # prompt + mnt > max_seq_len


def test_engine_rejects_non_transformer_model(cpu_devices):
    with pytest.raises(ValueError, match="not a TransformerLM"):
        DecodeEngine.from_config(_decode_cfg(model="toy_mlp"),
                                 devices=cpu_devices)


def test_engine_rejects_seq_len_beyond_position_table(cpu_devices):
    with pytest.raises(ValueError, match="position table"):
        DecodeEngine.from_config(
            _decode_cfg(max_seq_len=256),  # transformer_tiny holds 128
            devices=cpu_devices,
        )


def test_drain_then_submit_rejected(cpu_devices):
    eng = DecodeEngine.from_config(
        _decode_cfg(max_slots=2, kv_blocks=9), devices=cpu_devices
    )
    eng.start()
    rng = np.random.RandomState(6)
    res = eng.submit("t", _prompt(rng, 3))
    summary = eng.drain(reason="test")
    assert np.asarray(res.result(timeout=1)).ndim == 1  # finished, not cut
    with pytest.raises(AdmissionError) as e:
        eng.submit("t", _prompt(rng, 3))
    assert e.value.reason == "draining"
    assert summary["completed"] == 1
    # drain is idempotent
    assert eng.drain()["completed"] == 1


def test_failed_dispatch_with_consumed_pools_fails_over(cpu_devices):
    """A dispatch that raises after consuming its donated K/V pool buffers
    (real donation semantics on an accelerator; XLA:CPU ignores donation,
    so the injected failure deletes the arrays itself) must not poison the
    replica OR kill its streams: under the survivability layer
    (tpuddp/serving/survive.py) the in-flight sequence parks into its
    session journal, the replica rebuilds through probation, and the
    stream completes BITWISE-equal to an undisturbed same-seed run."""
    eng = DecodeEngine.from_config(_decode_cfg(), devices=cpu_devices)
    eng.start()
    try:
        rng = np.random.RandomState(13)
        p = _prompt(rng)
        # undisturbed twin first, so the failover run has a bitwise anchor
        twin = np.asarray(eng.submit("t", p, seed=3).result(timeout=120))
        replica = eng.replicas[0]
        real_step = replica._step
        fired = threading.Event()

        def consuming_step(params, kpool, vpool, *rest):
            if not fired.is_set():
                fired.set()
                kpool.delete()
                vpool.delete()
                raise RuntimeError("injected dispatch failure")
            return real_step(params, kpool, vpool, *rest)

        replica._step = consuming_step
        out = np.asarray(eng.submit("t", p, seed=3).result(timeout=120))
        assert fired.is_set()
        np.testing.assert_array_equal(out, twin)
        assert not replica.kpool.is_deleted()
        assert replica.recoveries == 1 and replica.healthy
        assert eng.stats.failovers == 1
    finally:
        eng.drain()


# ------------------------------------------------------- schema + history --


def test_decode_stats_rows_and_run_meta_validate(tmp_path, cpu_devices):
    out = str(tmp_path / "run")
    eng = DecodeEngine.from_config(
        _decode_cfg(stats_window=8), out_dir=out, devices=cpu_devices
    )
    eng.start()
    rng = np.random.RandomState(7)
    for r in [eng.submit("t", _prompt(rng)) for _ in range(6)]:
        r.result(timeout=120)
    eng.drain(reason="test_complete")
    history = os.path.join(out, "history.jsonl")
    errors, n = schema.validate_history_file(history)
    assert errors == [] and n >= 3
    records = [json.loads(l) for l in open(history) if l.strip()]
    meta = records[0]
    assert meta["type"] == "run_meta"
    assert meta["schema_version"] == schema.SCHEMA_VERSION
    # v7: the survivability provenance is non-null on decode headers
    assert meta["survivability"]["max_recoveries"] == 2
    dec = meta["decode"]
    assert dec["model"] == "transformer_tiny"
    assert dec["max_slots"] == 4 and dec["kv_block_size"] == 8
    windows = [r for r in records if r["type"] == "decode_stats"]
    assert windows, "no decode_stats rows emitted"
    assert sum(w["tokens"] for w in windows) == 6 * 8
    assert all(w["kv_occupancy"] is not None for w in windows)
    drains = [r for r in records if r.get("event") == "decode_drain"]
    assert drains and drains[-1]["reason"] == "test_complete"
    assert drains[-1]["completed"] == 6


def test_decode_stats_schema_reject_drift():
    good = schema.stamp("decode_stats", {
        "window": 0, "tokens": 16, "completed": 2, "requests": 2,
        "rejected": 0, "tokens_per_sec": 100.0,
        "ttft_ms_p50": 1.0, "ttft_ms_p95": 2.0,
        "itl_ms_p50": 0.5, "itl_ms_p95": 0.9, "itl_ms_p99": 1.1,
        "kv_occupancy": 0.25, "active_sequences": 2,
        "shed": 0, "failovers": 0,
    })
    assert schema.validate_record(good) == []
    bad = dict(good)
    del bad["tokens_per_sec"], bad["kv_occupancy"]
    errs = schema.validate_record(bad)
    assert any("tokens_per_sec" in e and "kv_occupancy" in e for e in errs)
    # v7 drift: a window without its survivability accounting is invalid —
    # but a v6 copy without them stays valid (versioned requirement)
    drifted = {k: v for k, v in good.items() if k not in ("shed", "failovers")}
    errs = schema.validate_record(drifted)
    assert errs and any("shed" in e and "failovers" in e for e in errs)
    v6 = dict(drifted)
    v6["schema_version"] = 6
    assert schema.validate_record(v6) == []


def test_v6_run_meta_requires_decode_provenance(tmp_path):
    """Drift-reject (satellite): a v6 header MISSING the decode key is
    invalid — a reader must always be able to tell 'not a decode run'
    (null) from 'predates the subsystem' (absent) — and the inspect CLI
    refuses the file the same way."""
    meta = schema.make_run_meta(world_size=1)
    assert "decode" in meta and meta["decode"] is None  # null, never absent
    assert schema.validate_record(meta) == []
    drifted = {k: v for k, v in meta.items() if k != "decode"}
    errs = schema.validate_record(drifted)
    assert errs and any("decode" in e for e in errs)
    # a v5 header without the key stays valid (versioned requirement)
    v5 = dict(drifted)
    v5["schema_version"] = 5
    assert schema.validate_record(v5) == []
    path = tmp_path / "history.jsonl"
    path.write_text(json.dumps(drifted) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpuddp_inspect.py"),
         "--validate", str(path)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "decode" in proc.stderr


def test_loadgen_token_curve_drift_rejected(tmp_path):
    """Drift-reject (satellite): a decode bench row that loses its rate
    metric fails validation — and the inspect CLI agrees."""
    payload = {
        "metric": "decode_tokens_per_sec", "value": 1.0, "unit": "tokens/sec",
        "vs_baseline": 2.0, "device": "cpu",
        "configs": {"closed_loop": {"tokens_per_sec": 900.0,
                                    "ms_per_step": 1.2}},
    }
    assert schema.validate_bench_payload(payload) == []
    del payload["configs"]["closed_loop"]["tokens_per_sec"]
    errs = schema.validate_bench_payload(payload)
    assert errs and any("needs one of" in e for e in errs)
    path = tmp_path / "bench_results.json"
    path.write_text(json.dumps(payload))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpuddp_inspect.py"),
         "--validate", str(path)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1


def test_stats_mark_since_and_flush():
    s = DecodeStats(writer=None, window=4)
    m = s.mark()
    s.record_submit()
    s.record_first_token(5.0, prompt_tokens=3)
    for _ in range(3):
        s.record_token(1.0)
    s.record_finish("a")
    d = s.since(m)
    assert d["tokens"] == 4 and d["completed"] == 1 and d["submitted"] == 1
    assert d["ttft_ms"]["p50"] == 5.0 and d["itl_ms"]["p50"] == 1.0
    # the 4-token window auto-emitted; a second flush with no traffic is None
    assert s.last_window is not None and s.last_window["tokens"] == 4
    assert s.flush_window() is None
    s.record_reject("a", "queue_full")
    w = s.flush_window()
    assert w["rejected"] == 1 and w["tokens"] == 0
    assert w["ttft_ms_p50"] is None  # null, never absent


# ------------------------------------------------- exporter scrape match --


def test_exporter_scrape_matches_decode_stats(tmp_path, cpu_devices):
    """Satellite acceptance: the /metrics decode gauges (tokens, sequences,
    KV occupancy, active sequences, queue depth) must equal the engine's
    own stats/gauges at scrape time."""
    import urllib.request

    eng = DecodeEngine.from_config(
        _decode_cfg(stats_window=8),
        out_dir=str(tmp_path / "run"),
        devices=cpu_devices,
        observability={"exporter": True, "exporter_port": 0},
    )
    eng.start()
    try:
        rng = np.random.RandomState(8)
        for r in [eng.submit("t", _prompt(rng)) for _ in range(4)]:
            r.result(timeout=120)
        deadline = time.time() + 10
        while eng.active_sequences() and time.time() < deadline:
            time.sleep(0.01)
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{eng.exporter.port}/metrics", timeout=10
        ).read().decode()

        def value(name):
            for line in text.splitlines():
                if line.startswith(f"tpuddp_{name} "):
                    return float(line.split()[-1])
            raise AssertionError(f"tpuddp_{name} missing from /metrics:\n{text}")

        assert value("decode_tokens_total") == eng.stats.tokens == 4 * 8
        assert value("decode_sequences_completed_total") == eng.stats.completed == 4
        assert value("decode_requests_total") == eng.stats.submitted == 4
        assert value("decode_rejected_total") == 0
        assert value("decode_kv_occupancy") == eng.kv_occupancy() == 0.0
        assert value("decode_active_sequences") == eng.active_sequences() == 0
        assert value("decode_queue_depth") == eng.queue.depth() == 0
        # a full window flushed (32 tokens > window 8): throughput is live,
        # the TTFT/ITL summary families are registered, and any percentile
        # the last window carries is served with the window's exact value
        win = eng.stats.last_window
        assert value("decode_tokens_per_sec") == win["tokens_per_sec"] > 0
        assert "# TYPE tpuddp_decode_ttft_ms summary" in text
        assert "# TYPE tpuddp_decode_itl_ms summary" in text
        for name, key, q in (("decode_ttft_ms", "ttft_ms_p50", "0.5"),
                             ("decode_itl_ms", "itl_ms_p99", "0.99")):
            if win[key] is not None:
                line = f'tpuddp_{name}{{quantile="{q}"}} '
                got = [l for l in text.splitlines() if l.startswith(line)]
                assert got and float(got[0].split()[-1]) == win[key]
    finally:
        eng.drain()


# ------------------------------------------------------------- slow tier --


def _subprocess_env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["TPUDDP_BACKEND"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _write_settings(tmp_path, **decode_overrides):
    decode = dict(
        vocab_size=VOCAB, max_slots=4, kv_blocks=17, kv_block_size=8,
        max_seq_len=32, max_new_tokens=8, stats_window=16,
    )
    decode.update(decode_overrides)
    path = str(tmp_path / "settings.yaml")
    with open(path, "w") as f:
        yaml.dump({"out_dir": os.path.join(str(tmp_path), "out"),
                   "serving": {"decode": decode}}, f)
    return path


@pytest.mark.slow
def test_decode_demo_entrypoint(tmp_path):
    settings = _write_settings(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "tpuddp.serving", "--settings", settings,
         "--decode", "--demo", "12", "--tenants", "2"],
        capture_output=True, text=True, env=_subprocess_env(), cwd=REPO,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["completed"] == 12
    assert summary["tokens"] == 12 * 8
    assert set(summary["per_tenant_completed"]) == {"tenant0", "tenant1"}
    errors, _ = schema.validate_history_file(
        os.path.join(str(tmp_path), "out", "history.jsonl")
    )
    assert errors == []


@pytest.mark.slow
@pytest.mark.chaos
def test_decode_sigterm_drain_exit75(tmp_path):
    """SIGTERM mid-decode: admission closes, every in-flight sequence
    finishes streaming (completed == submitted — nothing truncated), and
    the process exits 75 with a valid v6 history. The workload is sized so
    the signal lands seconds before decode could finish, and
    in_flight_at_drain proves it did — completed == submitted against an
    already-idle engine would be a vacuous pass."""
    settings = _write_settings(tmp_path, max_new_tokens=96, max_seq_len=128,
                               kv_blocks=65)
    n = 16
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "tpuddp.serving", "--settings", settings,
         "--decode", "--demo", str(n), "--serve", "120"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_subprocess_env(), cwd=REPO,
    )
    try:
        deadline = time.time() + 240
        ready = False
        for line in proc.stdout:
            if "serving: ready" in line:
                ready = True
                break
            if time.time() > deadline:
                break
        assert ready, "server never reported ready"
        proc.send_signal(signal.SIGTERM)  # demo sequences still in flight
        out = proc.stdout.read()
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == EXIT_PREEMPTED, out[-2000:]
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["submitted"] == n and summary["completed"] == n
    assert summary["in_flight_at_drain"] > 0
    history = os.path.join(str(tmp_path), "out", "history.jsonl")
    errors, _ = schema.validate_history_file(history)
    assert errors == []
    records = [json.loads(l) for l in open(history) if l.strip()]
    drain = [r for r in records if r.get("event") == "decode_drain"]
    assert drain and drain[-1]["reason"] == "sigterm_drain"
    assert drain[-1]["completed"] == n
