"""Observability subsystems (SURVEY.md §5, ISSUE 4): typed metrics schema,
step-level telemetry recorder, profiling triggers, NaN guard, loop resume."""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import optim
from tpuddp.data import ShardedDataLoader, SyntheticClassification
from tpuddp.models import ToyMLP
from tpuddp.nn import CrossEntropyLoss
from tpuddp.observability import (
    CommBytesCounter,
    MetricsWriter,
    StepStatsRecorder,
    check_finite,
    json_sanitize,
    percentiles,
    stamp,
)
from tpuddp.observability import profiling as profiling_mod
from tpuddp.observability import schema as schema_mod
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.training import checkpoint as ckpt
from tpuddp.training.loop import run_training_loop


def small_run(
    mesh, save_dir, num_epochs=2, start_epoch=0, state=None, n=64, **loop_kw
):
    ds = SyntheticClassification(n=n, shape=(8, 8, 3), seed=0)
    loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    test_loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh
    )
    if state is None:
        state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    return ddp, run_training_loop(
        ddp, state, loader, test_loader, save_dir,
        num_epochs=num_epochs, checkpoint_epoch=1, start_epoch=start_epoch,
        log=lambda *_: None, **loop_kw,
    )


def read_history(path):
    return [json.loads(l) for l in open(path).read().splitlines()]


def epoch_rows(records):
    return [r for r in records if r.get("type") == "epoch"]


def test_history_jsonl_written(mesh, tmp_path):
    _, (state, history) = small_run(mesh, str(tmp_path))
    path = tmp_path / "history.jsonl"
    assert path.exists()
    records = read_history(path)
    # typed stream: run_meta header first, then one epoch row per epoch
    assert records[0]["type"] == "run_meta"
    epochs = epoch_rows(records)
    assert len(epochs) == 2
    assert epochs[0]["epoch"] == 0
    assert {"train_loss", "test_loss", "test_accuracy", "epoch_time_s"} <= set(epochs[0])


def test_checkpoints_every_epoch_and_resume(mesh, tmp_path):
    ddp, (state, history) = small_run(mesh, str(tmp_path), num_epochs=2)
    assert os.path.exists(tmp_path / "ckpt_0.npz")
    assert os.path.exists(tmp_path / "ckpt_1.npz")

    # resume: restore newest, continue for one more epoch
    template = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    restored, start = ckpt.restore_latest(str(tmp_path), template)
    assert start == 2
    assert int(restored.step) == int(state.step)
    _, (state2, history2) = small_run(
        mesh, str(tmp_path), num_epochs=3, start_epoch=start, state=restored
    )
    assert [h["epoch"] for h in history2] == [2]
    assert os.path.exists(tmp_path / "ckpt_2.npz")
    # the resumed run appended a SECOND run_meta header before its epochs,
    # and the whole appended file still validates
    records = read_history(tmp_path / "history.jsonl")
    assert [r["type"] for r in records].count("run_meta") == 2
    assert schema_mod.validate_history_records(records) == []


def test_check_finite_guard(monkeypatch):
    check_finite(math.nan, "loss")  # disabled: no raise
    monkeypatch.setenv("TPUDDP_DEBUG_NANS", "1")
    check_finite(1.0, "loss")
    with pytest.raises(FloatingPointError, match="loss"):
        check_finite(math.nan, "loss")
    with pytest.raises(FloatingPointError):
        check_finite(math.inf, "loss")


def test_metrics_writer_none_dir_is_noop():
    w = MetricsWriter(None)
    w.write({"a": 1})  # no crash, nothing written
    assert w.path is None


def test_json_sanitize_nonfinite_to_null():
    """Strict-JSON contract (ISSUE 3 satellite): non-finite floats become
    None recursively; finite values and non-float types pass through."""
    rec = {
        "a": math.nan,
        "b": math.inf,
        "c": -math.inf,
        "d": 1.5,
        "e": "nan",  # strings are never touched
        "f": [math.nan, 2, {"g": math.inf}],
        "h": None,
        "i": 3,
    }
    out = json_sanitize(rec)
    assert out["a"] is None and out["b"] is None and out["c"] is None
    assert out["d"] == 1.5 and out["e"] == "nan" and out["i"] == 3
    assert out["f"] == [None, 2, {"g": None}]
    # and the sanitized record survives the strictest dumps
    json.dumps(out, allow_nan=False)


def test_json_sanitize_numpy_scalars_round_trip():
    """ISSUE 4 satellite: a stray device/numpy scalar in a record fails into
    a clean Python value — never a non-JSON repr, never a bare NaN token."""
    rec = {
        "f32": np.float32(1.5),
        "f64_nan": np.float64("nan"),
        "f32_inf": np.float32("inf"),
        "i64": np.int64(7),
        "i32": np.int32(-3),
        "bool": np.bool_(True),
        "zero_d": np.array(2.25),
        "zero_d_nan": np.array(np.nan),
        "zero_d_int": np.array(9, dtype=np.int64),
        "nested": [np.float32(0.5), {"x": np.int64(1), "y": np.bool_(False)}],
    }
    out = json_sanitize(rec)
    assert out["f32"] == 1.5 and isinstance(out["f32"], float)
    assert out["f64_nan"] is None and out["f32_inf"] is None
    assert out["i64"] == 7 and isinstance(out["i64"], int)
    assert out["i32"] == -3 and out["bool"] is True
    assert out["zero_d"] == 2.25 and out["zero_d_nan"] is None
    assert out["zero_d_int"] == 9
    assert out["nested"] == [0.5, {"x": 1, "y": False}]
    # the full round trip: dumps(strict) -> loads recovers plain values
    back = json.loads(json.dumps(out, allow_nan=False))
    assert back == out
    # jax device scalars fetch as numpy and sanitize the same way
    dev = jax.device_get(jnp.float32(3.5))
    assert json_sanitize({"v": dev})["v"] == 3.5
    json.dumps(json_sanitize({"v": dev}), allow_nan=False)


def test_metrics_writer_emits_null_not_nan(tmp_path, monkeypatch):
    """history.jsonl stays parseable by strict JSON consumers even when an
    epoch's metrics blew up."""
    w = MetricsWriter(str(tmp_path))
    w.write({"epoch": 0, "train_loss": math.nan, "test_loss": math.inf})
    w.close()
    raw = open(os.path.join(str(tmp_path), "history.jsonl")).read()
    assert "NaN" not in raw and "Infinity" not in raw
    row = json.loads(raw, parse_constant=lambda t: pytest.fail(f"bare {t}"))
    assert row["train_loss"] is None and row["test_loss"] is None


def test_metrics_writer_line_buffered_and_synced(tmp_path):
    """ISSUE 4 satellite: every completed write is a whole line on disk
    immediately (line-buffered append), and close() fsyncs."""
    w = MetricsWriter(str(tmp_path))
    w.write({"a": 1})
    # visible to an independent reader BEFORE any flush/close call
    raw = open(os.path.join(str(tmp_path), "history.jsonl")).read()
    assert raw == '{"a": 1}\n'
    w.write({"b": 2})
    w.sync()  # flush + fsync: must not error, file stays whole-line
    raw = open(os.path.join(str(tmp_path), "history.jsonl")).read()
    assert raw.endswith('{"b": 2}\n') and raw.count("\n") == 2
    w.close()
    w.close()  # idempotent


def test_profiler_env_toggle(monkeypatch, tmp_path, mesh):
    monkeypatch.setenv("TPUDDP_PROFILE", str(tmp_path / "trace"))
    small_run(mesh, str(tmp_path / "run"), num_epochs=1)
    # a trace directory with at least one artifact was produced
    trace_dir = tmp_path / "trace"
    assert trace_dir.exists()
    assert any(trace_dir.rglob("*"))


# ------------------------------------------------------------- new: schema --


def test_comm_bytes_counter_zero_is_not_none():
    """ISSUE 4 satellite: bytes_per_update=0 (a hookless/no-grad-comm config)
    is a true zero-byte measurement, not a disabled counter."""
    c = CommBytesCounter(0)
    c.add_updates(7)
    assert c.bytes_per_update == 0
    assert c.total_bytes == 0
    snap = c.snapshot(7)
    assert snap == {
        "grad_comm_bytes_per_update": 0,
        "grad_comm_bytes_total": 0,
        "grad_comm_bytes_epoch": 0,
    }
    # None still degrades to the inert counter (pre-init_state ddp objects)
    inert = CommBytesCounter(None)
    inert.add_updates(3)
    assert inert.total_bytes is None and inert.snapshot(3) == {}


def test_schema_validator_accepts_writer_output(mesh, tmp_path):
    """Every native-driver writer path produces records the validator (the
    same code tpuddp_inspect --validate runs) accepts; run_meta is present
    and FIRST."""
    small_run(mesh, str(tmp_path), num_epochs=2, step_stats_every=2, n=256)
    path = str(tmp_path / "history.jsonl")
    errors, n = schema_mod.validate_history_file(path)
    assert errors == [] and n >= 3
    records = read_history(path)
    assert records[0]["type"] == "run_meta"
    types = {r["type"] for r in records}
    assert {"run_meta", "epoch", "step_stats"} <= types
    meta = records[0]
    assert meta["world_size"] == 8 and meta["mesh_shape"] == {"data": 8}
    assert meta["jax_version"] and meta["tpuddp_version"]
    assert meta["api"] == "native"


def test_schema_rejects_unknown_type_and_missing_header(tmp_path):
    good_meta = schema_mod.make_run_meta(comm_hook="none")
    good_event = stamp("event", {"event": "x"})
    # unknown type
    errs = schema_mod.validate_history_records(
        [good_meta, {"type": "telemetry", "schema_version": 1}]
    )
    assert any("unknown type" in e for e in errs)
    # header missing / not first
    errs = schema_mod.validate_history_records([good_event, good_meta])
    assert any("must start with a run_meta" in e for e in errs)
    # empty file
    assert any("empty" in e for e in schema_mod.validate_history_records([]))
    # missing required epoch fields
    errs = schema_mod.validate_history_records(
        [good_meta, stamp("epoch", {"epoch": 0})]
    )
    assert any("missing required field" in e for e in errs)
    # newer schema version than this reader
    errs = schema_mod.validate_history_records(
        [dict(good_meta, schema_version=schema_mod.SCHEMA_VERSION + 1)]
    )
    assert any("newer" in e for e in errs)
    # a valid stream has no errors
    assert schema_mod.validate_history_records([good_meta, good_event]) == []
    # stamp refuses unknown types at write time too
    with pytest.raises(ValueError, match="unknown record type"):
        stamp("metrics", {})
    # non-strict JSON on disk is a validation error
    p = tmp_path / "bad.jsonl"
    p.write_text(json.dumps(good_meta) + "\n{\"type\": \"event\", \"schema_version\": 1, \"event\": \"e\", \"v\": NaN}\n")
    errors, _ = schema_mod.validate_history_file(str(p))
    assert any("invalid JSON" in e for e in errors)


def test_schema_v4_requires_comm_topology(tmp_path):
    """Comm-compression-v2 schema bump: a run_meta stamped at v4+ without
    ``comm_topology`` is drift and must be rejected; older headers (v3 and
    below, which predate the field) keep validating at their own version —
    and the shared make_run_meta always carries the field."""
    meta = schema_mod.make_run_meta(comm_hook="int8_ef", comm_topology="flat")
    assert meta["schema_version"] >= 4
    assert meta["comm_topology"] == "flat"
    assert schema_mod.validate_history_records([meta]) == []
    # null is legal (e.g. serving headers have no gradient comm)...
    assert schema_mod.validate_history_records(
        [schema_mod.make_run_meta(comm_hook=None)]
    ) == []
    # ...but ABSENCE at v4 is drift
    dropped = {k: v for k, v in meta.items() if k != "comm_topology"}
    errs = schema_mod.validate_history_records([dropped])
    assert any("comm_topology" in e for e in errs), errs
    # a v3 header without the field stays valid (its version's contract)
    v3 = dict(dropped, schema_version=3)
    assert schema_mod.validate_history_records([v3]) == []
    # the drift also fails through the file validator (the gate's path)
    p = tmp_path / "drift.jsonl"
    p.write_text(json.dumps(dropped) + "\n")
    errors, _ = schema_mod.validate_history_file(str(p))
    assert any("comm_topology" in e for e in errors)


def test_inspect_cli_validates_and_summarizes(mesh, tmp_path):
    """tools/tpuddp_inspect.py end to end: --validate accepts a real run's
    history, the summary renders, and a corrupted stream is refused."""
    import subprocess
    import sys

    small_run(mesh, str(tmp_path), num_epochs=1)
    path = str(tmp_path / "history.jsonl")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "tpuddp_inspect.py")
    ok = subprocess.run(
        [sys.executable, tool, "--validate", path],
        capture_output=True, text=True, cwd=repo,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "OK:" in ok.stdout
    summary = subprocess.run(
        [sys.executable, tool, path], capture_output=True, text=True, cwd=repo,
    )
    assert summary.returncode == 0, summary.stdout + summary.stderr
    assert "run_meta" in summary.stdout and "epochs (1)" in summary.stdout

    bad = tmp_path / "drifted.jsonl"
    with open(path) as f:
        lines = f.read().splitlines()
    lines.append(json.dumps({"type": "mystery", "schema_version": 1}))
    bad.write_text("\n".join(lines) + "\n")
    refused = subprocess.run(
        [sys.executable, tool, "--validate", str(bad)],
        capture_output=True, text=True, cwd=repo,
    )
    assert refused.returncode == 1
    assert "unknown type" in refused.stderr


def test_bench_payload_validator(tmp_path):
    payload = {
        "metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
        "device": "cpu",
        "configs": {"row": {"samples_per_sec_per_chip": 1.0, "ms_per_step": 2.0}},
    }
    assert schema_mod.validate_bench_payload(payload) == []
    p = tmp_path / "bench_results.json"
    p.write_text(json.dumps(payload, indent=2))
    errors, n = schema_mod.validate_bench_file(str(p))
    assert errors == [] and n == 1
    # a decode row satisfies the rate requirement with tokens_per_sec alone
    tok = dict(payload)
    tok["configs"] = {"row": {"tokens_per_sec": 9.0, "ms_per_step": 2.0}}
    assert schema_mod.validate_bench_payload(tok) == []
    bad = dict(payload)
    bad["configs"] = {"row": {"ms_per_step": 2.0}}
    assert any("needs one of" in e for e in schema_mod.validate_bench_payload(bad))
    bad["configs"] = {"row": {"samples_per_sec_per_chip": 1.0}}
    assert any("missing field" in e for e in schema_mod.validate_bench_payload(bad))
    del bad["metric"]
    assert any("'metric'" in e for e in schema_mod.validate_bench_payload(bad))


# ------------------------------------------------- new: the step recorder --


def test_step_stats_percentiles_match_synthetic_sequence(monkeypatch):
    """Percentile correctness against a known timing sequence: drive the
    recorder with a fake clock whose laps are exactly 1..100 ms and check the
    emitted fields against numpy's own percentiles of that sequence."""
    laps_ms = list(range(1, 101))  # 1, 2, ..., 100 ms — one step per lap
    clock = {"t": 0.0}

    def fake_clock():
        return clock["t"]

    import tpuddp.observability.recorder as rec_mod

    monkeypatch.setattr(rec_mod.time, "perf_counter", fake_clock)
    written = []

    class W:
        def write(self, r):
            written.append(r)

    r = StepStatsRecorder(writer=W(), window=50, peak_flops=None)
    r.start_epoch(0)
    for ms in laps_ms:
        clock["t"] += ms / 1e3
        r.record(1, 8)
    fields = r.epoch_summary()

    expect = np.asarray(laps_ms, np.float64)
    assert fields["train_steps"] == 100
    assert fields["step_time_ms_p50"] == pytest.approx(np.percentile(expect, 50), rel=1e-6)
    assert fields["step_time_ms_p95"] == pytest.approx(np.percentile(expect, 95), rel=1e-6)
    assert fields["step_time_ms_p99"] == pytest.approx(np.percentile(expect, 99), rel=1e-6)
    assert fields["step_time_ms_max"] == pytest.approx(100.0, rel=1e-6)
    # two window rows of 50 steps each, each with ITS OWN slice's percentiles
    assert [w["steps"] for w in written] == [50, 50]
    assert written[0]["step_start"] == 0 and written[1]["step_start"] == 50
    first = np.asarray(laps_ms[:50], np.float64)
    assert written[0]["step_time_ms_p50"] == pytest.approx(
        np.percentile(first, 50), rel=1e-6
    )
    assert written[0]["step_time_ms_max"] == pytest.approx(50.0, rel=1e-6)
    # throughput: 100 steps x 8 samples over 5.050 s (writer rounds to 2dp)
    assert fields["train_samples_per_sec"] == pytest.approx(
        800 / (sum(laps_ms) / 1e3), abs=0.01
    )
    # fused dispatches split their lap evenly across n_steps
    r2 = StepStatsRecorder(window=0, peak_flops=None)
    r2.start_epoch(0)
    clock["t"] += 0.064
    r2.record(64, 64)
    f2 = r2.epoch_summary()
    assert f2["train_steps"] == 64
    assert f2["step_time_ms_p50"] == pytest.approx(1.0, rel=1e-6)


def test_step_stats_mfu_fields():
    """MFU = flops / step-time / peak at the matching percentile; null
    without a known peak."""
    import tpuddp.observability.recorder as rec_mod

    fields = rec_mod.step_time_fields(
        [0.01, 0.01, 0.02], flops_per_step=1e9, peak_flops=1e12
    )
    # p50 step time is 10 ms -> 1e9 / 0.01 / 1e12 = 0.1
    assert fields["mfu_p50"] == pytest.approx(0.1, rel=1e-3)
    assert fields["mfu_p95"] is not None and fields["mfu_p95"] < fields["mfu_p50"]
    null = rec_mod.step_time_fields([0.01], flops_per_step=None, peak_flops=1e12)
    assert null["mfu_p50"] is None
    assert rec_mod.percentiles([]) == {
        "p50": None, "p95": None, "p99": None, "max": None
    }


def test_percentiles_helper_shared_with_bench():
    pct = percentiles([0.001, 0.002, 0.003, 0.010])
    assert pct["max"] == pytest.approx(0.010)
    assert pct["p50"] == pytest.approx(np.percentile([1, 2, 3, 10], 50) / 1e3)


def test_epoch_rows_carry_step_fields_and_no_recompilation(mesh, tmp_path):
    """ISSUE 4 acceptance: telemetry-on epoch rows carry step-time
    percentiles + MFU fields, the step program is HLO-identical with
    telemetry on or off, and no recompilation happens across epochs."""
    ddp, (state, history) = small_run(
        mesh, str(tmp_path), num_epochs=2, step_stats_every=2, n=256
    )
    for row in history:
        assert row["type"] == "epoch"
        assert row["step_time_ms_p50"] > 0
        assert row["step_time_ms_p95"] >= row["step_time_ms_p50"]
        assert row["train_steps"] == 4  # 256 samples / 64 global batch
        assert "mfu_p50" in row  # null on CPU (unknown peak), but present
    # one compiled scan step object across both epochs — telemetry added no
    # retrace (the guard test's no-recompilation contract, held here too)
    jitted = ddp._scan_step
    assert jitted is not None

    def lower_text(d, st):
        b = d.shard((
            np.zeros((64, 8, 8, 3), np.float32),
            np.zeros((64,), np.int32),
            np.ones((64,), np.float32),
        ))
        return jax.jit(lambda s, x: d.train_step(s, x)).lower(st, b).as_text()

    # telemetry is host-side only: the driven wrap's single-step program is
    # byte-identical to a fresh, never-telemetered build's
    fresh = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh
    )
    fresh_state = fresh.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    assert lower_text(ddp, fresh_state) == lower_text(fresh, fresh_state)


def test_mfu_populates_when_chip_peak_known(mesh, tmp_path, monkeypatch):
    """End-to-end MFU plumbing: with the device kind in the peak table (as
    on a real TPU), the FLOPs probe resolves and the epoch row's MFU fields
    are real numbers — on the CPU world they are null only because 'cpu'
    has no table entry, so teach the table 'cpu' and assert the full path."""
    import tpuddp.observability.recorder as rec_mod

    monkeypatch.setitem(rec_mod.PEAK_FLOPS, "cpu", 1e9)
    _, (state, history) = small_run(mesh, str(tmp_path), num_epochs=1, n=256)
    row = history[0]
    assert row["mfu_p50"] is not None and row["mfu_p50"] > 0
    assert row["mfu_p95"] is not None
    records = read_history(tmp_path / "history.jsonl")
    assert epoch_rows(records)[0]["mfu_p50"] == row["mfu_p50"]
    assert records[0]["device_kind"] == "cpu"  # the MESH device's kind


def test_step_stats_window_rows_inside_epoch(mesh, tmp_path):
    """step_stats_every=N emits intra-epoch rows at the N-step cadence with
    the window's own step range."""
    small_run(
        mesh, str(tmp_path), num_epochs=1, step_stats_every=2, scan_steps=2,
        n=512,
    )
    records = read_history(tmp_path / "history.jsonl")
    windows = [r for r in records if r["type"] == "step_stats"]
    # 512 samples / 64 global batch = 8 steps -> 4 windows of 2
    assert len(windows) == 4
    assert [w["step_start"] for w in windows] == [0, 2, 4, 6]
    assert all(w["steps"] == 2 and w["epoch"] == 0 for w in windows)
    assert all(w["samples_per_sec"] > 0 for w in windows)
    # cadence off -> no window rows, epoch percentiles still present
    small_run(mesh, str(tmp_path / "off"), num_epochs=1, n=512)
    records = read_history(tmp_path / "off" / "history.jsonl")
    assert not any(r["type"] == "step_stats" for r in records)
    assert epoch_rows(records)[0]["step_time_ms_p50"] is not None


# ------------------------------------------------- new: profiling triggers --


def test_profile_steps_env_parsing():
    assert profiling_mod.parse_profile_steps("10:20") == (10, 20)
    assert profiling_mod.parse_profile_steps("") is None
    for bad in ("10", "a:b", "5:5", "-1:3", "7:2"):
        with pytest.raises(ValueError):
            profiling_mod.parse_profile_steps(bad)


def test_profile_steps_window_trace(monkeypatch, tmp_path, mesh):
    """TPUDDP_PROFILE_STEPS=<a>:<b> produces a trace dir named for exactly
    the requested window, with artifacts, and releases the trace latch."""
    monkeypatch.setenv("TPUDDP_PROFILE_STEPS", "2:4")
    profiling_mod.reset_profiling_state()
    try:
        small_run(mesh, str(tmp_path), num_epochs=1, scan_steps=1, n=512)
    finally:
        profiling_mod.reset_profiling_state()
    trace_dir = tmp_path / "trace_steps_2_4"
    assert trace_dir.is_dir()
    assert any(trace_dir.rglob("*"))
    assert not profiling_mod._profiling["active"]
    # the first-epoch mode stands down while the step window owns the trace
    monkeypatch.setenv("TPUDDP_PROFILE", str(tmp_path / "unused"))
    assert profiling_mod.maybe_start_profiler(str(tmp_path)) is False


def test_sigusr1_epoch_trace(monkeypatch, tmp_path, mesh):
    """A SIGUSR1 received mid-run traces the NEXT epoch into
    trace_sigusr1_e<N> and records a profile_epoch event."""
    profiling_mod.reset_profiling_state()
    profiling_mod._sigusr1["requested"] = True  # as the signal handler would
    try:
        small_run(mesh, str(tmp_path), num_epochs=1)
    finally:
        profiling_mod.reset_profiling_state()
    trace_dir = tmp_path / "trace_sigusr1_e0"
    assert trace_dir.is_dir()
    assert any(trace_dir.rglob("*"))
    records = read_history(tmp_path / "history.jsonl")
    assert any(
        r.get("event") == "profile_epoch" and r["epoch"] == 0 for r in records
    )
    errors, _ = schema_mod.validate_history_file(str(tmp_path / "history.jsonl"))
    assert errors == []


def test_managed_fused_profile_window_covers_queued_group(
    monkeypatch, tmp_path, mesh
):
    """A TPUDDP_PROFILE_STEPS window falling INSIDE a not-yet-flushed fused
    group must still be traced: the managed driver arms the profiler with
    the queued-group size, so the flush carrying the window is captured."""
    import train_accelerate as ta
    from tpuddp import nn as tnn
    from tpuddp import optim as topt
    from tpuddp.accelerate import Accelerator
    from tpuddp.data import DataLoader

    monkeypatch.setenv("TPUDDP_PROFILE_STEPS", "2:3")  # inside group [0, 4)
    profiling_mod.reset_profiling_state()
    ds = SyntheticClassification(n=256, shape=(8, 8, 3), seed=0)
    acc = Accelerator(mesh=mesh, seed=0, fuse_steps=4)
    model, opt, loader = acc.prepare(
        ToyMLP(hidden=(16,)), topt.Adam(1e-2),
        DataLoader(ds, batch_size=4, shuffle=True),
    )
    test_loader = DataLoader(
        SyntheticClassification(n=64, shape=(8, 8, 3), seed=1), batch_size=32
    )
    try:
        ta.run_training_loop(
            model, loader, test_loader, tnn.CrossEntropyLoss(), opt,
            str(tmp_path), acc, jax.jit(lambda r, i, x: x),
            jax.jit(lambda x: x), num_epochs=1, checkpoint_epoch=5,
            deferred_metrics=True,
        )
    finally:
        profiling_mod.reset_profiling_state()
    trace_dir = tmp_path / "trace_steps_2_3"
    assert trace_dir.is_dir(), "window inside a fused group was not traced"
    assert any(trace_dir.rglob("*"))


def test_watchdog_stale_event_headers_empty_history(tmp_path):
    """A watchdog firing before ANY driver wrote run_meta (process 0 died in
    rendezvous) must still leave a history that validates: it prepends a
    minimal header to its fsync'd stale-peer event."""
    from tpuddp.resilience import watchdog as wd

    writer = MetricsWriter(str(tmp_path), main_only=False)
    fired = []
    w = wd.Watchdog(
        str(tmp_path / "hb"), process_id=1, num_processes=2, timeout=0.1,
        action=lambda stale: fired.append(stale), event_writer=writer,
    )
    w._fire([(0, 5.0)])
    assert fired
    records = read_history(tmp_path / "history.jsonl")
    assert records[0]["type"] == "run_meta" and records[0]["api"] == "watchdog"
    ev = records[1]
    assert ev["event"] == "watchdog_stale"
    assert ev["stale_peers"] == [{"process": 0, "lag_s": 5.0}]
    assert schema_mod.validate_history_records(records) == []
    # with a header already present (the normal mid-training case), no
    # second run_meta is injected
    w._fire([(0, 6.0)])
    records = read_history(tmp_path / "history.jsonl")
    assert [r["type"] for r in records].count("run_meta") == 1


def test_sigusr1_handler_installs_and_fires():
    import signal

    assert profiling_mod.install_sigusr1_trigger() is True
    profiling_mod._sigusr1["requested"] = False
    os.kill(os.getpid(), signal.SIGUSR1)
    # the handler runs on the main thread at the next bytecode boundary
    deadline = 200
    while not profiling_mod._sigusr1["requested"] and deadline:
        deadline -= 1
    assert profiling_mod.consume_sigusr1_request() is True
    assert profiling_mod.consume_sigusr1_request() is False


# ------------------------------------------------ ISSUE 10: live telemetry --


def _scrape(port: int, path: str = "/metrics") -> str:
    import urllib.request

    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ).read().decode()


def _prom_value(text: str, name: str, labels: str = ""):
    needle = f"{name}{labels} " if labels else f"{name} "
    for line in text.splitlines():
        if line.startswith(needle):
            return float(line.split()[-1])
    return None


def test_observability_config_resolution():
    from tpuddp import config as cfg_lib

    # defaults: exporter OFF, aggregation + flight recorder on
    cfg = cfg_lib.resolve_observability(None)
    assert cfg["exporter"] is False
    assert cfg["aggregate"] is True and cfg["flight_recorder"] is True
    # false turns the whole plane off
    off = cfg_lib.resolve_observability(False)
    assert not off["exporter"] and not off["aggregate"]
    assert not off["flight_recorder"]
    # the exporter dict shorthand expands to host/port knobs
    cfg = cfg_lib.resolve_observability(
        {"exporter": {"host": "0.0.0.0", "port": 9100}}
    )
    assert cfg["exporter"] is True
    assert cfg["exporter_host"] == "0.0.0.0" and cfg["exporter_port"] == 9100
    # unknown keys refused, both levels
    with pytest.raises(ValueError, match="unknown observability key"):
        cfg_lib.resolve_observability({"straggler_ration": 2.0})
    with pytest.raises(ValueError, match="observability.exporter"):
        cfg_lib.resolve_observability({"exporter": {"prot": 1}})


def test_exporter_ephemeral_bind_and_endpoints(tmp_path):
    """Port-0 binds ephemerally (two exporters coexist), the port file is
    published and removed, and all three endpoints answer."""
    from tpuddp.observability.exporter import (
        MetricsExporter, PORT_FILENAME, counter,
    )

    a = MetricsExporter(port=0, run_dir=str(tmp_path)).start()
    b = MetricsExporter(port=0).start()
    try:
        assert a.port and b.port and a.port != b.port
        port_file = tmp_path / PORT_FILENAME
        assert int(port_file.read_text().splitlines()[0]) == a.port
        a.register_source("t", lambda: {"x_total": counter(3, "x")})
        assert _prom_value(_scrape(a.port), "tpuddp_x_total") == 3
        health = json.loads(_scrape(a.port, "/healthz"))
        assert health["status"] == "ok" and health["uptime_s"] >= 0
        snap = json.loads(_scrape(a.port, "/snapshot"))
        assert snap["series"]["x_total"]["value"] == 3
        with pytest.raises(Exception):  # 404 on unknown paths
            _scrape(a.port, "/nope")
        # a failing source is skipped, the scrape survives
        def boom():
            raise RuntimeError("broken feeder")
        a.register_source("bad", boom)
        assert "tpuddp_x_total 3" in _scrape(a.port)
    finally:
        a.stop()
        b.stop()
    assert not (tmp_path / PORT_FILENAME).exists()
    # stop is idempotent
    a.stop()


def test_exporter_scrape_matches_recorder_state(monkeypatch):
    """ISSUE 10 acceptance (training side): /metrics values equal the
    recorder's last flushed window exactly — the live plane can never
    disagree with history.jsonl beyond one window."""
    import tpuddp.observability.recorder as rec_mod
    from tpuddp.observability.exporter import MetricsExporter
    from tpuddp.observability.telemetry import RunTelemetry

    clock = {"t": 0.0}
    monkeypatch.setattr(rec_mod.time, "perf_counter", lambda: clock["t"])
    tel = RunTelemetry(writer=None, step_stats_every=4)
    exporter = MetricsExporter(port=0).start()
    try:
        tel.attach_live(exporter=exporter)
        tel.start_epoch(0)
        for ms in (1, 2, 3, 4):  # one window of laps 1..4 ms
            clock["t"] += ms / 1e3
            tel.post_dispatch(1, 8)
        tel.update_live(skipped_steps=2, train_loss=0.5)
        text = _scrape(exporter.port)
        win = tel.recorder.last_window
        assert win is not None
        assert _prom_value(text, "tpuddp_train_steps_total") == 4
        assert _prom_value(text, "tpuddp_train_samples_total") == 32
        assert _prom_value(
            text, "tpuddp_step_time_ms", '{quantile="0.5"}'
        ) == pytest.approx(win["step_time_ms_p50"])
        assert _prom_value(
            text, "tpuddp_step_time_ms", '{quantile="0.99"}'
        ) == pytest.approx(win["step_time_ms_p99"])
        assert _prom_value(
            text, "tpuddp_train_samples_per_sec"
        ) == pytest.approx(win["samples_per_sec"])
        assert _prom_value(text, "tpuddp_skipped_steps") == 2
        assert _prom_value(text, "tpuddp_train_loss") == 0.5
    finally:
        exporter.stop()
        tel.finish()


def test_loop_live_plane_on_records_port_and_hlo_identical(mesh, tmp_path):
    """The whole plane on (exporter + flight + aggregation enabled) changes
    ZERO device semantics: run_meta records the bound endpoint, the step
    program lowers byte-identical to a never-telemetered build, and a clean
    exit leaves no flight recording and no port file."""
    ddp, (state, history) = small_run(
        mesh, str(tmp_path), num_epochs=1, step_stats_every=2, n=256,
        observability={"exporter": True, "exporter_port": 0},
    )
    records = read_history(tmp_path / "history.jsonl")
    meta = records[0]
    obs = meta["observability"]
    assert obs["exporter"]["port"] > 0
    assert obs["flight_recorder"] == {"capacity": 64}
    assert obs["straggler_ratio"] == 1.5 and obs["straggler_windows"] == 3
    assert schema_mod.validate_history_records(records) == []
    # clean exit: endpoint torn down, no crash artifact
    assert not (tmp_path / "exporter.port").exists()
    assert not list(tmp_path.glob("flightrec_*.json"))

    def lower_text(d, st):
        b = d.shard((
            np.zeros((64, 8, 8, 3), np.float32),
            np.zeros((64,), np.int32),
            np.ones((64,), np.float32),
        ))
        return jax.jit(lambda s, x: d.train_step(s, x)).lower(st, b).as_text()

    fresh = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh
    )
    fresh_state = fresh.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    assert lower_text(ddp, fresh_state) == lower_text(fresh, fresh_state)


def test_serving_engine_live_scrape_matches_stats(mesh, tmp_path):
    """Serving acceptance: a live /metrics scrape during traffic reports the
    engine's own counters and the LAST flushed serving_stats window; drain
    tears the endpoint down."""
    import urllib.error

    from tpuddp.serving import ServingEngine

    cfg = {
        "model": "toy_mlp", "num_classes": 10, "input_shape": [8, 8, 3],
        "checkpoint_dir": None, "checkpoint_prefix": "auto",
        "num_replicas": 2, "max_batch_size": 8, "max_queue_depth": 64,
        "per_tenant_quota": None, "batch_timeout_ms": 0.5,
        "stats_window": 8, "unhealthy_after": 3, "seed": 0,
    }
    engine = ServingEngine.from_config(
        cfg, out_dir=str(tmp_path),
        observability={"exporter": True, "exporter_port": 0},
    )
    engine.start()
    port = engine.exporter.port
    try:
        rng = np.random.RandomState(0)
        results = [
            engine.submit(f"tenant{i % 2}", rng.randn(2, 8, 8, 3).astype(np.float32))
            for i in range(24)
        ]
        for r in results:
            r.result(timeout=120)
        text = _scrape(port)
        assert _prom_value(text, "tpuddp_serving_completed_total") == 24
        assert _prom_value(text, "tpuddp_serving_requests_total") == 24
        assert _prom_value(text, "tpuddp_serving_replicas_healthy") == 2
        win = engine.stats.last_window
        assert win is not None  # 24 completed / window 8 -> windows flushed
        assert _prom_value(
            text, "tpuddp_serving_e2e_ms", '{quantile="0.5"}'
        ) == pytest.approx(win["e2e_ms_p50"])
        assert _prom_value(
            text, "tpuddp_serving_throughput_rps"
        ) == pytest.approx(win["throughput_rps"])
        assert _prom_value(
            text, "tpuddp_serving_tenant_completed_total", '{tenant="tenant0"}'
        ) == 12
        # and the flushed history agrees with the scrape (same record)
        records = read_history(tmp_path / "history.jsonl")
        flushed = [r for r in records if r["type"] == "serving_stats"]
        assert flushed[-1]["e2e_ms_p50"] == win["e2e_ms_p50"]
    finally:
        engine.drain()
    with pytest.raises(Exception):  # endpoint down after drain
        _scrape(port, "/healthz")
    errors, _ = schema_mod.validate_history_file(str(tmp_path / "history.jsonl"))
    assert errors == []


# ---------------------------------------------- shard channel + aggregator --


def test_heartbeat_shard_channel_round_trip(tmp_path):
    """The heartbeat file carries the telemetry shard on line 2; liveness
    reads (line 1) are indifferent, and a torn JSON line is skipped with a
    warning, never an exception."""
    from tpuddp.observability import aggregate
    from tpuddp.resilience import watchdog

    shard = {"window_index": 3, "step_time_ms_p50": 1.5, "skipped_steps": 0}
    aggregate.publish_shard(str(tmp_path), 1, shard)
    assert watchdog.read_heartbeat(str(tmp_path), 1) is not None
    assert aggregate.read_shard(str(tmp_path), 1) == shard
    # payload-free beats still read as alive, shard None
    watchdog.write_heartbeat(str(tmp_path), 2, now=123.0)
    assert watchdog.read_heartbeat(str(tmp_path), 2) == 123.0
    assert aggregate.read_shard(str(tmp_path), 2) is None
    # a torn mid-write line: liveness survives, shard read returns None
    with open(tmp_path / "hb_3", "w") as f:
        f.write("456.0\n{\"window_index\": 9, \"step_time")  # torn
    assert watchdog.read_heartbeat(str(tmp_path), 3) == 456.0
    assert aggregate.read_shard(str(tmp_path), 3) is None
    # absent peer
    assert aggregate.read_shard(str(tmp_path), 7) is None


def test_purge_stale_peers_preserves_live_shards(tmp_path):
    """ISSUE 10 satellite: the elastic-resume purge removes ONLY the old
    larger world's hb files — live peers' shard payloads survive."""
    from tpuddp.observability import aggregate
    from tpuddp.resilience import watchdog

    for pid in range(4):
        aggregate.publish_shard(
            str(tmp_path), pid, {"window_index": pid, "step_time_ms_p50": 1.0}
        )
    removed = watchdog.purge_stale_peers(str(tmp_path), 2)
    assert removed == 2
    assert not os.path.exists(tmp_path / "hb_2")
    assert not os.path.exists(tmp_path / "hb_3")
    for pid in (0, 1):  # the live world keeps both liveness AND shards
        assert watchdog.read_heartbeat(str(tmp_path), pid) is not None
        assert aggregate.read_shard(str(tmp_path), pid)["window_index"] == pid


def _shard_dir(tmp_path, p50s, window=1):
    from tpuddp.observability import aggregate

    for pid, p50 in enumerate(p50s):
        aggregate.publish_shard(str(tmp_path), pid, {
            "window_index": window, "epoch": 0, "step": window * 4,
            "step_time_ms_p50": p50, "host_stall_ms": 1.0,
            "skipped_steps": 0, "samples_per_sec": 100.0,
        })


def test_pod_aggregator_percentiles_match_numpy(tmp_path):
    from tpuddp.observability.aggregate import PodAggregator

    p50s = [1.0, 2.0, 3.0, 10.0]
    _shard_dir(tmp_path, p50s)
    agg = PodAggregator(str(tmp_path), 4)
    merged = agg.update()
    assert merged["hosts_reporting"] == 4
    assert merged["pod_step_time_ms_p50"] == pytest.approx(
        np.median(p50s), rel=1e-6
    )
    assert merged["pod_step_time_ms_p95"] == pytest.approx(
        np.percentile(p50s, 95), rel=1e-6
    )
    assert merged["pod_step_time_ms_max"] == 10.0
    assert merged["pod_host_stall_ms"] == pytest.approx(4.0)
    assert merged["hosts"]["3"]["step_time_ms_p50"] == 10.0
    # empty dir -> None, never a crash
    empty = PodAggregator(str(tmp_path / "none"), 2)
    assert empty.update() is None


def test_straggler_fires_at_exact_ratio_and_window(tmp_path):
    """The detector's contract: a host over ratio x pod-median for EXACTLY
    `straggler_windows` consecutive fresh windows produces exactly ONE typed
    event naming it; uniform hosts never fire; a recovered host can fire
    again on relapse; a stalled (non-fresh) shard never extends a streak."""
    from tpuddp.observability.aggregate import PodAggregator

    written = []

    class W:
        def write(self, r):
            written.append(r)

    agg = PodAggregator(
        str(tmp_path), 4, writer=W(),
        straggler_ratio=1.5, straggler_windows=3,
    )
    # uniform pod: many windows, zero events
    for w in range(1, 5):
        _shard_dir(tmp_path, [1.0, 1.0, 1.1, 0.9], window=w)
        agg.update()
    assert written == [] and agg.straggler_events == 0

    # host 3 goes slow: 2.0 vs median ~1.0 -> ratio 2.0 > 1.5
    for w in range(5, 8):  # exactly 3 consecutive slow fresh windows
        _shard_dir(tmp_path, [1.0, 1.0, 1.0, 2.0], window=w)
        merged = agg.update()
        if w < 7:
            assert written == []  # not yet: needs 3 consecutive
    assert len(written) == 1
    ev = written[0]
    assert ev["type"] == "event" and ev["event"] == "straggler"
    assert ev["host"] == 3 and ev["windows"] == 3
    assert ev["ratio"] == pytest.approx(2.0)
    assert merged["stragglers"] == [3]
    # still slow: the SAME episode never re-fires
    _shard_dir(tmp_path, [1.0, 1.0, 1.0, 2.0], window=8)
    agg.update()
    assert len(written) == 1
    # a stalled shard (same window index) cannot extend/refire either
    agg2 = PodAggregator(
        str(tmp_path / "stall"), 2, writer=W(),
        straggler_ratio=1.5, straggler_windows=2,
    )
    os.makedirs(tmp_path / "stall", exist_ok=True)
    from tpuddp.observability import aggregate as agg_mod

    for pid, p50 in ((0, 1.0), (1, 5.0)):
        agg_mod.publish_shard(str(tmp_path / "stall"), pid, {
            "window_index": 1, "step_time_ms_p50": p50,
        })
    before = len(written)
    for _ in range(5):  # window never advances -> streak frozen at 1
        agg2.update()
    assert len(written) == before
    # recovery then relapse: a SECOND event is legitimate
    _shard_dir(tmp_path, [1.0, 1.0, 1.0, 1.0], window=9)
    agg.update()  # recovered
    for w in range(10, 13):
        _shard_dir(tmp_path, [1.0, 1.0, 1.0, 3.0], window=w)
        agg.update()
    assert len(written) == 2 and written[1]["host"] == 3
    # knob validation
    with pytest.raises(ValueError, match="straggler_ratio"):
        PodAggregator(str(tmp_path), 2, straggler_ratio=1.0)
    with pytest.raises(ValueError, match="straggler_windows"):
        PodAggregator(str(tmp_path), 2, straggler_windows=0)


# -------------------------------------------------------- flight recorder --


def test_flight_ring_bound_and_dump_validates(tmp_path):
    from tpuddp.observability.flight import FlightRecorder

    rec = FlightRecorder(str(tmp_path), capacity=3, process_index=0)
    rec.observe(schema_mod.make_run_meta(comm_hook="none"))
    for i in range(7):
        rec.observe(stamp("step_stats", {
            "epoch": 0, "step_start": i * 2, "steps": 2,
            "step_time_ms_p50": 1.0, "step_time_ms_p95": 1.0,
            "step_time_ms_p99": 1.0, "step_time_ms_max": 1.0,
            "samples_per_sec": 10.0, "host_stall_ms": 0.0,
            "inflight_depth": 0, "staging_queue_depth": 0,
        }))
    rec.observe(stamp("event", {"event": "preempt", "epoch": 0, "step": 14}))
    rec.note(emergency_step=14)
    path = rec.dump("preempt")
    assert path and os.path.basename(path) == "flightrec_preempt.json"
    errors, n = schema_mod.validate_flight_file(path)
    assert errors == [] and n == 4  # 3-capped step_stats ring + 1 event
    payload = json.load(open(path))
    assert payload["counts"]["step_stats"] == 3  # ring bound respected
    assert payload["records"]["step_stats"][-1]["step_start"] == 12
    assert payload["notes"]["emergency_step"] == 14
    assert payload["observed_records"] == 9
    # idempotent per reason
    assert rec.dump("preempt") == path
    # no save_dir -> None, never a crash
    assert FlightRecorder(None).dump("exception") is None


def test_flight_payload_drift_rejected():
    from tpuddp.observability.flight import FlightRecorder

    rec = FlightRecorder(None, capacity=4)
    rec.observe(stamp("event", {"event": "x"}))
    good = rec.payload("exception")
    assert schema_mod.validate_flight_payload(good) == []
    # unknown reason
    errs = schema_mod.validate_flight_payload(dict(good, reason="mystery"))
    assert any("unknown reason" in e for e in errs)
    # missing envelope field
    dropped = {k: v for k, v in good.items() if k != "counts"}
    assert any("counts" in e for e in schema_mod.validate_flight_payload(dropped))
    # a ring holding a record of the wrong type
    bad = json.loads(json.dumps(good))
    bad["records"]["step_stats"] = [stamp("event", {"event": "y"})]
    errs = schema_mod.validate_flight_payload(bad)
    assert any("does not belong" in e for e in errs)
    # newer-version reject
    errs = schema_mod.validate_flight_payload(
        dict(good, schema_version=schema_mod.SCHEMA_VERSION + 1)
    )
    assert any("newer" in e for e in errs)
    # wrong type marker
    errs = schema_mod.validate_flight_payload(dict(good, type="history"))
    assert any("flight_recording" in e for e in errs)


def test_flight_dump_on_loop_exception(mesh, tmp_path):
    """An unhandled exception in the native epoch driver leaves a validated
    flightrec_exception.json holding the run header and the records written
    before the crash."""
    class PoisonedLoader:
        def __init__(self, inner):
            self.inner = inner

        def __getattr__(self, name):
            return getattr(self.inner, name)

        def __len__(self):
            return len(self.inner)

        def __iter__(self):
            it = iter(self.inner)
            yield next(it)
            raise RuntimeError("injected loader failure")

    ds = SyntheticClassification(n=256, shape=(8, 8, 3), seed=0)
    loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    test_loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh
    )
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    with pytest.raises(RuntimeError, match="injected loader failure"):
        run_training_loop(
            ddp, state, PoisonedLoader(loader), test_loader, str(tmp_path),
            num_epochs=2, checkpoint_epoch=1, step_stats_every=2,
            log=lambda *_: None,
        )
    path = tmp_path / "flightrec_exception.json"
    assert path.exists()
    errors, _ = schema_mod.validate_flight_file(str(path))
    assert errors == []
    payload = json.load(open(path))
    assert payload["reason"] == "exception"
    assert payload["run_meta"]["api"] == "native"
    # the recorder registry is clean after the loop's finally
    from tpuddp.observability import flight as flight_mod

    assert flight_mod._registry == []


@pytest.mark.slow
def test_flight_dump_on_exit75_matches_emergency_checkpoint(tmp_path):
    """ISSUE 10 acceptance (chaos leg): an injected preempt drains to exit
    75 and leaves a tpuddp_inspect-valid flight recording whose emergency
    note and preempt event agree with the emergency checkpoint's step."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "TPUDDP_BACKEND": "cpu",
        "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
        "TPUDDP_FAULT": "preempt@epoch=1",
        "TPUDDP_CHAOS_TRAINING": '{"step_stats_every": 2}',
    })
    proc = subprocess.run(
        [sys.executable, "-u",
         os.path.join(repo, "tests", "_chaos_train_worker.py"),
         str(tmp_path), "3"],
        env=env, cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 75, proc.stdout + proc.stderr
    path = tmp_path / "flightrec_preempt.json"
    assert path.exists()
    # the CLI validates it (the gate's path)
    check = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "tpuddp_inspect.py"),
         "--validate", str(path)],
        capture_output=True, text=True, cwd=repo,
    )
    assert check.returncode == 0, check.stdout + check.stderr
    payload = json.load(open(path))
    assert payload["reason"] == "preempt"
    preempts = [
        e for e in payload["records"]["event"] if e["event"] == "preempt"
    ]
    assert len(preempts) == 1
    # the recording's last window ends at (or before) the emergency step,
    # and the notes name the checkpoint the drain wrote
    notes = payload["notes"]
    assert notes["emergency_step"] == preempts[0]["step"]
    assert os.path.exists(notes["emergency_checkpoint"])
    windows = payload["records"]["step_stats"]
    assert windows, "no step_stats windows retained"
    last = windows[-1]
    assert last["step_start"] + last["steps"] <= notes["emergency_step"]
    # the emergency checkpoint is the newest on disk and restores at the
    # epoch the preempt event names
    from tpuddp.training import checkpoint as _ckpt

    newest = _ckpt.latest(str(tmp_path))
    assert newest is not None
    assert os.path.basename(newest[0]) == os.path.basename(
        notes["emergency_checkpoint"]
    )


# --------------------------------------------------------- schema v5 drift --


def test_schema_v5_requires_observability_field(tmp_path):
    """Live-plane schema bump: a run_meta stamped v5+ without the
    ``observability`` key is drift; v4 headers keep validating at their own
    version; the shared make_run_meta always carries the key (null = plane
    off)."""
    meta = schema_mod.make_run_meta(
        comm_hook="none", observability={"exporter": False}
    )
    assert meta["schema_version"] >= 5
    assert schema_mod.validate_history_records([meta]) == []
    # null is legal (a minimal watchdog header)...
    assert schema_mod.validate_history_records(
        [schema_mod.make_run_meta(comm_hook=None)]
    ) == []
    # ...but ABSENCE at v5 is drift
    dropped = {k: v for k, v in meta.items() if k != "observability"}
    errs = schema_mod.validate_history_records([dropped])
    assert any("observability" in e for e in errs), errs
    # a v4 header without the field stays valid (its version's contract)
    v4 = dict(dropped, schema_version=4)
    assert schema_mod.validate_history_records([v4]) == []
    # the drift also fails through the file validator (the gate's path)
    p = tmp_path / "drift5.jsonl"
    p.write_text(json.dumps(dropped) + "\n")
    errors, _ = schema_mod.validate_history_file(str(p))
    assert any("observability" in e for e in errors)


# ------------------------------------- inspect: resumed-run attribution fix --


def test_inspect_attributes_rows_to_latest_header(tmp_path):
    """ISSUE 10 satellite: after an elastic shrink-resume the summary's
    per-epoch table marks which header owns each row and the grad-comm
    savings line uses ONLY the latest run segment — pre- and post-resume
    worlds never mix."""
    import subprocess
    import sys

    # a realistic shrink-resume stream: world 4 (16 B/update) then a resumed
    # world 2 (8 B/update, resumed_from_world=4), built from the real
    # make_run_meta/stamp writers so it validates at v5
    records = [
        schema_mod.make_run_meta(
            world_size=4, comm_hook="bf16_ef", comm_topology="flat",
            extra={
                "api": "native",
                "grad_comm_bytes_per_update": 16,
                "grad_comm_bytes_per_update_f32": 32,
            },
        ),
    ]

    def epoch_row(epoch, total):
        return stamp("epoch", {
            "epoch": epoch, "train_loss": 1.0, "test_loss": 1.0,
            "test_accuracy": 50.0, "train_samples": 256, "test_samples": 64,
            "epoch_time_s": 1.0, "samples_per_sec": 320.0,
            "step_time_ms_p50": 1.0, "step_time_ms_p95": 1.0,
            "step_time_ms_p99": 1.0, "step_time_ms_max": 1.0,
            "mfu_p50": None, "grad_comm_bytes_total": total,
        })

    records += [epoch_row(0, 160), epoch_row(1, 320)]
    records.append(schema_mod.make_run_meta(
        world_size=2, comm_hook="bf16_ef", comm_topology="flat",
        extra={
            "api": "native",
            "resumed_from_world": 4,
            "grad_comm_bytes_per_update": 8,
            "grad_comm_bytes_per_update_f32": 16,
        },
    ))
    records.append(stamp("event", {
        "event": "topology_change", "from_world": 4, "to_world": 2,
    }))
    records += [epoch_row(2, 80)]
    path = tmp_path / "history.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    assert schema_mod.validate_history_records(records) == []

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "tpuddp_inspect.py")
    out = subprocess.run(
        [sys.executable, tool, str(path)],
        capture_output=True, text=True, cwd=repo,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    # the table names the owning run per row
    assert "epochs (3 across 2 runs" in out.stdout
    lines = out.stdout.splitlines()
    run_col = [
        line.split() for line in lines
        if line.strip() and line.split()[0] in ("0", "1", "2")
        and len(line.split()) > 5
    ]
    by_epoch = {cells[1]: cells[0] for cells in run_col}
    assert by_epoch["0"] == "0" and by_epoch["1"] == "0"
    assert by_epoch["2"] == "1"  # the resumed epoch belongs to header 1
    # grad-comm savings come from the LATEST segment: 8 B/update vs 16 B
    # f32 and the resumed run's own 80 B total — not the old world's 320
    assert "8 B/update on the wire vs 16 B" in out.stdout
    assert "80 B total this run (latest of 2)" in out.stdout
    assert "320 B total" not in out.stdout
    # resumed provenance is surfaced in the header block
    assert "resumed_from_world: 4" in out.stdout


def test_inspect_real_resumed_history_gains_run_column(mesh, tmp_path):
    """The same attribution over a REAL resumed run (double-header history
    from the actual writers)."""
    import subprocess
    import sys

    ddp, (state, _) = small_run(mesh, str(tmp_path), num_epochs=1)
    restored, start = ckpt.restore_latest(
        str(tmp_path), ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    )
    small_run(
        mesh, str(tmp_path), num_epochs=2, start_epoch=start, state=restored
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "tpuddp_inspect.py"),
         str(tmp_path / "history.jsonl")],
        capture_output=True, text=True, cwd=repo,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "across 2 runs" in out.stdout


def test_inspect_validates_and_summarizes_flight_recording(tmp_path):
    """The CLI's flight kind: --validate accepts a real dump, the summary
    renders, and drift (bad reason) is refused."""
    import subprocess
    import sys

    from tpuddp.observability.flight import FlightRecorder

    rec = FlightRecorder(str(tmp_path), capacity=4)
    rec.observe(schema_mod.make_run_meta(comm_hook="none", extra={"api": "native"}))
    rec.observe(stamp("event", {"event": "preempt", "epoch": 1, "step": 8}))
    path = rec.dump("preempt")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "tpuddp_inspect.py")
    ok = subprocess.run(
        [sys.executable, tool, "--validate", path],
        capture_output=True, text=True, cwd=repo,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "flight record" in ok.stdout
    summary = subprocess.run(
        [sys.executable, tool, path], capture_output=True, text=True, cwd=repo,
    )
    assert summary.returncode == 0
    assert "reason=preempt" in summary.stdout
    assert "preempt" in summary.stdout
    bad = tmp_path / "flightrec_bogus.json"
    payload = json.load(open(path))
    payload["reason"] = "mystery"
    bad.write_text(json.dumps(payload))
    refused = subprocess.run(
        [sys.executable, tool, "--validate", str(bad)],
        capture_output=True, text=True, cwd=repo,
    )
    assert refused.returncode == 1
    assert "unknown reason" in refused.stderr


def test_supervisor_summarizes_flight_before_restart(tmp_path, caplog):
    """tools/supervise.py pickup: the supervisor logs the child's flight
    recording after an abnormal exit, BEFORE deciding the restart."""
    import logging as _logging

    from tpuddp.observability.flight import FlightRecorder
    from tpuddp.resilience.supervisor import RestartSupervisor, SupervisorPolicy

    calls = {"n": 0}

    def runner(argv, env):
        calls["n"] += 1
        if calls["n"] == 1:
            rec = FlightRecorder(str(tmp_path), capacity=4)
            rec.observe(schema_mod.make_run_meta(
                comm_hook="none", extra={"api": "native"}
            ))
            rec.observe(stamp("event", {"event": "preempt", "epoch": 0}))
            rec.dump("preempt")
            return 75
        return 0

    sup = RestartSupervisor(
        ["cmd"], policy=SupervisorPolicy(max_restarts=3),
        runner=runner, sleep=lambda s: None, flight_dir=str(tmp_path),
    )
    with caplog.at_level(_logging.WARNING, logger="tpuddp"):
        rc = sup.run()
    assert rc == 0 and calls["n"] == 2
    flight_lines = [
        r.message for r in caplog.records if "flight recording" in r.message
    ]
    assert flight_lines, "supervisor never summarized the recording"
    assert any("reason=preempt" in m for m in flight_lines)
    # the same recording is not re-summarized on later exits
    assert len([m for m in flight_lines if "reason=preempt" in m]) == 1


def test_exporter_escapes_label_values():
    """A caller-supplied label value (tenant id!) containing quotes,
    backslashes, or newlines must not corrupt the exposition page."""
    from tpuddp.observability.exporter import MetricsExporter

    e = MetricsExporter(port=0)
    e.register_source("t", lambda: {
        "serving_tenant_completed_total": {
            "type": "counter", "help": "h",
            "values": [({"tenant": 'acme"prod\\x\ny'}, 3)],
        },
    })
    text = e.render_prometheus()
    line = [l for l in text.splitlines() if l.startswith(
        "tpuddp_serving_tenant_completed_total{")][0]
    assert line == (
        'tpuddp_serving_tenant_completed_total'
        '{tenant="acme\\"prod\\\\x\\ny"} 3'
    )
    assert "\n\n" not in text  # no raw newline leaked mid-sample


def test_flight_dump_per_process_qualified(tmp_path):
    """On a shared save_dir, non-zero processes dump under their own name —
    a pod-wide death must not be last-rename-wins."""
    from tpuddp.observability.flight import FlightRecorder, find_recordings

    for pid in (0, 1, 2):
        rec = FlightRecorder(str(tmp_path), capacity=2, process_index=pid)
        rec.observe(stamp("event", {"event": "watchdog_stale", "process": pid}))
        rec.dump("watchdog")
    names = sorted(os.path.basename(p) for p in find_recordings(str(tmp_path)))
    assert names == [
        "flightrec_watchdog.json",
        "flightrec_watchdog_p1.json",
        "flightrec_watchdog_p2.json",
    ]
    for path in find_recordings(str(tmp_path)):
        errors, _ = schema_mod.validate_flight_file(path)
        assert errors == []


def test_exporter_port_file_per_process_name(tmp_path, monkeypatch):
    """exporter_from_config qualifies the discovery file by process index —
    the shared run dir must hold one file per serving host."""
    import jax as _jax

    from tpuddp.observability import exporter as exp_mod

    monkeypatch.setattr(_jax, "process_index", lambda: 2)
    e = exp_mod.exporter_from_config(
        {"exporter": True, "exporter_port": 0}, run_dir=str(tmp_path)
    )
    assert e.port_filename == "exporter_p2.port"
    e.start()
    try:
        assert int((tmp_path / "exporter_p2.port").read_text().splitlines()[0]) == e.port
        assert not (tmp_path / "exporter.port").exists()
    finally:
        e.stop()
    assert not (tmp_path / "exporter_p2.port").exists()
