"""Observability subsystems (SURVEY.md §5): metrics JSONL, NaN guard,
profiler env toggle, and loop resume."""

import json
import math
import os

import jax
import jax.numpy as jnp
import pytest

from tpuddp import optim
from tpuddp.data import ShardedDataLoader, SyntheticClassification
from tpuddp.models import ToyMLP
from tpuddp.nn import CrossEntropyLoss
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.training import checkpoint as ckpt
from tpuddp.training.loop import run_training_loop
from tpuddp.utils.observability import MetricsWriter, check_finite, json_sanitize


def small_run(mesh, save_dir, num_epochs=2, start_epoch=0, state=None):
    ds = SyntheticClassification(n=64, shape=(8, 8, 3), seed=0)
    loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    test_loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh
    )
    if state is None:
        state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    return ddp, run_training_loop(
        ddp, state, loader, test_loader, save_dir,
        num_epochs=num_epochs, checkpoint_epoch=1, start_epoch=start_epoch,
        log=lambda *_: None,
    )


def test_history_jsonl_written(mesh, tmp_path):
    _, (state, history) = small_run(mesh, str(tmp_path))
    path = tmp_path / "history.jsonl"
    assert path.exists()
    records = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(records) == 2
    assert records[0]["epoch"] == 0
    assert {"train_loss", "test_loss", "test_accuracy", "epoch_time_s"} <= set(records[0])


def test_checkpoints_every_epoch_and_resume(mesh, tmp_path):
    ddp, (state, history) = small_run(mesh, str(tmp_path), num_epochs=2)
    assert os.path.exists(tmp_path / "ckpt_0.npz")
    assert os.path.exists(tmp_path / "ckpt_1.npz")

    # resume: restore newest, continue for one more epoch
    template = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    restored, start = ckpt.restore_latest(str(tmp_path), template)
    assert start == 2
    assert int(restored.step) == int(state.step)
    _, (state2, history2) = small_run(
        mesh, str(tmp_path), num_epochs=3, start_epoch=start, state=restored
    )
    assert [h["epoch"] for h in history2] == [2]
    assert os.path.exists(tmp_path / "ckpt_2.npz")


def test_check_finite_guard(monkeypatch):
    check_finite(math.nan, "loss")  # disabled: no raise
    monkeypatch.setenv("TPUDDP_DEBUG_NANS", "1")
    check_finite(1.0, "loss")
    with pytest.raises(FloatingPointError, match="loss"):
        check_finite(math.nan, "loss")
    with pytest.raises(FloatingPointError):
        check_finite(math.inf, "loss")


def test_metrics_writer_none_dir_is_noop():
    w = MetricsWriter(None)
    w.write({"a": 1})  # no crash, nothing written
    assert w.path is None


def test_json_sanitize_nonfinite_to_null():
    """Strict-JSON contract (ISSUE 3 satellite): non-finite floats become
    None recursively; finite values and non-float types pass through."""
    rec = {
        "a": math.nan,
        "b": math.inf,
        "c": -math.inf,
        "d": 1.5,
        "e": "nan",  # strings are never touched
        "f": [math.nan, 2, {"g": math.inf}],
        "h": None,
        "i": 3,
    }
    out = json_sanitize(rec)
    assert out["a"] is None and out["b"] is None and out["c"] is None
    assert out["d"] == 1.5 and out["e"] == "nan" and out["i"] == 3
    assert out["f"] == [None, 2, {"g": None}]
    # and the sanitized record survives the strictest dumps
    json.dumps(out, allow_nan=False)


def test_metrics_writer_emits_null_not_nan(tmp_path, monkeypatch):
    """history.jsonl stays parseable by strict JSON consumers even when an
    epoch's metrics blew up."""
    w = MetricsWriter(str(tmp_path))
    w.write({"epoch": 0, "train_loss": math.nan, "test_loss": math.inf})
    w.close()
    raw = open(os.path.join(str(tmp_path), "history.jsonl")).read()
    assert "NaN" not in raw and "Infinity" not in raw
    row = json.loads(raw, parse_constant=lambda t: pytest.fail(f"bare {t}"))
    assert row["train_loss"] is None and row["test_loss"] is None


def test_profiler_env_toggle(monkeypatch, tmp_path, mesh):
    monkeypatch.setenv("TPUDDP_PROFILE", str(tmp_path / "trace"))
    small_run(mesh, str(tmp_path / "run"), num_epochs=1)
    # a trace directory with at least one artifact was produced
    trace_dir = tmp_path / "trace"
    assert trace_dir.exists()
    assert any(trace_dir.rglob("*"))
