"""Numerical guard (tpuddp/resilience/guard.py) — ISSUE 3 contracts.

Pinned here:

- config resolution: ``training.guard`` bool/dict forms, unknown-key refusal,
  validation of the policy knobs;
- the firewall: an injected non-finite gradient is a BITWISE no-op on
  params / optimizer state / EF residual / module buffers, across
  mode (shard_map, auto, managed) x comm hook (none, bf16, bf16_ef) x
  clip_grad_norm x grad accumulation x weight-update sharding, with the
  ``skipped_steps`` counters incrementing and ``consecutive`` resetting on
  the next applied update;
- clip-and-check compose on the f32 aggregated gradient before quantization:
  guarded compressed training stays on the unguarded trajectory bit-for-bit
  when nothing is skipped;
- zero-cost-off: a guard-disabled build lowers to the IDENTICAL program as a
  build that never heard of the guard, and guard-on adds no collectives to
  the replicated step;
- the desync auditor: agreement -> None, a single-device perturbation of a
  replicated leaf -> that leaf's path (torch ``_verify_params_across_
  processes`` semantics), wrap-time audit raises ReplicaDesync (exit 77
  contract);
- resume: ``skipped_steps`` and the bf16_ef residual survive a checkpoint
  round trip (native and managed), and pre-guard checkpoints load into a
  guarded template at zero;
- the epoch driver: ``nan@step=N`` injection skips exactly one update, the
  history row records it with strict-JSON null losses, and
  ``max_consecutive_skips`` triggers rollback-to-last-good that redoes the
  epoch from the restored state.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuddp import nn, optim
from tpuddp.data import ShardedDataLoader, SyntheticClassification
from tpuddp.models import ToyCNN, ToyMLP
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.resilience import faults
from tpuddp.resilience import guard as guard_lib
from tpuddp.training import checkpoint as ckpt
from tpuddp.training.loop import run_training_loop
from tpuddp.training.step import stack_batches

KEY = jax.random.key(0)


def make_batch(n=32, seed=5, nan=False):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8, 8, 3).astype(np.float32)
    if nan:
        x[0, 0, 0, 0] = np.nan
    y = rng.randint(0, 10, n).astype(np.int32)
    return x, y, np.ones(n, np.float32)


def build(mesh, guard=True, hook="none", mode="shard_map", wus=False,
          accum=1, clip=None, model=None):
    return DistributedDataParallel(
        model if model is not None else ToyMLP(hidden=(16,)),
        optim.Adam(1e-2),
        nn.CrossEntropyLoss(),
        mesh=mesh,
        mode=mode,
        comm_hook=hook,
        weight_update_sharding=wus,
        grad_accumulation=accum,
        clip_grad_norm=clip,
        guard=guard,
    )


def snapshot(state):
    return jax.tree_util.tree_map(
        np.asarray,
        (state.params, state.opt_state, state.comm_state, state.model_state),
    )


def assert_bitwise_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y)


# ----------------------------------------------------------------- config --


def test_resolve_guard_forms():
    assert not guard_lib.resolve_guard(None).enabled
    assert not guard_lib.resolve_guard(False).enabled
    assert guard_lib.resolve_guard(True).enabled
    cfg = guard_lib.resolve_guard({"max_consecutive_skips": 7, "on_desync": "rollback"})
    assert cfg.enabled and cfg.max_consecutive_skips == 7
    assert cfg.on_desync == "rollback"
    assert guard_lib.resolve_guard(cfg) is cfg
    assert guard_lib.resolve_guard({"enabled": False}).enabled is False


def test_resolve_guard_refuses_bad_input():
    with pytest.raises(ValueError, match="did you mean 'max_consecutive_skips'"):
        guard_lib.resolve_guard({"max_consecutive_skip": 1})
    with pytest.raises(ValueError, match="on_desync"):
        guard_lib.resolve_guard({"on_desync": "panic"})
    with pytest.raises(ValueError, match="max_consecutive_skips"):
        guard_lib.resolve_guard({"max_consecutive_skips": -1})
    with pytest.raises(ValueError, match="audit_every_n_epochs"):
        guard_lib.resolve_guard({"audit_every_n_epochs": 0})
    with pytest.raises(ValueError, match="bool or a mapping"):
        guard_lib.resolve_guard("on")


def test_nan_fault_spec_grammar():
    specs = faults.parse_fault_specs("nan@step=5")
    assert specs[0].kind == "nan" and specs[0].site == "step" and specs[0].arg == "5"
    # step=N also takes the process-killing kinds (the elastic-resume
    # mid-epoch kill scenarios, ISSUE 7) ...
    for kind in ("crash", "preempt"):
        spec = faults.parse_fault_specs(f"{kind}@step=5")[0]
        assert spec.kind == kind and spec.site == "step" and spec.arg == "5"
    # ... but hang/corrupt at step=N stay typos, and nan stays step-only
    with pytest.raises(ValueError, match="step"):
        faults.parse_fault_specs("hang@step=5")
    with pytest.raises(ValueError, match="step"):
        faults.parse_fault_specs("corrupt@step=5")
    with pytest.raises(ValueError, match="nan"):
        faults.parse_fault_specs("nan@epoch=5")


# --------------------------------------------------------------- firewall --


@pytest.mark.parametrize("mode", ["shard_map", "auto"])
@pytest.mark.parametrize("hook", ["none", "bf16", "bf16_ef", "int8_ef", "topk_ef"])
@pytest.mark.parametrize("clip", [None, 1.0])
def test_firewall_skips_bitwise(cpu_devices, mode, hook, clip):
    """The acceptance matrix: a non-finite gradient leaves params, optimizer
    moments, and the EF residual bitwise untouched, counts the skip, and the
    next finite step trains and resets ``consecutive``. The int8/top-k hooks
    ride the same contract: their NaN-poisoned max-abs scale decompresses
    the whole payload to NaN (comm.quantize_int8's guard-visibility
    contract), so the post-reduce f32 check still fires — and since scales
    are recomputed in-jit each step, the bitwise-unchanged ``comm_state``
    assertion doubles as the no-stale-scale-leakage proof."""
    mesh = make_mesh(cpu_devices)
    ddp = build(mesh, hook=hook, mode=mode, clip=clip)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    good, bad = make_batch(), make_batch(nan=True)
    st, _ = ddp.train_step(st, ddp.shard(good))
    before = snapshot(st)
    st, _ = ddp.train_step(st, ddp.shard(bad))
    assert_bitwise_equal(before, snapshot(st))
    assert guard_lib.read_skip_counters(st) == (1, 1)
    st, m = ddp.train_step(st, ddp.shard(good))
    assert guard_lib.read_skip_counters(st) == (1, 0)
    assert np.isfinite(float(np.sum(np.asarray(m["loss_sum"]))))
    changed = any(
        not np.array_equal(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(before[0]),
            jax.tree_util.tree_leaves(
                jax.tree_util.tree_map(np.asarray, st.params)
            ),
        )
    )
    assert changed, "a finite step after a skip must still train"


def test_firewall_with_wus_and_clip(cpu_devices):
    """Composition corner: weight-update sharding (collectives inside the
    cond branch) x bf16_ef x clip — the skip must also preserve the sharded
    optimizer moments and the per-replica residual."""
    mesh = make_mesh(cpu_devices)
    ddp = build(mesh, hook="bf16_ef", wus=True, clip=0.5)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    good, bad = make_batch(), make_batch(nan=True)
    st, _ = ddp.train_step(st, ddp.shard(good))
    assert np.any(np.asarray(st.comm_state) != 0)  # EF residual is live
    before = snapshot(st)
    st, _ = ddp.train_step(st, ddp.shard(bad))
    assert_bitwise_equal(before, snapshot(st))
    assert guard_lib.read_skip_counters(st) == (1, 1)


@pytest.mark.parametrize("hook", ["int8_ef", "topk_ef"])
def test_firewall_with_wus_quantized_hooks(cpu_devices, hook):
    """The new hooks' WUS composition corner (structured int8/top-k payload
    exchanged whole, own shard sliced): the skip preserves the sharded
    moments AND the full-length residual bitwise."""
    mesh = make_mesh(cpu_devices)
    ddp = build(mesh, hook=hook, wus=True, clip=0.5)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    good, bad = make_batch(), make_batch(nan=True)
    st, _ = ddp.train_step(st, ddp.shard(good))
    assert np.any(np.asarray(st.comm_state) != 0)  # EF residual is live
    before = snapshot(st)
    st, _ = ddp.train_step(st, ddp.shard(bad))
    assert_bitwise_equal(before, snapshot(st))
    assert guard_lib.read_skip_counters(st) == (1, 1)


def test_firewall_hierarchical_topology(cpu_devices):
    """The guard composes with the hierarchical multi-hop reduction: the
    poisoned shard's NaN scale survives the inter-host exchange and the
    all-gather, so every replica's post-reduce verdict agrees and the skip
    is bitwise — residual (with its shard-placed error layout) included."""
    from tpuddp.parallel.mesh import hierarchical_mesh

    mesh = hierarchical_mesh(devices=cpu_devices)
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), nn.CrossEntropyLoss(),
        mesh=mesh, mode="shard_map", comm_hook="int8_ef",
        comm_topology="hierarchical", guard=True,
    )
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    good, bad = make_batch(), make_batch(nan=True)
    st, _ = ddp.train_step(st, ddp.shard(good))
    assert np.any(np.asarray(st.comm_state) != 0)
    before = snapshot(st)
    st, _ = ddp.train_step(st, ddp.shard(bad))
    assert_bitwise_equal(before, snapshot(st))
    assert guard_lib.read_skip_counters(st) == (1, 1)
    st, m = ddp.train_step(st, ddp.shard(good))  # recovers
    assert np.isfinite(float(np.sum(np.asarray(m["loss_sum"]))))
    assert guard_lib.read_skip_counters(st) == (1, 0)


def test_firewall_skips_whole_accumulation_cycle(cpu_devices):
    """grad_accumulation: one poisoned micro-batch inside a cycle poisons the
    cycle's aggregated gradient — the ONE update of that cycle is skipped
    bitwise; clean cycles in the same dispatch still apply."""
    mesh = make_mesh(cpu_devices)
    ddp = build(mesh, hook="bf16_ef", accum=2, clip=1.0)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    good, bad = make_batch(), make_batch(nan=True)
    st, _ = ddp.train_step_many(st, ddp.shard_stacked(stack_batches([good, good])))
    before = snapshot(st)
    # dispatch of 2 cycles: [bad, good] skipped, [good, good] applied
    st, _ = ddp.train_step_many(
        st, ddp.shard_stacked(stack_batches([bad, good, good, good]))
    )
    assert guard_lib.read_skip_counters(st) == (1, 0)
    changed = any(
        not np.array_equal(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(before[0]),
            jax.tree_util.tree_leaves(
                jax.tree_util.tree_map(np.asarray, st.params)
            ),
        )
    )
    assert changed  # the second (clean) cycle applied
    # an all-poisoned dispatch is a full bitwise no-op
    before = snapshot(st)
    st, _ = ddp.train_step_many(
        st, ddp.shard_stacked(stack_batches([bad, good]))
    )
    assert_bitwise_equal(before, snapshot(st))
    assert guard_lib.read_skip_counters(st) == (2, 1)


def test_firewall_reverts_batchnorm_buffers(cpu_devices):
    """The no-op extends to module buffers: BN running stats computed from
    the poisoned forward must not outlive the skipped update."""
    mesh = make_mesh(cpu_devices)
    model = ToyCNN(num_classes=10, widths=(4,), sync_bn=True)
    nn.convert_sync_batchnorm(model)
    ddp = build(mesh, model=model)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    good, bad = make_batch(), make_batch(nan=True)
    st, _ = ddp.train_step(st, ddp.shard(good))
    before = snapshot(st)
    st, _ = ddp.train_step(st, ddp.shard(bad))
    assert_bitwise_equal(before, snapshot(st))  # model_state included
    assert guard_lib.read_skip_counters(st) == (1, 1)


def test_guarded_compressed_training_matches_unguarded(cpu_devices):
    """Clip-and-check happen on the f32 aggregated gradient BEFORE
    quantization: on an all-finite stream the guarded bf16_ef+clip run is
    bit-identical to the unguarded one — the guard only observes."""
    mesh = make_mesh(cpu_devices)

    def run(guard):
        ddp = build(mesh, guard=guard, hook="bf16_ef", clip=1.0)
        st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        for seed in range(4):
            st, _ = ddp.train_step(st, ddp.shard(make_batch(seed=seed)))
        return st

    a, b = run(True), run(False)
    assert_bitwise_equal(
        (a.params, a.opt_state, a.comm_state), (b.params, b.opt_state, b.comm_state)
    )
    assert guard_lib.read_skip_counters(a) == (0, 0)


# ------------------------------------------------------------ zero-cost-off --


def _lowered_step_text(ddp, st, batch):
    return jax.jit(lambda s, b: ddp.train_step(s, b)).lower(st, batch).as_text()


def test_guard_off_lowers_to_identical_program(cpu_devices):
    """training.guard off is a strict no-op: same lowered program as a build
    that never passed the knob — no extra collectives, no reshapes, nothing."""
    mesh = make_mesh(cpu_devices)
    batch = make_batch()

    def lower(guard):
        ddp = build(mesh, guard=guard, hook="bf16", clip=1.0)
        st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        return _lowered_step_text(ddp, st, ddp.shard(batch))

    assert lower(None) == lower({"enabled": False}) == lower(False)


def test_guard_on_adds_no_collectives_to_replicated_step(cpu_devices):
    """The happy-path cost model: on the replicated (non-wus) step the
    verdict is a replica-local reduction over the post-allreduce gradient —
    guard-on and guard-off programs carry the same collective count."""
    mesh = make_mesh(cpu_devices)
    batch = make_batch()

    def collectives(guard):
        ddp = build(mesh, guard=guard)
        st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        txt = _lowered_step_text(ddp, st, ddp.shard(batch))
        return sum(txt.count(op) for op in (
            "stablehlo.all_reduce", "stablehlo.reduce_scatter",
            "stablehlo.all_gather", "stablehlo.collective_permute",
        ))

    assert collectives(True) == collectives(None)


def test_guard_on_no_recompilation_across_calls(cpu_devices):
    """Epoch cadence: repeated guarded steps reuse one compiled program (the
    counters are carried state, not a new shape per epoch)."""
    mesh = make_mesh(cpu_devices)
    ddp = build(mesh)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    st, _ = ddp.train_step(st, ddp.shard(make_batch()))  # build + compile
    jitted = ddp._train_step  # the cached compiled closure
    for seed in range(3):
        st, _ = ddp.train_step(st, ddp.shard(make_batch(seed=seed)))
    assert ddp._train_step is jitted


# ----------------------------------------------------------------- auditor --


def _perturb_one_device(mesh, params, device_idx=3, delta=0.25):
    """A desynced world: one device's copy of the first leaf differs."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    host = np.asarray(leaves[0])
    shards = []
    for i, d in enumerate(mesh.devices.flat):
        h = host.copy()
        if i == device_idx:
            h.flat[0] += delta
        shards.append(jax.device_put(h, d))
    bad = jax.make_array_from_single_device_arrays(
        host.shape, NamedSharding(mesh, P()), shards
    )
    return jax.tree_util.tree_unflatten(treedef, [bad] + leaves[1:])


def test_auditor_accepts_synced_and_names_divergent_leaf(cpu_devices):
    mesh = make_mesh(cpu_devices)
    ddp = build(mesh)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    assert guard_lib.audit_params(mesh, st.params) is None
    bad = _perturb_one_device(mesh, st.params)
    leaf = guard_lib.audit_params(mesh, bad)
    assert leaf is not None
    flat = jax.tree_util.tree_flatten_with_path(st.params)[0]
    assert leaf == jax.tree_util.keystr(flat[0][0])  # names the FIRST leaf
    with pytest.raises(guard_lib.ReplicaDesync, match="exit 77"):
        guard_lib.audit_or_raise(mesh, bad, where="test")


def test_auditor_flags_nonfinite_params(cpu_devices):
    """All-replica-identical NaN params are still flagged: never a state
    worth training on, and pmax - pmin of NaN is NaN, not 0."""
    mesh = make_mesh(cpu_devices)
    ddp = build(mesh)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    leaves, treedef = jax.tree_util.tree_flatten(st.params)
    poisoned = jnp.asarray(np.asarray(leaves[0]) * np.nan)
    bad = jax.tree_util.tree_unflatten(treedef, [poisoned] + leaves[1:])
    assert guard_lib.audit_params(mesh, bad) is not None


def test_exit_desync_registered():
    from tpuddp.resilience import EXIT_DESYNC, EXIT_PREEMPTED, EXIT_WATCHDOG

    assert EXIT_DESYNC == 77
    assert len({EXIT_DESYNC, EXIT_PREEMPTED, EXIT_WATCHDOG}) == 3


# ------------------------------------------------------------------ resume --


def test_skip_counters_and_residual_survive_checkpoint(cpu_devices, tmp_path):
    """The resume contract: skipped_steps and the EF residual round-trip
    through the native checkpoint and the restored state keeps training with
    the counters intact."""
    mesh = make_mesh(cpu_devices)
    ddp = build(mesh, hook="bf16_ef")
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    st, _ = ddp.train_step(st, ddp.shard(make_batch()))
    st, _ = ddp.train_step(st, ddp.shard(make_batch(nan=True)))
    assert guard_lib.read_skip_counters(st) == (1, 1)
    res = np.asarray(st.comm_state)
    path = ckpt.save(str(tmp_path / "ckpt_1.npz"), st)

    ddp2 = build(mesh, hook="bf16_ef")
    st2 = ddp2.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    restored = ckpt.load(path, st2)
    assert guard_lib.read_skip_counters(restored) == (1, 1)
    np.testing.assert_array_equal(np.asarray(restored.comm_state), res)
    st3, _ = ddp2.train_step(restored, ddp2.shard(make_batch()))
    assert guard_lib.read_skip_counters(st3) == (1, 0)


def test_pre_guard_checkpoint_loads_into_guarded_template(cpu_devices, tmp_path):
    """Turning the guard ON over checkpoints from an unguarded run must
    resume with zeroed counters, not crash on the missing leaves."""
    mesh = make_mesh(cpu_devices)
    plain = build(mesh, guard=False)
    st = plain.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    path = ckpt.save(str(tmp_path / "ckpt_1.npz"), st)  # no skipped_steps leaves
    with np.load(path) as data:
        assert not any("skipped_steps" in k for k in data.files)
    guarded = build(mesh, guard=True)
    st2 = guarded.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    restored = ckpt.load(path, st2)
    assert guard_lib.read_skip_counters(restored) == (0, 0)
    st3, _ = guarded.train_step(restored, guarded.shard(make_batch(nan=True)))
    assert guard_lib.read_skip_counters(st3) == (1, 1)


def test_managed_accumulation_skip_reverts_buffers(cpu_devices):
    """The managed grad-accumulation path commits model_state eagerly per
    micro-batch (grad-only programs), so the guard's skip branch must hand
    the PRE-cycle buffers back — a poisoned cycle's BatchNorm running stats
    must not outlive the skipped update (the wedge where every later
    forward emits NaN)."""
    from tpuddp.accelerate import Accelerator

    mesh = make_mesh(cpu_devices)
    model_def = ToyCNN(num_classes=10, widths=(4,), sync_bn=False)
    x, y, w = make_batch()
    xb, yb, wb = make_batch(nan=True)
    criterion = nn.CrossEntropyLoss()
    acc = Accelerator(
        mesh=mesh, seed=0, guard=True, gradient_accumulation_steps=2
    )
    model, opt = acc.prepare(model_def, optim.Adam(1e-2))

    def cycle(batches):
        for bx, by, bw in batches:
            loss = criterion(model(bx), by, bw)
            acc.backward(loss)
            opt.step()

    cycle([(x, y, w), (x, y, w)])  # clean cycle
    before = jax.tree_util.tree_map(
        np.asarray, (model._params, model._model_state, opt.opt_state)
    )
    cycle([(xb, yb, wb), (x, y, w)])  # poisoned first micro-batch
    after = jax.tree_util.tree_map(
        np.asarray, (model._params, model._model_state, opt.opt_state)
    )
    assert_bitwise_equal(before, after)  # buffers included
    assert opt.skip_counters() == (1, 1)
    cycle([(x, y, w), (x, y, w)])  # recovers: finite forward, counters reset
    assert opt.skip_counters() == (1, 0)
    ev = criterion(model.eval()(x), y, w)
    assert np.isfinite(float(ev.item()))


def test_managed_guard_state_roundtrip(cpu_devices, tmp_path):
    """save_state/load_state carry the managed skip counters with the rest
    of the lossless state."""
    from tpuddp.accelerate import Accelerator

    mesh = make_mesh(cpu_devices)
    x, y, w = make_batch()
    xb, yb, wb = make_batch(nan=True)
    criterion = nn.CrossEntropyLoss()
    acc = Accelerator(mesh=mesh, seed=3, guard=True, comm_hook="bf16_ef")
    model, opt = acc.prepare(ToyMLP(hidden=(16,)), optim.Adam(1e-2))
    for bx, by, bw in ((x, y, w), (xb, yb, wb)):
        loss = criterion(model(bx), by, bw)
        acc.backward(loss)
        opt.step()
    assert opt.skip_counters() == (1, 1)
    acc.save_state(model, opt, str(tmp_path), epoch=0)

    acc2 = Accelerator(mesh=mesh, seed=3, guard=True, comm_hook="bf16_ef")
    model2, opt2 = acc2.prepare(ToyMLP(hidden=(16,)), optim.Adam(1e-2))
    model2(x[:1])
    assert acc2.load_state(model2, opt2, str(tmp_path)) == 1
    assert opt2.skip_counters() == (1, 1)
    loss = criterion(model2(x), y, w)
    acc2.backward(loss)
    opt2.step()
    assert opt2.skip_counters() == (1, 0)


# ------------------------------------------------------------ epoch driver --


def _loaders(mesh, n_train=64, batch=2):
    train = ShardedDataLoader(
        SyntheticClassification(n=n_train, shape=(8, 8, 3), seed=0),
        batch_size=batch, mesh=mesh, shuffle=True,
    )
    test = ShardedDataLoader(
        SyntheticClassification(n=16, shape=(8, 8, 3), seed=1),
        batch_size=batch, mesh=mesh,
    )
    return train, test


def test_loop_nan_injection_skips_and_records(cpu_devices, tmp_path, monkeypatch):
    """nan@step=N end to end through the epoch driver: exactly one skipped
    update, the epoch's history row carries the skip counters with
    strict-JSON null losses, later epochs are finite, and the final params
    are finite."""
    monkeypatch.setenv("TPUDDP_FAULT", "nan@step=3")
    faults.reload_faults()
    try:
        mesh = make_mesh(cpu_devices)
        train, test = _loaders(mesh)
        ddp = build(mesh, guard={"audit_every_n_epochs": 1})
        st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        st, hist = run_training_loop(
            ddp, st, train, test, str(tmp_path), num_epochs=2,
            checkpoint_epoch=1, scan_steps=2, per_replica_log=False,
            log=lambda *a: None,
        )
        lines = [
            json.loads(l) for l in open(os.path.join(str(tmp_path), "history.jsonl"))
        ]
        rows = [l for l in lines if "train_loss" in l]
        assert rows[0]["skipped_steps"] == 1
        assert rows[0]["skipped_steps_epoch"] == 1
        assert rows[0]["train_loss"] is None  # NaN -> null, strict JSON
        assert rows[1]["skipped_steps_epoch"] == 0
        assert rows[1]["train_loss"] is not None
        assert all(
            np.all(np.isfinite(l)) for l in jax.tree_util.tree_leaves(
                jax.tree_util.tree_map(np.asarray, st.params)
            )
        )
    finally:
        faults.reload_faults()


def test_loop_rolls_back_to_last_good(cpu_devices, tmp_path, monkeypatch):
    """max_consecutive_skips exceeded at an epoch boundary: the driver
    restores the newest intact checkpoint, records the rollback event in
    history.jsonl, redoes the epoch (set_epoch re-derives its data order),
    and finishes clean once the fault does not recur."""
    # 4 batches/epoch at scan_steps=2: step 7 is epoch 1's LAST update, so
    # `consecutive` is still 1 when the driver reads the counters
    monkeypatch.setenv("TPUDDP_FAULT", "nan@step=7")
    faults.reload_faults()
    try:
        mesh = make_mesh(cpu_devices)
        train, test = _loaders(mesh)
        ddp = build(mesh, guard={"max_consecutive_skips": 0})
        st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        msgs = []
        st, hist = run_training_loop(
            ddp, st, train, test, str(tmp_path), num_epochs=3,
            checkpoint_epoch=1, scan_steps=2, per_replica_log=False,
            log=msgs.append,
        )
        lines = [
            json.loads(l) for l in open(os.path.join(str(tmp_path), "history.jsonl"))
        ]
        events = [l for l in lines if l.get("event") == "rollback"]
        assert events and events[0]["epoch"] == 1 and events[0]["resume_epoch"] == 1
        assert [l["epoch"] for l in lines if "train_loss" in l] == [0, 1, 1, 2]
        assert any("Guard rollback" in m for m in msgs)
    finally:
        faults.reload_faults()


def test_loop_rollback_without_checkpoint_raises(cpu_devices, monkeypatch):
    """No save_dir -> nothing to roll back to: the overflow surfaces as a
    FloatingPointError instead of looping on a poisoned trajectory."""
    monkeypatch.setenv("TPUDDP_FAULT", "nan@step=3")
    faults.reload_faults()
    try:
        mesh = make_mesh(cpu_devices)
        train, test = _loaders(mesh, n_train=8)  # 1 batch/epoch: skip IS the epoch
        ddp = build(mesh, guard={"max_consecutive_skips": 0})
        st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
        # steps 0..2 are epochs 0-2 (finite); step 3 poisons epoch 3's only update
        with pytest.raises(FloatingPointError, match="no checkpoint"):
            run_training_loop(
                ddp, st, train, test, None, num_epochs=6, checkpoint_epoch=1,
                scan_steps=1, per_replica_log=False, log=lambda *a: None,
            )
    finally:
        faults.reload_faults()


def test_loop_periodic_audit_trips_on_desync(cpu_devices, tmp_path):
    """audit_every_n_epochs: a single-replica perturbation injected between
    epochs is caught at the next epoch-start audit and raises ReplicaDesync
    (on_desync="exit"), with the divergence event recorded."""
    mesh = make_mesh(cpu_devices)
    train, test = _loaders(mesh)
    ddp = build(mesh, guard={"audit_every_n_epochs": 1})
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    st = __import__("dataclasses").replace(
        st, params=_perturb_one_device(mesh, st.params)
    )
    with pytest.raises(guard_lib.ReplicaDesync, match="audit"):
        run_training_loop(
            ddp, st, train, test, str(tmp_path), num_epochs=2,
            checkpoint_epoch=1, scan_steps=2, per_replica_log=False,
            log=lambda *a: None,
        )
    lines = [
        json.loads(l) for l in open(os.path.join(str(tmp_path), "history.jsonl"))
    ]
    assert any(l.get("event") == "desync" for l in lines)


def test_loop_desync_rollback_recovers(cpu_devices, tmp_path):
    """on_desync="rollback": with an intact checkpoint on disk, the desynced
    state is thrown away, the run restores and completes clean."""
    mesh = make_mesh(cpu_devices)
    train, test = _loaders(mesh)
    ddp = build(mesh, guard={"audit_every_n_epochs": 1, "on_desync": "rollback"})
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    # epoch 0 trains clean and checkpoints; then we desync and resume at 1
    st, _ = run_training_loop(
        ddp, st, train, test, str(tmp_path), num_epochs=1, checkpoint_epoch=1,
        scan_steps=2, per_replica_log=False, log=lambda *a: None,
    )
    bad = __import__("dataclasses").replace(
        st, params=_perturb_one_device(mesh, st.params)
    )
    st2, _ = run_training_loop(
        ddp, bad, train, test, str(tmp_path), num_epochs=3, checkpoint_epoch=1,
        scan_steps=2, per_replica_log=False, start_epoch=1, log=lambda *a: None,
    )
    lines = [
        json.loads(l) for l in open(os.path.join(str(tmp_path), "history.jsonl"))
    ]
    assert any(l.get("event") == "rollback" for l in lines)
    assert guard_lib.audit_params(mesh, st2.params) is None  # resynced
    assert [l["epoch"] for l in lines if "train_loss" in l] == [0, 1, 2]


def test_managed_loop_rolls_back_to_last_good(cpu_devices, tmp_path):
    """The managed epoch driver honors the same rollback policy as the
    native one: a fully-poisoned epoch (every update skipped, consecutive
    run over the limit) restores the newest state_{epoch}.npz via
    load_state, records the rollback, redoes the epoch, and finishes clean
    — never exit 0 with silently frozen weights."""
    import train_accelerate as ta
    from tpuddp.accelerate import Accelerator
    from tpuddp.data import DataLoader

    mesh = make_mesh(cpu_devices)
    ds = SyntheticClassification(n=32, shape=(8, 8, 3), seed=0)  # float32
    test_ds = SyntheticClassification(n=8, shape=(8, 8, 3), seed=1)
    clean = ds.images.copy()
    acc = Accelerator(mesh=mesh, seed=0, guard={"max_consecutive_skips": 0})
    model, opt, loader = acc.prepare(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), DataLoader(ds, batch_size=8)
    )

    class PoisonEpochOnce:
        """Wrapper loader: the FIRST time epoch 1 starts, every sample goes
        NaN (the whole epoch's updates skip); the redo sees clean data."""

        def __init__(self, inner):
            self.inner = inner
            self.fired = False

        def set_epoch(self, e):
            self.inner.set_epoch(e)
            if e == 1 and not self.fired:
                self.fired = True
                ds.images[:] = np.nan
            else:
                ds.images[:] = clean

        def __len__(self):
            return len(self.inner)

        def __iter__(self):
            return iter(self.inner)

    augment = jax.jit(lambda rng, i, x: x)
    transform = jax.jit(lambda x: x)
    ta.run_training_loop(
        model, PoisonEpochOnce(loader), DataLoader(test_ds, batch_size=8),
        nn.CrossEntropyLoss(), opt, str(tmp_path), acc, augment, transform,
        num_epochs=3, checkpoint_epoch=1,
    )
    rows = [
        json.loads(l) for l in open(os.path.join(str(tmp_path), "history.jsonl"))
    ]
    events = [r for r in rows if r.get("event") == "rollback"]
    assert events and events[0]["epoch"] == 1 and events[0]["resume_epoch"] == 1
    assert [r["epoch"] for r in rows if "train_loss" in r] == [0, 1, 1, 2]
    assert opt.skip_counters()[1] == 0  # the redo applied real updates
    leaves = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, model.params)
    )
    assert all(np.all(np.isfinite(l)) for l in leaves)


def test_history_jsonl_is_strict_json(cpu_devices, tmp_path):
    """Satellite: the empty-test-loader path writes NaN test metrics —
    history.jsonl must still be strict JSON (null), and every line must
    round-trip through a parser that refuses NaN tokens."""
    mesh = make_mesh(cpu_devices)
    train = ShardedDataLoader(
        SyntheticClassification(n=16, shape=(8, 8, 3), seed=0),
        batch_size=2, mesh=mesh, shuffle=True,
    )
    empty = ShardedDataLoader(
        SyntheticClassification(n=0, shape=(8, 8, 3), seed=1),
        batch_size=2, mesh=mesh,
    )
    ddp = build(mesh, guard=False)
    st = ddp.init_state(KEY, jnp.zeros((1, 8, 8, 3)))
    run_training_loop(
        ddp, st, train, empty, str(tmp_path), num_epochs=1, checkpoint_epoch=5,
        scan_steps=1, per_replica_log=False, log=lambda *a: None,
    )
    raw = open(os.path.join(str(tmp_path), "history.jsonl")).read()
    assert "NaN" not in raw and "Infinity" not in raw

    def reject_nan(tok):
        raise AssertionError(f"non-strict token {tok!r} in history.jsonl")

    rows = [
        json.loads(line, parse_constant=reject_nan)
        for line in raw.splitlines()
    ]
    rows = [r for r in rows if r.get("type") == "epoch"]
    assert rows[0]["test_loss"] is None and rows[0]["test_accuracy"] is None
    assert np.isfinite(rows[0]["train_loss"])
