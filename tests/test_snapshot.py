"""Async step-granular checkpointing (ISSUE 18): config resolution, the v4
data cursor, async-vs-sync byte identity, mixed-family retention,
peer-redundant placement, queue-full no-block, and EXACT mid-epoch resume
with bitwise loss parity — on both the native and managed drivers, all
in-process on the 8-device CPU world. The subprocess-kill scenarios live in
test_chaos.py (chaos marker)."""

import json
import logging
import os
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import optim
from tpuddp.data import ShardedDataLoader, SyntheticClassification
from tpuddp.models import ToyMLP
from tpuddp.nn import CrossEntropyLoss
from tpuddp.observability import schema as schema_mod
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.resilience import integrity, preemption
from tpuddp.resilience.preemption import TrainingPreempted
from tpuddp.training import checkpoint as ckpt
from tpuddp.training import snapshot as snap_mod
from tpuddp.training.loop import run_training_loop
from tpuddp.training.snapshot import (
    EpochTailLoader,
    SnapshotConfig,
    SnapshotEngine,
    acc_from_cursor,
    epoch_plan_key,
    resolve_snapshot,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ config


def test_resolve_snapshot_off_and_defaults():
    assert not resolve_snapshot(None).enabled
    assert not resolve_snapshot(False).enabled
    on = resolve_snapshot(True)
    assert on.enabled and on.every_steps == 50
    assert on.async_writes and on.inflight == 2 and not on.peer_redundancy
    # the serialized block uses the config KEY "async", not the field name
    assert resolve_snapshot({"every_steps": 3, "async": False}).as_dict() == {
        "every_steps": 3, "async": False, "inflight": 2,
        "peer_redundancy": False,
    }
    # every_steps == 0 is a valid explicit off
    assert not resolve_snapshot({"every_steps": 0}).enabled
    # idempotent on an already-resolved config
    cfg = SnapshotConfig(every_steps=7)
    assert resolve_snapshot(cfg) is cfg


def test_resolve_snapshot_refuses_unknown_keys_and_bad_values():
    with pytest.raises(ValueError, match="every_step"):
        resolve_snapshot({"every_step": 3})  # typo -> refused, with hint
    with pytest.raises(ValueError, match="must be a mapping"):
        resolve_snapshot("every 5")
    with pytest.raises(ValueError, match="every_steps"):
        resolve_snapshot({"every_steps": -1})
    with pytest.raises(ValueError, match="inflight"):
        resolve_snapshot({"inflight": 0})


# ------------------------------------------------------------------ cursor


def make_state():
    from tpuddp.training.train_state import create_train_state

    return create_train_state(
        ToyMLP(hidden=(8,)), optim.Adam(1e-3), jax.random.key(0),
        jnp.zeros((1, 4, 4, 3)),
    )


def assert_tree_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        a, b,
    )


def test_cursor_round_trip(tmp_path):
    state = make_state()
    acc = {
        "loss_sum": jnp.asarray(1.5, jnp.float32),
        "n": jnp.asarray(192.0, jnp.float32),
        "ef": jnp.ones((4,), jnp.bfloat16),  # bf16 leaf: the packed lane
    }
    path = ckpt.save_on_main(
        str(tmp_path), 2, state, step=6,
        cursor={"version": ckpt.FORMAT_VERSION, "epoch": 2, "step": 6,
                "plan_key": "abcd" * 4},
        cursor_acc=acc,
    )
    assert os.path.basename(path) == "ckpt_2_s6.npz"
    assert ckpt.read_meta(path) == {"epoch": 2, "completed": 0, "step": 6}
    cur = ckpt.read_cursor(path)
    assert cur["epoch"] == 2 and cur["step"] == 6
    assert cur["plan_key"] == "abcd" * 4
    assert cur["version"] == ckpt.FORMAT_VERSION
    got = acc_from_cursor(cur)
    assert set(got) == {"loss_sum", "n", "ef"}
    np.testing.assert_array_equal(got["loss_sum"], np.asarray(1.5, np.float32))
    assert np.asarray(got["ef"]).dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(got["ef"], np.float32), np.ones((4,), np.float32)
    )
    # a full-epoch save carries no cursor
    full = ckpt.save_on_main(str(tmp_path), 2, state)
    assert ckpt.read_cursor(full) is None
    assert acc_from_cursor(None) is None


def test_restore_latest_surfaces_cursor_and_family_order(tmp_path, caplog):
    state = make_state()
    ckpt.save_on_main(str(tmp_path), 0, state)  # full epoch 0
    ckpt.save_on_main(
        str(tmp_path), 1, state, step=4,
        cursor={"epoch": 1, "step": 4, "plan_key": "k1"},
    )
    # the step snapshot of epoch 1 outranks the full epoch-0 file
    cursor_out = []
    with caplog.at_level(logging.WARNING, logger="tpuddp"):
        _, next_epoch = ckpt.restore_latest(
            str(tmp_path), state, cursor_out=cursor_out
        )
    assert next_epoch == 1  # the cursor's epoch: continue it, don't redo
    (entry,) = cursor_out
    assert entry["step"] == 4 and entry["plan_key"] == "k1"
    assert entry["provenance"] == "local"
    assert any("zero batches replayed" in r.message for r in caplog.records)
    # ...but a full-epoch save of the SAME epoch ranks newer than its steps
    ckpt.save_on_main(str(tmp_path), 1, state)
    cursor_out = []
    _, next_epoch = ckpt.restore_latest(
        str(tmp_path), state, cursor_out=cursor_out
    )
    assert next_epoch == 2 and cursor_out == []


# ------------------------------------------------------------ byte identity


def test_async_snapshot_byte_identical_to_sync_save(tmp_path):
    """The matrix: engine-async, engine-sync, and a direct synchronous
    ``save_on_main`` of the same (state, cursor) must publish byte-identical
    ``.npz`` and ``.sha256`` files — mode-dependent facts (writer stats)
    live in the ``.writer.json`` sidecar, never the payload."""
    state = make_state()
    pk = "plan" * 4
    dirs = {}
    for mode, async_writes in (("async", True), ("sync", False)):
        d = tmp_path / mode
        engine = SnapshotEngine(
            str(d),
            SnapshotConfig(every_steps=4, async_writes=async_writes),
        )
        assert engine.maybe(state, epoch=0, step=4, plan_key=pk)
        assert engine.flush() == 4
        engine.close()
        dirs[mode] = d
    d = tmp_path / "direct"
    ckpt.save_on_main(
        str(d), 0, state, step=4,
        cursor={"version": ckpt.FORMAT_VERSION, "epoch": 0, "step": 4,
                "plan_key": pk},
    )
    dirs["direct"] = d
    blobs = {
        mode: (d / "ckpt_0_s4.npz").read_bytes() for mode, d in dirs.items()
    }
    assert blobs["async"] == blobs["sync"] == blobs["direct"]
    manifests = {
        mode: (d / "ckpt_0_s4.npz.sha256").read_bytes()
        for mode, d in dirs.items()
    }
    assert manifests["async"] == manifests["sync"] == manifests["direct"]
    # writer stats exist for the engine modes, outside the payload
    ws = snap_mod.read_writer_stats(str(dirs["async"] / "ckpt_0_s4.npz"))
    assert ws["snapshots"] == 1 and ws["async"] is True
    with np.load(dirs["async"] / "ckpt_0_s4.npz") as f:
        assert not any("writer" in k for k in f.files)


# ---------------------------------------------------------------- retention


def test_keep_last_orders_mixed_families_and_keeps_newest_full(tmp_path):
    """Retention across interleaved step/epoch files: keep_last counts by
    (epoch, step) recency, and the newest INTACT full-epoch checkpoint is
    never collected even when step snapshots outrank it."""
    state = make_state()
    ckpt.save_on_main(str(tmp_path), 0, state)  # full epoch 0
    for s in (2, 4):
        ckpt.save_on_main(str(tmp_path), 1, state, step=s)
    ckpt.prune_checkpoints(str(tmp_path), keep_last=2)
    kept = sorted(f for f in os.listdir(tmp_path) if f.endswith(".npz"))
    # keep_last=2 keeps the two newest (both epoch-1 steps) AND the hard
    # floor keeps ckpt_0.npz — the only epoch-granular fallback left
    assert kept == ["ckpt_0.npz", "ckpt_1_s2.npz", "ckpt_1_s4.npz"]
    # a full-epoch save of epoch 1 now outranks its own step snapshots:
    # the steps age out, the new full file is the floor
    ckpt.save_on_main(str(tmp_path), 1, state, keep_last=2)
    kept = sorted(f for f in os.listdir(tmp_path) if f.endswith(".npz"))
    assert kept == ["ckpt_1.npz", "ckpt_1_s4.npz"]
    sidecars = sorted(f for f in os.listdir(tmp_path) if f.endswith(".sha256"))
    assert sidecars == ["ckpt_1.npz.sha256", "ckpt_1_s4.npz.sha256"]


def test_stale_tmp_sweep_covers_step_files(tmp_path):
    state = make_state()
    ckpt.save_on_main(str(tmp_path), 0, state, step=3)
    (tmp_path / "ckpt_0_s6.npz.tmp").write_bytes(b"half")
    (tmp_path / "ckpt_0_s6.npz.sha256.tmp").write_bytes(b"half")
    assert ckpt.sweep_stale_tmp(str(tmp_path)) == 2
    assert (tmp_path / "ckpt_0_s3.npz").exists()


# ----------------------------------------------------------- peer redundancy


def test_peer_spill_and_restore_from_peer(tmp_path, monkeypatch, caplog):
    """With peer_redundancy on, the engine spills each published snapshot
    into the ring neighbor's directory under the heartbeat channel; losing
    the local copy must still yield a full restore, with the peer
    provenance logged and surfaced."""
    hb = tmp_path / "hb"
    monkeypatch.setenv("TPUDDP_HEARTBEAT_DIR", str(hb))
    local = tmp_path / "run"
    state = make_state()
    engine = SnapshotEngine(
        str(local),
        SnapshotConfig(every_steps=2, async_writes=False, peer_redundancy=True),
    )
    assert engine.maybe(state, epoch=0, step=2, plan_key="pk")
    engine.close()
    peer_file = hb / "peer_ckpt" / "ring_0" / "ckpt_0_s2.npz"
    assert peer_file.exists()
    assert integrity.verify_file(str(peer_file))
    assert ckpt.peer_checkpoint_dirs(str(local)) == [
        str(hb / "peer_ckpt" / "ring_0")
    ]
    # the peer copy is byte-identical to the local publish
    assert peer_file.read_bytes() == (local / "ckpt_0_s2.npz").read_bytes()
    # lose the local host's checkpoint directory entirely
    os.remove(local / "ckpt_0_s2.npz")
    os.remove(local / "ckpt_0_s2.npz.sha256")
    found = ckpt._latest_any(str(local))
    assert found is not None
    path, epoch, step, prov = found
    assert (epoch, step, prov) == (0, 2, "peer:ring_0")
    cursor_out = []
    with caplog.at_level(logging.WARNING, logger="tpuddp"):
        restored, next_epoch = ckpt.restore_latest(
            str(local), state, cursor_out=cursor_out
        )
    assert next_epoch == 0
    assert cursor_out[0]["provenance"] == "peer:ring_0"
    assert_tree_equal(restored.params, state.params)
    assert any("provenance=peer:ring_0" in r.message for r in caplog.records)


def test_corrupt_local_falls_back_to_peer_copy(tmp_path, monkeypatch):
    hb = tmp_path / "hb"
    monkeypatch.setenv("TPUDDP_HEARTBEAT_DIR", str(hb))
    local = tmp_path / "run"
    state = make_state()
    engine = SnapshotEngine(
        str(local),
        SnapshotConfig(every_steps=2, async_writes=False, peer_redundancy=True),
    )
    assert engine.maybe(state, epoch=0, step=2, plan_key="pk")
    engine.close()
    # torn local write: header garbage, manifest now stale
    with open(local / "ckpt_0_s2.npz", "r+b") as f:
        f.write(b"\x00CHAOS\x00")
        f.truncate(64)
    path, epoch, step, prov = ckpt._latest_any(str(local))
    assert prov == "peer:ring_0" and (epoch, step) == (0, 2)


# -------------------------------------------------------- queue-full no-block


def test_full_writer_queue_skips_without_blocking(tmp_path, monkeypatch):
    """The no-stall contract: a full bounded queue means the snapshot is
    SKIPPED (counted), never waited for — maybe() must return immediately
    even while the writer is wedged mid-serialize."""
    state = make_state()
    gate = threading.Event()
    real_save = ckpt.save

    def slow_save(*args, **kwargs):
        gate.wait(timeout=30)
        return real_save(*args, **kwargs)

    monkeypatch.setattr(ckpt, "save", slow_save)
    engine = SnapshotEngine(
        str(tmp_path), SnapshotConfig(every_steps=1, inflight=1)
    )
    try:
        assert engine.maybe(state, epoch=0, step=1, plan_key="pk")
        # the writer thread is now wedged inside slow_save; fill the queue
        deadline = time.time() + 10
        queued = False
        while time.time() < deadline:
            if engine.maybe(state, epoch=0, step=engine._next_due, plan_key="pk"):
                queued = True
                break
            time.sleep(0.01)
        assert queued  # inflight=1 slot occupied while the writer is wedged
        t0 = time.perf_counter()
        took = engine.maybe(state, epoch=0, step=engine._next_due, plan_key="pk")
        elapsed = time.perf_counter() - t0
        assert not took
        assert elapsed < 1.0  # skipped, not blocked on the wedged writer
        assert engine.stats["skipped_queue_full"] >= 1
    finally:
        gate.set()
        engine.close()
    assert engine.stats["snapshots"] == 2


# ------------------------------------------------------------ plan key / tail


class _Delegating:
    """Test wrapper with an ``inner`` attr — the shape of the chaos/test
    loaders the plan key must see through."""

    def __init__(self, inner):
        self.inner = inner

    def __len__(self):
        return len(self.inner)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def test_epoch_plan_key_wrapper_invariance_and_sensitivity(mesh):
    ds = SyntheticClassification(n=128, shape=(4, 4, 3), seed=0)
    loader = ShardedDataLoader(ds, 4, mesh, shuffle=True)
    key = epoch_plan_key(loader, 0)
    assert epoch_plan_key(_Delegating(loader), 0) == key
    assert epoch_plan_key(EpochTailLoader(loader, 0), 0) == key
    # anything that changes the batch order changes the key
    assert epoch_plan_key(loader, 1) != key
    other = ShardedDataLoader(
        SyntheticClassification(n=128, shape=(4, 4, 3), seed=1),
        4, mesh, shuffle=True, seed=7,
    )
    assert epoch_plan_key(other, 0) != key
    # stable across processes/runs: a pure function of the plan inputs
    assert epoch_plan_key(loader, 0) == key


def test_epoch_tail_loader_zero_replay():
    fetched = []

    class Planned:
        def __len__(self):
            return 8

        def make_batch_plan(self):
            def fetch(s):
                fetched.append(s)
                return s * 10
            return 8, fetch

    tail = EpochTailLoader(Planned(), 5)
    assert len(tail) == 3
    assert list(tail) == [50, 60, 70]
    assert fetched == [5, 6, 7]  # the applied prefix was never assembled

    class Unplanned:
        def __iter__(self):
            return iter(range(8))

        def __len__(self):
            return 8

    assert list(EpochTailLoader(Unplanned(), 6)) == [6, 7]


# ------------------------------------------------- exact resume (native) ----


@pytest.fixture
def preempt_guard(monkeypatch):
    monkeypatch.setenv("TPUDDP_PREEMPT_GRACE", "3600")
    preemption.reset_preemption()
    yield
    preemption.reset_preemption()


class _PreemptingLoader:
    def __init__(self, inner, after):
        self.inner = inner
        self.after = after

    def __len__(self):
        return len(self.inner)

    def set_epoch(self, epoch):
        self.inner.set_epoch(epoch)

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def __iter__(self):
        for i, batch in enumerate(self.inner):
            if i == self.after:
                preemption.request_preemption()
            yield batch


def _toy_ddp(mesh):
    ds = SyntheticClassification(n=512, shape=(8, 8, 3), seed=0)
    loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    test_loader = ShardedDataLoader(ds, 8, mesh, shuffle=True)
    ddp = DistributedDataParallel(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), CrossEntropyLoss(), mesh=mesh
    )
    state = ddp.init_state(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))
    return ddp, state, loader, test_loader


SNAP = {"every_steps": 3, "async": True, "inflight": 2}


def test_native_exact_resume_bitwise_parity(mesh, tmp_path, preempt_guard):
    """The tentpole contract end-to-end: SIGTERM mid-epoch with the engine
    armed -> the drain flushes the async writer and lands a step snapshot
    -> auto_resume continues the epoch AT the recorded step (zero batches
    replayed) -> the loss trajectory is BITWISE-equal to an uninterrupted
    same-seed run. Retires the 'redo the interrupted epoch' contract."""
    ref_dir = tmp_path / "ref"
    run_dir = tmp_path / "run"
    ddp, state, loader, test_loader = _toy_ddp(mesh)
    _, hist_ref = run_training_loop(
        ddp, state, loader, test_loader, str(ref_dir), num_epochs=2,
        checkpoint_epoch=1, scan_steps=1, snapshot=SNAP, log=lambda *_: None,
    )
    ref = {h["epoch"]: h["train_loss"] for h in hist_ref}

    preemption.reset_preemption()
    ddp, state, loader, test_loader = _toy_ddp(mesh)
    with pytest.raises(TrainingPreempted) as ei:
        run_training_loop(
            ddp, state, _PreemptingLoader(loader, after=5), test_loader,
            str(run_dir), num_epochs=2, checkpoint_epoch=1, scan_steps=1,
            snapshot=SNAP, log=lambda *_: None,
        )
    assert ei.value.epoch == 0
    # the drain reused the writer's flush path: the emergency artifact IS a
    # step snapshot (cursor-bearing), not a legacy ckpt_0.npz. The exact
    # drained step depends on how many staged batches the pipeline had
    # dispatched when the poll caught the flag — read it from the cursor.
    steps = sorted(
        f for f in os.listdir(run_dir)
        if f.startswith("ckpt_0_s") and f.endswith(".npz")
    )
    assert steps and not (run_dir / "ckpt_0.npz").exists()
    snap_file = run_dir / steps[-1]
    assert integrity.verify_file(str(snap_file))
    cur = ckpt.read_cursor(str(snap_file))
    drained_step = cur["step"]
    assert cur["epoch"] == 0 and drained_step >= 3 and cur["plan_key"]
    assert set(acc_from_cursor(cur)) == {"loss_sum", "n"}
    # the PERIODIC async snapshot at the every_steps=3 cadence published
    assert (run_dir / "ckpt_0_s3.npz").exists()

    preemption.reset_preemption()
    ddp, state, loader, test_loader = _toy_ddp(mesh)
    logs = []
    _, hist = run_training_loop(
        ddp, state, loader, test_loader, str(run_dir), num_epochs=2,
        checkpoint_epoch=1, scan_steps=1, snapshot=SNAP, auto_resume=True,
        log=lambda *a: logs.append(" ".join(map(str, a))),
    )
    assert any(
        f"Exact resume: epoch 0 continues at step {drained_step} "
        "(zero batches replayed)." in l for l in logs
    )
    got = {h["epoch"]: h["train_loss"] for h in hist}
    assert got == ref  # bitwise: == on the exact floats, both epochs
    # v11 provenance: every run_meta header carries the snapshot block
    with open(run_dir / "history.jsonl") as f:
        records = [json.loads(l) for l in f if l.strip()]
    metas = [r for r in records if r["type"] == "run_meta"]
    assert metas and all(
        m["snapshot"]["every_steps"] == 3 for m in metas
    )
    errs = schema_mod.validate_history_records(records)
    assert errs == []


def test_native_plan_key_mismatch_falls_back_to_redo(
    mesh, tmp_path, preempt_guard, caplog
):
    """A cursor whose plan key no longer matches (here: the snapshot was
    cut on a different shuffle seed) must NOT skip wrong batches — the
    driver redoes the epoch from the restored state, the pre-v4 contract."""
    ddp, state, loader, test_loader = _toy_ddp(mesh)
    with pytest.raises(TrainingPreempted):
        run_training_loop(
            ddp, state, _PreemptingLoader(loader, after=5), test_loader,
            str(tmp_path), num_epochs=1, checkpoint_epoch=1, scan_steps=1,
            snapshot=SNAP, log=lambda *_: None,
        )
    preemption.reset_preemption()
    ddp, state, _, test_loader = _toy_ddp(mesh)
    ds = SyntheticClassification(n=512, shape=(8, 8, 3), seed=0)
    other_loader = ShardedDataLoader(ds, 8, mesh, shuffle=True, seed=9)
    with caplog.at_level(logging.WARNING, logger="tpuddp"):
        _, hist = run_training_loop(
            ddp, state, other_loader, test_loader, str(tmp_path),
            num_epochs=1, checkpoint_epoch=1, scan_steps=1, snapshot=SNAP,
            auto_resume=True, log=lambda *_: None,
        )
    assert any("plan key mismatch" in r.message for r in caplog.records)
    assert [h["epoch"] for h in hist] == [0]  # epoch redone, run completed


def test_native_snapshot_on_off_zero_semantic_cost(mesh, tmp_path):
    """Arming the engine must not change training semantics or the step
    program: same-seed runs with snapshots on and off land bitwise-equal
    loss trajectories and final checkpoints, and the lowered step HLO is
    byte-identical."""
    hlo = {}
    hist = {}
    for key, snap in (("on", SNAP), ("off", None)):
        d = tmp_path / key
        ddp, state, loader, test_loader = _toy_ddp(mesh)
        _, h = run_training_loop(
            ddp, state, loader, test_loader, str(d), num_epochs=1,
            checkpoint_epoch=1, scan_steps=1, snapshot=snap,
            log=lambda *_: None,
        )
        hist[key] = [(r["epoch"], r["train_loss"], r["test_loss"]) for r in h]
        state_struct = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(np.shape(l), l.dtype), state
        )
        batch_struct = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(np.shape(l), np.asarray(l).dtype),
            next(iter(loader)),
        )
        hlo[key] = jax.jit(ddp.train_step).lower(
            state_struct, batch_struct
        ).as_text()
    assert hist["on"] == hist["off"]
    assert hlo["on"] == hlo["off"]
    template = _toy_ddp(mesh)[1]
    a = ckpt.load(str(tmp_path / "on" / "ckpt_0.npz"), template)
    b = ckpt.load(str(tmp_path / "off" / "ckpt_0.npz"), template)
    assert_tree_equal(a.params, b.params)
    assert_tree_equal(a.opt_state, b.opt_state)


# ------------------------------------------------ exact resume (managed) ----


class _ManagedPreempt:
    def __init__(self, inner, after):
        self.inner = inner
        self.after = after

    def __len__(self):
        return len(self.inner)

    def set_epoch(self, epoch):
        self.inner.set_epoch(epoch)

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def __iter__(self):
        for i, batch in enumerate(self.inner):
            if i == self.after:
                preemption.request_preemption()
            yield batch


def _managed_setup():
    import train_accelerate as ta
    from tpuddp import nn as tnn
    from tpuddp.accelerate import Accelerator
    from tpuddp.data import DataLoader
    from tpuddp.data.transforms import make_eval_transform

    accel = Accelerator(seed=0, fuse_steps=1)
    ds = SyntheticClassification(n=256, shape=(8, 8, 3), seed=0)
    train_loader = DataLoader(ds, batch_size=8, shuffle=True)
    test_loader = DataLoader(ds, batch_size=32)
    model, opt, prepared = accel.prepare(
        ToyMLP(hidden=(16,)), optim.Adam(1e-2), train_loader
    )
    criterion = tnn.CrossEntropyLoss()
    eval_tf = jax.jit(make_eval_transform(size=None))
    return ta, accel, model, opt, prepared, test_loader, criterion, eval_tf


def _managed_losses(save_dir):
    with open(os.path.join(save_dir, "history.jsonl")) as f:
        records = [json.loads(l) for l in f if l.strip()]
    return records, {
        r["epoch"]: r["train_loss"] for r in records if r["type"] == "epoch"
    }


def test_managed_exact_resume_bitwise_parity(tmp_path, preempt_guard):
    """The managed driver's leg: a mid-epoch preempt drains a step snapshot
    (state_<e>_s<s>.npz with the v4 cursor), load_state surfaces the
    cursor, and the resumed run's loss trajectory is bitwise-equal to the
    uninterrupted twin — carried partial accumulator included."""
    snap = {"every_steps": 1}
    ref_dir, run_dir = str(tmp_path / "ref"), str(tmp_path / "run")
    ta, accel, model, opt, prepared, test_loader, crit, etf = _managed_setup()
    ta.run_training_loop(
        model, prepared, test_loader, crit, opt, ref_dir, accel, None, etf,
        num_epochs=2, checkpoint_epoch=1, snapshot=snap,
    )
    _, ref = _managed_losses(ref_dir)

    preemption.reset_preemption()
    ta, accel, model, opt, prepared, test_loader, crit, etf = _managed_setup()
    with pytest.raises(TrainingPreempted):
        ta.run_training_loop(
            model, _ManagedPreempt(prepared, 2), test_loader, crit, opt,
            run_dir, accel, None, etf, num_epochs=2, checkpoint_epoch=1,
            snapshot=snap,
        )
    snap_file = os.path.join(run_dir, "state_0_s3.npz")
    assert os.path.exists(snap_file)
    cur = ckpt.read_cursor(snap_file)
    assert cur["epoch"] == 0 and cur["step"] == 3 and cur["plan_key"]
    assert set(acc_from_cursor(cur)) == {"loss_total", "n_seen"}

    preemption.reset_preemption()
    ta, accel, model, opt, prepared, test_loader, crit, etf = _managed_setup()
    img0 = np.asarray(SyntheticClassification(n=256, shape=(8, 8, 3), seed=0)[0][0])
    model(etf(jnp.asarray(img0[None])))  # lazy init for load_state
    start = accel.load_state(model, opt, run_dir)
    assert start == 0  # the cursor's epoch: continue it
    assert accel.last_restore_cursor["step"] == 3
    ta.run_training_loop(
        model, prepared, test_loader, crit, opt, run_dir, accel, None, etf,
        num_epochs=2, checkpoint_epoch=1, start_epoch=start, snapshot=snap,
    )
    records, got = _managed_losses(run_dir)
    assert got == ref  # bitwise, both epochs
    metas = [r for r in records if r["type"] == "run_meta"]
    assert metas and all(m["snapshot"]["mode"] == "drain" for m in metas)
    assert schema_mod.validate_history_records(records) == []


# --------------------------------------------------------------- schema v11


def test_schema_v11_requires_snapshot_provenance():
    """v11 bump: a run_meta stamped at v11+ without the ``snapshot`` field
    is drift and must be rejected; older headers keep validating at their
    own version, and make_run_meta always carries the field."""
    meta = schema_mod.make_run_meta(comm_hook="none", snapshot=SNAP)
    assert meta["schema_version"] >= 11
    assert meta["snapshot"]["every_steps"] == 3
    assert schema_mod.validate_history_records([meta]) == []
    # disabled engine -> explicit false, never absent
    off = schema_mod.make_run_meta(comm_hook="none")
    assert off["snapshot"] is False
    assert schema_mod.validate_history_records([off]) == []
    dropped = {k: v for k, v in meta.items() if k != "snapshot"}
    errs = schema_mod.validate_history_records([dropped])
    assert any("snapshot" in e for e in errs), errs
    # a v10 header without the field stays valid (its version's contract)
    v10 = dict(dropped, schema_version=10)
    assert schema_mod.validate_history_records([v10]) == []


# ------------------------------------------------------------- inspect CLI


def test_inspect_ckpt_prints_cursor_and_writer_stats(tmp_path):
    """``tpuddp_inspect ckpt`` (numpy + stdlib only — no accelerator
    runtime) must print the v4 cursor, the writer sidecar, and pick the
    newest file in a dir by (epoch, step) family order."""
    state = make_state()
    engine = SnapshotEngine(
        str(tmp_path), SnapshotConfig(every_steps=4, async_writes=False)
    )
    acc = {"loss_sum": jnp.asarray(2.5), "n": jnp.asarray(64.0)}
    engine.final_snapshot(state, epoch=1, step=4, plan_key="pk" * 8, acc=acc)
    engine.close()
    ckpt.save_on_main(str(tmp_path), 0, state)  # older full epoch
    tool = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    out = subprocess.run(
        [sys.executable, tool, "ckpt", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    assert "2 checkpoint(s) (1 step snapshot(s))" in out.stdout
    # dir mode picked the step snapshot of epoch 1 over the full epoch 0
    assert "ckpt_1_s4.npz" in out.stdout
    assert "cursor (v4): epoch=1 step=4 plan_key=" + "pk" * 8 in out.stdout
    assert "loss_sum" in out.stdout and "zero batches replayed" in out.stdout
    assert "writer: async=False" in out.stdout
    assert "manifest:" in out.stdout and "verified" in out.stdout
    # the single-file mode on a cursor-free full checkpoint prints no cursor
    out = subprocess.run(
        [sys.executable, tool, "ckpt", str(tmp_path / "ckpt_0.npz")],
        capture_output=True, text=True,
    )
    assert out.returncode == 0 and "cursor (v4)" not in out.stdout
