"""FusedAdam Pallas kernel — must match tpuddp.optim.Adam (== torch.optim.Adam)
exactly. Runs in Pallas interpret mode on CPU; the same kernel compiles
natively on TPU (validated there to 1e-7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp.ops import FusedAdam
from tpuddp.optim import Adam


def tree_maxdiff(a, b):
    return max(
        float(jnp.abs(x - y).max())
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


@pytest.fixture()
def problem():
    rng = np.random.RandomState(0)
    params = {
        "w": jnp.asarray(rng.randn(37, 50).astype(np.float32)),
        "b": jnp.asarray(rng.randn(5).astype(np.float32)),  # < one lane
        "big": jnp.asarray(rng.randn(700, 130).astype(np.float32)),  # multi-block
    }
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)), params
    )
    return params, grads


def test_fused_matches_adam_over_steps(problem):
    params, grads = problem
    ref = Adam(1e-2)
    fused = FusedAdam(1e-2, impl="pallas")  # interpret mode on CPU
    rs, fs = ref.init(params), fused.init(params)
    rp, fp = params, params
    for _ in range(3):
        rp, rs = ref.update(grads, rs, rp)
        fp, fs = fused.update(grads, fs, fp)
    assert tree_maxdiff(rp, fp) < 1e-5
    assert tree_maxdiff(rs.m, fs.m) < 1e-6
    assert tree_maxdiff(rs.v, fs.v) < 1e-6
    assert int(fs.step) == 3


def test_impl_xla_inherits_adam(problem):
    params, grads = problem
    a, b = Adam(1e-3), FusedAdam(1e-3, impl="xla")
    pa, _ = a.update(grads, a.init(params), params)
    pb, _ = b.update(grads, b.init(params), params)
    assert tree_maxdiff(pa, pb) == 0.0


def test_impl_auto_falls_back_off_tpu(problem):
    params, grads = problem
    opt = FusedAdam(1e-3, impl="auto")
    # on CPU default backend this must route to XLA math and still be correct
    p, s = opt.update(grads, opt.init(params), params)
    ref = Adam(1e-3)
    rp, _ = ref.update(grads, ref.init(params), params)
    assert tree_maxdiff(p, rp) < 1e-6


def test_invalid_impl():
    with pytest.raises(ValueError):
        FusedAdam(impl="cuda")


def test_fused_in_jitted_train_step(problem):
    """The kernel must compose with jit + value_and_grad like any optimizer."""
    params, _ = problem
    fused = FusedAdam(1e-2, impl="pallas")
    state = fused.init(params)

    def loss_fn(p):
        return sum(jnp.sum(l**2) for l in jax.tree_util.tree_leaves(p))

    @jax.jit
    def step(p, s):
        g = jax.grad(loss_fn)(p)
        return fused.update(g, s, p)

    p1, s1 = step(params, state)
    assert float(loss_fn(p1)) < float(loss_fn(params))
