"""Cross-topology checkpoint resharding (ISSUE 16), fast tier.

The tentpole module under test is ``tpuddp.training.reshard`` — the pure
checkpoint -> checkpoint reshaper the supervisor/fleet relaunch path and the
``tpuddp_inspect reshard`` CLI share. Pins:

- the format-constant and placement-rule-table mirrors against the live
  checkpoint writer (drift here silently corrupts offline reshapes);
- ``redistribute_rows`` == ``comm.redistribute_residual`` bitwise;
- the W -> W' -> W round trip is byte-identical through a model-width
  crossing (QKV relayout is a pure reshape both ways);
- synthesized placement tags (model=1 -> model>1) match what a real TP save
  derives from live shardings;
- per-replica residual redistribution per model column, data_flat re-pad,
  and the typed refusals (v1 files, non-dividing widths, data_flat under
  model>1) — plus the regression that ORDINARY refusals survive: a
  wrong-shape head or a dtype flip still fails loudly with
  ``reshard_on_mismatch`` enabled;
- the stale-``.tmp`` sweep, the config/env levers, the supervisor's
  mesh-aware shrink ladder, the fleet gang clamp, and the two new
  ``tpuddp_inspect`` subcommands in-process.

The chaos-tier proofs (kill a live TP=2 x DP=2 job, resume smaller with
loss parity) live in tests/test_chaos.py.
"""

import dataclasses
import importlib.util
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuddp import config as cfg_lib
from tpuddp import nn, optim
from tpuddp.fleet.spec import FleetAdmissionError, JobSpec
from tpuddp.models import load_model
from tpuddp.parallel.comm import redistribute_residual
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.parallel.mesh2d import mesh2d
from tpuddp.resilience.supervisor import RestartSupervisor, SupervisorPolicy
from tpuddp.training import checkpoint as ckpt
from tpuddp.training import reshard as rs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KEY = jax.random.PRNGKey(0)
V, T = 64, 16


def _inspect():
    spec = importlib.util.spec_from_file_location(
        "_tpuddp_inspect", os.path.join(REPO, "tools", "tpuddp_inspect.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def tp_state(cpu_devices, data=2, model=2, **kw):
    """A real TP state on a (data, model) mesh — the cheap test_mesh2d
    idiom: init only, no training, so tier-1 stays fast."""
    m = load_model("transformer_tiny", num_classes=V, max_seq_len=32)
    ddp = DistributedDataParallel(
        m, optim.Adam(lr=1e-2), nn.CrossEntropyLoss(),
        mesh=mesh2d(data, model, devices=cpu_devices[: data * model]), **kw,
    )
    st = ddp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    return ddp, st


def save_tp(cpu_devices, tmp_path, data=2, model=2, epoch=0, **kw):
    ddp, st = tp_state(cpu_devices, data, model, **kw)
    path = ckpt.save_on_main(str(tmp_path), epoch, st, world_size=data * model)
    return ddp, st, path


def load_npz(path):
    with np.load(path) as f:
        return dict(f.items())


def payload_equal(a, b, ignore=()):
    """Byte-identical npz payloads (modulo ``ignore``d keys and the
    topology record, whose ``resharded`` provenance legitimately differs)."""
    ka = {k for k in a if k != rs.TOPO_MARK and k not in ignore}
    kb = {k for k in b if k != rs.TOPO_MARK and k not in ignore}
    assert ka == kb, ka.symmetric_difference(kb)
    for k in ka:
        x, y = np.asarray(a[k]), np.asarray(b[k])
        assert x.dtype == y.dtype and x.shape == y.shape, (k, x.dtype, x.shape, y.dtype, y.shape)
        np.testing.assert_array_equal(x, y, err_msg=k)


# ---------------------------------------------------------- mirror drift --


def test_format_marks_mirror_checkpoint_module():
    """reshard.py duplicates the npz markers so it imports without jax; any
    drift silently mis-classifies every leaf of every offline reshape."""
    assert rs.KEY_MARK == ckpt._KEY_MARK
    assert rs.BF16_MARK == ckpt._BF16_MARK
    assert rs.META_MARK == ckpt._META_MARK
    assert rs.TOPO_MARK == ckpt._TOPO_MARK
    assert rs.FORMAT_VERSION == ckpt.FORMAT_VERSION


def test_redistribute_rows_mirrors_comm_rule():
    """The numpy mirror must be bitwise the live elastic rule — shrink
    (grouped sum), grow (verbatim placement), and the M-nmid-N reset."""
    mat = (
        np.random.default_rng(7).normal(size=(4, 5)).astype(np.float32)
    )
    for new_world in (1, 2, 4, 8, 3):
        ours, act_ours = rs.redistribute_rows(mat, new_world)
        live, act_live = redistribute_residual(mat, new_world)
        assert act_ours == act_live
        np.testing.assert_array_equal(ours, live, err_msg=f"world {new_world}")


def test_placement_rule_table_matches_live_tp_save(cpu_devices, tmp_path):
    """The static TP_PLACEMENT_RULES table vs what derive_topology records
    from live NamedShardings on a real TP=2 save: resharding a canonical
    (model=1) file up to model=2 must synthesize EXACTLY the tags the live
    writer would have derived — params and path-congruent moments both."""
    _, _, path = save_tp(cpu_devices, tmp_path)
    live = ckpt.read_topology(path)
    assert live["model_size"] == 2 and live["placement"]

    stored = load_npz(path)
    canonical, topo1, _ = rs.reshard_arrays(stored, data=4, model=1)
    assert topo1["model_size"] == 1
    back, topo2, _ = rs.reshard_arrays(canonical, data=2, model=2)

    def norm(placement):
        # live tags spell replicated trailing dims explicitly for some
        # leaves (['model', None]); synthesized tags trim them — identical
        # shardings, so compare modulo the trailing-None spelling
        out = {}
        for k, axes in placement.items():
            axes = list(axes)
            while axes and axes[-1] is None:
                axes.pop()
            out[k] = axes
        return out

    assert norm(topo2["placement"]) == norm(live["placement"])


# --------------------------------------------------------- the round trip --


def test_round_trip_through_model_crossing_is_bitwise(cpu_devices, tmp_path):
    """W -> W' -> W through the TP=2 -> canonical -> TP=2 crossing: every
    array byte-identical (the QKV relayout is a pure reshape both ways, and
    full gathered params/moments are mesh-shape-independent)."""
    _, _, path = save_tp(cpu_devices, tmp_path)
    stored = load_npz(path)
    down, _, acts_down = rs.reshard_arrays(stored, data=2, model=1)
    back, topo, acts_up = rs.reshard_arrays(down, data=2, model=2)
    # the crossing touched the fused-QKV leaves both ways (param + moments)
    relayouts = [a["leaf"] for a in acts_down if a["action"] == "relayout"]
    assert any(leaf.endswith("['attn']['wqkv']") for leaf in relayouts)
    assert len(acts_down) == len(acts_up) == len(relayouts)
    payload_equal(stored, back)
    assert topo["resharded"]["from"] == [2, 1]
    assert topo["resharded"]["to"] == [2, 2]


def test_same_shape_target_is_identity(cpu_devices, tmp_path):
    _, _, path = save_tp(cpu_devices, tmp_path)
    stored = load_npz(path)
    out, _, actions = rs.reshard_arrays(stored, data=2, model=2)
    assert actions == []
    payload_equal(stored, out)


def test_reshard_checkpoint_writes_manifest_and_is_loadable(
    cpu_devices, tmp_path
):
    """File-level wrapper: atomic publish + fresh sha256 manifest, and the
    result restores onto the target mesh without the reshard-on-load path
    (the file IS the target shape now)."""
    from tpuddp.resilience import integrity

    _, st, path = save_tp(cpu_devices, tmp_path)
    dst = os.path.join(str(tmp_path), "ckpt_0.d2m1.npz")
    report = rs.reshard_checkpoint(path, dst, data=2, model=1)
    assert report["from"] == {"data": 2, "model": 2}
    assert report["to"] == {"data": 2, "model": 1}
    assert integrity.verify_file(dst, require_manifest=True)
    assert not os.path.exists(dst + ".tmp")

    topo = ckpt.read_topology(dst)
    assert topo["model_size"] == 1 and topo["mesh_axes"] == ["data"]
    # loads as a plain model=1 checkpoint (canonical QKV layout) — no width
    # mismatch, no opt-in; the model-replicated embed survives bitwise
    _, st1 = tp_state(cpu_devices, data=2, model=1)
    restored, _ = ckpt.load_with_topology(dst, st1, world_size=2)
    np.testing.assert_array_equal(
        np.asarray(restored.params["embed"]["weight"]),
        np.asarray(st.params["embed"]["weight"]),
    )


# ------------------------------------------- shape-dependent flat leaves --


def synthetic_payload(data=2, model=2, per=6, with_comm=True):
    """A hand-built v3 payload: one replicated param + a per-replica
    comm_state laid out data-major/model-minor, exactly like a shard_map
    bf16_ef save on a (data, model) mesh."""
    world = data * model
    param = np.arange(8, dtype=np.float32).reshape(2, 4)
    topo = {
        "format": rs.FORMAT_VERSION,
        "world_size": world,
        "model_size": model,
        "mesh_axes": ["data", "model"] if model > 1 else ["data"],
        "mesh_shape": [data, model] if model > 1 else [data],
        "leaves": {},
        "placement": {},
    }
    stored = {
        ".params['w']": param,
        rs.META_MARK + "epoch": np.asarray(3),
    }
    if with_comm:
        mat = (
            np.random.default_rng(11)
            .normal(size=(world, per))
            .astype(np.float32)
        )
        mat[:, per - 1] = 0.0  # padding tail: raw < per
        stored[".comm_state"] = mat.reshape(-1)
        topo["leaves"][".comm_state"] = {
            "kind": "per_replica", "world": world, "per": per, "model": model,
        }
        topo["placement"][".comm_state"] = [["data", "model"]]
    stored[rs.TOPO_MARK] = np.asarray(json.dumps(topo))
    return stored


def test_per_replica_redistributes_per_model_column():
    """Growing the data axis at fixed model width: each model column is an
    independent pure-data residual — redistributed with the live rule,
    column by column, in the data-major/model-minor layout."""
    stored = synthetic_payload(data=2, model=2, per=6)
    raw = 8  # the one (2, 4) param, replicated -> per-replica pad target
    out, topo, actions = rs.reshard_arrays(stored, data=4, model=2)
    per_to = rs._padded_total(raw, 4)
    old = stored[".comm_state"].reshape(2, 2, 6)
    new = out[".comm_state"].reshape(4, 2, per_to)
    for m in range(2):
        col = old[:, m, :]
        if per_to != 6:
            pad = np.zeros((2, per_to), np.float32)
            pad[:, : min(6, per_to)] = col[:, : min(6, per_to)]
            col = pad
        want, act = redistribute_residual(col, 4)
        assert act == "redistributed"
        np.testing.assert_array_equal(new[:, m, :], want, err_msg=f"col {m}")
    assert topo["leaves"][".comm_state"]["world"] == 8
    assert any(a["leaf"] == ".comm_state" for a in actions)


def test_per_replica_drops_across_model_widths():
    """A model-width crossing DROPS the residual (slices key by model
    shard) — recorded as a reset action and in the topology provenance, so
    the loader's zero re-init is auditable."""
    stored = synthetic_payload(data=2, model=2, per=6)
    out, topo, actions = rs.reshard_arrays(stored, data=2, model=1)
    assert ".comm_state" not in out
    assert topo["resharded"]["dropped"] == [".comm_state"]
    resets = [a for a in actions if a["action"] == "reset"]
    assert resets and resets[0]["leaf"] == ".comm_state"


def test_data_flat_repads_and_refuses_model_targets():
    param = np.arange(8, dtype=np.float32).reshape(2, 4)
    raw = param.size
    vec = np.zeros(rs._padded_total(raw, 4), np.float32)
    vec[:raw] = np.arange(raw, dtype=np.float32) + 1
    topo = {
        "format": rs.FORMAT_VERSION, "world_size": 4, "model_size": 1,
        "mesh_axes": ["data"], "mesh_shape": [4],
        "leaves": {".opt_state.m": {"kind": "data_flat"}},
        "placement": {},
    }
    stored = {
        ".params['w']": param,
        ".opt_state.m": vec,
        rs.TOPO_MARK: np.asarray(json.dumps(topo)),
    }
    out, _, actions = rs.reshard_arrays(stored, data=3, model=1)
    want = np.zeros(rs._padded_total(raw, 3), np.float32)
    want[:raw] = vec[:raw]
    np.testing.assert_array_equal(out[".opt_state.m"], want)
    assert [a["action"] for a in actions] == ["repadded"]
    # WUS flat moments have no TP layout: model>1 targets are refused
    with pytest.raises(rs.ReshardError, match="model>1"):
        rs.reshard_arrays(stored, data=2, model=2)


# ---------------------------------------------------------- the refusals --


def test_v1_checkpoint_refused():
    stored = {".params['w']": np.ones((2, 2), np.float32)}
    with pytest.raises(rs.ReshardError, match="predates the topology"):
        rs.reshard_arrays(stored, data=2, model=1)


def test_non_dividing_model_width_refused(cpu_devices, tmp_path):
    """transformer_tiny's model-split dims don't divide by 3 — the
    feasibility check names the first offending leaf instead of writing a
    torn file."""
    _, _, path = save_tp(cpu_devices, tmp_path)
    with pytest.raises(rs.ReshardError, match="does not divide"):
        rs.reshard_arrays(load_npz(path), data=1, model=3)


def test_wrong_shape_head_still_refused_with_reshard_enabled(
    cpu_devices, tmp_path
):
    """Regression: reshard_on_mismatch widens the TOPOLOGY surface only.
    A checkpoint from a different architecture (wrong-vocab head) must
    still fail loudly at load, not be 'resharded' into the wrong model."""
    save_tp(cpu_devices, tmp_path)
    m = load_model("transformer_tiny", num_classes=V + 8, max_seq_len=32)
    ddp = DistributedDataParallel(
        m, optim.Adam(lr=1e-2), nn.CrossEntropyLoss(),
        mesh=mesh2d(2, 2, devices=cpu_devices[:4]),
    )
    st = ddp.init_state(KEY, jnp.zeros((1, T), jnp.int32))
    with pytest.raises(ValueError, match="the model expects"):
        ckpt.restore_latest(
            str(tmp_path), st, world_size=4, model_size=2,
            reshard_on_mismatch=True,
        )


def test_dtype_mismatch_still_refused_with_reshard_enabled(
    cpu_devices, tmp_path
):
    _, st, path = save_tp(cpu_devices, tmp_path)
    cast = dataclasses.replace(
        st,
        params=jax.tree_util.tree_map(
            lambda a: np.asarray(a, np.float64), st.params
        ),
    )
    with pytest.raises(ValueError, match="dtype"):
        ckpt.load_with_topology(
            path, cast, world_size=4, model_size=2, reshard_on_mismatch=True,
        )


# ------------------------------------------------------- startup hygiene --


def test_sweep_stale_tmp(tmp_path):
    d = str(tmp_path)
    for name in (
        "ckpt_0.npz.tmp", "ckpt_1.npz.sha256.tmp", "ckpt_2.npz",
        "ckpt_2.npz.sha256", "notes.tmp", "ckpt_x.npz.tmp",
    ):
        with open(os.path.join(d, name), "w") as f:
            f.write("x")
    assert ckpt.sweep_stale_tmp(d) == 2
    left = sorted(os.listdir(d))
    assert left == ["ckpt_2.npz", "ckpt_2.npz.sha256", "ckpt_x.npz.tmp",
                    "notes.tmp"]
    assert ckpt.sweep_stale_tmp(d) == 0
    assert ckpt.sweep_stale_tmp(os.path.join(d, "missing")) == 0


# ------------------------------------------------------ config + levers --


def test_reshard_knob_defaults_off_and_unknown_key_refused():
    assert cfg_lib.TRAINING_DEFAULTS["reshard_on_mismatch"] is False
    with pytest.raises(ValueError, match="unknown"):
        cfg_lib.training_config({"training": {"reshard_on_mismtach": True}})


def test_model_size_env_overrides_parallel_block(monkeypatch):
    """$TPUDDP_MODEL_SIZE is the relaunch lever: it pins the width AND
    resets an explicit data factorization to auto (it was for the old
    world)."""
    monkeypatch.delenv("TPUDDP_MODEL_SIZE", raising=False)
    base = cfg_lib.resolve_parallel({"data": 2, "model": 2})
    assert base["data"] == 2 and base["model"] == 2
    monkeypatch.setenv("TPUDDP_MODEL_SIZE", "1")
    over = cfg_lib.resolve_parallel({"data": 2, "model": 2})
    assert over["model"] == 1 and over["data"] == "auto"


# ------------------------------------------- supervisor mesh-aware shrink --


def sup(world, model=None, **pol):
    policy = SupervisorPolicy(**pol) if pol else None
    return RestartSupervisor(
        ["true"], policy=policy, world_size=world, model_size=model,
        runner=lambda argv, env: 0,
    )


def test_shrunk_mesh_data_axis_first():
    assert sup(8, 2)._shrunk_mesh() == (4, 2)
    assert sup(4, 2)._shrunk_mesh() == (2, 2)


def test_shrunk_mesh_model_axis_only_at_data_one():
    # data=1: the model axis itself halves (the reshaper re-splits leaves)
    assert sup(2, 2)._shrunk_mesh() == (1, 1)
    assert sup(4, 4)._shrunk_mesh() == (2, 2)


def test_shrunk_mesh_respects_min_world_and_divisibility():
    assert sup(4, 2, min_world=4)._shrunk_mesh() is None
    assert sup(2, 2, min_world=2)._shrunk_mesh() is None
    # shrink_factor 3 divides neither data=1's model=2 nor leaves data >= 1
    assert sup(2, 2, shrink_factor=3)._shrunk_mesh() is None
    # pure DP unchanged: plain halving with the floor
    assert sup(4)._shrunk_mesh() == (2, None)
    assert sup(2, min_world=2)._shrunk_mesh() is None


def test_supervisor_refuses_non_mesh_world_model():
    with pytest.raises(ValueError, match="not a multiple"):
        sup(6, 4)


def test_supervisor_exports_model_env():
    s = sup(4, 2)
    env = s._child_env(attempt=0)
    assert env["TPUDDP_MODEL_SIZE"] == "2"
    assert env["TPUDDP_WORLD_SIZE"] == "4"
    assert "TPUDDP_MODEL_SIZE" not in sup(4)._child_env(attempt=0)


# ------------------------------------------------------- fleet gang math --


def test_jobspec_model_size_admission():
    ok = JobSpec(name="tp", kind="training", priority=0, min_world=2,
                 max_world=4, model_size=2, argv=("true",))
    assert ok.model_size == 2
    with pytest.raises(FleetAdmissionError):
        JobSpec(name="bad", kind="serving", priority=0, min_world=2,
                max_world=4, model_size=2, argv=("true",))
    with pytest.raises(FleetAdmissionError):
        JobSpec(name="bad", kind="training", priority=0, min_world=3,
                max_world=4, model_size=2, argv=("true",))
    with pytest.raises(FleetAdmissionError):
        JobSpec(name="bad", kind="training", priority=0, min_world=2,
                max_world=2, model_size=0, argv=("true",))


def test_gang_world_clamps_to_model_multiples():
    from tpuddp.fleet.controller import FleetController

    spec = JobSpec(name="tp", kind="training", priority=0, min_world=2,
                   max_world=8, model_size=2, argv=("true",))
    gang = FleetController._gang_world
    assert gang(spec, 8) == 8
    assert gang(spec, 7) == 6
    assert gang(spec, 3) == 2
    assert gang(spec, 1) == 2  # floored to min_world (a valid multiple)
    dp = JobSpec(name="dp", kind="training", priority=0, min_world=1,
                 max_world=8, argv=("true",))
    assert gang(dp, 3) == 3  # model_size=1 jobs are untouched


# ----------------------------------------------------------------- CLI --


def test_inspect_ckpt_and_reshard_cli(cpu_devices, tmp_path, capsys):
    _, _, path = save_tp(cpu_devices, tmp_path)
    with open(os.path.join(str(tmp_path), "ckpt_7.npz.tmp"), "w") as f:
        f.write("orphan")
    insp = _inspect()

    assert insp.main(["ckpt", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "1 checkpoint(s), 1 stale .tmp file(s)" in out
    assert "mesh" in out and "model" in out

    assert insp.main(["ckpt", path]) == 0
    out = capsys.readouterr().out
    assert "placement" in out and "manifest" in out

    assert insp.main(["reshard", path, "--to", "data=2,model=1"]) == 0
    out = capsys.readouterr().out
    dst = path[: -len(".npz")] + ".d2m1.npz"
    assert os.path.exists(dst)
    assert "relayout" in out
    assert ckpt.read_topology(dst)["model_size"] == 1

    # the refusal surfaces as REFUSED + rc 1, not a stack trace
    assert insp.main(["reshard", path, "--to", "data=1,model=3"]) == 1
    err = capsys.readouterr().err
    assert "REFUSED" in err and "does not divide" in err


def test_inspect_reshard_round_trip_cli(cpu_devices, tmp_path, capsys):
    _, _, path = save_tp(cpu_devices, tmp_path)
    insp = _inspect()
    down = os.path.join(str(tmp_path), "down.npz")
    back = os.path.join(str(tmp_path), "back.npz")
    assert insp.main(["reshard", path, "--to", "data=4,model=1",
                      "--out", down]) == 0
    assert insp.main(["reshard", down, "--to", "data=2,model=2",
                      "--out", back]) == 0
    capsys.readouterr()
    payload_equal(load_npz(path), load_npz(back))
