"""Training worker for the chaos suite (launched by test_chaos.py).

Runs a small native DP training job (toy MLP, synthetic-fallback data, 4
virtual CPU devices) through the full spawn path so the resilience wiring is
live: SIGTERM drain handlers installed, ``TrainingPreempted`` -> exit 75,
``$TPUDDP_FAULT`` injection hooks armed, ``$TPUDDP_AUTO_RESUME`` resume.

Usage: python _chaos_train_worker.py <out_dir> <num_epochs>

``$TPUDDP_CHAOS_TRAINING`` may hold a JSON object of training-config
overrides (e.g. ``{"guard": {"max_consecutive_skips": 0}}``) so chaos
scenarios can arm the numerical guard without a worker per knob.
``$TPUDDP_CHAOS_OBS`` does the same for the ``observability`` block (e.g.
``{"exporter": true}`` to scrape a live chaos run); the defaults (flight
recorder on, exporter off) apply otherwise. ``$TPUDDP_WORLD_SIZE``
overrides the 4-device default world — the elastic chaos matrix (and the
restart supervisor's shrink policy) resumes the same out_dir on a
different world size through the v2 reshard path.
"""

import json
import os
import sys
from functools import partial

out_dir, num_epochs = sys.argv[1], int(sys.argv[2])
world_size = int(os.environ.get("TPUDDP_WORLD_SIZE") or 4)

from tpuddp.parallel.spawn import run_ddp_training  # noqa: E402
from train_native import basic_ddp_training_loop  # noqa: E402

TRAINING = {
    "model": "toy_mlp",
    "dataset": "cifar10",
    "data_root": "/nonexistent",  # forces the zero-egress synthetic fallback
    "train_batch_size": 8,  # per replica: 32-sample global batches
    "test_batch_size": 8,
    "learning_rate": 0.01,
    "num_epochs": num_epochs,
    "checkpoint_epoch": 1,
    "image_size": None,
    "seed": 0,
    "mode": "shard_map",
    "synthetic_n": (256, 64),  # 8 train batch groups per epoch
}
TRAINING.update(json.loads(os.environ.get("TPUDDP_CHAOS_TRAINING") or "{}"))
OBSERVABILITY = json.loads(os.environ.get("TPUDDP_CHAOS_OBS") or "null")
# 2-D mesh override (e.g. '{"data": 2, "model": 2}') for ad-hoc chaos
# scenarios on a factored mesh. The full gate's mesh leg drives
# tools/bench_mesh.py (a token workload — this worker's CNN data cannot
# feed a tensor-parallel transformer); this env hook exists so future
# chaos legs can pin the mesh shape without a worker per knob.
PARALLEL = json.loads(os.environ.get("TPUDDP_CHAOS_PARALLEL") or "null")

run_ddp_training(
    partial(
        basic_ddp_training_loop, training=TRAINING,
        observability=OBSERVABILITY, parallel=PARALLEL,
    ),
    world_size=world_size,
    save_dir=out_dir,
    optional_args={"set_epoch": True, "print_rand": False},
    backend="cpu",
)
