"""Explicit (native) DP training entrypoint — the tpuddp analog of the
reference's ``multi-GPU-training-torch.py`` (call stack SURVEY.md §3.1).

Same shape, TPU-native pieces:

    setup/process group        -> tpuddp.parallel.backend (TPU->CPU ladder)
    mp.spawn per-GPU workers   -> one process drives all local chips
                                  (tpuddp.parallel.spawn.run_ddp_training)
    set_seed_based_on_rank     -> tpuddp.seeding
    DistributedSampler loaders -> ShardedDataLoader (per-replica samplers)
    DDP(model) + NCCL allreduce-> DistributedDataParallel (shard_map + pmean)
    run_training_loop          -> tpuddp.training.loop (same per-epoch flow)

Usage parity:  python train_native.py --settings_file local_settings.yaml
"""

from __future__ import annotations

import argparse
import logging
from functools import partial

import jax
import jax.numpy as jnp

from tpuddp import config as cfg_lib
from tpuddp import nn, observability as obs, seeding
from tpuddp.data import (
    PrefetchLoader,
    ShardedDataLoader,
    compute_dtype_for,
    flip_for,
    load_datasets_for,
    norm_stats_for,
)
from tpuddp.data.transforms import make_eval_transform, make_train_augment
from tpuddp.models import load_model
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.parallel.spawn import run_ddp_training
from tpuddp.training.loop import run_training_loop

logging.basicConfig(level=logging.INFO, format="%(message)s")


def basic_ddp_training_loop(
    rank, world_size, save_dir, optional_args, training=None, observability=None,
    parallel=None,
):
    """Per-process worker — parity with the reference's
    ``basic_DDP_training_loop`` (multi-GPU-training-torch.py:228-266). The
    process group is already up (run_ddp_training called setup)."""
    print(f"Running DDP training on process {rank} ({world_size}-chip world).")
    training = training or cfg_lib.TRAINING_DEFAULTS
    # Tune overlay ($TPUDDP_TUNE_OVERLAY) applies here too so workers handed
    # a pre-resolved training dict (fleet relaunch, chaos harness) pick it
    # up; re-application after training_config is an idempotent merge.
    training, _tune_prov = cfg_lib.apply_tune_overlay(training, section="training")

    # Seeds per rank (reference :234); the data permutation seed stays shared
    # across ranks (DistributedSampler contract) and independent of model seed.
    key, _base_seed = seeding.set_seed_based_on_rank(rank, training.get("seed"))

    # Mesh: the ``parallel`` block factors the world into the 2-D
    # ("data", "model") grid (config.mesh_from; model=1 is exactly today's
    # flat mesh), and comm_topology: hierarchical factors the data axis
    # ("host", "local") so the comm hooks can split the intra-/inter-host
    # hops (parallel/comm.py). Bad factorizations refuse at mesh_from.
    comm_topology = str(training.get("comm_topology") or "flat")
    mesh = cfg_lib.mesh_from(parallel, world_size, comm_topology=comm_topology)

    # Data + model (reference :237-238); synthetic fallback keeps the tutorial
    # runnable with no dataset staged (zero-egress environments).
    train_ds, test_ds = load_datasets_for(training)
    train_loader = ShardedDataLoader(
        train_ds, training["train_batch_size"], mesh, shuffle=True
    )
    test_loader = ShardedDataLoader(
        test_ds, training["test_batch_size"], mesh, shuffle=True
    )
    # async pipeline (training.pipeline, tpuddp/training/pipeline.py):
    # staged-chunk depth + host worker count + the synchronous A/B mode
    from tpuddp.training.pipeline import resolve_pipeline

    pipeline = resolve_pipeline(training.get("pipeline"))
    if training.get("prefetch", True) and pipeline.host_workers > 0:
        # overlap host batch assembly with device compute (the reference's
        # num_workers analog, multi-GPU-training-torch.py:90-98); workers > 1
        # parallelize assembly itself over the loaders' batch plan
        train_loader = PrefetchLoader(train_loader, workers=pipeline.host_workers)
        test_loader = PrefetchLoader(test_loader, workers=pipeline.host_workers)

    # Device-side transform pipeline (replaces data_and_toy_model.py:13-29);
    # normalization stats follow the dataset, and flip is a config knob
    # (digits are not flip-invariant, unlike CIFAR photos).
    size = training.get("image_size")
    mean, std = norm_stats_for(training)
    cdtype = compute_dtype_for(training)
    is_token_model = str(training.get("model") or "").startswith("transformer")
    if is_token_model:
        # token models take int sequences: the image augment/normalize
        # pipeline does not apply (and the TP wrap refuses it outright)
        augment = eval_transform = None
    else:
        augment = make_train_augment(
            size=size, flip=flip_for(training), mean=mean, std=std,
            compute_dtype=cdtype,
        )
        eval_transform = make_eval_transform(
            size=size, mean=mean, std=std, compute_dtype=cdtype
        )

    # Model, optionally fine-tuning from a torch checkpoint on disk — the
    # reference's central pretrained-AlexNet workflow (data_and_toy_model.py:41-45).
    init_params = init_mstate = None
    if training.get("pretrained_path"):
        from tpuddp.models.torch_import import pretrained_from_config

        model, init_params, init_mstate = pretrained_from_config(training, key)
        print(
            f"Loaded pretrained {training['model']} weights from "
            f"{training['pretrained_path']}."
        )
    else:
        model = load_model(training["model"], cfg_lib.num_classes_from(training))
    if training.get("sync_bn"):
        nn.convert_sync_batchnorm(model)

    # Loss + optimizer (reference :248-249). training.optimizer selects the
    # update rule (adam default; lars/lamb for large-batch trust-ratio
    # scaling, sgdw as their decay-only baseline — config.optimizer_from,
    # shared with the managed entrypoint). optimizer_state_dtype: bfloat16
    # stores Adam m/v in bf16 (f32 math, f32 master params) — halves the
    # optimizer HBM traffic that dominates FC-heavy steps (BASELINE.md).
    criterion = nn.CrossEntropyLoss()
    optimizer = cfg_lib.optimizer_from(training)

    # The DDP wrap (reference :245): builds the shard_map'd pmean train step.
    # weight_update_sharding swaps the allreduce+replicated-update for
    # reduce-scatter + 1/N-shard update + all-gather (ZeRO-1 on ICI).
    clip = training.get("clip_grad_norm")
    ddp = DistributedDataParallel(
        model,
        optimizer,
        criterion,
        mesh=mesh,
        mode=training.get("mode", "shard_map"),
        augment=augment,
        eval_transform=eval_transform,
        remat=bool(training.get("remat", False)),
        clip_grad_norm=float(clip) if clip is not None else None,
        weight_update_sharding=bool(training.get("weight_update_sharding", False)),
        # effective-batch control (reference multi-GPU-training-torch.py:88's
        # batch-size knob): one optimizer update per A micro-batches, fused
        # into the scan step — same knob name as the managed path
        grad_accumulation=int(training.get("gradient_accumulation_steps") or 1),
        # gradient-comm hook (torch DDP comm-hook analog, parallel/comm.py):
        # bf16/bf16_ef halve the gradient interconnect bytes per step;
        # int8_ef cuts ~75%, topk_ef ~87.5% at density 0.1 (error-feedback
        # residual carries what compression dropped)
        comm_hook=str(training.get("comm_hook") or "none"),
        bucket_cap_mb=float(training.get("bucket_cap_mb") or 25),
        comm_topology=comm_topology,
        # segmented-backward overlap (training/step.py): issue each bucket
        # group's collective inside the backward walk instead of one trailing
        # block; "auto" enables it only where it genuinely segments
        comm_overlap=training.get("comm_overlap", "auto"),
        topk_density=float(training.get("topk_density") or 0.1),
        # numerical guard (resilience/guard.py): non-finite-update firewall +
        # desync auditor + rollback-to-last-good; off (exact legacy step)
        # unless the training.guard block asks for it
        guard=training.get("guard"),
    )
    in_hw = size if size else train_ds.images.shape[1]
    state = ddp.init_state(
        key, jnp.zeros((1, in_hw, in_hw, 3)), params=init_params, model_state=init_mstate
    )

    # Resume path (the reference only documents loading, README.md:51-52):
    # training.resume: true restores the newest ckpt_{epoch}.npz in out_dir —
    # routed through the epoch driver's auto-resume restore (one restore
    # implementation), which also reshards elastically onto THIS mesh and
    # lands the topology-change event rows in history.jsonl.
    run_training_loop(
        ddp,
        state,
        train_loader,
        test_loader,
        save_dir,
        num_epochs=training["num_epochs"],
        checkpoint_epoch=training["checkpoint_epoch"],
        set_epoch=optional_args.get("set_epoch", True),
        print_rand=optional_args.get("print_rand", False),
        data_probe_every=100,  # shard-disjointness probe (reference :112-115)
        scan_steps=training.get("scan_steps", "auto"),
        per_replica_log=True,  # reference's per-device loss lines (:186-191)
        # resilience knobs: auto_resume restores the newest INTACT checkpoint
        # (training.resume rides the same path; also forced by
        # $TPUDDP_AUTO_RESUME=1, the scheduler-requeue contract);
        # keep_last bounds checkpoint disk on long runs
        auto_resume=bool(training.get("auto_resume") or training.get("resume")),
        # elastic mesh failover: opt into re-shaping a checkpoint written on
        # a different (data, model) mesh at restore (training/reshard.py)
        reshard_on_mismatch=bool(training.get("reshard_on_mismatch")),
        keep_last=(
            int(training["keep_last"]) if training.get("keep_last") else None
        ),
        # telemetry (tpuddp.observability): per-window step_stats cadence +
        # run provenance for the history.jsonl run_meta header
        step_stats_every=int(training.get("step_stats_every") or 0),
        pipeline=pipeline,
        # live telemetry plane (observability block): opt-in /metrics
        # exporter, pod aggregation + straggler detection, flight recorder
        observability=observability,
        # async step-granular checkpointing (training/snapshot.py): step
        # snapshots with v4 data cursors for exact mid-epoch resume
        snapshot=training.get("snapshot"),
        run_meta={
            "config_hash": obs.config_hash(training),
            "model": training.get("model"),
            "dataset": training.get("dataset"),
        },
    )


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="tpuddp explicit-API DP training (ShardedDataLoader + "
        "DistributedDataParallel over the XLA mesh backend).",
    )
    parser.add_argument(
        "--settings_file",
        type=str,
        required=True,
        help="YAML settings (see local_settings.yaml for the schema: out_dir, "
        "local.{device,tpu}, optional_args, training overrides).",
    )
    args = parser.parse_args()

    settings = cfg_lib.load_settings(args.settings_file)
    out_dir = cfg_lib.prepare_out_dir(settings, args.settings_file)
    world_size = cfg_lib.world_size_from(settings)
    optional_args = cfg_lib.optional_args_from(settings)
    training = cfg_lib.training_config(settings)
    # multi-host rendezvous (local.rendezvous / TPUDDP_* env) — the analog of
    # the reference's MASTER_ADDR:MASTER_PORT (multi-GPU-training-torch.py:30-31)
    rendezvous = cfg_lib.rendezvous_from(settings)

    run_ddp_training(
        partial(
            basic_ddp_training_loop,
            training=training,
            observability=cfg_lib.observability_config(settings),
            parallel=cfg_lib.parallel_config(settings),
        ),
        world_size,
        out_dir,
        optional_args,
        backend=cfg_lib.device_from(settings),
        **rendezvous,
    )
