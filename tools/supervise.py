#!/usr/bin/env python
"""Run a tpuddp training command under the restart supervisor.

The supervisor (tpuddp/resilience/supervisor.py) interprets the exit-code
contract the training processes already speak (README "Fault tolerance"):

    0   done                         -> exit 0
    75  preemption drain             -> resume IMMEDIATELY (auto-resume env)
    76  stale peer (watchdog)        -> jittered-backoff restart; after
                                        --shrink-after consecutive 76s,
                                        SHRINK the mesh (data axis first,
                                        model axis only at data=1; via
                                        $TPUDDP_WORLD_SIZE/$TPUDDP_MODEL_SIZE)
                                        and resume through the elastic restore
    77  replica desync               -> jittered-backoff restart + resume
    *   anything else non-zero       -> jittered-backoff restart + resume,
                                        bounded by --max-restarts

Usage::

    python tools/supervise.py [options] -- <command> [args...]

    # e.g. supervise a native run, starting on 8 chips, allowed to shrink
    # to 2 after repeated peer death:
    python tools/supervise.py --world 8 --min-world 2 -- \
        python train_native.py --settings_file local_settings.yaml

Options map 1:1 onto SupervisorPolicy; --first-env KEY=VAL applies env to
the FIRST attempt only (chaos injection: the fault must not re-fire in the
resumed child). --world pins $TPUDDP_WORLD_SIZE (both entrypoints honor it)
and arms the shrink policy; without it the supervisor cannot shrink.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

logging.basicConfig(level=logging.INFO, format="%(message)s")


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description="Restart supervisor for tpuddp training commands "
        "(exit-code contract interpreter + elastic world shrink).",
    )
    parser.add_argument("--world", type=int, default=None,
                        help="initial world size (pins $TPUDDP_WORLD_SIZE; "
                        "required for elastic shrink)")
    parser.add_argument("--model", type=int, default=None,
                        help="tensor-parallel width (pins $TPUDDP_MODEL_SIZE); "
                        "arms MESH-aware shrink: data axis halves first, the "
                        "model axis shrinks only once data=1 — the child "
                        "reshards its checkpoint onto the smaller mesh "
                        "(training.reshard_on_mismatch)")
    parser.add_argument("--max-restarts", type=int, default=8,
                        help="total restart budget across all causes")
    parser.add_argument("--backoff-base", type=float, default=1.0,
                        help="first-failure backoff seconds")
    parser.add_argument("--backoff-cap", type=float, default=60.0,
                        help="backoff ceiling seconds")
    parser.add_argument("--jitter", type=float, default=0.5,
                        help="backoff jitter fraction in [0, 1]")
    parser.add_argument("--shrink-after", type=int, default=2,
                        help="consecutive peer-death exits (76) before the "
                        "world shrinks")
    parser.add_argument("--shrink-factor", type=int, default=2,
                        help="world divisor per shrink step")
    parser.add_argument("--min-world", type=int, default=1,
                        help="never shrink below this world size")
    parser.add_argument("--auto-resume", action="store_true",
                        help="set $TPUDDP_AUTO_RESUME=1 on the FIRST attempt "
                        "too (restarts always resume)")
    parser.add_argument("--flight-dir", default=None, metavar="DIR",
                        help="summarize flightrec_<reason>.json crash "
                        "recordings from DIR (usually the run's out_dir) at "
                        "startup and after every abnormal child exit, before "
                        "deciding restart/shrink")
    parser.add_argument("--first-env", action="append", default=[],
                        metavar="KEY=VAL",
                        help="env applied to attempt 0 only (repeatable; "
                        "e.g. --first-env TPUDDP_FAULT=preempt@epoch=1)")
    if argv is None:
        argv = sys.argv[1:]
    if "--" not in argv:
        parser.error("separate the supervised command with '--': "
                     "supervise.py [options] -- <command> [args...]")
    split = argv.index("--")
    args = parser.parse_args(argv[:split])
    command = argv[split + 1:]
    if not command:
        parser.error("no command after '--'")
    return args, command


def main(argv=None) -> int:
    args, command = parse_args(argv)
    first_env = {}
    for kv in args.first_env:
        if "=" not in kv:
            raise SystemExit(f"--first-env expects KEY=VAL, got {kv!r}")
        k, v = kv.split("=", 1)
        first_env[k] = v

    from tpuddp.resilience.supervisor import RestartSupervisor, SupervisorPolicy

    policy = SupervisorPolicy(
        max_restarts=args.max_restarts,
        backoff_base=args.backoff_base,
        backoff_cap=args.backoff_cap,
        jitter=args.jitter,
        shrink_after=args.shrink_after,
        shrink_factor=args.shrink_factor,
        min_world=args.min_world,
    )
    return RestartSupervisor(
        command,
        policy=policy,
        world_size=args.world,
        model_size=args.model,
        first_attempt_env=first_env,
        auto_resume_first=args.auto_resume,
        flight_dir=args.flight_dir,
    ).run()


if __name__ == "__main__":
    sys.exit(main())
