#!/usr/bin/env python
"""loadgen — closed/open-loop load generator for the tpuddp serving engine.

Produces the latency-vs-offered-throughput curve that makes a serving stack
measurable: a closed-loop phase finds the engine's sustainable peak, then
open-loop phases replay fixed offered rates (fractions of that peak) and
record what clients would actually experience — end-to-end p50/p95/p99,
achieved throughput, batch occupancy, rejects. ``vs_baseline`` anchors
against sequential per-request serving (one request in flight, no
coalescing — the no-continuous-batching strawman, measured through the same
engine so queue costs land on both sides of the ratio); the raw batch=1
direct-dispatch rate is reported alongside as the device ceiling.

Artifacts:

- ``--out``         — the curve in the ``bench_results.json`` payload format
  (validated by ``tools/tpuddp_inspect.py --validate``; each offered-load
  point is one row under ``configs``);
- ``--history-dir`` — the engine's own ``history.jsonl`` (run_meta +
  serving_stats windows + drain event), same validation;
- stdout            — progress on stderr-like log lines, and the LAST line
  is one compact JSON summary (bench.py's driver-parseable contract).

Runs entirely in-process on the local mesh (CPU-friendly: the gate's serving
leg drives ~100 requests against 2 replicas over 2 tenants); the same flags
scale the sweep up on real chips.

``--decode`` switches to the TOKEN-level engine (tpuddp/serving/decode/):
the curve becomes tokens/sec + time-to-first-token vs offered request rate,
and ``vs_baseline`` anchors against request-level SEQUENTIAL decode (one
sequence in flight, no continuous batching — the regime the decode engine
exists to beat). Rows carry ``tokens_per_sec`` instead of
``samples_per_sec_per_chip``; ``tools/bench_trend.py`` tracks either.

Usage:
    python tools/loadgen.py --quick --history-dir /tmp/serve \\
        --out /tmp/serve/bench_results.json
    python tools/loadgen.py --decode --quick --history-dir /tmp/decode
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def log(msg: str) -> None:
    print(f"[loadgen] {msg}", flush=True)


def _make_requests(rng, n, rows_max, sample_shape):
    """Pre-generate request payloads so generation cost never pollutes the
    timed phases."""
    return [
        rng.randn(int(rng.randint(1, rows_max + 1)), *sample_shape).astype(
            np.float32
        )
        for _ in range(n)
    ]


def _pct(values, keys=(50, 95, 99)):
    from tpuddp.observability import percentiles

    return {
        k: (None if v is None else round(v, 3))
        for k, v in percentiles(values, keys).items()
    }


def closed_loop(engine, payloads, tenants, workers):
    """Every worker keeps exactly one request in flight (submit -> wait ->
    repeat): the classic saturation probe. Returns (e2e_ms list, wall_s)."""
    from tpuddp.serving import AdmissionError

    lock = threading.Lock()
    cursor = {"i": 0}
    e2e_ms = []

    def run(worker_idx):
        while True:
            with lock:
                i = cursor["i"]
                if i >= len(payloads):
                    return
                cursor["i"] = i + 1
            t0 = time.perf_counter()
            try:
                res = engine.submit(f"tenant{i % tenants}", payloads[i])
            except AdmissionError:
                continue  # counted by engine stats; keep probing
            res.result(timeout=120)
            with lock:
                e2e_ms.append((res.done_at - t0) * 1e3)

    threads = [
        threading.Thread(target=run, args=(w,), daemon=True)
        for w in range(workers)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return e2e_ms, time.perf_counter() - t0


def open_loop(engine, payloads, tenants, offered_rps):
    """Fixed-rate arrivals regardless of completions (the honest overload
    probe: a closed loop self-throttles, an open loop does not). Returns
    (e2e_ms of completed, rejected count, wall_s)."""
    from tpuddp.serving import AdmissionError

    interval = 1.0 / offered_rps
    inflight = []
    rejected = 0
    t_start = time.perf_counter()
    for i, x in enumerate(payloads):
        target = t_start + i * interval
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t_submit = time.perf_counter()
        try:
            inflight.append((t_submit, engine.submit(f"tenant{i % tenants}", x)))
        except AdmissionError:
            rejected += 1
    e2e_ms = []
    for t_submit, res in inflight:
        res.result(timeout=120)
        e2e_ms.append((res.done_at - t_submit) * 1e3)
    return e2e_ms, rejected, time.perf_counter() - t_start


def raw_dispatch_rate(engine, payloads_1row, steps):
    """Raw device ceiling: one replica, batch=1, direct ``infer`` calls with
    no queue/thread machinery at all — the context figure that separates
    engine overhead from device time in the report."""
    replica = engine.pool.replicas[0]
    t0 = time.perf_counter()
    for x in payloads_1row[:steps]:
        np.asarray(replica.infer(x))
    dt = time.perf_counter() - t0
    return steps / dt


def _decode_prompts(rng, n, max_prompt, vocab):
    return [
        rng.randint(0, vocab, size=int(rng.randint(1, max_prompt + 1))).astype(
            np.int32
        )
        for _ in range(n)
    ]


class _occupancy_peak:
    """Context manager sampling ``engine.kv_occupancy()`` on a background
    thread while the phase runs — the loop drains every sequence before
    returning, so a post-hoc read always sees an EMPTY pool (0.0), never
    the pressure the phase actually applied. Enter yields a zero-arg
    callable returning the max observed so far."""

    def __init__(self, engine, interval_s: float = 0.005):
        self._engine = engine
        self._interval = interval_s
        self._peak = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.is_set():
            self._peak = max(self._peak, self._engine.kv_occupancy())
            self._stop.wait(self._interval)

    def __enter__(self):
        self._thread.start()
        return lambda: self._peak

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=10)
        return False


def decode_closed_loop(engine, prompts, tenants, workers):
    """Workers each keep one SEQUENCE in flight (submit -> stream to the
    end -> repeat). Returns (completed count, wall_s)."""
    from tpuddp.serving import AdmissionError

    lock = threading.Lock()
    cursor = {"i": 0, "done": 0}

    def run(_w):
        while True:
            with lock:
                i = cursor["i"]
                if i >= len(prompts):
                    return
                cursor["i"] = i + 1
            try:
                res = engine.submit(f"tenant{i % tenants}", prompts[i])
            except AdmissionError:
                continue
            res.result(timeout=300)
            with lock:
                cursor["done"] += 1

    threads = [
        threading.Thread(target=run, args=(w,), daemon=True)
        for w in range(workers)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return cursor["done"], time.perf_counter() - t0


def decode_open_loop(engine, prompts, tenants, offered_rps):
    """Fixed-rate sequence arrivals; returns (completed, rejected, wall_s)."""
    from tpuddp.serving import AdmissionError

    interval = 1.0 / offered_rps
    inflight = []
    rejected = 0
    t_start = time.perf_counter()
    for i, p in enumerate(prompts):
        target = t_start + i * interval
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        try:
            inflight.append(engine.submit(f"tenant{i % tenants}", p))
        except AdmissionError:
            rejected += 1
    for res in inflight:
        res.result(timeout=300)
    return len(inflight), rejected, time.perf_counter() - t_start


def _decode_row(name, mode, d, offered_rps=None, **extra):
    """One bench-format row from a DecodeStats.since delta: the token-rate
    family (tokens_per_sec + TTFT/ITL) instead of samples/sec/chip."""
    return {
        name: {
            "mode": mode,
            "offered_rps": offered_rps,
            "achieved_rps": round(d["completed"] / max(d["wall_s"], 1e-9), 2),
            "requests": d["submitted"],
            "completed": d["completed"],
            "rejected": d["rejected"],
            "tokens": d["tokens"],
            "tokens_per_sec": d["tokens_per_sec"],
            **{f"ttft_ms_{k}": v for k, v in d["ttft_ms"].items()
               if k in ("p50", "p95", "p99")},
            **{f"itl_ms_{k}": v for k, v in d["itl_ms"].items()
               if k in ("p50", "p95", "p99")},
            # the decode path's "step" is one token: ITL p50 is its ms/step
            "ms_per_step": d["itl_ms"]["p50"],
            **extra,
        }
    }


def _decode_chaos_phase(engine, rng, max_prompt, configs) -> int:
    """The serving-chaos proof (README "Serving survivability", the full
    gate's serving-chaos leg): kill a replica MID-SWEEP through the real
    ``$TPUDDP_FAULT`` env contract and require the survivability layer's
    headline — zero lost streams, every stream BITWISE-equal to its
    undisturbed same-seed twin, the replica back in routing after
    probation — plus the deadline-shedding contract (an expired queued
    request is rejected typed, never dispatched). Returns 0 on pass; on
    failure logs FATAL and returns 1 (the caller fails the run)."""
    from tpuddp.resilience import faults
    from tpuddp.serving import AdmissionError

    n_sessions = min(6, 2 * engine.replicas[0].cache.max_slots)
    prompts = _decode_prompts(rng, n_sessions, max_prompt, engine.vocab_size)
    # undisturbed twins first: same seeds, same temperature-sampled stream
    twins = [
        np.asarray(
            engine.submit("chaos", p, seed=900 + i, temperature=0.9)
            .result(timeout=300)
        )
        for i, p in enumerate(prompts)
    ]
    # arm a replica kill a few decode steps ahead via the env contract the
    # chaos suite documents (tools/run_chaos.py). The engine's fault-site
    # step counter has advanced exactly once per executed decode step, and
    # the pool is idle right now — so "current total + 3" lands mid-sweep.
    steps_now = sum(r.steps for r in engine.replicas)
    prev = os.environ.get("TPUDDP_FAULT")
    os.environ["TPUDDP_FAULT"] = f"replica_kill@step={steps_now + 3}"
    faults.reload_faults()
    m = engine.stats.mark()
    try:
        results = [
            engine.submit("chaos", p, seed=900 + i, temperature=0.9)
            for i, p in enumerate(prompts)
        ]
        outs = [np.asarray(r.result(timeout=300)) for r in results]
        fired = all(s.fired for s in faults.active_faults())
    finally:
        if prev is None:
            os.environ.pop("TPUDDP_FAULT", None)
        else:
            os.environ["TPUDDP_FAULT"] = prev
        faults.reload_faults()
    if not fired:
        log("FATAL: chaos phase finished without the replica_kill firing")
        return 1
    for i, (out, twin) in enumerate(zip(outs, twins)):
        if not np.array_equal(out, twin):
            log(f"FATAL: stream {i} diverged from its undisturbed twin "
                "after failover")
            return 1
    # deadline shedding: an already-expired queued request must be shed
    # with the typed verdict before it can cost a prefill
    doomed = engine.submit("chaos", prompts[0], deadline_s=0.0)
    try:
        doomed.result(timeout=60)
        log("FATAL: an expired queued request was served, not shed")
        return 1
    except AdmissionError as e:
        if e.reason != "deadline_exceeded":
            log(f"FATAL: shed rejection carried reason {e.reason!r}, not "
                "deadline_exceeded")
            return 1
    d = engine.stats.since(m)
    if d["failovers"] < 1:
        log("FATAL: the kill fired but no session_failover was recorded")
        return 1
    if not all(r.healthy for r in engine.replicas):
        log("FATAL: a replica is still out of routing after probation")
        return 1
    configs.update(_decode_row(
        "chaos_failover", "chaos", d,
        fault=f"replica_kill@step={steps_now + 3}",
        sessions=n_sessions,
        failovers=d["failovers"],
        shed=d["shed"],
        bitwise_equal=True,
        replicas_healthy=sum(1 for r in engine.replicas if r.healthy),
    ))
    log(
        f"chaos: replica_kill mid-sweep -> {d['failovers']} session "
        f"failover(s), {n_sessions}/{n_sessions} streams bitwise-equal to "
        f"their undisturbed twins, {d['shed']} expired request(s) shed "
        "typed, replica back in routing after probation"
    )
    return 0


def run_decode(args) -> int:
    """The --decode sweep: tokens/sec + TTFT vs offered sequence rate, with
    request-level sequential decode as the vs_baseline anchor."""
    from tpuddp import config as config_lib
    from tpuddp.observability import json_sanitize
    from tpuddp.serving.decode import DecodeEngine

    settings = (
        config_lib.load_settings(args.settings) if args.settings else {}
    )
    serving = config_lib.serving_config(settings)
    cfg = config_lib.decode_config(serving) or dict(config_lib.DECODE_DEFAULTS)
    if args.model:
        cfg["model"] = args.model
    if args.replicas:
        cfg["num_replicas"] = args.replicas
    n_per_load = args.requests
    if args.quick:
        # CI sizing: tiny vocab/model state, short generations, ~100
        # sequences across calibration + 3 open points
        n_per_load = 24
        cfg.update(
            vocab_size=min(int(cfg["vocab_size"]), 64),
            max_slots=min(int(cfg["max_slots"]), 4),
            max_seq_len=min(int(cfg["max_seq_len"]), 64),
            max_new_tokens=min(int(cfg["max_new_tokens"]), 8),
            stats_window=32,
        )

    observability = None
    if args.exporter is not None:
        observability = {"exporter": True, "exporter_port": args.exporter}
    engine = DecodeEngine.from_config(
        cfg, out_dir=args.history_dir, observability=observability
    )
    log(
        f"decode engine: model={cfg['model']} replicas={len(engine.replicas)} "
        f"max_slots={cfg['max_slots']} kv={cfg['kv_blocks']}x"
        f"{cfg['kv_block_size']} prefill_buckets={engine.buckets}"
    )
    engine.start()
    if engine.exporter is not None:
        log(f"exporter: /metrics on {engine.exporter.host}:{engine.exporter.port}")

    rng = np.random.RandomState(args.seed)
    max_prompt = min(16, engine.max_prompt_len)
    configs = {}

    # -- correctness proof before any timing: a sequence decoded inside a
    # full concurrent batch must be BITWISE the sequence decoded alone —
    # continuous batching and KV paging are numerically invisible
    probe = _decode_prompts(rng, 1 + int(cfg["max_slots"]), max_prompt,
                            engine.vocab_size)
    solo = engine.submit("verify", probe[0], seed=123).result(timeout=300)
    crowd = [engine.submit("verify", p, seed=123) for p in probe]
    packed = crowd[0].result(timeout=300)
    for r in crowd[1:]:
        r.result(timeout=300)
    if not np.array_equal(solo, packed):
        log("FATAL: batched decode diverged from single-sequence decode")
        return 1
    log("verified: batched decode bitwise-equal to single-sequence decode")

    # -- baseline: request-level SEQUENTIAL decode (one sequence in flight,
    # the no-continuous-batching strawman) through the same engine
    # one-sequence-in-flight decode is the slowest phase of the sweep: cap
    # it in the full run (the quick sizing is already tiny) — 64 sequences
    # is plenty of signal for a tokens/sec anchor
    base_n = n_per_load if args.quick else min(n_per_load, 64)
    base_prompts = _decode_prompts(rng, base_n, max_prompt, engine.vocab_size)
    m = engine.stats.mark()
    decode_closed_loop(engine, base_prompts, args.tenants, workers=1)
    d_base = engine.stats.since(m)
    base_tps = d_base["tokens_per_sec"]
    configs.update(_decode_row("sequential_baseline", "sequential", d_base))
    log(
        f"baseline (sequential, 1 sequence in flight): {base_tps:,.1f} "
        f"tokens/s, TTFT p50 {d_base['ttft_ms']['p50']} ms"
    )

    # -- closed loop: saturate the slots, find the peak token rate
    workers = args.workers or 2 * int(cfg["max_slots"]) * len(engine.replicas)
    prompts = _decode_prompts(rng, n_per_load, max_prompt, engine.vocab_size)
    m = engine.stats.mark()
    with _occupancy_peak(engine) as kv_peak:
        done, wall = decode_closed_loop(engine, prompts, args.tenants, workers)
    d = engine.stats.since(m)
    peak_tps = d["tokens_per_sec"]
    peak_rps = done / max(wall, 1e-9)
    configs.update(_decode_row(
        "closed_loop", "closed", d, workers=workers,
        kv_occupancy_peak=round(kv_peak(), 4),
    ))
    log(
        f"closed loop ({workers} workers): {peak_tps:,.1f} tokens/s "
        f"({peak_rps:,.1f} seq/s), TTFT p50 {d['ttft_ms']['p50']} ms, "
        f"ITL p50 {d['itl_ms']['p50']} ms"
    )

    # -- open loop: TTFT/ITL vs offered sequence rate
    fractions = [float(f) for f in args.loads.split(",") if f.strip()]
    for frac in fractions:
        offered = max(0.5, peak_rps * frac)
        prompts = _decode_prompts(rng, n_per_load, max_prompt, engine.vocab_size)
        m = engine.stats.mark()
        _, rejected, _ = decode_open_loop(engine, prompts, args.tenants, offered)
        d = engine.stats.since(m)
        name = f"open_{frac:g}x"
        configs.update(_decode_row(
            name, "open", d,
            offered_rps=round(offered, 2),
            offered_fraction_of_peak=frac,
        ))
        log(
            f"open loop {frac:g}x ({offered:,.1f} seq/s offered): "
            f"{d['tokens_per_sec']:,.1f} tokens/s, TTFT p50 "
            f"{d['ttft_ms']['p50']} ms, ITL p99 {d['itl_ms']['p99']} ms, "
            f"rejected {rejected}"
        )

    if args.chaos:
        rc = _decode_chaos_phase(engine, rng, max_prompt, configs)
        if rc:
            engine.drain(reason="loadgen_chaos_failed")
            return rc

    summary = engine.drain(reason="loadgen_complete")

    import jax

    device_kind = jax.devices()[0].device_kind
    vs = peak_tps / base_tps if base_tps else 1.0
    payload = {
        "metric": f"decode_{cfg['model']}_tokens_per_sec",
        "value": round(peak_tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(vs, 2),
        "vs_baseline_basis": "request-level sequential decode (1 sequence in flight)",
        "baseline_tokens_per_sec": round(base_tps, 2),
        "device": device_kind,
        "tenants": args.tenants,
        "replicas": len(engine.replicas),
        "max_slots": int(cfg["max_slots"]),
        "kv_blocks": int(cfg["kv_blocks"]),
        "kv_block_size": int(cfg["kv_block_size"]),
        "max_new_tokens": int(cfg["max_new_tokens"]),
        "configs": configs,
    }
    out_path = args.out or (
        os.path.join(args.history_dir, "bench_results.json")
        if args.history_dir
        else os.path.join(_REPO, "bench_results.json")
    )
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(json_sanitize(payload), f, indent=2, allow_nan=False)
        f.write("\n")
    log(f"token curve -> {out_path}")
    if args.history_dir:
        log(f"history -> {os.path.join(args.history_dir, 'history.jsonl')}")

    print(json.dumps(json_sanitize({
        "metric": payload["metric"],
        "value": payload["value"],
        "unit": payload["unit"],
        "vs_baseline": payload["vs_baseline"],
        "device": device_kind,
        "n_configs": len(configs),
        "submitted": summary["submitted"],
        "completed": summary["completed"],
        "tokens": summary["tokens"],
        "rejected": sum(summary["rejected"].values()),
        "shed": summary["shed"],
        "failovers": summary["failovers"],
        "results_file": os.path.basename(out_path),
    }), allow_nan=False))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    parser.add_argument("--settings", default=None,
                        help="YAML settings file (serving block)")
    parser.add_argument("--model", default=None, help="override serving.model")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--tenants", type=int, default=2)
    parser.add_argument("--max-batch", type=int, default=None)
    parser.add_argument("--requests", type=int, default=300,
                        help="requests per load point")
    parser.add_argument("--rows-max", type=int, default=4,
                        help="rows per request drawn uniform from [1, rows_max]")
    parser.add_argument("--loads", default="0.5,0.75,1.0",
                        help="open-loop offered rates as fractions of the "
                        "closed-loop peak (comma separated)")
    parser.add_argument("--workers", type=int, default=None,
                        help="closed-loop concurrency (default 4 x replicas)")
    parser.add_argument("--history-dir", default=None,
                        help="engine history.jsonl destination")
    parser.add_argument("--out", default=None,
                        help="bench-format curve destination "
                        "(default: <history-dir>/bench_results.json)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--quick", action="store_true",
                        help="CI sizing: ~100 requests total, tiny model")
    parser.add_argument("--decode", action="store_true",
                        help="token-level decode sweep (tokens/sec + TTFT "
                        "curves against the serving.decode engine)")
    parser.add_argument("--chaos", action="store_true",
                        help="(--decode only) append the serving-chaos "
                        "proof: kill a replica mid-sweep via $TPUDDP_FAULT "
                        "and require zero lost streams, bitwise-equal "
                        "continuations, typed deadline shedding, and the "
                        "replica back after probation")
    parser.add_argument("--exporter", nargs="?", const=0, default=None,
                        type=int, metavar="PORT",
                        help="serve the live /metrics endpoint during the "
                        "run (PORT omitted or 0 = ephemeral; the bound port "
                        "lands in <history-dir>/exporter.port)")
    args = parser.parse_args(argv)

    if args.chaos and not args.decode:
        parser.error("--chaos requires --decode (the serving-chaos proof "
                     "runs against the token-level engine)")
    if args.decode:
        return run_decode(args)

    from tpuddp import config as config_lib
    from tpuddp.observability import json_sanitize
    from tpuddp.serving import ServingEngine

    settings = (
        config_lib.load_settings(args.settings) if args.settings else {}
    )
    cfg = config_lib.serving_config(settings)
    if args.model:
        cfg["model"] = args.model
    if args.replicas:
        cfg["num_replicas"] = args.replicas
    if args.max_batch:
        cfg["max_batch_size"] = args.max_batch
    n_per_load = args.requests
    if args.quick:
        n_per_load = 34  # 3 open points -> ~100 requests + calibration
        cfg["max_batch_size"] = min(int(cfg["max_batch_size"]), 8)
        cfg["stats_window"] = 16

    observability = None
    if args.exporter is not None:
        observability = {"exporter": True, "exporter_port": args.exporter}
    engine = ServingEngine.from_config(
        cfg, out_dir=args.history_dir, observability=observability
    )
    log(
        f"engine: model={cfg['model']} replicas={len(engine.pool)} "
        f"max_batch={engine.scheduler.max_batch_size} "
        f"buckets={engine.scheduler.buckets} tenants={args.tenants}"
    )
    engine.start()  # warms every bucket program on every replica
    if engine.exporter is not None:
        log(f"exporter: /metrics on {engine.exporter.host}:{engine.exporter.port}")

    rng = np.random.RandomState(args.seed)
    shape = engine.pool.sample_shape
    rows_max = max(1, min(args.rows_max, engine.scheduler.max_batch_size))
    configs = {}

    # -- correctness proof before any timing: served logits must be bitwise
    # a direct model forward over the same padded batch (params passed as
    # arguments, exactly like the replica's own program)
    import jax

    from tpuddp.nn.core import Context
    from tpuddp.utils import batching

    module = engine.pool.module
    r0 = engine.pool.replicas[0]

    @jax.jit
    def _direct(p, s, x):
        ctx = Context(train=False, rng=jax.random.key(0), axis_name=None)
        return module.apply(p, s, x, ctx)[0]

    for rows in sorted({1, rows_max, engine.scheduler.max_batch_size}):
        x = rng.randn(rows, *shape).astype(np.float32)
        served = engine.submit("verify", x).result(timeout=120)
        xp, _, _ = batching.pad_batch(
            x, None, batching.bucket_for(rows, engine.scheduler.max_batch_size)
        )
        ref = np.asarray(_direct(r0.params, r0.model_state, xp))[:rows]
        if not np.array_equal(served, ref):
            log(f"FATAL: served logits diverge from direct forward at "
                f"rows={rows}")
            return 1
    log("verified: served logits bitwise-equal direct forward "
        f"(rows in {sorted({1, rows_max, engine.scheduler.max_batch_size})})")

    # -- baseline: sequential per-request serving (the strawman a server
    # WITHOUT continuous batching is: one request in flight, no coalescing,
    # every request its own dispatch) — through the engine, so queue/thread
    # costs land on both sides of the ratio honestly
    ones = _make_requests(rng, 64, 1, shape)
    baseline_steps = 32 if args.quick else 128
    raw_dispatch_rate(engine, ones, 8)  # warm the (1,...) program path
    raw_rps = raw_dispatch_rate(engine, ones, min(baseline_steps, len(ones)))
    base_n = min(n_per_load, 64) if args.quick else n_per_load
    base_payloads = _make_requests(rng, base_n, rows_max, shape)
    # a per-request server would not linger hoping to coalesce — zero the
    # batch timeout for the baseline phase so the ratio measures continuous
    # batching, not the engine's own linger penalty charged to the strawman
    linger = engine.scheduler.batch_timeout_s
    engine.scheduler.batch_timeout_s = 0.0
    try:
        base_e2e, base_wall = closed_loop(engine, base_payloads, args.tenants, 1)
    finally:
        engine.scheduler.batch_timeout_s = linger
    base_rps = len(base_e2e) / max(base_wall, 1e-9)
    log(
        f"baseline (sequential per-request serving): {base_rps:,.1f} req/s "
        f"(raw single-dispatch ceiling {raw_rps:,.0f}/s)"
    )

    # -- closed loop: find the sustainable peak -----------------------------
    workers = args.workers or 4 * len(engine.pool)
    payloads = _make_requests(rng, n_per_load, rows_max, shape)
    m = engine.stats.mark()
    e2e, wall = closed_loop(engine, payloads, args.tenants, workers)
    d = engine.stats.since(m)
    peak_rps = len(e2e) / wall if wall else 0.0
    configs["closed_loop"] = {
        "mode": "closed",
        "workers": workers,
        "offered_rps": None,
        "achieved_rps": round(peak_rps, 2),
        "requests": len(payloads),
        "completed": len(e2e),
        "rejected": d["rejected"],
        **{f"e2e_ms_{k}": v for k, v in _pct(e2e).items()
           if k in ("p50", "p95", "p99")},
        "queue_ms_p50": d["queue_ms"]["p50"],
        "batch_occupancy": d["batch_occupancy"],
        "samples_per_sec_per_chip": round(
            d["rows"] / max(wall, 1e-9) / len(engine.pool), 2
        ),
        "ms_per_step": d["device_ms"]["p50"],
    }
    log(
        f"closed loop ({workers} workers): {peak_rps:,.1f} req/s, "
        f"p99 {configs['closed_loop']['e2e_ms_p99']} ms, "
        f"occupancy {d['batch_occupancy']}"
    )

    # -- open loop: the latency-vs-offered-throughput curve -----------------
    fractions = [float(f) for f in args.loads.split(",") if f.strip()]
    for frac in fractions:
        offered = max(1.0, peak_rps * frac)
        payloads = _make_requests(rng, n_per_load, rows_max, shape)
        m = engine.stats.mark()
        e2e, rejected, wall = open_loop(engine, payloads, args.tenants, offered)
        d = engine.stats.since(m)
        name = f"open_{frac:g}x"
        configs[name] = {
            "mode": "open",
            "offered_fraction_of_peak": frac,
            "offered_rps": round(offered, 2),
            "achieved_rps": round(len(e2e) / max(wall, 1e-9), 2),
            "requests": len(payloads),
            "completed": len(e2e),
            "rejected": rejected,
            **{f"e2e_ms_{k}": v for k, v in _pct(e2e).items()
               if k in ("p50", "p95", "p99")},
            "queue_ms_p50": d["queue_ms"]["p50"],
            "batch_occupancy": d["batch_occupancy"],
            "samples_per_sec_per_chip": round(
                d["rows"] / max(wall, 1e-9) / len(engine.pool), 2
            ),
            "ms_per_step": d["device_ms"]["p50"],
        }
        log(
            f"open loop {frac:g}x ({offered:,.1f} req/s offered): "
            f"achieved {configs[name]['achieved_rps']:,.1f} req/s, "
            f"p50 {configs[name]['e2e_ms_p50']} ms, "
            f"p99 {configs[name]['e2e_ms_p99']} ms, rejected {rejected}"
        )

    summary = engine.drain(reason="loadgen_complete")

    # -- bench-format artifact ----------------------------------------------
    import jax

    device_kind = jax.devices()[0].device_kind
    vs = peak_rps / base_rps if base_rps else 1.0
    payload = {
        "metric": f"serving_{cfg['model']}_peak_requests_per_sec",
        "value": round(peak_rps, 1),
        "unit": "requests/sec",
        "vs_baseline": round(vs, 2),
        "vs_baseline_basis": "sequential per-request serving (1 in flight)",
        "baseline_rps": round(base_rps, 2),
        "raw_single_dispatch_rps": round(raw_rps, 2),
        "device": device_kind,
        "tenants": args.tenants,
        "replicas": len(engine.pool),
        "max_batch_size": engine.scheduler.max_batch_size,
        "rows_max": rows_max,
        "configs": configs,
    }
    out_path = args.out or (
        os.path.join(args.history_dir, "bench_results.json")
        if args.history_dir
        else os.path.join(_REPO, "bench_results.json")
    )
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(json_sanitize(payload), f, indent=2, allow_nan=False)
        f.write("\n")
    log(f"curve -> {out_path}")
    if args.history_dir:
        log(f"history -> {os.path.join(args.history_dir, 'history.jsonl')}")

    # last stdout line: compact driver-parseable summary (bench.py contract)
    print(json.dumps(json_sanitize({
        "metric": payload["metric"],
        "value": payload["value"],
        "unit": payload["unit"],
        "vs_baseline": payload["vs_baseline"],
        "device": device_kind,
        "n_configs": len(configs),
        "completed": summary["completed"],
        "rejected": sum(summary["rejected"].values()),
        "results_file": os.path.basename(out_path),
    }), allow_nan=False))
    return 0


if __name__ == "__main__":
    sys.exit(main())
