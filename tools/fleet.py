#!/usr/bin/env python
"""Fleet controller CLI — gang-schedule many tpuddp jobs over one pool.

Subcommands:

``run --spec fleet.yaml``
    Run a declared fleet until every training job reaches a terminal state,
    then drain the serving jobs and exit (0 iff nothing FAILED). The spec
    file::

        pool: 8                       # device pool size
        fleet_dir: ./fleet            # run-dir namespace root (jobs/<name>/)
        poll: 1.0                     # controller tick seconds
        autoscale:                    # optional (fleet/autoscale.py knobs)
          slo_p99_ms: 50.0
          occupancy_high: 0.9
          hysteresis: 2
          cooldown_s: 30.0
        tune:                         # optional online tuner (tune/online.py)
          report: TUNE_r01.json       # offline probe's endorsement list
          cooldown_s: 300.0           # ... any TunePolicy knob
          # trust_advisor: true       # act on unprobed predictions (opt-in)
        jobs:
          - name: cnn-a
            kind: training            # training | serving
            priority: 1
            min_world: 2
            max_world: 4
            argv: [python, train_native.py, --settings_file, a.yaml]
            env: {TPUDDP_CHAOS_TRAINING: '{}'}

    ``{run_dir}`` inside argv/env expands to the job's namespaced run dir.

``chaos-demo --out DIR``
    The pool-level chaos proof (ISSUE 11 acceptance): N >= 3 jobs share one
    CPU-mesh pool; one training job is SIGKILLed mid-run and resumes
    elastically; a late high-priority arrival preempts capacity through the
    drain contract (SIGTERM -> exit 75 -> shrunk resume, never
    SIGKILL-first); the serving job breaches its (deliberately absurd) p99
    SLO and is autoscaled to more replicas via
    ``$TPUDDP_SERVING_REPLICAS``; every job's ``history.jsonl`` must pass
    ``tpuddp_inspect --validate`` with correct ``resumed_from_world``
    attribution, and the run-dir namespacing is asserted (per-job ports,
    heartbeats, checkpoints). Exit 0 only when every check holds — wired
    into ``tools/run_full_gate.py`` as the fleet gate; the chaos pytest leg
    re-asserts over the artifacts this leaves in ``--out``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

logging.basicConfig(level=logging.INFO, format="%(message)s")
logger = logging.getLogger("tpuddp")


def _load_yaml(path):
    import yaml

    with open(path) as f:
        obj = yaml.safe_load(f)
    if not isinstance(obj, dict):
        raise SystemExit(f"fleet spec {path} did not parse to a mapping")
    return obj


def cmd_run(args) -> int:
    from tpuddp.fleet.autoscale import Autoscaler, AutoscalePolicy
    from tpuddp.fleet.controller import FleetController
    from tpuddp.fleet.spec import spec_from_dict
    from tpuddp.tune.online import (
        FleetTuner,
        TunePolicy,
        endorsed_rules_from_report,
    )

    spec = _load_yaml(args.spec)
    pool = int(spec.get("pool") or 0)
    if pool < 1:
        raise SystemExit("fleet spec needs a positive 'pool' size")
    fleet_dir = args.fleet_dir or spec.get("fleet_dir") or "./fleet"
    autoscaler = None
    if spec.get("autoscale"):
        autoscaler = Autoscaler(AutoscalePolicy(**spec["autoscale"]))
    # optional online tuner (tpuddp/tune/online.py):
    #   tune:
    #     report: TUNE_r01.json      # the offline probe's endorsement list
    #     cooldown_s: 300.0          # ... any TunePolicy knob
    # without 'report' the tuner stays inert (nothing is endorsed) unless
    # 'trust_advisor: true' explicitly opts into unprobed predictions.
    tuner = None
    if spec.get("tune"):
        tune_cfg = dict(spec["tune"])
        report = tune_cfg.pop("report", None)
        trust = bool(tune_cfg.pop("trust_advisor", False))
        if trust:
            endorsed = None
        elif report:
            endorsed = endorsed_rules_from_report(
                report if os.path.isabs(report)
                else os.path.join(_REPO, report)
            )
        else:
            endorsed = set()
        tuner = FleetTuner(
            policy=TunePolicy(**tune_cfg), endorsed_rules=endorsed,
        )
    controller = FleetController(
        pool, fleet_dir=fleet_dir, autoscaler=autoscaler, tuner=tuner,
        observability=spec.get("observability"),
    )
    for entry in spec.get("jobs") or []:
        controller.submit(spec_from_dict(entry))
    if not controller.jobs:
        raise SystemExit("fleet spec declares no jobs")
    poll = float(args.poll or spec.get("poll") or 1.0)
    completed = False
    try:
        completed = controller.run_until(
            lambda c: c.training_complete(), poll=poll, timeout=args.timeout
        )
    finally:
        controller.shutdown()
    failed = [s for s in controller.status() if s["state"] == "failed"]
    for s in controller.status():
        print(f"fleet: {s['name']}: {s['state']} (world {s['world']}, "
              f"rc {s['exit_code']})")
    if not completed:
        # a hung fleet must not read as success: the shutdown preempts the
        # stuck jobs (state 'preempted', not 'failed'), so surface the
        # timeout explicitly
        print("fleet: timed out before every training job finished",
              file=sys.stderr)
        return 1
    return 1 if failed else 0


# --------------------------------------------------------------- chaos demo --
def _history_records(run_dir):
    path = os.path.join(run_dir, "history.jsonl")
    if not os.path.exists(path):
        return []
    records = []
    with open(path) as f:
        for line in f:
            if line.strip():
                try:
                    records.append(json.loads(line))
                except ValueError:
                    records.append({"type": "<unparseable>"})
    return records


def _epoch_rows(run_dir):
    return [r for r in _history_records(run_dir) if r.get("type") == "epoch"]


def _validate(run_dir) -> bool:
    rc = subprocess.call(
        [
            sys.executable, os.path.join(_REPO, "tools", "tpuddp_inspect.py"),
            "--validate", os.path.join(run_dir, "history.jsonl"),
        ],
        cwd=_REPO,
    )
    return rc == 0


class ChaosCheckFailure(AssertionError):
    pass


def _check(cond, message):
    if not cond:
        raise ChaosCheckFailure(message)


def run_chaos_demo(out_dir: str, pool: int = 5, timeout: float = 900.0) -> int:
    """The scripted multi-job chaos scenario; see the module docstring."""
    from tpuddp.fleet.autoscale import Autoscaler, AutoscalePolicy
    from tpuddp.fleet.controller import FleetController
    from tpuddp.fleet.spec import JobSpec
    from tpuddp.observability.exporter import read_live_port
    from tpuddp.resilience.supervisor import SupervisorPolicy

    t0 = time.monotonic()

    def remaining():
        left = timeout - (time.monotonic() - t0)
        _check(left > 0, "chaos demo exceeded its overall timeout")
        return left

    worker = os.path.join(_REPO, "tests", "_chaos_train_worker.py")
    base_env = dict(os.environ)
    base_env.pop("TPUDDP_FAULT", None)
    base_env.pop("TPUDDP_AUTO_RESUME", None)
    base_env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "TPUDDP_BACKEND": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + base_env.get("PYTHONPATH", ""),
    })
    training_cfg = json.dumps({
        "synthetic_n": [128, 32],  # short epochs: the scenario has 4 jobs
        "checkpoint_epoch": 1,
        "step_stats_every": 4,
    })

    os.makedirs(out_dir, exist_ok=True)
    # the serving job's settings must point its out_dir INTO its namespaced
    # run dir — the controller derives it the same way for every job
    c_run_dir = os.path.join(out_dir, "jobs", "serve-c")
    settings = os.path.join(out_dir, "serve_c_settings.yaml")
    with open(settings, "w") as f:
        f.write(
            "out_dir: %s\n"
            "serving:\n"
            "  num_replicas: 1\n"
            "  max_batch_size: 8\n"
            "  stats_window: 8\n"
            "observability:\n"
            "  exporter: true\n"
            "  exporter_port: 0\n" % c_run_dir
        )

    # an SLO no CPU-rung batch can meet -> a deterministic p99 breach; one
    # fresh breached window is enough evidence for the demo (the hysteresis
    # matrix is unit-tested in tests/test_fleet.py)
    autoscaler = Autoscaler(AutoscalePolicy(
        slo_p99_ms=0.05, hysteresis=1, cooldown_s=10.0,
    ))
    controller = FleetController(
        pool, fleet_dir=out_dir, autoscaler=autoscaler, env=base_env,
        supervisor_policy=SupervisorPolicy(backoff_base=0.3, backoff_cap=2.0),
    )

    py = sys.executable
    job_a = controller.submit(JobSpec(
        name="train-a", kind="training", priority=1, min_world=1, max_world=2,
        argv=(py, "-u", worker, "{run_dir}", "5"),
        env={"TPUDDP_CHAOS_TRAINING": training_cfg,
             "TPUDDP_CHAOS_OBS": '{"exporter": true}'},
    ))
    job_b = controller.submit(JobSpec(
        name="train-b", kind="training", priority=1, min_world=1, max_world=1,
        argv=(py, "-u", worker, "{run_dir}", "3"),
        env={"TPUDDP_CHAOS_TRAINING": training_cfg},
    ))
    job_c = controller.submit(JobSpec(
        name="serve-c", kind="serving", priority=2, min_world=1, max_world=2,
        argv=(py, "-u", "-m", "tpuddp.serving", "--settings", settings,
              "--demo", "32", "--serve", "0"),
    ))

    def wait_for(cond, what, poll=0.5):
        deadline = time.monotonic() + remaining()
        while time.monotonic() < deadline:
            controller.step()
            if cond():
                return
            time.sleep(poll)
        raise ChaosCheckFailure(f"timed out waiting for {what}")

    killed = {"done": False}
    ports = {}

    print("fleet chaos: phase 1 — three jobs share the pool", flush=True)
    wait_for(
        lambda: job_a.world == 2 and job_b.state == "running"
        and job_c.state == "running",
        "initial gang placement (A=2, B=1, C=1)",
    )
    alloc = controller.last_plan.alloc
    _check(
        alloc.get("train-a") == 2 and alloc.get("train-b") == 1
        and alloc.get("serve-c", 0) >= 1,
        f"unexpected initial allocation: {alloc}",
    )

    print("fleet chaos: phase 2 — SIGKILL train-b mid-run", flush=True)
    wait_for(lambda: len(_epoch_rows(job_b.run_dir)) >= 1,
             "train-b's first epoch row")
    child = job_b.supervisor.child
    _check(child is not None, "train-b has no live child to kill")
    os.kill(child.pid, signal.SIGKILL)
    killed["done"] = True
    wait_for(
        lambda: any(rc < 0 for _, rc, _ in job_b.supervisor.history),
        "train-b's supervisor to observe the signal death",
    )

    # per-job live endpoints: ports are discovered through each job's OWN
    # run dir and verified via /healthz — the namespacing half of the proof
    wait_for(lambda: len(_epoch_rows(job_a.run_dir)) >= 1,
             "train-a's first epoch row")
    for job in (job_a, job_c):
        port = read_live_port(job.run_dir, probe_timeout=2.0)
        if port is not None:
            ports[job.spec.name] = port
    _check(
        len(set(ports.values())) == len(ports) and len(ports) >= 1,
        f"expected distinct live per-job exporter ports, got {ports}",
    )

    print("fleet chaos: phase 3 — high-priority arrival preempts capacity",
          flush=True)
    job_d = controller.submit(JobSpec(
        name="train-d", kind="training", priority=100, min_world=2,
        max_world=2,
        argv=(py, "-u", worker, "{run_dir}", "2"),
        env={"TPUDDP_CHAOS_TRAINING": training_cfg},
    ))
    wait_for(
        lambda: job_d.state == "running" and job_a.world == 1,
        "train-d placed at world 2 with train-a drained to world 1",
    )
    _check(job_a.resizes >= 1, "train-a was never resized")

    print("fleet chaos: phase 4 — wait out train-d, autoscale serve-c",
          flush=True)
    wait_for(lambda: job_d.state == "done", "train-d to finish")
    wait_for(
        lambda: job_c.world == 2,
        "serve-c to autoscale to 2 replicas on the p99 breach",
    )
    _check(
        any(a["action"] == "scale_up" and a["job"] == "serve-c"
            for a in autoscaler.actions),
        f"no scale_up action recorded: {autoscaler.actions}",
    )

    print("fleet chaos: phase 5 — drain the fleet", flush=True)
    wait_for(
        lambda: job_a.state == "done" and job_b.state == "done",
        "train-a and train-b to finish",
    )
    # serve-c restarted with $TPUDDP_SERVING_REPLICAS=2: its newest header
    # must record the scaled world before we stop it
    wait_for(
        lambda: any(
            r.get("type") == "run_meta" and r.get("num_replicas") == 2
            for r in _history_records(job_c.run_dir)
        ),
        "serve-c's scaled run_meta header (num_replicas=2)",
    )
    controller.stop_job("serve-c")
    wait_for(lambda: job_c.state == "preempted", "serve-c to drain out")
    controller.shutdown()

    print("fleet chaos: phase 6 — verify the artifacts", flush=True)
    for job in (job_a, job_b, job_c, job_d):
        _check(_validate(job.run_dir),
               f"{job.spec.name}: history.jsonl failed tpuddp_inspect")

    # A: preemption shrank it 2 -> 1 through the drain contract — the
    # elastic restore must attribute the resume to the OLD world
    a_records = _history_records(job_a.run_dir)
    topo = [r for r in a_records if r.get("event") == "topology_change"]
    _check(
        any(t["from_world"] == 2 and t["to_world"] == 1 for t in topo),
        f"train-a: no 2->1 topology_change event (saw {topo})",
    )
    _check(
        any(
            r.get("type") == "run_meta" and r.get("resumed_from_world") == 2
            and r.get("world_size") == 1
            for r in a_records
        ),
        "train-a: no run_meta header attributing the resume to world 2",
    )
    # B: SIGKILLed, classified as a signal death, resumed at the SAME world
    # — its headers must NOT invent a topology change
    _check(killed["done"], "the kill phase never ran")
    b_records = _history_records(job_b.run_dir)
    b_metas = [r for r in b_records if r.get("type") == "run_meta"]
    _check(len(b_metas) >= 2, "train-b: expected a resumed (second) header")
    _check(
        not any(r.get("resumed_from_world") for r in b_metas),
        "train-b resumed on its own world; resumed_from_world must be unset",
    )
    _check(
        {r["epoch"] for r in _epoch_rows(job_b.run_dir)} == {0, 1, 2},
        f"train-b epochs incomplete: {_epoch_rows(job_b.run_dir)}",
    )
    # C: scaled 1 -> 2 replicas
    c_metas = [
        r for r in _history_records(job_c.run_dir)
        if r.get("type") == "run_meta"
    ]
    _check(
        c_metas and c_metas[0].get("num_replicas") == 1
        and any(r.get("num_replicas") == 2 for r in c_metas),
        f"serve-c replica headers wrong: "
        f"{[r.get('num_replicas') for r in c_metas]}",
    )
    # D: ran once, gang-placed at exactly its min=max=2 world
    d_metas = [
        r for r in _history_records(job_d.run_dir)
        if r.get("type") == "run_meta"
    ]
    _check(
        len(d_metas) == 1 and d_metas[0].get("world_size") == 2,
        f"train-d headers wrong: {d_metas}",
    )

    # namespacing: every job's channels live under its OWN run dir (the
    # per-job exporter ports were already proven distinct mid-run; the
    # heartbeat channel only exists on multi-process pods and inherits the
    # same save_dir namespace)
    for job in (job_a, job_b, job_d):
        _check(
            any(f.startswith("ckpt_") for f in os.listdir(job.run_dir)),
            f"{job.spec.name}: no namespaced checkpoints",
        )
    run_dirs = [j.run_dir for j in (job_a, job_b, job_c, job_d)]
    _check(len(set(map(os.path.realpath, run_dirs))) == 4,
           "run dirs are not disjoint")

    print("fleet chaos: PASS — kill/preempt/autoscale survived with every "
          "history valid and namespaced", flush=True)
    return 0


def cmd_chaos_demo(args) -> int:
    try:
        return run_chaos_demo(args.out, pool=args.pool, timeout=args.timeout)
    except ChaosCheckFailure as e:
        print(f"fleet chaos: FAIL — {e}", file=sys.stderr)
        return 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="tpuddp fleet controller (gang scheduling + priority "
        "preemption + metric-driven autoscaling over one device pool)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_run = sub.add_parser("run", help="run a declared fleet spec file")
    p_run.add_argument("--spec", required=True, help="fleet YAML file")
    p_run.add_argument("--fleet-dir", default=None,
                       help="override the spec's fleet_dir")
    p_run.add_argument("--poll", type=float, default=None,
                       help="controller tick seconds")
    p_run.add_argument("--timeout", type=float, default=None,
                       help="give up after this many seconds")
    p_run.set_defaults(fn=cmd_run)
    p_demo = sub.add_parser(
        "chaos-demo",
        help="the pool-level chaos proof (kill/preempt/autoscale, N jobs)",
    )
    p_demo.add_argument("--out", required=True, help="fleet dir for the demo")
    p_demo.add_argument("--pool", type=int, default=5)
    p_demo.add_argument("--timeout", type=float, default=900.0)
    p_demo.set_defaults(fn=cmd_chaos_demo)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
