#!/usr/bin/env python
"""autotune — the A/B probe harness: advisor predictions vs measured truth.

The offline advisor (``tpuddp_inspect tune``) PREDICTS; this tool makes it
answer for the prediction. It drives the REAL epoch driver twice — a
baseline dryrun on the given knobs, then a tuned dryrun launched under the
advisor's ``$TPUDDP_TUNE_OVERLAY`` — measures both runs from their own
history artifacts (``tpuddp.observability.advisor.measure_run``), and
writes every recommendation's predicted-vs-measured delta into a
schema-v12-validated ``TUNE_rNN.json`` (the BENCH_r*/SERVING_r* artifact
family). A rule whose measured delta regresses ships ``endorsed: false``
— the probe refuses to endorse it, whatever the prediction promised — and
the fleet tuner (tpuddp/tune/online.py) only ever acts on endorsed rules.

Honesty note: on the CPU rung (forced host-platform devices) the measured
deltas calibrate the RULES' direction, not TPU magnitudes — wire-byte and
counter metrics (grad_comm_bytes, snapshot skips) transfer; wall-clock
ratios largely do not. ``device`` in the artifact records the rung so
bench_trend never mixes rungs.

Usage:
    python tools/autotune.py --quick                  # CPU-rung probe
    python tools/autotune.py --baseline-dir RUN_DIR   # reuse a run as A
    python tools/autotune.py --training '{"snapshot": {"every_steps": 1}}'

Exit: 0 on a written report (even when nothing is endorsed — the artifact
IS the result), nonzero when a dryrun or validation fails.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuddp.observability import advisor as advisor_lib  # noqa: E402
from tpuddp.tune import probe  # noqa: E402

# deliberately BAD defaults for the quick probe: each arms a different rule
# class on a real run (pipeline_sync_readback, snapshot_cadence_hot,
# comm_hook_uncompressed fires off the default hook=none)
_QUICK_BASELINE = {
    "pipeline": False,
    "snapshot": {"every_steps": 1, "inflight": 1},
    "step_stats_every": 4,
}


def _worker_env(extra=None):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "TPUDDP_BACKEND": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.pop("TPUDDP_TUNE_OVERLAY", None)
    env.update(extra or {})
    return env


def _dryrun(out_dir, *, training, epochs, world, overlay=None):
    """One pass through the real epoch driver (the chaos worker's spawn
    path — drain handlers, snapshots, tracing all live). ``overlay`` rides
    ``$TPUDDP_TUNE_OVERLAY`` exactly as a fleet relaunch would."""
    extra = {
        "TPUDDP_CHAOS_TRAINING": json.dumps(training),
        "TPUDDP_CHAOS_OBS": json.dumps({"tracing": True}),
        "TPUDDP_WORLD_SIZE": str(world),
    }
    if overlay is not None:
        extra["TPUDDP_TUNE_OVERLAY"] = json.dumps(overlay)
    return subprocess.call(
        [
            sys.executable, "-u",
            os.path.join(REPO, "tests", "_chaos_train_worker.py"),
            out_dir, str(epochs),
        ],
        cwd=REPO, env=_worker_env(extra),
    )


def _device_of(run_dir):
    try:
        with open(os.path.join(run_dir, "history.jsonl")) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("type") == "run_meta":
                    return rec.get("device_kind")
    except (OSError, ValueError):
        pass
    return None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--quick", action="store_true",
        help="CPU-rung probe on deliberately bad baseline knobs (2 epochs)",
    )
    parser.add_argument(
        "--training", default=None, metavar="JSON",
        help="baseline training-config overrides (JSON object); default: "
        "the --quick bad-knob set",
    )
    parser.add_argument(
        "--baseline-dir", default=None, metavar="RUN_DIR",
        help="reuse an existing run dir as the baseline (skips the A leg; "
        "its history must carry the knobs the advisor should see)",
    )
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--world", type=int, default=4)
    parser.add_argument(
        "--out", default=None, metavar="PATH",
        help="report path (default: next TUNE_rNN.json at the repo root)",
    )
    parser.add_argument(
        "--min-improvement", type=float, default=0.0, metavar="PCT",
        help="endorsement floor on the measured delta (default 0.0: any "
        "regression refuses endorsement)",
    )
    parser.add_argument(
        "--keep", default=None, metavar="DIR",
        help="keep the probe run dirs under DIR (default: temp, deleted)",
    )
    args = parser.parse_args(argv)

    training = dict(_QUICK_BASELINE)
    if args.training:
        training.update(json.loads(args.training))

    with tempfile.TemporaryDirectory(prefix="tpuddp_autotune_") as tmp:
        work = args.keep or tmp
        os.makedirs(work, exist_ok=True)
        baseline_dir = args.baseline_dir
        if baseline_dir is None:
            baseline_dir = os.path.join(work, "baseline")
            print(f"autotune: baseline dryrun -> {baseline_dir}")
            rc = _dryrun(
                baseline_dir, training=training, epochs=args.epochs,
                world=args.world,
            )
            if rc != 0:
                print(f"autotune: baseline dryrun exited {rc}",
                      file=sys.stderr)
                return rc

        report = advisor_lib.advise(baseline_dir)
        recs = report["recommendations"]
        if not recs:
            print("autotune: advisor found nothing to recommend on the "
                  "baseline — no probe to run, no report written")
            return 0
        overlay = advisor_lib.overlay_from(recs)
        overlay["source"] = "autotune"
        print(f"autotune: {len(recs)} recommendation(s); overlay = "
              + json.dumps(overlay, sort_keys=True))

        tuned_dir = os.path.join(work, "tuned")
        print(f"autotune: tuned dryrun -> {tuned_dir}")
        rc = _dryrun(
            tuned_dir, training=training, epochs=args.epochs,
            world=args.world, overlay=overlay,
        )
        if rc != 0:
            print(f"autotune: tuned dryrun exited {rc}", file=sys.stderr)
            return rc

        baseline_metrics = advisor_lib.measure_run(baseline_dir)
        tuned_metrics = advisor_lib.measure_run(tuned_dir)
        results = [
            probe.make_result_row(
                rec, baseline_metrics, tuned_metrics,
                min_improvement_pct=args.min_improvement,
            )
            for rec in recs
        ]
        payload = probe.build_tune_report(
            device=_device_of(baseline_dir) or "cpu",
            mode="train",
            baseline_metrics=baseline_metrics,
            results=results,
            extra={
                "tuned_metrics": tuned_metrics,
                "overlay": overlay,
                "epochs": args.epochs,
                "world_size": args.world,
                "baseline_training": training,
            },
        )
        out = args.out or probe.next_tune_path(REPO)
        tmp_path = out + ".tmp"
        with open(tmp_path, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp_path, out)

        endorsed = [r for r in results if r["endorsed"]]
        print(f"\nautotune: wrote {out}")
        for r in results:
            verdict = "endorsed" if r["endorsed"] else "REFUSED"
            meas = r["measured_delta_pct"]
            meas_s = f"{meas:+.1f}%" if meas is not None else "unmeasured"
            print(f"  [{verdict}] {r['rule']} ({r['metric']}): predicted "
                  f"{r['predicted_delta_pct']:+.1f}%, measured {meas_s}")
        print(f"autotune: {len(endorsed)}/{len(results)} endorsed")
        return 0


if __name__ == "__main__":
    sys.exit(main())
